"""In-suite slice of the native sanitizer lane (scripts/wf_sanitize.py):
build the instrumented stress driver and run a small seeded corpus under
each sanitizer.  Slow-marked — each lane pays a full compile of
wf_native.cpp under -fsanitize."""

import os
import shutil
import subprocess
import sys

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

pytestmark = pytest.mark.skipif(
    shutil.which("make") is None or shutil.which("g++") is None,
    reason="no native toolchain")


@pytest.mark.slow
@pytest.mark.parametrize("san", ["tsan", "asan"])
def test_sanitizer_stress_lane(san):
    """The lane must build its instrumented binary and run the seeded
    stress corpus with zero sanitizer reports and zero stress-assertion
    failures."""
    proc = subprocess.run(
        [sys.executable, os.path.join(ROOT, "scripts", "wf_sanitize.py"),
         "--san", san, "--n", "2", "--seed", "11"],
        capture_output=True, text=True, timeout=900)
    assert proc.returncode == 0, (
        f"sanitizer lane {san} failed:\n{proc.stdout}\n{proc.stderr}")
    assert "OK" in proc.stdout
