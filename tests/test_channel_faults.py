"""Channel fault-injection tests (parallel/channel.py hardening): every
failure mode must surface as a *fast, classified error* — bounded
wall-clock, never an indefinite hang, never a silent truncation passed
off as clean EOS.  The wall-clock bounds are generous (CI jitter) but
orders of magnitude below "hang"."""

import socket
import threading
import time

import numpy as np
import pytest

from windflow_tpu.core.tuples import Schema, batch_from_columns
from windflow_tpu.parallel.channel import (_LEN, ChannelError, PeerAbort,
                                           PeerStall, RowReceiver,
                                           RowSender, WireConfig,
                                           _encode_dtype)

SCHEMA = Schema(value=np.int64)


def mk_batch(n=8, lo=0):
    ids = np.arange(lo, lo + n)
    return batch_from_columns(SCHEMA, key=np.zeros(n), id=ids, ts=ids,
                              value=ids)


def free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


# ------------------------------------------------------- connect retries

def test_connection_refused_without_deadline_fails_immediately():
    t0 = time.monotonic()
    with pytest.raises(OSError):
        RowSender("127.0.0.1", free_port())
    assert time.monotonic() - t0 < 5


def test_connection_refused_with_deadline_bounded():
    """Backoff retries stop at the total deadline with a clear error —
    not one attempt, not forever."""
    t0 = time.monotonic()
    with pytest.raises(ConnectionError, match="attempts"):
        RowSender("127.0.0.1", free_port(), connect_deadline=0.5)
    dt = time.monotonic() - t0
    assert 0.3 < dt < 10


def test_connect_retry_reaches_late_receiver():
    """Peers boot in any order: a sender started BEFORE its receiver
    connects once the receiver comes up (exponential backoff + jitter)."""
    port = free_port()
    out = {}

    def late_boot():
        time.sleep(0.4)
        out["recv"] = RowReceiver(n_senders=1, port=port)

    t = threading.Thread(target=late_boot)
    t.start()
    snd = RowSender("127.0.0.1", port, connect_deadline=30)
    t.join()
    snd.send(mk_batch())
    snd.close()
    got = list(out["recv"].batches())
    assert len(got) == 1 and got[0]["value"].sum() == 28


# ------------------------------------------------------------ peer death

def test_receiver_killed_mid_stream_fails_sender_fast():
    """A receiver that dies mid-stream surfaces as an OSError on the
    sender's send path within bounded time — not a hang, not silence."""
    recv = RowReceiver(n_senders=1)
    snd = RowSender("127.0.0.1", recv.port)
    snd.send(mk_batch())
    recv.close()
    t0 = time.monotonic()
    with pytest.raises(OSError):
        # TCP buffers the first post-mortem sends; the RST lands within
        # a few round trips
        for i in range(1000):
            snd.send(mk_batch(lo=i))
            time.sleep(0.001)
    assert time.monotonic() - t0 < 30


def test_sender_killed_mid_stream_fails_receiver():
    """Hard sender death without EOS is an error from batches(), never a
    clean (truncated) end of stream."""
    recv = RowReceiver(n_senders=1)

    def half_send():
        snd = RowSender("127.0.0.1", recv.port)
        snd.send(mk_batch())
        snd._sock.shutdown(socket.SHUT_RDWR)
        snd._sock.close()

    t = threading.Thread(target=half_send)
    t.start()
    with pytest.raises((ConnectionError, OSError)):
        list(recv.batches())
    t.join()


def test_close_on_dead_peer_is_flagged_not_clean():
    """RowSender.close() must SURFACE an undeliverable EOS (peer already
    dead) instead of reporting a clean shutdown."""
    recv = RowReceiver(n_senders=1)
    snd = RowSender("127.0.0.1", recv.port)
    snd.send(mk_batch())

    class DeadSock:
        def sendall(self, data):
            raise BrokenPipeError("peer gone")

        def close(self):
            pass

    snd._sock.close()
    snd._sock = DeadSock()
    assert snd.failed is None
    with pytest.raises(ChannelError, match="not delivered"):
        snd.close()
    assert isinstance(snd.failed, OSError)


def test_never_connected_sender_bounded_by_accept_timeout():
    """A peer that dies before EVER connecting must surface within the
    accept window — not hang batches() forever waiting for accept()."""
    recv = RowReceiver(n_senders=1, accept_timeout=0.5)
    t0 = time.monotonic()
    with pytest.raises(PeerStall, match="0/1 senders"):
        list(recv.batches())
    assert time.monotonic() - t0 < 10


def test_receiver_close_wakes_blocked_batches():
    """close() during the accept phase must wake a consumer blocked in
    batches() with a classified error, not leave it blocked forever."""
    recv = RowReceiver(n_senders=1)
    result = {}

    def consume():
        try:
            list(recv.batches())
            result["err"] = None
        except Exception as e:  # noqa: BLE001 — asserted below
            result["err"] = e

    t = threading.Thread(target=consume, daemon=True)
    t.start()
    time.sleep(0.2)
    recv.close()
    t.join(timeout=10)
    assert not t.is_alive(), "batches() still blocked after close()"
    assert isinstance(result["err"], ChannelError)


def test_connect_deadline_clamps_attempt_timeout():
    """The per-attempt socket timeout is clamped to the remaining
    deadline, so a blackholed host cannot overshoot the bound by a whole
    attempt (attempt timeout 30s vs deadline 0.6s)."""
    # 10.255.255.1 is a non-routable address: SYNs are dropped silently
    # (blackhole) on typical CI hosts; if the network answers fast with
    # RST instead, the test still passes through the refused path
    t0 = time.monotonic()
    with pytest.raises((ConnectionError, OSError)):
        RowSender("10.255.255.1", 9, timeout=30.0, connect_deadline=0.6)
    assert time.monotonic() - t0 < 10


# -------------------------------------------------------- frame protocol

def test_truncated_frame_is_an_error():
    """A frame header promising more bytes than ever arrive must raise,
    not hang or truncate."""
    recv = RowReceiver(n_senders=1)
    raw = socket.create_connection(("127.0.0.1", recv.port))
    d = _encode_dtype(mk_batch().dtype)
    raw.sendall(_LEN.pack(len(d)) + d)
    raw.sendall(_LEN.pack(100) + b"only ten b")
    raw.close()
    with pytest.raises(ConnectionError, match="mid-frame"):
        list(recv.batches())


def test_garbage_frame_length_is_an_error():
    recv = RowReceiver(n_senders=1)
    raw = socket.create_connection(("127.0.0.1", recv.port))
    raw.sendall(_LEN.pack(-7))
    with pytest.raises(ChannelError, match="bad row-channel frame"):
        list(recv.batches())
    raw.close()


def test_abort_frame_distinguishable_from_eos():
    """abort() is the failure-path close: the receiver classifies it as
    PeerAbort (truncated prefix), NOT as a clean EOS."""
    recv = RowReceiver(n_senders=1)
    snd = RowSender("127.0.0.1", recv.port)
    snd.send(mk_batch())
    snd.abort()
    got = []
    with pytest.raises(PeerAbort, match="truncated"):
        for b in recv.batches():
            got.append(b)
    assert len(got) == 1    # data before the abort is delivered, flagged


# ------------------------------------------------------------ heartbeats

def test_heartbeat_stall_timeout_bounded():
    """A peer that goes silent mid-stream (no data, no heartbeat) trips
    the receiver's stall timeout within bounded wall-clock — the
    _read_exact-hangs-forever failure mode of the un-hardened channel."""
    recv = RowReceiver(n_senders=1, stall_timeout=0.5)
    raw = socket.create_connection(("127.0.0.1", recv.port))
    d = _encode_dtype(mk_batch().dtype)
    raw.sendall(_LEN.pack(len(d)) + d)
    t0 = time.monotonic()
    with pytest.raises(PeerStall, match="silent"):
        list(recv.batches())
    dt = time.monotonic() - t0
    assert dt < 10
    raw.close()


def test_heartbeats_keep_idle_link_alive():
    """An idle-but-alive sender (heartbeat < stall timeout) must NOT trip
    the stall timeout; the stream completes cleanly after the idle gap."""
    recv = RowReceiver(n_senders=1, stall_timeout=0.6)
    snd = RowSender("127.0.0.1", recv.port, heartbeat=0.1)
    err = []

    def feed():
        try:
            snd.send(mk_batch())
            time.sleep(1.3)         # > 2x stall timeout, bridged by beats
            snd.send(mk_batch(lo=100))
            snd.close()
        except Exception as e:  # noqa: BLE001 — surfaced via main thread
            err.append(e)

    t = threading.Thread(target=feed)
    t.start()
    got = list(recv.batches())
    t.join()
    assert not err
    assert len(got) == 2


def test_wire_config_defaults():
    w = WireConfig()
    assert (w.connect_deadline, w.heartbeat, w.stall_timeout) \
        == (None, None, None)       # bare = seed-identical protocol
    h = WireConfig.hardened()
    assert h.connect_deadline and h.heartbeat and h.stall_timeout
    assert h.stall_timeout >= 3 * h.heartbeat


# ----------------------------------------------- dataflow integration

def test_peer_death_surfaces_in_dataflow_errors():
    """The acceptance-criteria path: a multihost source feeding from a
    row channel whose peer stalls -> wait() raises within the stall
    timeout; the error lands in Dataflow._errors, no hang."""
    from windflow_tpu.patterns.basic import Sink, Source
    from windflow_tpu.runtime.engine import Dataflow
    from windflow_tpu.runtime.farm import build_pipeline

    recv = RowReceiver(n_senders=1, stall_timeout=0.5)
    raw = socket.create_connection(("127.0.0.1", recv.port))
    d = _encode_dtype(mk_batch().dtype)
    raw.sendall(_LEN.pack(len(d)) + d)
    payload = np.ascontiguousarray(mk_batch()).tobytes()
    raw.sendall(_LEN.pack(len(payload)) + payload)
    # ... then the peer stalls mid-stream, forever

    df = Dataflow("wire", capacity=4)
    build_pipeline(df, [Source(batches=recv.batches(), schema=SCHEMA),
                        Sink(lambda rows: None, vectorized=True)])
    t0 = time.monotonic()
    with pytest.raises(PeerStall):
        df.run_and_wait_end()
    assert time.monotonic() - t0 < 10
    assert any(isinstance(e, PeerStall) for e in df._errors)
    raw.close()


def test_open_row_plane_two_ends():
    """multihost.open_row_plane builds a full hardened plane in any boot
    order; a clean run round-trips, and closing is clean."""
    from windflow_tpu.parallel.multihost import open_row_plane

    addrs = {0: ("127.0.0.1", free_port()), 1: ("127.0.0.1", free_port())}
    wire = WireConfig(connect_deadline=30, heartbeat=0.2, stall_timeout=2.0)
    planes = {}

    def boot(pid):
        planes[pid] = open_row_plane(pid, addrs, wire=wire)

    threads = [threading.Thread(target=boot, args=(p,)) for p in (1, 0)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    r0, send0 = planes[0]
    r1, send1 = planes[1]
    send0[1].send(mk_batch())
    send0[1].close()
    send1[0].close()
    assert len(list(r1.batches())) == 1
    assert list(r0.batches()) == []


def test_open_row_plane_rejects_unknown_pid():
    with pytest.raises(KeyError, match="no entry"):
        from windflow_tpu.parallel.multihost import open_row_plane
        open_row_plane(7, {0: ("127.0.0.1", 1)})


# ------------------------------------------------- wire epoch alignment

def test_wire_epoch_frames_align_across_senders():
    """RowSender.send_epoch / batches(epoch_markers=True): the receiver
    yields EpochMarker(e) only once EVERY sender shipped its epoch-e
    frame — after all rows of epochs <= e, before any later-epoch row
    (rows from senders that run ahead are held back)."""
    from windflow_tpu.recovery.epoch import EpochMarker

    recv = RowReceiver(n_senders=2)
    s_a = RowSender("127.0.0.1", recv.port)
    s_b = RowSender("127.0.0.1", recv.port)
    # sender A runs two epochs ahead of sender B
    s_a.send(mk_batch(4, lo=0))        # epoch 1 content
    s_a.send_epoch(1)
    s_a.send(mk_batch(4, lo=100))      # epoch 2 content
    s_a.send_epoch(2)
    s_a.send(mk_batch(4, lo=200))      # tail content
    time.sleep(0.2)                    # let A's frames land first
    s_b.send(mk_batch(4, lo=1000))     # epoch 1 content
    s_b.send_epoch(1)
    s_b.send(mk_batch(4, lo=1100))     # epoch 2 content
    s_b.send_epoch(2)
    s_a.close()
    s_b.close()
    seq = list(recv.batches(epoch_markers=True))
    markers = [i for i, x in enumerate(seq) if isinstance(x, EpochMarker)]
    assert [seq[i].epoch for i in markers] == [1, 2]
    m1, m2 = markers
    lows = lambda idxs: {int(seq[i]["value"][0]) for i in idxs
                         if not isinstance(seq[i], EpochMarker)}
    # every epoch-1 row before marker 1; epoch-2 rows between the
    # markers; A's tail after marker 2
    assert {0, 1000} <= lows(range(m1))
    assert lows(range(m1)) & {100, 1100, 200} == set()
    assert lows(range(m1 + 1, m2)) == {100, 1100}
    assert lows(range(m2 + 1, len(seq))) == {200}
    # total content is conserved
    assert sum(len(x) for x in seq
               if not isinstance(x, EpochMarker)) == 20


def test_wire_epoch_frames_silent_without_optin():
    """Default batches() consumes epoch frames silently: same yielded
    rows as the un-epoched protocol."""
    recv = RowReceiver(n_senders=1)
    snd = RowSender("127.0.0.1", recv.port)
    snd.send(mk_batch(4))
    snd.send_epoch(1)
    snd.send(mk_batch(4, lo=50))
    snd.close()
    got = list(recv.batches())
    assert all(isinstance(b, np.ndarray) for b in got)
    assert sum(len(b) for b in got) == 8


def test_wire_epoch_eos_releases_held_rows():
    """A sender that closes while ahead of the barrier: EOS aligns it to
    every epoch, so held rows drain instead of truncating the stream."""
    recv = RowReceiver(n_senders=2)
    s_a = RowSender("127.0.0.1", recv.port)
    s_b = RowSender("127.0.0.1", recv.port)
    s_a.send(mk_batch(3))
    s_a.send_epoch(5)
    s_a.send(mk_batch(3, lo=10))   # beyond any epoch B will reach
    s_a.close()
    s_b.send(mk_batch(3, lo=20))
    s_b.close()                    # B never ships an epoch frame
    got = list(recv.batches(epoch_markers=True))
    rows = sum(len(x) for x in got if isinstance(x, np.ndarray))
    assert rows == 9               # nothing held forever, nothing lost
