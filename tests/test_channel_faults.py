"""Channel fault-injection tests (parallel/channel.py hardening): every
failure mode must surface as a *fast, classified error* — bounded
wall-clock, never an indefinite hang, never a silent truncation passed
off as clean EOS.  The wall-clock bounds are generous (CI jitter) but
orders of magnitude below "hang"."""

import socket
import threading
import time

import numpy as np
import pytest

from windflow_tpu.core.tuples import Schema, batch_from_columns
from windflow_tpu.parallel.channel import (_LEN, ChannelError, PeerAbort,
                                           PeerStall, RowReceiver,
                                           RowSender, WireConfig,
                                           WireResume, _encode_dtype)

SCHEMA = Schema(value=np.int64)


def mk_batch(n=8, lo=0):
    ids = np.arange(lo, lo + n)
    return batch_from_columns(SCHEMA, key=np.zeros(n), id=ids, ts=ids,
                              value=ids)


def free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


# ------------------------------------------------------- connect retries

def test_connection_refused_without_deadline_fails_immediately():
    t0 = time.monotonic()
    with pytest.raises(OSError):
        RowSender("127.0.0.1", free_port())
    assert time.monotonic() - t0 < 5


def test_connection_refused_with_deadline_bounded():
    """Backoff retries stop at the total deadline with a clear error —
    not one attempt, not forever."""
    t0 = time.monotonic()
    with pytest.raises(ConnectionError, match="attempts"):
        RowSender("127.0.0.1", free_port(), connect_deadline=0.5)
    dt = time.monotonic() - t0
    assert 0.3 < dt < 10


def test_connect_retry_reaches_late_receiver():
    """Peers boot in any order: a sender started BEFORE its receiver
    connects once the receiver comes up (exponential backoff + jitter)."""
    port = free_port()
    out = {}

    def late_boot():
        time.sleep(0.4)
        out["recv"] = RowReceiver(n_senders=1, port=port)

    t = threading.Thread(target=late_boot)
    t.start()
    snd = RowSender("127.0.0.1", port, connect_deadline=30)
    t.join()
    snd.send(mk_batch())
    snd.close()
    got = list(out["recv"].batches())
    assert len(got) == 1 and got[0]["value"].sum() == 28


# ------------------------------------------------------------ peer death

def test_receiver_killed_mid_stream_fails_sender_fast():
    """A receiver that dies mid-stream surfaces as an OSError on the
    sender's send path within bounded time — not a hang, not silence."""
    recv = RowReceiver(n_senders=1)
    snd = RowSender("127.0.0.1", recv.port)
    snd.send(mk_batch())
    recv.close()
    t0 = time.monotonic()
    with pytest.raises(OSError):
        # TCP buffers the first post-mortem sends; the RST lands within
        # a few round trips
        for i in range(1000):
            snd.send(mk_batch(lo=i))
            time.sleep(0.001)
    assert time.monotonic() - t0 < 30


def test_sender_killed_mid_stream_fails_receiver():
    """Hard sender death without EOS is an error from batches(), never a
    clean (truncated) end of stream."""
    recv = RowReceiver(n_senders=1)

    def half_send():
        snd = RowSender("127.0.0.1", recv.port)
        snd.send(mk_batch())
        snd._sock.shutdown(socket.SHUT_RDWR)
        snd._sock.close()

    t = threading.Thread(target=half_send)
    t.start()
    with pytest.raises((ConnectionError, OSError)):
        list(recv.batches())
    t.join()


def test_close_on_dead_peer_is_flagged_not_clean():
    """RowSender.close() must SURFACE an undeliverable EOS (peer already
    dead) instead of reporting a clean shutdown."""
    recv = RowReceiver(n_senders=1)
    snd = RowSender("127.0.0.1", recv.port)
    snd.send(mk_batch())

    class DeadSock:
        def sendall(self, data):
            raise BrokenPipeError("peer gone")

        def close(self):
            pass

    snd._sock.close()
    snd._sock = DeadSock()
    assert snd.failed is None
    with pytest.raises(ChannelError, match="not delivered"):
        snd.close()
    assert isinstance(snd.failed, OSError)


def test_never_connected_sender_bounded_by_accept_timeout():
    """A peer that dies before EVER connecting must surface within the
    accept window — not hang batches() forever waiting for accept()."""
    recv = RowReceiver(n_senders=1, accept_timeout=0.5)
    t0 = time.monotonic()
    with pytest.raises(PeerStall, match="0/1 senders"):
        list(recv.batches())
    assert time.monotonic() - t0 < 10


def test_receiver_close_wakes_blocked_batches():
    """close() during the accept phase must wake a consumer blocked in
    batches() with a classified error, not leave it blocked forever."""
    recv = RowReceiver(n_senders=1)
    result = {}

    def consume():
        try:
            list(recv.batches())
            result["err"] = None
        except Exception as e:  # noqa: BLE001 — asserted below
            result["err"] = e

    t = threading.Thread(target=consume, daemon=True)
    t.start()
    time.sleep(0.2)
    recv.close()
    t.join(timeout=10)
    assert not t.is_alive(), "batches() still blocked after close()"
    assert isinstance(result["err"], ChannelError)


def _blackhole_addr():
    """((host, port), holder): an address this host cannot complete a
    connect to.  10.255.255.1 drops SYNs silently on typical CI hosts,
    but some container networks NAT it to a real listener — probe first,
    and fall back to a bound-but-never-listening local socket (connects
    get RST: the refused path, still deadline-bounded)."""
    probe = socket.socket()
    probe.settimeout(0.25)
    try:
        probe.connect(("10.255.255.1", 9))
    except OSError:
        # timed out (genuine blackhole) or refused fast — either way the
        # address never yields a usable connection, so keep it
        probe.close()
        return ("10.255.255.1", 9), None
    probe.close()
    hold = socket.socket()
    hold.bind(("127.0.0.1", 0))      # bound, no listen(): RST on connect
    return hold.getsockname(), hold


def test_connect_deadline_clamps_attempt_timeout():
    """The per-attempt socket timeout is clamped to the remaining
    deadline, so a blackholed host cannot overshoot the bound by a whole
    attempt (attempt timeout 30s vs deadline 0.6s)."""
    (host, port), hold = _blackhole_addr()
    try:
        t0 = time.monotonic()
        with pytest.raises((ConnectionError, OSError)):
            RowSender(host, port, timeout=30.0, connect_deadline=0.6)
        assert time.monotonic() - t0 < 10
    finally:
        if hold is not None:
            hold.close()


# -------------------------------------------------------- frame protocol

def test_truncated_frame_is_an_error():
    """A frame header promising more bytes than ever arrive must raise,
    not hang or truncate."""
    recv = RowReceiver(n_senders=1)
    raw = socket.create_connection(("127.0.0.1", recv.port))
    d = _encode_dtype(mk_batch().dtype)
    raw.sendall(_LEN.pack(len(d)) + d)
    raw.sendall(_LEN.pack(100) + b"only ten b")
    raw.close()
    with pytest.raises(ConnectionError, match="mid-frame"):
        list(recv.batches())


def test_garbage_frame_length_is_an_error():
    recv = RowReceiver(n_senders=1)
    raw = socket.create_connection(("127.0.0.1", recv.port))
    raw.sendall(_LEN.pack(-99))   # below every known control family
    with pytest.raises(ChannelError, match="bad row-channel frame"):
        list(recv.batches())
    raw.close()


def test_ckpt_frame_with_garbage_subtype_is_an_error():
    """-7 is the portable-checkpoint family: a frame carrying an unknown
    subtype must raise, not hang waiting for a payload."""
    recv = RowReceiver(n_senders=1)
    raw = socket.create_connection(("127.0.0.1", recv.port))
    raw.sendall(_LEN.pack(-7) + _LEN.pack(99))
    with pytest.raises(ChannelError, match="ckpt subtype"):
        list(recv.batches())
    raw.close()


def test_abort_frame_distinguishable_from_eos():
    """abort() is the failure-path close: the receiver classifies it as
    PeerAbort (truncated prefix), NOT as a clean EOS."""
    recv = RowReceiver(n_senders=1)
    snd = RowSender("127.0.0.1", recv.port)
    snd.send(mk_batch())
    snd.abort()
    got = []
    with pytest.raises(PeerAbort, match="truncated"):
        for b in recv.batches():
            got.append(b)
    assert len(got) == 1    # data before the abort is delivered, flagged


# ------------------------------------------------------------ heartbeats

def test_heartbeat_stall_timeout_bounded():
    """A peer that goes silent mid-stream (no data, no heartbeat) trips
    the receiver's stall timeout within bounded wall-clock — the
    _read_exact-hangs-forever failure mode of the un-hardened channel."""
    recv = RowReceiver(n_senders=1, stall_timeout=0.5)
    raw = socket.create_connection(("127.0.0.1", recv.port))
    d = _encode_dtype(mk_batch().dtype)
    raw.sendall(_LEN.pack(len(d)) + d)
    t0 = time.monotonic()
    with pytest.raises(PeerStall, match="silent"):
        list(recv.batches())
    dt = time.monotonic() - t0
    assert dt < 10
    raw.close()


def test_heartbeats_keep_idle_link_alive():
    """An idle-but-alive sender (heartbeat < stall timeout) must NOT trip
    the stall timeout; the stream completes cleanly after the idle gap."""
    recv = RowReceiver(n_senders=1, stall_timeout=0.6)
    snd = RowSender("127.0.0.1", recv.port, heartbeat=0.1)
    err = []

    def feed():
        try:
            snd.send(mk_batch())
            time.sleep(1.3)         # > 2x stall timeout, bridged by beats
            snd.send(mk_batch(lo=100))
            snd.close()
        except Exception as e:  # noqa: BLE001 — surfaced via main thread
            err.append(e)

    t = threading.Thread(target=feed)
    t.start()
    got = list(recv.batches())
    t.join()
    assert not err
    assert len(got) == 2


def test_wire_config_defaults():
    w = WireConfig()
    assert (w.connect_deadline, w.heartbeat, w.stall_timeout) \
        == (None, None, None)       # bare = seed-identical protocol
    h = WireConfig.hardened()
    assert h.connect_deadline and h.heartbeat and h.stall_timeout
    assert h.stall_timeout >= 3 * h.heartbeat


# ----------------------------------------------- dataflow integration

def test_peer_death_surfaces_in_dataflow_errors():
    """The acceptance-criteria path: a multihost source feeding from a
    row channel whose peer stalls -> wait() raises within the stall
    timeout; the error lands in Dataflow._errors, no hang."""
    from windflow_tpu.patterns.basic import Sink, Source
    from windflow_tpu.runtime.engine import Dataflow
    from windflow_tpu.runtime.farm import build_pipeline

    recv = RowReceiver(n_senders=1, stall_timeout=0.5)
    raw = socket.create_connection(("127.0.0.1", recv.port))
    d = _encode_dtype(mk_batch().dtype)
    raw.sendall(_LEN.pack(len(d)) + d)
    payload = np.ascontiguousarray(mk_batch()).tobytes()
    raw.sendall(_LEN.pack(len(payload)) + payload)
    # ... then the peer stalls mid-stream, forever

    df = Dataflow("wire", capacity=4)
    build_pipeline(df, [Source(batches=recv.batches(), schema=SCHEMA),
                        Sink(lambda rows: None, vectorized=True)])
    t0 = time.monotonic()
    with pytest.raises(PeerStall):
        df.run_and_wait_end()
    assert time.monotonic() - t0 < 10
    assert any(isinstance(e, PeerStall) for e in df._errors)
    raw.close()


def test_open_row_plane_two_ends():
    """multihost.open_row_plane builds a full hardened plane in any boot
    order; a clean run round-trips, and closing is clean."""
    from windflow_tpu.parallel.multihost import open_row_plane

    addrs = {0: ("127.0.0.1", free_port()), 1: ("127.0.0.1", free_port())}
    wire = WireConfig(connect_deadline=30, heartbeat=0.2, stall_timeout=2.0)
    planes = {}

    def boot(pid):
        planes[pid] = open_row_plane(pid, addrs, wire=wire)

    threads = [threading.Thread(target=boot, args=(p,)) for p in (1, 0)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    r0, send0 = planes[0]
    r1, send1 = planes[1]
    send0[1].send(mk_batch())
    send0[1].close()
    send1[0].close()
    assert len(list(r1.batches())) == 1
    assert list(r0.batches()) == []


def test_open_row_plane_rejects_unknown_pid():
    with pytest.raises(KeyError, match="no entry"):
        from windflow_tpu.parallel.multihost import open_row_plane
        open_row_plane(7, {0: ("127.0.0.1", 1)})


# ------------------------------------------------- wire epoch alignment

def test_wire_epoch_frames_align_across_senders():
    """RowSender.send_epoch / batches(epoch_markers=True): the receiver
    yields EpochMarker(e) only once EVERY sender shipped its epoch-e
    frame — after all rows of epochs <= e, before any later-epoch row
    (rows from senders that run ahead are held back)."""
    from windflow_tpu.recovery.epoch import EpochMarker

    recv = RowReceiver(n_senders=2)
    s_a = RowSender("127.0.0.1", recv.port)
    s_b = RowSender("127.0.0.1", recv.port)
    # sender A runs two epochs ahead of sender B
    s_a.send(mk_batch(4, lo=0))        # epoch 1 content
    s_a.send_epoch(1)
    s_a.send(mk_batch(4, lo=100))      # epoch 2 content
    s_a.send_epoch(2)
    s_a.send(mk_batch(4, lo=200))      # tail content
    time.sleep(0.2)                    # let A's frames land first
    s_b.send(mk_batch(4, lo=1000))     # epoch 1 content
    s_b.send_epoch(1)
    s_b.send(mk_batch(4, lo=1100))     # epoch 2 content
    s_b.send_epoch(2)
    s_a.close()
    s_b.close()
    seq = list(recv.batches(epoch_markers=True))
    markers = [i for i, x in enumerate(seq) if isinstance(x, EpochMarker)]
    assert [seq[i].epoch for i in markers] == [1, 2]
    m1, m2 = markers
    lows = lambda idxs: {int(seq[i]["value"][0]) for i in idxs
                         if not isinstance(seq[i], EpochMarker)}
    # every epoch-1 row before marker 1; epoch-2 rows between the
    # markers; A's tail after marker 2
    assert {0, 1000} <= lows(range(m1))
    assert lows(range(m1)) & {100, 1100, 200} == set()
    assert lows(range(m1 + 1, m2)) == {100, 1100}
    assert lows(range(m2 + 1, len(seq))) == {200}
    # total content is conserved
    assert sum(len(x) for x in seq
               if not isinstance(x, EpochMarker)) == 20


def test_wire_epoch_frames_silent_without_optin():
    """Default batches() consumes epoch frames silently: same yielded
    rows as the un-epoched protocol."""
    recv = RowReceiver(n_senders=1)
    snd = RowSender("127.0.0.1", recv.port)
    snd.send(mk_batch(4))
    snd.send_epoch(1)
    snd.send(mk_batch(4, lo=50))
    snd.close()
    got = list(recv.batches())
    assert all(isinstance(b, np.ndarray) for b in got)
    assert sum(len(b) for b in got) == 8


def test_wire_epoch_eos_releases_held_rows():
    """A sender that closes while ahead of the barrier: EOS aligns it to
    every epoch, so held rows drain instead of truncating the stream."""
    recv = RowReceiver(n_senders=2)
    s_a = RowSender("127.0.0.1", recv.port)
    s_b = RowSender("127.0.0.1", recv.port)
    s_a.send(mk_batch(3))
    s_a.send_epoch(5)
    s_a.send(mk_batch(3, lo=10))   # beyond any epoch B will reach
    s_a.close()
    s_b.send(mk_batch(3, lo=20))
    s_b.close()                    # B never ships an epoch frame
    got = list(recv.batches(epoch_markers=True))
    rows = sum(len(x) for x in got if isinstance(x, np.ndarray))
    assert rows == 9               # nothing held forever, nothing lost


# ------------------------------------------------------------ wire resume
# docs/ROBUSTNESS.md "Wire resume": sender journals + -6 handshake +
# seq dedup make peer death on an established edge a bounded retry.
# Everything here is opt-in; the first tests pin the opt-OUT contract.

def _values(seq):
    """All row values, in arrival order, from a batches() iteration."""
    out = []
    for x in seq:
        if isinstance(x, np.ndarray):
            out.extend(int(v) for v in x["value"])
    return out


def test_wire_config_validate_called_from_constructors():
    """Satellite: a direct-constructed pair must reject an inconsistent
    WireConfig (WF205) at the constructor, not only via open_row_plane."""
    bad = WireConfig(heartbeat=5.0, stall_timeout=2.0)
    with pytest.raises(ValueError, match="WF205"):
        RowSender("127.0.0.1", 1, wire=bad)
    with pytest.raises(ValueError, match="WF205"):
        RowReceiver(n_senders=1, wire=bad)


def test_resume_unset_wire_is_byte_identical_to_seed():
    """resume= unset: the wire carries ONLY the seed grammar (dtype
    frame, data frames, -4 epochs, -1 EOS) — no -6 frames, no journal,
    no ack thread.  Captured off a raw socket so nothing in the channel
    implementation can vouch for itself."""
    srv = socket.create_server(("127.0.0.1", 0))
    port = srv.getsockname()[1]

    def feed():
        s = RowSender("127.0.0.1", port)
        s.send(mk_batch(4))
        s.send_epoch(1)
        s.send(mk_batch(4, lo=50))
        s.close()
        assert not hasattr(s, "_journal"), "journal built without resume="
        assert s._ack_thread is None if hasattr(s, "_ack_thread") else True

    t = threading.Thread(target=feed)
    t.start()
    conn, _ = srv.accept()
    raw = bytearray()
    while True:
        chunk = conn.recv(65536)
        if not chunk:
            break
        raw.extend(chunk)
    t.join()
    conn.close()
    srv.close()
    # parse the whole stream with the SEED grammar
    lens, off = [], 0
    while off < len(raw):
        (n,) = _LEN.unpack(bytes(raw[off:off + 8]))
        off += 8
        lens.append(n)
        if n > 0:
            off += n
        elif n == -4:
            off += 8
        else:
            assert n == -1, f"non-seed control frame {n} on the wire"
    assert off == len(raw)
    # dtype frame, data, epoch, data, EOS — and nothing else
    assert [n for n in lens if n < 0] == [-4, -1]
    assert sum(1 for n in lens if n > 0) == 3   # dtype + 2 payloads


def test_faults_module_never_imported_without_a_plan():
    """The chaos harness is dead weight unless threaded in: a plan-less
    roundtrip must not even import parallel.faults."""
    import subprocess
    import sys as _sys
    code = (
        "import sys\n"
        "import numpy as np\n"
        "from windflow_tpu.core.tuples import Schema, batch_from_columns\n"
        "from windflow_tpu.parallel.channel import RowReceiver, RowSender\n"
        "r = RowReceiver(n_senders=1)\n"
        "s = RowSender('127.0.0.1', r.port)\n"
        "ids = np.arange(4)\n"
        "s.send(batch_from_columns(Schema(value=np.int64), key=ids*0,\n"
        "                          id=ids, ts=ids, value=ids))\n"
        "s.close()\n"
        "assert sum(len(b) for b in r.batches()) == 4\n"
        "assert 'windflow_tpu.parallel.faults' not in sys.modules\n"
    )
    proc = subprocess.run([_sys.executable, "-c", code],
                          capture_output=True, timeout=120,
                          env={**__import__('os').environ,
                               "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 0, proc.stderr.decode()[-2000:]


def test_resume_roundtrip_preserves_order_and_markers():
    """A resumable edge with no faults yields exactly the seed
    sequence: rows in order, EpochMarker at the barrier."""
    from windflow_tpu.recovery.epoch import EpochMarker
    rs = WireResume(deadline=10.0)
    recv = RowReceiver(n_senders=1, resume=rs)
    snd = RowSender("127.0.0.1", recv.port, resume=rs)
    snd.send(mk_batch(4))
    snd.send_epoch(1)
    snd.send(mk_batch(4, lo=50))
    snd.close()
    seq = list(recv.batches(epoch_markers=True))
    recv.close()
    markers = [i for i, x in enumerate(seq) if isinstance(x, EpochMarker)]
    assert len(markers) == 1 and seq[markers[0]].epoch == 1
    assert _values(seq) == list(range(4)) + list(range(50, 54))


def test_resume_receiver_restart_replays_tail():
    """Kill the receiver mid-stream; a restarted receiver on the same
    port gets the whole journaled tail replayed — nothing lost."""
    rs = WireResume(deadline=20.0)
    r1 = RowReceiver(n_senders=1, resume=rs)
    port = r1.port
    snd = RowSender("127.0.0.1", port, resume=rs, connect_deadline=10.0)
    for i in range(8):
        snd.send(mk_batch(1, lo=i))
    r1.close()                      # peer death, no EOS seen
    r2 = RowReceiver(n_senders=1, port=port, resume=rs)
    for i in range(8, 16):
        snd.send(mk_batch(1, lo=i))
    snd.close()
    vals = _values(r2.batches())
    r2.close()
    # r1 consumed nothing, so the fresh receiver sees the full stream
    assert sorted(set(vals)) == list(range(16))
    assert vals == sorted(vals), "replay broke arrival order"


@pytest.mark.parametrize("seed", [1, 2, 3])
def test_resume_fault_plan_differential(seed):
    """Acceptance: >= 3 distinct seeded FaultPlans, output byte-identical
    (values, order) to the unfaulted oracle."""
    from windflow_tpu.parallel.faults import FaultPlan
    plan = FaultPlan.seeded(seed, horizon=28, n_faults=3,
                            kinds=("kill", "torn", "dup"))
    rs = WireResume(deadline=15.0)
    recv = RowReceiver(n_senders=1, resume=rs)
    got, errs = [], []

    def consume():
        try:
            got.extend(_values(recv.batches(epoch_markers=True)))
        except Exception as e:
            errs.append(e)

    t = threading.Thread(target=consume, daemon=True)
    t.start()
    snd = RowSender("127.0.0.1", recv.port, resume=rs, faults=plan,
                    connect_deadline=10.0)
    for i in range(24):
        snd.send(mk_batch(1, lo=i))
        if (i + 1) % 6 == 0:
            snd.send_epoch((i + 1) // 6)
    snd.close()
    t.join(timeout=60)
    assert not t.is_alive() and not errs, (plan, errs)
    recv.close()
    assert got == list(range(24)), (plan, got)


def test_resume_dup_faults_dedup_by_seq():
    """Duplicated delivery (the at-least-once replay race) must be
    absorbed by seq dedup: exactly-once yield, exact order."""
    from windflow_tpu.parallel.faults import FaultPlan
    rs = WireResume(deadline=10.0)
    recv = RowReceiver(n_senders=1, resume=rs)
    snd = RowSender("127.0.0.1", recv.port, resume=rs,
                    faults=FaultPlan(dup_at=(3, 6)))
    for i in range(8):
        snd.send(mk_batch(1, lo=i))
    snd.close()
    vals = _values(recv.batches())
    recv.close()
    assert vals == list(range(8))


def test_kill_peer_mid_epoch_restart_matches_oracle():
    """Acceptance: receiver killed and restarted mid-epoch with
    resume_epoch=K — sealed-epoch output from the dead receiver plus the
    restarted receiver's output equals the unkilled oracle, per-key
    byte-identical."""
    from windflow_tpu.recovery.epoch import EpochMarker

    def drive(port, rs, half_sent, proceed):
        """Epoch 1, then HALF of epoch 2, then (gated) the rest — the
        gate keeps the sender alive across the receiver's death, so the
        kill really lands mid-epoch."""
        snd = RowSender("127.0.0.1", port, resume=rs,
                        connect_deadline=10.0)
        for i in range(4):
            snd.send(mk_batch(1, lo=i))
        snd.send_epoch(1)
        for i in range(4, 8):
            snd.send(mk_batch(1, lo=i))
        half_sent.set()
        assert proceed.wait(30)
        for i in range(8, 12):
            snd.send(mk_batch(1, lo=i))
        snd.send_epoch(2)
        snd.close()

    def events(pre_set=False):
        a, b = threading.Event(), threading.Event()
        if pre_set:
            b.set()
        return a, b

    # oracle: the same stream, nobody dies (gate pre-opened)
    rs = WireResume(deadline=20.0)
    r = RowReceiver(n_senders=1, resume=rs)
    t = threading.Thread(target=drive, args=(r.port, rs, *events(True)))
    t.start()
    oracle = _values(r.batches())
    t.join()
    r.close()

    # killed run: r1 consumes exactly the sealed epoch 1, then dies
    # while epoch 2 is half on the wire
    r1 = RowReceiver(n_senders=1, resume=rs)
    port = r1.port
    sealed, entered = [], threading.Event()

    def consume_epoch1():
        for x in r1.batches(epoch_markers=True):
            if isinstance(x, EpochMarker):
                break
            sealed.extend(int(v) for v in x["value"])
        entered.set()

    ct = threading.Thread(target=consume_epoch1, daemon=True)
    ct.start()
    half_sent, proceed = events()
    st = threading.Thread(target=drive, args=(port, rs, half_sent,
                                              proceed))
    st.start()
    assert entered.wait(30), "epoch-1 barrier never completed"
    assert half_sent.wait(30)
    ct.join(timeout=10)
    r1.close()                               # mid-epoch-2 death
    r2 = RowReceiver(n_senders=1, port=port, resume=rs, resume_epoch=1)
    proceed.set()
    tail = _values(r2.batches())
    st.join(timeout=30)
    r2.close()
    assert sealed + tail == oracle


def test_resume_journal_trims_on_epoch_ack():
    """ack_epochs (WireConfig recovery=): each completed barrier acks
    back and the sender journal trims to the unsealed tail — bounded by
    epoch width, the WF214 contract."""
    rs = WireResume(deadline=10.0)
    recv = RowReceiver(n_senders=1, resume=rs, ack_epochs=True)
    done = threading.Event()

    def consume():
        for _ in recv.batches(epoch_markers=True):
            pass
        done.set()

    t = threading.Thread(target=consume, daemon=True)
    t.start()
    snd = RowSender("127.0.0.1", recv.port, resume=rs)
    for e in range(1, 4):
        for i in range(4):
            snd.send(mk_batch(1, lo=e * 10 + i))
        snd.send_epoch(e)
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        with snd._journal_mu:
            depth = len(snd._journal)
        if depth == 0:
            break
        time.sleep(0.05)
    assert depth == 0, f"journal never trimmed (depth {depth})"
    snd.close()
    assert done.wait(10)
    recv.close()


def test_resume_journal_overflow_fails_loudly():
    """A journal past its cap evicts; a resume that would need the
    evicted prefix must raise ChannelError, never silently truncate."""
    rs = WireResume(deadline=3.0, journal_frames=4)
    r1 = RowReceiver(n_senders=1, resume=rs)
    port = r1.port
    snd = RowSender("127.0.0.1", port, resume=rs, connect_deadline=5.0)
    for i in range(10):                 # no acks: floor moves past 0
        snd.send(mk_batch(1, lo=i))
    r1.close()
    r2 = RowReceiver(n_senders=1, port=port, resume=rs)
    with pytest.raises(ChannelError):
        # the fresh receiver answers WELCOME{seq: 0} < journal floor
        for i in range(10, 40):
            snd.send(mk_batch(1, lo=i))
            time.sleep(0.05)
    snd.abort()
    r2.close()


def test_resume_counters_and_events():
    """Resume telemetry: wire_down/wire_resume events and the
    wire_resumes / wire_replayed_frames counters (docs/OBSERVABILITY.md)."""
    from windflow_tpu.obs import EventLog, MetricsRegistry
    reg, log = MetricsRegistry(), EventLog()
    rs = WireResume(deadline=20.0)
    r1 = RowReceiver(n_senders=1, resume=rs, metrics=reg, events=log)
    port = r1.port
    snd = RowSender("127.0.0.1", port, resume=rs, connect_deadline=10.0,
                    metrics=reg, events=log)
    for i in range(4):
        snd.send(mk_batch(1, lo=i))
    r1.close()
    r2 = RowReceiver(n_senders=1, port=port, resume=rs,
                     metrics=reg, events=log)
    for i in range(4, 8):
        snd.send(mk_batch(1, lo=i))
    snd.close()
    assert _values(r2.batches()) == list(range(8))
    r2.close()
    assert reg.counter("wire_resumes").value >= 1
    assert reg.counter("wire_replayed_frames").value >= 1
    kinds = {e["event"] for e in log.recent}
    assert {"wire_down", "wire_resume"} <= kinds


def test_open_row_plane_resume_knob_plumbs_through():
    """open_row_plane(resume=...) hands the knob to both halves of the
    plane; unset leaves the raw seed channel objects."""
    from windflow_tpu.parallel.multihost import open_row_plane
    p0, p1 = free_port(), free_port()
    addrs = {0: ("127.0.0.1", p0), 1: ("127.0.0.1", p1)}
    rs = WireResume(deadline=10.0)

    planes = {}

    def open_half(pid):
        planes[pid] = open_row_plane(pid, addrs, resume=rs)

    t = threading.Thread(target=open_half, args=(1,))
    t.start()
    open_half(0)
    t.join(timeout=30)
    recv0, senders0 = planes[0]
    recv1, senders1 = planes[1]
    try:
        assert recv0._resume is rs and recv1._resume is rs
        assert senders0[1]._resume is rs and senders1[0]._resume is rs
        senders0[1].send(mk_batch(3))
        senders0[1].close()
        senders1[0].close()
        assert sum(len(b) for b in recv1.batches()) == 3
        assert sum(len(b) for b in recv0.batches()) == 0
    finally:
        for r in (recv0, recv1):
            r.close()


@pytest.mark.slow
def test_soak_wire_slice():
    """Small in-suite slice of scripts/soak_wire.py (the full soak is a
    standalone seeded harness, docs/ROBUSTNESS.md "Wire resume")."""
    import importlib.util
    import os
    spec = importlib.util.spec_from_file_location(
        "soak_wire", os.path.join(os.path.dirname(__file__), os.pardir,
                                  "scripts", "soak_wire.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    for case in range(6):
        mod.run_case(seed=7, case=case)
