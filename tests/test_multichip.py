"""Multi-chip wiring of the streaming patterns, on the virtual 8-device CPU
mesh (conftest): farm workers own one device each (the reference gives each
GPU worker its own stream/device, win_farm_gpu.hpp:132-168), and the
mesh-resident executor serves every key group from ONE sharded dispatch
(ring P(kf, None), ops/resident.py:MeshResidentExecutor)."""

import numpy as np
import pytest

import jax

from windflow_tpu.core.windows import WinType
from windflow_tpu.ops.functions import Reducer
from windflow_tpu.parallel.mesh import make_mesh
from windflow_tpu.patterns.win_seq import WinSeq
from windflow_tpu.patterns.win_seq_tpu import (KeyFarmTPU, WinFarmTPU,
                                               WinSeqTPU)

from test_farms import cb_stream_batches, run_windowed, tb_stream_batches

KEYS, N = 8, 120
WIN, SLIDE = 12, 4


def stream(wt):
    return (cb_stream_batches(KEYS, N) if wt is WinType.CB
            else tb_stream_batches(KEYS, N))


def worker_devices(farm):
    """Every device owning a ring/executor across the farm's replicas."""
    devs = set()
    for r in farm.replicas():
        core = r.core
        ex = getattr(core, "executor", None)
        if ex is not None:
            devs.add(ex.device)
        for sub in getattr(core, "executors", []):
            devs.add(sub.device)
    return devs


@pytest.mark.parametrize("farm_cls", [KeyFarmTPU, WinFarmTPU],
                         ids=["kf", "wf"])
def test_farm_workers_spread_over_devices(farm_cls):
    n_dev = len(jax.devices())
    assert n_dev == 8, "conftest must provide the virtual 8-device mesh"
    farm = farm_cls(Reducer("sum"), WIN, SLIDE, WinType.CB, pardegree=8,
                    batch_len=16)
    devs = worker_devices(farm)
    assert len(devs) == 8, (
        f"pardegree=8 farm placed rings on {len(devs)} devices, want 8")


def test_farm_device_list_pins_workers():
    """An explicit device list spreads over exactly those devices."""
    pick = jax.devices()[:2]
    farm = KeyFarmTPU(Reducer("sum"), WIN, SLIDE, WinType.CB, pardegree=4,
                      batch_len=16, device=pick)
    assert worker_devices(farm) == set(pick)


def test_farm_single_device_pins_all_workers():
    d = jax.devices()[3]
    farm = KeyFarmTPU(Reducer("sum"), WIN, SLIDE, WinType.CB, pardegree=4,
                      batch_len=16, device=d)
    assert worker_devices(farm) == {d}


@pytest.mark.parametrize("wt", [WinType.CB, WinType.TB], ids=["cb", "tb"])
@pytest.mark.parametrize("farm_cls", [KeyFarmTPU, WinFarmTPU],
                         ids=["kf", "wf"])
def test_spread_farm_matches_seq(farm_cls, wt):
    """Differential: an 8-worker farm spread over 8 devices produces the
    host Win_Seq totals with per-key in-order delivery."""
    ref = run_windowed(WinSeq(Reducer("sum"), WIN, SLIDE, wt), stream(wt))
    got = run_windowed(
        farm_cls(Reducer("sum"), WIN, SLIDE, wt, pardegree=8, batch_len=16),
        stream(wt))
    assert got.keys() == ref.keys()
    for k in ref:
        assert got[k] == ref[k], f"key {k} mismatch"


# ---------------------------------------------------------- mesh-resident

@pytest.mark.parametrize("wt", [WinType.CB, WinType.TB], ids=["cb", "tb"])
@pytest.mark.parametrize("op", ["sum", "max"])
def test_mesh_resident_matches_seq(wt, op):
    """One WinSeqTPU, ring sharded P(kf, None) over a 4-device mesh: one
    dispatch serves all key groups; totals equal the host core's."""
    mesh = make_mesh(n_kf=4)
    ref = run_windowed(WinSeq(Reducer(op), WIN, SLIDE, wt), stream(wt))
    got = run_windowed(
        WinSeqTPU(Reducer(op), WIN, SLIDE, wt, batch_len=16, mesh=mesh),
        stream(wt))
    assert got == ref


def test_mesh_resident_uses_all_mesh_devices():
    """Every mesh device must hold live archive rows — the stride mapping
    (row r -> shard r % S) balances keys over the shards, not just the
    NamedSharding's formal block count."""
    mesh = make_mesh(n_kf=8)
    core = WinSeqTPU(Reducer("sum"), WIN, SLIDE, WinType.CB, batch_len=16,
                     mesh=mesh).make_core()
    outs = [core.process(b) for b in stream(WinType.CB)]
    outs.append(core.flush())
    assert sum(len(o) for o in outs) > 0
    ring = core.executor._ring
    assert ring is not None
    shards = list(ring.addressable_shards)
    devs = {s.device for s in shards}
    assert len(devs) == 8
    # 8 keys over 8 shards: each shard owns exactly one live key's rows
    occupancy = [bool(np.asarray(s.data).any()) for s in shards]
    assert all(occupancy), f"idle shards: {occupancy}"


def test_mesh_resident_rejects_non_monoid():
    mesh = make_mesh(n_kf=2)
    with pytest.raises(ValueError, match="resident-path Reducer"):
        WinSeqTPU(Reducer("count"), WIN, SLIDE, WinType.CB,
                  mesh=mesh).make_core()


def test_mesh_resident_many_keys_rebase():
    """Key cardinality beyond the initial ring forces rebases across the
    sharded ring; totals must survive them."""
    mesh = make_mesh(n_kf=4)
    keys, n = 37, 60   # not a multiple of the shard count
    ref = run_windowed(WinSeq(Reducer("sum"), 8, 8, WinType.CB),
                       cb_stream_batches(keys, n))
    got = run_windowed(
        WinSeqTPU(Reducer("sum"), 8, 8, WinType.CB, batch_len=8,
                  flush_rows=64, mesh=mesh),
        cb_stream_batches(keys, n))
    assert got == ref


def test_mesh_routes_through_native_core():
    """r2 weak #3: make_core_for(mesh=) must ride the C++ bookkeeping when
    the native lib is available — not re-pay the Python hot loop on the
    multi-chip path."""
    from windflow_tpu import native as native_mod
    if native_mod.enabled() is None:
        pytest.skip("native library unavailable")
    from windflow_tpu.ops.resident import MeshResidentExecutor
    from windflow_tpu.patterns.native_core import NativeResidentCore
    mesh = make_mesh(n_kf=4)
    core = WinSeqTPU(Reducer("sum"), WIN, SLIDE, WinType.CB,
                     mesh=mesh).make_core()
    assert isinstance(core, NativeResidentCore)
    assert isinstance(core.executors[0], MeshResidentExecutor)


def test_mesh_multistat_matches_host():
    """Multi-stat MultiReducer (sum + max over one field, plus count) on
    the sharded ring: every stat evaluates in ONE mesh dispatch (r2 weak
    #3 'single-stat only' resolved)."""
    from windflow_tpu.core.windows import WindowSpec
    from windflow_tpu.core.winseq import WinSeqCore
    from windflow_tpu.ops.functions import MultiReducer
    from windflow_tpu.patterns.win_seq_tpu import make_core_for
    mk = MultiReducer(("count", None, "cnt"), ("sum", "value", "sm"),
                      ("max", "value", "mx"))
    spec = WindowSpec(WIN, SLIDE, WinType.CB)
    mesh = make_mesh(n_kf=4)
    batches = cb_stream_batches(11, 90)

    def run_core(core):
        outs = [core.process(b) for b in batches]
        outs.append(core.flush())
        outs = [o for o in outs if len(o)]
        res = np.concatenate(outs)
        return np.sort(res, order=["key", "id"])

    import warnings
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        got = run_core(make_core_for(spec, mk, mesh=mesh, batch_len=16))
    want = run_core(WinSeqCore(spec, mk))
    assert len(got) == len(want)
    for f in ("key", "id", "ts", "cnt", "sm", "mx"):
        np.testing.assert_array_equal(got[f], want[f], err_msg=f)


def test_mesh_regular_descriptors_engage_and_match():
    """The native-mesh core compresses steady CB windows into per-key
    arithmetic descriptors and dispatches them through
    MeshResidentExecutor.launch_regular (r2 weak #3 'no regular-descriptor
    compression' resolved) — asserted to actually engage, with totals
    equal to the host core."""
    from windflow_tpu import native as native_mod
    if native_mod.enabled() is None:
        pytest.skip("native library unavailable")
    from windflow_tpu.ops.resident import MeshResidentExecutor
    mesh = make_mesh(n_kf=4)
    calls = []
    orig = MeshResidentExecutor.launch_regular

    def counting(self, *a, **kw):
        calls.append(1)
        return orig(self, *a, **kw)

    MeshResidentExecutor.launch_regular = counting
    try:
        ref = run_windowed(WinSeq(Reducer("sum"), WIN, SLIDE, WinType.CB),
                           stream(WinType.CB))
        got = run_windowed(
            WinSeqTPU(Reducer("sum"), WIN, SLIDE, WinType.CB, batch_len=16,
                      flush_rows=128, mesh=mesh),
            stream(WinType.CB))
    finally:
        MeshResidentExecutor.launch_regular = orig
    assert got == ref
    assert calls, "regular-descriptor mesh dispatch never engaged"


def test_mesh_multifield_matches_host():
    """Multi-FIELD MultiReducer (stats over two different payload fields)
    on per-field mesh-sharded rings: the general whole-tuple functor
    contract (win_seq_gpu.hpp:54-67) distributed over the kf axis
    (MeshMultiFieldResidentExecutor, VERDICT r3 item 7)."""
    from windflow_tpu.core.tuples import Schema, batch_from_columns
    from windflow_tpu.core.windows import WindowSpec
    from windflow_tpu.core.winseq import WinSeqCore
    from windflow_tpu.ops.functions import MultiReducer
    from windflow_tpu.ops.resident import MeshMultiFieldResidentExecutor
    from windflow_tpu.patterns.win_seq_tpu import make_core_for

    schema = Schema(a=np.int64, b=np.int64)
    rng = np.random.default_rng(17)
    batches = []
    for lo in range(0, 96, 23):
        m = min(23, 96 - lo)
        ids = np.repeat(np.arange(lo, lo + m), 11)
        ks = np.tile(np.arange(11), m)
        batches.append(batch_from_columns(
            schema, key=ks, id=ids, ts=ids,
            a=rng.integers(0, 100, m * 11), b=rng.integers(0, 60, m * 11)))

    mf = MultiReducer(("count", None, "cnt"), ("sum", "a", "sa"),
                      ("max", "b", "mb"), ("min", "a", "na"))
    spec = WindowSpec(WIN, SLIDE, WinType.CB)
    mesh = make_mesh(n_kf=4)

    def run_core(core):
        outs = [core.process(b) for b in batches]
        outs.append(core.flush())
        outs = [o for o in outs if len(o)]
        res = np.concatenate(outs)
        return np.sort(res, order=["key", "id"])

    import warnings
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        core = make_core_for(spec, mf, mesh=mesh, batch_len=16)
        assert isinstance(core.executor, MeshMultiFieldResidentExecutor)
        # r5: the pod shape keeps the C++ hot loop for rich aggregates
        # too — mesh multi-field rides NativeResidentCore when the
        # native library is available (Python core otherwise)
        from windflow_tpu.native import enabled
        if enabled() is not None:
            from windflow_tpu.patterns.native_core import \
                NativeResidentCore
            assert isinstance(core, NativeResidentCore) and core._multi
        got = run_core(core)
    want = run_core(WinSeqCore(spec, mf))
    assert len(got) == len(want)
    for f in ("key", "id", "ts", "cnt", "sa", "mb", "na"):
        np.testing.assert_array_equal(got[f], want[f], err_msg=f)


def test_mesh_jax_fn_matches_host():
    """An arbitrary batched JaxWindowFunction over two fields evaluates on
    the mesh-sharded per-field rings — one SPMD dispatch per flush."""
    from windflow_tpu.core.tuples import Schema, batch_from_columns
    from windflow_tpu.core.windows import WindowSpec
    from windflow_tpu.core.winseq import WinSeqCore
    from windflow_tpu.ops.functions import WindowFunction
    from windflow_tpu.patterns.win_seq_tpu import (JaxWindowFunction,
                                                   make_core_for)

    schema = Schema(a=np.int64, b=np.int64)
    batches = []
    for lo in range(0, 72, 24):
        ids = np.repeat(np.arange(lo, lo + 24), 5)
        ks = np.tile(np.arange(5), 24)
        batches.append(batch_from_columns(
            schema, key=ks, id=ids, ts=ids, a=ids % 13, b=(ids * 5) % 7))

    class HostDot(WindowFunction):
        result_fields = {"dot": np.int64}
        required_fields = ("a", "b")

        def apply(self, key, gwid, rows):
            return (int((rows["a"] * rows["b"]).sum()),)

    import jax.numpy as jnp

    def fn(keys, gwids, cols, mask):
        return (jnp.sum(jnp.where(mask, cols["a"] * cols["b"], 0), axis=1),)

    jf = JaxWindowFunction(fn, fields=("a", "b"),
                           result_fields={"dot": np.int64})
    spec = WindowSpec(WIN, SLIDE, WinType.CB)
    mesh = make_mesh(n_kf=4)

    def run_core(core):
        outs = [core.process(b) for b in batches]
        outs.append(core.flush())
        outs = [o for o in outs if len(o)]
        res = np.concatenate(outs)
        return np.sort(res, order=["key", "id"])

    import warnings
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        got = run_core(make_core_for(spec, jf, mesh=mesh, batch_len=16))
    want = run_core(WinSeqCore(spec, HostDot()))
    assert len(got) == len(want)
    for f in ("key", "id", "ts", "dot"):
        np.testing.assert_array_equal(got[f], want[f], err_msg=f)


def test_mesh_with_host_shards_matches_host():
    """Host key-sharding composes with mesh execution (r3 weak #5): each
    shard's C++ bookkeeping feeds its OWN P(kf, None)-sharded ring, so a
    multicore host parallelises the hot loop while every dispatch still
    serves all key groups."""
    from windflow_tpu import native as native_mod
    if native_mod.enabled() is None:
        pytest.skip("native library unavailable")
    from windflow_tpu.core.windows import WindowSpec
    from windflow_tpu.core.winseq import WinSeqCore
    from windflow_tpu.ops.resident import MeshResidentExecutor
    from windflow_tpu.patterns.win_seq_tpu import make_core_for

    spec = WindowSpec(WIN, SLIDE, WinType.CB)
    mesh = make_mesh(n_kf=4)
    batches = cb_stream_batches(13, 110)

    def run_core(core):
        outs = [core.process(b) for b in batches]
        outs.append(core.flush())
        outs = [o for o in outs if len(o)]
        res = np.concatenate(outs)
        return np.sort(res, order=["key", "id"])

    import warnings
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        core = make_core_for(spec, Reducer("sum"), mesh=mesh, shards=2,
                             batch_len=16)
        assert len(core.executors) == 2
        assert all(isinstance(ex, MeshResidentExecutor)
                   for ex in core.executors)
        got = run_core(core)
    want = run_core(WinSeqCore(WindowSpec(WIN, SLIDE, WinType.CB),
                               Reducer("sum")))
    assert len(got) == len(want)
    for f in ("key", "id", "ts", "value"):
        np.testing.assert_array_equal(got[f], want[f], err_msg=f)


def test_mesh_multifield_scatter_dispatch_economics():
    """Perf-shaped exercise of MeshMultiFieldResidentExecutor's S-way
    scatter at realistic cardinality (VERDICT r4 weak #5): 256 keys
    sharded over a 4-device kf mesh, ~100k rows, two payload fields.
    Pins the dispatch-count behavior — ONE fused SPMD dispatch per
    flush, NOT one per shard or per field — alongside correctness at
    this scale (the small differential above cannot see the economics)."""
    from windflow_tpu.core.tuples import Schema, batch_from_columns
    from windflow_tpu.core.windows import WindowSpec
    from windflow_tpu.core.vecinc import VecIncSlidingCore
    from windflow_tpu.ops.functions import MultiReducer
    from windflow_tpu.ops import resident
    from windflow_tpu.ops.resident import MeshMultiFieldResidentExecutor
    from windflow_tpu.patterns.win_seq_tpu import make_core_for

    NK, ROWS, CHUNK = 256, 98_304, 1 << 14
    schema = Schema(a=np.int64, b=np.int64)
    rng = np.random.default_rng(23)
    batches = []
    per = CHUNK // NK
    for lo in range(0, ROWS // NK, per):
        ids = np.repeat(np.arange(lo, lo + per), NK)
        ks = np.tile(np.arange(NK), per)
        batches.append(batch_from_columns(
            schema, key=ks, id=ids, ts=ids,
            a=rng.integers(0, 100, per * NK), b=rng.integers(0, 60, per * NK)))

    mf = MultiReducer(("sum", "a", "sa"), ("max", "b", "mb"))
    spec = WindowSpec(64, 16, WinType.CB)
    mesh = make_mesh(n_kf=4)

    import warnings
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        core = make_core_for(spec, mf, mesh=mesh, batch_len=1 << 12,
                             flush_rows=1 << 15)
        assert isinstance(core.executor, MeshMultiFieldResidentExecutor)
        resident.stats_snapshot(reset=True)
        outs = [core.process(b) for b in batches]
        outs.append(core.flush())
        diag = resident.stats_snapshot(reset=True)
    got = np.concatenate([o for o in outs if len(o)])
    got = np.sort(got, order=["key", "id"])

    # economics: ~ROWS/flush_rows natural flushes; the scatter path must
    # not multiply that by fields (2) or shards (4) — one fused SPMD
    # dispatch per flush, +2 slack for the EOS tail
    flushes = -(-ROWS // (1 << 15))           # ceil
    assert 1 <= diag["dispatches"] <= flushes + 2, diag

    # correctness at scale, against the vectorised host core
    host = VecIncSlidingCore(spec, mf)
    want = [host.process(b) for b in batches]
    want.append(host.flush())
    want = np.concatenate([w for w in want if len(w)])
    want = np.sort(want, order=["key", "id"])
    assert len(got) == len(want)
    for f in ("key", "id", "sa", "mb"):
        np.testing.assert_array_equal(got[f], want[f], err_msg=f)
