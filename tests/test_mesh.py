"""Mesh-sharded window evaluation vs the host oracle, on the virtual
8-device CPU mesh (conftest.py forces it).  Exercises both mesh axes:
kf (group parallel, no collectives) and sp (window partition + psum /
all-gather over the axis) in every 8-device factorization."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from windflow_tpu.parallel.mesh import (
    MeshStreamStep, MeshWindowedReduce, make_mesh, partition_stream_by_key)

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs the 8-device virtual mesh")


def _random_windows(rng, n_groups, n_rows, n_wins, max_len):
    flat = rng.integers(-50, 50, size=(n_groups, n_rows)).astype(np.int32)
    lens = rng.integers(1, max_len + 1, size=(n_groups, n_wins))
    starts = rng.integers(0, n_rows - max_len, size=(n_groups, n_wins))
    return flat, starts.astype(np.int32), lens.astype(np.int32)


def _oracle(flat, starts, lens, op):
    KF, B = starts.shape
    out = np.zeros((KF, B), dtype=np.int64)
    for k in range(KF):
        for i in range(B):
            w = flat[k, starts[k, i]:starts[k, i] + lens[k, i]]
            out[k, i] = {"sum": np.sum, "count": len, "min": np.min,
                         "max": np.max, "prod": np.prod}[op](w)
    return out


@pytest.mark.parametrize("n_kf,n_sp", [(8, 1), (4, 2), (2, 4), (1, 8)])
@pytest.mark.parametrize("op", ["sum", "count", "min", "max"])
def test_mesh_reduce_matches_oracle(n_kf, n_sp, op):
    rng = np.random.default_rng(42 + n_kf)
    mesh = make_mesh(n_kf, n_sp)
    flat, starts, lens = _random_windows(rng, n_kf, 300, 40, 64)
    got = MeshWindowedReduce(mesh, op=op)(flat, starts, lens)
    np.testing.assert_array_equal(got, _oracle(flat, starts, lens, op))


def test_mesh_mean():
    rng = np.random.default_rng(7)
    mesh = make_mesh(2, 4)
    flat, starts, lens = _random_windows(rng, 2, 256, 16, 32)
    got = MeshWindowedReduce(mesh, op="mean", dtype=jnp.float32)(
        flat.astype(np.float32), starts, lens)
    want = np.stack([
        [flat[k, s:s + l].mean() for s, l in zip(starts[k], lens[k])]
        for k in range(2)])
    np.testing.assert_allclose(got, want, rtol=1e-5)


def test_mesh_windows_spanning_shard_boundaries():
    # windows crossing sp-shard row boundaries must still reduce exactly
    mesh = make_mesh(1, 8)
    n_rows = 8 * 16  # Ns = 16 per shard
    flat = np.arange(n_rows, dtype=np.int32)[None, :]
    starts = np.array([[0, 10, 60, 100]], dtype=np.int32)
    lens = np.array([[128, 50, 40, 28]], dtype=np.int32)
    got = MeshWindowedReduce(mesh, op="sum")(flat, starts, lens)
    np.testing.assert_array_equal(got, _oracle(flat, starts, lens, "sum"))


def test_mesh_stream_step_fused_map_filter():
    # full step: map(x -> 2x) then filter(x > 0) then windowed sum
    rng = np.random.default_rng(3)
    mesh = make_mesh(4, 2)
    flat, starts, lens = _random_windows(rng, 4, 200, 24, 48)
    step = MeshStreamStep(mesh, op="sum",
                          map_fn=lambda v: v * 2,
                          filter_fn=lambda v: v > 0)
    got = step(flat, starts, lens)
    mapped = flat * 2
    mapped = np.where(mapped > 0, mapped, 0)
    np.testing.assert_array_equal(got, _oracle(mapped, starts, lens, "sum"))


def test_partition_stream_by_key():
    keys = np.arange(100)
    assert (partition_stream_by_key(keys, 4) == keys % 4).all()
    odd = partition_stream_by_key(keys, 4, routing=lambda k, n: (k + 1) % n)
    assert (odd == (keys + 1) % 4).all()


def test_jit_cache_reused_across_calls():
    mesh = make_mesh(2, 4)
    red = MeshWindowedReduce(mesh, op="sum")
    rng = np.random.default_rng(0)
    for _ in range(3):
        flat, starts, lens = _random_windows(rng, 2, 300, 40, 64)
        got = red(flat, starts, lens)
        np.testing.assert_array_equal(got, _oracle(flat, starts, lens, "sum"))
    assert len(red._jits) == 1  # same shape bucket -> one compile


def test_mesh_filter_semantics_count_and_mean():
    """Filtered rows must leave count and the mean denominator (regression:
    filter was a value rewrite, so count/mean still saw dropped rows)."""
    mesh = make_mesh(1, 2)
    flat = np.array([[1, 2, -3, 4, -5, 6, 7, -8]], dtype=np.int32)
    starts = np.array([[0, 4]], dtype=np.int32)
    lens = np.array([[4, 4]], dtype=np.int32)

    cnt = MeshStreamStep(mesh, op="count", filter_fn=lambda v: v > 0)(
        flat, starts, lens)
    np.testing.assert_array_equal(cnt, [[3, 2]])

    mean = MeshStreamStep(mesh, op="mean", dtype=jnp.float32,
                          filter_fn=lambda v: v > 0)(
        flat.astype(np.float32), starts, lens)
    np.testing.assert_allclose(mean, [[(1 + 2 + 4) / 3, (6 + 7) / 2]])


def test_mesh_3d_window_axis():
    """(kf=2, wf=2, sp=2): windows shard over wf, rows over sp."""
    rng = np.random.default_rng(11)
    flat = rng.integers(-20, 20, size=(2, 64)).astype(np.int32)
    starts = np.stack([np.arange(8) * 7 for _ in range(2)]).astype(np.int32)
    lens = np.full((2, 8), 9, dtype=np.int32)
    mesh = make_mesh(2, 2, n_wf=2)
    got = MeshWindowedReduce(mesh, op="sum")(flat, starts, lens)
    want = np.stack([
        [flat[g, s:s + 9].sum() for s in starts[g]] for g in range(2)])
    np.testing.assert_array_equal(got, want)


@pytest.mark.parametrize("op", ["sum", "min", "max"])
def test_mesh_ring_collective_matches_psum(op):
    """ppermute ring accumulation == the one-shot collective."""
    rng = np.random.default_rng(13)
    flat = rng.integers(-30, 30, size=(2, 128)).astype(np.int32)
    starts = np.stack([np.sort(rng.integers(0, 100, size=6))
                       for _ in range(2)]).astype(np.int32)
    lens = rng.integers(1, 28, size=(2, 6)).astype(np.int32)
    mesh = make_mesh(2, 4)
    a = MeshWindowedReduce(mesh, op=op)(flat, starts, lens)
    b = MeshWindowedReduce(mesh, op=op, collective="ring")(
        flat, starts, lens)
    np.testing.assert_array_equal(a, b)


def test_mesh_ring_mean():
    rng = np.random.default_rng(17)
    flat = rng.integers(0, 50, size=(1, 64)).astype(np.int32)
    starts = np.array([[0, 10, 30]], dtype=np.int32)
    lens = np.array([[10, 16, 20]], dtype=np.int32)
    mesh = make_mesh(1, 8)
    import jax.numpy as jnp
    got = MeshWindowedReduce(mesh, op="mean", dtype=jnp.float32,
                             collective="ring")(flat, starts, lens)
    want = np.array([[flat[0, s:s + l].mean() for s, l in
                      zip(starts[0], lens[0])]], dtype=np.float32)
    np.testing.assert_allclose(got, want, rtol=1e-6)
