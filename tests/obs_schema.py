"""Shared validator for the observability file schemas
(docs/OBSERVABILITY.md): every line of ``metrics.jsonl`` and
``events.jsonl`` must parse and carry the documented fields with the
documented types.  One definition, imported by the tier-1 smoke test and
the slow soak slice — the schema the docs promise is the schema the
tests enforce."""

import json

from windflow_tpu.obs.events import EVENT_KINDS

#: required metrics-sample fields -> accepted types
SAMPLE_FIELDS = {
    "t": (float,),
    "seq": (int,),
    "dataflow": (str,),
    "nodes": (list,),
    "dead_letters": (int,),
    "counters": (dict,),
    "gauges": (dict,),
    "histograms": (dict,),
}

#: required per-node fields (sampler may add optional NodeStats fields:
#: rcv_batches, rcv_tuples, ewma/avg_service_us_per_batch)
NODE_FIELDS = {
    "node": (str,),
    "id": (str,),
    "depth": (int,),
    "hwm": (int,),
    "shed": (int,),
    "quarantined": (int,),
}

NODE_OPTIONAL_FIELDS = {
    "rcv_batches": (int,),
    "rcv_tuples": (int,),
    "ewma_service_us_per_batch": (int, float),
    "avg_service_us_per_batch": (int, float),
    # span-tracing latency fields (obs/trace.py; only on traced graphs)
    "q_p50_us": (int, float),
    "q_p95_us": (int, float),
    "q_p99_us": (int, float),
    "svc_p50_us": (int, float),
    "svc_p95_us": (int, float),
    "svc_p99_us": (int, float),
}

#: span-record kinds (trace.jsonl, obs/trace.py) -> kind-specific
#: required fields; the common fields are checked for every kind
SPAN_COMMON_FIELDS = {
    "t": (float,),
    "kind": (str,),
    "span": (int,),
    "dataflow": (str,),
}
SPAN_KIND_FIELDS = {
    "hop": {"trace": (int,), "node": (str,), "q_us": (int, float),
            "svc_us": (int, float), "end_us": (int, float),
            "rows": (int,)},
    "launch": {"trace": (int,), "phase": (str,), "dur_us": (int, float),
               "end_us": (int, float)},
    "ctrl": {"name": (str,), "node": (str,), "epoch": (int,),
             "dur_us": (int, float)},
}


def _typed(obj, field, types, ctx):
    assert field in obj, f"{ctx}: missing field {field!r} in {obj}"
    v = obj[field]
    assert isinstance(v, types) and not (
        bool not in types and isinstance(v, bool)), \
        f"{ctx}: field {field!r} has type {type(v).__name__}, " \
        f"wanted {[t.__name__ for t in types]}"
    return v


def validate_sample(sample: dict, ctx: str = "metrics.jsonl"):
    """One metrics.jsonl record against the documented schema."""
    for field, types in SAMPLE_FIELDS.items():
        _typed(sample, field, types, ctx)
    assert sample["seq"] >= 0, f"{ctx}: negative seq"
    assert sample["t"] > 0, f"{ctx}: non-positive timestamp"
    for node in sample["nodes"]:
        nctx = f"{ctx} node {node.get('node')!r}"
        for field, types in NODE_FIELDS.items():
            v = _typed(node, field, types, nctx)
            if field in ("depth", "hwm", "shed", "quarantined"):
                assert v >= 0, f"{nctx}: negative {field}"
        for field, types in NODE_OPTIONAL_FIELDS.items():
            if field in node:
                _typed(node, field, types, nctx)
    for name, v in sample["counters"].items():
        assert isinstance(v, (int, float)), \
            f"{ctx}: counter {name!r} not numeric"
    for name, h in sample["histograms"].items():
        for field in ("buckets", "sum", "count"):
            assert field in h, f"{ctx}: histogram {name!r} missing {field}"


def validate_span(rec: dict, ctx: str = "trace.jsonl"):
    """One trace.jsonl span record against the documented schema
    (docs/OBSERVABILITY.md §tracing)."""
    for field, types in SPAN_COMMON_FIELDS.items():
        _typed(rec, field, types, ctx)
    kind = rec["kind"]
    assert kind in SPAN_KIND_FIELDS, f"{ctx}: unknown span kind {kind!r}"
    for field, types in SPAN_KIND_FIELDS[kind].items():
        v = _typed(rec, field, types, ctx)
        if field in ("q_us", "svc_us", "dur_us", "rows"):
            assert v >= 0, f"{ctx}: negative {field}"
    # parent is optional-by-None: root hops and ctrl spans carry None
    if rec.get("parent") is not None:
        _typed(rec, "parent", (int,), ctx)
    json.dumps(rec)     # every field must be JSON-serialisable


def validate_event(event: dict, ctx: str = "events.jsonl"):
    """One events.jsonl record against the documented schema."""
    _typed(event, "t", (float,), ctx)
    kind = _typed(event, "event", (str,), ctx)
    assert kind in EVENT_KINDS, f"{ctx}: unknown event kind {kind!r}"
    if "node" in event:
        _typed(event, "node", (str,), ctx)
    json.dumps(event)   # every field must be JSON-serialisable


def validate_file(path: str, validator) -> int:
    """Validate every line of a JSONL file; returns the line count (a
    caller asserting `> 0` distinguishes 'valid' from 'empty')."""
    n = 0
    with open(path) as f:
        for i, line in enumerate(f, 1):
            ctx = f"{path}:{i}"
            assert line.endswith("\n"), f"{ctx}: torn/unterminated line"
            try:
                obj = json.loads(line)
            except json.JSONDecodeError as e:
                raise AssertionError(f"{ctx}: invalid JSON: {e}") from e
            validator(obj, ctx)
            n += 1
    return n
