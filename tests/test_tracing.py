"""Tracing subsystem tests — the -DLOG_DIR instrumentation analog
(SURVEY.md §5): per-node counters written at svc_end when enabled, zero
files (and near-zero overhead branches) when disabled."""

import json
import os

import numpy as np

from windflow_tpu import (MultiPipe, Reducer, Schema, Sink_Builder,
                          Source_Builder, WinSeq_Builder,
                          batch_from_columns)

SCHEMA = Schema(value=np.int64)


def batches(n=100):
    ids = np.arange(n)
    return [batch_from_columns(SCHEMA, key=ids % 2, id=ids // 2,
                               ts=ids // 2, value=np.ones(n, dtype=np.int64))]


def build(trace_dir=None):
    return (MultiPipe("tr", trace_dir=trace_dir)
            .add_source(Source_Builder().withBatches(batches())
                        .withSchema(SCHEMA).build())
            .add(WinSeq_Builder(Reducer("sum")).withCBWindow(10, 10).build())
            .add_sink(Sink_Builder(lambda r: None).build()))


def test_trace_files_written(tmp_path):
    d = str(tmp_path / "log")
    build(trace_dir=d).run_and_wait_end()
    files = sorted(os.listdir(d))
    assert len(files) == 3  # source, win_seq, sink
    logs = {f: json.load(open(os.path.join(d, f))) for f in files}
    win = next(v for v in logs.values() if "windows_fired" in v)
    assert win["rcv_batches"] == 1
    assert win["rcv_tuples"] == 100
    assert win["windows_fired"] == 10  # 2 keys x 5 tumbling windows
    assert win["avg_service_us_per_batch"] > 0
    sink = next(v for v in logs.values() if v["node"].endswith("sink.0"))
    assert sink["rcv_tuples"] == 10


def test_no_trace_files_by_default(tmp_path, monkeypatch):
    monkeypatch.delenv("WF_LOG_DIR", raising=False)
    build().run_and_wait_end()
    assert not os.path.exists(str(tmp_path / "log"))


def test_env_var_enables_tracing(tmp_path, monkeypatch):
    d = str(tmp_path / "envlog")
    monkeypatch.setenv("WF_LOG_DIR", d)
    build().run_and_wait_end()
    assert len(os.listdir(d)) == 3


def test_snapshot_carries_robustness_counters():
    """NodeStats.snapshot() is the one view the end-of-run log, the live
    sampler, and wf_top all read: the robustness counters
    (docs/ROBUSTNESS.md) must surface there by their documented names."""
    from windflow_tpu.utils.tracing import NodeStats
    stats = NodeStats("df_00_check.0")
    stats.record_svc(100, 5_000)
    stats.record_shed(7)
    stats.record_quarantined()
    stats.record_quarantined()
    snap = stats.snapshot()
    assert snap["shed"] == 7
    assert snap["quarantined"] == 2
    assert snap["rcv_tuples"] == 100
    assert snap["node"] == "df_00_check.0"


def test_snapshot_is_live_mid_run():
    """snapshot() readable while the node is still running — the
    contract the background sampler (obs/sampler.py) relies on."""
    from windflow_tpu.utils.tracing import NodeStats
    stats = NodeStats("live")
    before = stats.snapshot()
    assert before["rcv_batches"] == 0
    stats.record_svc(10, 1_000)
    after = stats.snapshot()
    assert after["rcv_batches"] == 1
    assert after["alive_sec"] >= before["alive_sec"]
