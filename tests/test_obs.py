"""Observability-layer tests (windflow_tpu/obs, docs/OBSERVABILITY.md):
the registry/event-log primitives, the background sampler's file output
validated line-by-line against the documented schema (obs_schema.py —
the same validator the slow soak slice uses), the single-branch disabled
contract, the wire telemetry, the Prometheus exposition, wf_top's
renderer, and the profile/latency satellite knobs."""

import importlib.util
import json
import os
import threading
import time

import numpy as np
import pytest

from obs_schema import validate_event, validate_file, validate_sample
from windflow_tpu import (EventLog, Map_Builder, MetricsRegistry, MultiPipe,
                          Sink_Builder, Source_Builder)
from windflow_tpu.core.tuples import Schema, batch_from_columns
from windflow_tpu.obs import expo
from windflow_tpu.patterns.basic import Map, Sink, Source
from windflow_tpu.runtime.engine import Dataflow
from windflow_tpu.runtime.farm import build_pipeline
from windflow_tpu.runtime.overload import OverloadPolicy

SCHEMA = Schema(value=np.int64)


def make_batches(n=40, rows=10, poison_at=()):
    out = []
    for i in range(n):
        vals = np.full(rows, i, dtype=np.int64)
        if i in poison_at:
            vals[0] = -1
        out.append(batch_from_columns(
            SCHEMA, key=np.zeros(rows), id=np.arange(rows),
            ts=np.arange(rows), value=vals))
    return out


def _load_script(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "scripts", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# ------------------------------------------------------------ primitives

def test_registry_counter_gauge_histogram():
    reg = MetricsRegistry()
    reg.counter("c").inc()
    reg.counter("c").inc(4)
    reg.gauge("g").set(2.5)
    h = reg.histogram("h", buckets=(0.01, 0.1, 1.0))
    for v in (0.005, 0.05, 0.5, 5.0):
        h.observe(v)
    snap = reg.snapshot()
    assert snap["counters"]["c"] == 5
    assert snap["gauges"]["g"] == 2.5
    hs = snap["histograms"]["h"]
    assert hs["count"] == 4 and hs["sum"] == pytest.approx(5.555)
    # cumulative prometheus-style buckets; 5.0 only in implicit +Inf
    assert list(hs["buckets"].values()) == [1, 2, 3]
    # same name, different kind: loud error, not silent shadowing
    with pytest.raises(TypeError, match="already registered"):
        reg.gauge("c")


def test_registry_thread_safety():
    reg = MetricsRegistry()
    c = reg.counter("hits")

    def spin():
        for _ in range(10_000):
            c.inc()

    threads = [threading.Thread(target=spin) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert c.value == 40_000


def test_event_log_ring_file_and_vocabulary(tmp_path):
    path = str(tmp_path / "sub" / "events.jsonl")
    log = EventLog(path, keep=3)
    assert not os.path.exists(path)     # lazy: nothing until first emit
    for i in range(5):
        log.emit("eos", node="n", channel=i)
    log.close()
    assert [e["channel"] for e in log.recent] == [2, 3, 4]  # bounded ring
    assert validate_file(path, validate_event) == 5
    with pytest.raises(ValueError, match="unknown event"):
        log.emit("made_up_event")


# ------------------------------------------------------- engine sampling

def build_observed(tmp_path, sink_delay=0.002, n=40, sample_period=0.005,
                   policy=None, metrics=None):
    d = str(tmp_path / "obs")

    def consume(rows):
        if rows is not None and len(rows) and sink_delay:
            time.sleep(sink_delay)

    df = Dataflow("obs", capacity=4, trace_dir=d, overload=policy,
                  metrics=metrics, sample_period=sample_period)
    build_pipeline(df, [Source(batches=make_batches(n), schema=SCHEMA),
                        Sink(consume, vectorized=True)])
    return df, d


def test_smoke_metrics_and_events_schema(tmp_path):
    """The tier-1 observability smoke test (ISSUE 4 satellite): a tiny
    dataflow with sample_period set writes metrics.jsonl + events.jsonl
    whose EVERY line satisfies the documented schema, with live samples
    (not just the final flush) present."""
    df, d = build_observed(tmp_path)
    df.run_and_wait_end()
    n_samples = validate_file(os.path.join(d, "metrics.jsonl"),
                              validate_sample)
    n_events = validate_file(os.path.join(d, "events.jsonl"),
                             validate_event)
    assert n_samples >= 2       # the t=0 sample plus at least the flush
    assert n_events >= 2 + 2 * df.cardinality()  # start/stop + per node
    lines = [json.loads(line)
             for line in open(os.path.join(d, "metrics.jsonl"))]
    assert [s["seq"] for s in lines] == list(range(len(lines)))
    # the sink's queue visibly backs up while running: live occupancy
    assert max(n["depth"] for s in lines for n in s["nodes"]) > 0
    assert max(n["hwm"] for s in lines for n in s["nodes"]) > 0
    kinds = {json.loads(line)["event"]
             for line in open(os.path.join(d, "events.jsonl"))}
    assert {"dataflow_start", "node_start", "eos", "node_stop",
            "dataflow_stop"} <= kinds


def test_observability_disabled_is_inert(tmp_path):
    """Knobs unset => no registry, no event log, no sampler thread, no
    metrics/events files, no inbox tracking — the seed contract."""
    d = str(tmp_path / "plain")
    df = Dataflow("plain", capacity=4, trace_dir=d)
    build_pipeline(df, [Source(batches=make_batches(8), schema=SCHEMA),
                        Sink(lambda r: None, vectorized=True)])
    assert df.metrics is None and df.events is None
    assert all(not ib._track for ib in df._inboxes.values())
    df.run_and_wait_end()
    assert df._sampler is None
    files = set(os.listdir(d))
    assert "metrics.jsonl" not in files and "events.jsonl" not in files
    assert len(files) == 2      # exactly the seed per-node .log files


def test_metrics_without_trace_dir_stays_in_memory(tmp_path, monkeypatch):
    monkeypatch.delenv("WF_LOG_DIR", raising=False)
    df = Dataflow("mem", capacity=4, metrics=True, sample_period=0.005)
    build_pipeline(df, [Source(batches=make_batches(10), schema=SCHEMA),
                        Sink(lambda r: None, vectorized=True)])
    df.run_and_wait_end()
    assert df.metrics is not None
    assert any(e["event"] == "dataflow_stop" for e in df.events.recent)
    assert not os.path.exists(str(tmp_path / "metrics.jsonl"))
    # NodeStats exist for live sampling even though nothing hit disk
    assert all(n.stats is not None for n in df.nodes)


def test_sample_period_env_hook(tmp_path, monkeypatch):
    d = str(tmp_path / "env")
    monkeypatch.setenv("WF_LOG_DIR", d)
    monkeypatch.setenv("WF_SAMPLE_PERIOD", "0.005")
    df = Dataflow("envobs", capacity=4)
    build_pipeline(df, [Source(batches=make_batches(10), schema=SCHEMA),
                        Sink(lambda r: None, vectorized=True)])
    df.run_and_wait_end()
    assert validate_file(os.path.join(d, "metrics.jsonl"),
                         validate_sample) >= 1
    monkeypatch.setenv("WF_SAMPLE_PERIOD", "not-a-number")
    with pytest.raises(ValueError):
        Dataflow("bad")
    monkeypatch.setenv("WF_SAMPLE_PERIOD", "-1")
    with pytest.raises(ValueError):
        Dataflow("bad")


def test_rich_functions_see_ctx_metrics():
    seen = []

    def bump(batch, ctx):
        ctx.metrics.counter("custom_rows").inc(len(batch))
        seen.append(ctx.metrics)

    pipe = (MultiPipe("rich", metrics=True)
            .add_source(Source_Builder().withBatches(make_batches(5))
                        .withSchema(SCHEMA).build())
            .add(Map_Builder(bump).withRich().vectorized().build())
            .add_sink(Sink_Builder(lambda r: None).vectorized().build()))
    pipe.run_and_wait_end()
    assert seen and all(m is pipe.metrics for m in seen)
    assert pipe.metrics.snapshot()["counters"]["custom_rows"] == 50


def test_ctx_metrics_survives_chain_fusion():
    """chain() fuses stages into one Comb thread; each fused stage keeps
    its own RuntimeContext, so the registry handle must be forwarded."""

    def bump(batch, ctx):
        ctx.metrics.counter("chained_rows").inc(len(batch))

    pipe = (MultiPipe("fused", metrics=True)
            .add_source(Source_Builder().withBatches(make_batches(4))
                        .withSchema(SCHEMA).build())
            .add(Map_Builder(lambda b: b).vectorized().build())
            .chain(Map_Builder(bump).withRich().vectorized().build())
            .add_sink(Sink_Builder(lambda r: None).vectorized().build()))
    pipe.run_and_wait_end()
    assert pipe.metrics.snapshot()["counters"]["chained_rows"] == 40


def test_multipipe_plumbing_and_union(tmp_path):
    reg = MetricsRegistry()
    p1 = (MultiPipe("a", metrics=reg, sample_period=0.5)
          .add_source(Source_Builder().withBatches(make_batches(3))
                      .withSchema(SCHEMA).build()))
    p2 = (MultiPipe("b", sample_period=0.25)
          .add_source(Source_Builder().withBatches(make_batches(3))
                      .withSchema(SCHEMA).build()))
    merged = MultiPipe.union(p1, p2, name="u")
    merged.add_sink(Sink_Builder(lambda r: None).vectorized().build())
    assert merged.sample_period == 0.25     # finest cadence wins
    merged.run_and_wait_end()
    assert merged.metrics is reg            # first configured registry


# ------------------------------------------------------------- exposition

def test_expo_registry_and_sample_formats():
    reg = MetricsRegistry()
    reg.counter("wire_bytes_sent").inc(128)
    reg.gauge("depth").set(3)
    reg.histogram("lat", buckets=(0.1, 1.0)).observe(0.05)
    txt = expo.render_registry(reg)
    assert "# TYPE wf_wire_bytes_sent counter" in txt
    assert "wf_wire_bytes_sent 128" in txt
    assert 'wf_lat_bucket{le="0.1"} 1' in txt
    assert "wf_lat_count 1" in txt
    sample = {"t": time.time(), "seq": 0, "dataflow": "df",
              "nodes": [{"node": "sink.0", "id": "df_01_sink.0",
                         "depth": 2, "hwm": 4, "shed": 7,
                         "quarantined": 0}],
              "dead_letters": 1, "counters": {"wire_frames_sent": 9},
              "gauges": {}, "histograms": {}}
    txt = expo.render_sample(sample)
    assert 'wf_node_inbox_depth{dataflow="df",node="sink.0"} 2' in txt
    assert 'wf_node_shed_total{dataflow="df",node="sink.0"} 7' in txt
    assert 'wf_dead_letters{dataflow="df"} 1' in txt
    assert "wf_wire_frames_sent 9" in txt


# ---------------------------------------------------------------- wf_top

def test_wf_top_renders_live_dir(tmp_path):
    df, d = build_observed(tmp_path)
    df.run_and_wait_end()
    wf_top = _load_script("wf_top")
    samples, _ = wf_top.read_samples(os.path.join(d, "metrics.jsonl"))
    assert len(samples) >= 2
    frame = wf_top.render(samples[-1], samples[-2],
                          wf_top.tail_events(os.path.join(d,
                                                          "events.jsonl")))
    assert "sink.0" in frame and "DEPTH" in frame and "SHED" in frame
    assert "dataflow=obs" in frame
    # --once exercises the CLI path end to end
    assert wf_top.main([d, "--once"]) == 0
    # --expo path renders the final sample
    assert wf_top.main([d, "--expo"]) == 0


# ------------------------------------------------------------- wire plane

def test_wire_telemetry_counters_conserved():
    from windflow_tpu.parallel.channel import RowReceiver, RowSender
    reg = MetricsRegistry()
    log = EventLog()
    recv = RowReceiver(n_senders=1, metrics=reg, events=log)
    got = []
    t = threading.Thread(target=lambda: got.extend(recv.batches()))
    t.start()
    snd = RowSender(recv.host, recv.port, metrics=reg, events=log)
    for lo in (0, 8):
        ids = np.arange(lo, lo + 8)
        snd.send(batch_from_columns(SCHEMA, key=np.zeros(8), id=ids,
                                    ts=ids, value=ids))
    snd.close()
    t.join(10)
    assert len(got) == 2
    c = reg.snapshot()["counters"]
    # dtype frame + 2 payload frames, byte-for-byte conserved
    assert c["wire_frames_sent"] == c["wire_frames_recv"] == 3
    assert c["wire_bytes_sent"] == c["wire_bytes_recv"] > 0


def test_wire_reconnect_events():
    import socket
    from windflow_tpu.parallel.channel import RowReceiver, RowSender
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    reg = MetricsRegistry()
    log = EventLog()
    out = {}

    def connect_late():
        out["snd"] = RowSender("127.0.0.1", port, connect_deadline=30,
                               metrics=reg, events=log)

    th = threading.Thread(target=connect_late)
    th.start()
    time.sleep(0.25)
    recv = RowReceiver(n_senders=1, port=port)
    th.join(30)
    out["snd"].close()
    recv.close()
    assert reg.snapshot()["counters"]["wire_connect_retries"] >= 1
    events = [e for e in log.recent if e["event"] == "reconnect_attempt"]
    assert events and events[0]["port"] == port
    for e in log.recent:
        validate_event(e)


# ----------------------------------------------------- profile satellite

def test_profile_toggles_without_reimport(monkeypatch):
    from windflow_tpu.utils import profile
    profile.auto()
    profile.reset()
    monkeypatch.delenv("WF_PROFILE", raising=False)
    with profile.span("phase"):
        pass
    assert profile.report() == {}       # env off => no accumulation
    monkeypatch.setenv("WF_PROFILE", "1")   # no re-import required
    with profile.span("phase"):
        pass
    profile.add("bytes", 7)
    assert profile.report()["phase"][1] == 1
    assert profile.counters()["bytes"] == 7
    profile.disable()                   # explicit pin beats the env
    with profile.span("phase"):
        pass
    assert profile.report()["phase"][1] == 1
    profile.enable()
    monkeypatch.delenv("WF_PROFILE", raising=False)
    with profile.span("phase"):
        pass
    assert profile.report()["phase"][1] == 2
    profile.auto()
    profile.reset()
    assert not profile.ENABLED


# ----------------------------------------------------- latency satellite

def test_latency_summarize_p50_and_n():
    from windflow_tpu.utils.latency import summarize
    s = summarize([np.arange(1, 101, dtype=np.float64)])
    assert s["n"] == 100
    assert s["p50"] == pytest.approx(50.5)
    assert set(s) == {"avg", "p50", "p95", "p99", "n"}
    assert summarize([]) == {}
    # the bench sinks splat these through unchanged names + new keys
    from windflow_tpu.apps.ysb import YSBSink
    sink = YSBSink(start_wall_us=0, now_us=lambda: 1000)
    sink(batch_from_columns(Schema(count=np.int64, lastUpdate=np.int64),
                            key=np.zeros(4), id=np.arange(4),
                            ts=np.arange(4), count=np.ones(4),
                            lastUpdate=np.arange(4)))
    m = sink.latency_summary_us()
    assert m["n_latency_samples"] == 4
    assert {"avg_latency_us", "p50_latency_us",
            "p95_latency_us", "p99_latency_us"} <= set(m)
