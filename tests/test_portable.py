"""Cross-host recovery unit tests (recovery/portable.py,
parallel/plane.py, the store's CRC fallback — docs/ROBUSTNESS.md
"Cross-host recovery"):

* CheckpointStore integrity: per-blob CRC in the manifest; a torn or
  bit-flipped ``.ckpt`` makes ``latest_complete()`` fall back to the
  previous sealed epoch, counted + evented;
* portable round-trip: a sealed epoch shipped over a real localhost
  RowSender/RowReceiver (the ``-7`` wire family) lands in a
  PortableSpool bit-identically and restores via the ordinary store
  recipe;
* refusals: version skew (PortableSkew), CRC mismatch in transit,
  the ``-7`` family on a receiver with no ``ckpt_sink``;
* PlaneSupervisor: membership down/dead transitions on the senders'
  link health, deterministic ring-successor election, adoption via the
  spool, rejoin, the WF216 construction-time warning;
* the knob contract: a plain resumable plane (no supervisor, no
  ckpt_sink) never imports parallel.plane or recovery.portable.
"""

import os
import socket
import subprocess
import sys
import textwrap
import threading
import time

import numpy as np
import pytest

from windflow_tpu.core.tuples import Schema, batch_from_columns
from windflow_tpu.obs import EventLog, MetricsRegistry
from windflow_tpu.parallel.channel import (ChannelError, RowReceiver,
                                           RowSender, WireConfig)
from windflow_tpu.parallel.plane import (PlanePolicy, PlaneSupervisor,
                                         open_supervised_plane)
from windflow_tpu.recovery.portable import (PortableSkew, PortableSpool,
                                            blob_crc, export_header,
                                            iter_blobs, ship_checkpoint)
from windflow_tpu.recovery.store import CheckpointStore

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SCHEMA = Schema(value=np.int64)


def mk_batch(n=8, lo=0):
    ids = np.arange(lo, lo + n)
    return batch_from_columns(SCHEMA, key=np.zeros(n), id=ids, ts=ids,
                              value=ids)


def _mk_store(root, epochs=(3,), retain=4, metrics=None, events=None):
    """A store with one pickled blob per node per sealed epoch."""
    store = CheckpointStore(str(root), retain=retain, metrics=metrics,
                            events=events)
    for e in epochs:
        nodes = {}
        for node in ("df.0.win", "df.1.agg"):
            n = store.save_blob(e, node, {"epoch": e, "node": node,
                                          "v": list(range(50))})
            nodes[node] = {"bytes": n}
        store.commit(e, nodes)
    return store


# ------------------------------------------------- store CRC + fallback


def test_store_manifest_records_crc(tmp_path):
    store = _mk_store(tmp_path)
    epoch, manifest = store.latest_complete()
    assert epoch == 3
    for safe, meta in manifest["nodes"].items():
        with open(os.path.join(store._epoch_dir(3),
                               f"{safe}.ckpt"), "rb") as f:
            assert meta["crc"] == blob_crc(f.read())


def test_corrupt_blob_falls_back_to_previous_epoch(tmp_path):
    metrics, events = MetricsRegistry(), EventLog()
    store = _mk_store(tmp_path, epochs=(3, 4), metrics=metrics,
                      events=events)
    path = os.path.join(store._epoch_dir(4), "df.0.win.ckpt")
    raw = bytearray(open(path, "rb").read())
    raw[len(raw) // 2] ^= 0xFF                     # bit flip, same size
    with open(path, "wb") as f:
        f.write(raw)
    epoch, manifest = store.latest_complete()
    assert epoch == 3
    assert metrics.snapshot()["counters"]["ckpt_fallbacks"] == 1
    [ev] = [e for e in events.recent
            if e["event"] == "checkpoint_fallback"]
    assert ev["epoch"] == 4 and "CRC32" in ev["reason"]
    # the surviving epoch still loads
    assert store.load(3, "df.1.agg")["epoch"] == 3


def test_torn_blob_falls_back(tmp_path):
    metrics = MetricsRegistry()
    store = _mk_store(tmp_path, epochs=(1, 2), metrics=metrics)
    path = os.path.join(store._epoch_dir(2), "df.1.agg.ckpt")
    raw = open(path, "rb").read()
    with open(path, "wb") as f:
        f.write(raw[:len(raw) // 2])               # truncated write
    epoch, _ = store.latest_complete()
    assert epoch == 1
    assert metrics.snapshot()["counters"]["ckpt_fallbacks"] == 1


def test_all_epochs_corrupt_returns_none(tmp_path):
    store = _mk_store(tmp_path, epochs=(1,))
    os.remove(os.path.join(store._epoch_dir(1), "df.0.win.ckpt"))
    assert store.latest_complete() is None


# --------------------------------------------------- portable round-trip


def test_export_header_versioned_with_crcs(tmp_path):
    store = _mk_store(tmp_path)
    header = export_header(store, 3, origin=7)
    assert header["v"] == 1 and header["origin"] == 7
    assert header["epoch"] == 3
    blobs = list(iter_blobs(store, 3, header))
    assert len(blobs) == 2
    for meta, raw in blobs:
        assert meta["bytes"] == len(raw)
        assert meta["crc"] == blob_crc(raw)
        assert header["nodes"][meta["node"]]["crc"] == meta["crc"]


def test_ship_over_wire_lands_bit_identical(tmp_path):
    """The full -7 family over a real socket: OFFER + BLOBs + COMMIT
    land in the spool as a restorable CheckpointStore epoch; ordinary
    data batches interleave untouched."""
    store = _mk_store(tmp_path / "local", epochs=(5,))
    spool = PortableSpool(str(tmp_path / "spool"),
                          metrics=MetricsRegistry())
    recv = RowReceiver(n_senders=1, ckpt_sink=spool)
    snd = RowSender("127.0.0.1", recv.port)
    snd.send(mk_batch())
    shipped = ship_checkpoint(snd, store, 5, origin=0)
    assert shipped > 0
    snd.send(mk_batch(lo=100))
    snd.close()
    got = list(recv.batches())
    assert len(got) == 2
    recv.close()
    assert spool.peers() == ["0"]
    epoch, manifest = spool.latest(0)
    assert epoch == 5 and manifest["origin"] == 0
    peer_store = spool.store_for(0)
    for node in ("df.0.win", "df.1.agg"):
        assert peer_store.load(5, node) == store.load(5, node)
        with open(os.path.join(store._epoch_dir(5),
                               f"{node}.ckpt"), "rb") as f_local, \
                open(os.path.join(peer_store._epoch_dir(5),
                                  f"{node}.ckpt"), "rb") as f_spool:
            assert f_local.read() == f_spool.read()   # bit-identical
    snap = spool._metrics.snapshot()["counters"]
    assert snap["ckpt_spooled"] == 1
    # wire telemetry: shipped byte counters live on the sender registry
    # only when one is attached; the default wire has none — covered by
    # the soak/differential paths


def test_reship_is_idempotent(tmp_path):
    store = _mk_store(tmp_path / "l", epochs=(2,))
    spool = PortableSpool(str(tmp_path / "s"))
    recv = RowReceiver(n_senders=1, ckpt_sink=spool)
    snd = RowSender("127.0.0.1", recv.port)
    ship_checkpoint(snd, store, 2, origin=3)
    ship_checkpoint(snd, store, 2, origin=3)
    snd.close()
    list(recv.batches())
    recv.close()
    epoch, _ = spool.latest(3)
    assert epoch == 2
    assert spool.store_for(3).load(2, "df.0.win") == \
        store.load(2, "df.0.win")


def test_version_skew_refused(tmp_path):
    spool = PortableSpool(str(tmp_path))
    with pytest.raises(PortableSkew, match="v2"):
        spool.offer({"v": 2, "origin": 1, "epoch": 9, "nodes": {}})


def test_blob_crc_mismatch_refused(tmp_path):
    spool = PortableSpool(str(tmp_path))
    spool.offer({"v": 1, "origin": 1, "epoch": 9,
                 "nodes": {"n": {"bytes": 3, "crc": 0}}})
    with pytest.raises(ValueError, match="CRC32"):
        spool.blob({"origin": 1, "epoch": 9, "node": "n", "bytes": 3,
                    "crc": 12345}, b"abc")
    with pytest.raises(ValueError, match="bytes"):
        spool.blob({"origin": 1, "epoch": 9, "node": "n", "bytes": 5,
                    "crc": blob_crc(b"abc")}, b"abc")


def test_commit_without_offer_or_blob_refused(tmp_path):
    spool = PortableSpool(str(tmp_path))
    with pytest.raises(ValueError, match="OFFER"):
        spool.commit({"origin": 2, "epoch": 1})
    spool.offer({"v": 1, "origin": 2, "epoch": 1,
                 "nodes": {"n": {"bytes": 3,
                                 "crc": blob_crc(b"abc")}}})
    with pytest.raises(ValueError, match="never arrived"):
        spool.commit({"origin": 2, "epoch": 1})
    # an unsealed spool epoch is invisible to restore
    assert spool.latest(2) is None


def test_ckpt_family_without_sink_refused(tmp_path):
    """A receiver with no ckpt_sink= must refuse the -7 family loudly
    (classified error from batches()), never silently drop state."""
    store = _mk_store(tmp_path, epochs=(1,))
    recv = RowReceiver(n_senders=1)
    snd = RowSender("127.0.0.1", recv.port)
    try:
        ship_checkpoint(snd, store, 1, origin=0)
    except OSError:
        # the receiver can slam the connection at the first -7 frame
        # while the ship is still writing — that reset IS the refusal
        pass
    with pytest.raises((ChannelError, OSError)):
        list(recv.batches())
    recv.close()
    try:
        snd.abort()
    except OSError:
        pass


# ------------------------------------------------------ plane supervisor


class _FakeSender:
    """Just the health surface the supervisor polls."""

    def __init__(self):
        self._link_down = False
        self._hb_error = None


def _wait_until(fn, timeout=10.0):
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout:
        if fn():
            return True
        time.sleep(0.01)
    return False


def test_supervisor_detects_death_elects_and_adopts(tmp_path):
    """kill -9 of peer 2 (modelled as its sender link going down past
    the deadline): pid 1 is the ring successor among candidates {1, 2},
    pulls the dead peer's spooled epoch, and fires on_adopt."""
    import pickle
    metrics, events = MetricsRegistry(), EventLog()
    spool = PortableSpool(str(tmp_path))
    # peer 2 replicated epoch 4 to us before dying
    raw = pickle.dumps({"x": 1})
    spool.offer({"v": 1, "origin": 2, "epoch": 4,
                 "nodes": {"n": {"bytes": len(raw),
                                 "crc": blob_crc(raw)}}})
    spool.blob({"origin": 2, "epoch": 4, "node": "n",
                "bytes": len(raw), "crc": blob_crc(raw)}, raw)
    spool.commit({"origin": 2, "epoch": 4})
    senders = {0: _FakeSender(), 2: _FakeSender()}
    adopted = []
    sup = PlaneSupervisor(
        1, {0: ("h", 1), 1: ("h", 2), 2: ("h", 3)}, senders,
        policy=PlanePolicy(down_deadline=0.15, period=0.02,
                           candidates={1, 2}),
        spool=spool, metrics=metrics, events=events,
        on_adopt=lambda pid, epoch, store: adopted.append(
            (pid, epoch, store)))
    sup.start()
    try:
        assert sup.live() == [0, 1, 2]
        senders[2]._link_down = True                   # the kill
        assert _wait_until(lambda: adopted)
        [(pid, epoch, store)] = adopted
        assert pid == 2 and epoch == 4
        assert store.load(4, "n") == {"x": 1}
        assert sup.dead() == [2]
        states = [(e["peer"], e["state"]) for e in events.recent
                  if e["event"] == "membership"]
        assert (2, "down") in states and (2, "dead") in states
        phases = [e["phase"] for e in events.recent
                  if e["event"] == "handoff"]
        assert phases == ["elected", "adopted"]
        snap = metrics.snapshot()
        assert snap["counters"]["plane_handoffs"] == 1
        assert _wait_until(lambda: metrics.snapshot()["gauges"]
                           ["plane_members"] == 2)
        # the restarted/taken-over peer answers again: rejoin
        senders[2]._link_down = False
        assert _wait_until(lambda: sup.live() == [0, 1, 2])
    finally:
        sup.close()


def test_supervisor_blip_shorter_than_deadline_recovers(tmp_path):
    events = EventLog()
    senders = {0: _FakeSender()}
    sup = PlaneSupervisor(
        1, {0: ("h", 1), 1: ("h", 2)}, senders,
        policy=PlanePolicy(down_deadline=5.0, period=0.02),
        events=events, on_adopt=lambda *a: pytest.fail("adopted a blip"))
    sup.start()
    try:
        senders[0]._link_down = True
        assert _wait_until(lambda: any(
            e["event"] == "membership" and e["state"] == "down"
            for e in events.recent))
        senders[0]._link_down = False
        assert _wait_until(lambda: any(
            e["event"] == "membership" and e["state"] == "up"
            for e in events.recent))
        assert sup.dead() == []
    finally:
        sup.close()


def test_successor_election_is_deterministic_ring():
    sup = PlaneSupervisor(
        1, {0: ("h", 1), 1: ("h", 2), 2: ("h", 3), 3: ("h", 4)}, {},
        policy=PlanePolicy(candidates={1, 2, 3}))
    assert sup.successor_for(2) == 3
    assert sup.successor_for(3) == 1          # wraps past 0 (no cand)
    sup._dead.add(3)
    assert sup.successor_for(2) == 1          # skips the dead
    sup2 = PlaneSupervisor(2, {0: ("h", 1), 1: ("h", 2), 2: ("h", 3)},
                           {}, policy=PlanePolicy(candidates={0}))
    sup2._dead.add(0)
    assert sup2.successor_for(0) is None      # no candidate survives


def test_plane_policy_validation_and_wf216_warning():
    with pytest.raises(ValueError, match="down_deadline"):
        PlanePolicy(down_deadline=0)
    with pytest.raises(ValueError, match="period"):
        PlanePolicy(period=-1)
    from windflow_tpu.check.diagnostics import CheckWarning
    with pytest.warns(CheckWarning, match=r"\[WF216\]"):
        PlaneSupervisor(0, {0: ("h", 1)}, {},
                        policy=PlanePolicy(wire=WireConfig.hardened()))


# --------------------------------------------------------- knob contract


def test_plain_resume_plane_never_imports_new_modules():
    """The seed contract: a resumable plane with no supervisor and no
    ckpt_sink must not import parallel.plane or recovery.portable —
    the cross-host layer costs nothing until opted into."""
    code = textwrap.dedent("""
        import socket, sys, threading
        from windflow_tpu.parallel.multihost import open_row_plane
        from windflow_tpu.parallel.channel import WireConfig

        def port():
            s = socket.socket(); s.bind(("127.0.0.1", 0))
            p = s.getsockname()[1]; s.close(); return p

        addrs = {0: ("127.0.0.1", port()), 1: ("127.0.0.1", port())}
        wire = WireConfig(connect_deadline=30.0, resume=True,
                          recovery=True)
        planes = {}
        def boot(pid):
            planes[pid] = open_row_plane(pid, addrs, wire=wire)
        ts = [threading.Thread(target=boot, args=(p,)) for p in addrs]
        [t.start() for t in ts]; [t.join() for t in ts]
        for pid, (recv, senders) in planes.items():
            for snd in senders.values():
                snd.send_epoch(1)
                snd.close()
        for pid, (recv, senders) in planes.items():
            list(recv.batches())
            recv.close()
        assert 'windflow_tpu.parallel.plane' not in sys.modules, \\
            "plane imported without a supervisor"
        assert 'windflow_tpu.recovery.portable' not in sys.modules, \\
            "portable imported without a ckpt_sink"
        print("CONTRACT_OK")
    """)
    out = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True,
        timeout=120, cwd=REPO,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert out.returncode == 0, out.stderr
    assert "CONTRACT_OK" in out.stdout


def test_open_supervised_plane_roundtrip(tmp_path):
    """Two supervised processes in one interpreter: both planes open,
    replicate() ships pid 0's sealed epoch into pid 1's spool."""
    def port():
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        p = s.getsockname()[1]
        s.close()
        return p

    addrs = {0: ("127.0.0.1", port()), 1: ("127.0.0.1", port())}
    store0 = _mk_store(tmp_path / "store0", epochs=(7,))
    out = {}

    def boot(pid, **kw):
        out[pid] = open_supervised_plane(
            pid, addrs, spool_dir=str(tmp_path / f"spool{pid}"),
            policy=PlanePolicy(down_deadline=30.0, period=0.05), **kw)

    t = threading.Thread(target=boot, args=(1,))
    t.start()
    boot(0, store=store0)
    t.join()
    r0, s0, sup0 = out[0]
    r1, s1, sup1 = out[1]
    try:
        shipped = sup0.replicate(7)
        assert shipped > 0
        spool1 = sup1.spool
        assert _wait_until(lambda: spool1.latest(0) is not None)
        epoch, _ = spool1.latest(0)
        assert epoch == 7
        assert spool1.store_for(0).load(7, "df.0.win") == \
            store0.load(7, "df.0.win")
    finally:
        for sup in (sup0, sup1):
            sup.close()
        for snds in (s0, s1):
            for snd in snds.values():
                try:
                    snd.abort()
                except OSError:
                    pass
        r0.close()
        r1.close()


@pytest.mark.slow
def test_soak_handoff_slice():
    """Small in-suite slice of scripts/soak_handoff.py (the full soak is
    a standalone seeded harness, docs/ROBUSTNESS.md "Cross-host
    recovery")."""
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "soak_handoff", os.path.join(os.path.dirname(__file__), os.pardir,
                                     "scripts", "soak_handoff.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    for case in range(4):
        mod.run_case(seed=11, case=case)


def test_wf_top_renders_plane_line():
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "wf_top", os.path.join(os.path.dirname(__file__), os.pardir,
                               "scripts", "wf_top.py"))
    wf_top = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(wf_top)
    sample = {
        "t": time.time(), "seq": 3, "dataflow": "job", "nodes": [],
        "dead_letters": 0,
        "counters": {"plane_handoffs": 1, "ckpt_shipped_bytes": 4096,
                     "ckpt_spooled": 2, "other": 1},
        "gauges": {"plane_members": 2.0, "plane_down": 1.0},
        "histograms": {},
    }
    frame = wf_top.render(sample, None)
    assert "plane: members=2  down=1" in frame
    assert "plane_handoffs=1" in frame
    assert "ckpt_shipped_bytes=4096" in frame
    # plane counters live on the plane line, not the counters line
    assert "counters: other=1" in frame
    # no supervised plane -> no plane line
    bare = dict(sample, counters={}, gauges={})
    assert "plane:" not in wf_top.render(bare, None)
