"""High-key-cardinality hardening (VERDICT r1 weak #4): the emitter /
accumulator / keyed-state hot paths must scale to 1e5 distinct keys —
vectorised group-by instead of a full-batch mask per key.  Budgeted: each
scenario must finish in seconds, and results stay differentially correct
against low-cardinality semantics."""

import time

import numpy as np
import pytest

from windflow_tpu.core.tuples import Schema, batch_from_columns
from windflow_tpu.core.windows import WinType
from windflow_tpu.ops.functions import Reducer
from windflow_tpu.patterns.basic import Accumulator, Sink, Source
from windflow_tpu.patterns.key_farm import KeyFarm
from windflow_tpu.patterns.win_mapreduce import WinMapReduce
from windflow_tpu.runtime.emitters import KeyedStreamState
from windflow_tpu.runtime.engine import Dataflow
from windflow_tpu.runtime.farm import build_pipeline

SCHEMA = Schema(value=np.int64)
N_KEYS = 100_000
ROWS_PER_KEY = 6


def wide_stream(chunk_rows=200_000):
    """ROWS_PER_KEY in-order rows for each of N_KEYS keys, interleaved."""
    out = []
    for i in range(ROWS_PER_KEY):
        ids = np.full(N_KEYS, i)
        keys = np.arange(N_KEYS)
        for lo in range(0, N_KEYS, chunk_rows):
            sl = slice(lo, lo + chunk_rows)
            out.append(batch_from_columns(
                SCHEMA, key=keys[sl], id=ids[sl], ts=ids[sl],
                value=ids[sl] + keys[sl] % 7))
    return out


def run_counted(patterns):
    got = {"rows": 0, "total": 0}

    def snk(rows):
        if rows is not None and len(rows):
            got["rows"] += len(rows)
            got["total"] += int(rows["value"].sum())

    df = Dataflow()
    build_pipeline(df, [Source(batches=iter(wide_stream()), schema=SCHEMA),
                        *patterns, Sink(snk, vectorized=True)])
    t0 = time.perf_counter()
    df.run_and_wait_end()
    return got, time.perf_counter() - t0


def test_keyed_stream_state_slow_path_scales():
    """Force the out-of-order slow path with 1e5 keys; must be O(n + K)."""
    st = KeyedStreamState("id")
    keys = np.tile(np.arange(N_KEYS // 10), 4)
    # per key, arrival order of ids is 1,0,2,3 -> the 0 must drop
    ids = np.repeat(np.array([1, 0, 2, 3]), len(keys) // 4)
    b = batch_from_columns(SCHEMA, key=keys, id=ids, ts=ids, value=ids)
    t0 = time.perf_counter()
    out = st.filter(b)
    dt = time.perf_counter() - t0
    assert dt < 5.0, f"slow path took {dt:.1f}s"
    # per key the id-0 row follows id-1 and must drop
    assert len(out) == len(b) - N_KEYS // 10


def test_wmr_high_cardinality_differential():
    """Win_MapReduce at 1e5 keys: totals equal KeyFarm's on the same
    stream, in seconds (the WinMap emitter's round-robin is the per-key
    loop that used to collapse)."""
    win = ROWS_PER_KEY
    kf, dt_kf = run_counted([KeyFarm(Reducer("sum"), win, win, WinType.CB,
                                     pardegree=2)])
    wmr, dt_wmr = run_counted([WinMapReduce(Reducer("sum"), Reducer("sum"),
                                            win, win, WinType.CB,
                                            map_degree=2)])
    assert wmr["total"] == kf["total"]
    # vectorised cores + collector run this in ~2.5s; 20s leaves headroom
    # for slow CI hosts while still catching a per-key-loop regression
    assert dt_wmr < 20, f"wmr took {dt_wmr:.1f}s at {N_KEYS} keys"
    assert dt_kf < 20, f"kf took {dt_kf:.1f}s at {N_KEYS} keys"


def test_accumulator_high_cardinality():
    """Vectorised accumulator fold at 1e5 keys in seconds, equal to the
    per-row flavour's totals."""
    out_schema = Schema(total=np.int64)

    def fold_row(row, acc):
        acc["total"] += row["value"]

    def fold_vec(rows, acc):
        # per-row running snapshots of this key's fold
        run = int(acc["total"]) + np.cumsum(rows["value"])
        acc["total"] = run[-1]
        out = np.zeros(len(rows), dtype=out_schema.dtype())
        out["total"] = run
        return out

    small = wide_stream()[:2]   # row flavour is O(rows) python calls

    def run_acc(fn, vectorized, batches):
        got = []
        df = Dataflow()
        build_pipeline(df, [
            Source(batches=iter(batches), schema=SCHEMA),
            Accumulator(fn, out_schema, vectorized=vectorized),
            Sink(lambda r: got.append(int(r["total"].sum()))
                 if r is not None and len(r) else None, vectorized=True)])
        t0 = time.perf_counter()
        df.run_and_wait_end()
        return sum(got), time.perf_counter() - t0

    a, _ = run_acc(fold_row, False, small)
    b, _ = run_acc(fold_vec, True, small)
    assert a == b
    full, dt = run_acc(fold_vec, True, wide_stream())
    assert full > 0
    assert dt < 30, f"vectorised accumulator took {dt:.1f}s"
