"""MultiPipe + builder tests — the pipe_test_cpu/pipe_test_gpu + union_test
equivalents (SURVEY.md §4): full pipelines Source→Filter→FlatMap→Map→WinOp→
Sink with randomized parallelism degrees, chaining variants asserting thread
fusion, and unions of MultiPipes feeding windowed consumers."""

import threading

import numpy as np
import pytest

from windflow_tpu import (Accumulator_Builder, Filter_Builder,
                          FlatMap_Builder, KeyFarm_Builder, Map_Builder,
                          MultiPipe, PaneFarm_Builder, Reducer, Schema,
                          Sink_Builder, Source_Builder, WinFarm_Builder,
                          WinMapReduce_Builder, WinSeq_Builder,
                          WinSeqTPU_Builder, WinType, batch_from_columns,
                          union_multipipes)

SCHEMA = Schema(value=np.int64)


def stream_batches(keys, n, chunk=64, id0=0, seed=None):
    """Deterministic (or seeded-random-value) per-key-ordered stream."""
    rng = np.random.default_rng(seed) if seed is not None else None
    out = []
    for i in range(0, n, chunk):
        ids = np.repeat(np.arange(i, min(i + chunk, n)), keys)
        ks = np.tile(np.arange(keys), len(ids) // keys)
        vals = (rng.integers(0, 100, len(ids)).astype(np.int64)
                if rng is not None else ids.astype(np.int64))
        out.append(batch_from_columns(SCHEMA, key=ks, id=ids + id0,
                                      ts=ids + id0, value=vals))
    return out


class Gather:
    def __init__(self):
        self.rows = []
        self._lock = threading.Lock()

    def __call__(self, row):
        if row is None:
            return
        with self._lock:
            self.rows.append((int(row["key"]), int(row["id"]),
                              int(row["value"])))

    @property
    def total(self):
        return sum(r[2] for r in self.rows)


def source_of(batches):
    return Source_Builder().withBatches(batches).withSchema(SCHEMA).build()


# ----------------------------------------------------------- full pipelines

@pytest.mark.parametrize("par", [1, 3])
def test_pipe_basic_ops(par):
    """Source→Filter(even)→Map(x2)→Sink; degrees randomized like
    test_pipe_*.cpp re-draws (SURVEY.md §4)."""
    got = Gather()
    pipe = (MultiPipe("p1")
            .add_source(source_of(stream_batches(2, 100)))
            .add(Filter_Builder(lambda b: b["value"] % 2 == 0)
                 .vectorized().withParallelism(par).build())
            .add(Map_Builder(lambda b: b.__setitem__("value", b["value"] * 2))
                 .vectorized().withParallelism(par).build())
            .add_sink(Sink_Builder(got).build()))
    pipe.run_and_wait_end()
    want = sorted(2 * v for v in range(100) if v % 2 == 0) * 2
    assert sorted(r[2] for r in got.rows) == sorted(want)


def test_pipe_chained_vs_added_same_results_fewer_threads():
    def build(chained):
        got = Gather()
        pipe = MultiPipe("p").add_source(source_of(stream_batches(1, 200)))
        f = Filter_Builder(lambda b: b["value"] % 3 != 0).vectorized().build()
        m = Map_Builder(lambda b: b.__setitem__("value", b["value"] + 7)) \
            .vectorized().build()
        s = Sink_Builder(got).build()
        if chained:
            pipe.chain(f).chain(m).chain_sink(s)
        else:
            pipe.add(f).add(m).add_sink(s)
        return pipe, got

    p_add, g_add = build(False)
    p_chain, g_chain = build(True)
    p_add.run_and_wait_end()
    p_chain.run_and_wait_end()
    assert sorted(g_add.rows) == sorted(g_chain.rows)
    # chained: one fused thread (source+filter+map+sink)
    assert p_chain.getNumThreads() == 1
    assert p_add.getNumThreads() == 4


def test_chain_falls_back_to_add_when_keyed_or_width_mismatch():
    got = Gather()
    pipe = (MultiPipe("p")
            .add_source(source_of(stream_batches(4, 50)))
            # keyed map cannot fuse (needs routing emitter)
            .chain(Map_Builder(lambda b: b.__setitem__("value", b["value"]))
                   .vectorized().keyBy().withParallelism(2).build())
            .chain_sink(Sink_Builder(got).build()))
    pipe.run_and_wait_end()
    assert len(got.rows) == 200
    # source / emitter / 2 workers / collector+sink — no fusion of the map
    assert pipe.getNumThreads() >= 4


def test_pipe_flatmap_and_accumulator():
    out_schema = Schema(value=np.int64)

    def dup(row, shipper):
        shipper.push(key=int(row["key"]), id=int(row["id"]),
                     ts=int(row["ts"]), value=int(row["value"]))
        shipper.push(key=int(row["key"]), id=int(row["id"]),
                     ts=int(row["ts"]), value=int(row["value"]) * 10)

    def fold(row, acc):
        acc["value"] += row["value"]

    got = Gather()
    (MultiPipe("p")
     .add_source(source_of(stream_batches(2, 30)))
     .add(FlatMap_Builder(dup).withOutputSchema(out_schema).build())
     .add(Accumulator_Builder(fold).withResultSchema(Schema(value=np.int64))
          .withParallelism(2).build())
     .add_sink(Sink_Builder(got).build())).run_and_wait_end()
    # accumulator emits one running total per input row; the last per key
    # equals the key's grand total of 11 * sum(ids)
    per_key = {}
    for k, _, v in got.rows:
        per_key[k] = max(per_key.get(k, 0), v)
    want = 11 * sum(range(30))
    assert per_key == {0: want, 1: want}


# ----------------------------------------------------- windowed stages in MP

def winseq_oracle(batches, win, slide, wt=WinType.CB):
    from windflow_tpu import WinSeq
    from windflow_tpu.runtime.engine import Dataflow
    from windflow_tpu.runtime.farm import build_pipeline
    from windflow_tpu.patterns.basic import Sink, Source
    got = Gather()
    df = Dataflow()
    build_pipeline(df, [Source(batches=batches, schema=SCHEMA),
                        WinSeq(Reducer("sum"), win, slide, wt),
                        Sink(got)])
    df.run_and_wait_end()
    return sorted(got.rows)


@pytest.mark.parametrize("builder_fn", [
    lambda: WinSeq_Builder(Reducer("sum")).withCBWindow(16, 5).build(),
    lambda: WinFarm_Builder(Reducer("sum")).withCBWindow(16, 5)
        .withParallelism(3).withOrdered().build(),
    lambda: KeyFarm_Builder(Reducer("sum")).withCBWindow(16, 5)
        .withParallelism(2).build(),
    lambda: PaneFarm_Builder(Reducer("sum"), Reducer("sum"))
        .withCBWindow(16, 5).withParallelism(2, 2).build(),
    lambda: WinMapReduce_Builder(Reducer("sum"), Reducer("sum"))
        .withCBWindow(16, 5).withParallelism(2, 1).build(),
    lambda: WinSeqTPU_Builder(Reducer("sum")).withCBWindow(16, 5)
        .withBatch(32).build(),
])
def test_windowed_stage_differential(builder_fn):
    """Every windowed pattern built fluently inside a MultiPipe matches the
    Win_Seq oracle (the test_all_* differential harness shape)."""
    batches = stream_batches(3, 120)
    got = Gather()
    (MultiPipe("wp")
     .add_source(source_of(batches))
     .add(builder_fn())
     .add_sink(Sink_Builder(got).build())).run_and_wait_end()
    assert sorted(got.rows) == winseq_oracle(batches, 16, 5)


def test_full_pipeline_with_window_and_chaining():
    """Source→chain(Filter)→WinFarm→chain(Sink): mixed fusion + shuffle."""
    batches = stream_batches(2, 150)
    got = Gather()
    pipe = (MultiPipe("mix")
            .add_source(source_of(batches))
            .chain(Filter_Builder(lambda b: b["value"] >= 0)
                   .vectorized().build())
            .add(WinFarm_Builder(Reducer("sum")).withCBWindow(10, 10)
                 .withParallelism(2).build())
            .chain_sink(Sink_Builder(got).build()))
    pipe.run_and_wait_end()
    assert sorted(got.rows) == winseq_oracle(batches, 10, 10)


# ------------------------------------------------------------------- unions

def test_union_tumbling_cb_total_preserved():
    """union_test analog: two source pipes into one CB tumbling-window sum.
    TS_RENUMBERING merges by ts and renumbers per key, so totals and window
    counts are interleave-invariant."""
    W = 8
    a = MultiPipe("a").add_source(source_of(stream_batches(2, 60, seed=1)))
    b = MultiPipe("b").add_source(
        source_of(stream_batches(2, 44, id0=60, seed=2)))
    got = Gather()
    u = (union_multipipes(a, b, name="u")
         .add(WinSeq_Builder(Reducer("sum")).withCBWindow(W, W).build())
         .add_sink(Sink_Builder(got).build()))
    u.run_and_wait_end()
    total_in = sum(int(bt["value"].sum())
                   for bt in stream_batches(2, 60, seed=1)
                   + stream_batches(2, 44, id0=60, seed=2))
    assert got.total == total_in
    per_key_n = 60 + 44
    n_windows_per_key = -(-per_key_n // W)
    assert len(got.rows) == 2 * n_windows_per_key


def test_union_requires_two_sources_and_no_sinks():
    a = MultiPipe("a").add_source(source_of(stream_batches(1, 5)))
    with pytest.raises(ValueError):
        union_multipipes(a)
    g = Gather()
    b = (MultiPipe("b").add_source(source_of(stream_batches(1, 5)))
         .add_sink(Sink_Builder(g).build()))
    with pytest.raises(ValueError):
        union_multipipes(a, b)


def test_union_of_union():
    """Three-way union via nesting (test_union_3 analog)."""
    pipes = [MultiPipe(f"s{i}").add_source(
        source_of(stream_batches(1, 30, id0=30 * i))) for i in range(3)]
    inner = union_multipipes(pipes[0], pipes[1])
    got = Gather()
    (union_multipipes(inner, pipes[2], name="u3")
     .add(WinSeq_Builder(Reducer("count")).withCBWindow(6, 6).build())
     .add_sink(Sink_Builder(got).build())).run_and_wait_end()
    assert got.total == 90  # every tuple counted exactly once
    assert len(got.rows) == 15


def test_union_through_map_counts_every_tuple():
    """Regression: a stage between the union and the windowed consumer must
    not lose the ordering merge (tuples were silently dropped as
    out-of-order before)."""
    a = MultiPipe("a").add_source(source_of(stream_batches(1, 40)))
    b = MultiPipe("b").add_source(source_of(stream_batches(1, 40, id0=40)))
    got = Gather()
    (union_multipipes(a, b)
     .add(Map_Builder(lambda bt: bt.__setitem__("value", bt["value"] * 1))
          .vectorized().build())
     .add(WinSeq_Builder(Reducer("count")).withCBWindow(8, 8).build())
     .add_sink(Sink_Builder(got).build())).run_and_wait_end()
    assert got.total == 80
    assert len(got.rows) == 10  # 80 tuples / tumbling 8


def test_cb_window_after_filter_counts_survivors():
    """CB windows downstream of a Filter follow the reference's
    broadcast+TS_RENUMBERING semantics (multipipe.hpp:494-537): the window
    holds `win` *surviving* tuples, not `win` original ids."""
    batches = stream_batches(1, 100)
    got = Gather()
    (MultiPipe("f")
     .add_source(source_of(batches))
     .add(Filter_Builder(lambda b: b["value"] % 2 == 0).vectorized().build())
     .add(WinSeq_Builder(Reducer("count")).withCBWindow(10, 10).build())
     .add_sink(Sink_Builder(got).build())).run_and_wait_end()
    # 50 survivors -> 5 full tumbling windows of 10
    assert [r[2] for r in sorted(got.rows)] == [10] * 5


def test_cb_window_after_parallel_map_is_exact():
    """A non-keyed parallel stage interleaves worker outputs; the CB
    consumer must still see every tuple exactly once, in renumbered order."""
    batches = stream_batches(2, 96)
    got = Gather()
    (MultiPipe("pm")
     .add_source(source_of(batches))
     .add(Map_Builder(lambda b: b.__setitem__("value", np.ones_like(b["value"])))
          .vectorized().withParallelism(3).build())
     .add(WinSeq_Builder(Reducer("sum")).withCBWindow(12, 12).build())
     .add_sink(Sink_Builder(got).build())).run_and_wait_end()
    assert got.total == 2 * 96
    assert len(got.rows) == 2 * 8


# ---------------------------------------------------------------- api errors

def test_multipipe_requires_source_first():
    with pytest.raises(ValueError):
        MultiPipe("x").add(Map_Builder(lambda b: b).vectorized().build())


def test_multipipe_sink_closes_pipe():
    p = (MultiPipe("x").add_source(source_of(stream_batches(1, 5)))
         .add_sink(Sink_Builder(Gather()).build()))
    with pytest.raises(ValueError):
        p.add(Map_Builder(lambda b: b).vectorized().build())


def test_cb_window_after_parallel_source_is_exact():
    """Regression: replicated sources interleave at their collector; the
    windowed consumer still sees every tuple exactly once."""
    per_replica = [stream_batches(1, 48, id0=48 * i) for i in range(2)]
    got = Gather()
    (MultiPipe("ps")
     .add_source(Source_Builder().withBatches(lambda i: per_replica[i])
                 .withSchema(SCHEMA).withParallelism(2).build())
     .add(WinSeq_Builder(Reducer("count")).withCBWindow(8, 8).build())
     .add_sink(Sink_Builder(got).build())).run_and_wait_end()
    assert got.total == 96
    assert len(got.rows) == 12


def test_tb_window_after_parallel_map_loses_nothing():
    """Regression: worker outputs stay as separate ordered channels into a
    real k-way TS merge; a blind collector merge used to hand the ordering
    core interleaved rows and TB windows silently dropped tuples."""
    batches = stream_batches(1, 1024)
    got = Gather()
    (MultiPipe("tbp")
     .add_source(source_of(batches))
     .add(Map_Builder(lambda b: b.__setitem__("value", np.ones_like(b["value"])))
          .vectorized().withParallelism(4).build())
     .add(WinSeq_Builder(Reducer("sum")).withTBWindow(64, 64).build())
     .add_sink(Sink_Builder(got).build())).run_and_wait_end()
    assert got.total == 1024


def test_cb_window_after_accumulator_renumbers():
    """Regression: accumulator snapshots reuse input ids which are not
    window-meaningful; the downstream CB window must renumber (one window
    per `win` snapshots), not collapse everything into window 0."""
    def fold(row, acc):
        acc["value"] += row["value"]

    got = Gather()
    (MultiPipe("accw")
     .add_source(source_of(stream_batches(1, 100)))
     .add(Accumulator_Builder(fold).withResultSchema(Schema(value=np.int64))
          .build())
     .add(WinSeq_Builder(Reducer("count")).withCBWindow(10, 10).build())
     .add_sink(Sink_Builder(got).build())).run_and_wait_end()
    assert [r[2] for r in sorted(got.rows)] == [10] * 10


def test_parallel_source_then_serial_stage_then_window():
    """Regression: a parallelism-1 stage between kept replica channels and
    the window must not blindly merge them — a TS merge is interposed so
    the CB window still sees every tuple exactly once."""
    per_replica = [stream_batches(1, 48, id0=48 * i) for i in range(2)]
    got = Gather()
    (MultiPipe("psm")
     .add_source(Source_Builder().withBatches(lambda i: per_replica[i])
                 .withSchema(SCHEMA).withParallelism(2).build())
     .add(Map_Builder(lambda b: b.__setitem__("value", b["value"]))
          .vectorized().build())
     .add(WinSeq_Builder(Reducer("count")).withCBWindow(8, 8).build())
     .add_sink(Sink_Builder(got).build())).run_and_wait_end()
    assert got.total == 96
    assert len(got.rows) == 12


def test_failing_sink_propagates_instead_of_hanging():
    """Regression: with bounded queues, a raising node used to deadlock
    producers on its full inbox; the error must surface from
    run_and_wait_end within bounded time."""
    def bad_sink(row):
        raise ValueError("boom")

    p = (MultiPipe("err")
         .add_source(source_of(stream_batches(1, 5000, chunk=8)))
         .add_sink(Sink_Builder(bad_sink).build()))
    with pytest.raises(ValueError, match="boom"):
        p.run_and_wait_end()


def test_run_then_run_and_wait_end_is_single_execution():
    got = Gather()
    p = (MultiPipe("dbl").add_source(source_of(stream_batches(1, 25)))
         .add_sink(Sink_Builder(got).build()))
    p.run()
    p.run_and_wait_end()  # must wait, not re-run
    assert len(got.rows) == 25


def test_ordering_streams_disjoint_keys():
    """Regression: a key flowing on only one channel must be released as
    that channel's watermark advances — not buffered until EOS."""
    from windflow_tpu.runtime.ordering import OrderingCore, OrderingMode
    core = OrderingCore(2, OrderingMode.TS)
    b0 = stream_batches(1, 40)[0]           # key 0 on channel 0
    b1 = stream_batches(1, 40)[0].copy()    # key 1 on channel 1
    b1["key"] = 1
    out = core.push(b0, 0)
    out += core.push(b1, 1)
    released = sum(len(o) for o in out)
    assert released > 0, "disjoint-key streams stalled until EOS"
    # everything still arrives exactly once after flush
    released += sum(len(o) for o in core.flush())
    assert released == 80


def test_ordering_channel_eos_unblocks():
    """A finished channel leaves the watermark min (orderingNode.hpp
    eosnotify semantics)."""
    from windflow_tpu.runtime.ordering import OrderingCore, OrderingMode
    core = OrderingCore(2, OrderingMode.TS)
    b = stream_batches(1, 40)[0]
    assert sum(len(o) for o in core.push(b, 0)) == 0  # ch1 watermark -inf
    out = core.channel_eos(1)
    assert sum(len(o) for o in out) == 40


def test_get_num_threads_keeps_pipe_open():
    got = Gather()
    p = (MultiPipe("x").add_source(source_of(stream_batches(1, 10)))
         .add(Map_Builder(lambda b: b).vectorized().build()))
    n_before = p.getNumThreads()
    p.add_sink(Sink_Builder(got).build())  # must still be allowed
    p.run_and_wait_end()
    assert len(got.rows) == 10
    assert p.getNumThreads() == n_before + 1


def test_builder_option_passthrough():
    wf = (WinFarm_Builder(Reducer("max")).withName("w").withCBWindow(9, 4)
          .withParallelism(5).withOrdered(False).build())
    assert wf.name == "w" and wf.parallelism == 5 and not wf.ordered
    assert wf.spec.win_len == 9 and wf.spec.slide_len == 4
    tpu = (WinSeqTPU_Builder(Reducer("sum")).withTBWindow(1000, 500)
           .withBatch(64).build())
    assert tpu.spec.win_type is WinType.TB


def test_builder_cuda_args_warn_and_ignore():
    with pytest.warns(UserWarning):
        WinSeqTPU_Builder(Reducer("sum")).withCBWindow(4, 2) \
            .withBatch(32, n_thread_block=128).build()
    with pytest.warns(UserWarning):
        WinSeqTPU_Builder(Reducer("sum")).withCBWindow(4, 2) \
            .withScratchpad(64).build()


@pytest.mark.parametrize("use_native", [True, False])
def test_renumbering_single_channel_fast_path_matches_general(use_native):
    """The single-upstream TS_RENUMBERING fast path (arrival-order
    vectorised/native cumcount, no pos argsort) must be row-identical to
    the general merge path, markers included (r4: the general path was
    the pipe benchmark's largest host cost).  use_native=False pins the
    numpy groupby-cumcount fallback, which on a normally-built checkout
    never runs otherwise (ADVICE r4)."""
    import numpy as np

    from windflow_tpu.core.tuples import (MARKER_FIELD, Schema,
                                          batch_from_columns)
    from windflow_tpu.runtime.ordering import OrderingCore, OrderingMode

    rng = np.random.default_rng(23)
    schema = Schema(value=np.int64)
    batches = []
    nxt = {}
    for _ in range(6):
        n = int(rng.integers(50, 300))
        keys = rng.integers(0, 7, n)
        ids = np.empty(n, dtype=np.int64)
        for i, k in enumerate(keys):     # per-key ordered ids (contract)
            ids[i] = nxt.get(int(k), 0)
            nxt[int(k)] = ids[i] + 1
        b = batch_from_columns(schema, key=keys, id=ids, ts=ids * 10,
                               value=rng.integers(0, 99, n))
        batches.append(b)
    # a marker row per key at the end (EOS markers ride the same edge)
    mk = batch_from_columns(schema, key=np.arange(7),
                            id=[nxt.get(k, 0) for k in range(7)],
                            ts=[nxt.get(k, 0) * 10 for k in range(7)],
                            value=np.zeros(7))
    mk[MARKER_FIELD] = True
    batches.append(mk)

    def run(nch):
        core = OrderingCore(nch, OrderingMode.TS_RENUMBERING,
                            ordered_input=(nch == 1))
        if nch == 1 and not use_native:
            core._renum_lib = False    # tried-and-unavailable sentinel
        run.core = core if nch == 1 else getattr(run, "core", None)
        outs = []
        if nch == 2:       # channel 1 immediately EOS: general path,
            outs.extend(core.channel_eos(1))   # same stream semantics
        for b in batches:
            outs.extend(core.push(b, 0))
        outs.extend(core.channel_eos(0))
        outs.extend(core.flush())
        allr = np.concatenate([o for o in outs if len(o)])
        return np.sort(allr, order=["key", "id"])

    fast, general = run(1), run(2)
    np.testing.assert_array_equal(fast, general)
    assert fast[MARKER_FIELD].sum() == 7   # markers replayed, renumbered
    if use_native:
        # a checkout without the built native lib would silently degrade
        # this arm to the fallback the other arm already pins
        assert run.core._renum is not None, "native renum lib not built"


def test_renumbering_disordered_single_tail_keeps_general_path():
    """A DISORDERED single tail must keep the general TS_RENUMBERING
    path (per-release ts sort before ids are assigned): the r4 fast path
    is gated on the caller vouching order — this pins both the gate and
    the semantics it protects."""
    import numpy as np

    from windflow_tpu.core.tuples import Schema, batch_from_columns
    from windflow_tpu.runtime.ordering import OrderingCore, OrderingMode

    schema = Schema(value=np.int64)
    # one batch with per-key ts INVERSIONS (keys interleaved, ts shuffled
    # within each key)
    keys = np.array([0, 1, 0, 1, 0, 1], dtype=np.int64)
    ts = np.array([30, 10, 10, 30, 20, 20], dtype=np.int64)
    b = batch_from_columns(schema, key=keys, id=np.arange(6), ts=ts,
                           value=ts)

    core = OrderingCore(1, OrderingMode.TS_RENUMBERING)  # not vouched
    outs = list(core.push(b, 0))
    outs.extend(core.channel_eos(0))
    outs.extend(core.flush())
    allr = np.sort(np.concatenate(outs), order=["key", "id"])
    # ids must follow TS order per key (general-path semantics), so the
    # value column (== ts) must be ascending per key after id-sort
    for k in (0, 1):
        vals = allr[allr["key"] == k]["value"]
        assert list(vals) == sorted(vals), (k, list(vals))
