"""Operator tests mirroring the reference suites src/{source,map,filter,
flatmap,accumulator,sink}_test: every functor flavour per operator, plus a
micro pipeline (src/microbenchmarks/test_micro_1.cpp)."""

import threading

import numpy as np
import pytest

from windflow_tpu.core.tuples import Schema, batch_from_columns
from windflow_tpu.patterns.basic import (Accumulator, Filter, FlatMap, Map,
                                         Sink, Source)
from windflow_tpu.runtime.engine import Dataflow
from windflow_tpu.runtime.farm import build_pipeline

SCHEMA = Schema(value=np.int64)


def int_stream(n, keys=1, chunk=64):
    """Batches of the deterministic integer stream (ids 0..n-1 per key)."""
    out = []
    for i in range(0, n, chunk):
        ids = np.repeat(np.arange(i, min(i + chunk, n)), keys)
        ks = np.tile(np.arange(keys), len(ids) // keys)
        out.append(batch_from_columns(SCHEMA, key=ks, id=ids, ts=ids, value=ids))
    return out


class Gather:
    """Thread-safe sink collector for tests."""

    def __init__(self):
        self.rows = []
        self.eos_calls = 0
        self._lock = threading.Lock()

    def __call__(self, row):
        with self._lock:
            if row is None:
                self.eos_calls += 1
            else:
                self.rows.append((int(row["key"]), int(row["id"]),
                                  int(row["value"])))


def run_pipe(*patterns):
    df = Dataflow()
    build_pipeline(df, list(patterns))
    df.run_and_wait_end()


# ------------------------------------------------------------------- sources

def test_source_itemized():
    state = {"i": 0}

    def gen(row):
        row["id"] = row["value"] = state["i"]
        state["i"] += 1
        return state["i"] < 100

    got = Gather()
    run_pipe(Source(gen, SCHEMA, itemized=True), Sink(got))
    assert [r[1] for r in got.rows] == list(range(100))
    assert got.eos_calls == 1


def test_source_loop_shipper():
    def gen(shipper):
        for i in range(50):
            shipper.push(key=i % 2, id=i, ts=i, value=i * 2)

    got = Gather()
    run_pipe(Source(gen, SCHEMA), Sink(got))
    assert sorted(r[2] for r in got.rows) == [i * 2 for i in range(50)]


def test_source_rich_parallel():
    def gen(shipper, ctx):
        base = ctx.getReplicaIndex() * 100
        for i in range(10):
            shipper.push(key=ctx.getReplicaIndex(), id=base + i, value=1)

    got = Gather()
    run_pipe(Source(gen, SCHEMA, parallelism=4, rich=True), Sink(got))
    assert len(got.rows) == 40
    assert {r[0] for r in got.rows} == {0, 1, 2, 3}


# ---------------------------------------------------------------------- maps

@pytest.mark.parametrize("parallelism", [1, 3])
def test_map_inplace(parallelism):
    def double(row):
        row["value"] *= 2

    got = Gather()
    run_pipe(Source(batches=int_stream(100), schema=SCHEMA),
             Map(double, parallelism=parallelism), Sink(got))
    assert sorted(r[2] for r in got.rows) == [2 * i for i in range(100)]


def test_map_non_inplace_new_schema():
    out_schema = Schema(squared=np.int64)

    def sq(row, out):
        out["squared"] = row["value"] ** 2

    rows = []
    run_pipe(Source(batches=int_stream(20), schema=SCHEMA),
             Map(sq, output_schema=out_schema),
             Sink(lambda r: rows.append(int(r["squared"])) if r is not None else None))
    assert sorted(rows) == [i * i for i in range(20)]


def test_map_vectorized_and_rich():
    def vfn(batch, ctx):
        batch["value"] += ctx.getParallelism()

    got = Gather()
    run_pipe(Source(batches=int_stream(30), schema=SCHEMA),
             Map(vfn, parallelism=2, vectorized=True, rich=True), Sink(got))
    assert sorted(r[2] for r in got.rows) == [i + 2 for i in range(30)]


def test_map_keyed_routing_preserves_per_key_order():
    def ident(row):
        pass

    per_key = {}

    def snk(row):
        if row is not None:
            per_key.setdefault(int(row["key"]), []).append(int(row["id"]))

    run_pipe(Source(batches=int_stream(200, keys=4, chunk=16), schema=SCHEMA),
             Map(ident, parallelism=3, keyed=True), Sink(snk))
    for ids in per_key.values():
        assert ids == sorted(ids)


# -------------------------------------------------------------------- filter

@pytest.mark.parametrize("vectorized", [False, True])
def test_filter(vectorized):
    fn = (lambda b: b["value"] % 2 == 0) if vectorized else \
         (lambda r: r["value"] % 2 == 0)
    got = Gather()
    run_pipe(Source(batches=int_stream(100), schema=SCHEMA),
             Filter(fn, vectorized=vectorized), Sink(got))
    assert sorted(r[2] for r in got.rows) == [i for i in range(100) if i % 2 == 0]


# ------------------------------------------------------------------- flatmap

def test_flatmap_one_to_many():
    def fm(row, shipper):
        for j in range(int(row["value"]) % 3):
            shipper.push(key=row["key"], id=row["id"], value=j)

    got = Gather()
    run_pipe(Source(batches=int_stream(30), schema=SCHEMA),
             FlatMap(fm, SCHEMA), Sink(got))
    assert len(got.rows) == sum(i % 3 for i in range(30))


def test_flatmap_vectorized():
    def fm(batch, shipper):
        shipper.push_batch(np.concatenate([batch, batch]))

    got = Gather()
    run_pipe(Source(batches=int_stream(25), schema=SCHEMA),
             FlatMap(fm, SCHEMA, vectorized=True), Sink(got))
    assert len(got.rows) == 50


# --------------------------------------------------------------- accumulator

def test_accumulator_running_sum():
    def acc_fn(row, acc):
        acc["value"] += row["value"]

    per_key = {}

    def snk(row):
        if row is not None:
            per_key.setdefault(int(row["key"]), []).append(int(row["value"]))

    run_pipe(Source(batches=int_stream(40, keys=2, chunk=8), schema=SCHEMA),
             Accumulator(acc_fn, SCHEMA, parallelism=2), Sink(snk))
    expect = list(np.cumsum(np.arange(40)))
    assert per_key[0] == expect and per_key[1] == expect


# --------------------------------------------------------------------- pipes

def test_micro_pipeline():
    """Source -> Map -> Filter -> FlatMap -> Sink with mixed parallelism
    (test_micro_1.cpp shape)."""
    def double(row):
        row["value"] *= 2

    def keep_mod4(row):
        return row["value"] % 4 == 0

    def dup(row, shipper):
        shipper.push(key=row["key"], id=row["id"], value=row["value"])
        shipper.push(key=row["key"], id=row["id"], value=row["value"] + 1)

    got = Gather()
    run_pipe(Source(batches=int_stream(200), schema=SCHEMA),
             Map(double, parallelism=2),
             Filter(keep_mod4, parallelism=3),
             FlatMap(dup, SCHEMA, parallelism=2),
             Sink(got))
    kept = [2 * i for i in range(200) if (2 * i) % 4 == 0]
    assert sorted(r[2] for r in got.rows) == sorted(
        [v for v in kept] + [v + 1 for v in kept])


def test_engine_error_propagates():
    def boom(row):
        raise RuntimeError("user function failed")

    with pytest.raises(RuntimeError, match="user function failed"):
        run_pipe(Source(batches=int_stream(10), schema=SCHEMA),
                 Map(boom), Sink(lambda r: None))
