"""Differential tests for the device-resident window path (ops/resident.py +
ResidentWinSeqCore): the resident core must produce byte-identical results to
the host WinSeqCore on the same stream — the same invariant the reference's
``src/sum_test_gpu/test_all_*.cpp`` asserts between CPU and GPU pattern
variants, here asserted per-row rather than on totals."""

import warnings

import numpy as np
import pytest

# pin this module to the pure-Python resident core; the native C++ core has
# its own differential suite (test_native.py)
pytestmark = pytest.mark.usefixtures("no_native")


@pytest.fixture(autouse=True)
def no_native(monkeypatch):
    monkeypatch.setenv("WF_NO_NATIVE", "1")

from windflow_tpu.core.tuples import Schema, batch_from_columns
from windflow_tpu.core.windows import PatternConfig, Role, WindowSpec, WinType
from windflow_tpu.core.winseq import WinSeqCore
from windflow_tpu.ops.functions import Reducer
from windflow_tpu.patterns.win_seq_tpu import (DeviceWinSeqCore,
                                               ResidentWinSeqCore,
                                               make_core_for)

SCHEMA = Schema(value=np.int64)


def run_core(core, batches):
    outs = []
    for b in batches:
        r = core.process(b)
        if len(r):
            outs.append(r)
    r = core.flush()
    if len(r):
        outs.append(r)
    if not outs:
        return np.zeros(0, dtype=core._result_dtype)
    out = np.concatenate(outs)
    return np.sort(out, order=["key", "id"])


def cb_stream(n_keys, per_key, chunk=37, seed=0, lo_val=-50, hi_val=100):
    rng = np.random.default_rng(seed)
    batches = []
    for lo in range(0, per_key, chunk):
        m = min(chunk, per_key - lo)
        ids = np.repeat(np.arange(lo, lo + m), n_keys)
        keys = np.tile(np.arange(n_keys), m)
        vals = rng.integers(lo_val, hi_val, size=m * n_keys).astype(np.int64)
        batches.append(batch_from_columns(
            SCHEMA, key=keys, id=ids, ts=ids, value=vals))
    return batches


def tb_stream(n_keys, per_key, seed=0):
    rng = np.random.default_rng(seed)
    ts_all = np.sort(rng.integers(0, per_key * 2, size=per_key))
    batches = []
    for lo in range(0, per_key, 53):
        m = min(53, per_key - lo)
        tss = np.repeat(ts_all[lo:lo + m], n_keys)
        ids = np.repeat(np.arange(lo, lo + m), n_keys)
        keys = np.tile(np.arange(n_keys), m)
        vals = rng.integers(0, 100, size=m * n_keys).astype(np.int64)
        batches.append(batch_from_columns(
            SCHEMA, key=keys, id=ids, ts=tss, value=vals))
    return batches


def assert_equal_results(a, b):
    assert len(a) == len(b)
    for f in ("key", "id", "ts", "value"):
        np.testing.assert_array_equal(a[f], b[f])


@pytest.mark.parametrize("op", ["sum", "min", "max"])
@pytest.mark.parametrize("win,slide", [(16, 4), (8, 8), (4, 12)])
@pytest.mark.parametrize("n_keys", [1, 5])
def test_resident_cb_matches_host(op, win, slide, n_keys):
    batches = cb_stream(n_keys, 503, seed=win * 31 + slide)
    spec = WindowSpec(win, slide, WinType.CB)
    host = run_core(WinSeqCore(spec, Reducer(op)), batches)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        dev_core = make_core_for(spec, Reducer(op), batch_len=64,
                                 flush_rows=200)
    assert isinstance(dev_core, ResidentWinSeqCore)
    assert_equal_results(host, run_core(dev_core, batches))


@pytest.mark.parametrize("op", ["sum", "max"])
@pytest.mark.parametrize("win,slide", [(20, 5), (10, 10), (6, 16)])
def test_resident_tb_matches_host(op, win, slide):
    batches = tb_stream(3, 400, seed=win + slide)
    spec = WindowSpec(win, slide, WinType.TB)
    host = run_core(WinSeqCore(spec, Reducer(op)), batches)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        dev_core = make_core_for(spec, Reducer(op), batch_len=32,
                                 flush_rows=150)
    assert_equal_results(host, run_core(dev_core, batches))


def test_resident_tiny_flush_forces_rebase():
    """Aggressive flush thresholds force many ring rebases; results must
    still match (exercises the deferred-purge + rebase invariant)."""
    batches = cb_stream(4, 1000, chunk=29, seed=9)
    spec = WindowSpec(32, 8, WinType.CB)
    host = run_core(WinSeqCore(spec, Reducer("sum")), batches)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        dev_core = ResidentWinSeqCore(spec, Reducer("sum"), batch_len=16,
                                      flush_rows=64)
    assert_equal_results(host, run_core(dev_core, batches))


def test_resident_plq_renumbering():
    """PLQ role renumbers result ids (win_seq.hpp:396-405); the resident
    path must renumber identically to the host core."""
    batches = cb_stream(3, 300, seed=4)
    spec = WindowSpec(8, 8, WinType.CB)
    cfg = PatternConfig(0, 1, 8, 1, 2, 8)
    host = run_core(
        WinSeqCore(spec, Reducer("sum"), config=cfg, role=Role.PLQ), batches)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        dev_core = ResidentWinSeqCore(spec, Reducer("sum"), config=cfg,
                                      role=Role.PLQ, batch_len=32,
                                      flush_rows=100)
    assert_equal_results(host, run_core(dev_core, batches))


def test_resident_narrow_wire_dtypes():
    """Values outside int8/int16 ranges must widen the wire dtype."""
    batches = cb_stream(2, 256, seed=5, lo_val=-40000, hi_val=40000)
    spec = WindowSpec(16, 4, WinType.CB)
    host = run_core(WinSeqCore(spec, Reducer("sum")), batches)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        dev_core = ResidentWinSeqCore(spec, Reducer("sum"), batch_len=64,
                                      flush_rows=300)
    assert_equal_results(host, run_core(dev_core, batches))


def test_resident_prod_matches_host():
    """prod rides the masked gather-reduce branch; regression for pad=0
    (which made every prod window return the identity)."""
    batches = cb_stream(2, 120, chunk=17, seed=11, lo_val=1, hi_val=4)
    spec = WindowSpec(6, 3, WinType.CB)
    host = run_core(WinSeqCore(spec, Reducer("prod")), batches)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        dev_core = make_core_for(spec, Reducer("prod"), batch_len=16,
                                 flush_rows=60)
    assert isinstance(dev_core, ResidentWinSeqCore)
    assert_equal_results(host, run_core(dev_core, batches))


def test_resident_float_sum_keeps_restaging_path():
    """float32 cumsum accumulates rounding error over the ring, so float
    sums must not auto-select the resident path."""
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        core = make_core_for(WindowSpec(4, 2, WinType.CB),
                             Reducer("sum", dtype=np.float32))
    assert not isinstance(core, ResidentWinSeqCore)


def test_resident_64bit_compute_dtype_needs_x64():
    """compute_dtype=int64 without jax x64 would silently truncate device
    buffers to 32 bits; the core must refuse instead."""
    import jax
    if jax.config.jax_enable_x64:
        pytest.skip("x64 enabled in this environment")
    with pytest.raises(ValueError, match="x64"):
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            ResidentWinSeqCore(WindowSpec(4, 2, WinType.CB), Reducer("sum"),
                               compute_dtype=np.int64)


def test_resident_count_skips_device_entirely():
    """count needs no device work at all: it routes to the HOST core
    (window lengths answer it), not to a restaging device core."""
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        core = make_core_for(WindowSpec(4, 2, WinType.CB), Reducer("count"))
    assert not isinstance(core, (DeviceWinSeqCore, ResidentWinSeqCore))
    # max over the position field is host-free too (the archive is
    # position-ordered), for both window kinds
    from windflow_tpu.core.winseq import WinSeqCore as _Host
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        mx_tb = make_core_for(WindowSpec(10, 5, WinType.TB),
                              Reducer("max", "ts", "hi"))
        mx_cb = make_core_for(WindowSpec(10, 5, WinType.CB),
                              Reducer("max", "id", "hi"))
        mx_val = make_core_for(WindowSpec(10, 5, WinType.CB),
                               Reducer("max", "value"))
    assert not isinstance(mx_tb, (DeviceWinSeqCore, ResidentWinSeqCore))
    assert not isinstance(mx_cb, (DeviceWinSeqCore, ResidentWinSeqCore))
    assert isinstance(mx_val, ResidentWinSeqCore)  # real device work


def test_resident_rejects_incremental():
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        core = ResidentWinSeqCore(WindowSpec(4, 2, WinType.CB),
                                  Reducer("sum"))
    with pytest.raises(TypeError):
        core.use_incremental()


# ------------------------------------------------------------- multi-stat

from windflow_tpu.core.winseq import WinSeqCore as _HostCore
from windflow_tpu.ops.functions import MultiReducer


def _assert_multi_equal(a, b, fields):
    assert len(a) == len(b)
    for f in ("key", "id", "ts") + fields:
        np.testing.assert_array_equal(a[f], b[f], err_msg=f)


@pytest.mark.parametrize("wt", [WinType.CB, WinType.TB], ids=["cb", "tb"])
def test_multi_stat_matches_host(wt):
    """count + max + sum over one shipped column in ONE fused dispatch
    must equal the host NIC evaluation of the same MultiReducer."""
    mk = lambda: MultiReducer(("count", None, "n"),
                              ("max", "value", "hi"),
                              ("sum", "value", "total"))
    spec = WindowSpec(12, 4, wt)
    stream = (cb_stream(3, 150) if wt is WinType.CB else tb_stream(3, 150))
    host = run_core(_HostCore(spec, mk()), stream)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        core = make_core_for(spec, mk(), batch_len=16)
    assert isinstance(core, ResidentWinSeqCore)
    got = run_core(core, stream)
    assert len(host) > 0
    _assert_multi_equal(np.sort(host, order=["key", "id"]),
                        np.sort(got, order=["key", "id"]),
                        ("n", "hi", "total"))


def test_multi_stat_mesh_matches_host():
    """The same multi-stat windows over a mesh-sharded ring."""
    from windflow_tpu.parallel.mesh import make_mesh
    mk = lambda: MultiReducer(("count", None, "n"),
                              ("max", "value", "hi"))
    spec = WindowSpec(8, 8, WinType.CB)
    stream = cb_stream(7, 120)
    host = run_core(_HostCore(spec, mk()), stream)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        core = make_core_for(spec, mk(), batch_len=8,
                             mesh=make_mesh(n_kf=4))
    got = run_core(core, stream)
    _assert_multi_equal(np.sort(host, order=["key", "id"]),
                        np.sort(got, order=["key", "id"]), ("n", "hi"))


def test_multi_stat_count_only_routes_host_forced_device_rejects():
    """A count-only MultiReducer is entirely host-free, so it routes to
    the host core; forcing the device still raises (nothing to ship)."""
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        core = make_core_for(WindowSpec(4, 2, WinType.CB),
                             MultiReducer(("count", None, "n")))
    assert not isinstance(core, (DeviceWinSeqCore, ResidentWinSeqCore))
    with pytest.raises(ValueError, match="non-count"):
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            make_core_for(WindowSpec(4, 2, WinType.CB),
                          MultiReducer(("count", None, "n")),
                          use_resident=True)


def test_multi_stat_two_fields_takes_multifield_rings():
    """Stats over different fields get one resident ring each (was a
    rejection before MultiFieldResidentExecutor existed)."""
    from windflow_tpu.ops.resident import MultiFieldResidentExecutor
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        core = make_core_for(WindowSpec(4, 2, WinType.CB),
                             MultiReducer(("sum", "value", "s"),
                                          ("max", "ts", "m")))
    assert isinstance(core.executor, MultiFieldResidentExecutor)
    assert core.executor.fields == ("value", "ts")


# ---------------------------------------------------------- latency bound

def test_max_delay_flushes_partial_batches():
    """With max_delay_ms, pending windows ship on the next process() after
    the deadline even though neither batch_len nor flush_rows was hit."""
    import time as _time
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        # use_resident=True pins the DEVICE path: this test covers the
        # device cores' force-flush timer, and the budget-aware routing
        # would otherwise (correctly) send a 1 ms budget to the host
        # core once any earlier test seeded the global weather record
        core = make_core_for(WindowSpec(4, 4, WinType.CB), Reducer("sum"),
                             batch_len=1 << 20, flush_rows=1 << 20,
                             max_delay_ms=1, use_resident=True)
    b1 = cb_stream(1, 8, chunk=8)[0]
    got = core.process(b1)          # windows fire internally, none shipped
    _time.sleep(0.01)
    got2 = core.process(cb_stream(1, 8, chunk=8, seed=1)[0])
    # the delayed flush launched; poll on a later call (or drain) sees it
    deadline = _time.monotonic() + 5
    n = len(got) + len(got2)
    while n == 0 and _time.monotonic() < deadline:
        _time.sleep(0.01)
        n += len(core.process(np.zeros(0, dtype=b1.dtype)))
    assert n > 0, "max_delay did not ship the pending windows"
    core.flush()


# ---------------------------------------------------------------- multi-field

SCHEMA2 = Schema(a=np.int64, b=np.int64)


def two_field_stream(n_keys=4, per_key=400, chunk=61, seed=3):
    rng = np.random.default_rng(seed)
    batches = []
    for lo in range(0, per_key, chunk):
        m = min(chunk, per_key - lo)
        ids = np.repeat(np.arange(lo, lo + m), n_keys)
        keys = np.tile(np.arange(n_keys), m)
        batches.append(batch_from_columns(
            SCHEMA2, key=keys, id=ids, ts=ids,
            a=rng.integers(-50, 100, m * n_keys),
            b=rng.integers(0, 2000, m * n_keys)))
    return batches


def test_resident_multifield_multireducer_matches_host():
    """sum(a) + max(b) + count over per-field resident rings equals the
    host core row for row (the reference's device functors read whole POD
    tuples, win_seq_gpu.hpp:54-67 — here each field ships once into its
    own HBM ring)."""
    from windflow_tpu.ops.functions import MultiReducer
    from windflow_tpu.ops.resident import MultiFieldResidentExecutor

    def mk():
        return MultiReducer(("sum", "a", "sa"), ("max", "b", "mb"),
                            ("count", None, "n"))

    spec = WindowSpec(16, 4, WinType.CB)
    batches = two_field_stream()
    host = run_core(WinSeqCore(spec, mk()), batches)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        core = make_core_for(spec, mk(), batch_len=32, flush_rows=100)
    assert isinstance(core, ResidentWinSeqCore)
    assert isinstance(core.executor, MultiFieldResidentExecutor)
    got = run_core(core, batches)
    assert len(host) == len(got)
    for f in ("key", "id", "ts", "sa", "mb", "n"):
        np.testing.assert_array_equal(host[f], got[f])


def test_resident_multifield_tiny_flush_rebases():
    """Multi-field rings rebuild correctly across ring rebases."""
    from windflow_tpu.ops.functions import MultiReducer

    def mk():
        return MultiReducer(("min", "a", "mn"), ("sum", "b", "sb"))

    spec = WindowSpec(12, 6, WinType.CB)
    batches = two_field_stream(n_keys=3, per_key=300, chunk=23, seed=9)
    host = run_core(WinSeqCore(spec, mk()), batches)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        core = make_core_for(spec, mk(), batch_len=8, flush_rows=24)
    got = run_core(core, batches)
    for f in ("key", "id", "ts", "mn", "sb"):
        np.testing.assert_array_equal(host[f], got[f])


def test_resident_jax_fn_matches_restaging_and_host():
    """An arbitrary JAX window fn (sum of a*b per window) over resident
    rings (use_resident=True) equals the restaging executor and the host
    oracle."""
    import jax.numpy as jnp
    from windflow_tpu.ops.functions import FnWindowFunction
    from windflow_tpu.patterns.win_seq_tpu import JaxWindowFunction

    def dev_fn(keys, gwids, cols, mask):
        prod = jnp.where(mask, cols["a"] * cols["b"], 0)
        return jnp.sum(prod, axis=1)

    def host_fn(key, gwid, rows):
        return (int((rows["a"] * rows["b"]).sum()),)

    spec = WindowSpec(10, 5, WinType.CB)
    batches = two_field_stream(n_keys=3, per_key=250, chunk=41, seed=5,
                               )
    host = run_core(
        WinSeqCore(spec, FnWindowFunction(host_fn, {"value": np.int64})),
        batches)

    def jf():
        return JaxWindowFunction(dev_fn, fields=("a", "b"),
                                 result_fields={"value": np.int64})

    resident = run_core(
        make_core_for(spec, jf(), batch_len=32, flush_rows=90,
                      use_resident=True), batches)
    restaged = run_core(
        make_core_for(spec, jf(), batch_len=32), batches)
    assert_equal_results(host, resident)
    assert_equal_results(host, restaged)


def test_resident_jax_fn_multi_output():
    """A JAX fn returning several result columns maps them to its declared
    result_fields in order."""
    import jax.numpy as jnp
    from windflow_tpu.patterns.win_seq_tpu import JaxWindowFunction
    from windflow_tpu.ops.functions import FnWindowFunction

    def dev_fn(keys, gwids, cols, mask):
        a = jnp.where(mask, cols["a"], 0)
        return jnp.sum(a, axis=1), jnp.max(jnp.where(mask, cols["a"], -1 << 30), axis=1)

    def host_fn(key, gwid, rows):
        return (int(rows["a"].sum()),
                int(rows["a"].max()) if len(rows) else -(1 << 30))

    spec = WindowSpec(8, 8, WinType.CB)
    batches = two_field_stream(n_keys=2, per_key=200, chunk=33, seed=7)
    host = run_core(WinSeqCore(spec, FnWindowFunction(
        host_fn, {"s": np.int64, "m": np.int64})), batches)
    jf = JaxWindowFunction(dev_fn, fields=("a",),
                           result_fields={"s": np.int64, "m": np.int64})
    got = run_core(make_core_for(spec, jf, batch_len=16, flush_rows=64,
                                 use_resident=True), batches)
    for f in ("key", "id", "ts", "s", "m"):
        np.testing.assert_array_equal(host[f], got[f])


def test_resident_jax_fn_rejects_int64_ring_without_x64():
    """Declared 64-bit ring dtypes need jax x64 (otherwise jax silently
    truncates the ring to 32 bits)."""
    import jax
    from windflow_tpu.patterns.win_seq_tpu import JaxWindowFunction
    if jax.config.jax_enable_x64:
        pytest.skip("x64 enabled in this process")
    jf = JaxWindowFunction(lambda k, g, c, m: c["a"].sum(axis=1),
                           fields=("a",), result_fields={"v": np.int64},
                           field_dtypes={"a": np.int64})
    with pytest.raises(ValueError, match="x64"):
        make_core_for(WindowSpec(4, 2, WinType.CB), jf, use_resident=True)


def test_resident_float_column_into_int_ring_rejected():
    """A float column shipped into a default int32 ring must raise, not
    silently truncate (declare field_dtypes for float data)."""
    from windflow_tpu.patterns.win_seq_tpu import JaxWindowFunction
    schema = Schema(x=np.float64)
    b = batch_from_columns(schema, key=np.zeros(8), id=np.arange(8),
                           ts=np.arange(8),
                           x=np.full(8, 0.5, dtype=np.float64))
    jf = JaxWindowFunction(lambda k, g, c, m: c["x"].sum(axis=1),
                           fields=("x",), result_fields={"v": np.float64})
    core = make_core_for(WindowSpec(4, 4, WinType.CB), jf,
                         batch_len=4, flush_rows=4, use_resident=True)
    with pytest.raises(ValueError, match="float column"):
        core.process(b)
        core.flush()


def test_host_free_tb_aggregate_routes_to_host_core():
    """COUNT + MAX(ts) over TB windows has no device-worthy compute
    (counts from lens, max-ts from the ts-ordered archive): make_core_for
    routes it to the vectorised host core; use_resident=True still forces
    the device ring (wire benchmarking)."""
    from windflow_tpu.core.vecinc import VecIncTumblingCore
    from windflow_tpu.ops.functions import MultiReducer

    def agg():
        return MultiReducer(("count", None, "n"), ("max", "ts", "hi"))

    spec_args = (1000, 1000, WinType.TB)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        core = make_core_for(WindowSpec(*spec_args), agg())
        forced = make_core_for(WindowSpec(*spec_args), agg(),
                               use_resident=True)
        cb = make_core_for(WindowSpec(1000, 1000, WinType.CB), agg())
    assert isinstance(core, VecIncTumblingCore)     # tumbling TB -> vec
    assert isinstance(forced, ResidentWinSeqCore)   # explicit device
    # CB windows: ts is NOT the position field, max(ts) needs real work
    assert isinstance(cb, ResidentWinSeqCore)


def test_host_free_routing_honors_pallas_request():
    """use_pallas=True must keep the device path even for host-free
    reducers (Pallas benchmarking stays reachable)."""
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        core = make_core_for(WindowSpec(10, 5, WinType.TB),
                             Reducer("max", "ts", "hi"), use_pallas=True)
    assert isinstance(core, DeviceWinSeqCore)


def test_host_free_multireducer_ignores_pallas_flag():
    """MultiReducer has no Pallas path, so use_pallas must not block its
    host-free routing (it used to raise a misleading resident-only
    error)."""
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        core = make_core_for(
            WindowSpec(10, 5, WinType.TB),
            MultiReducer(("count", None, "c"), ("max", "ts", "hi")),
            use_pallas=True)
    assert not isinstance(core, (DeviceWinSeqCore, ResidentWinSeqCore))


def test_acc_dtype_warning_gated_on_value_range():
    """VERDICT r2 hygiene: the int32-accumulate wrap warning must not fire
    when the Reducer's declared value_range plus the CB window length prove
    the results fit (bench/YSB configs run warning-clean); it still fires
    when no range is declared or the range genuinely overflows."""
    import warnings
    from windflow_tpu.core.windows import WindowSpec, WinType
    from windflow_tpu.patterns import win_seq_tpu
    from windflow_tpu.patterns.win_seq_tpu import select_acc_dtype

    spec = WindowSpec(256, 64, WinType.CB)
    tb = WindowSpec(256, 64, WinType.TB)

    def fires(reducer, spec_):
        win_seq_tpu._ACC_WARNED.clear()
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            acc = select_acc_dtype(reducer, None, spec_)
        assert acc == np.dtype(np.int32)
        return any("wrap" in str(x.message) for x in w)

    # provably safe: |sum| <= 256 * 100 << 2^31
    assert not fires(Reducer("sum", value_range=(0, 100)), spec)
    # min/max never leave the input range, even for TB windows
    assert not fires(Reducer("max", value_range=(-7, 10 ** 6)), tb)
    # no declared range -> warn (the pre-r3 behavior)
    assert fires(Reducer("sum"), spec)
    # TB sum: row count unbounded, range proves nothing -> warn
    assert fires(Reducer("sum", value_range=(0, 100)), tb)
    # declared range too wide for the window length -> warn
    assert fires(Reducer("sum", value_range=(0, 2 ** 40)), spec)


def test_pos_max_split_ships_single_column():
    """r3: COUNT + MAX(ts) + SUM(revenue) over TB windows must ship ONLY
    the revenue column — max-ts is free from the ts-ordered archive, so
    the executor is the single-field ring, not multi-field — and results
    must match the host core."""
    from windflow_tpu.core.tuples import Schema, batch_from_columns
    from windflow_tpu.core.winseq import WinSeqCore
    from windflow_tpu.ops.functions import MultiReducer
    from windflow_tpu.ops.resident import (MultiFieldResidentExecutor,
                                           ResidentWindowExecutor)

    schema = Schema(revenue=np.int64)
    mk = MultiReducer(("count", None, "n"), ("max", "ts", "hi"),
                      ("sum", "revenue", "rev"))
    spec = WindowSpec(100, 100, WinType.TB)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        core = make_core_for(spec, mk)
    assert isinstance(core, ResidentWinSeqCore)
    assert isinstance(core.executor, ResidentWindowExecutor)
    assert not isinstance(core.executor, MultiFieldResidentExecutor)
    assert core._ship_fields == ("revenue",)
    assert [p.out_field for p in core._pos_max_parts] == ["hi"]

    rng = np.random.default_rng(3)
    nk, per = 4, 300
    batches = []
    for lo in range(0, per, 60):
        m = min(60, per - lo)
        ts = np.repeat(np.arange(lo, lo + m) * 7, nk)
        batches.append(batch_from_columns(
            schema, key=np.tile(np.arange(nk), m),
            id=np.repeat(np.arange(lo, lo + m), nk), ts=ts,
            revenue=rng.integers(1, 98, m * nk)))

    def run(c):
        outs = [c.process(b) for b in batches]
        outs.append(c.flush())
        outs = [o for o in outs if len(o)]
        return np.sort(np.concatenate(outs), order=["key", "id"])

    got = run(core)
    want = run(WinSeqCore(spec, MultiReducer(
        ("count", None, "n"), ("max", "ts", "hi"),
        ("sum", "revenue", "rev"))))
    assert len(got) == len(want)
    for f in ("key", "id", "ts", "n", "hi", "rev"):
        np.testing.assert_array_equal(got[f], want[f], err_msg=f)
