"""Plane-wide observability (ISSUE 19, docs/OBSERVABILITY.md
"Federation & SLOs"):

* the ``federate=`` knob contract: unset never imports
  ``obs.federation`` / ``obs.slo`` and keeps the wire byte-identical;
* the ``-8`` TELEMETRY frame family (ship, sink, refusal, size cap);
* SLO objectives and multi-window burn rates (transitions-only events);
* the aggregator: ingest, staleness, spooling, the federated
  host-labelled exposition, and the ``wf_top --plane`` state file;
* the crash black-box (flight recorder + wf_blackbox renderer);
* size-based rotation of ``metrics.jsonl`` / ``events.jsonl`` and
  ``wf_top``'s read-across-the-roll;
* ``Rescale(up_slo_burn=)``, the control-plane bridge;
* the 3-process demo: two shipping workers, one killed -9 — the
  availability objective burns, the victim's black box survives at the
  aggregator, the survivor stays fresh.
"""

import glob
import importlib.util
import json
import os
import socket
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from windflow_tpu.core.tuples import Schema, batch_from_columns
from windflow_tpu.obs import EventLog, MetricsRegistry
from windflow_tpu.obs.expo import _esc, render_registry, render_sample
from windflow_tpu.obs.federation import (SNAP_VERSION, BlackBox,
                                         FederationPolicy,
                                         FederationShipper,
                                         TelemetryAggregator, as_policy)
from windflow_tpu.obs.sampler import Sampler
from windflow_tpu.obs.slo import (SloEvaluator, SloObjective, SloPolicy,
                                  local_view)
from windflow_tpu.parallel.channel import (_LEN, ChannelError, RowReceiver,
                                           RowSender)
from windflow_tpu.runtime.engine import Dataflow
from windflow_tpu.runtime.node import Node, SourceNode

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SCHEMA = Schema(value=np.int64)


@pytest.fixture(autouse=True)
def _no_ambient_obs_env(monkeypatch):
    monkeypatch.delenv("WF_LOG_DIR", raising=False)
    monkeypatch.delenv("WF_SAMPLE_PERIOD", raising=False)


def _load_script(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "scripts", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def mk_batch(n=8, lo=0):
    ids = np.arange(lo, lo + n)
    return batch_from_columns(SCHEMA, key=np.zeros(n), id=ids, ts=ids,
                              value=ids)


def mk_snap(host="w1", seq=0, t=None, **over):
    snap = {"v": SNAP_VERSION, "host": host,
            "t": time.time() if t is None else t, "seq": seq,
            "dataflow": "df", "nodes": [], "dead_letters": 0,
            "counters": {}, "gauges": {}}
    snap.update(over)
    return snap


# ----------------------------------------------------------- knob contract

def test_federation_policy_validation():
    with pytest.raises(ValueError):
        FederationPolicy(period=0)
    with pytest.raises(ValueError):
        FederationPolicy(keep=0)
    with pytest.raises(ValueError):
        FederationPolicy(event_tail=-1)
    with pytest.raises(ValueError):
        FederationPolicy(stale_after=0)
    with pytest.raises(TypeError):
        FederationPolicy(slo=object())
    assert FederationPolicy(period=2.0).stale_after == 6.0
    assert as_policy(True).period == 1.0
    pol = FederationPolicy(host="h")
    assert as_policy(pol) is pol
    with pytest.raises(TypeError):
        as_policy(1.5)


def test_federation_policy_agrees_with():
    slo = SloPolicy([SloObjective("a", "depth", bad_above=10)])
    a = FederationPolicy(host="h", period=0.5, slo=slo)
    assert a.agrees_with(FederationPolicy(host="h", period=0.5, slo=slo))
    assert not a.agrees_with(FederationPolicy(host="h", period=0.25,
                                              slo=slo))
    # slo compares by identity: one process runs one evaluator
    twin = SloPolicy([SloObjective("a", "depth", bad_above=10)])
    assert not a.agrees_with(FederationPolicy(host="h", period=0.5,
                                              slo=twin))


def test_federate_unset_never_imports_package():
    """Seed contract: federate= unset => windflow_tpu.obs.federation and
    obs.slo are never imported (subprocess keeps sys.modules clean)."""
    code = (
        "import sys\n"
        "import numpy as np\n"
        "from windflow_tpu.api import MultiPipe\n"
        "from windflow_tpu.core.tuples import Schema\n"
        "from windflow_tpu.patterns.basic import Sink, Source\n"
        "S = Schema(value=np.int64)\n"
        "def gen(sh):\n"
        "    sh.push(key=0, id=0, ts=0, value=1)\n"
        "got = []\n"
        "p = (MultiPipe('seed', metrics=True)\n"
        "     .add_source(Source(gen, S))\n"
        "     .chain_sink(Sink(lambda b: got.append(b),"
        " vectorized=True)))\n"
        "p.run_and_wait_end()\n"
        "assert any(b is not None and len(b) for b in got)\n"
        "for mod in ('windflow_tpu.obs.federation',"
        " 'windflow_tpu.obs.slo'):\n"
        "    assert mod not in sys.modules, \\\n"
        "        mod + ' imported on the seed path'\n"
    )
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("WF_LOG_DIR", None)
    r = subprocess.run([sys.executable, "-c", code], cwd=REPO, env=env,
                       capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, r.stdout + r.stderr


def test_federate_unset_wire_is_byte_identical_to_seed():
    """federate= unset: the wire carries ONLY the seed grammar (dtype
    frame, data frames, -4 epochs, -1 EOS) — no -8 telemetry frames.
    Captured off a raw socket so nothing in the channel implementation
    can vouch for itself."""
    srv = socket.create_server(("127.0.0.1", 0))
    port = srv.getsockname()[1]

    def feed():
        s = RowSender("127.0.0.1", port)
        s.send(mk_batch(4))
        s.send_epoch(1)
        s.send(mk_batch(4, lo=50))
        s.close()
        assert not hasattr(s, "_journal"), "journal built without resume="

    t = threading.Thread(target=feed)
    t.start()
    conn, _ = srv.accept()
    raw = bytearray()
    while True:
        chunk = conn.recv(65536)
        if not chunk:
            break
        raw.extend(chunk)
    t.join()
    conn.close()
    srv.close()
    lens, off = [], 0
    while off < len(raw):
        (n,) = _LEN.unpack(bytes(raw[off:off + 8]))
        off += 8
        lens.append(n)
        if n > 0:
            off += n
        elif n == -4:
            off += 8
        else:
            assert n == -1, f"non-seed control frame {n} on the wire"
    assert off == len(raw)
    assert [n for n in lens if n < 0] == [-4, -1]
    assert sum(1 for n in lens if n > 0) == 3   # dtype + 2 payloads


def test_engine_federate_falsy_means_off():
    for falsy in (None, 0, 0.0, False):
        df = Dataflow("off", federate=falsy)
        assert df.federate is None and df.federation is None


def test_wf217_federate_without_metrics_warns():
    with pytest.warns(UserWarning, match="WF217"):
        Dataflow("blind", federate=True)
    import warnings
    with warnings.catch_warnings(record=True) as rec:
        warnings.simplefilter("always")
        Dataflow("fed", metrics=True, federate=True)
    assert not [w for w in rec if "WF217" in str(w.message)]


def test_union_federate_policies_must_agree():
    from windflow_tpu.api import MultiPipe, union_multipipes
    from windflow_tpu.patterns.basic import Source

    def _leg(name, fed):
        p = MultiPipe(name, federate=fed)
        p.add_source(Source(lambda sh: None, SCHEMA))
        return p

    pol = FederationPolicy(host="h", period=0.5)
    merged = union_multipipes(_leg("a", pol), _leg("b", None), name="u")
    assert merged.federate is pol
    with pytest.raises(ValueError, match="conflicting federate"):
        union_multipipes(_leg("c", pol),
                         _leg("d", FederationPolicy(host="h", period=2.0)),
                         name="u2")


# ------------------------------------------------------------- SLO layer

def test_slo_objective_validation():
    with pytest.raises(ValueError):
        SloObjective("", "sig", bad_above=1)
    with pytest.raises(ValueError):
        SloObjective("x", "sig")                       # no direction
    with pytest.raises(ValueError):
        SloObjective("x", "sig", bad_above=1, bad_below=0)
    with pytest.raises(ValueError):
        SloObjective("x", "sig", bad_above=1, budget=0.0)
    with pytest.raises(ValueError):
        SloObjective("x", "sig", bad_above=1, budget=1.0)
    with pytest.raises(ValueError):
        SloObjective("x", "sig", bad_above=1, fast_window=0)
    with pytest.raises(ValueError):
        SloObjective("x", "sig", bad_above=1, fast_window=30,
                     slow_window=30)
    with pytest.raises(ValueError):
        SloObjective("x", "sig", bad_above=1, burn_threshold=0)
    hi = SloObjective("lat", "q95_us", bad_above=100.0)
    assert hi.bad(101) and not hi.bad(100)
    lo = SloObjective("avail", "availability", bad_below=0.9)
    assert lo.bad(0.5) and not lo.bad(0.9)


def test_slo_policy_validation():
    with pytest.raises(ValueError):
        SloPolicy([])
    with pytest.raises(TypeError):
        SloPolicy([object()])
    o = SloObjective("a", "sig", bad_above=1)
    with pytest.raises(ValueError):
        SloPolicy([o, SloObjective("a", "sig", bad_below=0)])
    with pytest.raises(TypeError):
        SloEvaluator(o)   # needs the policy, not a bare objective


def test_slo_multi_window_burn_and_transition_events():
    """burn = bad_fraction/budget over BOTH windows; one event per state
    transition, never per observation."""
    obj = SloObjective("lat", "q95_us", bad_above=100.0, budget=0.5,
                       fast_window=10.0, slow_window=100.0,
                       burn_threshold=1.0)
    m, ev = MetricsRegistry(), EventLog()
    sl = SloEvaluator(SloPolicy([obj]), metrics=m, events=ev, scope="t")
    for now in range(0, 5):                       # 5 good samples
        sl.observe({"q95_us": 50.0}, now=float(now))
    assert sl.burning() == []
    for now in range(5, 10):                      # then 5 bad
        sl.observe({"q95_us": 200.0}, now=float(now))
    # at now=9 both windows hold 5/10 bad = burn 1.0 >= threshold
    assert sl.burning() == ["lat"]
    for now in range(10, 26):                     # recovery
        sl.observe({"q95_us": 50.0}, now=float(now))
    assert sl.burning() == []
    burns = [e for e in ev.recent if e["event"] == "slo_burn"]
    assert [e["state"] for e in burns] == ["burn", "ok"]
    assert burns[0]["objective"] == "lat"
    assert burns[0]["scope"] == "t"
    assert burns[0]["threshold"] == 1.0
    g = m.snapshot()["gauges"]
    assert 'slo_burn_fast{objective="lat"}' in g
    assert 'slo_burn_slow{objective="lat"}' in g
    assert g["slo_burn_max"] < 1.0                # recovered


def test_slo_absent_signal_is_skipped():
    sl = SloEvaluator(SloPolicy([SloObjective(
        "avail", "availability", bad_below=0.9)]), metrics=MetricsRegistry())
    assert sl.observe({"q95_us": 1.0}, now=1.0) == []
    g = sl._metrics.snapshot()["gauges"]
    assert 'slo_burn_fast{objective="avail"}' not in g


def test_slo_local_view_signals_and_rates():
    prev = {"t": 10.0, "nodes": [{"shed": 4, "quarantined": 0}],
            "dead_letters": 0}
    rec = {"t": 12.0, "dead_letters": 3,
           "nodes": [{"q_p95_us": 5.0, "svc_p95_us": 7.0, "depth": 3,
                      "shed": 10, "quarantined": 2}]}
    v = local_view(rec, prev)
    assert v["q95_us"] == 5.0 and v["svc95_us"] == 7.0
    assert v["depth"] == 3 and v["dead_letters"] == 3
    assert v["shed_rate"] == 3.0 and v["quarantine_rate"] == 1.0
    first = local_view(rec)                       # no prev: rates 0
    assert first["shed_rate"] == 0.0


# -------------------------------------------------------------- shipper

def test_shipper_snapshot_schema_and_ring():
    ev = EventLog()
    for i in range(4):
        ev.emit("epoch", n=i)
    pol = FederationPolicy(host="h1", keep=3, event_tail=2)
    sh = FederationShipper(pol, host="h1", dataflow_name="df0", events=ev)
    for seq in range(5):
        sh.on_sample({"t": 100.0 + seq, "seq": seq, "dataflow": "df0",
                      "nodes": [{"node": "n", "id": "x", "depth": seq,
                                 "hwm": 9, "shed": 0, "quarantined": 0,
                                 "rcv_tuples": 10 * seq,
                                 "q_p95_us": 1.5}],
                      "dead_letters": 1, "counters": {"c": seq},
                      "gauges": {"g": 2.0}})
    assert len(sh.recent) == 3                    # keep-bounded ring
    snap = sh.snapshot()
    assert snap["v"] == SNAP_VERSION and snap["host"] == "h1"
    assert snap["seq"] == 4 and snap["dataflow"] == "df0"
    assert snap["counters"] == {"c": 4} and snap["gauges"] == {"g": 2.0}
    (n,) = snap["nodes"]
    # compact node projection: no id/hwm, keeps the plane-view fields
    assert n == {"node": "n", "depth": 4, "shed": 0, "quarantined": 0,
                 "rcv_tuples": 40, "q_p95_us": 1.5}
    assert [e["n"] for e in snap["events"]] == [2, 3]   # event_tail=2
    assert json.loads(json.dumps(snap)) == snap   # wire-encodable


def test_shipper_host_label_sanitised():
    sh = FederationShipper(FederationPolicy(), host='bad host/"x"')
    assert sh.host == "bad_host__x_"


# ------------------------------------------------------- the -8 family

def test_telemetry_frame_roundtrip_over_the_wire():
    ms, mr = MetricsRegistry(), MetricsRegistry()
    agg = TelemetryAggregator(FederationPolicy())
    recv = RowReceiver(n_senders=1, metrics=mr, telemetry_sink=agg)

    def feed():
        s = RowSender("127.0.0.1", recv.port, metrics=ms)
        s.send(mk_batch(4))
        s.send_telemetry(mk_snap(host="w1", seq=7,
                                 counters={"sealed": 3}))
        s.close()

    t = threading.Thread(target=feed)
    t.start()
    got = list(recv.batches())
    t.join()
    assert len(got) == 1
    last = agg.latest("w1")
    assert last["seq"] == 7 and last["counters"] == {"sealed": 3}
    assert ms.snapshot()["counters"]["fed_shipped_bytes"] > 0
    assert mr.snapshot()["counters"]["fed_fetched_bytes"] > 0


def test_telemetry_frame_without_sink_refused_loudly():
    recv = RowReceiver(n_senders=1)

    def feed():
        s = RowSender("127.0.0.1", recv.port)
        try:
            s.send_telemetry(mk_snap())
            s.close()
        except OSError:
            pass    # receiver died on the refusal first

    t = threading.Thread(target=feed)
    t.start()
    with pytest.raises(ChannelError, match="telemetry_sink"):
        list(recv.batches())
    t.join()


def test_telemetry_frame_size_cap():
    recv = RowReceiver(n_senders=1,
                       telemetry_sink=TelemetryAggregator())

    def feed():
        s = RowSender("127.0.0.1", recv.port)
        try:
            # a hand-rolled oversized -8 frame straight onto the socket
            s._sock.sendall(_LEN.pack(-8) + _LEN.pack(5 << 20))
        except OSError:
            pass

    t = threading.Thread(target=feed)
    t.start()
    with pytest.raises(ChannelError, match="telemetry-frame"):
        list(recv.batches())
    t.join()


# ------------------------------------------------------------ aggregator

def test_aggregator_refuses_bad_snapshots():
    agg = TelemetryAggregator()
    with pytest.raises(ValueError, match="version"):
        agg.accept(mk_snap(v=SNAP_VERSION + 1))
    with pytest.raises(ValueError):
        agg.accept("not a dict")
    snap = mk_snap()
    del snap["host"]
    with pytest.raises(ValueError, match="host"):
        agg.accept(snap)


def test_aggregator_staleness_spool_and_refresh(tmp_path):
    pol = FederationPolicy(period=1.0, stale_after=5.0, keep=4)
    m, ev = MetricsRegistry(), EventLog()
    agg = TelemetryAggregator(pol, metrics=m, events=ev,
                              spool_dir=str(tmp_path))
    agg.accept(mk_snap("w1", seq=1))
    agg.accept(mk_snap("w1", seq=2))
    agg.accept(mk_snap("w2", seq=9))
    assert agg.poll() == []                       # everyone fresh
    assert [s["seq"] for s in agg.snapshots("w1")] == [1, 2]
    assert m.snapshot()["gauges"]["fed_hosts"] == 2

    late = time.monotonic() + 100.0
    assert agg.poll(now=late) == ["w1", "w2"]
    stale_ev = [e for e in ev.recent if e["event"] == "fed_peer"
                and e["state"] == "stale"]
    assert {e["host"] for e in stale_ev} == {"w1", "w2"}
    # the dead peers' last snapshots were spooled, once per episode
    files = sorted(glob.glob(os.path.join(str(tmp_path),
                                          "blackbox-*.json")))
    assert len(files) == 2
    agg.poll(now=late + 1)                        # idempotent re-poll
    assert len(glob.glob(os.path.join(str(tmp_path),
                                      "blackbox-*.json"))) == 2
    with open([f for f in files if "-w1-" in f][0]) as f:
        box = json.load(f)
    assert box["reason"] == "stale" and box["host"] == "w1"
    assert [s["seq"] for s in box["samples"]] == [1, 2]

    # a returning peer flips back to fresh and re-arms the spool
    agg.accept(mk_snap("w1", seq=3))
    fresh_ev = [e for e in ev.recent if e["event"] == "fed_peer"
                and e["state"] == "fresh"]
    assert [e["host"] for e in fresh_ev] == ["w1"]
    assert agg.hosts()["w1"]["fresh"]
    assert not agg.hosts(now=late)["w2"]["fresh"]
    assert agg.poll(now=time.monotonic() + 300)   # re-stales w1
    assert len(glob.glob(os.path.join(str(tmp_path),
                                      "blackbox-w1-*.json"))) == 2
    assert m.snapshot()["counters"]["fed_spooled"] == 3


def test_aggregator_on_death_spools_by_pid(tmp_path):
    agg = TelemetryAggregator(FederationPolicy(stale_after=60.0),
                              spool_dir=str(tmp_path))
    agg.accept(mk_snap("7", seq=4))
    agg.on_death(7)     # plane supervisor adapter: host label "<pid>"
    files = glob.glob(os.path.join(str(tmp_path), "blackbox-7-*.json"))
    assert len(files) == 1
    with open(files[0]) as f:
        assert json.load(f)["reason"] == "plane_death"


def test_aggregator_view_availability_and_rates():
    pol = FederationPolicy(period=0.05, stale_after=0.2)
    agg = TelemetryAggregator(pol)
    agg.accept(mk_snap("w2", seq=1))
    time.sleep(0.3)                               # w2 goes stale
    agg.accept(mk_snap("w1", seq=1, t=100.0,
                       nodes=[{"node": "n", "shed": 0, "q_p95_us": 4.0}]))
    agg.accept(mk_snap("w1", seq=2, t=101.0,
                       nodes=[{"node": "n", "shed": 5, "q_p95_us": 9.0}]))
    agg.poll()
    v = agg.view()
    assert v["availability"] == 0.5               # 1 fresh of 2
    assert v["q95_us"] == 9.0                     # fresh hosts only
    assert v["shed_rate"] == 5.0                  # 5 sheds over 1 s
    assert v["stale_seconds"] > 0.2


def test_aggregator_federated_exposition():
    agg = TelemetryAggregator(FederationPolicy())
    agg.accept(mk_snap("w1", seq=3, dead_letters=2,
                       counters={"sealed": 4, 'edge{peer="2"}': 7},
                       gauges={"depth": 1.5},
                       nodes=[{"node": "map", "depth": 2,
                               "q_p95_us": 8.0}]))
    fed = agg.federated()
    assert fed["counters"]['sealed{host="w1"}'] == 4
    # a name with embedded labels gets host appended, not nested
    assert fed["counters"]['edge{peer="2",host="w1"}'] == 7
    assert fed["gauges"]['fed_fresh{host="w1"}'] == 1
    assert fed["gauges"]['fed_dead_letters{host="w1"}'] == 2
    assert fed["gauges"]['fed_node_depth{host="w1",node="map"}'] == 2
    text = agg.render()
    assert 'wf_sealed{host="w1"} 4' in text
    assert 'wf_fed_node_q_p95_us{host="w1",node="map"} 8.0' in text
    # one HELP/TYPE per family, however many hosts
    agg.accept(mk_snap("w2", counters={"sealed": 1}))
    text = agg.render()
    assert text.count("# HELP wf_sealed") == 1
    assert 'wf_sealed{host="w2"} 1' in text


def test_aggregator_state_file_and_wf_top_plane(tmp_path):
    state_path = os.path.join(str(tmp_path), "federation.json")
    pol = FederationPolicy(period=1.0, stale_after=5.0)
    agg = TelemetryAggregator(pol, state_path=state_path)
    agg.accept(mk_snap("w1", seq=6, dataflow="demo",
                       nodes=[{"node": "n", "depth": 2, "rcv_tuples": 40,
                               "shed": 1, "q_p95_us": 3.0}]))
    agg.accept(mk_snap("w2", seq=2, dataflow="demo"))
    agg.poll()
    with open(state_path) as f:
        state = json.load(f)
    assert set(state) >= {"hosts", "latest", "view", "slo_burning"}
    assert state["hosts"]["w1"]["seq"] == 6

    wf_top = _load_script("wf_top")
    text = wf_top.render_plane(state)
    assert "hosts=2 fresh=2" in text
    assert "w1" in text and "demo" in text
    assert "availability=1.00" in text and "slo=ok" in text

    # stale + burning renders STALE / BURN markers
    agg.poll(now=time.monotonic() + 100)
    with open(state_path) as f:
        state = json.load(f)
    text = wf_top.render_plane(state)
    assert "STALE" in text and "fresh=0" in text


# ---------------------------------------------------- label escaping (_esc)

def _parse_series(text):
    """Tiny Prometheus text-format parser: {family: [(labels, value)]},
    undoing the three _esc escapes — the round-trip oracle."""
    out = {}
    for line in text.splitlines():
        if not line or line.startswith("#") or "{" not in line:
            continue
        name, rest = line.split("{", 1)
        labstr, val = rest.rsplit("} ", 1)
        labels, i = {}, 0
        while i < len(labstr):
            j = labstr.index("=", i)
            key = labstr[i:j]
            assert labstr[j + 1] == '"'
            i, buf = j + 2, []
            while True:
                c = labstr[i]
                if c == "\\":
                    buf.append({"\\": "\\", '"': '"', "n": "\n"}[labstr[i + 1]])
                    i += 2
                elif c == '"':
                    i += 1
                    break
                else:
                    buf.append(c)
                    i += 1
            labels[key] = "".join(buf)
            if i < len(labstr) and labstr[i] == ",":
                i += 1
        out.setdefault(name, []).append((labels, val))
    return out


WEIRD = 'a\\b"c\nd'


def test_esc_escapes_all_three():
    assert _esc(WEIRD) == 'a\\\\b\\"c\\nd'
    assert "\n" not in _esc(WEIRD)


def test_esc_roundtrip_through_sample_exposition():
    """A node name with backslash/quote/newline survives render + parse
    — no torn lines, no doubled escapes."""
    sample = {"dataflow": "df", "nodes": [
        {"node": WEIRD, "id": "x", "depth": 3, "hwm": 4, "shed": 0,
         "quarantined": 0}]}
    text = render_sample(sample)
    assert all(ln.startswith(("#", "wf_")) for ln in
               text.splitlines() if ln)          # nothing torn mid-line
    series = _parse_series(text)
    labels, val = series["wf_node_inbox_depth"][0]
    assert labels["node"] == WEIRD and val == "3"


def test_esc_roundtrip_through_federated_exposition():
    """An embedded-label registry name built with _esc survives the
    aggregator's host-label append and the federated render."""
    name = f'odd{{path="{_esc(WEIRD)}"}}'
    agg = TelemetryAggregator(FederationPolicy())
    agg.accept(mk_snap("w1", counters={name: 5}))
    series = _parse_series(agg.render())
    matches = [lv for lv in series.get("wf_odd", ()) ]
    assert len(matches) == 1
    labels, val = matches[0]
    assert labels == {"path": WEIRD, "host": "w1"} and val == "5"


# ------------------------------------------------------------- black box

def test_blackbox_dump_contents_and_budget(tmp_path):
    ev = EventLog()
    ev.emit("epoch", n=1)
    sh = FederationShipper(FederationPolicy(keep=2), host="w1")
    sh.on_sample({"t": 1.0, "seq": 0, "nodes": []})
    sh.on_sample({"t": 2.0, "seq": 1, "nodes": []})
    bb = BlackBox(str(tmp_path), "w1", events=ev, shipper=sh, max_dumps=2)
    path = bb.dump("node_error", failed_node="map", error="RuntimeError")
    assert path and os.path.exists(path)
    with open(path) as f:
        doc = json.load(f)
    assert doc["v"] == SNAP_VERSION and doc["node"] == "w1"
    assert doc["reason"] == "node_error"
    assert doc["failed_node"] == "map" and doc["error"] == "RuntimeError"
    assert [e["event"] for e in doc["events"]][0] == "epoch"
    assert [s["seq"] for s in doc["samples"]] == [0, 1]
    assert any(e["event"] == "blackbox" and e["path"] == path
               for e in ev.recent)
    assert bb.dump("again") is not None           # budget: 2 dumps
    assert bb.dump("past budget") is None
    assert len(glob.glob(os.path.join(str(tmp_path),
                                      "blackbox-w1-*.json"))) == 2
    # no trace_dir: silently declined, never raises
    assert BlackBox(None, "x").dump("whatever") is None


def test_wf_blackbox_renderer(tmp_path):
    wb = _load_script("wf_blackbox")
    doc = {"v": 1, "node": "w1", "t": 100.0, "reason": "node_error",
           "failed_node": "map",
           "events": [{"t": 90.0, "event": "epoch", "n": 3}],
           "spans": [{"t": 95.0, "node": "map", "q_us": 10.0,
                      "svc_us": 20.0}],
           "samples": [{"t": 99.0, "seq": 7,
                        "nodes": [{"depth": 5, "shed": 2}],
                        "dead_letters": 1}]}
    rows = wb.timeline(doc)
    assert [k for _, k, _ in rows] == ["event", "span", "sample"]
    assert [t for t, _, _ in rows] == sorted(t for t, _, _ in rows)
    text = wb.render(doc)
    assert "reason=node_error" in text and "failed_node=map" in text
    assert "seq=7" in text and "max_depth=5" in text
    assert "(empty rings" in wb.render({"node": "x", "reason": "r"})

    p = os.path.join(str(tmp_path), "blackbox-w1-1.json")
    with open(p, "w") as f:
        json.dump(doc, f)
    assert wb.find_dumps(str(tmp_path)) == [p]
    assert wb.find_dumps(p) == [p]
    assert wb.main([p]) == 0
    assert wb.main([str(tmp_path), "--list"]) == 0
    assert wb.main([os.path.join(str(tmp_path), "empty")]) == 2


# ----------------------------------------------------- engine integration

class _Src(SourceNode):
    def __init__(self, n=6, name="src"):
        super().__init__(name)
        self.n = n

    def generate(self):
        for i in range(self.n):
            self.emit(np.arange(4, dtype=np.int64) + i)


class _Snk(Node):
    def __init__(self, name="snk", boom=False):
        super().__init__(name)
        self.boom = boom
        self.got = []

    def svc(self, batch, channel=0):
        if self.boom:
            raise RuntimeError("injected sink fault")
        self.got.append(batch.copy())


def _fed_linear(tmp, boom=False, **fed_kw):
    df = Dataflow("fedgraph", trace_dir=str(tmp), metrics=True,
                  sample_period=0.02,
                  federate=FederationPolicy(host="h1", period=0.02,
                                            **fed_kw))
    s = df.add(_Src())
    k = df.add(_Snk(boom=boom))
    df.connect(s, k)
    return df, k


def test_engine_builds_shipper_and_blackbox(tmp_path):
    df, k = _fed_linear(tmp_path)
    df.run_and_wait_end()
    assert df.federation is not None and df.federation.host == "h1"
    assert len(df.federation.recent) >= 1         # rode the sampler
    assert df._blackbox is not None
    assert len(k.got) == 6
    # no plane bound: nothing shipped, nothing dumped
    assert not glob.glob(os.path.join(str(tmp_path), "blackbox-*"))


def test_engine_blackbox_off_by_policy(tmp_path):
    df, _ = _fed_linear(tmp_path, blackbox=False)
    df.run_and_wait_end()
    assert df.federation is not None and df._blackbox is None


def test_node_error_dumps_blackbox(tmp_path):
    df, _ = _fed_linear(tmp_path, boom=True)
    df.run()
    with pytest.raises(RuntimeError, match="injected sink fault"):
        df.wait(timeout=120)
    files = glob.glob(os.path.join(str(tmp_path), "blackbox-*.json"))
    assert len(files) == 1
    with open(files[0]) as f:
        doc = json.load(f)
    assert doc["reason"] == "node_error"
    assert doc["failed_node"] == "snk"
    assert doc["error"] == "RuntimeError"
    assert any(e["event"] == "node_error" for e in doc["events"])
    assert any(e["event"] == "blackbox" for e in df.events.recent)


def test_local_slo_evaluates_on_the_sampler(tmp_path):
    slo = SloPolicy([SloObjective("dl", "dead_letters", bad_above=1e9)])
    df, _ = _fed_linear(tmp_path, slo=slo)
    df.run_and_wait_end()
    g = df.metrics.snapshot()["gauges"]
    assert 'slo_burn_fast{objective="dl"}' in g   # evaluator ran
    assert g["slo_burn_max"] == 0.0


# -------------------------------------------------- control-plane bridge

def test_rescale_up_slo_burn_rule():
    from windflow_tpu.control import Rescale
    with pytest.raises(ValueError, match="up_slo_burn"):
        Rescale("kf", max_workers=4, up_slo_burn=0)
    r = Rescale("kf", max_workers=4, up_slo_burn=1.0, hysteresis=1,
                cooldown=0.0)
    assert r.observe((0, 0.0, 0.0, 2.0), now=1.0) == 1
    assert r.observe((0, 0.0, 0.0, 0.5), now=2.0) == 0
    assert r.observe((0, 0.0), now=3.0) == 0      # pre-SLO tuple form
    assert r.observe((0, 0.0, 0.0), now=4.0) == 0  # pre-burn tuple form
    assert 1.0 in r._key() and "up_slo_burn=1.0" in repr(r)
    twin = Rescale("kf", max_workers=4, up_slo_burn=1.0, hysteresis=1,
                   cooldown=0.0)
    assert r._key() == twin._key()
    assert r._key() != Rescale("kf", max_workers=4, up_slo_burn=2.0,
                               hysteresis=1, cooldown=0.0)._key()


# --------------------------------------------------------- file rotation

class _StubDF:
    def __init__(self, trace_dir):
        self.trace_dir = trace_dir
        self.name = "stub"
        self.nodes = []
        self.metrics = None
        self.events = None
        self.dead_letters = []
        self._inboxes = {}


def test_sampler_rotation_keeps_n_and_loses_no_line(tmp_path):
    with pytest.raises(ValueError):
        Sampler(_StubDF(None), 0.01, max_bytes=0)
    with pytest.raises(ValueError):
        Sampler(_StubDF(None), 0.01, keep=0)
    s = Sampler(_StubDF(str(tmp_path)), 0.01, max_bytes=256, keep=2)
    path = os.path.join(str(tmp_path), "metrics.jsonl")
    s._path = path
    f = open(path, "a")
    for _ in range(60):
        f = s._write_sample(f)
    f.close()
    assert os.path.exists(path + ".1") and os.path.exists(path + ".2")
    assert not os.path.exists(path + ".3")        # keep=2 bound
    assert os.path.getsize(path) <= 256
    seqs = []
    for p in (path + ".2", path + ".1", path):
        with open(p) as fh:
            for line in fh:
                seqs.append(json.loads(line)["seq"])
    # rotation is between whole lines: the kept tail is contiguous
    assert seqs == list(range(seqs[0], 60))


def test_wf_top_read_samples_follows_the_roll(tmp_path):
    wf_top = _load_script("wf_top")
    path = os.path.join(str(tmp_path), "metrics.jsonl")

    def put(seqs, p=path):
        with open(p, "a") as f:
            for s in seqs:
                f.write(json.dumps({"t": float(s), "seq": s}) + "\n")

    put(range(5))
    samples, off = wf_top.read_samples(path, 0)
    assert [s["seq"] for s in samples] == [0, 1, 2, 3, 4]
    put([5, 6])                                   # appended after read
    os.replace(path, path + ".1")                 # ...then the roll
    put([7, 8])
    samples, off2 = wf_top.read_samples(path, off)
    # the unread tail of the rolled file, then the fresh file's head
    assert [s["seq"] for s in samples] == [5, 6, 7, 8]
    assert wf_top.read_samples(path, off2)[0] == []


def test_eventlog_rotation_preserves_every_event(tmp_path):
    with pytest.raises(ValueError):
        EventLog(max_bytes=0)
    path = os.path.join(str(tmp_path), "events.jsonl")
    log = EventLog(path, max_bytes=200)
    for i in range(12):
        log.emit("epoch", n=i)
    log.close()
    assert os.path.exists(path + ".1")
    assert os.path.getsize(path) <= 200
    ns = []
    for p in (path + ".1", path):
        with open(p) as f:
            for line in f:
                rec = json.loads(line)            # whole records only
                ns.append(rec["n"])
    # .1 holds one rolled generation; live + .1 cover a contiguous tail
    # through the newest event, and the ring still holds everything
    assert ns == list(range(ns[0], 12))
    assert [e["n"] for e in log.recent] == list(range(12))
    log.emit("epoch", n=99)                       # post-close: ring only
    assert log.recent[-1]["n"] == 99
    assert 99 not in [json.loads(l)["n"] for l in open(path)]


# -------------------------------------------------- the 3-process demo

_WORKER = """\
import sys, time
from windflow_tpu.parallel.channel import RowSender
port, label = int(sys.argv[1]), sys.argv[2]
s = RowSender("127.0.0.1", port, connect_deadline=30)
seq = 0
end = time.time() + 60
while time.time() < end:
    s.send_telemetry({"v": 1, "host": label, "t": time.time(),
                      "seq": seq, "dataflow": "demo",
                      "nodes": [{"node": "n0", "depth": seq, "shed": 0}],
                      "dead_letters": 0, "counters": {"beats": seq},
                      "gauges": {}})
    seq += 1
    time.sleep(0.05)
"""


def test_plane_demo_kill_one_worker_burns_availability(tmp_path):
    """The ISSUE 19 acceptance demo: two worker processes ship
    snapshots over real row-plane links into one aggregator; kill -9
    one worker => the availability objective burns, the aggregator
    holds the victim's black box, the survivor stays fresh."""
    slo = SloPolicy([SloObjective("availability", "availability",
                                  bad_below=0.9, budget=0.2,
                                  fast_window=0.5, slow_window=3.0)])
    pol = FederationPolicy(period=0.05, stale_after=0.4, slo=slo)
    spool = os.path.join(str(tmp_path), "spool")
    m, ev = MetricsRegistry(), EventLog()
    agg = TelemetryAggregator(pol, metrics=m, events=ev, spool_dir=spool)

    recvs = [RowReceiver(n_senders=1, telemetry_sink=agg)
             for _ in range(2)]
    threads = []
    for r in recvs:
        def drain(r=r):
            try:
                for _ in r.batches():
                    pass
            except Exception:   # noqa: BLE001 — a killed peer tears its
                pass            # own link; the demo asserts via the agg
        t = threading.Thread(target=drain, daemon=True)
        t.start()
        threads.append(t)

    script = os.path.join(str(tmp_path), "worker.py")
    with open(script, "w") as f:
        f.write(_WORKER)
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    env.pop("WF_LOG_DIR", None)
    procs = [subprocess.Popen(
        [sys.executable, script, str(r.port), label], cwd=REPO, env=env)
        for r, label in zip(recvs, ("w1", "w2"))]
    try:
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            agg.poll()
            h = agg.hosts()
            if {"w1", "w2"} <= set(h) and all(
                    v["fresh"] and v["seq"] >= 2 for v in h.values()):
                break
            time.sleep(0.05)
        else:
            pytest.fail(f"workers never federated: {agg.hosts()}")

        procs[0].kill()                           # SIGKILL w1
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            agg.poll()
            if "availability" in (agg.slo.burning()):
                break
            time.sleep(0.05)
        else:
            pytest.fail(f"availability never burned: view={agg.view()} "
                        f"hosts={agg.hosts()}")

        h = agg.hosts()
        assert not h["w1"]["fresh"], "victim still fresh"
        assert h["w2"]["fresh"], "survivor went stale"
        burns = [e for e in ev.recent if e["event"] == "slo_burn"]
        assert burns and burns[0]["objective"] == "availability"
        assert burns[0]["state"] == "burn" and burns[0]["scope"] == "plane"
        # the victim's last snapshots survived it at the aggregator
        boxes = glob.glob(os.path.join(spool, "blackbox-w1-*.json"))
        assert len(boxes) == 1
        with open(boxes[0]) as f:
            box = json.load(f)
        assert box["reason"] == "stale"
        assert box["samples"] and all(s["host"] == "w1"
                                      for s in box["samples"])
        seqs = [s["seq"] for s in box["samples"]]
        assert seqs == sorted(seqs) and seqs[-1] >= 1
        assert not glob.glob(os.path.join(spool, "blackbox-w2-*"))

        wf_top = _load_script("wf_top")
        text = wf_top.render_plane(agg.state())
        assert "STALE" in text and "slo=BURN[availability]" in text
        assert m.snapshot()["gauges"]["slo_burn_max"] >= 1.0
    finally:
        for p in procs:
            p.kill()
            p.wait(timeout=30)
        for r in recvs:
            r.close()
        for t in threads:
            t.join(timeout=10)
