"""Static graph validation (windflow_tpu/check/, docs/CHECKS.md):

* a parametrized corpus where every WF### id has a minimal failing
  graph AND a minimally-fixed twin that must validate clean;
* the ``check=`` knob contract: unset never imports the package,
  'warn' reports CheckWarnings and still runs, 'error' raises
  CheckError before any thread starts (WF id + node_stats_name in the
  message), union merges by strictness;
* suppression directives (``# wf-lint: disable=WF###``) and the
  closure analyzer's lock heuristic;
* the tier-1 self-lint: the four bench apps validate diagnostic-free;
* the ``scripts/wf_lint.py`` CLI over the seeded misconfig corpus
  (tests/check_corpus.py) and over the bench apps.
"""

import json
import os
import subprocess
import sys
import textwrap
import threading
import time
import warnings

import numpy as np
import pytest

from windflow_tpu.api import MultiPipe, union_multipipes
from windflow_tpu.check import CheckError, CheckWarning, validate
from windflow_tpu.control import Admission, ControlPolicy, Rescale
from windflow_tpu.ops.functions import Reducer
from windflow_tpu.patterns.key_farm import KeyFarm
from windflow_tpu.core.tuples import Schema
from windflow_tpu.core.windows import WindowSpec, WinType
from windflow_tpu.parallel.channel import WireConfig
from windflow_tpu.parallel.plane import PlanePolicy
from windflow_tpu.patterns.basic import (Map, Sink, Source,
                                         _AccumulatorNode)
from windflow_tpu.patterns.pane_farm import PaneFarm
from windflow_tpu.patterns.win_seq import WinSeq, WinSeqNode
from windflow_tpu.recovery.policy import RecoveryPolicy
from windflow_tpu.runtime.emitters import StandardEmitter, default_routing
from windflow_tpu.runtime.engine import Dataflow
from windflow_tpu.runtime.overload import OverloadPolicy

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SCHEMA = Schema(value=np.int64)


@pytest.fixture(autouse=True)
def _no_ambient_obs_env(monkeypatch):
    """The corpus pins exact diagnostic sets: an ambient WF_LOG_DIR
    would silence WF207, an ambient WF_SAMPLE_PERIOD would plant it
    everywhere."""
    monkeypatch.delenv("WF_LOG_DIR", raising=False)
    monkeypatch.delenv("WF_SAMPLE_PERIOD", raising=False)


def _src(shipper):
    return None


def _red(key, gwid, rows):
    return {"value": rows["value"].sum()}


def _win_fields():
    return {"value": np.int64}


def _sink():
    return Sink(lambda b: None, vectorized=True)


def _pipe(*patterns, **kw):
    p = MultiPipe(kw.pop("name", "chk"), **kw)
    p.add_source(Source(_src, SCHEMA))
    for pat in patterns:
        p.add(pat)
    p.add_sink(_sink())
    return p


# ------------------------------------------------------- stub cores

class NativeResidentCore:
    """Stub matching the WF215 duck-type probe (class name + missing
    has_state_abi), so the corpus runs with or without the native .so.
    The real core sets ``has_state_abi`` from the loaded library; the
    stub's default (absent → False) models a pre-ABI .so."""
    spec = WindowSpec(4, 2, WinType.CB)

    def __init__(self, abi=False):
        if abi:
            self.has_state_abi = True


class _StubAsyncCore:
    """Async device core shape: process_batches + max_delay_s."""
    spec = WindowSpec(4, 2, WinType.CB)
    max_delay_s = None

    def process_batches(self, batch):
        return []


def _acc_node(name):
    return _AccumulatorNode(lambda row, acc: None, None, SCHEMA, name,
                            rich=False)


def _routing_df(routing):
    df = Dataflow("route")
    em = df.add(StandardEmitter(2, routing, name="em"))
    a = df.add(_acc_node("acc.0"))
    b = df.add(_acc_node("acc.1"))
    df.connect(em, a)
    df.connect(em, b)
    return df


def _native_df(abi=False):
    df = Dataflow("nat", recovery=RecoveryPolicy())
    df.add(WinSeqNode(NativeResidentCore(abi=abi), name="agg.0"))
    return df


def _async_df(max_delay):
    core = _StubAsyncCore()
    core.max_delay_s = max_delay
    df = Dataflow("dev", recovery=RecoveryPolicy())
    df.add(WinSeqNode(core, name="agg.0"))
    return df


def _comb_df(async_first):
    from windflow_tpu.runtime.comb import make_comb
    from windflow_tpu.patterns.basic import _MapNode
    win = WinSeqNode(_StubAsyncCore(), name="agg.0")
    mp = _MapNode(lambda b: None, "map.0", False, True, None)
    stages = [win, mp] if async_first else [mp, win]
    df = Dataflow("comb", recovery=RecoveryPolicy())
    df.add(make_comb(stages, name="chain.0"))
    return df


def _recovery_sink_pipe(opt_in):
    s = _sink()
    if opt_in:
        s.recoverable = True
    p = MultiPipe("recsink", recovery=RecoveryPolicy())
    p.add_source(Source(_src, SCHEMA))
    p.add_sink(s)
    return p


def _race_pipe(guarded):
    counts = [0]
    lock = threading.Lock()

    if guarded:
        def bump(batch):
            with lock:
                counts[0] += len(batch)
    else:
        def bump(batch):
            counts[0] += len(batch)

    return _pipe(Map(bump, parallelism=2, vectorized=True))


def _ctl_pipe(t, *, rescale=True, recovery=True, obs=True,
              recoverable=None, target="kf"):
    """Control-plane corpus factory (WF209-212): a keyed farm under a
    ControlPolicy, with the blinding / recoverable / recovery / target
    knobs toggled per case.  The sink opts into restart so recovery=
    twins stay WF204-clean."""
    if rescale:
        rules = [Rescale(target, max_workers=4)]
    else:
        rules = [Admission(max_rate=1e6, min_rate=1e3, high_depth=8,
                           low_depth=2)]
    kf = KeyFarm(Reducer("sum", "value"), win_len=8, slide_len=4,
                 pardegree=2, name="kf")
    if recoverable is not None:
        kf.recoverable = recoverable
    s = _sink()
    s.recoverable = True
    p = MultiPipe("ctl", control=ControlPolicy(rules),
                  recovery=RecoveryPolicy() if recovery else None,
                  metrics=True if obs else None,
                  trace_dir=str(t) if obs else None)
    p.add_source(Source(_src, SCHEMA))
    p.add(kf)
    p.add_sink(s)
    return p


def _trace_pipe(trace_dir):
    from windflow_tpu.obs.trace import TracePolicy
    return _pipe(name="tr", trace=TracePolicy(sample_rate=0.5),
                 trace_dir=trace_dir)


def _fed_pipe(t, obs=False):
    from windflow_tpu.obs.federation import FederationPolicy
    kw = dict(metrics=True, trace_dir=str(t)) if obs else {}
    return _pipe(name="fed", federate=FederationPolicy(host="chk"), **kw)


_G = 0


def _global_pipe(bad):
    if bad:
        def fn(batch):
            global _G
            _G += 1
    else:
        def fn(batch):
            return None
    return _pipe(Map(fn, parallelism=2, vectorized=True))


def _plane(flaw=None):
    """WF22x corpus: a declared 2-host plane (check/plane.py), clean by
    construction; ``flaw`` plants exactly one defect."""
    from windflow_tpu.check.plane import HostSpec, PlaneSpec
    wire = WireConfig(connect_deadline=30.0, heartbeat=2.0,
                      stall_timeout=10.0, resume=True, recovery=True)
    addresses = {0: ("10.0.0.1", 9000), 1: ("10.0.0.2", 9000)}
    if flaw == "orphan":
        addresses[2] = ("10.0.0.3", 9000)
    h0 = HostSpec(0, sends="<i8", resume=True,
                  plane=PlanePolicy(wire=wire), federate=True)
    h1 = HostSpec(1, sends="<i8",
                  expects="<f8" if flaw == "dtype" else None,
                  resume=None if flaw == "resume" else True,
                  ckpt_sink=None if flaw == "nosink" else True,
                  aggregator=flaw != "noagg")
    return PlaneSpec(addresses, [h0, h1], name="pl", wire=wire)


def _replay_pipe(kind):
    """WF303/WF304 corpus: a recoverable Map under recovery= whose fn
    commits (or avoids) the flagged effect."""
    if kind == "time":
        def fn(b):
            if b is not None:
                b["ts"][:] = int(time.time())
            return b
    elif kind == "rng":
        rng = np.random.default_rng(7)

        def fn(b):
            if b is not None:
                b["value"][:] = rng.integers(0, 10, len(b))
            return b
    elif kind == "file":
        def fn(b):
            open(os.devnull, "a").close()
            return b
    else:
        def fn(b):
            return b
    s = _sink()
    s.recoverable = True
    p = MultiPipe("eff", recovery=RecoveryPolicy())
    p.add_source(Source(_src, SCHEMA))
    p.add(Map(fn, vectorized=True))
    p.add_sink(s)
    return p


def _latency_pipe(t, blocking, latency=True):
    """WF305 corpus: a keyed farm whose window fn does (or does not)
    block, under a Rescale rule that is (or is not) latency-triggered."""
    if blocking:
        def wfn(key, gwid, rows):
            time.sleep(0.001)
            return {"value": rows["value"].sum()}
    else:
        def wfn(key, gwid, rows):
            return {"value": rows["value"].sum()}
    rule = (Rescale("kf", max_workers=4, up_q95_us=5000.0) if latency
            else Rescale("kf", max_workers=4))
    kf = KeyFarm(wfn, win_len=8, slide_len=4, pardegree=2, name="kf",
                 result_fields=_win_fields())
    s = _sink()
    s.recoverable = True
    p = MultiPipe("lat", control=ControlPolicy([rule]),
                  recovery=RecoveryPolicy(), metrics=True,
                  trace_dir=str(t))
    p.add_source(Source(_src, SCHEMA))
    p.add(kf)
    p.add_sink(s)
    return p


#: WF### -> (bad_factory, good_factory); factories take tmp_path.
#: Every bad graph must report exactly its id (subset check: the id is
#: present); every good twin must validate with ZERO diagnostics.
CORPUS = {
    "WF101": (lambda t: _routing_df(None),
              lambda t: _routing_df(default_routing)),
    "WF102": (lambda t: _pipe(WinSeq(_red, 4, 8, WinType.CB,
                                     result_fields=_win_fields())),
              lambda t: _pipe(WinSeq(_red, 8, 4, WinType.CB,
                                     result_fields=_win_fields()))),
    "WF103": (lambda t: _pipe(PaneFarm(_red, _red, 10, 3, WinType.CB,
                                       plq_result_fields=_win_fields(),
                                       wlq_result_fields=_win_fields())),
              lambda t: _pipe(PaneFarm(_red, _red, 10, 5, WinType.CB,
                                       plq_result_fields=_win_fields(),
                                       wlq_result_fields=_win_fields()))),
    "WF202": (lambda t: _async_df(0.005), lambda t: _async_df(None)),
    "WF203": (lambda t: _comb_df(async_first=True),
              lambda t: _comb_df(async_first=False)),
    "WF204": (lambda t: _recovery_sink_pipe(False),
              lambda t: _recovery_sink_pipe(True)),
    "WF205": (lambda t: WireConfig(heartbeat=5.0, stall_timeout=2.0),
              lambda t: WireConfig.hardened()),
    "WF206": (lambda t: WireConfig(heartbeat=2.0),
              lambda t: WireConfig(heartbeat=2.0, stall_timeout=10.0)),
    "WF207": (lambda t: _pipe(name="obs", metrics=True),
              lambda t: _pipe(name="obs", metrics=True,
                              trace_dir=str(t))),
    "WF208": (lambda t: _pipe(name="ovl", capacity=0,
                              overload=OverloadPolicy(shed="shed_newest")),
              lambda t: _pipe(name="ovl", capacity=16,
                              overload=OverloadPolicy(shed="shed_newest"))),
    "WF209": (lambda t: _ctl_pipe(t, rescale=False, recovery=False,
                                  obs=False),
              lambda t: _ctl_pipe(t, rescale=False, recovery=False)),
    "WF210": (lambda t: _ctl_pipe(t, recoverable=False),
              lambda t: _ctl_pipe(t)),
    "WF211": (lambda t: _ctl_pipe(t, recovery=False),
              lambda t: _ctl_pipe(t)),
    "WF212": (lambda t: _ctl_pipe(t, target="kfarm"),
              lambda t: _ctl_pipe(t)),
    "WF213": (lambda t: _trace_pipe(None),
              lambda t: _trace_pipe(str(t))),
    "WF214": (lambda t: WireConfig(resume=True),
              lambda t: WireConfig(resume=True, recovery=True)),
    "WF215": (lambda t: _native_df(), lambda t: _native_df(abi=True)),
    "WF217": (lambda t: _fed_pipe(t),
              lambda t: _fed_pipe(t, obs=True)),
    "WF216": (lambda t: PlanePolicy(wire=WireConfig.hardened()),
              lambda t: PlanePolicy(wire=WireConfig(
                  connect_deadline=60.0, heartbeat=2.0,
                  stall_timeout=10.0, resume=True, recovery=True))),
    "WF220": (lambda t: _plane("orphan"), lambda t: _plane()),
    "WF221": (lambda t: _plane("dtype"), lambda t: _plane()),
    "WF222": (lambda t: _plane("resume"), lambda t: _plane()),
    "WF223": (lambda t: _plane("nosink"), lambda t: _plane()),
    "WF224": (lambda t: _plane("noagg"), lambda t: _plane()),
    "WF301": (lambda t: _race_pipe(guarded=False),
              lambda t: _race_pipe(guarded=True)),
    "WF302": (lambda t: _global_pipe(True),
              lambda t: _global_pipe(False)),
    "WF303": (lambda t: _replay_pipe("time"),
              lambda t: _replay_pipe("rng")),
    "WF304": (lambda t: _replay_pipe("file"),
              lambda t: _replay_pipe("pure")),
    "WF305": (lambda t: _latency_pipe(t, blocking=True),
              lambda t: _latency_pipe(t, blocking=False)),
}


def test_corpus_covers_catalog():
    from windflow_tpu.check.diagnostics import CATALOG
    assert set(CORPUS) == set(CATALOG), (
        "every catalog id needs a minimal failing graph + fixed twin")


@pytest.mark.parametrize("code", sorted(CORPUS))
def test_minimal_failing_graph(code, tmp_path):
    bad, _good = CORPUS[code]
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")   # WF207's construction warning
        report = validate(bad(tmp_path))
    assert code in report.codes(), (
        f"{code} not reported; got: {report.render()}")
    from windflow_tpu.check.diagnostics import CATALOG
    for d in report:
        if d.code == code:
            assert d.severity == CATALOG[code][0]


@pytest.mark.parametrize("code", sorted(CORPUS))
def test_minimally_fixed_twin(code, tmp_path):
    _bad, good = CORPUS[code]
    report = validate(good(tmp_path))
    assert len(report) == 0, (
        f"fixed twin for {code} still reports: {report.render()}")


# ---------------------------------------------------------- knob tests

def test_check_error_raises_before_threads():
    """Acceptance (ISSUE 11): an error diagnostic (recovery= x
    max_delay_ms device core) under check='error' raises BEFORE any
    thread starts, naming the WF id and the node's canonical
    node_stats_name."""
    df = _async_df(0.005)
    df.check = "error"
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        with pytest.raises(CheckError) as ei:
            df.run()
    msg = str(ei.value)
    assert "WF202" in msg
    from windflow_tpu.utils.tracing import node_stats_name
    assert node_stats_name("dev", 0, "agg.0") in msg
    assert df._threads == []          # nothing started
    assert ei.value.report.has_errors


def test_check_native_stale_so_is_warning():
    """ISSUE 17: the retired WF201 error is now the WF215 warning — a
    native core on a pre-ABI .so under recovery= warns (default paths
    run; the first snapshot declines loudly at the barrier) instead of
    blocking run, and a state-ABI library reports nothing."""
    report = validate(_native_df())
    [d] = [d for d in report if d.code == "WF215"]
    assert d.severity == "warning"
    assert not report.has_errors      # check='error' no longer blocks
    from windflow_tpu.utils.tracing import node_stats_name
    assert d.node == node_stats_name("nat", 0, "agg.0")
    from windflow_tpu.check.diagnostics import CATALOG
    assert "WF201" not in CATALOG     # retired, never reused


def test_check_warn_reports_and_still_runs():
    pipe = _pipe(WinSeq(_red, 4, 8, WinType.CB,
                        result_fields=_win_fields()),
                 name="warnrun", check="warn")
    with pytest.warns(CheckWarning, match="WF102"):
        pipe.run_and_wait_end()


def test_check_mode_validated():
    with pytest.raises(ValueError, match="check="):
        Dataflow("bad", check="loud")


def test_check_events_mirrored(tmp_path):
    """check diagnostics land in the event log (kind 'check') when the
    graph is observed."""
    pipe = _pipe(WinSeq(_red, 4, 8, WinType.CB,
                        result_fields=_win_fields()),
                 name="evt", check="warn", metrics=True,
                 trace_dir=str(tmp_path))
    with pytest.warns(CheckWarning):
        pipe.run_and_wait_end()
    kinds = [e for e in pipe.events.recent if e["event"] == "check"]
    assert kinds and kinds[0]["code"] == "WF102"
    assert kinds[0]["severity"] == "warning"


def test_union_takes_strictest_check_mode():
    def mk(name, check):
        p = MultiPipe(name, check=check)
        p.add_source(Source(_src, SCHEMA))
        return p
    u = union_multipipes(mk("a", "warn"), mk("b", "error"))
    assert u.check == "error"
    u2 = union_multipipes(mk("c", "off"), mk("d", None))
    assert u2.check == "off"
    u3 = union_multipipes(mk("e", None), mk("f", None))
    assert u3.check is None
    with pytest.raises(ValueError, match="check="):
        MultiPipe("typo", check="eror")   # eager, not deferred to run()


def test_union_branch_trace_dir_no_false_wf207(tmp_path):
    """A union where one branch supplies metrics and the OTHER the
    trace_dir writes telemetry — no WF207 on the merged graph."""
    a = MultiPipe("a", metrics=True)
    a.add_source(Source(_src, SCHEMA))
    b = MultiPipe("b", trace_dir=str(tmp_path))
    b.add_source(Source(_src, SCHEMA))
    u = union_multipipes(a, b)
    u.add_sink(_sink())
    report = validate(u)
    assert "WF207" not in report.codes(), report.render()


def test_check_unset_never_imports_package():
    """Seed contract: check= unset => the check package is never
    imported (subprocess keeps sys.modules clean)."""
    code = textwrap.dedent("""
        import sys
        import numpy as np
        from windflow_tpu.api import MultiPipe
        from windflow_tpu.core.tuples import Schema
        from windflow_tpu.patterns.basic import Sink, Source
        S = Schema(value=np.int64)
        def gen(sh):
            sh.push(key=0, id=0, ts=0, value=1)
        got = []
        p = (MultiPipe("seed")
             .add_source(Source(gen, S))
             .chain_sink(Sink(lambda b: got.append(b), vectorized=True)))
        p.run_and_wait_end()
        assert any(b is not None and len(b) for b in got)
        bad = [m for m in sys.modules if m.startswith("windflow_tpu.check")]
        assert not bad, f"check package imported on seed path: {bad}"
    """)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    r = subprocess.run([sys.executable, "-c", code], cwd=REPO, env=env,
                       capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, r.stdout + r.stderr


def test_wf207_one_shot_engine_warning():
    """Satellite (ISSUE 11): metrics with no resolvable trace_dir warns
    at construction, naming the missing knob."""
    with pytest.warns(UserWarning, match=r"WF207.*trace_dir"):
        Dataflow("noop", metrics=True)


def test_wireconfig_validate_raises():
    with pytest.raises(ValueError, match="WF205"):
        WireConfig(heartbeat=5.0, stall_timeout=2.0).validate()
    WireConfig.hardened().validate()     # clean config chains through


def test_open_row_plane_rejects_bad_wire():
    from windflow_tpu.parallel.multihost import open_row_plane
    with pytest.raises(ValueError, match="WF205"):
        open_row_plane(0, {0: ("127.0.0.1", 1), 1: ("127.0.0.1", 2)},
                       wire=WireConfig(heartbeat=9.0, stall_timeout=1.0))


# ------------------------------------------------- suppression directives

def _validate_tmp_module(tmp_path, body, name):
    mod = tmp_path / f"{name}.py"
    mod.write_text(body)
    import importlib.util
    spec = importlib.util.spec_from_file_location(name, str(mod))
    m = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(m)
    return validate(m.build())


_SUPPRESSED_SRC = """
import numpy as np
from windflow_tpu.api import MultiPipe
from windflow_tpu.core.tuples import Schema
from windflow_tpu.core.windows import WinType
from windflow_tpu.patterns.basic import Map, Sink, Source
from windflow_tpu.patterns.win_seq import WinSeq

S = Schema(value=np.int64)
RF = {{"value": np.int64}}


def red(k, g, r):
    return {{"value": r["value"].sum()}}


def build():
    counts = [0]

    def bump(b):
        counts[0] += len(b){mark301}

    win = WinSeq(red, 4, 8, WinType.CB, result_fields=RF){mark102}
    return (MultiPipe("sup")
            .add_source(Source(lambda sh: None, S))
            .add(Map(bump, parallelism=2, vectorized=True))
            .add(win)
            .chain_sink(Sink(lambda b: None, vectorized=True)))
"""


def test_suppression_directives(tmp_path):
    noisy = _validate_tmp_module(
        tmp_path, _SUPPRESSED_SRC.format(mark301="", mark102=""),
        "wfmod_noisy")
    assert {"WF301", "WF102"} <= noisy.codes()

    quiet = _validate_tmp_module(
        tmp_path, _SUPPRESSED_SRC.format(
            mark301="   # wf-lint: disable=WF301",
            mark102="   # wf-lint: disable=WF102"),
        "wfmod_quiet")
    assert quiet.codes() == set()
    assert {d.code for d in quiet.suppressed} >= {"WF102"}


def test_directive_parser():
    from windflow_tpu.check.directives import parse_directive
    assert parse_directive("x = 1  # wf-lint: disable=WF102") == {"WF102"}
    assert parse_directive("# wf-lint: disable=wf102, WF301") == \
        {"WF102", "WF301"}
    assert parse_directive("# wf-lint: disable") == {"all"}
    assert parse_directive("# wf-lint:disable=WF102") == {"WF102"}
    assert parse_directive("plain line") is None
    # a typo'd id suppresses NOTHING — it must never widen to "all"
    assert parse_directive("# wf-lint: disable=nonsense") == set()
    assert parse_directive("# wf-lint: disable=WF30l") == set()


# ------------------------------------------------- effect analyzer (WF30x)

def _stamp_helper():
    return time.time()


def test_effects_seeded_generator_exempt():
    """A fn that captures a seeded Generator is trusted for WF303 —
    seeded-generator state rides the snapshot, the blessed pattern."""
    from windflow_tpu.check.effects import analyze_effects

    def bad(b):
        np.random.shuffle(b)

    def good(b, _rng=np.random.default_rng(7)):
        np.random.shuffle(b)

    assert any(d.code == "WF303"
               for d in analyze_effects(bad, {"WF303"}, "kf"))
    assert analyze_effects(good, {"WF303"}, "kf") == []


def test_effects_helper_following():
    """One level of same-module call following: a helper defined next
    to the user fn is scanned too, reported 'via helper'."""
    from windflow_tpu.check.effects import analyze_effects

    def fn(b):
        return _stamp_helper()

    ds = analyze_effects(fn, {"WF303"}, "m")
    assert ds and ds[0].code == "WF303"
    assert "via helper" in ds[0].message
    assert "_stamp_helper" in ds[0].message


def test_effects_blocking_acquire_untimed_only():
    """WF305's name heuristic: an untimed .acquire() flags, a timed one
    (bounded wait) does not."""
    from windflow_tpu.check.effects import analyze_effects
    lk = threading.Lock()

    def bad(b):
        lk.acquire()
        lk.release()

    def good(b):
        if lk.acquire(timeout=0.1):
            lk.release()

    assert any(d.code == "WF305"
               for d in analyze_effects(bad, {"WF305"}, "svc"))
    assert analyze_effects(good, {"WF305"}, "svc") == []


def test_effects_gating_by_contract(tmp_path):
    """A blocking fn under a depth-triggered Rescale (no up_q95_us/
    up_slo_burn) must NOT arm WF305 — the rule does not watch latency."""
    report = validate(_latency_pipe(tmp_path, blocking=True,
                                    latency=False))
    assert "WF305" not in report.codes(), report.render()


def test_effects_suppression_directive(tmp_path):
    """# wf-lint: disable=WF303 on the call line suppresses, same as
    the closure analyzer's directives."""
    from windflow_tpu.check.effects import analyze_effects
    mod = tmp_path / "eff_sup.py"
    mod.write_text(textwrap.dedent("""
        import time

        def noisy(b):
            return time.time()

        def quiet(b):
            return time.time()   # wf-lint: disable=WF303
    """))
    import importlib.util
    spec = importlib.util.spec_from_file_location("eff_sup", str(mod))
    m = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(m)
    assert any(d.code == "WF303"
               for d in analyze_effects(m.noisy, {"WF303"}, "n"))
    assert analyze_effects(m.quiet, {"WF303"}, "n") == []


# ------------------------------------------------------------- self-lint

APP_MODULES = ("windflow_tpu.apps.micro", "windflow_tpu.apps.pipe",
               "windflow_tpu.apps.spatial", "windflow_tpu.apps.ysb")


@pytest.mark.parametrize("modname", APP_MODULES)
def test_bench_apps_self_lint(modname):
    """Tier-1 gate (ISSUE 11): the four bundled bench apps validate
    diagnostic-free through their wf_check_pipelines() hooks."""
    import importlib
    mod = importlib.import_module(modname)
    targets = mod.wf_check_pipelines()
    assert targets
    for target in targets:
        report = validate(target)
        assert len(report) == 0, (
            f"{modname}: {report.render()}")


SOAK_SCRIPTS = ("soak_overload.py", "soak_crash.py", "soak_rescale.py",
                "soak_wire.py", "soak_handoff.py", "wf_roll.py")


def _load_script(fname):
    import importlib.util
    path = os.path.join(REPO, "scripts", fname)
    name = os.path.splitext(fname)[0]
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.mark.parametrize("fname", SOAK_SCRIPTS)
def test_soak_scripts_self_lint(fname):
    """Tier-1 gate (ISSUE 20): the soak/roll scripts validate
    diagnostic-free through their wf_check_pipelines() hooks — incl.
    the new WF30x effect analysis over their recovery-opted sinks and
    the WF22x plane lint of soak_handoff's declared topology."""
    mod = _load_script(fname)
    targets = mod.wf_check_pipelines()
    assert targets
    for target in targets:
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            report = validate(target)
        assert len(report) == 0, f"{fname}: {report.render()}"


# ------------------------------------------------------------ wf-lint CLI

def _run_lint(args):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("WF_LOG_DIR", None)
    env.pop("WF_SAMPLE_PERIOD", None)
    return subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "wf_lint.py"),
         *args],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=300)


def _load_corpus():
    import importlib.util
    path = os.path.join(REPO, "tests", "check_corpus.py")
    spec = importlib.util.spec_from_file_location("check_corpus", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_wf_lint_cli_corpus():
    """The CLI reports every planted diagnostic of the seeded misconfig
    corpus and (under --error) exits nonzero."""
    r = _run_lint(["tests/check_corpus.py", "--error"])
    assert r.returncode == 1, r.stdout + r.stderr
    corpus = _load_corpus()
    for code in corpus.PLANTED:
        assert code in r.stdout, (
            f"{code} missing from CLI output:\n{r.stdout}\n{r.stderr}")


@pytest.mark.slow
def test_wf_lint_cli_apps_clean():
    """All four bench apps lint clean through the CLI (exit 0 even with
    --error).  Slow-marked: the subprocess cold-imports jax + the apps;
    the in-process self-lint above is the tier-1 gate."""
    r = _run_lint(["--error", *APP_MODULES])
    assert r.returncode == 0, r.stdout + r.stderr
    assert "0 diagnostic(s)" in r.stdout


def test_wf_lint_cli_plane_corpus():
    """Acceptance (ISSUE 20): --plane over the seeded misconfigured
    2-host spec reports the full planted WF22x + cross-host set; the
    minimally-fixed twin reports zero."""
    r = _run_lint(["--plane", "tests/plane_corpus.py", "--error"])
    assert r.returncode == 1, r.stdout + r.stderr
    import importlib.util
    path = os.path.join(REPO, "tests", "plane_corpus.py")
    spec = importlib.util.spec_from_file_location("plane_corpus", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    for code in mod.PLANTED:
        assert code in r.stdout, (
            f"{code} missing from --plane output:\n{r.stdout}\n{r.stderr}")

    r2 = _run_lint(["--plane", "tests/plane_corpus_fixed.py", "--error"])
    assert r2.returncode == 0, r2.stdout + r2.stderr
    assert "0 diagnostic(s)" in r2.stdout


def test_wf_lint_cli_json():
    """--json emits one machine-readable document: every planted id of
    the misconfig corpus as {id, severity, module, target, message}
    records plus the target count."""
    r = _run_lint(["tests/check_corpus.py", "--json"])
    assert r.returncode == 0, r.stdout + r.stderr
    doc = json.loads(r.stdout)
    assert doc["targets"] > 0
    recs = doc["diagnostics"]
    corpus = _load_corpus()
    assert set(corpus.PLANTED) <= {d["id"] for d in recs}
    for d in recs:
        assert {"id", "severity", "module", "target", "message"} <= set(d)
    anchored = [d for d in recs if "file" in d]
    assert anchored and all(isinstance(d["line"], int) for d in anchored)


def test_wf_lint_cli_module_scan_fallback(tmp_path):
    """A manual-graph script with NO wf_check_pipelines() hook is still
    lintable: module-level Dataflow objects are picked up by the
    fallback scan (here a round-robin emitter over keyed state ->
    WF101)."""
    mod = tmp_path / "manual_graph.py"
    mod.write_text(textwrap.dedent("""
        import numpy as np
        from windflow_tpu.core.tuples import Schema
        from windflow_tpu.patterns.basic import _AccumulatorNode
        from windflow_tpu.runtime.emitters import StandardEmitter
        from windflow_tpu.runtime.engine import Dataflow

        S = Schema(value=np.int64)
        DF = Dataflow("manual")
        _em = DF.add(StandardEmitter(2, None, name="em"))
        _a = DF.add(_AccumulatorNode(lambda row, acc: None, None, S,
                                     "acc.0", rich=False))
        _b = DF.add(_AccumulatorNode(lambda row, acc: None, None, S,
                                     "acc.1", rich=False))
        DF.connect(_em, _a)
        DF.connect(_em, _b)
    """))
    r = _run_lint([str(mod), "--error"])
    assert r.returncode == 1, r.stdout + r.stderr
    assert "WF101" in r.stdout


def test_wf_lint_cli_exit2_contract():
    """Usage/import failures exit 2, distinct from 'findings' (1) and
    'clean' (0) — the documented scriptable contract."""
    r = _run_lint([])
    assert r.returncode == 2
    r = _run_lint(["tests/no_such_module_xyz.py"])
    assert r.returncode == 2
    # a module with no lintable targets is a usage error too
    r = _run_lint(["tests/oracle.py"])
    assert r.returncode == 2
