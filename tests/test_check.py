"""Static graph validation (windflow_tpu/check/, docs/CHECKS.md):

* a parametrized corpus where every WF### id has a minimal failing
  graph AND a minimally-fixed twin that must validate clean;
* the ``check=`` knob contract: unset never imports the package,
  'warn' reports CheckWarnings and still runs, 'error' raises
  CheckError before any thread starts (WF id + node_stats_name in the
  message), union merges by strictness;
* suppression directives (``# wf-lint: disable=WF###``) and the
  closure analyzer's lock heuristic;
* the tier-1 self-lint: the four bench apps validate diagnostic-free;
* the ``scripts/wf_lint.py`` CLI over the seeded misconfig corpus
  (tests/check_corpus.py) and over the bench apps.
"""

import os
import subprocess
import sys
import textwrap
import threading
import warnings

import numpy as np
import pytest

from windflow_tpu.api import MultiPipe, union_multipipes
from windflow_tpu.check import CheckError, CheckWarning, validate
from windflow_tpu.control import Admission, ControlPolicy, Rescale
from windflow_tpu.ops.functions import Reducer
from windflow_tpu.patterns.key_farm import KeyFarm
from windflow_tpu.core.tuples import Schema
from windflow_tpu.core.windows import WindowSpec, WinType
from windflow_tpu.parallel.channel import WireConfig
from windflow_tpu.parallel.plane import PlanePolicy
from windflow_tpu.patterns.basic import (Map, Sink, Source,
                                         _AccumulatorNode)
from windflow_tpu.patterns.pane_farm import PaneFarm
from windflow_tpu.patterns.win_seq import WinSeq, WinSeqNode
from windflow_tpu.recovery.policy import RecoveryPolicy
from windflow_tpu.runtime.emitters import StandardEmitter, default_routing
from windflow_tpu.runtime.engine import Dataflow
from windflow_tpu.runtime.overload import OverloadPolicy

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SCHEMA = Schema(value=np.int64)


@pytest.fixture(autouse=True)
def _no_ambient_obs_env(monkeypatch):
    """The corpus pins exact diagnostic sets: an ambient WF_LOG_DIR
    would silence WF207, an ambient WF_SAMPLE_PERIOD would plant it
    everywhere."""
    monkeypatch.delenv("WF_LOG_DIR", raising=False)
    monkeypatch.delenv("WF_SAMPLE_PERIOD", raising=False)


def _src(shipper):
    return None


def _red(key, gwid, rows):
    return {"value": rows["value"].sum()}


def _win_fields():
    return {"value": np.int64}


def _sink():
    return Sink(lambda b: None, vectorized=True)


def _pipe(*patterns, **kw):
    p = MultiPipe(kw.pop("name", "chk"), **kw)
    p.add_source(Source(_src, SCHEMA))
    for pat in patterns:
        p.add(pat)
    p.add_sink(_sink())
    return p


# ------------------------------------------------------- stub cores

class NativeResidentCore:
    """Stub matching the WF215 duck-type probe (class name + missing
    has_state_abi), so the corpus runs with or without the native .so.
    The real core sets ``has_state_abi`` from the loaded library; the
    stub's default (absent → False) models a pre-ABI .so."""
    spec = WindowSpec(4, 2, WinType.CB)

    def __init__(self, abi=False):
        if abi:
            self.has_state_abi = True


class _StubAsyncCore:
    """Async device core shape: process_batches + max_delay_s."""
    spec = WindowSpec(4, 2, WinType.CB)
    max_delay_s = None

    def process_batches(self, batch):
        return []


def _acc_node(name):
    return _AccumulatorNode(lambda row, acc: None, None, SCHEMA, name,
                            rich=False)


def _routing_df(routing):
    df = Dataflow("route")
    em = df.add(StandardEmitter(2, routing, name="em"))
    a = df.add(_acc_node("acc.0"))
    b = df.add(_acc_node("acc.1"))
    df.connect(em, a)
    df.connect(em, b)
    return df


def _native_df(abi=False):
    df = Dataflow("nat", recovery=RecoveryPolicy())
    df.add(WinSeqNode(NativeResidentCore(abi=abi), name="agg.0"))
    return df


def _async_df(max_delay):
    core = _StubAsyncCore()
    core.max_delay_s = max_delay
    df = Dataflow("dev", recovery=RecoveryPolicy())
    df.add(WinSeqNode(core, name="agg.0"))
    return df


def _comb_df(async_first):
    from windflow_tpu.runtime.comb import make_comb
    from windflow_tpu.patterns.basic import _MapNode
    win = WinSeqNode(_StubAsyncCore(), name="agg.0")
    mp = _MapNode(lambda b: None, "map.0", False, True, None)
    stages = [win, mp] if async_first else [mp, win]
    df = Dataflow("comb", recovery=RecoveryPolicy())
    df.add(make_comb(stages, name="chain.0"))
    return df


def _recovery_sink_pipe(opt_in):
    s = _sink()
    if opt_in:
        s.recoverable = True
    p = MultiPipe("recsink", recovery=RecoveryPolicy())
    p.add_source(Source(_src, SCHEMA))
    p.add_sink(s)
    return p


def _race_pipe(guarded):
    counts = [0]
    lock = threading.Lock()

    if guarded:
        def bump(batch):
            with lock:
                counts[0] += len(batch)
    else:
        def bump(batch):
            counts[0] += len(batch)

    return _pipe(Map(bump, parallelism=2, vectorized=True))


def _ctl_pipe(t, *, rescale=True, recovery=True, obs=True,
              recoverable=None, target="kf"):
    """Control-plane corpus factory (WF209-212): a keyed farm under a
    ControlPolicy, with the blinding / recoverable / recovery / target
    knobs toggled per case.  The sink opts into restart so recovery=
    twins stay WF204-clean."""
    if rescale:
        rules = [Rescale(target, max_workers=4)]
    else:
        rules = [Admission(max_rate=1e6, min_rate=1e3, high_depth=8,
                           low_depth=2)]
    kf = KeyFarm(Reducer("sum", "value"), win_len=8, slide_len=4,
                 pardegree=2, name="kf")
    if recoverable is not None:
        kf.recoverable = recoverable
    s = _sink()
    s.recoverable = True
    p = MultiPipe("ctl", control=ControlPolicy(rules),
                  recovery=RecoveryPolicy() if recovery else None,
                  metrics=True if obs else None,
                  trace_dir=str(t) if obs else None)
    p.add_source(Source(_src, SCHEMA))
    p.add(kf)
    p.add_sink(s)
    return p


def _trace_pipe(trace_dir):
    from windflow_tpu.obs.trace import TracePolicy
    return _pipe(name="tr", trace=TracePolicy(sample_rate=0.5),
                 trace_dir=trace_dir)


def _fed_pipe(t, obs=False):
    from windflow_tpu.obs.federation import FederationPolicy
    kw = dict(metrics=True, trace_dir=str(t)) if obs else {}
    return _pipe(name="fed", federate=FederationPolicy(host="chk"), **kw)


_G = 0


def _global_pipe(bad):
    if bad:
        def fn(batch):
            global _G
            _G += 1
    else:
        def fn(batch):
            return None
    return _pipe(Map(fn, parallelism=2, vectorized=True))


#: WF### -> (bad_factory, good_factory); factories take tmp_path.
#: Every bad graph must report exactly its id (subset check: the id is
#: present); every good twin must validate with ZERO diagnostics.
CORPUS = {
    "WF101": (lambda t: _routing_df(None),
              lambda t: _routing_df(default_routing)),
    "WF102": (lambda t: _pipe(WinSeq(_red, 4, 8, WinType.CB,
                                     result_fields=_win_fields())),
              lambda t: _pipe(WinSeq(_red, 8, 4, WinType.CB,
                                     result_fields=_win_fields()))),
    "WF103": (lambda t: _pipe(PaneFarm(_red, _red, 10, 3, WinType.CB,
                                       plq_result_fields=_win_fields(),
                                       wlq_result_fields=_win_fields())),
              lambda t: _pipe(PaneFarm(_red, _red, 10, 5, WinType.CB,
                                       plq_result_fields=_win_fields(),
                                       wlq_result_fields=_win_fields()))),
    "WF202": (lambda t: _async_df(0.005), lambda t: _async_df(None)),
    "WF203": (lambda t: _comb_df(async_first=True),
              lambda t: _comb_df(async_first=False)),
    "WF204": (lambda t: _recovery_sink_pipe(False),
              lambda t: _recovery_sink_pipe(True)),
    "WF205": (lambda t: WireConfig(heartbeat=5.0, stall_timeout=2.0),
              lambda t: WireConfig.hardened()),
    "WF206": (lambda t: WireConfig(heartbeat=2.0),
              lambda t: WireConfig(heartbeat=2.0, stall_timeout=10.0)),
    "WF207": (lambda t: _pipe(name="obs", metrics=True),
              lambda t: _pipe(name="obs", metrics=True,
                              trace_dir=str(t))),
    "WF208": (lambda t: _pipe(name="ovl", capacity=0,
                              overload=OverloadPolicy(shed="shed_newest")),
              lambda t: _pipe(name="ovl", capacity=16,
                              overload=OverloadPolicy(shed="shed_newest"))),
    "WF209": (lambda t: _ctl_pipe(t, rescale=False, recovery=False,
                                  obs=False),
              lambda t: _ctl_pipe(t, rescale=False, recovery=False)),
    "WF210": (lambda t: _ctl_pipe(t, recoverable=False),
              lambda t: _ctl_pipe(t)),
    "WF211": (lambda t: _ctl_pipe(t, recovery=False),
              lambda t: _ctl_pipe(t)),
    "WF212": (lambda t: _ctl_pipe(t, target="kfarm"),
              lambda t: _ctl_pipe(t)),
    "WF213": (lambda t: _trace_pipe(None),
              lambda t: _trace_pipe(str(t))),
    "WF214": (lambda t: WireConfig(resume=True),
              lambda t: WireConfig(resume=True, recovery=True)),
    "WF215": (lambda t: _native_df(), lambda t: _native_df(abi=True)),
    "WF217": (lambda t: _fed_pipe(t),
              lambda t: _fed_pipe(t, obs=True)),
    "WF216": (lambda t: PlanePolicy(wire=WireConfig.hardened()),
              lambda t: PlanePolicy(wire=WireConfig(
                  connect_deadline=60.0, heartbeat=2.0,
                  stall_timeout=10.0, resume=True, recovery=True))),
    "WF301": (lambda t: _race_pipe(guarded=False),
              lambda t: _race_pipe(guarded=True)),
    "WF302": (lambda t: _global_pipe(True),
              lambda t: _global_pipe(False)),
}


def test_corpus_covers_catalog():
    from windflow_tpu.check.diagnostics import CATALOG
    assert set(CORPUS) == set(CATALOG), (
        "every catalog id needs a minimal failing graph + fixed twin")


@pytest.mark.parametrize("code", sorted(CORPUS))
def test_minimal_failing_graph(code, tmp_path):
    bad, _good = CORPUS[code]
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")   # WF207's construction warning
        report = validate(bad(tmp_path))
    assert code in report.codes(), (
        f"{code} not reported; got: {report.render()}")
    from windflow_tpu.check.diagnostics import CATALOG
    for d in report:
        if d.code == code:
            assert d.severity == CATALOG[code][0]


@pytest.mark.parametrize("code", sorted(CORPUS))
def test_minimally_fixed_twin(code, tmp_path):
    _bad, good = CORPUS[code]
    report = validate(good(tmp_path))
    assert len(report) == 0, (
        f"fixed twin for {code} still reports: {report.render()}")


# ---------------------------------------------------------- knob tests

def test_check_error_raises_before_threads():
    """Acceptance (ISSUE 11): an error diagnostic (recovery= x
    max_delay_ms device core) under check='error' raises BEFORE any
    thread starts, naming the WF id and the node's canonical
    node_stats_name."""
    df = _async_df(0.005)
    df.check = "error"
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        with pytest.raises(CheckError) as ei:
            df.run()
    msg = str(ei.value)
    assert "WF202" in msg
    from windflow_tpu.utils.tracing import node_stats_name
    assert node_stats_name("dev", 0, "agg.0") in msg
    assert df._threads == []          # nothing started
    assert ei.value.report.has_errors


def test_check_native_stale_so_is_warning():
    """ISSUE 17: the retired WF201 error is now the WF215 warning — a
    native core on a pre-ABI .so under recovery= warns (default paths
    run; the first snapshot declines loudly at the barrier) instead of
    blocking run, and a state-ABI library reports nothing."""
    report = validate(_native_df())
    [d] = [d for d in report if d.code == "WF215"]
    assert d.severity == "warning"
    assert not report.has_errors      # check='error' no longer blocks
    from windflow_tpu.utils.tracing import node_stats_name
    assert d.node == node_stats_name("nat", 0, "agg.0")
    from windflow_tpu.check.diagnostics import CATALOG
    assert "WF201" not in CATALOG     # retired, never reused


def test_check_warn_reports_and_still_runs():
    pipe = _pipe(WinSeq(_red, 4, 8, WinType.CB,
                        result_fields=_win_fields()),
                 name="warnrun", check="warn")
    with pytest.warns(CheckWarning, match="WF102"):
        pipe.run_and_wait_end()


def test_check_mode_validated():
    with pytest.raises(ValueError, match="check="):
        Dataflow("bad", check="loud")


def test_check_events_mirrored(tmp_path):
    """check diagnostics land in the event log (kind 'check') when the
    graph is observed."""
    pipe = _pipe(WinSeq(_red, 4, 8, WinType.CB,
                        result_fields=_win_fields()),
                 name="evt", check="warn", metrics=True,
                 trace_dir=str(tmp_path))
    with pytest.warns(CheckWarning):
        pipe.run_and_wait_end()
    kinds = [e for e in pipe.events.recent if e["event"] == "check"]
    assert kinds and kinds[0]["code"] == "WF102"
    assert kinds[0]["severity"] == "warning"


def test_union_takes_strictest_check_mode():
    def mk(name, check):
        p = MultiPipe(name, check=check)
        p.add_source(Source(_src, SCHEMA))
        return p
    u = union_multipipes(mk("a", "warn"), mk("b", "error"))
    assert u.check == "error"
    u2 = union_multipipes(mk("c", "off"), mk("d", None))
    assert u2.check == "off"
    u3 = union_multipipes(mk("e", None), mk("f", None))
    assert u3.check is None
    with pytest.raises(ValueError, match="check="):
        MultiPipe("typo", check="eror")   # eager, not deferred to run()


def test_union_branch_trace_dir_no_false_wf207(tmp_path):
    """A union where one branch supplies metrics and the OTHER the
    trace_dir writes telemetry — no WF207 on the merged graph."""
    a = MultiPipe("a", metrics=True)
    a.add_source(Source(_src, SCHEMA))
    b = MultiPipe("b", trace_dir=str(tmp_path))
    b.add_source(Source(_src, SCHEMA))
    u = union_multipipes(a, b)
    u.add_sink(_sink())
    report = validate(u)
    assert "WF207" not in report.codes(), report.render()


def test_check_unset_never_imports_package():
    """Seed contract: check= unset => the check package is never
    imported (subprocess keeps sys.modules clean)."""
    code = textwrap.dedent("""
        import sys
        import numpy as np
        from windflow_tpu.api import MultiPipe
        from windflow_tpu.core.tuples import Schema
        from windflow_tpu.patterns.basic import Sink, Source
        S = Schema(value=np.int64)
        def gen(sh):
            sh.push(key=0, id=0, ts=0, value=1)
        got = []
        p = (MultiPipe("seed")
             .add_source(Source(gen, S))
             .chain_sink(Sink(lambda b: got.append(b), vectorized=True)))
        p.run_and_wait_end()
        assert any(b is not None and len(b) for b in got)
        bad = [m for m in sys.modules if m.startswith("windflow_tpu.check")]
        assert not bad, f"check package imported on seed path: {bad}"
    """)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    r = subprocess.run([sys.executable, "-c", code], cwd=REPO, env=env,
                       capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, r.stdout + r.stderr


def test_wf207_one_shot_engine_warning():
    """Satellite (ISSUE 11): metrics with no resolvable trace_dir warns
    at construction, naming the missing knob."""
    with pytest.warns(UserWarning, match=r"WF207.*trace_dir"):
        Dataflow("noop", metrics=True)


def test_wireconfig_validate_raises():
    with pytest.raises(ValueError, match="WF205"):
        WireConfig(heartbeat=5.0, stall_timeout=2.0).validate()
    WireConfig.hardened().validate()     # clean config chains through


def test_open_row_plane_rejects_bad_wire():
    from windflow_tpu.parallel.multihost import open_row_plane
    with pytest.raises(ValueError, match="WF205"):
        open_row_plane(0, {0: ("127.0.0.1", 1), 1: ("127.0.0.1", 2)},
                       wire=WireConfig(heartbeat=9.0, stall_timeout=1.0))


# ------------------------------------------------- suppression directives

def _validate_tmp_module(tmp_path, body, name):
    mod = tmp_path / f"{name}.py"
    mod.write_text(body)
    import importlib.util
    spec = importlib.util.spec_from_file_location(name, str(mod))
    m = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(m)
    return validate(m.build())


_SUPPRESSED_SRC = """
import numpy as np
from windflow_tpu.api import MultiPipe
from windflow_tpu.core.tuples import Schema
from windflow_tpu.core.windows import WinType
from windflow_tpu.patterns.basic import Map, Sink, Source
from windflow_tpu.patterns.win_seq import WinSeq

S = Schema(value=np.int64)
RF = {{"value": np.int64}}


def red(k, g, r):
    return {{"value": r["value"].sum()}}


def build():
    counts = [0]

    def bump(b):
        counts[0] += len(b){mark301}

    win = WinSeq(red, 4, 8, WinType.CB, result_fields=RF){mark102}
    return (MultiPipe("sup")
            .add_source(Source(lambda sh: None, S))
            .add(Map(bump, parallelism=2, vectorized=True))
            .add(win)
            .chain_sink(Sink(lambda b: None, vectorized=True)))
"""


def test_suppression_directives(tmp_path):
    noisy = _validate_tmp_module(
        tmp_path, _SUPPRESSED_SRC.format(mark301="", mark102=""),
        "wfmod_noisy")
    assert {"WF301", "WF102"} <= noisy.codes()

    quiet = _validate_tmp_module(
        tmp_path, _SUPPRESSED_SRC.format(
            mark301="   # wf-lint: disable=WF301",
            mark102="   # wf-lint: disable=WF102"),
        "wfmod_quiet")
    assert quiet.codes() == set()
    assert {d.code for d in quiet.suppressed} >= {"WF102"}


def test_directive_parser():
    from windflow_tpu.check.directives import parse_directive
    assert parse_directive("x = 1  # wf-lint: disable=WF102") == {"WF102"}
    assert parse_directive("# wf-lint: disable=wf102, WF301") == \
        {"WF102", "WF301"}
    assert parse_directive("# wf-lint: disable") == {"all"}
    assert parse_directive("# wf-lint:disable=WF102") == {"WF102"}
    assert parse_directive("plain line") is None
    # a typo'd id suppresses NOTHING — it must never widen to "all"
    assert parse_directive("# wf-lint: disable=nonsense") == set()
    assert parse_directive("# wf-lint: disable=WF30l") == set()


# ------------------------------------------------------------- self-lint

APP_MODULES = ("windflow_tpu.apps.micro", "windflow_tpu.apps.pipe",
               "windflow_tpu.apps.spatial", "windflow_tpu.apps.ysb")


@pytest.mark.parametrize("modname", APP_MODULES)
def test_bench_apps_self_lint(modname):
    """Tier-1 gate (ISSUE 11): the four bundled bench apps validate
    diagnostic-free through their wf_check_pipelines() hooks."""
    import importlib
    mod = importlib.import_module(modname)
    targets = mod.wf_check_pipelines()
    assert targets
    for target in targets:
        report = validate(target)
        assert len(report) == 0, (
            f"{modname}: {report.render()}")


# ------------------------------------------------------------ wf-lint CLI

def _run_lint(args):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("WF_LOG_DIR", None)
    env.pop("WF_SAMPLE_PERIOD", None)
    return subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "wf_lint.py"),
         *args],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=300)


def _load_corpus():
    import importlib.util
    path = os.path.join(REPO, "tests", "check_corpus.py")
    spec = importlib.util.spec_from_file_location("check_corpus", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_wf_lint_cli_corpus():
    """The CLI reports every planted diagnostic of the seeded misconfig
    corpus and (under --error) exits nonzero."""
    r = _run_lint(["tests/check_corpus.py", "--error"])
    assert r.returncode == 1, r.stdout + r.stderr
    corpus = _load_corpus()
    for code in corpus.PLANTED:
        assert code in r.stdout, (
            f"{code} missing from CLI output:\n{r.stdout}\n{r.stderr}")


@pytest.mark.slow
def test_wf_lint_cli_apps_clean():
    """All four bench apps lint clean through the CLI (exit 0 even with
    --error).  Slow-marked: the subprocess cold-imports jax + the apps;
    the in-process self-lint above is the tier-1 gate."""
    r = _run_lint(["--error", *APP_MODULES])
    assert r.returncode == 0, r.stdout + r.stderr
    assert "0 diagnostic(s)" in r.stdout
