"""Differential tests for Pane_Farm and Win_MapReduce vs Win_Seq — the
equivalent of src/sum_test_cpu test_{pf,wm}_{cb,tb}_{nic,inc}. Results are
compared on (id, value) per key: the reference's own ts bookkeeping differs
across compositions (the test_all harness compares totals only); values and
dense window ids must match exactly."""

import numpy as np
import pytest

from windflow_tpu.core.windows import WinType
from windflow_tpu.ops.functions import Reducer
from windflow_tpu.patterns.pane_farm import PaneFarm
from windflow_tpu.patterns.win_mapreduce import WinMapReduce
from windflow_tpu.patterns.win_seq import WinSeq

from test_farms import cb_stream_batches, tb_stream_batches, run_windowed


def iv(per_key):
    return {k: [(r[0], r[2]) for r in rs] for k, rs in per_key.items()}


CASES_CB = [(8, 4), (12, 3), (10, 5), (9, 3)]
CASES_TB = [(40, 20), (30, 10)]


@pytest.mark.parametrize("win,slide", CASES_CB)
@pytest.mark.parametrize("plq,wlq", [(1, 1), (2, 1), (1, 2), (3, 2)])
@pytest.mark.parametrize("inc", [False, True])
def test_pane_farm_cb(win, slide, plq, wlq, inc):
    keys, n = 3, 120
    ref = run_windowed(
        WinSeq(Reducer("sum"), win, slide, WinType.CB, incremental=inc),
        cb_stream_batches(keys, n))
    got = run_windowed(
        PaneFarm(Reducer("sum"), Reducer("sum"), win, slide, WinType.CB,
                 plq_degree=plq, wlq_degree=wlq, plq_incremental=inc,
                 wlq_incremental=inc),
        cb_stream_batches(keys, n))
    assert iv(got) == iv(ref)


@pytest.mark.parametrize("win,slide", CASES_TB)
@pytest.mark.parametrize("plq,wlq", [(1, 1), (2, 2)])
def test_pane_farm_tb(win, slide, plq, wlq):
    keys, n = 2, 150
    ref = run_windowed(WinSeq(Reducer("sum"), win, slide, WinType.TB),
                       tb_stream_batches(keys, n))
    got = run_windowed(
        PaneFarm(Reducer("sum"), Reducer("sum"), win, slide, WinType.TB,
                 plq_degree=plq, wlq_degree=wlq),
        tb_stream_batches(keys, n))
    assert iv(got) == iv(ref)


def test_pane_farm_rejects_non_sliding():
    with pytest.raises(ValueError, match="sliding"):
        PaneFarm(Reducer("sum"), Reducer("sum"), 5, 5, WinType.CB)


@pytest.mark.parametrize("win,slide", CASES_CB + [(3, 8)])
@pytest.mark.parametrize("map_d,red_d", [(2, 1), (3, 1), (2, 2), (4, 3)])
@pytest.mark.parametrize("inc", [False, True])
def test_win_mapreduce_cb(win, slide, map_d, red_d, inc):
    keys, n = 3, 110
    ref = run_windowed(
        WinSeq(Reducer("sum"), win, slide, WinType.CB, incremental=inc),
        cb_stream_batches(keys, n))
    got = run_windowed(
        WinMapReduce(Reducer("sum"), Reducer("sum"), win, slide, WinType.CB,
                     map_degree=map_d, reduce_degree=red_d,
                     map_incremental=inc, reduce_incremental=inc),
        cb_stream_batches(keys, n))
    assert iv(got) == iv(ref)


@pytest.mark.parametrize("win,slide", CASES_TB + [(10, 25)])
@pytest.mark.parametrize("map_d,red_d", [(2, 1), (3, 2)])
def test_win_mapreduce_tb(win, slide, map_d, red_d):
    keys, n = 2, 140
    ref = run_windowed(WinSeq(Reducer("sum"), win, slide, WinType.TB),
                       tb_stream_batches(keys, n))
    got = run_windowed(
        WinMapReduce(Reducer("sum"), Reducer("sum"), win, slide, WinType.TB,
                     map_degree=map_d, reduce_degree=red_d),
        tb_stream_batches(keys, n))
    assert iv(got) == iv(ref)


def test_win_mapreduce_rejects_serial_map():
    with pytest.raises(ValueError, match="parallel MAP"):
        WinMapReduce(Reducer("sum"), Reducer("sum"), 8, 4, map_degree=1)


def test_all_compositions_equal_totals():
    """The reference's test_all_cb differential harness: Win_Seq first, then
    every composition on the SAME stream must give the same total sum
    (test_all_cb.cpp:171-473)."""
    from windflow_tpu.patterns.key_farm import KeyFarm
    from windflow_tpu.patterns.win_farm import WinFarm

    keys, n, win, slide = 4, 150, 12, 4
    stream = lambda: cb_stream_batches(keys, n)

    def total(per_key):
        return sum(v for rs in per_key.values() for _, _, v in rs)

    ref = total(run_windowed(WinSeq(Reducer("sum"), win, slide, WinType.CB),
                             stream()))
    compositions = [
        WinFarm(Reducer("sum"), win, slide, WinType.CB, pardegree=3),
        KeyFarm(Reducer("sum"), win, slide, WinType.CB, pardegree=3),
        PaneFarm(Reducer("sum"), Reducer("sum"), win, slide, WinType.CB,
                 plq_degree=2, wlq_degree=2),
        WinMapReduce(Reducer("sum"), Reducer("sum"), win, slide, WinType.CB,
                     map_degree=3, reduce_degree=2),
    ]
    for comp in compositions:
        assert total(run_windowed(comp, stream())) == ref, comp
