"""Seeded misconfigured 2-host plane topology for ``scripts/wf_lint.py
--plane`` (ISSUE 20 acceptance): one declared deployment planting the
whole WF22x family plus the cross-host pairings the per-process checks
cannot see (WF205/WF214/WF216 across an edge).

Not a test module itself — ``tests/test_check.py`` drives the CLI over
it and asserts every id in ``PLANTED`` is reported;
``tests/plane_corpus_fixed.py`` is the minimally-fixed twin that must
lint clean.
"""

from windflow_tpu.check.plane import HostSpec, PlaneSpec
from windflow_tpu.parallel.channel import WireConfig
from windflow_tpu.parallel.plane import PlanePolicy

#: WF### ids a ``--plane`` run over this module must report
PLANTED = ("WF205", "WF214", "WF216", "WF220", "WF221", "WF222",
           "WF223", "WF224")

#: host 0's wire: heartbeats at 5s into host 1, journals outbound
_WIRE0 = WireConfig(connect_deadline=30.0, heartbeat=5.0, resume=True,
                    recovery=True)
#: host 1's wire: 2s stall timeout (< host 0's heartbeat -> WF205) and
#: no recovery= (host 0 journals into the void -> WF214)
_WIRE1 = WireConfig(connect_deadline=30.0, stall_timeout=2.0)

_HOSTS = [
    # resume= set here but not on host 1 -> WF222 (both edges); the
    # federated shipper with no aggregator anywhere -> WF224
    HostSpec(0, wire=_WIRE0, sends="<i8", resume=True, federate=True),
    # expects a different row dtype than host 0 ships -> WF221; a
    # PlanePolicy over a wire that never journals -> WF216, and no host
    # offers a ckpt_sink for its takeovers -> WF223
    HostSpec(1, wire=_WIRE1, sends="<i8", expects="<f8",
             plane=PlanePolicy(wire=_WIRE1)),
]

#: pid 2 is in the address book but no HostSpec describes it -> WF220
SPEC = PlaneSpec({0: ("10.0.0.1", 9000), 1: ("10.0.0.2", 9000),
                  2: ("10.0.0.3", 9000)}, _HOSTS, name="plane_corpus")


def wf_plane_spec():
    return [SPEC]
