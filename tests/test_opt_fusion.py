"""LEVEL1/LEVEL2 graph optimisation (runtime/farm.py:fuse_two_stage — the
reference's optimize_PaneFarm / optimize_WinMapReduce, pane_farm.hpp:426-466)
and the multi-emitter Win_Farm path (win_farm.hpp:147-166): differential
against Win_Seq plus node-count assertions showing the graph shrinks."""

import numpy as np
import pytest

from windflow_tpu.api.builders import (LEVEL1, LEVEL2, PaneFarm_Builder,
                                       WinMapReduce_Builder)
from windflow_tpu.core.windows import WinType
from windflow_tpu.ops.functions import Reducer
from windflow_tpu.patterns.basic import Sink, Source
from windflow_tpu.patterns.pane_farm import PaneFarm
from windflow_tpu.patterns.win_farm import WinFarm
from windflow_tpu.patterns.win_mapreduce import WinMapReduce
from windflow_tpu.patterns.win_seq import WinSeq
from windflow_tpu.runtime.engine import Dataflow
from windflow_tpu.runtime.farm import add_farm, build_pipeline

from test_farms import (SCHEMA, cb_stream_batches, run_windowed,
                        tb_stream_batches)

KEYS, N = 3, 140
WIN, SLIDE = 12, 4


def stream(wt):
    return (cb_stream_batches(KEYS, N) if wt is WinType.CB
            else tb_stream_batches(KEYS, N))


def totals(per_key):
    return sum(v for rs in per_key.values() for _, _, v in rs)


def graph_node_count(pattern, batches):
    df = Dataflow()
    build_pipeline(df, [Source(batches=iter(batches), schema=SCHEMA),
                        pattern, Sink(lambda r: None)])
    return len(df.nodes)


# ------------------------------------------------------------ differential

@pytest.mark.parametrize("wt", [WinType.CB, WinType.TB], ids=["cb", "tb"])
@pytest.mark.parametrize("level", [LEVEL1, LEVEL2])
@pytest.mark.parametrize("inc", [False, True], ids=["nic", "inc"])
def test_pane_farm_opt_matches_seq(wt, level, inc):
    ref = totals(run_windowed(
        WinSeq(Reducer("sum"), WIN, SLIDE, wt, incremental=inc), stream(wt)))
    for degs in ((1, 1), (3, 1), (1, 3), (2, 3)):
        pf = PaneFarm(Reducer("sum"), Reducer("sum"), WIN, SLIDE, wt,
                      plq_degree=degs[0], wlq_degree=degs[1],
                      plq_incremental=inc, wlq_incremental=inc,
                      opt_level=level)
        got = run_windowed(pf, stream(wt))
        assert totals(got) == ref, f"degs={degs}"


@pytest.mark.parametrize("wt", [WinType.CB, WinType.TB], ids=["cb", "tb"])
@pytest.mark.parametrize("level", [LEVEL1, LEVEL2])
def test_wmr_opt_matches_seq(wt, level):
    ref = totals(run_windowed(
        WinSeq(Reducer("sum"), WIN, SLIDE, wt), stream(wt)))
    for map_deg, red_deg in ((2, 1), (3, 2)):
        wmr = WinMapReduce(Reducer("sum"), Reducer("sum"), WIN, SLIDE, wt,
                           map_degree=map_deg, reduce_degree=red_deg,
                           opt_level=level)
        got = run_windowed(wmr, stream(wt))
        assert totals(got) == ref, f"degs={(map_deg, red_deg)}"


def test_opt_results_in_order():
    """LEVEL2's OrderingCore merge must preserve per-key result order."""
    pf = PaneFarm(Reducer("sum"), Reducer("sum"), WIN, SLIDE, WinType.CB,
                  plq_degree=3, wlq_degree=2, opt_level=LEVEL2)
    got = run_windowed(pf, stream(WinType.CB))
    for key, rs in got.items():
        ids = [i for i, _, _ in rs]
        assert ids == sorted(ids), f"key {key} out of order"


# ------------------------------------------------------------- node counts

def test_opt_levels_shrink_graph():
    def pf(level):
        return PaneFarm(Reducer("sum"), Reducer("sum"), WIN, SLIDE,
                        WinType.CB, plq_degree=3, wlq_degree=2,
                        opt_level=level)
    n0 = graph_node_count(pf(0), stream(WinType.CB))
    n1 = graph_node_count(pf(LEVEL1), stream(WinType.CB))
    n2 = graph_node_count(pf(LEVEL2), stream(WinType.CB))
    # LEVEL1 fuses plq-collector + wlq-emitter (2 threads -> 1);
    # LEVEL2 removes the boundary entirely (emitter clones ride the plq
    # worker threads)
    assert n1 == n0 - 1
    assert n2 <= n0 - 2


def test_opt_level1_seq_seq_single_thread():
    def pf(level):
        return PaneFarm(Reducer("sum"), Reducer("sum"), WIN, SLIDE,
                        WinType.CB, plq_degree=1, wlq_degree=1,
                        opt_level=level)
    n0 = graph_node_count(pf(0), stream(WinType.CB))
    n1 = graph_node_count(pf(LEVEL1), stream(WinType.CB))
    assert n1 == n0 - 1  # the two sequential cores share one thread


def test_builder_withopt_drives_fusion():
    pf = (PaneFarm_Builder(Reducer("sum"), Reducer("sum"))
          .withCBWindow(WIN, SLIDE).withParallelism(2, 2)
          .withOpt(LEVEL2).build())
    assert pf.opt_level == LEVEL2
    wmr = (WinMapReduce_Builder(Reducer("sum"), Reducer("sum"))
           .withCBWindow(WIN, SLIDE).withParallelism(2, 1)
           .withOpt(LEVEL1).build())
    assert wmr.opt_level == LEVEL1
    ref = totals(run_windowed(
        WinSeq(Reducer("sum"), WIN, SLIDE, WinType.CB), stream(WinType.CB)))
    assert totals(run_windowed(pf, stream(WinType.CB))) == ref
    assert totals(run_windowed(wmr, stream(WinType.CB))) == ref


# ------------------------------------------------------- multi-emitter WF

def split_stream(batches, n):
    """Partition a batch stream row-round-robin into n in-order substreams
    (the reference's multi-emitter mode feeds one emitter per upstream
    pipeline, win_farm.hpp:147-166)."""
    outs = [[] for _ in range(n)]
    for b in batches:
        for i in range(n):
            part = b[i::n]
            if len(part):
                outs[i].append(part)
    return outs


@pytest.mark.parametrize("wt", [WinType.CB, WinType.TB], ids=["cb", "tb"])
@pytest.mark.parametrize("pardegree", [2, 3])
def test_multi_emitter_win_farm_matches_seq(wt, pardegree):
    ref = run_windowed(WinSeq(Reducer("sum"), WIN, SLIDE, wt), stream(wt))
    parts = split_stream(stream(wt), 2)

    per_key = {}

    def snk(row):
        if row is not None:
            per_key.setdefault(int(row["key"]), []).append(
                (int(row["id"]), int(row["ts"]), int(row["value"])))

    df = Dataflow()
    sources = []
    for i in range(2):
        s = Source(batches=iter(parts[i]), schema=SCHEMA,
                   name=f"src{i}")._make_replica(0)
        df.add(s)
        sources.append(s)
    wf = WinFarm(Reducer("sum"), WIN, SLIDE, wt, pardegree=pardegree,
                 n_emitters=2)
    tails = add_farm(df, wf, sources)
    snk_node = Sink(snk)._make_replica(0)
    df.add(snk_node)
    for t in tails:
        df.connect(t, snk_node)
    df.run_and_wait_end()

    assert per_key.keys() == ref.keys()
    for k in ref:
        assert per_key[k] == ref[k], f"key {k} mismatch"


def test_multi_emitter_wrong_upstream_count_raises():
    df = Dataflow()
    s = Source(batches=iter(stream(WinType.CB)),
               schema=SCHEMA)._make_replica(0)
    df.add(s)
    wf = WinFarm(Reducer("sum"), WIN, SLIDE, WinType.CB, pardegree=2,
                 n_emitters=2)
    with pytest.raises(ValueError, match="n_emitters"):
        add_farm(df, wf, [s])


def test_opt_level_survives_nesting_clone():
    """clone_with must propagate opt_level so nested replicas keep the
    requested fusion (and stay differentially correct)."""
    from windflow_tpu.patterns.nesting import KeyFarmOf, WinFarmOf
    ref = totals(run_windowed(
        WinSeq(Reducer("sum"), WIN, SLIDE, WinType.CB), stream(WinType.CB)))
    for level in (LEVEL1, LEVEL2):
        pf = PaneFarm(Reducer("sum"), Reducer("sum"), WIN, SLIDE,
                      WinType.CB, plq_degree=2, wlq_degree=2,
                      opt_level=level)
        clone = pf.clone_with("n", slide_len=SLIDE * 2)
        assert clone.opt_level == level
        for nested in (KeyFarmOf(PaneFarm(
                Reducer("sum"), Reducer("sum"), WIN, SLIDE, WinType.CB,
                plq_degree=2, wlq_degree=2, opt_level=level), pardegree=2),):
            assert totals(run_windowed(nested, stream(WinType.CB))) == ref


# ------------------------------------------- TPU two-stage patterns (r3)

@pytest.mark.filterwarnings("ignore:resident device path accumulates")
@pytest.mark.parametrize("wt", [WinType.CB, WinType.TB], ids=["cb", "tb"])
@pytest.mark.parametrize("level", [LEVEL1, LEVEL2])
def test_pane_farm_tpu_opt_matches_seq(wt, level):
    """VERDICT r2 item 6: LEVEL1/LEVEL2 fusion over device-core PaneFarm
    stages (optimize_PaneFarmGPU, pane_farm_gpu.hpp:488-529) — the LEVEL2
    path mutates stage2.n_emitters and fronts workers with OrderingCores,
    which must compose with device-batched workers."""
    from windflow_tpu.patterns.win_seq_tpu import PaneFarmTPU
    ref = totals(run_windowed(
        WinSeq(Reducer("sum"), WIN, SLIDE, wt), stream(wt)))
    for degs in ((1, 1), (3, 1), (2, 3)):
        pf = PaneFarmTPU(Reducer("sum"), Reducer("sum"), WIN, SLIDE, wt,
                         plq_degree=degs[0], wlq_degree=degs[1],
                         batch_len=16, flush_rows=128, opt_level=level)
        got = run_windowed(pf, stream(wt))
        assert totals(got) == ref, f"degs={degs}"


@pytest.mark.filterwarnings("ignore:resident device path accumulates")
@pytest.mark.parametrize("wt", [WinType.CB, WinType.TB], ids=["cb", "tb"])
@pytest.mark.parametrize("level", [LEVEL1, LEVEL2])
@pytest.mark.parametrize("reduce_dev", [False, True],
                         ids=["red-host", "red-dev"])
def test_wmr_tpu_opt_matches_seq(wt, level, reduce_dev):
    """LEVEL1/LEVEL2 over WinMapReduceTPU with the MAP stage (and
    optionally REDUCE) device-batched (optimize_WinMapReduceGPU,
    win_mapreduce_gpu.hpp:529-558)."""
    from windflow_tpu.patterns.win_seq_tpu import WinMapReduceTPU
    ref = totals(run_windowed(
        WinSeq(Reducer("sum"), WIN, SLIDE, wt), stream(wt)))
    for map_deg, red_deg in ((2, 1), (3, 2)):
        wmr = WinMapReduceTPU(Reducer("sum"), Reducer("sum"), WIN, SLIDE,
                              wt, map_degree=map_deg, reduce_degree=red_deg,
                              reduce_on_device=reduce_dev, batch_len=16,
                              flush_rows=128, opt_level=level)
        got = run_windowed(wmr, stream(wt))
        assert totals(got) == ref, f"degs={(map_deg, red_deg)}"


@pytest.mark.filterwarnings("ignore:resident device path accumulates")
def test_pane_farm_tpu_opt_shrinks_graph():
    from windflow_tpu.patterns.win_seq_tpu import PaneFarmTPU

    def pf(level):
        return PaneFarmTPU(Reducer("sum"), Reducer("sum"), WIN, SLIDE,
                           WinType.CB, plq_degree=3, wlq_degree=2,
                           batch_len=16, flush_rows=128, opt_level=level)
    n0 = graph_node_count(pf(0), stream(WinType.CB))
    n1 = graph_node_count(pf(LEVEL1), stream(WinType.CB))
    n2 = graph_node_count(pf(LEVEL2), stream(WinType.CB))
    assert n1 == n0 - 1
    assert n2 <= n0 - 2


@pytest.mark.filterwarnings("ignore:resident device path accumulates")
def test_pane_farm_tpu_opt_results_in_order():
    from windflow_tpu.patterns.win_seq_tpu import PaneFarmTPU
    pf = PaneFarmTPU(Reducer("sum"), Reducer("sum"), WIN, SLIDE, WinType.CB,
                     plq_degree=3, wlq_degree=2, batch_len=16,
                     flush_rows=128, opt_level=LEVEL2)
    got = run_windowed(pf, stream(WinType.CB))
    for key, rs in got.items():
        ids = [i for i, _, _ in rs]
        assert ids == sorted(ids), f"key {key} out of order"
