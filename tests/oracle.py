"""Brute-force oracle: a literal per-tuple simulation of the reference
Win_Seq state machine (win_seq.hpp:268-474, window.hpp).  Deliberately slow
and obvious — used only to differentially validate the vectorised engine.
"""

from __future__ import annotations

import bisect
import math


class OracleWinSeq:
    def __init__(self, win_len, slide_len, win_type, func, is_nic,
                 config=None, role="SEQ", map_indexes=(0, 1)):
        # config = (id_outer, n_outer, slide_outer, id_inner, n_inner, slide_inner)
        self.win = win_len
        self.slide = slide_len
        self.wt = win_type  # "CB" | "TB"
        self.func = func    # NIC: f(key,gwid,rows)->value ; INC: f(key,gwid,row,acc)->acc
        self.is_nic = is_nic
        self.cfg = config or (0, 1, slide_len, 0, 1, slide_len)
        self.role = role
        self.map_indexes = map_indexes
        self.keys = {}

    def _kd(self, key):
        kd = self.keys.get(key)
        if kd is None:
            io, no, so, ii, ni, si = self.cfg
            first_gwid = ((ii - (key % ni) + ni) % ni) * no + (io - (key % no) + no) % no
            init_outer = ((io - (key % no) + no) % no) * so
            init_inner = ((ii - (key % ni) + ni) % ni) * si
            initial = init_inner if self.role in ("WLQ", "REDUCE") else init_outer + init_inner
            kd = {
                "archive": [],  # list of (pos, rowdict) sorted by pos
                "wins": [],     # list of window dicts, in lwid order
                "next_lwid": 0,
                "rcv": 0,
                "last_pos": None,
                "emit": self.map_indexes[0] if self.role == "MAP" else 0,
                "first_gwid": first_gwid,
                "initial": initial,
            }
            self.keys[key] = kd
        return kd

    def _emit(self, key, kd, w, rows_or_acc):
        if self.is_nic:
            value = self.func(key, w["gwid"], rows_or_acc)
        else:
            value = rows_or_acc
        rid = w["gwid"]
        if self.role == "MAP":
            rid = kd["emit"]
            kd["emit"] += self.map_indexes[1]
        elif self.role == "PLQ":
            io, no, so, ii, ni, si = self.cfg
            rid = ((ii - (key % ni) + ni) % ni) + kd["emit"] * ni
            kd["emit"] += 1
        return {"key": key, "id": rid, "ts": w["result_ts"], "value": value}

    def push(self, key, id, ts, marker=False, value=0):
        out = []
        kd = self._kd(key)
        pos = id if self.wt == "CB" else ts
        if kd["last_pos"] is not None and pos < kd["last_pos"]:
            return out
        kd["rcv"] += 1
        kd["last_pos"] = pos
        initial = kd["initial"]
        if pos < initial:
            return out
        io, no, so, ii, ni, si = self.cfg
        # last window containing pos
        if self.win >= self.slide:
            last_w = math.ceil((pos + 1 - initial) / self.slide) - 1
        else:
            n = (pos - initial) // self.slide
            last_w = n
            if (pos - initial < n * self.slide) or (pos - initial >= n * self.slide + self.win):
                if not marker:
                    return out
        row = {"key": key, "id": id, "ts": ts, "value": value}
        if not marker and self.is_nic:
            poslist = [p for p, _ in kd["archive"]]
            i = bisect.bisect_left(poslist, pos)
            kd["archive"].insert(i, (pos, row))
        # create new windows
        while kd["next_lwid"] <= last_w:
            lwid = kd["next_lwid"]
            gwid = kd["first_gwid"] + lwid * no * ni
            w = {
                "lwid": lwid, "gwid": gwid,
                "result_ts": (gwid * self.slide + self.win - 1) if self.wt == "TB" else 0,
                "acc": None if self.is_nic else self.func(key, gwid, None, None),
                "first_pos": None, "firing_pos": None,
            }
            kd["wins"].append(w)
            kd["next_lwid"] += 1
        # evaluate open windows
        fired = 0
        for w in kd["wins"]:
            if self.wt == "CB":
                is_fired = id > (self.win + w["lwid"] * self.slide - 1) + initial
            else:
                is_fired = ts >= (self.win + w["lwid"] * self.slide) + initial
            if not is_fired:
                # CONTINUE
                if w["first_pos"] is None:
                    w["first_pos"] = pos
                if self.wt == "CB":
                    w["result_ts"] = ts
                if not self.is_nic and not marker:
                    w["acc"] = self.func(key, w["gwid"], row, w["acc"])
            else:
                if w["firing_pos"] is None:
                    w["firing_pos"] = pos
                if self.is_nic:
                    if w["first_pos"] is None:
                        rows = []
                    else:
                        poslist = [p for p, _ in kd["archive"]]
                        lo = bisect.bisect_left(poslist, w["first_pos"])
                        hi = bisect.bisect_left(poslist, w["firing_pos"])
                        rows = [r for _, r in kd["archive"][lo:hi]]
                    out.append(self._emit(key, kd, w, rows))
                    if w["first_pos"] is not None:
                        poslist = [p for p, _ in kd["archive"]]
                        cut = bisect.bisect_left(poslist, w["first_pos"])
                        kd["archive"] = kd["archive"][cut:]
                else:
                    out.append(self._emit(key, kd, w, w["acc"]))
                fired += 1
        kd["wins"] = kd["wins"][fired:]
        return out

    def eos(self):
        out = []
        for key, kd in self.keys.items():
            for w in kd["wins"]:
                if self.is_nic:
                    if w["first_pos"] is None:
                        rows = []
                    else:
                        poslist = [p for p, _ in kd["archive"]]
                        lo = bisect.bisect_left(poslist, w["first_pos"])
                        rows = [r for _, r in kd["archive"][lo:]]
                    out.append(self._emit(key, kd, w, rows))
                else:
                    out.append(self._emit(key, kd, w, w["acc"]))
            kd["wins"] = []
        return out
