"""Differential farm tests — the equivalent of src/sum_test_cpu's
test_{wf,kf}_{cb,tb}_{nic,inc} plus the test_all differential harness:
every farm composition must produce the SAME per-key ordered results as
the sequential Win_Seq on the same stream."""

import numpy as np
import pytest

from windflow_tpu.core.tuples import Schema, batch_from_columns
from windflow_tpu.core.windows import WinType
from windflow_tpu.ops.functions import Reducer
from windflow_tpu.patterns.basic import Sink, Source
from windflow_tpu.patterns.key_farm import KeyFarm
from windflow_tpu.patterns.win_farm import WinFarm
from windflow_tpu.patterns.win_seq import WinSeq
from windflow_tpu.runtime.engine import Dataflow
from windflow_tpu.runtime.farm import build_pipeline

SCHEMA = Schema(value=np.int64)


def cb_stream_batches(keys, n, chunk=32):
    out = []
    for i in range(0, n, chunk):
        ids = np.arange(i, min(i + chunk, n))
        ids = np.repeat(ids, keys)
        ks = np.tile(np.arange(keys), len(ids) // keys)
        out.append(batch_from_columns(SCHEMA, key=ks, id=ids, ts=ids * 7,
                                      value=ids))
    return out


def tb_stream_batches(keys, n, chunk=32, seed=0):
    rng = np.random.default_rng(seed)
    rows = []
    for k in range(keys):
        ts = 0
        for i in range(n):
            ts += int(rng.integers(0, 9))
            rows.append((k, i, ts, i))
    rows.sort(key=lambda r: r[2])
    out = []
    for i in range(0, len(rows), chunk):
        part = rows[i:i + chunk]
        out.append(batch_from_columns(
            SCHEMA, key=[r[0] for r in part], id=[r[1] for r in part],
            ts=[r[2] for r in part], value=[r[3] for r in part]))
    return out


def run_windowed(pattern, batches):
    """Run Source -> pattern -> Sink; returns per-key ordered results."""
    per_key = {}

    def snk(row):
        if row is not None:
            per_key.setdefault(int(row["key"]), []).append(
                (int(row["id"]), int(row["ts"]), int(row["value"])))

    df = Dataflow()
    build_pipeline(df, [Source(batches=iter(batches), schema=SCHEMA),
                        pattern, Sink(snk)])
    df.run_and_wait_end()
    return per_key


CASES = [(8, 3), (8, 8), (3, 8), (5, 1), (16, 7)]


@pytest.mark.parametrize("win,slide", CASES)
@pytest.mark.parametrize("pardegree", [2, 3, 5])
@pytest.mark.parametrize("inc", [False, True])
def test_win_farm_cb_matches_seq(win, slide, pardegree, inc):
    keys, n = 3, 120
    ref = run_windowed(
        WinSeq(Reducer("sum"), win, slide, WinType.CB, incremental=inc),
        cb_stream_batches(keys, n))
    got = run_windowed(
        WinFarm(Reducer("sum"), win, slide, WinType.CB, pardegree=pardegree,
                incremental=inc),
        cb_stream_batches(keys, n))
    assert got == ref


@pytest.mark.parametrize("win,slide", [(40, 15), (30, 30), (15, 40)])
@pytest.mark.parametrize("pardegree", [2, 4])
def test_win_farm_tb_matches_seq(win, slide, pardegree):
    keys, n = 2, 150
    ref = run_windowed(WinSeq(Reducer("sum"), win, slide, WinType.TB),
                       tb_stream_batches(keys, n))
    got = run_windowed(
        WinFarm(Reducer("sum"), win, slide, WinType.TB, pardegree=pardegree),
        tb_stream_batches(keys, n))
    assert got == ref


@pytest.mark.parametrize("win,slide", CASES)
@pytest.mark.parametrize("pardegree", [2, 4])
@pytest.mark.parametrize("inc", [False, True])
def test_key_farm_cb_matches_seq(win, slide, pardegree, inc):
    keys, n = 5, 100
    ref = run_windowed(
        WinSeq(Reducer("sum"), win, slide, WinType.CB, incremental=inc),
        cb_stream_batches(keys, n))
    got = run_windowed(
        KeyFarm(Reducer("sum"), win, slide, WinType.CB, pardegree=pardegree,
                incremental=inc),
        cb_stream_batches(keys, n))
    assert got == ref


@pytest.mark.parametrize("pardegree", [2, 3])
def test_key_farm_tb_matches_seq(pardegree):
    keys, n = 4, 120
    ref = run_windowed(WinSeq(Reducer("sum"), 25, 10, WinType.TB),
                       tb_stream_batches(keys, n))
    got = run_windowed(
        KeyFarm(Reducer("sum"), 25, 10, WinType.TB, pardegree=pardegree),
        tb_stream_batches(keys, n))
    assert got == ref


def test_win_farm_ordered_collector_dense_ids():
    """Ordered collector delivers result ids 0,1,2,... per key (the
    Consumer check, sum_cb.hpp:146-150)."""
    got = run_windowed(
        WinFarm(Reducer("sum"), 10, 5, WinType.CB, pardegree=4),
        cb_stream_batches(2, 200))
    for rs in got.values():
        assert [r[0] for r in rs] == list(range(len(rs)))


def test_win_farm_unordered_same_multiset():
    ref = run_windowed(WinSeq(Reducer("sum"), 10, 5, WinType.CB),
                       cb_stream_batches(2, 150))
    got = run_windowed(
        WinFarm(Reducer("sum"), 10, 5, WinType.CB, pardegree=3, ordered=False),
        cb_stream_batches(2, 150))
    for k in ref:
        assert sorted(got[k]) == sorted(ref[k])


def test_ordering_core_kway_merge():
    """OrderingCore releases rows only once all channels' watermarks pass,
    and flushes markers last."""
    from windflow_tpu.runtime.ordering import OrderingCore, OrderingMode

    oc = OrderingCore(2, OrderingMode.ID)
    b1 = batch_from_columns(SCHEMA, key=[0, 0], id=[0, 2], ts=[0, 2],
                            value=[0, 2])
    b2 = batch_from_columns(SCHEMA, key=[0, 0], id=[1, 3], ts=[1, 3],
                            value=[1, 3])
    out1 = oc.push(b1, 0)      # channel-1 watermark still -inf -> nothing
    assert out1 == []
    out2 = oc.push(b2, 1)      # min watermark now min(2,3)=2 -> 0,1,2 out
    released = np.concatenate(out2)["id"].tolist()
    assert released == [0, 1, 2]
    rest = [r["id"][0] for r in oc.flush()]
    assert rest == [3]


def test_ordering_renumbering():
    from windflow_tpu.runtime.ordering import OrderingCore, OrderingMode

    oc = OrderingCore(2, OrderingMode.TS_RENUMBERING)
    b1 = batch_from_columns(SCHEMA, key=[0, 0], id=[40, 41], ts=[10, 30],
                            value=[0, 0])
    b2 = batch_from_columns(SCHEMA, key=[0, 0], id=[90, 91], ts=[20, 40],
                            value=[0, 0])
    oc.push(b1, 0)
    outs = oc.push(b2, 1) + oc.flush()
    merged = np.concatenate(outs)
    assert merged["ts"].tolist() == [10, 20, 30, 40]   # ts-ordered
    assert merged["id"].tolist() == [0, 1, 2, 3]       # densely renumbered
