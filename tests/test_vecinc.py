"""Differential: VecIncTumblingCore vs the reference per-key WinSeqCore.

The vectorised core must be row-for-row identical (per key) to WinSeqCore
on tumbling windows for every role / config / reducer / disorder mix it
claims to support (vec_core_supported)."""

import numpy as np
import pytest

from windflow_tpu.core.tuples import MARKER_FIELD, Schema, batch_from_columns
from windflow_tpu.core.vecinc import VecIncTumblingCore, vec_core_supported
from windflow_tpu.core.windows import PatternConfig, Role, WindowSpec, WinType
from windflow_tpu.core.winseq import WinSeqCore
from windflow_tpu.ops.functions import MultiReducer, Reducer

SCHEMA = Schema(value=np.int64)


def make_stream(rng, n_keys, n_chunks, rows_per_chunk, *, ooo_frac=0.0,
                gaps=False, markers_at_end=True):
    """Chunks of interleaved keyed rows with optional disorder and id gaps;
    the final chunk optionally carries per-key EOS markers (each key's last
    row replayed with the marker flag, as the farm emitters do)."""
    next_id = {k: 0 for k in range(n_keys)}
    last_row = {}
    chunks = []
    for _ in range(n_chunks):
        keys = rng.integers(0, n_keys, rows_per_chunk)
        ids = np.empty(rows_per_chunk, dtype=np.int64)
        for i, k in enumerate(keys):
            step = int(rng.integers(1, 4)) if gaps else 1
            ids[i] = next_id[k]
            next_id[k] += step
        if ooo_frac:
            flip = rng.random(rows_per_chunk) < ooo_frac
            ids[flip] = np.maximum(ids[flip] - rng.integers(1, 6, flip.sum()), 0)
        ts = ids * 3 + keys
        vals = rng.integers(-5, 50, rows_per_chunk)
        b = batch_from_columns(SCHEMA, key=keys, id=ids, ts=ts, value=vals)
        for i in range(rows_per_chunk):
            k = int(keys[i])
            if k not in last_row or ids[i] >= int(last_row[k]["id"]):
                last_row[k] = b[i].copy()
        chunks.append(b)
    if markers_at_end and last_row:
        mk = np.stack([last_row[k] for k in sorted(last_row)])
        mk[MARKER_FIELD] = True
        chunks.append(mk)
    return chunks


def run_core(core, chunks):
    outs = [core.process(c) for c in chunks]
    outs.append(core.flush())
    outs = [o for o in outs if len(o)]
    return (np.concatenate(outs) if outs
            else np.zeros(0, dtype=core.result_schema.dtype()))


def per_key_sorted(res):
    """Row sequences grouped per key (cross-key emission order is not part
    of the contract — the reference's is thread-timing dependent too)."""
    out = {}
    for k in np.unique(res["key"]):
        out[int(k)] = res[res["key"] == k]
    return out


def assert_equivalent(a, b):
    ka, kb = per_key_sorted(a), per_key_sorted(b)
    assert set(ka) == set(kb)
    for k in ka:
        ra, rb = ka[k], kb[k]
        assert len(ra) == len(rb), f"key {k}: {len(ra)} vs {len(rb)} rows"
        for f in ra.dtype.names:
            np.testing.assert_array_equal(
                ra[f], rb[f], err_msg=f"key {k} field {f}")


CASES = [
    dict(),                                   # in-order, dense
    dict(ooo_frac=0.15),                      # out-of-order drops
    dict(gaps=True),                          # id gaps -> empty fired windows
    dict(gaps=True, ooo_frac=0.1),
    dict(markers_at_end=False),               # no EOS markers
]


@pytest.mark.parametrize("win_type", [WinType.CB, WinType.TB])
@pytest.mark.parametrize("case", range(len(CASES)))
def test_vec_vs_ref_seq(win_type, case):
    rng = np.random.default_rng(100 + case)
    spec = WindowSpec(4, 4, win_type)
    chunks = make_stream(rng, 37, 6, 200, **CASES[case])
    red = Reducer("sum")
    ref = WinSeqCore(spec, red).use_incremental()
    vec = VecIncTumblingCore(spec, red)
    assert_equivalent(run_core(vec, chunks), run_core(ref, chunks))


@pytest.mark.parametrize("role,map_indexes", [
    (Role.MAP, (1, 3)), (Role.PLQ, (0, 1)), (Role.WLQ, (0, 1)),
    (Role.REDUCE, (0, 1)),
])
def test_vec_vs_ref_roles(role, map_indexes):
    rng = np.random.default_rng(7)
    spec = WindowSpec(5, 5, WinType.CB)
    cfg = PatternConfig(id_outer=1, n_outer=2, slide_outer=10,
                        id_inner=1, n_inner=3, slide_inner=5)
    chunks = make_stream(rng, 23, 5, 150, gaps=True)
    red = Reducer("max")
    ref = WinSeqCore(spec, red, config=cfg, role=role,
                     map_indexes=map_indexes).use_incremental()
    vec = VecIncTumblingCore(spec, red, config=cfg, role=role,
                             map_indexes=map_indexes)
    assert_equivalent(run_core(vec, chunks), run_core(ref, chunks))


@pytest.mark.parametrize("op", ["sum", "min", "max", "prod", "count"])
def test_vec_vs_ref_ops(op):
    rng = np.random.default_rng(11)
    spec = WindowSpec(3, 3, WinType.CB)
    chunks = make_stream(rng, 11, 4, 90, ooo_frac=0.1)
    red = Reducer(op, out_field="r")
    ref = WinSeqCore(spec, red).use_incremental()
    vec = VecIncTumblingCore(spec, red)
    assert_equivalent(run_core(vec, chunks), run_core(ref, chunks))


def test_vec_vs_ref_multireducer():
    rng = np.random.default_rng(13)
    spec = WindowSpec(6, 6, WinType.TB)
    chunks = make_stream(rng, 19, 5, 120, gaps=True)
    mk = MultiReducer(("count", None, "cnt"), ("max", "value", "mx"),
                      ("sum", "value", "sm"))
    ref = WinSeqCore(spec, mk).use_incremental()
    vec = VecIncTumblingCore(spec, mk)
    assert_equivalent(run_core(vec, chunks), run_core(ref, chunks))


def test_vec_core_gate():
    """make_core picks the vectorised core exactly when supported:
    tumbling + sliding (W <= 64) vectorise; hopping and extreme
    win/slide ratios stay on the general per-key core."""
    from windflow_tpu.core.vecinc import VecIncSlidingCore
    from windflow_tpu.patterns.win_seq import WinSeq
    assert vec_core_supported(WindowSpec(4, 4, WinType.CB), Reducer("sum"))
    assert vec_core_supported(WindowSpec(8, 4, WinType.CB), Reducer("sum"))
    assert not vec_core_supported(WindowSpec(4, 8, WinType.CB),
                                  Reducer("sum"))         # hopping
    assert not vec_core_supported(WindowSpec(256, 1, WinType.CB),
                                  Reducer("sum"))         # W > 64
    assert isinstance(WinSeq(Reducer("sum"), 4, 4, WinType.CB).make_core(),
                      VecIncTumblingCore)
    from windflow_tpu.core.vecinc import LazySlidingCore
    assert isinstance(WinSeq(Reducer("sum"), 8, 4, WinType.CB).make_core(),
                      LazySlidingCore)
    assert isinstance(WinSeq(Reducer("sum"), 4, 8, WinType.CB).make_core(),
                      WinSeqCore)


def test_vec_initial_id_drop():
    """Rows below a worker's initial position are dropped identically."""
    rng = np.random.default_rng(17)
    spec = WindowSpec(4, 4, WinType.CB)
    cfg = PatternConfig(id_outer=1, n_outer=3, slide_outer=4,
                        id_inner=0, n_inner=1, slide_inner=4)
    chunks = make_stream(rng, 9, 4, 80)
    red = Reducer("sum")
    ref = WinSeqCore(spec, red, config=cfg).use_incremental()
    vec = VecIncTumblingCore(spec, red, config=cfg)
    assert_equivalent(run_core(vec, chunks), run_core(ref, chunks))


def test_vec_disorder_stays_vectorised_at_high_cardinality():
    """Sustained out-of-order input at 2e4 keys must not collapse into
    per-key Python (the segmented doubling running-max keeps the drop
    pass O(rows log rows)); results stay identical to the reference."""
    import time
    rng = np.random.default_rng(23)
    spec = WindowSpec(4, 4, WinType.CB)
    n_keys, rows = 20_000, 5
    chunks = []
    next_id = np.zeros(n_keys, dtype=np.int64)
    for _ in range(rows):
        keys = np.arange(n_keys)
        ids = next_id.copy()
        next_id += 1
        flip = rng.random(n_keys) < 0.15          # 15% disorder every chunk
        ids[flip] = np.maximum(ids[flip] - rng.integers(1, 4, flip.sum()), 0)
        chunks.append(batch_from_columns(
            SCHEMA, key=keys, id=ids, ts=ids * 2, value=ids + keys % 5))
    red = Reducer("sum")
    t0 = time.perf_counter()
    got = run_core(VecIncTumblingCore(spec, red), chunks)
    dt = time.perf_counter() - t0
    want = run_core(WinSeqCore(spec, red).use_incremental(), chunks)
    assert_equivalent(got, want)
    assert dt < 5, f"disordered vec path took {dt:.1f}s at {n_keys} keys"


# ---------------------------------------------------------------- sliding

from windflow_tpu.core.vecinc import VecIncSlidingCore  # noqa: E402


@pytest.mark.parametrize("win,slide", [(8, 4), (6, 2), (7, 3), (256, 64)])
@pytest.mark.parametrize("win_type", [WinType.CB, WinType.TB])
@pytest.mark.parametrize("case", range(len(CASES)))
def test_vec_sliding_vs_ref_seq(win, slide, win_type, case):
    rng = np.random.default_rng(300 + case + win * 7)
    spec = WindowSpec(win, slide, win_type)
    chunks = make_stream(rng, 17, 6, 200, **CASES[case])
    red = Reducer("sum")
    ref = WinSeqCore(spec, red).use_incremental()
    vec = VecIncSlidingCore(spec, red)
    assert_equivalent(run_core(vec, chunks), run_core(ref, chunks))


@pytest.mark.parametrize("role,map_indexes", [
    (Role.MAP, (1, 3)), (Role.PLQ, (0, 1)), (Role.WLQ, (0, 1)),
])
def test_vec_sliding_vs_ref_roles(role, map_indexes):
    rng = np.random.default_rng(31)
    spec = WindowSpec(10, 4, WinType.CB)
    cfg = PatternConfig(id_outer=1, n_outer=2, slide_outer=8,
                        id_inner=1, n_inner=3, slide_inner=4)
    chunks = make_stream(rng, 13, 5, 150, gaps=True)
    red = Reducer("max")
    ref = WinSeqCore(spec, red, config=cfg, role=role,
                     map_indexes=map_indexes).use_incremental()
    vec = VecIncSlidingCore(spec, red, config=cfg, role=role,
                            map_indexes=map_indexes)
    assert_equivalent(run_core(vec, chunks), run_core(ref, chunks))


@pytest.mark.parametrize("op", ["sum", "min", "max", "prod", "count"])
def test_vec_sliding_vs_ref_ops(op):
    rng = np.random.default_rng(37)
    spec = WindowSpec(9, 3, WinType.CB)
    chunks = make_stream(rng, 11, 4, 90, ooo_frac=0.1)
    red = Reducer(op, out_field="r")
    ref = WinSeqCore(spec, red).use_incremental()
    vec = VecIncSlidingCore(spec, red)
    assert_equivalent(run_core(vec, chunks), run_core(ref, chunks))


def test_vec_sliding_multireducer():
    rng = np.random.default_rng(41)
    spec = WindowSpec(12, 5, WinType.TB)
    chunks = make_stream(rng, 19, 5, 120, gaps=True)
    mk = MultiReducer(("count", None, "cnt"), ("max", "value", "mx"),
                      ("sum", "value", "sm"))
    ref = WinSeqCore(spec, mk).use_incremental()
    vec = VecIncSlidingCore(spec, mk)
    assert_equivalent(run_core(vec, chunks), run_core(ref, chunks))


def test_vec_sliding_high_cardinality_budget():
    """VERDICT r2 weak #2 / next-round #3: a 1e5-key SLIDING differential
    must complete in seconds — the general core's per-key-group path
    collapses here; the lane core is O(W * rows log rows)."""
    import time
    rng = np.random.default_rng(43)
    spec = WindowSpec(16, 4, WinType.CB)
    n_keys, n_chunks = 100_000, 8
    chunks = []
    for c in range(n_chunks):
        keys = np.arange(n_keys)
        ids = np.full(n_keys, c, dtype=np.int64)
        vals = rng.integers(-5, 50, n_keys)
        chunks.append(batch_from_columns(
            SCHEMA, key=keys, id=ids, ts=ids * 2, value=vals))
    red = Reducer("sum")
    t0 = time.perf_counter()
    got = run_core(VecIncSlidingCore(spec, red), chunks)
    dt = time.perf_counter() - t0
    assert dt < 10, f"sliding vec path took {dt:.1f}s at {n_keys} keys"
    # oracle on a key sample (the full per-key ref would take minutes)
    sample = [0, 1, 12345, 99_999]
    sub = [c[np.isin(c["key"], sample)] for c in chunks]
    want = run_core(WinSeqCore(spec, red).use_incremental(), sub)
    got_sub = got[np.isin(got["key"], sample)]
    assert_equivalent(got_sub, want)


def test_lazy_sliding_core_picks_by_cardinality():
    """Sliding windows defer the core choice to the first chunk: few
    distinct keys -> the per-key-group WinSeqCore (faster below the
    crossover), many -> the lane-vectorised core; results identical
    either way."""
    from windflow_tpu.core.vecinc import LazySlidingCore, VecIncSlidingCore
    spec = WindowSpec(8, 4, WinType.CB)

    def stream(n_keys):
        ids = np.repeat(np.arange(40), n_keys)
        keys = np.tile(np.arange(n_keys), 40)
        return [batch_from_columns(SCHEMA, key=keys, id=ids, ts=ids,
                                   value=ids + keys % 7)]

    small = LazySlidingCore(spec, Reducer("sum"))
    got_small = run_core(small, stream(10))
    assert isinstance(small._core, WinSeqCore)
    big = LazySlidingCore(spec, Reducer("sum"), threshold=16)
    got_big = run_core(big, stream(32))
    assert isinstance(big._core, VecIncSlidingCore)
    want_small = run_core(WinSeqCore(spec, Reducer("sum")).use_incremental(),
                          stream(10))
    assert_equivalent(got_small, want_small)
    want_big = run_core(WinSeqCore(spec, Reducer("sum")).use_incremental(),
                        stream(32))
    assert_equivalent(got_big, want_big)


@pytest.mark.parametrize("op", ["sum", "max", "count"])
@pytest.mark.parametrize("wt", [WinType.CB, WinType.TB])
def test_lazy_sliding_core_escalates_mid_stream(wt, op):
    """A key-clustered stream (first chunks carry few keys) must not lock
    the lazy selector into the per-key core: when observed cardinality
    crosses the threshold, the per-key state migrates into the lane core
    mid-stream, with results identical to the reference oracle."""
    from windflow_tpu.core.vecinc import LazySlidingCore, VecIncSlidingCore
    spec = WindowSpec(9, 4, wt)
    rng = np.random.default_rng(57)
    n_keys = 40

    def clustered():
        chunks = []
        # phase 1: two keys only, long runs (under-represents the set)
        for lo in range(0, 30, 10):
            ids = np.repeat(np.arange(lo, lo + 10), 2)
            keys = np.tile(np.arange(2), 10)
            chunks.append(batch_from_columns(
                SCHEMA, key=keys, id=ids, ts=ids * 3 + keys,
                value=rng.integers(-5, 50, 20)))
        # phase 2: every key arrives (ids resume mid-stream per key)
        for lo in range(0, 40, 8):
            ids = np.repeat(np.arange(lo, lo + 8), n_keys)
            keys = np.tile(np.arange(n_keys), 8)
            # keys 0/1 continue beyond their phase-1 ids
            ids = np.where(keys < 2, ids + 30, ids)
            chunks.append(batch_from_columns(
                SCHEMA, key=keys, id=ids, ts=ids * 3 + keys,
                value=rng.integers(-5, 50, 8 * n_keys)))
        return chunks

    chunks = clustered()
    red = Reducer(op, out_field="r")
    lazy = LazySlidingCore(spec, Reducer(op, out_field="r"), threshold=16)
    got = run_core(lazy, chunks)
    assert isinstance(lazy._core, VecIncSlidingCore), \
        "selector never escalated despite crossing the threshold"
    want = run_core(WinSeqCore(spec, red).use_incremental(), chunks)
    assert_equivalent(got, want)


@pytest.mark.parametrize("role,map_indexes", [
    (Role.SEQ, (0, 1)), (Role.MAP, (1, 3)), (Role.PLQ, (0, 1)),
])
@pytest.mark.parametrize("case", [1, 3])   # ooo+markers / gaps+ooo
def test_lazy_sliding_escalation_roles_disorder(role, map_indexes, case):
    """Escalation under the hard paths: role renumbering state
    (emit_counter for MAP/PLQ), out-of-order drops, id gaps, and
    mid-stream markers must all survive the per-key -> lane migration."""
    from windflow_tpu.core.vecinc import LazySlidingCore, VecIncSlidingCore
    rng = np.random.default_rng(71 + case)
    spec = WindowSpec(10, 4, WinType.CB)
    cfg = PatternConfig(id_outer=1, n_outer=2, slide_outer=8,
                        id_inner=1, n_inner=3, slide_inner=4)
    # clustered prefix (1 key) keeps the selector on the per-key core,
    # then the full stream crosses the tiny threshold -> escalate
    pre = batch_from_columns(SCHEMA, key=np.zeros(12),
                             id=np.arange(12), ts=np.arange(12) * 3,
                             value=rng.integers(-5, 50, 12))
    chunks = [pre] + make_stream(rng, 25, 4, 150, **CASES[case])

    def mk():
        return Reducer("max")

    lazy = LazySlidingCore(spec, mk(), threshold=8, config=cfg, role=role,
                           map_indexes=map_indexes)
    got = run_core(lazy, chunks)
    assert isinstance(lazy._core, VecIncSlidingCore)
    ref = WinSeqCore(spec, mk(), config=cfg, role=role,
                     map_indexes=map_indexes).use_incremental()
    assert_equivalent(got, run_core(ref, chunks))


def test_lazy_sliding_escalation_multireducer():
    """MultiReducer accumulators (count + max + sum lanes) migrate too."""
    from windflow_tpu.core.vecinc import LazySlidingCore, VecIncSlidingCore
    rng = np.random.default_rng(83)
    spec = WindowSpec(12, 5, WinType.TB)

    def mk():
        return MultiReducer(("count", None, "cnt"), ("max", "value", "mx"),
                            ("sum", "value", "sm"))

    pre = batch_from_columns(SCHEMA, key=np.zeros(10),
                             id=np.arange(10), ts=np.arange(10) * 3,
                             value=rng.integers(-5, 50, 10))
    chunks = [pre] + make_stream(rng, 21, 4, 130, gaps=True)
    lazy = LazySlidingCore(spec, mk(), threshold=8)
    got = run_core(lazy, chunks)
    assert isinstance(lazy._core, VecIncSlidingCore)
    assert_equivalent(got, run_core(WinSeqCore(spec, mk()).use_incremental(),
                                    chunks))


def test_sliding_crossover_is_derived_not_encoded():
    """r3 weak #4: the per-key vs lane-core crossover is MEASURED on the
    running host (derived_sliding_threshold), not a baked-in constant —
    and whatever value the measurement returns, both cores agree
    differentially on streams straddling it."""
    from windflow_tpu.core.vecinc import (LazySlidingCore,
                                          VecIncSlidingCore,
                                          derived_sliding_threshold)
    from windflow_tpu.core.winseq import WinSeqCore

    th = derived_sliding_threshold()
    assert 64 <= th <= 8192, th
    assert derived_sliding_threshold() == th, "must cache per process"
    # default-constructed selector adopts the derived value
    spec = WindowSpec(8, 2, WinType.CB)
    lazy = LazySlidingCore(spec, Reducer("sum"))
    assert lazy._threshold == th
    # differential straddle: a stream just under and just over the
    # measured crossover picks different cores, same results
    for nk in (max(th - 8, 2), th + 8):
        n = 6 * nk
        ids = np.repeat(np.arange(n // nk, dtype=np.int64), nk)
        keys = np.tile(np.arange(nk, dtype=np.int64), n // nk)
        b = batch_from_columns(Schema(value=np.int64), key=keys, id=ids,
                               ts=ids, value=(ids * 7 + keys) % 101)
        lz = LazySlidingCore(spec, Reducer("sum"))
        got = np.concatenate([lz.process(b), lz.flush()])
        picked = type(lz._core)
        assert picked is (VecIncSlidingCore if nk >= th else WinSeqCore)
        ref = WinSeqCore(spec, Reducer("sum"))
        want = np.concatenate([ref.process(b), ref.flush()])
        got = np.sort(got, order=["key", "id"])
        want = np.sort(want, order=["key", "id"])
        np.testing.assert_array_equal(got, want, err_msg=f"nk={nk}")
