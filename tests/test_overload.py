"""Overload-policy tests (runtime/overload.py): shedding disciplines, put
deadlines, and poison-tuple quarantine — run against BOTH inbox
implementations (native C++ ring and Python queue fallback), since the
policies are implemented twice.  The contract under test is
docs/ROBUSTNESS.md: knobs unset => seed-identical behavior; knobs set =>
the graph degrades (sheds / quarantines / fails fast) instead of dying on
the first error or hanging on a stalled stage."""

import importlib.util
import json
import os
import time

import numpy as np
import pytest

from windflow_tpu import (Map_Builder, MultiPipe, Sink_Builder,
                          Source_Builder)
from windflow_tpu.core.tuples import Schema, batch_from_columns
from windflow_tpu.patterns.basic import Map, Sink, Source
from windflow_tpu.runtime.engine import Dataflow
from windflow_tpu.runtime.farm import build_pipeline
from windflow_tpu.runtime.overload import (OverloadError, OverloadPolicy,
                                           SHED_POLICIES)

SCHEMA = Schema(value=np.int64)


@pytest.fixture(params=["native", "python"])
def inbox_kind(request, monkeypatch):
    if request.param == "python":
        monkeypatch.setenv("WF_NO_NATIVE", "1")
    else:
        from windflow_tpu import native
        if not native.available():
            pytest.skip("native library unavailable")
        monkeypatch.delenv("WF_NO_NATIVE", raising=False)
    return request.param


def make_batches(n_batches=200, rows=10, poison_at=()):
    out = []
    for i in range(n_batches):
        vals = np.full(rows, i, dtype=np.int64)
        if i in poison_at:
            vals[0] = -1
        out.append(batch_from_columns(
            SCHEMA, key=np.zeros(rows), id=np.arange(rows),
            ts=np.arange(rows), value=vals))
    return out


def run_source_sink(policy, n_batches=200, sink_delay=0.005, capacity=4):
    """Fast source -> slow sink, two nodes, single edge: the conservation
    equation delivered + shed == emitted holds exactly."""
    delivered = [0]
    total = [0]

    def consume(rows):
        if rows is not None and len(rows):
            delivered[0] += 1
            total[0] += int(rows["value"].sum())
            if sink_delay:
                time.sleep(sink_delay)

    df = Dataflow(capacity=capacity, overload=policy)
    build_pipeline(df, [Source(batches=make_batches(n_batches),
                               schema=SCHEMA),
                        Sink(consume, vectorized=True)])
    t0 = time.monotonic()
    df.run_and_wait_end()
    return delivered[0], total[0], df, time.monotonic() - t0


# ------------------------------------------------------------- shedding

@pytest.mark.parametrize("shed", ["shed_oldest", "shed_newest"])
def test_shedding_bounds_slow_sink(inbox_kind, shed):
    """Fast source + slow sink under a shedding policy: the run completes
    quickly (the source never waits on the sink), queue occupancy stays
    bounded by construction, shed counters are nonzero and conserved."""
    n = 200
    delivered, _, df, wall = run_source_sink(OverloadPolicy(shed=shed),
                                             n_batches=n)
    shed_counts = df.shed_counts()
    assert delivered < n
    assert sum(shed_counts.values()) > 0
    # exact conservation on the single sink inbox
    assert delivered + shed_counts["sink.0"] == n
    # a blocking run would take ~n * sink_delay = 1s+; shedding must not
    assert wall < 5.0


def test_block_policy_still_backpressures(inbox_kind):
    """The explicit block policy (and the no-policy default) delivers
    everything: backpressure, no shedding."""
    n = 60
    for policy in (None, OverloadPolicy(shed="block")):
        delivered, total, df, _ = run_source_sink(policy, n_batches=n,
                                                  sink_delay=0.002)
        assert delivered == n
        assert total == sum(10 * i for i in range(n))
        assert df.shed_counts() == {}


def test_shed_newest_keeps_oldest_items(inbox_kind):
    """shed_newest drops the incoming item: what was queued first wins,
    so the delivered set is biased to the stream's prefix."""
    delivered_ids = []

    def consume(rows):
        if rows is not None and len(rows):
            delivered_ids.append(int(rows["value"][0]))
            time.sleep(0.005)

    df = Dataflow(capacity=4, overload=OverloadPolicy(shed="shed_newest"))
    build_pipeline(df, [Source(batches=make_batches(100), schema=SCHEMA),
                        Sink(consume, vectorized=True)])
    df.run_and_wait_end()
    assert delivered_ids == sorted(delivered_ids)   # arrival order kept
    assert delivered_ids[0] == 0                    # the head survived


def test_put_deadline_fails_fast_not_hang(inbox_kind):
    """A stage stalled past the put deadline surfaces as OverloadError
    from wait() within bounded wall-clock — never an indefinite hang."""

    def stall(rows):
        if rows is not None:
            time.sleep(0.4)

    df = Dataflow(capacity=2,
                  overload=OverloadPolicy(put_deadline=0.2))
    build_pipeline(df, [Source(batches=make_batches(50), schema=SCHEMA),
                        Sink(stall, vectorized=True)])
    t0 = time.monotonic()
    with pytest.raises(OverloadError, match="deadline"):
        df.run_and_wait_end()
    assert time.monotonic() - t0 < 10


def test_policy_validation():
    with pytest.raises(ValueError, match="must be one of"):
        OverloadPolicy(shed="drop_everything")
    with pytest.raises(ValueError, match="never blocks"):
        OverloadPolicy(shed="shed_oldest", put_deadline=1.0)
    with pytest.raises(ValueError, match="error_budget"):
        OverloadPolicy(error_budget=-1)
    assert [p for p in SHED_POLICIES] == ["block", "shed_oldest",
                                          "shed_newest"]
    # an unbounded queue never fills: shed/deadline knobs would be
    # silently inert, so the combination is rejected loudly
    with pytest.raises(ValueError, match="bounded"):
        Dataflow(capacity=0, overload=OverloadPolicy(shed="shed_oldest"))
    # a pure error-budget policy has no put-side knob: fine unbounded
    Dataflow(capacity=0, overload=OverloadPolicy(error_budget=3))


def test_shedding_confined_to_shed_safe_inboxes(inbox_kind):
    """Internal window-farm edges (multicast copies, dense-id result
    streams) must never shed — only the farm-head emitter and the sink
    may — and a windowed run where nothing sheds is byte-identical to
    the no-policy run (no silent window corruption)."""
    from windflow_tpu.core.windows import WinType
    from windflow_tpu.ops.functions import Reducer
    from windflow_tpu.patterns.win_farm import WinFarm

    def run(policy):
        got = []
        # capacity > batch count: no inbox can ever fill, so a correct
        # implementation sheds nothing anywhere
        df = Dataflow(capacity=16, overload=policy)
        build_pipeline(df, [
            Source(batches=make_batches(8, rows=12), schema=SCHEMA),
            WinFarm(Reducer("sum"), 16, 8, WinType.CB, pardegree=2),
            Sink(lambda r: got.append(r) if r is not None else None,
                 vectorized=True)])
        df.run_and_wait_end()
        rows = sorted((int(r["key"]), int(r["id"]), int(r["value"]))
                      for g in got for r in g)
        return rows, df

    base, _ = run(None)
    shedded, df = run(OverloadPolicy(shed="shed_oldest"))
    # no queue ever filled: nothing sheds, results identical to no-policy
    assert df.shed_counts() == {}
    assert shedded == base
    # and the internal edges genuinely run policy-free inboxes
    for node in df.nodes:
        inbox = df._inboxes[id(node)]
        if not getattr(node, "shed_safe", False):
            assert inbox._policy is None, node.name


def test_put_deadline_not_consumed_by_error_budget(inbox_kind):
    """An OverloadError raised by a downstream put inside svc's emit is
    backpressure failure, NOT a poison tuple: it must fail fast without
    burning the error budget or landing in the dead-letter queue."""

    def stall(rows):
        if rows is not None:
            time.sleep(0.4)

    df = Dataflow(capacity=2,
                  overload=OverloadPolicy(put_deadline=0.2,
                                          error_budget=50))
    build_pipeline(df, [Source(batches=make_batches(50), schema=SCHEMA),
                        Map(lambda b: b, name="fwd", vectorized=True),
                        Sink(stall, vectorized=True)])
    with pytest.raises(OverloadError):
        df.run_and_wait_end()
    assert df.dead_letters == []


def test_shed_newest_observes_graph_failure(inbox_kind):
    """A failed graph must stop a shed_newest producer too: shedding
    never blocks, so the full-queue path is where cancellation is
    observed (an unbounded source would otherwise generate forever)."""

    def boom(rows):
        if rows is not None:
            raise RuntimeError("sink boom")

    df = Dataflow(capacity=2, overload=OverloadPolicy(shed="shed_newest"))
    build_pipeline(df, [Source(batches=make_batches(5000, rows=4),
                               schema=SCHEMA),
                        Sink(boom, vectorized=True)])
    t0 = time.monotonic()
    with pytest.raises(RuntimeError, match="sink boom"):
        df.run_and_wait_end()
    assert time.monotonic() - t0 < 30


# ----------------------------------------------------------- quarantine

def poison_graph(budget_policy=None, node_budget=None, poison_at=(3, 7),
                 n=20, trace_dir=None):
    got = [0]

    def check(b):
        if (b["value"] < 0).any():
            raise ValueError("poison batch")

    def consume(rows):
        if rows is not None and len(rows):
            got[0] += 1

    mp = Map(check, name="check", vectorized=True)
    if node_budget is not None:
        mp.error_budget = node_budget
    df = Dataflow("poison", capacity=4, overload=budget_policy,
                  trace_dir=trace_dir)
    build_pipeline(df, [
        Source(batches=make_batches(n, poison_at=poison_at),
               schema=SCHEMA),
        mp,
        Sink(consume, vectorized=True)])
    return df, got


def test_poison_within_budget_quarantines(inbox_kind):
    """Poison batches within the error budget land in the dead-letter
    queue; the graph runs to completion and the rest of the stream is
    processed normally."""
    df, got = poison_graph(OverloadPolicy(error_budget=3),
                           poison_at=(3, 7), n=20)
    df.run_and_wait_end()
    assert got[0] == 18
    assert len(df.dead_letters) == 2
    dl = df.dead_letters[0]
    assert dl.node == "check.0"
    assert isinstance(dl.error, ValueError)
    assert int(dl.batch["value"][0]) == -1      # the offending batch
    assert "DeadLetter" in repr(dl)


def test_poison_over_budget_fails_fast(inbox_kind):
    """Budget exhausted => the NEXT poison error propagates exactly like
    the default engine (fail-fast preserved), after quarantining up to
    the budget."""
    df, _ = poison_graph(OverloadPolicy(error_budget=2),
                         poison_at=(2, 5, 8, 11), n=20)
    with pytest.raises(ValueError, match="poison"):
        df.run_and_wait_end()
    assert len(df.dead_letters) == 2


def test_poison_default_fails_on_first_error(inbox_kind):
    """No budget set: first poison batch tears the graph down (seed
    behavior) and nothing is quarantined."""
    df, _ = poison_graph(None, poison_at=(4,), n=20)
    with pytest.raises(ValueError, match="poison"):
        df.run_and_wait_end()
    assert df.dead_letters == []


def test_node_budget_overrides_policy():
    """A node-level budget (builders' withErrorBudget path) wins over the
    dataflow-wide default."""
    df, got = poison_graph(OverloadPolicy(error_budget=0), node_budget=5,
                           poison_at=(1, 2, 3), n=12)
    df.run_and_wait_end()
    assert got[0] == 9
    assert len(df.dead_letters) == 3


def test_quarantine_counter_in_tracing(tmp_path):
    d = str(tmp_path / "log")
    df, _ = poison_graph(OverloadPolicy(error_budget=2), poison_at=(3,),
                         n=10, trace_dir=d)
    df.run_and_wait_end()
    logs = [json.load(open(os.path.join(d, f))) for f in os.listdir(d)]
    check = next(v for v in logs if v["node"].endswith("check.0"))
    assert check["quarantined"] == 1


def test_shed_counter_in_tracing(tmp_path, inbox_kind):
    d = str(tmp_path / "log")
    delivered = [0]

    def consume(rows):
        if rows is not None and len(rows):
            delivered[0] += 1
            time.sleep(0.005)

    df = Dataflow("tr", capacity=4,
                  overload=OverloadPolicy(shed="shed_oldest"),
                  trace_dir=d)
    build_pipeline(df, [Source(batches=make_batches(100), schema=SCHEMA),
                        Sink(consume, vectorized=True)])
    df.run_and_wait_end()
    logs = [json.load(open(os.path.join(d, f))) for f in os.listdir(d)]
    sink = next(v for v in logs if v["node"].endswith("sink.0"))
    assert sink["shed"] == 100 - delivered[0] > 0


# -------------------------------------------------- builder / MultiPipe

def test_with_error_budget_through_multipipe():
    """Fluent path end to end: withErrorBudget on a builder, OverloadPolicy
    on the MultiPipe, dead letters inspectable on the pipe after wait()."""
    got = [0]

    def check(b):
        if (b["value"] < 0).any():
            raise ValueError("poison batch")

    def consume(rows):
        if rows is not None and len(rows):
            got[0] += 1

    pipe = (MultiPipe("robust", overload=OverloadPolicy())
            .add_source(Source_Builder()
                        .withBatches(make_batches(16, poison_at=(5,)))
                        .withSchema(SCHEMA).build())
            .add(Map_Builder(check).vectorized().withErrorBudget(2)
                 .withName("check").build())
            .add_sink(Sink_Builder(consume).vectorized().build()))
    pipe.run_and_wait_end()
    assert got[0] == 15
    assert len(pipe.dead_letters) == 1
    assert pipe.dead_letters[0].node == "check.0"
    assert pipe.shed_counts() == {}


def test_with_error_budget_survives_chaining():
    """chain() fuses operators into one thread; the tightest member
    budget must govern the fused node, not vanish."""
    got = [0]

    def check(b):
        if (b["value"] < 0).any():
            raise ValueError("poison batch")

    def consume(rows):
        if rows is not None and len(rows):
            got[0] += 1

    pipe = (MultiPipe("chained")
            .add_source(Source_Builder()
                        .withBatches(make_batches(12, poison_at=(4,)))
                        .withSchema(SCHEMA).build())
            .add(Map_Builder(check).vectorized().withErrorBudget(2)
                 .withName("check").build())
            .chain(Map_Builder(lambda b: b).vectorized()
                   .withName("fwd").build())
            .add_sink(Sink_Builder(consume).vectorized().build()))
    pipe.run_and_wait_end()
    assert got[0] == 11
    assert len(pipe.dead_letters) == 1      # the chained budget held


def test_with_error_budget_validation():
    with pytest.raises(ValueError, match=">= 0"):
        Map_Builder(lambda b: b).withErrorBudget(-1)


def test_shell_nodes_exempt_from_policy_budget():
    """Framework shells (emitters/collectors/ordering merges) never
    inherit the dataflow-wide budget: an error there is a framework bug,
    and quarantining it would silently corrupt the stream."""
    from windflow_tpu.runtime.emitters import Collector, StandardEmitter
    from windflow_tpu.runtime.ordering import OrderingMode, OrderingNode

    from windflow_tpu.core.windows import WindowSpec, WinType
    from windflow_tpu.core.winseq import WinSeqCore
    from windflow_tpu.ops.functions import Reducer
    from windflow_tpu.patterns.win_seq import WinSeqNode
    from windflow_tpu.runtime.comb import make_comb

    df = Dataflow(overload=OverloadPolicy(error_budget=5))
    win_node = WinSeqNode(WinSeqCore(WindowSpec(4, 2, WinType.CB),
                                     Reducer("sum")))
    for exempt in (StandardEmitter(2), Collector(),
                   OrderingNode(2, OrderingMode.TS),
                   # window cores fold rows into state before raising:
                   # quarantining them would corrupt windows silently
                   win_node):
        assert exempt.quarantine_exempt
        assert df._error_budget_of(exempt) == 0
    # worker nodes DO inherit it
    from windflow_tpu.patterns.basic import Map
    worker = Map(lambda b: b, vectorized=True)._make_replica(0)
    assert df._error_budget_of(worker) == 5
    # a Comb containing any exempt stage inherits fail-fast; a Comb of
    # pure user operators does not
    w2 = Map(lambda b: b, vectorized=True)._make_replica(0)
    assert make_comb([w2, StandardEmitter(2)]).quarantine_exempt
    assert not make_comb(
        [Map(lambda b: b, vectorized=True)._make_replica(0),
         Map(lambda b: b, vectorized=True)._make_replica(0)]
    ).quarantine_exempt


def test_union_rejects_conflicting_policies():
    from windflow_tpu import union_multipipes

    def pipe(policy):
        return (MultiPipe("b", overload=policy)
                .add_source(Source_Builder().withBatches(make_batches(2))
                            .withSchema(SCHEMA).build()))

    with pytest.raises(ValueError, match="conflicting overload"):
        union_multipipes(pipe(OverloadPolicy(shed="shed_oldest")),
                         pipe(OverloadPolicy(put_deadline=2.0)))
    # identical / partially-unset policies merge fine
    merged = union_multipipes(pipe(OverloadPolicy(error_budget=1)),
                              pipe(None))
    assert merged.overload.error_budget == 1


# ------------------------------------- robustness counters, end to end

def test_robustness_counters_end_to_end(inbox_kind, tmp_path):
    """ISSUE 4 satellite: one graph that BOTH sheds (overloaded sink)
    and quarantines (poison within budget) must surface the counters in
    all three observability views — NodeStats.snapshot() (the end-of-run
    .log), the live sampler's metrics.jsonl, and events.jsonl — with
    every line schema-valid (tests/obs_schema.py)."""
    from obs_schema import validate_event, validate_file, validate_sample
    d = str(tmp_path / "e2e")
    delivered = [0]

    def check(b):
        if (b["value"] < 0).any():
            raise ValueError("poison batch")

    def consume(rows):
        if rows is not None and len(rows):
            delivered[0] += 1
            time.sleep(0.004)

    # a paced source keeps the map's inbox drained (the instant map
    # never sheds), so every poison batch deterministically reaches
    # check and quarantines; the slow sink's inbox is the one that
    # overloads and sheds.  Budget exceeds the poison count so the
    # graph always completes.
    batches = make_batches(80, poison_at=tuple(range(0, 80, 10)))

    def gen(shipper):
        for b in batches:
            shipper.push_batch(b.copy())
            time.sleep(0.001)

    df = Dataflow("e2e", capacity=4, trace_dir=d, sample_period=0.005,
                  overload=OverloadPolicy(shed="shed_oldest",
                                          error_budget=80))
    build_pipeline(df, [
        Source(gen, SCHEMA),
        Map(check, name="check", vectorized=True),
        Sink(consume, vectorized=True)])
    df.run_and_wait_end()
    shed_total = sum(df.shed_counts().values())
    assert shed_total > 0 and len(df.dead_letters) >= 1

    # view 1: NodeStats.snapshot() as written to the per-node .log
    logs = {f: json.load(open(os.path.join(d, f)))
            for f in os.listdir(d) if f.endswith(".log")}
    sink_log = next(v for v in logs.values()
                    if v["node"].endswith("sink.0"))
    assert sink_log["shed"] == df.shed_counts()["sink.0"]
    check_log = next(v for v in logs.values()
                     if v["node"].endswith("check.0"))
    assert check_log["quarantined"] == len(df.dead_letters)

    # view 2: the live sampler's metrics.jsonl (schema-valid, and the
    # final sample agrees with the end-of-run accounting)
    mpath = os.path.join(d, "metrics.jsonl")
    assert validate_file(mpath, validate_sample) >= 2
    last = json.loads(open(mpath).read().splitlines()[-1])
    by_node = {n["node"]: n for n in last["nodes"]}
    assert sum(n["shed"] for n in by_node.values()) == shed_total
    assert by_node["check.0"]["quarantined"] == len(df.dead_letters)
    assert last["dead_letters"] == len(df.dead_letters)

    # view 3: events.jsonl carries shed + quarantine events
    epath = os.path.join(d, "events.jsonl")
    assert validate_file(epath, validate_event) > 0
    events = [json.loads(line) for line in open(epath)]
    kinds = {e["event"] for e in events}
    assert {"shed", "quarantine"} <= kinds
    q = next(e for e in events if e["event"] == "quarantine")
    assert q["node"] == "check.0" and q["error"] == "ValueError"
    shed_ev_total = sum(e["n"] for e in events if e["event"] == "shed")
    assert shed_ev_total == shed_total


# ------------------------------------------------------------- slow soak

def _soak_module():
    spec = importlib.util.spec_from_file_location(
        "soak_overload",
        os.path.join(os.path.dirname(os.path.dirname(__file__)),
                     "scripts", "soak_overload.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.mark.slow
def test_overload_soak_small():
    """A small slice of scripts/soak_overload.py (the standalone repro
    harness): randomized policies / capacities / poison patterns, all
    invariants conserved."""
    stats = _soak_module().run_soak(n=60, seed=123)
    assert stats["cases"] == 60
    assert stats["shed_cases"] > 0 and stats["poison_cases"] > 0


@pytest.mark.slow
def test_overload_soak_with_metrics(tmp_path):
    """The soak with the observability layer ON (ISSUE 4 satellite):
    every conservation invariant still holds with the sampler running,
    and the files it leaves behind are schema-valid with live (pre-final)
    samples showing real occupancy."""
    from obs_schema import validate_event, validate_file, validate_sample
    d = str(tmp_path / "soakobs")
    stats = _soak_module().run_soak(n=25, seed=321, trace_dir=d,
                                    sample_period=0.01)
    assert stats["cases"] == 25 and stats["shed_cases"] > 0
    assert validate_file(os.path.join(d, "metrics.jsonl"),
                         validate_sample) >= 25
    assert validate_file(os.path.join(d, "events.jsonl"),
                         validate_event) > 0
    samples = [json.loads(line)
               for line in open(os.path.join(d, "metrics.jsonl"))]
    assert max(n["depth"] for s in samples for n in s["nodes"]) > 0
    assert max(n["shed"] for s in samples for n in s["nodes"]) > 0
