"""Doc/code drift gates (ISSUE 11): the catalogs the code enforces and
the tables the docs promise must list identical ids — a new WF###
diagnostic or event kind that skips its documentation row fails tier-1.
"""

import os
import re

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _read(relpath: str) -> str:
    with open(os.path.join(REPO, relpath)) as f:
        return f.read()


def test_checks_doc_matches_catalog():
    """docs/CHECKS.md's table rows == check.diagnostics.CATALOG, id for
    id — and the doc's severity column matches the catalog severity."""
    from windflow_tpu.check.diagnostics import CATALOG
    doc = _read("docs/CHECKS.md")
    rows = re.findall(r"^\|\s*(WF\d+)\s*\|\s*(\w+)\s*\|", doc, re.M)
    doc_ids = {code for code, _sev in rows}
    assert doc_ids == set(CATALOG), (
        f"docs/CHECKS.md vs CATALOG drift: doc-only "
        f"{sorted(doc_ids - set(CATALOG))}, catalog-only "
        f"{sorted(set(CATALOG) - doc_ids)}")
    for code, sev in rows:
        assert sev == CATALOG[code][0], (
            f"{code}: docs/CHECKS.md says {sev!r}, catalog says "
            f"{CATALOG[code][0]!r}")


def _doc_event_kinds() -> set:
    """Backticked kinds from the first column of the events table in
    docs/OBSERVABILITY.md (rows may combine kinds with `/`)."""
    doc = _read("docs/OBSERVABILITY.md")
    m = re.search(r"^## `events\.jsonl`.*?$(.*?)(?:^## )", doc,
                  re.M | re.S)
    assert m, "events.jsonl section missing from docs/OBSERVABILITY.md"
    kinds = set()
    for line in m.group(1).splitlines():
        line = line.strip()
        if not line.startswith("|"):
            continue
        first = line.split("|")[1]
        if "kind" in first and "`" not in first:
            continue          # header row
        kinds.update(re.findall(r"`([a-z_]+)`", first))
    return kinds


def test_observability_doc_matches_event_kinds():
    from windflow_tpu.obs.events import EVENT_KINDS
    doc_kinds = _doc_event_kinds()
    assert doc_kinds == set(EVENT_KINDS), (
        f"docs/OBSERVABILITY.md vs EVENT_KINDS drift: doc-only "
        f"{sorted(doc_kinds - set(EVENT_KINDS))}, code-only "
        f"{sorted(set(EVENT_KINDS) - doc_kinds)}")


def test_catalog_shape():
    """Catalog invariants the suppression/docs machinery relies on:
    id format, known severities, non-empty titles, family prefixes."""
    from windflow_tpu.check.diagnostics import CATALOG, ERROR, WARNING
    assert CATALOG, "empty catalog"
    for code, (sev, title) in CATALOG.items():
        assert re.fullmatch(r"WF\d{3}", code), code
        assert sev in (ERROR, WARNING), (code, sev)
        assert title.strip(), code
        assert code[2] in "123", f"{code}: unknown family"
