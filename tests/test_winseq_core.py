"""Differential tests: vectorised WinSeqCore vs the brute-force oracle.

Covers CB/TB x NIC/INC x sliding/tumbling/hopping x single/multi key x
chunk sizes x farm-worker PatternConfigs x EOS markers — the same invariant
matrix the reference exercises via src/sum_test_cpu (test_all_cb/tb.cpp).
"""

import numpy as np
import pytest

from windflow_tpu.core.tuples import Schema, batch_from_columns
from windflow_tpu.core.windows import PatternConfig, Role, WindowSpec, WinType
from windflow_tpu.core.winseq import WinSeqCore
from windflow_tpu.ops.functions import Reducer

from oracle import OracleWinSeq

SCHEMA = Schema(value=np.int64)


def run_core(core, stream, chunk):
    """Feed `stream` (list of (key,id,ts,value[,marker])) in chunks; return
    per-key result lists."""
    results = []
    for i in range(0, len(stream), chunk):
        part = stream[i:i + chunk]
        b = batch_from_columns(
            SCHEMA,
            key=[r[0] for r in part], id=[r[1] for r in part],
            ts=[r[2] for r in part], value=[r[3] for r in part])
        b["marker"] = [len(r) > 4 and r[4] for r in part]
        results.append(core.process(b))
    results.append(core.flush())
    out = np.concatenate(results)
    per_key = {}
    for r in out:
        per_key.setdefault(int(r["key"]), []).append(
            (int(r["id"]), int(r["ts"]), int(r["value"])))
    return per_key


def run_oracle(oracle, stream):
    res = []
    for r in stream:
        marker = r[4] if len(r) > 4 else False
        res += oracle.push(r[0], r[1], r[2], marker=marker, value=r[3])
    res += oracle.eos()
    per_key = {}
    for r in res:
        per_key.setdefault(int(r["key"]), []).append(
            (int(r["id"]), int(r["ts"]), int(r["value"])))
    return per_key


def nic_sum(key, gwid, rows):
    return sum(r["value"] for r in rows)


def inc_sum(key, gwid, row, acc):
    if row is None:
        return 0
    return acc + row["value"]


def make_cb_stream(keys, n, seed=0, interleave=True):
    """Deterministic integer stream like the reference sum_cb Generator:
    ids 0..n-1 per key, value = id (sum_cb.hpp:105-110)."""
    rng = np.random.default_rng(seed)
    stream = []
    if interleave:
        for i in range(n):
            for k in range(keys):
                stream.append((k, i, i * 10 + int(rng.integers(0, 10)), i))
    else:
        for k in range(keys):
            for i in range(n):
                stream.append((k, i, i * 10, i))
    return stream


def make_tb_stream(keys, n, seed=0, max_gap=30):
    """Time-based stream with irregular (possibly gapping/duplicate) ts."""
    rng = np.random.default_rng(seed)
    stream = []
    for k in range(keys):
        ts = 0
        for i in range(n):
            ts += int(rng.integers(0, max_gap))
            stream.append((k, i, ts, i))
    stream.sort(key=lambda r: (r[2], r[0]))
    return stream


CASES = [
    # (win, slide) sliding / tumbling / hopping
    (8, 3), (8, 8), (3, 8), (5, 1), (1, 1), (16, 7),
]


@pytest.mark.parametrize("win,slide", CASES)
@pytest.mark.parametrize("chunk", [1, 7, 1000])
@pytest.mark.parametrize("keys", [1, 3])
def test_cb_nic_matches_oracle(win, slide, chunk, keys):
    stream = make_cb_stream(keys, 100)
    spec = WindowSpec(win, slide, WinType.CB)
    core = WinSeqCore(spec, Reducer("sum"))
    oracle = OracleWinSeq(win, slide, "CB", nic_sum, True)
    assert run_core(core, stream, chunk) == run_oracle(oracle, stream)


@pytest.mark.parametrize("win,slide", CASES)
@pytest.mark.parametrize("chunk", [1, 7, 1000])
def test_cb_inc_matches_oracle(win, slide, chunk):
    stream = make_cb_stream(2, 80)
    spec = WindowSpec(win, slide, WinType.CB)
    core = WinSeqCore(spec, Reducer("sum")).use_incremental()
    oracle = OracleWinSeq(win, slide, "CB", inc_sum, False)
    assert run_core(core, stream, chunk) == run_oracle(oracle, stream)


@pytest.mark.parametrize("win,slide", [(50, 20), (40, 40), (20, 50), (100, 7)])
@pytest.mark.parametrize("chunk", [1, 13, 1000])
@pytest.mark.parametrize("nic", [True, False])
def test_tb_matches_oracle(win, slide, chunk, nic):
    stream = make_tb_stream(2, 120)
    spec = WindowSpec(win, slide, WinType.TB)
    core = WinSeqCore(spec, Reducer("sum"))
    if not nic:
        core.use_incremental()
    oracle = OracleWinSeq(win, slide, "TB", nic_sum if nic else inc_sum, nic)
    assert run_core(core, stream, chunk) == run_oracle(oracle, stream)


@pytest.mark.parametrize("op", ["sum", "count", "min", "max"])
def test_reducers_match_oracle(op):
    stream = make_cb_stream(2, 60, seed=3)
    spec = WindowSpec(10, 4, WinType.CB)
    core = WinSeqCore(spec, Reducer(op))

    def nic(key, gwid, rows):
        vals = [r["value"] for r in rows]
        if op == "sum":
            return sum(vals)
        if op == "count":
            return len(vals)
        if op == "min":
            return min(vals) if vals else np.iinfo(np.int64).max
        return max(vals) if vals else np.iinfo(np.int64).min

    oracle = OracleWinSeq(10, 4, "CB", nic, True)
    assert run_core(core, stream, 17) == run_oracle(oracle, stream)


@pytest.mark.parametrize("role,cfg_t", [
    # farm-worker configs: (id_outer, n_outer, slide_outer, id_inner, n_inner, slide_inner)
    (Role.SEQ, (1, 4, 3, 0, 1, 3)),    # Win_Farm worker 1 of 4 (private slide)
    (Role.SEQ, (3, 4, 3, 0, 1, 3)),
    (Role.PLQ, (0, 1, 2, 1, 3, 2)),    # Pane_Farm PLQ worker
    (Role.WLQ, (0, 1, 4, 2, 4, 4)),    # Pane_Farm WLQ worker
    (Role.MAP, (0, 1, 3, 0, 1, 3)),
])
@pytest.mark.parametrize("chunk", [1, 11, 1000])
def test_pattern_config_roles_match_oracle(role, cfg_t, chunk):
    win, slide = 6, 3
    if role is Role.SEQ:
        # Win_Farm worker: window wid of worker i covers the same ids as
        # global window gwid; private slide = slide * n_outer
        slide_eff = cfg_t[2] * cfg_t[1]
    else:
        slide_eff = slide
    stream = make_cb_stream(3, 90, seed=7)
    spec = WindowSpec(win, slide_eff if role is Role.SEQ else slide, WinType.CB)
    cfg = PatternConfig(*cfg_t)
    mi = (1, 3) if role is Role.MAP else (0, 1)
    core = WinSeqCore(spec, Reducer("sum"), config=cfg, role=role, map_indexes=mi)
    oracle = OracleWinSeq(spec.win_len, spec.slide_len, "CB", nic_sum, True,
                          config=cfg_t, role=role.name, map_indexes=mi)
    assert run_core(core, stream, chunk) == run_oracle(oracle, stream)


@pytest.mark.parametrize("chunk", [1, 9, 1000])
def test_markers_match_oracle(chunk):
    """EOS markers (the last real tuple replayed with marker=True) open and
    fire trailing windows without contributing values."""
    base = make_cb_stream(2, 40)
    # append a marker per key replaying its last tuple
    last = {}
    for r in base:
        last[r[0]] = r
    stream = base + [(k, r[1], r[2], r[3], True) for k, r in sorted(last.items())]
    spec = WindowSpec(7, 2, WinType.CB)
    core = WinSeqCore(spec, Reducer("sum"))
    oracle = OracleWinSeq(7, 2, "CB", nic_sum, True)
    assert run_core(core, stream, chunk) == run_oracle(oracle, stream)


def test_out_of_order_dropped():
    spec = WindowSpec(4, 2, WinType.CB)
    core = WinSeqCore(spec, Reducer("sum"))
    stream = [(0, 0, 0, 0), (0, 1, 1, 1), (0, 5, 5, 5), (0, 2, 2, 2),
              (0, 6, 6, 6), (0, 7, 7, 7)]
    oracle = OracleWinSeq(4, 2, "CB", nic_sum, True)
    assert run_core(core, stream, 3) == run_oracle(oracle, stream)


def test_duplicate_positions():
    spec = WindowSpec(5, 5, WinType.TB)
    core = WinSeqCore(spec, Reducer("sum"))
    stream = [(0, 0, 1, 1), (0, 1, 1, 2), (0, 2, 3, 3), (0, 3, 3, 4),
              (0, 4, 7, 5), (0, 5, 12, 6)]
    oracle = OracleWinSeq(5, 5, "TB", nic_sum, True)
    assert run_core(core, stream, 2) == run_oracle(oracle, stream)


@pytest.mark.parametrize("seed", range(8))
@pytest.mark.parametrize("nic", [True, False])
def test_fuzz_sparse_streams(seed, nic):
    """Sparse/gapping id streams (empty windows, id jumps) vs the oracle —
    the dense-stream cases never exercise empty CB windows."""
    rng = np.random.default_rng(seed)
    win = int(rng.integers(1, 12))
    slide = int(rng.integers(1, 12))
    keys = int(rng.integers(1, 4))
    wt = WinType.CB if seed % 2 == 0 else WinType.TB
    stream = []
    for k in range(keys):
        pos = 0
        for i in range(60):
            pos += int(rng.integers(0, 9))  # gaps and duplicates
            if wt is WinType.CB:
                stream.append((k, pos, int(rng.integers(0, 1000)), i))
            else:
                stream.append((k, i, pos, i))
    rng.shuffle(stream)  # interleave keys; per-key order is preserved by sort
    stream.sort(key=lambda r: (r[1] if wt is WinType.CB else r[2]))
    spec = WindowSpec(win, slide, wt)
    core = WinSeqCore(spec, Reducer("sum"))
    if not nic:
        core.use_incremental()
    oracle = OracleWinSeq(win, slide, wt.name, nic_sum if nic else inc_sum, nic)
    chunk = int(rng.integers(1, 40))
    assert run_core(core, stream, chunk) == run_oracle(oracle, stream)


def test_sum_invariant_totals():
    """The reference's headline invariant: total sum over all windows is
    identical however the stream is chunked (test_all_cb.cpp:171+)."""
    stream = make_cb_stream(4, 200)
    totals = set()
    for chunk in (1, 3, 64, 10000):
        spec = WindowSpec(10, 5, WinType.CB)
        core = WinSeqCore(spec, Reducer("sum"))
        per_key = run_core(core, stream, chunk)
        totals.add(sum(v for rs in per_key.values() for _, _, v in rs))
        # per-key results arrive in wid order 0,1,2,... (Consumer check,
        # sum_cb.hpp:146-150)
        for rs in per_key.values():
            assert [r[0] for r in rs] == list(range(len(rs)))
    assert len(totals) == 1
