"""The full test_all differential harness — the port of the reference's
``src/sum_test_cpu/test_all_cb.cpp`` / ``test_all_tb.cpp`` (and the GPU
mirrors): run Win_Seq first on a deterministic stream to obtain the
reference totals, then run EVERY farm / nesting / device composition with
**randomized parallelism degrees** on the *same* stream and assert equal
totals — plus the per-key in-order delivery counter the reference's
Consumer asserts (``check_counters[key] == id``, sum_cb.hpp:146-150)."""

import numpy as np
import pytest

from windflow_tpu.core.windows import WinType
from windflow_tpu.ops.functions import Reducer
from windflow_tpu.patterns.key_farm import KeyFarm
from windflow_tpu.patterns.nesting import KeyFarmOf, WinFarmOf
from windflow_tpu.patterns.pane_farm import PaneFarm
from windflow_tpu.patterns.win_farm import WinFarm
from windflow_tpu.patterns.win_mapreduce import WinMapReduce
from windflow_tpu.patterns.win_seq import WinSeq
from windflow_tpu.patterns.win_seq_tpu import (KeyFarmTPU, PaneFarmTPU,
                                               WinFarmTPU, WinMapReduceTPU,
                                               WinSeqTPU)

from test_farms import cb_stream_batches, tb_stream_batches, run_windowed

KEYS, N = 3, 140
WIN, SLIDE = 12, 4          # sliding (pane-decomposable: slide < win)
RNG = np.random.default_rng(20260729)


def rand_deg(lo=2, hi=4):
    """Randomized parallelism degrees, re-drawn per composition — the
    mt19937 re-draws of test_pipe_*.cpp:233-264."""
    return int(RNG.integers(lo, hi + 1))


def stream(wt):
    return (cb_stream_batches(KEYS, N) if wt is WinType.CB
            else tb_stream_batches(KEYS, N))


def total_of(per_key):
    return sum(v for rs in per_key.values() for _, _, v in rs)


def assert_in_order(per_key):
    """Per-key result ids must arrive consecutively from their first id
    (the Consumer's check_counters assertion)."""
    for key, rs in per_key.items():
        ids = [i for i, _, _ in rs]
        assert ids == sorted(ids), f"key {key} results out of order"


def compositions(wt, inc):
    """Every composition of the test_all matrix, degrees re-drawn each
    call.  `inc`: incremental (INC) vs non-incremental (NIC) user function
    — Reducer serves as both, like the reference's sum functors."""
    w, s = WIN, SLIDE
    red = lambda: Reducer("sum")
    kw = dict(incremental=inc) if inc is not None else {}

    def pf(ordered=True):
        return PaneFarm(red(), red(), w, s, wt, plq_degree=rand_deg(),
                        wlq_degree=rand_deg(),
                        plq_incremental=inc, wlq_incremental=inc)

    def wmr(ordered=True):
        return WinMapReduce(red(), red(), w, s, wt, map_degree=rand_deg(),
                            reduce_degree=rand_deg(2, 2),
                            map_incremental=inc, reduce_incremental=inc)

    return {
        "wf": WinFarm(red(), w, s, wt, pardegree=rand_deg(), **kw),
        "kf": KeyFarm(red(), w, s, wt, pardegree=rand_deg(), **kw),
        "pf": pf(),
        "wmr": wmr(),
        "wf+pf": WinFarmOf(pf(), pardegree=2),
        "wf+wmr": WinFarmOf(wmr(), pardegree=2),
        "kf+pf": KeyFarmOf(pf(), pardegree=rand_deg()),
        "kf+wmr": KeyFarmOf(wmr(), pardegree=rand_deg()),
    }


@pytest.mark.parametrize("wt", [WinType.CB, WinType.TB],
                         ids=["cb", "tb"])
@pytest.mark.parametrize("inc", [False, True], ids=["nic", "inc"])
def test_all_host_compositions(wt, inc):
    ref = run_windowed(WinSeq(Reducer("sum"), WIN, SLIDE, wt,
                              incremental=inc), stream(wt))
    assert_in_order(ref)
    ref_total = total_of(ref)
    assert ref_total > 0
    for name, comp in compositions(wt, inc).items():
        got = run_windowed(comp, stream(wt))
        assert total_of(got) == ref_total, f"{name} total mismatch"
        if getattr(comp, "ordered", True):
            assert_in_order(got)


@pytest.mark.parametrize("wt", [WinType.CB, WinType.TB],
                         ids=["cb", "tb"])
def test_all_device_compositions(wt):
    """The sum_test_gpu test_all mirror: every device-batched composition
    equals the host Win_Seq on the same stream."""
    ref_total = total_of(run_windowed(
        WinSeq(Reducer("sum"), WIN, SLIDE, wt), stream(wt)))
    device = {
        "seq_tpu": WinSeqTPU(Reducer("sum"), WIN, SLIDE, wt, batch_len=32),
        "wf_tpu": WinFarmTPU(Reducer("sum"), WIN, SLIDE, wt,
                             pardegree=rand_deg(), batch_len=32),
        "kf_tpu": KeyFarmTPU(Reducer("sum"), WIN, SLIDE, wt,
                             pardegree=rand_deg(), batch_len=32),
        "pf_tpu_plq": PaneFarmTPU(Reducer("sum"), Reducer("sum"), WIN, SLIDE,
                                  wt, plq_degree=rand_deg(), wlq_degree=2,
                                  wlq_on_device=False, batch_len=32),
        "pf_tpu_wlq": PaneFarmTPU(Reducer("sum"), Reducer("sum"), WIN, SLIDE,
                                  wt, plq_degree=2, wlq_degree=rand_deg(),
                                  plq_on_device=False, batch_len=32),
        "wmr_tpu_map": WinMapReduceTPU(Reducer("sum"), Reducer("sum"), WIN,
                                       SLIDE, wt, map_degree=rand_deg(),
                                       batch_len=32),
        "wmr_tpu_red": WinMapReduceTPU(Reducer("sum"), Reducer("sum"), WIN,
                                       SLIDE, wt, map_degree=2,
                                       map_on_device=False,
                                       reduce_on_device=True, batch_len=32),
        # nesting with device inner patterns (the reference's GPU nesting
        # ctors III/IV, win_farm_gpu.hpp:227+, key_farm_gpu.hpp:167-334)
        "kf+pf_tpu": KeyFarmOf(
            PaneFarmTPU(Reducer("sum"), Reducer("sum"), WIN, SLIDE, wt,
                        plq_degree=2, wlq_degree=2, wlq_on_device=False,
                        batch_len=16), pardegree=2),
        "wf+wmr_tpu": WinFarmOf(
            WinMapReduceTPU(Reducer("sum"), Reducer("sum"), WIN, SLIDE, wt,
                            map_degree=2, reduce_on_device=False,
                            batch_len=16), pardegree=2),
    }
    for name, comp in device.items():
        got = run_windowed(comp, stream(wt))
        assert total_of(got) == ref_total, f"{name} total mismatch"


def test_all_repeated_draws_stable():
    """Re-drawing degrees (the -r flag loop of the reference harness) keeps
    totals identical across 3 rounds."""
    ref_total = total_of(run_windowed(
        WinSeq(Reducer("sum"), WIN, SLIDE, WinType.CB), stream(WinType.CB)))
    for _ in range(3):
        for name, comp in compositions(WinType.CB, None).items():
            got = run_windowed(comp, stream(WinType.CB))
            assert total_of(got) == ref_total, name
