"""Recovery layer tests (windflow_tpu/recovery/, docs/ROBUSTNESS.md
"Recovery"): epoch checkpoints, supervised restart, and the differential
oracle — a graph that crashes a stateful worker mid-stream and recovers
must produce byte-identical window results to the never-crashed run.
"""

import os
import threading
import time

import numpy as np
import pytest

from windflow_tpu import (MultiPipe, RecoveryPolicy, Reducer, Sink, Source,
                          WinFarm, WinSeq, union_multipipes)
from windflow_tpu.core.tuples import Schema
from windflow_tpu.core.windows import WinType
from windflow_tpu.recovery import CheckpointStore
from windflow_tpu.recovery.store import resolve_state
from windflow_tpu.runtime.engine import Dataflow
from windflow_tpu.runtime.farm import build_pipeline

SCHEMA = Schema(value=np.int64)


def keyed_batches(n_batches=40, rows=50, n_keys=5, seed=7):
    """Per-key dense ids / monotone ts — the pristine-source contract CB
    windows want."""
    rng = np.random.default_rng(seed)
    ctr = {}
    for _ in range(n_batches):
        b = np.zeros(rows, dtype=SCHEMA.dtype())
        keys = rng.integers(0, n_keys, rows)
        b["key"] = keys
        b["value"] = rng.integers(0, 100, rows)
        for i, k in enumerate(keys.tolist()):
            b["id"][i] = ctr.get(k, 0)
            ctr[k] = ctr.get(k, 0) + 1
        b["ts"] = b["id"]
        yield b


def install_kill_point(node, kill_at: int, exc=RuntimeError):
    """Monkey-wrap ``node.svc`` to raise once on its ``kill_at``-th call
    — the transient-fault model (OOM, device error, preemption): the
    same batch succeeds when replayed."""
    orig = node.svc
    state = {"n": 0, "fired": False}

    def svc(batch, channel=0):
        state["n"] += 1
        if not state["fired"] and state["n"] == kill_at:
            state["fired"] = True
            raise exc(f"injected crash at svc #{kill_at}")
        return orig(batch, channel)

    node.svc = svc
    return state


def find_node(df, prefix):
    nodes = [n for n in df.nodes if n.name.startswith(prefix)]
    assert nodes, f"no node named {prefix}* in {[n.name for n in df.nodes]}"
    return nodes[0]


def rows_of(out):
    return [tuple(int(x) for x in r) for r in out]


# --------------------------------------------------------------- policy


def test_policy_validation():
    with pytest.raises(ValueError):
        RecoveryPolicy(epoch_batches=0)
    with pytest.raises(ValueError):
        RecoveryPolicy(epoch_period=-1)
    with pytest.raises(ValueError):
        RecoveryPolicy(retain=0)
    with pytest.raises(ValueError):
        RecoveryPolicy(replay_capacity=0)
    with pytest.raises(TypeError):
        Dataflow("x", recovery=object())
    assert RecoveryPolicy(epoch_batches=5).agrees_with(
        RecoveryPolicy(epoch_batches=5))
    assert not RecoveryPolicy(epoch_batches=5).agrees_with(
        RecoveryPolicy(epoch_batches=6))


def test_unset_recovery_is_seed_identical_wiring():
    """No policy => no recovery records, no supervisor, no envelopes."""
    out = []
    df = Dataflow("plain", capacity=8)
    build_pipeline(df, [
        Source(batches=lambda i: keyed_batches(4), name="src"),
        Sink(lambda r: out.append(r) if r is not None else None,
             name="sink"),
    ])
    df.run_and_wait_end()
    assert df._supervisor is None
    assert all(n._recov is None for n in df.nodes)


# ------------------------------------------------- differential restarts


def winseq_pipe(out, recovery=None, nic=True):
    """Source -> WinSeq(sum, CB 8/4) -> Sink as a manual Dataflow.
    ``nic=True`` uses an arbitrary host function (general per-key core);
    ``nic=False`` a Reducer (vectorised multi-key core)."""
    if nic:
        fn = WinSeq(lambda key, gwid, rows: (int(rows["value"].sum()),),
                    win_len=8, slide_len=4,
                    result_fields={"value": np.int64})
    else:
        fn = WinSeq(Reducer("sum", "value"), win_len=8, slide_len=4)
    df = Dataflow("t", capacity=8, recovery=recovery)
    build_pipeline(df, [
        Source(batches=lambda i: keyed_batches(), name="src"),
        fn,
        Sink(lambda r: out.append((int(r["key"]), int(r["id"]),
                                   int(r["value"])))
             if r is not None else None, name="sink"),
    ])
    return df


@pytest.mark.parametrize("nic", [True, False])
@pytest.mark.parametrize("kill_at", [2, 7, 17, 39])
def test_winseq_crash_matches_uncrashed_oracle(nic, kill_at):
    """Kill-point mid-window, then the restored + replayed run must be
    byte-identical to the differential oracle (the same pipeline, never
    crashed)."""
    oracle = []
    winseq_pipe(oracle, nic=nic).run_and_wait_end(timeout=120)
    got = []
    pol = RecoveryPolicy(epoch_batches=5, restart_backoff=0.01)
    df = winseq_pipe(got, recovery=pol, nic=nic)
    install_kill_point(find_node(df, "win_seq"), kill_at)
    df.run_and_wait_end(timeout=120)
    assert got == oracle


def test_flush_crash_recovers():
    """A crash in the EOS flush (eosnotify) restores, replays, and
    re-flushes — still byte-identical."""
    oracle = []
    winseq_pipe(oracle).run_and_wait_end(timeout=120)
    got = []
    df = winseq_pipe(got, recovery=RecoveryPolicy(epoch_batches=5,
                                                  restart_backoff=0.01))
    node = find_node(df, "win_seq")
    orig = node.eosnotify
    fired = []

    def eosnotify():
        if not fired:
            fired.append(1)
            raise RuntimeError("injected flush crash")
        return orig()

    node.eosnotify = eosnotify
    df.run_and_wait_end(timeout=120)
    assert got == oracle


def test_crash_without_recovery_still_fails():
    got = []
    df = winseq_pipe(got)
    install_kill_point(find_node(df, "win_seq"), 5)
    with pytest.raises(RuntimeError, match="injected crash"):
        df.run_and_wait_end(timeout=120)


def test_restart_budget_exhausted_fails_like_seed():
    """A persistent (non-transient) fault drains the restart budget and
    then propagates exactly like the un-supervised engine."""
    got = []
    df = winseq_pipe(got, recovery=RecoveryPolicy(
        epoch_batches=5, max_restarts=2, restart_backoff=0.001))
    node = find_node(df, "win_seq")
    orig = node.svc
    state = {"n": 0}

    def svc(batch, channel=0):
        state["n"] += 1
        if state["n"] >= 10:    # fails on every call from then on
            raise RuntimeError("persistent fault")
        return orig(batch, channel)

    node.svc = svc
    with pytest.raises(RuntimeError, match="persistent fault"):
        df.run_and_wait_end(timeout=120)


def farm_rows(out):
    return sorted(rows_of(out))


def test_winfarm_worker_crash_differential():
    """Parallel stateful workers: kill one Win_Farm worker mid-stream;
    recovered results match the uncrashed oracle (sorted by (key, id):
    worker->collector interleave is scheduling-dependent either way,
    per-key order is pinned by the dense result ids)."""

    def build(out, recovery=None):
        pipe = MultiPipe("farm", capacity=8, recovery=recovery)
        pipe.add_source(Source(batches=lambda i: keyed_batches(),
                               name="src"))
        pipe.add(WinFarm(Reducer("sum", "value"), win_len=8, slide_len=4,
                         pardegree=2, name="wf"))
        pipe.add_sink(Sink(
            lambda r: out.append((int(r["key"]), int(r["id"]),
                                  int(r["value"])))
            if r is not None else None, name="sink"))
        return pipe

    oracle = []
    build(oracle).run_and_wait_end(timeout=120)
    got = []
    pipe = build(got, recovery=RecoveryPolicy(epoch_batches=5,
                                              restart_backoff=0.01))
    df = pipe._build()
    install_kill_point(find_node(df, "wf.1"), 9)
    pipe.run()
    pipe.wait(timeout=120)
    assert farm_rows(got) == farm_rows(oracle)


@pytest.mark.parametrize("victim", ["w", "u.order_merge"])
def test_union_multi_input_alignment_and_crash(victim):
    """Two sources => epoch barriers align across merged inputs; a
    mid-stream crash still matches the oracle — both at the window
    stage and at the multi-input ordering merge itself (the node whose
    snapshot cut actually holds items back; its journal contains
    held-at-commit items, the restore path's hardest case)."""

    def monotone_batches(parity, n_batches=20, rows=40, n_keys=3, seed=1):
        """Globally ts-monotone per source (the union merge's global
        watermark contract), disjoint ts parity across the two sources
        so the merged order is fully deterministic."""
        rng = np.random.default_rng(seed)
        t = parity
        for _ in range(n_batches):
            b = np.zeros(rows, dtype=SCHEMA.dtype())
            b["key"] = rng.integers(0, n_keys, rows)
            b["value"] = rng.integers(0, 100, rows)
            b["ts"] = t + 2 * np.arange(rows)
            b["id"] = b["ts"]
            t += 2 * rows
            yield b

    def build(out, recovery=None):
        a = MultiPipe("a").add_source(Source(
            batches=lambda i: monotone_batches(0, seed=1), name="src_a"))
        b = MultiPipe("b").add_source(Source(
            batches=lambda i: monotone_batches(1, seed=2), name="src_b"))
        u = union_multipipes(a, b, name="u")
        u.recovery = recovery
        u.add(WinSeq(Reducer("sum", "value"), win_len=6, slide_len=6,
                     win_type=WinType.TB, name="w"))
        u.add_sink(Sink(
            lambda r: out.append((int(r["key"]), int(r["id"]),
                                  int(r["value"])))
            if r is not None else None, name="sink"))
        return u

    oracle = []
    build(oracle).run_and_wait_end(timeout=120)
    got = []
    pipe = build(got, recovery=RecoveryPolicy(epoch_batches=4,
                                              restart_backoff=0.01))
    df = pipe._build()
    install_kill_point(find_node(df, victim), 11)
    pipe.run_and_wait_end(timeout=120)
    assert farm_rows(got) == farm_rows(oracle)


def test_accumulator_crash_differential():
    from windflow_tpu.patterns.basic import Accumulator

    def build(out, recovery=None):
        acc = Accumulator(lambda row, a: a.__setitem__(
            "value", a["value"] + row["value"]), SCHEMA, name="acc")
        df = Dataflow("acc", capacity=8, recovery=recovery)
        build_pipeline(df, [
            Source(batches=lambda i: keyed_batches(n_batches=15),
                   name="src"),
            acc,
            Sink(lambda r: out.append((int(r["key"]), int(r["id"]),
                                       int(r["value"])))
                 if r is not None else None, name="sink"),
        ])
        return df

    oracle = []
    build(oracle).run_and_wait_end(timeout=120)
    got = []
    df = build(got, recovery=RecoveryPolicy(epoch_batches=4,
                                            restart_backoff=0.01))
    install_kill_point(find_node(df, "acc"), 8)
    df.run_and_wait_end(timeout=120)
    assert got == oracle


def _device_pipe(out, recovery=None, **tpu_kw):
    from windflow_tpu.patterns.win_seq_tpu import WinSeqTPU
    df = Dataflow("dev", capacity=8, recovery=recovery)
    build_pipeline(df, [
        Source(batches=lambda i: keyed_batches(n_batches=20),
               name="src"),
        WinSeqTPU(Reducer("sum", "value"), win_len=8, slide_len=4,
                  batch_len=16, name="wtpu", **tpu_kw),
        Sink(lambda r: out.append((int(r["key"]), int(r["id"]),
                                   int(r["value"])))
             if r is not None else None, name="sink"),
    ])
    return df


def test_resident_core_crash_differential(monkeypatch):
    """Resident-ring window core: the epoch snapshot drains the launch
    queue and captures the ring via the async device->host handle
    (ops/resident.RingSnapshot); a crash mid-stream restores the ring +
    host bookkeeping and replays to oracle-identical results.
    WF_NO_NATIVE_CORE pins the recoverable Python resident core (the
    C++ core declines snapshots, patterns/native_core.py)."""
    monkeypatch.setenv("WF_NO_NATIVE_CORE", "1")
    oracle = []
    _device_pipe(oracle).run_and_wait_end(timeout=300)
    got = []
    df = _device_pipe(got, recovery=RecoveryPolicy(epoch_batches=4,
                                                   restart_backoff=0.01))
    from windflow_tpu.patterns.win_seq_tpu import ResidentWinSeqCore
    assert isinstance(find_node(df, "wtpu").core, ResidentWinSeqCore)
    install_kill_point(find_node(df, "wtpu"), 9)
    df.run_and_wait_end(timeout=300)
    assert got == oracle


def test_resident_core_crash_without_ring_snapshot(monkeypatch):
    """snapshot_rings=False restores by rebasing the ring from the
    host-live archive rows instead of the device->host copy."""
    monkeypatch.setenv("WF_NO_NATIVE_CORE", "1")
    oracle = []
    _device_pipe(oracle).run_and_wait_end(timeout=300)
    got = []
    df = _device_pipe(got, recovery=RecoveryPolicy(
        epoch_batches=4, restart_backoff=0.01, snapshot_rings=False))
    install_kill_point(find_node(df, "wtpu"), 13)
    df.run_and_wait_end(timeout=300)
    assert got == oracle


def test_restaging_core_crash_differential():
    """Segment-restaging device core (float sum stays off the resident
    path): the executor keeps no cross-launch state, so the snapshot is
    the host bookkeeping alone plus a pre-snapshot drain."""
    oracle = []
    _device_pipe(oracle, use_resident=False).run_and_wait_end(timeout=300)
    got = []
    df = _device_pipe(got, use_resident=False,
                      recovery=RecoveryPolicy(epoch_batches=4,
                                              restart_backoff=0.01))
    from windflow_tpu.patterns.win_seq_tpu import DeviceWinSeqCore
    assert isinstance(find_node(df, "wtpu").core, DeviceWinSeqCore)
    install_kill_point(find_node(df, "wtpu"), 9)
    df.run_and_wait_end(timeout=300)
    assert got == oracle


def _native_pipe_or_skip(out, **kw):
    """A `_device_pipe` whose window node routed to the C++ core, or
    skip (no lib / routing picked another core on this host)."""
    from windflow_tpu.native import enabled
    if enabled() is None:
        pytest.skip("native library not built")
    from windflow_tpu.patterns.native_core import NativeResidentCore
    df = _device_pipe(out, **kw)
    node = find_node(df, "wtpu")
    if not isinstance(node.core, NativeResidentCore):
        pytest.skip("routing did not pick the native core here")
    return df, node


def test_native_core_crash_differential():
    """ISSUE 17 acceptance: with the state-ABI .so the C++ core is a
    first-class recovery citizen — a kill-point crash restores the
    native state blob and replays to byte-identical output vs the
    uncrashed oracle (no WF_NO_NATIVE_CORE pin: the native tier itself
    is under test)."""
    oracle = []
    df0, node0 = _native_pipe_or_skip(oracle)
    if not node0.core.has_state_abi:
        pytest.skip("loaded .so lacks the state ABI")
    df0.run_and_wait_end(timeout=300)
    got = []
    df, node = _native_pipe_or_skip(
        got, recovery=RecoveryPolicy(epoch_batches=4,
                                     restart_backoff=0.01))
    install_kill_point(node, 9)
    df.run_and_wait_end(timeout=300)
    assert got == oracle


def test_native_core_stale_so_declines_snapshot(monkeypatch):
    """A pre-ABI .so (simulated via the binding flags) declines exactly
    as before the ABI existed: the first checkpoint marks the node
    unrecoverable (SnapshotUnsupported), so a crash fails like the seed
    engine instead of restoring silently-wrong state — while a no-crash
    run of the same stale configuration is output-identical."""
    from windflow_tpu.runtime.node import SnapshotUnsupported

    oracle = []
    df0, _node0 = _native_pipe_or_skip(oracle)
    df0.run_and_wait_end(timeout=300)

    # default execution unchanged on the stale flags
    plain = []
    dfp, nodep = _native_pipe_or_skip(plain)
    nodep.core.has_state_abi = False
    nodep.core.keyed_migratable = False
    dfp.run_and_wait_end(timeout=300)
    assert plain == oracle

    got = []
    df, node = _native_pipe_or_skip(
        got, recovery=RecoveryPolicy(epoch_batches=4,
                                     restart_backoff=0.01))
    node.core.has_state_abi = False
    node.core.keyed_migratable = False
    with pytest.raises(SnapshotUnsupported, match="state ABI"):
        node.state_snapshot()
    install_kill_point(node, 9)
    with pytest.raises(RuntimeError, match="injected crash"):
        df.run_and_wait_end(timeout=300)


def test_replay_does_not_duplicate_dead_letters():
    """A poison batch quarantined after the last checkpoint re-raises
    during journal replay: the budget is spent again (it was restored
    with the snapshot) but the dead letter is NOT recorded twice."""
    from windflow_tpu.patterns.basic import Map

    batches = list(keyed_batches(n_batches=12))
    poison_sum = int(batches[4]["id"].sum())   # content-based: replay-safe

    def poison_map(batch):
        if int(batch["id"].sum()) == poison_sum:
            raise ValueError("poison")

    m = Map(poison_map, vectorized=True, name="m")
    m.error_budget = 1
    got = []
    df = Dataflow("q", capacity=8,
                  recovery=RecoveryPolicy(epoch_batches=3,
                                          restart_backoff=0.005))
    build_pipeline(df, [
        Source(batches=lambda i: iter(batches), name="src"),
        m,
        Sink(lambda r: got.append(1) if r is not None else None,
             name="sink"),
    ])
    node = find_node(df, "m.0")
    orig, st = node.svc, {"n": 0, "fired": False}

    def svc(batch, channel=0):
        st["n"] += 1
        if not st["fired"] and st["n"] == 8:
            st["fired"] = True
            raise RuntimeError("injected crash")
        return orig(batch, channel)

    node.svc = svc
    df.run_and_wait_end(timeout=120)
    poison = [d for d in df.dead_letters if "poison" in str(d.error)]
    assert len(poison) == 1, df.dead_letters


def test_sink_not_restarted_by_default():
    """A sink has no downstream to dedup a replay, so a sink crash fails
    the graph even with recovery on — unless the pattern explicitly
    opts in (idempotent sinks)."""

    def build(opt_in):
        got = []
        sink = Sink(lambda r: got.append(r), name="sink")
        if opt_in:
            sink.recoverable = True   # propagated to replicas (farm.py)
        df = Dataflow("s", capacity=8,
                      recovery=RecoveryPolicy(epoch_batches=5,
                                              restart_backoff=0.005))
        build_pipeline(df, [
            Source(batches=lambda i: keyed_batches(n_batches=10),
                   name="src"), sink])
        install_kill_point(find_node(df, "sink"), 4)
        return df

    with pytest.raises(RuntimeError, match="injected crash"):
        build(opt_in=False).run_and_wait_end(timeout=120)
    build(opt_in=True).run_and_wait_end(timeout=120)   # restarts fine


# ------------------------------------------------------ checkpoint store


def test_checkpoint_store_roundtrip_and_retention(tmp_path):
    store = CheckpointStore(str(tmp_path / "ck"), retain=2)
    for e in (1, 2, 3):
        n = store.save_blob(e, "pipe_01_w", {"arr": np.arange(e)})
        assert n > 0
        store.commit(e, {"pipe_01_w": {"bytes": n}})
    assert store.epochs() == [2, 3]          # retain=2 pruned epoch 1
    epoch, manifest = store.latest_complete()
    assert epoch == 3 and not manifest["partial"]
    got = store.load(3, "pipe_01_w")
    np.testing.assert_array_equal(got["arr"], np.arange(3))


def test_checkpoint_store_manifest_written_last(tmp_path):
    store = CheckpointStore(str(tmp_path / "ck"), retain=4)
    store.save_blob(1, "n", {"x": 1})
    # no commit yet: the epoch is invisible (a torn checkpoint can never
    # be mistaken for a complete one)
    assert store.epochs() == []
    assert store.latest_complete() is None
    store.commit(1, {"n": {"bytes": 1}})
    assert store.epochs() == [1]


def test_durable_checkpoints_written_by_supervisor(tmp_path):
    ckdir = str(tmp_path / "ck")
    got = []
    pol = RecoveryPolicy(epoch_batches=5, checkpoint_dir=ckdir, retain=3)
    df = winseq_pipe(got, recovery=pol, nic=False)
    df.run_and_wait_end(timeout=120)
    store = CheckpointStore(ckdir, retain=3)
    done = store.epochs()
    assert done, "no sealed checkpoint epochs on disk"
    epoch, manifest = store.latest_complete()
    assert not manifest["partial"]
    # the window worker's blob restores into a core that reproduces the
    # remaining stream — here just prove it unpickles to the right shape
    wid = [k for k in manifest["nodes"] if "win_seq" in k]
    assert wid and manifest["nodes"][wid[0]]["bytes"] > 0
    state = store.load(epoch, wid[0])
    assert "core" in state


def test_resolve_state_materialises_lazy_handles():
    class Lazy:
        def resolve(self):
            return {"rings": (np.ones(3),), "KP": 1, "cap": 4}

    out = resolve_state({"a": Lazy(), "b": [Lazy(), 2], "c": 5})
    assert out["c"] == 5 and out["b"][1] == 2
    np.testing.assert_array_equal(out["a"]["rings"][0], np.ones(3))


# ------------------------------------------------------- wait() satellites


def test_wait_timeout_bounds_hung_graph():
    df = Dataflow("hang", capacity=4)
    build_pipeline(df, [
        Source(batches=lambda i: keyed_batches(n_batches=30), name="src"),
        Sink(lambda r: time.sleep(0.2) if r is not None else None,
             vectorized=True, name="slow"),
    ])
    df.run()
    t0 = time.monotonic()
    with pytest.raises(TimeoutError, match="still running"):
        df.wait(timeout=0.4)
    assert time.monotonic() - t0 < 10


def test_multipipe_wait_timeout():
    pipe = MultiPipe("hang2", capacity=4)
    pipe.add_source(Source(batches=lambda i: keyed_batches(n_batches=30),
                           name="src"))
    pipe.add_sink(Sink(lambda r: time.sleep(0.2) if r is not None else None,
                       vectorized=True, name="slow"))
    pipe.run()
    with pytest.raises(TimeoutError):
        pipe.wait(timeout=0.4)


def test_wait_notes_sibling_errors():
    """Multi-node crashes: wait() raises the first error but keeps the
    rest reachable (count + types) instead of silently dropping them."""
    df = Dataflow("multi", capacity=0)

    class Boom(Exception):
        pass

    def bang(r):
        if r is not None:
            raise Boom(threading.current_thread().name)

    src = Source(batches=lambda i: keyed_batches(n_batches=2), name="src")
    s1 = Sink(bang, name="s1")
    s2 = Sink(bang, name="s2")
    [t] = build_pipeline(df, [src])
    for s in (s1, s2):
        (rep,) = s.replicas()
        df.add(rep)
        df.connect(t, rep)
    df.run()
    time.sleep(0.3)   # let both sinks consume their broadcast copy
    with pytest.raises(Boom) as ei:
        df.wait()
    errs = getattr(ei.value, "dataflow_errors", (ei.value,))
    assert len(errs) == 2
    assert all(isinstance(e, Boom) for e in errs)
    assert ei.value.__cause__ is errs[1]


# ------------------------------------------------------------ observability


def test_recovery_surfaces_events_and_metrics():
    got = []
    pol = RecoveryPolicy(epoch_batches=5, restart_backoff=0.01)
    df = Dataflow("obs", capacity=8, recovery=pol, metrics=True)
    build_pipeline(df, [
        Source(batches=lambda i: keyed_batches(), name="src"),
        WinSeq(Reducer("sum", "value"), win_len=8, slide_len=4),
        Sink(lambda r: got.append(1) if r is not None else None,
             name="sink"),
    ])
    install_kill_point(find_node(df, "win_seq"), 9)
    df.run_and_wait_end(timeout=120)
    kinds = {e["event"] for e in df.events.recent}
    assert {"epoch", "checkpoint", "node_restart", "restore"} <= kinds
    snap = df.metrics.snapshot()
    assert snap["counters"]["node_restarts"] == 1
    assert snap["counters"]["node_restores"] == 1
    assert snap["counters"]["ckpt_snapshots"] > 0


# ------------------------------------------------------------- soak slice


@pytest.mark.slow
def test_soak_crash_slice():
    """Small in-suite slice of scripts/soak_crash.py (the full soak is a
    standalone seeded harness, docs/ROBUSTNESS.md)."""
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "soak_crash", os.path.join(os.path.dirname(__file__), os.pardir,
                                   "scripts", "soak_crash.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    for case in range(8):
        mod.run_case(seed=11, case=case)


@pytest.mark.slow
def test_soak_crash_native_slice():
    """Small in-suite slice of `scripts/soak_crash.py --native`:
    randomized crash differentials over the C++ resident core's state
    ABI (docs/ROBUSTNESS.md "Native state ABI")."""
    from windflow_tpu.native import enabled
    lib = enabled()
    if lib is None or not getattr(lib, "wf_has_state_abi", False):
        pytest.skip("native library with the state ABI unavailable")
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "soak_crash", os.path.join(os.path.dirname(__file__), os.pardir,
                                   "scripts", "soak_crash.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    for case in range(4):
        try:
            mod.run_case_native(seed=11, case=case)
        except mod.NativeUnavailable as e:
            pytest.skip(str(e))
