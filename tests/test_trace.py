"""End-to-end span tracing (windflow_tpu/obs/trace.py,
docs/OBSERVABILITY.md §tracing):

* the ``trace=`` knob contract: unset never imports the package, off
  means byte-identical results, falsy means OFF;
* span stitching source → sink (parentage across threads and farm
  fan-out, device-launch child spans via the profile recorder, Comb
  fusion, the supervised/recovery receive loop, ctrl spans);
* wire propagation (TRACE frame, ``decode_trace``, adoption);
* the sampler's per-node latency percentile fields and the
  ``Rescale(up_q95_us=)`` pure-observe path;
* ``scripts/wf_trace.py`` summary + Chrome trace-event export;
* the expo labelled-family rendering and the profile satellites.
"""

import importlib.util
import json
import os
import subprocess
import sys
import threading

import numpy as np
import pytest

from obs_schema import validate_sample, validate_span
from windflow_tpu.api import MultiPipe, union_multipipes
from windflow_tpu.core.tuples import Schema
from windflow_tpu.obs import MetricsRegistry
from windflow_tpu.obs.trace import Stamped, TracePolicy, Tracer, as_policy
from windflow_tpu.parallel.channel import RowReceiver, RowSender, TracedRows
from windflow_tpu.patterns.basic import Map, Sink, Source
from windflow_tpu.runtime.engine import Dataflow
from windflow_tpu.runtime.node import Node, SourceNode
from windflow_tpu.utils import profile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SCHEMA = Schema(value=np.int64)


@pytest.fixture(autouse=True)
def _no_ambient_obs_env(monkeypatch):
    """An ambient WF_LOG_DIR would turn ring-only graphs into writers
    (and silence the WF213 warning these tests pin)."""
    monkeypatch.delenv("WF_LOG_DIR", raising=False)
    monkeypatch.delenv("WF_SAMPLE_PERIOD", raising=False)


def _load_script(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "scripts", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# ------------------------------------------------------------ test graph

class _Src(SourceNode):
    def __init__(self, n=6, name="src"):
        super().__init__(name)
        self.n = n

    def generate(self):
        for i in range(self.n):
            self.emit(np.arange(4, dtype=np.int64) + i)


class _Mid(Node):
    """Host stage that also brackets a profile span, standing in for the
    device ship phases (ops/resident.py uses the same primitive)."""

    def svc(self, batch, channel=0):
        with profile.span("dispatch"):
            pass
        self.emit(batch * 2)


class _Snk(Node):
    def __init__(self, name="snk"):
        super().__init__(name)
        self.got = []

    def svc(self, batch, channel=0):
        self.got.append(batch.copy())


def _run_linear(trace=None, trace_dir=None, metrics=None, n=6):
    import warnings
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")   # ring-only runs warn WF213
        df = Dataflow("tr", trace_dir=trace_dir, metrics=metrics,
                      trace=trace)
    s = df.add(_Src(n))
    m = df.add(_Mid("mid"))
    k = df.add(_Snk())
    df.connect(s, m)
    df.connect(m, k)
    df.run_and_wait_end()
    return df, k


# ---------------------------------------------------------- knob contract

def test_trace_policy_validation():
    with pytest.raises(ValueError):
        TracePolicy(sample_rate=0.0)
    with pytest.raises(ValueError):
        TracePolicy(sample_rate=1.5)
    with pytest.raises(ValueError):
        TracePolicy(max_spans=0)
    with pytest.raises(ValueError):
        TracePolicy(ring=0)
    assert TracePolicy(sample_rate=1.0).sample_every == 1
    assert TracePolicy(sample_rate=0.01).sample_every == 100
    assert as_policy(True).sample_every == 1
    assert as_policy(0.5).sample_every == 2
    pol = TracePolicy(sample_rate=0.5)
    assert as_policy(pol) is pol


def test_trace_falsy_means_off():
    for falsy in (None, 0, 0.0, False):
        df = Dataflow("off", trace=falsy)
        assert df.tracer is None and df.trace is None


def test_trace_unset_never_imports_package():
    """Seed contract: trace= unset => windflow_tpu.obs.trace is never
    imported (subprocess keeps sys.modules clean)."""
    code = (
        "import sys\n"
        "import numpy as np\n"
        "from windflow_tpu.api import MultiPipe\n"
        "from windflow_tpu.core.tuples import Schema\n"
        "from windflow_tpu.patterns.basic import Sink, Source\n"
        "S = Schema(value=np.int64)\n"
        "def gen(sh):\n"
        "    sh.push(key=0, id=0, ts=0, value=1)\n"
        "got = []\n"
        "p = (MultiPipe('seed')\n"
        "     .add_source(Source(gen, S))\n"
        "     .chain_sink(Sink(lambda b: got.append(b),"
        " vectorized=True)))\n"
        "p.run_and_wait_end()\n"
        "assert any(b is not None and len(b) for b in got)\n"
        "assert 'windflow_tpu.obs.trace' not in sys.modules, \\\n"
        "    'obs.trace imported on the seed path'\n"
    )
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("WF_LOG_DIR", None)
    r = subprocess.run([sys.executable, "-c", code], cwd=REPO, env=env,
                       capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, r.stdout + r.stderr


def test_trace_off_results_byte_identical():
    _df0, k0 = _run_linear(trace=None)
    _df1, k1 = _run_linear(trace=TracePolicy(sample_rate=1.0))
    assert len(k0.got) == len(k1.got)
    assert (np.concatenate(k0.got).tobytes()
            == np.concatenate(k1.got).tobytes())


def test_ring_only_warns_wf213():
    with pytest.warns(UserWarning, match=r"WF213.*trace_dir"):
        Dataflow("ringy", trace=0.5)


# -------------------------------------------------------------- stitching

def _by_span(records):
    return {r["span"]: r for r in records}


def test_span_stitching_source_to_sink_with_launch_children():
    df, k = _run_linear(trace=TracePolicy(sample_rate=1.0), n=5)
    recs = list(df.tracer.recent)
    hops = [r for r in recs if r["kind"] == "hop"]
    launches = [r for r in recs if r["kind"] == "launch"]
    spans = _by_span(recs)
    traces = {}
    for r in hops:
        traces.setdefault(r["trace"], []).append(r)
    assert len(traces) == 5                     # every batch sampled
    for recs_t in traces.values():
        chain = sorted(recs_t, key=lambda r: r["end_us"])
        names = [r["node"] for r in chain]
        assert names == ["tr_00_src", "tr_01_mid", "tr_02_snk"]
        assert chain[0]["parent"] is None       # the root
        # each hop's parent is the upstream hop of the SAME trace
        for up, down in zip(chain, chain[1:]):
            assert down["parent"] == up["span"]
        assert chain[-1]["end_us"] >= chain[0]["end_us"]
    # the profile span inside mid.svc became a child of mid's hop
    assert len(launches) == 5
    for lr in launches:
        assert lr["phase"] == "dispatch"
        parent = spans[lr["parent"]]
        assert parent["kind"] == "hop" and parent["node"] == "tr_01_mid"
        assert lr["trace"] == parent["trace"]
    # sink saw every row exactly once (tracing is observability only)
    assert sum(len(b) for b in k.got) == 20


def test_sampling_fraction_respected():
    df, _k = _run_linear(trace=TracePolicy(sample_rate=0.5), n=8)
    hops = [r for r in df.tracer.recent if r["kind"] == "hop"]
    traces = {r["trace"] for r in hops}
    assert len(traces) == 4                     # 1-in-2 of 8 batches


def test_comb_fusion_propagates_context(tmp_path):
    """chain() fuses source+map into one thread: the sampling decision
    happens at the fused first stage, the tail wraps, and the sink hop
    parents on the comb's root span."""
    got = []

    def gen(sh):
        for i in range(4):
            sh.push(key=0, id=i, ts=i, value=i)

    p = (MultiPipe("fuse", trace=TracePolicy(sample_rate=1.0),
                   trace_dir=str(tmp_path))
         .add_source(Source(gen, SCHEMA, name="src"))
         .chain(Map(lambda b: None, vectorized=True))
         .add_sink(Sink(lambda b: got.append(b), vectorized=True)))
    p.run_and_wait_end()
    tracer = p._df.tracer
    hops = [r for r in tracer.recent if r["kind"] == "hop"]
    spans = _by_span(hops)
    roots = [r for r in hops if r["parent"] is None]
    assert roots, "no root spans recorded"
    non_roots = [r for r in hops if r["parent"] is not None]
    assert non_roots, "nothing downstream of the fused source"
    for r in non_roots:
        assert r["parent"] in spans
        assert spans[r["parent"]]["trace"] == r["trace"]


def test_supervised_loop_records_spans(tmp_path):
    """recovery= + trace=: the supervised receive loop unwraps Stamped
    payloads (inside the Tagged envelope), records hops, and the
    checkpoint commits appear as ctrl spans."""
    from windflow_tpu.recovery.policy import RecoveryPolicy
    got = []

    def gen(sh):
        for i in range(8):
            sh.push(key=i % 2, id=i, ts=i, value=i)
            sh.flush()

    p = (MultiPipe("sup", trace=TracePolicy(sample_rate=1.0),
                   trace_dir=str(tmp_path), metrics=True,
                   recovery=RecoveryPolicy(
                       epoch_batches=2, checkpoint_dir=str(tmp_path)))
         .add_source(Source(gen, SCHEMA, name="src"))
         .add(Map(lambda b: None, vectorized=True))
         .add_sink(Sink(lambda b: got.append(b), vectorized=True)))
    p.run_and_wait_end()
    tracer = p._df.tracer
    recs = list(tracer.recent)
    hops = [r for r in recs if r["kind"] == "hop"]
    ctrls = [r for r in recs if r["kind"] == "ctrl"]
    spans = _by_span(hops)
    assert any(r["parent"] is not None for r in hops)
    for r in hops:
        if r["parent"] is not None and r["parent"] in spans:
            assert spans[r["parent"]]["trace"] == r["trace"]
    assert any(c["name"] == "checkpoint" for c in ctrls)
    assert sum(len(b) for b in got if b is not None) == 8


# ------------------------------------------------------------------ wire

def test_wire_trace_frame_roundtrip():
    batch = np.arange(6, dtype=np.int64).view([("value", np.int64)])
    info = {"trace": 4242, "span": 7, "elapsed_us": 1500.0}
    recv = RowReceiver(n_senders=1, decode_trace=True)
    snd = RowSender(recv.host, recv.port)
    snd.send(batch[:3], trace=info)
    snd.send(batch[3:])             # untraced frame rides the same link
    snd.close()
    out = list(recv.batches())
    assert len(out) == 2
    traced = [b for b in out if getattr(b, "wf_trace", None) is not None]
    plain = [b for b in out if getattr(b, "wf_trace", None) is None]
    assert len(traced) == 1 and len(plain) == 1
    assert traced[0].wf_trace == info
    assert isinstance(traced[0], TracedRows)
    np.testing.assert_array_equal(
        np.sort(np.concatenate(out)["value"]), np.arange(6))


def test_wire_trace_frame_discarded_by_default():
    batch = np.arange(3, dtype=np.int64).view([("value", np.int64)])
    recv = RowReceiver(n_senders=1)
    snd = RowSender(recv.host, recv.port)
    snd.send(batch, trace={"trace": 1, "span": 2, "elapsed_us": 3.0})
    snd.close()
    out = list(recv.batches())
    assert len(out) == 1
    assert getattr(out[0], "wf_trace", None) is None


def test_source_adopts_wire_trace():
    """A TracedRows batch emitted by a traced source joins the remote
    trace instead of starting a fresh one: same trace id, root parented
    on the remote span."""
    tracer = Tracer("adoptee", TracePolicy(sample_rate=1.0))

    class N(SourceNode):
        pass

    node = N("src")
    node._trace_origin = True
    node._hop_id = "adoptee_00_src"
    batch = np.arange(3, dtype=np.int64).view(TracedRows)
    batch.wf_trace = {"trace": 990011, "span": 41, "elapsed_us": 2000.0}
    out = tracer.outgoing(batch, node)
    assert isinstance(out, Stamped)
    assert out.ctx.trace_id == 990011
    roots = [r for r in tracer.recent if r["kind"] == "hop"]
    assert roots[0]["trace"] == 990011 and roots[0]["parent"] == 41
    # the back-dated anchor puts the local root past the remote elapsed
    assert roots[0]["end_us"] >= 2000.0
    tracer.close()      # balance the process-wide recorder refcount


# ------------------------------------- sampler percentiles / control rule

def test_sampler_carries_latency_percentiles():
    from windflow_tpu.obs.sampler import Sampler
    reg = MetricsRegistry()
    df, _k = _run_linear(trace=TracePolicy(sample_rate=1.0), metrics=reg)
    sample = Sampler(df, 1.0).sample()
    validate_sample(sample, "sample")
    by_node = {n["node"]: n for n in sample["nodes"]}
    for field in ("q_p50_us", "q_p95_us", "q_p99_us",
                  "svc_p50_us", "svc_p95_us", "svc_p99_us"):
        assert field in by_node["mid"], (field, by_node["mid"])
        assert by_node["mid"][field] >= 0
    assert by_node["mid"]["q_p50_us"] <= by_node["mid"]["q_p99_us"]


def test_untraced_sample_has_no_latency_fields():
    from windflow_tpu.obs.sampler import Sampler
    reg = MetricsRegistry()
    df, _k = _run_linear(metrics=reg)
    sample = Sampler(df, 1.0).sample()
    validate_sample(sample, "sample")
    for n in sample["nodes"]:
        assert "q_p95_us" not in n and "svc_p95_us" not in n


def test_rescale_rule_thresholds_on_tail_latency():
    """Pure observe() path (ISSUE acceptance): a Rescale rule fires on
    the q95 signal alone, and the legacy 2-tuple form stays accepted."""
    from windflow_tpu.control.policy import Rescale
    rule = Rescale("kf", max_workers=4, up_q95_us=50_000.0,
                   hysteresis=2, cooldown=0.0)
    assert rule.observe((0, 0.0, 10_000.0), 0.0) == 0
    assert rule.observe((0, 0.0, 60_000.0), 1.0) == 0   # streak 1/2
    assert rule.observe((0, 0.0, 75_000.0), 2.0) == 1   # fires on q95
    # depth threshold still works through the 2-tuple form
    rule2 = Rescale("kf", max_workers=4, up_depth=8, hysteresis=1,
                    cooldown=0.0)
    assert rule2.observe((9, 0.0), 0.0) == 1
    with pytest.raises(ValueError):
        Rescale("kf", max_workers=4, up_q95_us=0)


# --------------------------------------------------- file sinks / bounds

def test_trace_jsonl_schema_and_bound(tmp_path):
    df, _k = _run_linear(trace=TracePolicy(sample_rate=1.0),
                         trace_dir=str(tmp_path), n=6)
    path = os.path.join(str(tmp_path), "trace.jsonl")
    assert os.path.exists(path)
    n = 0
    with open(path) as f:
        for i, line in enumerate(f, 1):
            assert line.endswith("\n")
            validate_span(json.loads(line), f"trace.jsonl:{i}")
            n += 1
    assert n == df.tracer.written and n > 0


def test_max_spans_drops_and_counts(tmp_path):
    from windflow_tpu.obs import EventLog
    ev = EventLog()
    reg = MetricsRegistry()
    tracer = Tracer("cap", TracePolicy(sample_rate=1.0, max_spans=2),
                    trace_dir=str(tmp_path), metrics=reg, events=ev)
    from windflow_tpu.obs.trace import SpanCtx
    from time import perf_counter_ns
    ctx = SpanCtx(1, perf_counter_ns(), tracer)
    for i in range(5):
        tracer.record_hop(ctx, "n", 100 + i, None, 1000, 1000, 1)
    tracer.close()
    assert tracer.written == 2 and tracer.dropped == 3
    assert tracer.spans == 5                    # ring saw everything
    assert len(tracer.recent) == 5
    with open(os.path.join(str(tmp_path), "trace.jsonl")) as f:
        assert sum(1 for _ in f) == 2
    kinds = [e["event"] for e in ev.recent]
    assert kinds.count("trace_drop") == 1       # rate-limited
    assert reg.counter("trace_spans_dropped").value == 3


def test_union_trace_policies_must_agree(tmp_path):
    def _leg(name, trace):
        p = MultiPipe(name, trace=trace, trace_dir=str(tmp_path))
        p.add_source(Source(lambda sh: None, SCHEMA))
        return p

    pol = TracePolicy(sample_rate=0.5)
    merged = union_multipipes(_leg("a", pol), _leg("b", None), name="u")
    assert merged.trace is pol
    with pytest.raises(ValueError, match="conflicting trace"):
        union_multipipes(_leg("c", pol),
                         _leg("d", TracePolicy(sample_rate=0.25)),
                         name="u2")


# -------------------------------------------------------------- wf_trace

def test_wf_trace_summary_and_chrome_export(tmp_path):
    df, _k = _run_linear(trace=TracePolicy(sample_rate=1.0),
                         trace_dir=str(tmp_path), n=6)
    wf_trace = _load_script("wf_trace")
    records = wf_trace.read_records(
        os.path.join(str(tmp_path), "trace.jsonl"))
    assert records
    rep = wf_trace.summarize(records)
    assert rep["n_traces"] == 6
    assert [s["node"] for s in rep["stages"]] == \
        ["tr_00_src", "tr_01_mid", "tr_02_snk"]
    assert rep["critical_stage"]
    assert "dispatch" in rep["launch_phases"]
    text = wf_trace.render(rep)
    assert "tr_01_mid" in text and "end-to-end" in text
    # Chrome trace-event export: loads as JSON, has process/thread
    # metadata, queue+svc slices, launch child slices, and flow arrows
    doc = wf_trace.chrome_trace(records)
    blob = json.dumps(doc)
    doc2 = json.loads(blob)
    evs = doc2["traceEvents"]
    assert evs and doc2["displayTimeUnit"] == "ms"
    phases = {e["ph"] for e in evs}
    assert {"M", "X", "s", "t"} <= phases
    for e in evs:
        assert isinstance(e["pid"], int)
        if e["ph"] != "M" or "tid" in e:
            assert isinstance(e.get("tid", 1), int)
        if e["ph"] == "X":
            assert e["dur"] >= 0 and e["ts"] > 0
    names = {e["name"] for e in evs if e["ph"] == "X"}
    assert {"queue", "svc", "dispatch"} <= names
    # the CLI end-to-end, into a file
    out = str(tmp_path / "chrome.json")
    assert wf_trace.main([str(tmp_path), "--chrome", out]) == 0
    with open(out) as f:
        assert json.load(f)["traceEvents"]
    assert wf_trace.main([str(tmp_path), "--json"]) == 0


def test_wf_trace_chrome_ctrl_instants(tmp_path):
    """ctrl spans (checkpoint/rescale) and events.jsonl epochs render as
    instant events."""
    records = [
        {"t": 100.0, "kind": "hop", "trace": 1, "span": 10,
         "parent": None, "dataflow": "d", "node": "n0", "q_us": 0.0,
         "svc_us": 5.0, "end_us": 5.0, "rows": 1},
        {"t": 101.0, "kind": "ctrl", "trace": None, "span": 11,
         "parent": None, "dataflow": "d", "node": "n0",
         "name": "checkpoint", "epoch": 3, "dur_us": 250.0},
    ]
    events = [{"t": 102.0, "event": "rescale", "dataflow": "d",
               "farm": "kf", "epoch": 4, "width_from": 1,
               "width_to": 2, "moved_keys": 5, "ms": 1.5}]
    wf_trace = _load_script("wf_trace")
    for rec in records:
        validate_span(rec)
    doc = wf_trace.chrome_trace(records, events)
    instants = [e for e in doc["traceEvents"] if e["ph"] == "i"]
    assert len(instants) == 2
    assert any(e["name"] == "checkpoint e3" for e in instants)
    assert any(e["name"] == "rescale e4" for e in instants)
    assert all(e["s"] == "p" for e in instants)


# ------------------------------------------------------- expo satellites

def test_expo_renders_labelled_histogram_families():
    from windflow_tpu.obs import expo
    reg = MetricsRegistry()
    for node in ("a", "b"):
        h = reg.histogram(f'trace_service_seconds{{node="{node}"}}',
                          (0.1, 1.0))
        h.observe(0.05)
    txt = expo.render_registry(reg)
    # ONE family declaration, two labelled series
    assert txt.count("# TYPE wf_trace_service_seconds histogram") == 1
    assert 'wf_trace_service_seconds_bucket{node="a",le="0.1"} 1' in txt
    assert 'wf_trace_service_seconds_bucket{node="b",le="+Inf"} 1' in txt
    assert 'wf_trace_service_seconds_count{node="a"} 1' in txt
    # flat names keep their historical rendering
    reg2 = MetricsRegistry()
    reg2.counter("wire_bytes_sent").inc(5)
    assert "wf_wire_bytes_sent 5" in expo.render_registry(reg2)


def test_expo_sample_renders_latency_gauges():
    from windflow_tpu.obs import expo
    sample = {"dataflow": "d", "dead_letters": 0,
              "nodes": [{"node": "m", "id": "d_01_m", "depth": 0,
                         "hwm": 0, "shed": 0, "quarantined": 0,
                         "q_p95_us": 120.5, "svc_p95_us": 30.0}]}
    txt = expo.render_sample(sample)
    assert 'wf_node_queue_wait_p95_us{dataflow="d",node="m"} 120.5' in txt
    assert 'wf_node_service_p95_us{dataflow="d",node="m"} 30.0' in txt


# ----------------------------------------------------- review hardening

def test_stamped_copy_detaches_batch():
    """The recovery journal's copy_inputs defense duck-types on
    ``.copy()``: a journaled Stamped must not alias the live batch a
    mutating node scribbles on (replay would see transformed rows)."""
    from time import perf_counter_ns
    from windflow_tpu.obs.trace import SpanCtx
    batch = np.arange(4, dtype=np.int64)
    st = Stamped(batch, SpanCtx(1, perf_counter_ns(), None), None,
                 perf_counter_ns())
    dup = st.copy()
    batch[:] = -1                       # the in-place mutation
    np.testing.assert_array_equal(dup.batch, np.arange(4))
    assert dup.ctx is st.ctx and dup.parent is st.parent


def test_span_ids_stay_js_safe():
    """Trace/span ids feed the Chrome export, read by JavaScript:
    anything at or above 2**53 loses low bits to double rounding and
    distinct ids silently merge in Perfetto."""
    from windflow_tpu.obs.trace import _new_id
    for _ in range(64):
        assert 0 < _new_id() < 2 ** 53
    df, _k = _run_linear(trace=TracePolicy(sample_rate=1.0))
    for r in df.tracer.recent:
        for field in ("trace", "span", "parent"):
            v = r.get(field)
            if v is not None:
                assert 0 < v < 2 ** 53


def test_recorder_uninstalls_with_last_tracer():
    """Once the last live tracer closes, profile spans return to the
    bare disabled probe — an untraced run after a traced one must not
    keep paying the recorder tax.  (Relative to the baseline: other
    suites may hold never-run traced graphs whose tracers stay open.)"""
    from windflow_tpu.obs import trace as trace_mod
    base = trace_mod._RECORDER_REFS
    t1 = Tracer("a", TracePolicy(sample_rate=1.0))
    t2 = Tracer("b", TracePolicy(sample_rate=1.0))
    assert trace_mod._RECORDER_REFS == base + 2
    assert profile._RECORDER is not None
    t1.close()
    t1.close()                          # idempotent: no double-decrement
    assert trace_mod._RECORDER_REFS == base + 1
    assert profile._RECORDER is not None
    t2.close()
    assert trace_mod._RECORDER_REFS == base
    if base == 0:
        assert profile._RECORDER is None


def test_unclosed_tracer_releases_recorder_on_gc():
    """A tracer that never reaches close() (preview graph, run()
    raising before wait()) must not leak the process-wide recorder."""
    import gc
    from windflow_tpu.obs import trace as trace_mod
    base = trace_mod._RECORDER_REFS
    t = Tracer("leaky", TracePolicy(sample_rate=1.0))
    assert trace_mod._RECORDER_REFS == base + 1
    del t
    gc.collect()
    assert trace_mod._RECORDER_REFS == base


# ---------------------------------------------------- profile satellites

def test_profile_recorder_hook_fires_without_profiling():
    seen = []
    profile.disable()
    try:
        profile.set_recorder(lambda name, dt: seen.append((name, dt)))
        with profile.span("harvest_wait"):
            pass
        assert seen and seen[0][0] == "harvest_wait" and seen[0][1] >= 0
        # profiling disabled: the accumulators must stay untouched
        assert "harvest_wait" not in profile.report()
    finally:
        profile.set_recorder(None)
        profile.auto()


def test_profile_report_snapshots_under_lock():
    """report()/counters()/reset() while ship threads mutate the
    accumulators: no 'dictionary changed size during iteration'."""
    profile.enable()
    stop = threading.Event()

    def writer(i):
        n = 0
        while not stop.is_set():
            profile.add(f"phase_{i}_{n % 97}", 1.0)
            with profile.span(f"span_{i}_{n % 89}"):
                pass
            n += 1

    threads = [threading.Thread(target=writer, args=(i,), daemon=True)
               for i in range(3)]
    try:
        for t in threads:
            t.start()
        for _ in range(60):
            profile.report()
            profile.counters()
        profile.reset()
        for _ in range(30):
            profile.report()
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=5.0)
        profile.reset()
        profile.auto()
