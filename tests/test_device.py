"""Device-path differential tests — the equivalent of src/sum_test_gpu:
every TPU pattern must produce the same results as its host counterpart /
Win_Seq on the same stream.  Under pytest these run on the CPU XLA backend
(conftest pins JAX_PLATFORMS=cpu); bench.py runs the same code on the real
chip."""

import numpy as np
import pytest

import jax.numpy as jnp

from windflow_tpu.core.windows import WinType
from windflow_tpu.ops.functions import Reducer
from windflow_tpu.patterns.win_seq import WinSeq
from windflow_tpu.patterns.win_seq_tpu import (DeviceWinSeqCore,
                                               JaxWindowFunction, KeyFarmTPU,
                                               PaneFarmTPU, WinFarmTPU,
                                               WinMapReduceTPU, WinSeqTPU)

from test_farms import cb_stream_batches, tb_stream_batches, run_windowed
from test_pane_wmr import iv


def ref(win, slide, wt, batches):
    return run_windowed(WinSeq(Reducer("sum"), win, slide, wt), batches)


@pytest.mark.parametrize("win,slide", [(8, 3), (8, 8), (3, 8), (16, 7)])
@pytest.mark.parametrize("batch_len", [1, 7, 64, 100000])
def test_win_seq_tpu_cb(win, slide, batch_len):
    keys, n = 3, 150
    got = run_windowed(
        WinSeqTPU(Reducer("sum"), win, slide, WinType.CB,
                  batch_len=batch_len),
        cb_stream_batches(keys, n))
    assert got == ref(win, slide, WinType.CB, cb_stream_batches(keys, n))


@pytest.mark.parametrize("win,slide", [(40, 15), (30, 30), (15, 40)])
def test_win_seq_tpu_tb_ragged(win, slide):
    """TB windows are ragged -> exercises bucket padding + masking."""
    keys, n = 2, 160
    got = run_windowed(
        WinSeqTPU(Reducer("sum"), win, slide, WinType.TB, batch_len=32),
        tb_stream_batches(keys, n))
    assert got == ref(win, slide, WinType.TB, tb_stream_batches(keys, n))


@pytest.mark.parametrize("op", ["sum", "count", "min", "max", "mean"])
def test_builtin_ops_device(op):
    if op == "mean":
        pytest.skip("host Reducer has no mean; covered by jax-fn test")
    got = run_windowed(
        WinSeqTPU(Reducer(op), 10, 4, WinType.CB, batch_len=16),
        cb_stream_batches(2, 100))
    want = run_windowed(WinSeq(Reducer(op), 10, 4, WinType.CB),
                        cb_stream_batches(2, 100))
    assert got == want


def test_count_without_value_field():
    """count stages no payload columns at all (required_fields=())."""
    from windflow_tpu.core.tuples import Schema, batch_from_columns
    from windflow_tpu.patterns.basic import Sink, Source
    from windflow_tpu.runtime.engine import Dataflow
    from windflow_tpu.runtime.farm import build_pipeline

    bare = Schema()  # no payload fields
    ids = np.arange(40)
    b = batch_from_columns(bare, key=np.zeros(40), id=ids, ts=ids)
    got = []
    df = Dataflow()
    build_pipeline(df, [
        Source(batches=[b], schema=bare),
        WinSeqTPU(Reducer("count"), 10, 10, WinType.CB, batch_len=4),
        Sink(lambda r: got.append(int(r["value"])) if r is not None else None)])
    df.run_and_wait_end()
    assert got == [10, 10, 10, 10]


def test_user_jax_window_function():
    """Arbitrary JAX function over the window batch — the CUDA-functor
    replacement: here, sum of squares."""
    def fn(keys, gwids, cols, mask):
        v = cols["value"]
        return jnp.sum(jnp.where(mask, v * v, 0), axis=1)

    jf = JaxWindowFunction(fn, fields=("value",),
                           result_fields={"value": np.int64})
    got = run_windowed(WinSeqTPU(jf, 6, 2, WinType.CB, batch_len=32),
                       cb_stream_batches(2, 80))

    def host(key, gwid, rows):
        return int(np.sum(rows["value"].astype(np.int64) ** 2))

    from windflow_tpu.ops.functions import FnWindowFunction
    want = run_windowed(
        WinSeq(FnWindowFunction(host, {"value": np.int64}), 6, 2, WinType.CB),
        cb_stream_batches(2, 80))
    assert got == want


def test_host_python_fn_rejected_on_device():
    with pytest.raises(TypeError, match="cannot be staged"):
        WinSeqTPU(lambda k, g, rows: 0, 4, 2, WinType.CB).make_core()


def test_incremental_rejected_on_device():
    core = WinSeqTPU(Reducer("sum"), 4, 2, WinType.CB).make_core()
    with pytest.raises(TypeError, match="non-incremental"):
        core.use_incremental()


def test_pallas_windowed_reduce_interpret():
    """The pallas kernel (interpret mode on CPU) against numpy."""
    from windflow_tpu.ops.pallas_kernels import windowed_reduce_pallas

    rng = np.random.default_rng(0)
    flat = rng.integers(0, 100, size=256).astype(np.int32)
    starts = np.arange(0, 128, 2, dtype=np.int32)   # 64 windows
    lens = rng.integers(0, 17, size=64).astype(np.int32)
    out = np.asarray(windowed_reduce_pallas(
        np.concatenate([flat, np.zeros(32, np.int32)]), starts, lens, 32,
        "sum", interpret=True))
    want = np.array([flat[s:s + l].sum() for s, l in zip(starts, lens)],
                    dtype=np.int32)
    assert np.array_equal(out, want)


def test_win_seq_tpu_pallas_matches():
    got = run_windowed(
        WinSeqTPU(Reducer("sum"), 12, 5, WinType.CB, batch_len=64,
                  use_pallas=True),
        cb_stream_batches(2, 200))
    assert got == ref(12, 5, WinType.CB, cb_stream_batches(2, 200))


@pytest.mark.parametrize("pardegree", [2, 3])
def test_win_farm_tpu(pardegree):
    keys, n = 3, 140
    got = run_windowed(
        WinFarmTPU(Reducer("sum"), 10, 4, WinType.CB, pardegree=pardegree,
                   batch_len=16),
        cb_stream_batches(keys, n))
    assert got == ref(10, 4, WinType.CB, cb_stream_batches(keys, n))


@pytest.mark.parametrize("pardegree", [2, 4])
def test_key_farm_tpu(pardegree):
    keys, n = 5, 120
    got = run_windowed(
        KeyFarmTPU(Reducer("sum"), 10, 4, WinType.CB, pardegree=pardegree,
                   batch_len=16),
        cb_stream_batches(keys, n))
    assert got == ref(10, 4, WinType.CB, cb_stream_batches(keys, n))


@pytest.mark.parametrize("plq_dev,wlq_dev", [(True, False), (False, True),
                                             (True, True)])
def test_pane_farm_tpu_stage_placement(plq_dev, wlq_dev):
    keys, n = 3, 120
    got = iv(run_windowed(
        PaneFarmTPU(Reducer("sum"), Reducer("sum"), 12, 4, WinType.CB,
                    plq_degree=2, wlq_degree=2, plq_on_device=plq_dev,
                    wlq_on_device=wlq_dev, batch_len=16),
        cb_stream_batches(keys, n)))
    assert got == iv(ref(12, 4, WinType.CB, cb_stream_batches(keys, n)))


@pytest.mark.parametrize("map_dev,red_dev", [(True, False), (False, True),
                                             (True, True)])
def test_win_mapreduce_tpu_stage_placement(map_dev, red_dev):
    keys, n = 3, 120
    got = iv(run_windowed(
        WinMapReduceTPU(Reducer("sum"), Reducer("sum"), 12, 4, WinType.CB,
                        map_degree=3, reduce_degree=2, map_on_device=map_dev,
                        reduce_on_device=red_dev, batch_len=16),
        cb_stream_batches(keys, n)))
    assert got == iv(ref(12, 4, WinType.CB, cb_stream_batches(keys, n)))


def test_nested_tpu_inner():
    """Nesting with device inner patterns: WF(PF_TPU)."""
    from windflow_tpu.patterns.nesting import WinFarmOf

    keys, n = 3, 140
    inner = PaneFarmTPU(Reducer("sum"), Reducer("sum"), 16, 4, WinType.CB,
                        plq_degree=2, wlq_degree=1, batch_len=16)
    got = iv(run_windowed(WinFarmOf(inner, pardegree=2),
                          cb_stream_batches(keys, n)))
    assert got == iv(ref(16, 4, WinType.CB, cb_stream_batches(keys, n)))


@pytest.mark.parametrize("op", ["min", "max"])
def test_empty_windows_match_host_identity(op):
    """A TB stream with a time gap produces empty windows; the device path
    must emit the host Reducer identity (int64 extremes), not the narrowed
    compute-dtype identity (regression: int32 iinfo leaked through)."""
    from windflow_tpu.core.tuples import Schema, batch_from_columns

    def gap_stream():
        ids = np.arange(8)
        ts = np.concatenate([ids[:4], ids[:4] + 100])
        yield batch_from_columns(Schema(value=np.int64), key=np.zeros(8),
                                 id=ids, ts=ts, value=ids + 1)

    got = run_windowed(WinSeqTPU(Reducer(op), 10, 10, WinType.TB,
                                 batch_len=4), list(gap_stream()))
    want = run_windowed(WinSeq(Reducer(op), 10, 10, WinType.TB),
                        list(gap_stream()))
    assert got == want


def test_budget_aware_routing_fake_ema(monkeypatch):
    """VERDICT r4 item 4: a latency budget under ~2x the measured
    per-launch wire service routes the stage to the HOST core (the
    device path cannot meet it by construction); generous budgets, an
    unmeasured wire, or an explicit use_resident force keep the device.
    Faked EMA — no wire needed."""
    from windflow_tpu.core.windows import WindowSpec
    from windflow_tpu.ops import resident
    from windflow_tpu.patterns.win_seq_tpu import make_core_for

    spec = WindowSpec(16, 4, WinType.CB)
    red = Reducer("sum", value_range=(0, 100))

    def kind(core):
        name = type(core).__name__
        return "host" if "Resident" not in name and "Device" not in name \
            else "device"

    from collections import deque

    def seed(*obs):
        monkeypatch.setitem(resident._WEATHER, "recent", deque(maxlen=16))
        monkeypatch.setitem(resident._WEATHER, "floor_ms", None)
        monkeypatch.setitem(resident._WEATHER, "ema_ms", None)
        for ms in obs:   # the public feed path recomputes the floor
            resident.note_wire_service_ms(ms)

    # recent-best service 700 ms: a 250 ms budget is unmeetable on device
    seed(900.0, 700.0, 1100.0)
    assert kind(make_core_for(spec, red, max_delay_ms=250)) == "host"
    # a >= 2x-floor budget stays on the device path
    assert kind(make_core_for(spec, red, max_delay_ms=2000)) == "device"
    # the floor ignores compile-inflated outliers: one good launch among
    # terrible ones keeps a 2x-floor budget on the device
    seed(5000.0, 120.0, 4000.0)
    assert kind(make_core_for(spec, red, max_delay_ms=250)) == "device"
    # no observation yet: device keeps the benefit of the doubt
    seed()
    assert kind(make_core_for(spec, red, max_delay_ms=250)) == "device"
    # explicit force outranks the budget heuristic
    seed(700.0)
    assert kind(make_core_for(spec, red, max_delay_ms=250,
                              use_resident=True)) == "device"
    # an explicit use_pallas benchmarking request is never silently
    # rerouted to the host core
    assert kind(make_core_for(spec, red, max_delay_ms=250,
                              use_pallas=True)) == "device"
    # and with no budget at all the heuristic never engages
    assert kind(make_core_for(spec, red)) == "device"
