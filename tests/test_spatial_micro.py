"""Spatial (skyline) + microbenchmark suites — src/spatial_test and
src/microbenchmarks analogs: a heavy NIC window function through WF / PF /
WF(PF), differentially checked; the micro pipeline's counters."""

import numpy as np
import pytest

from windflow_tpu.apps.micro import run as micro_run
from windflow_tpu.apps.spatial import (POINT_SCHEMA, SkylinePLQ,
                                       SkylineWindow, SkylineWLQ,
                                       point_batches, skyline, skyline_mask)
from windflow_tpu.core.windows import WinType
from windflow_tpu.patterns.nesting import WinFarmOf
from windflow_tpu.patterns.pane_farm import PaneFarm
from windflow_tpu.patterns.win_farm import WinFarm
from windflow_tpu.patterns.win_seq import WinSeq
from windflow_tpu.patterns.basic import Sink, Source
from windflow_tpu.runtime.engine import Dataflow
from windflow_tpu.runtime.farm import build_pipeline

WIN, SLIDE = 200, 50   # ts units; sliding TB windows


def run_spatial(pattern, batches):
    got = {}

    def snk(row):
        if row is not None:
            got.setdefault(int(row["key"]), []).append(
                (int(row["id"]), int(row["size"]),
                 round(float(row["checksum"]), 6)))

    df = Dataflow()
    build_pipeline(df, [Source(batches=batches, schema=POINT_SCHEMA),
                        pattern, Sink(snk)])
    df.run_and_wait_end()
    return got


# ------------------------------------------------------------ skyline kernel

def test_skyline_mask_basic():
    pts = np.array([[1.0, 1.0], [2.0, 2.0], [0.5, 3.0], [3.0, 0.5]])
    mask = skyline_mask(pts)
    # (2,2) dominated by (1,1); the rest are pareto-optimal
    assert mask.tolist() == [True, False, True, True]


def test_skyline_decomposability():
    rng = np.random.default_rng(1)
    pts = rng.uniform(0, 10, (500, 2))
    direct = skyline(pts)
    merged = skyline(np.concatenate([skyline(pts[:250]),
                                     skyline(pts[250:])]))
    assert sorted(map(tuple, direct)) == sorted(map(tuple, merged))


# ----------------------------------------------------- pattern differentials

@pytest.fixture(scope="module")
def ref_results():
    batches = point_batches(300, keys=2)
    return run_spatial(WinSeq(SkylineWindow(), WIN, SLIDE, WinType.TB),
                       batches), batches


def test_spatial_win_farm(ref_results):
    ref, batches = ref_results
    got = run_spatial(WinFarm(SkylineWindow(), WIN, SLIDE, WinType.TB,
                              pardegree=3), batches)
    assert got == ref


def test_spatial_pane_farm(ref_results):
    """PLQ pane-skylines (object-valued results) merged by the WLQ give the
    same skylines as the monolithic evaluation."""
    ref, batches = ref_results
    got = run_spatial(
        PaneFarm(SkylinePLQ(), SkylineWLQ(), WIN, SLIDE, WinType.TB,
                 plq_degree=2, wlq_degree=2), batches)
    assert got == ref


def test_spatial_nested_wf_of_pf(ref_results):
    ref, batches = ref_results
    inner = PaneFarm(SkylinePLQ(), SkylineWLQ(), WIN, SLIDE, WinType.TB,
                     plq_degree=2, wlq_degree=1)
    got = run_spatial(WinFarmOf(inner, pardegree=2), batches)
    assert got == ref


# -------------------------------------------------------------------- micro

def test_micro_pipeline_counters():
    m = micro_run(duration_sec=0.3, chunk=4096)
    assert m["sent"] > 0
    # Filter keeps value*3 even <=> original value even: exactly half
    assert m["received"] == m["sent"] // 2
    assert m["avg_latency_us"] >= 0


def test_spatial_device_skyline_matches_host():
    """The skyline as an arbitrary JAX window function on the device path
    (WinSeqTPU / WinFarmTPU) must match the host skyline.  Coordinates are
    quantized to a 1/256 grid so the device's float32 compute is exact."""
    from windflow_tpu.apps.spatial import device_skyline
    from windflow_tpu.patterns.win_seq_tpu import WinFarmTPU, WinSeqTPU

    def quantize(b):
        b = b.copy()
        b["x"] = np.round(b["x"] * 256) / 256
        b["y"] = np.round(b["y"] * 256) / 256
        return b

    batches = [quantize(b) for b in point_batches(300, keys=2)]
    host = run_spatial(WinSeq(SkylineWindow(), WIN, SLIDE, WinType.TB),
                       batches)
    dev = run_spatial(WinSeqTPU(device_skyline(), WIN, SLIDE, WinType.TB,
                                batch_len=16), batches)
    assert host == dev
    farm = run_spatial(WinFarmTPU(device_skyline(), WIN, SLIDE, WinType.TB,
                                  pardegree=2, batch_len=8), batches)
    assert host == farm
    # device-RESIDENT variant: the (x, y) columns live in float32 HBM
    # rings (field_dtypes) and cross the wire once, instead of restaging
    # every fired window's rows
    res = run_spatial(WinSeqTPU(device_skyline(), WIN, SLIDE, WinType.TB,
                                batch_len=16, use_resident=True), batches)
    assert host == res


# ----------------------------------------------------------------- k-means

from windflow_tpu.apps.spatial import (KMEANS_FIELDS, KMeansOverSkylines,
                                       KMeansWindow, kmeans_lloyd)
from windflow_tpu.patterns.key_farm import KeyFarm


def run_kmeans(pattern, batches):
    got = {}

    def snk(row):
        if row is not None:
            got.setdefault(int(row["key"]), []).append(
                (int(row["id"]),)
                + tuple(round(float(row[f]), 9) for f in KMEANS_FIELDS
                        if f != "iters"))

    df = Dataflow()
    build_pipeline(df, [Source(batches=batches, schema=POINT_SCHEMA),
                        pattern, Sink(snk)])
    df.run_and_wait_end()
    return got


def test_kmeans_lloyd_recovers_separated_clusters():
    # seed chosen so the deterministic init (the reference's random_my
    # trades cluster quality for reproducibility) lands one seed per
    # cluster; other seeds legitimately converge to local optima
    rng = np.random.default_rng(5)
    centers = np.array([[0.0, 0.0], [50.0, 0.0], [0.0, 50.0]])
    pts = np.concatenate([c + rng.normal(0, 0.5, size=(40, 2))
                          for c in centers])
    means, clusters, iters = kmeans_lloyd(pts)
    assert iters >= 1
    means = means[np.lexsort((means[:, 1], means[:, 0]))]
    np.testing.assert_allclose(means, centers[[0, 2, 1]], atol=1.0)
    assert len(np.unique(clusters)) == 3


def test_kmeans_small_window_edge_cases():
    means, cl, it = kmeans_lloyd(np.zeros((0, 2)))
    assert means.shape == (3, 2) and len(cl) == 0 and it == 0
    means, cl, it = kmeans_lloyd(np.array([[1.0, 2.0], [3.0, 4.0]]))
    assert means.shape == (3, 2)   # padded: fewer points than clusters


def test_kmeans_window_farms_match_seq():
    """The NIC-only heavy path: every whole-window composition of the
    k-means operator equals the sequential core (test_spatial_wf's role
    with KmeansFunction, dkm.hpp:262-276)."""
    batches = point_batches(900, keys=2, chunk=128)
    ref = run_kmeans(WinSeq(KMeansWindow(), WIN, SLIDE, WinType.TB),
                     iter(batches))
    assert ref and all(len(v) > 3 for v in ref.values())
    for comp in (WinFarm(KMeansWindow(), WIN, SLIDE, WinType.TB,
                         pardegree=3),
                 KeyFarm(KMeansWindow(), WIN, SLIDE, WinType.TB,
                         pardegree=2)):
        got = run_kmeans(comp, iter(batches))
        assert got == ref, type(comp).__name__


def test_kmeans_over_skylines_two_stage():
    """skyline (full-content payload) -> windowed k-means over the skyline
    union — the dkm fixture's Iterable<Skyline> signature."""
    from windflow_tpu.core.windows import PatternConfig, Role
    batches = point_batches(600, keys=1, chunk=128)
    # stage 1: per-pane skylines (SkylinePLQ carries the point payload)
    stage1 = WinSeq(SkylinePLQ(), SLIDE, SLIDE, WinType.TB, name="sky",
                    role=Role.PLQ, config=PatternConfig.plain(SLIDE))
    # stage 2: k-means over windows of 4 consecutive skylines
    stage2 = WinSeq(KMeansOverSkylines(), 4, 1, WinType.CB, name="km")
    got = {}

    def snk(row):
        if row is not None:
            got.setdefault(int(row["key"]), []).append(
                tuple(round(float(row[f]), 9) for f in KMEANS_FIELDS
                      if f != "iters"))

    df = Dataflow()
    build_pipeline(df, [Source(batches=iter(batches), schema=POINT_SCHEMA),
                        stage1, stage2, Sink(snk)])
    df.run_and_wait_end()
    assert got and all(len(v) >= 2 for v in got.values())


def test_spatial_pf_opt_levels_match():
    """test_spatial_pf.cpp's --opt flag: the skyline Pane_Farm produces
    identical results at LEVEL0/1/2."""
    batches = point_batches(700, keys=2, chunk=128)
    outs = []
    for lvl in (0, 1, 2):
        pf = PaneFarm(SkylinePLQ(), SkylineWLQ(), WIN, SLIDE, WinType.TB,
                      plq_degree=2, wlq_degree=2, opt_level=lvl)
        outs.append(run_spatial(pf, iter(batches)))
    assert outs[0] == outs[1] == outs[2]
    assert outs[0]
