"""Control-plane tests (windflow_tpu/control/, docs/CONTROL.md): rule
hysteresis/cooldown state machines, the live-rescale differential (a
Key_Farm rescaled N→N+k and back mid-stream must be byte-identical to
the fixed-width oracle, across every host core flavour), adaptive-shed
threshold movement, admission-control rate clamps, the knob-unset
no-import contract, and the new event kinds' schema.
"""

import importlib.util
import os
import subprocess
import sys
import threading
import time
import warnings

import numpy as np
import pytest

from windflow_tpu import (Accumulator, KeyFarm, MultiPipe, OverloadPolicy,
                          RecoveryPolicy, Reducer, Sink, Source, WinFarm)
from windflow_tpu.control import (Admission, AdaptiveShed, ControlPolicy,
                                  Rescale, TokenBucket)
from windflow_tpu.core.tuples import Schema
from windflow_tpu.runtime.engine import Dataflow

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SCHEMA = Schema(value=np.int64)


@pytest.fixture(autouse=True)
def _no_ambient_obs_env(monkeypatch):
    """Ambient WF_LOG_DIR/WF_SAMPLE_PERIOD would change which warnings
    fire and write files the assertions don't expect."""
    monkeypatch.delenv("WF_LOG_DIR", raising=False)
    monkeypatch.delenv("WF_SAMPLE_PERIOD", raising=False)


def keyed_batches(n_batches=60, rows=50, n_keys=13, seed=7):
    """Per-key dense ids / monotone ts — the pristine-source contract."""
    rng = np.random.default_rng(seed)
    ctr = {}
    for _ in range(n_batches):
        b = np.zeros(rows, dtype=SCHEMA.dtype())
        keys = rng.integers(0, n_keys, rows)
        b["key"] = keys
        b["value"] = rng.integers(0, 100, rows)
        for i, k in enumerate(keys.tolist()):
            b["id"][i] = ctr.get(k, 0)
            ctr[k] = ctr.get(k, 0) + 1
        b["ts"] = b["id"]
        yield b


def _run_quiet(fn):
    with warnings.catch_warnings():
        warnings.filterwarnings("ignore", message=r"\[WF20[79]\]")
        return fn()


def per_key(rows):
    """(key, id, value) rows grouped by key, ARRIVAL ORDER KEPT — the
    differential invariant live rescale must preserve is each key's
    result sequence (cross-key interleave is scheduling-dependent in
    both runs); comparing these dicts checks order, drops, and dups at
    once."""
    d = {}
    for k, i, v in rows:
        d.setdefault(k, []).append((i, v))
    return d


#: a Rescale rule that never fires on its own — scripted requests only
def _manual_rule(max_workers=4):
    return Rescale("kf", max_workers=max_workers, min_workers=1,
                   up_depth=10 ** 9, down_depth=-1, cooldown=10 ** 9)


# --------------------------------------------------------------- policy


def test_policy_validation():
    with pytest.raises(ValueError, match="at least one rule"):
        ControlPolicy([])
    with pytest.raises(TypeError, match="unknown rule"):
        ControlPolicy([object()])
    with pytest.raises(ValueError, match="period"):
        ControlPolicy([_manual_rule()], period=0)
    with pytest.raises(ValueError, match="duplicate Rescale"):
        ControlPolicy([_manual_rule(), _manual_rule()])
    with pytest.raises(ValueError, match="AdaptiveShed"):
        ControlPolicy([AdaptiveShed(8, 2), AdaptiveShed(9, 3)])
    with pytest.raises(ValueError, match="max_workers"):
        Rescale("kf", max_workers=2, min_workers=2)
    with pytest.raises(ValueError, match="low threshold"):
        Rescale("kf", max_workers=4, up_depth=2, down_depth=5)
    with pytest.raises(ValueError, match="min_rate"):
        Admission(max_rate=10, min_rate=20, high_depth=8, low_depth=2)
    with pytest.raises(ValueError, match="down"):
        Admission(max_rate=10, min_rate=1, high_depth=8, low_depth=2,
                  down=1.5)
    with pytest.raises(ValueError, match="overlapping Admission"):
        ControlPolicy([
            Admission(max_rate=10, min_rate=1, high_depth=8,
                      low_depth=2),
            Admission(max_rate=5, min_rate=1, high_depth=8,
                      low_depth=2)])
    # distinct source patterns may each carry their own cap
    ControlPolicy([
        Admission(max_rate=10, min_rate=1, high_depth=8, low_depth=2,
                  pattern="a"),
        Admission(max_rate=5, min_rate=1, high_depth=8, low_depth=2,
                  pattern="b")])
    with pytest.raises(TypeError, match="ControlPolicy"):
        Dataflow("x", control=object())


def test_rescale_without_recovery_refused():
    with pytest.raises(ValueError, match="WF211"):
        Dataflow("x", control=ControlPolicy([_manual_rule()]))
    # non-rescale rules need no recovery
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        Dataflow("x", control=ControlPolicy(
            [Admission(max_rate=10, min_rate=1, high_depth=8,
                       low_depth=2)]))


def test_blind_control_warns_wf209():
    with pytest.warns(UserWarning, match=r"WF209.*blind"):
        Dataflow("x", metrics=None, recovery=RecoveryPolicy(),
                 control=ControlPolicy([_manual_rule()]))


def test_policy_agreement():
    def mk():
        return ControlPolicy([_manual_rule(),
                              AdaptiveShed(8, 2)], period=0.5)
    assert mk().agrees_with(mk())
    other = ControlPolicy([_manual_rule()], period=0.5)
    assert not mk().agrees_with(other)


# ------------------------------------------------- rule state machines


def test_hysteresis_requires_consecutive_samples():
    r = AdaptiveShed(high_depth=10, low_depth=2, hysteresis=3,
                     cooldown=0.0)
    assert r.observe(12, 0.0) == 0
    assert r.observe(12, 1.0) == 0
    assert r.observe(12, 2.0) == 1          # third consecutive high
    # a low sample resets the high streak
    assert r.observe(12, 3.0) == 0
    assert r.observe(1, 4.0) == 0
    assert r.observe(12, 5.0) == 0
    assert r.observe(12, 6.0) == 0
    assert r.observe(12, 7.0) == 1


def test_cooldown_suppresses_actions():
    r = AdaptiveShed(high_depth=10, low_depth=2, hysteresis=1,
                     cooldown=5.0)
    assert r.observe(12, 0.0) == 1
    assert r.observe(12, 1.0) == 0          # inside the cooldown
    assert r.observe(12, 4.9) == 0
    assert r.observe(12, 5.0) == 1          # cooldown elapsed
    assert r.observe(1, 10.1) == -1         # low side symmetric


def test_rescale_rule_shed_signal():
    r = Rescale("kf", max_workers=4, up_depth=100, down_depth=0,
                up_shed=50.0, hysteresis=1, cooldown=0.0)
    assert r.observe((0, 80.0), 0.0) == 1   # shed rate alone scales up
    assert r.observe((0, 0.0), 1.0) == -1   # idle depth scales down
    assert r.observe((5, 0.0), 2.0) == 0    # neither side


def test_token_bucket_rates_and_debt():
    b = TokenBucket(rate=1000.0, burst=100.0)
    t0 = time.monotonic()
    b.throttle(100)                          # the full burst: immediate
    b.throttle(500)                          # > burst: debt, rate-bound
    b.throttle(1)
    dt = time.monotonic() - t0
    assert dt >= 0.4, f"600 tokens at 1000/s took only {dt:.3f}s"


# ------------------------------------------- live-rescale differential


def _kf_pattern(flavour):
    if flavour == "tumbling":        # VecIncTumblingCore
        return KeyFarm(Reducer("sum", "value"), win_len=4, slide_len=4,
                       pardegree=2, name="kf")
    if flavour in ("sliding", "sliding_vec"):  # LazySlidingCore
        return KeyFarm(Reducer("sum", "value"), win_len=8, slide_len=4,
                       pardegree=2, name="kf")
    if flavour == "nic":             # general WinSeqCore, NIC archive
        return KeyFarm(lambda key, gwid, rows: (int(rows["value"].sum()),),
                       win_len=8, slide_len=4, pardegree=2, name="kf",
                       result_fields={"value": np.int64})
    raise AssertionError(flavour)


def _build_pipe(out, pattern, control=None, recovery=None, metrics=None):
    pipe = MultiPipe("job", capacity=8, recovery=recovery,
                     metrics=metrics, control=control)
    pipe.add_source(Source(batches=lambda i: keyed_batches(),
                           name="src"))
    pipe.add(pattern)
    pipe.add_sink(Sink(
        lambda r: out.append((int(r["key"]), int(r["id"]),
                              int(r["value"])))
        if r is not None else None, name="sink"))
    return pipe


def _await_width(ctl, width, timeout=60.0):
    t0 = time.monotonic()
    while ctl.width_of("kf") != width:
        if time.monotonic() - t0 > timeout:
            raise AssertionError(
                f"rescale to {width} did not land in {timeout}s "
                f"(width {ctl.width_of('kf')})")
        time.sleep(0.01)


@pytest.mark.parametrize("flavour", ["tumbling", "sliding",
                                     "sliding_vec", "nic"])
def test_keyfarm_rescale_up_and_back_matches_oracle(flavour):
    """Acceptance (ISSUE 12): a Key_Farm rescaled N→N+k and back
    mid-stream produces output identical to the fixed-width oracle —
    per-key order preserved, no drops, no dups — across every host core
    flavour (vec tumbling, lazy sliding per-key and lane-escalated,
    general NIC)."""
    oracle = []
    _build_pipe(oracle, _kf_pattern(flavour)).run_and_wait_end(timeout=120)

    got = []
    pipe = _build_pipe(
        got, _kf_pattern(flavour),
        control=ControlPolicy([_manual_rule()], period=0.02),
        recovery=RecoveryPolicy(epoch_batches=5, restart_backoff=0.01),
        metrics=True)
    if flavour == "sliding_vec":
        # pin the lazy cores' crossover BEFORE run so the first chunk
        # escalates to the lane-vectorised sliding core on every worker
        for n in pipe._build().nodes:
            core = getattr(n, "core", None)
            if core is not None and hasattr(core, "_threshold"):
                core._threshold = 1
    _run_quiet(pipe.run)
    ctl = pipe.controller
    assert ctl.request_rescale("kf", 4)
    _await_width(ctl, 4)
    assert ctl.request_rescale("kf", 2)
    pipe.wait(timeout=120)
    history = [h for fc in ctl.farms for h in fc.history]
    assert history and history[0][:2] == (2, 4), history
    assert per_key(got) == per_key(oracle)


def test_keyfarm_scale_down_matches_oracle():
    oracle = []
    _build_pipe(oracle, KeyFarm(Reducer("sum", "value"), 8, 4,
                                pardegree=3, name="kf")
                ).run_and_wait_end(timeout=120)
    got = []
    pipe = _build_pipe(
        got, KeyFarm(Reducer("sum", "value"), 8, 4, pardegree=3,
                     name="kf"),
        control=ControlPolicy([_manual_rule()], period=0.02),
        recovery=RecoveryPolicy(epoch_batches=4, restart_backoff=0.01),
        metrics=True)
    _run_quiet(pipe.run)
    assert pipe.controller.request_rescale("kf", 1)
    pipe.wait(timeout=120)
    assert per_key(got) == per_key(oracle)


def test_accumulator_farm_rescale_matches_oracle():
    """Keyed Accumulator farms migrate their fold dicts."""
    def acc():
        a = Accumulator(lambda row, a_: a_.__setitem__(
            "value", a_["value"] + row["value"]), SCHEMA, parallelism=2,
            name="kf")
        return a

    oracle = []
    _build_pipe(oracle, acc()).run_and_wait_end(timeout=120)
    got = []
    pipe = _build_pipe(
        got, acc(),
        control=ControlPolicy([_manual_rule()], period=0.02),
        recovery=RecoveryPolicy(epoch_batches=5, restart_backoff=0.01),
        metrics=True)
    _run_quiet(pipe.run)
    ctl = pipe.controller
    assert ctl.request_rescale("kf", 4)
    _await_width(ctl, 4)
    assert ctl.request_rescale("kf", 2)
    pipe.wait(timeout=120)
    assert per_key(got) == per_key(oracle)


def test_threshold_driven_rescale_differential():
    """Rule-driven (not scripted) scale-up under a slow sink still
    matches the oracle, and the decision surfaces in ctl_* metrics."""
    def build(out, **kw):
        pipe = MultiPipe("job", capacity=4, **kw)
        pipe.add_source(Source(batches=lambda i: keyed_batches(),
                               name="src"))
        pipe.add(KeyFarm(Reducer("sum", "value"), 4, 4, pardegree=2,
                         name="kf"))
        def sink(r):
            if r is not None:
                time.sleep(0.0002)
                out.append((int(r["key"]), int(r["id"]),
                            int(r["value"])))
        pipe.add_sink(Sink(sink, name="sink"))
        return pipe

    oracle = []
    build(oracle).run_and_wait_end(timeout=120)
    got = []
    pipe = build(got, control=ControlPolicy(
        [Rescale("kf", max_workers=4, min_workers=1, up_depth=1,
                 down_depth=-1, hysteresis=1, cooldown=0.0)],
        period=0.02),
        recovery=RecoveryPolicy(epoch_batches=4, restart_backoff=0.01),
        metrics=True)
    _run_quiet(lambda: pipe.run_and_wait_end(timeout=120))
    hist = [h for fc in pipe.controller.farms for h in fc.history]
    assert hist, "threshold rule never fired"
    snap = pipe.metrics.snapshot()
    assert snap["counters"]["ctl_rescale_up"] >= 1
    assert snap["gauges"]["ctl_width_kf"] == hist[-1][1]
    assert per_key(got) == per_key(oracle)


def test_native_keyfarm_threshold_rescale_matches_oracle():
    """ISSUE 17 acceptance: a threshold-driven Rescale on a Key_Farm of
    native C++ cores migrates per-key wf_core state at the epoch
    barrier — per-key result sequences identical to the fixed-width
    oracle (order, drops, dups checked per key)."""
    from windflow_tpu.native import enabled
    lib = enabled()
    if lib is None or not getattr(lib, "wf_has_state_abi", False):
        pytest.skip("native library with the state ABI unavailable")
    from windflow_tpu.patterns.native_core import NativeResidentCore
    from windflow_tpu.patterns.win_seq_tpu import KeyFarmTPU

    def build(out, **kw):
        pipe = MultiPipe("job", capacity=4, **kw)
        pipe.add_source(Source(batches=lambda i: keyed_batches(),
                               name="src"))
        pipe.add(KeyFarmTPU(Reducer("sum", "value"), 8, 4, pardegree=2,
                            batch_len=64, name="kf"))

        def sink(r):
            if r is not None:
                time.sleep(0.0002)    # slow sink: inbox depth drives the rule
                out.append((int(r["key"]), int(r["id"]),
                            int(r["value"])))
        pipe.add_sink(Sink(sink, name="sink"))
        return pipe

    with warnings.catch_warnings():
        warnings.simplefilter("ignore")   # int32-accumulation advisory
        oracle = []
        build(oracle).run_and_wait_end(timeout=300)
        got = []
        pipe = build(got, control=ControlPolicy(
            [Rescale("kf", max_workers=4, min_workers=1, up_depth=1,
                     down_depth=-1, hysteresis=1, cooldown=0.0)],
            period=0.02),
            recovery=RecoveryPolicy(epoch_batches=4,
                                    restart_backoff=0.01),
            metrics=True)
        df = pipe._build()
        workers = [n for n in df.nodes if n.name.startswith("kf.")
                   and "emitter" not in n.name
                   and "collector" not in n.name]
        assert workers
        for w in workers:
            assert isinstance(w.core, NativeResidentCore)
            assert w.core.has_state_abi and w.core.keyed_migratable
        pipe.run_and_wait_end(timeout=300)
    hist = [h for fc in pipe.controller.farms for h in fc.history]
    assert hist, "threshold rule never fired"
    assert per_key(got) == per_key(oracle)


def test_crash_after_rescale_restores_migrated_placement():
    """A worker crash after a completed rescale restores the
    POST-migration snapshot (re-committed through the writer path) and
    still matches the oracle."""
    oracle = []
    _build_pipe(oracle, KeyFarm(Reducer("sum", "value"), 4, 4,
                                pardegree=2, name="kf")
                ).run_and_wait_end(timeout=120)
    got = []
    pipe = _build_pipe(
        got, KeyFarm(Reducer("sum", "value"), 4, 4, pardegree=2,
                     name="kf"),
        control=ControlPolicy([_manual_rule()], period=0.02),
        recovery=RecoveryPolicy(epoch_batches=4, restart_backoff=0.01),
        metrics=True)
    df = pipe._build()
    workers = [n for n in df.nodes if n.name.startswith("kf.")
               and "emitter" not in n.name and "collector" not in n.name]
    assert len(workers) == 4          # pre-provisioned to max_workers
    state = {"n": 0, "fired": False}
    for w in workers:
        orig = w.svc

        def svc(batch, channel=0, _o=orig):
            state["n"] += 1
            if not state["fired"] and state["n"] == 30:
                state["fired"] = True
                raise RuntimeError("injected crash post-rescale")
            return _o(batch, channel)

        w.svc = svc
    _run_quiet(pipe.run)
    ctl = pipe.controller
    assert ctl.request_rescale("kf", 4)
    pipe.wait(timeout=120)
    assert state["fired"], "kill point never reached"
    assert [h[:2] for fc in ctl.farms for h in fc.history] == [(2, 4)]
    assert per_key(got) == per_key(oracle)


def test_migration_failure_fails_graph_without_restart():
    """A fault inside the migration leaves sibling cores inconsistent:
    the graph must fail like the seed engine (RescaleError is never
    restored through), not restart into silently-wrong state."""
    from windflow_tpu.control import RescaleError
    pipe = _build_pipe(
        [], KeyFarm(Reducer("sum", "value"), 4, 4, pardegree=2,
                    name="kf"),
        control=ControlPolicy([_manual_rule()], period=0.02),
        recovery=RecoveryPolicy(epoch_batches=4, restart_backoff=0.01,
                                max_restarts=5),
        metrics=True)
    df = pipe._build()
    for n in df.nodes:
        core = getattr(n, "core", None)
        if core is not None and hasattr(core, "keyed_state_import"):
            def bad_import(frag, _c=core):
                raise RuntimeError("injected migration fault")
            core.keyed_state_import = bad_import
    _run_quiet(pipe.run)
    assert pipe.controller.request_rescale("kf", 4)
    with pytest.raises(RescaleError):
        pipe.wait(timeout=120)
    ev = [e for e in pipe.events.recent if e["event"] == "node_error"]
    assert any("migration" in e.get("message", "") for e in ev)


def test_rescale_rule_targeting_winfarm_refused():
    """Window-parallel farms own window slices, not keys: the wiring
    layer refuses the rule loudly (WF210; docs/CONTROL.md)."""
    pipe = _build_pipe(
        [], WinFarm(Reducer("sum", "value"), 8, 4, pardegree=2,
                    name="kf"),
        control=ControlPolicy([_manual_rule()], period=0.05),
        recovery=RecoveryPolicy(epoch_batches=5), metrics=True)
    with pytest.raises(ValueError, match="WF210"):
        _run_quiet(pipe.run)


def test_rescale_rule_targeting_device_core_refused(monkeypatch):
    """Device cores INHERIT the host keyed hooks from WinSeqCore but
    mirror per-key rows into HBM rings the hooks cannot move — the
    keyed_migratable opt-out must make attach refuse (both the native
    and the Python resident core)."""
    from windflow_tpu import KeyFarmTPU
    monkeypatch.setenv("WF_NO_NATIVE_CORE", "1")
    pipe = MultiPipe("dev", metrics=True,
                     recovery=RecoveryPolicy(epoch_batches=3),
                     control=ControlPolicy([_manual_rule()], period=0.05))
    pipe.add_source(Source(batches=lambda i: keyed_batches(n_batches=2),
                           name="src"))
    pipe.add(KeyFarmTPU(Reducer("sum", "value"), 4, 4, pardegree=2,
                        name="kf", batch_len=8))
    pipe.add_sink(Sink(lambda r: None, name="sink"))
    with pytest.raises(ValueError, match="keyed-state migration"):
        _run_quiet(pipe.run)


def test_rescale_rule_unknown_pattern_refused():
    pipe = _build_pipe(
        [], KeyFarm(Reducer("sum", "value"), 8, 4, pardegree=2,
                    name="other"),
        control=ControlPolicy([_manual_rule()], period=0.05),
        recovery=RecoveryPolicy(epoch_batches=5), metrics=True)
    with pytest.raises(ValueError, match="no key-partitioned farm"):
        _run_quiet(pipe.run)


# ------------------------------------------- adaptive shed / admission


def _overload_pipe(out, control):
    pipe = MultiPipe("ovl", capacity=4, metrics=True,
                     overload=OverloadPolicy(shed="shed_oldest"),
                     control=control)
    pipe.add_source(Source(batches=lambda i: keyed_batches(n_batches=80),
                           name="src"))

    def sink(r):
        if r is not None:
            time.sleep(0.001)
            out.append(1)

    pipe.add_sink(Sink(sink, name="sink"))
    return pipe


def test_adaptive_shed_moves_soft_limit():
    got = []
    pipe = _overload_pipe(got, ControlPolicy(
        [AdaptiveShed(high_depth=3, low_depth=0, min_limit=1, step=1,
                      hysteresis=1, cooldown=0.0)], period=0.02))
    _run_quiet(lambda: pipe.run_and_wait_end(timeout=180))
    snap = pipe.metrics.snapshot()
    assert snap["counters"].get("ctl_shed_tighten", 0) >= 1
    # the policy object itself moved (min_limit clamps the floor)
    lim = pipe._df.overload.soft_limit
    assert lim is None or lim >= 1
    assert got, "sink starved"


def test_adaptive_shed_requires_shedding_policy():
    pipe = MultiPipe("ovl", capacity=4, metrics=True,
                     control=ControlPolicy([AdaptiveShed(3, 0)],
                                           period=0.05))
    pipe.add_source(Source(batches=lambda i: keyed_batches(n_batches=2),
                           name="src"))
    pipe.add_sink(Sink(lambda r: None, name="sink"))
    with pytest.raises(ValueError, match="AdaptiveShed"):
        _run_quiet(pipe.run)


def test_admission_rate_clamped_and_content_preserved():
    """Admission throttling delays emission but never drops: content is
    oracle-identical, the rate gauge moves and respects min_rate."""
    def build(out, control=None):
        pipe = MultiPipe("adm", capacity=4,
                         metrics=True if control else None,
                         control=control)
        pipe.add_source(Source(
            batches=lambda i: keyed_batches(n_batches=30), name="src"))

        def sink(r):
            if r is not None:
                time.sleep(0.0005)
                out.append((int(r["key"]), int(r["id"]),
                            int(r["value"])))

        pipe.add_sink(Sink(sink, name="sink"))
        return pipe

    oracle = []
    build(oracle).run_and_wait_end(timeout=120)
    got = []
    min_rate = 2e4
    pipe = build(got, ControlPolicy(
        [Admission(max_rate=1e6, min_rate=min_rate, high_depth=2,
                   low_depth=0, hysteresis=1, cooldown=0.0)],
        period=0.02))
    _run_quiet(lambda: pipe.run_and_wait_end(timeout=180))
    snap = pipe.metrics.snapshot()
    assert snap["counters"].get("ctl_admission_down", 0) >= 1
    assert snap["gauges"]["ctl_admission_rate"] >= min_rate
    assert per_key(got) == per_key(oracle)


def test_rescale_width_outside_rule_range_reported_not_raised():
    """A pre-build conflict the wiring layer refuses (declared width
    outside the rule's range) must surface as a WF210 diagnostic from
    validate()/wf-lint, not as a raw build ValueError."""
    from windflow_tpu.check import validate
    pipe = _build_pipe(
        [], KeyFarm(Reducer("sum", "value"), 8, 4, pardegree=6,
                    name="kf"),
        control=ControlPolicy([_manual_rule(max_workers=4)],
                              period=0.05),
        recovery=RecoveryPolicy(epoch_batches=5), metrics=True)
    report = validate(pipe)
    assert "WF210" in report.codes(), report.render()
    with pytest.raises(ValueError, match="outside"):
        _run_quiet(pipe.run)


def test_admission_replica_name_overlap_refused():
    """'src' and 'src.0' both match replica src.0: the attach-time
    guard must refuse the double wrap the policy check cannot see."""
    pipe = MultiPipe("adm2", metrics=True, control=ControlPolicy([
        Admission(max_rate=10, min_rate=1, high_depth=8, low_depth=2,
                  pattern="src"),
        Admission(max_rate=5, min_rate=1, high_depth=8, low_depth=2,
                  pattern="src.0"),
    ], period=0.05))
    pipe.add_source(Source(batches=lambda i: keyed_batches(n_batches=2),
                           name="src"))
    pipe.add_sink(Sink(lambda r: None, name="sink"))
    with pytest.raises(ValueError, match="double-throttle"):
        _run_quiet(pipe.run)


def test_admission_unknown_source_refused():
    pipe = MultiPipe("adm", metrics=True, control=ControlPolicy(
        [Admission(max_rate=10, min_rate=1, high_depth=8, low_depth=2,
                   pattern="nosuch")], period=0.05))
    pipe.add_source(Source(batches=lambda i: keyed_batches(n_batches=2),
                           name="src"))
    pipe.add_sink(Sink(lambda r: None, name="sink"))
    with pytest.raises(ValueError, match="Admission"):
        _run_quiet(pipe.run)


# ----------------------------------------------------------------- drain


def test_drain_quiesces_then_resumes_content_preserved():
    """request_drain gates the sources and settles the graph; release
    resumes exactly where it parked — content oracle-identical, the
    drain observable as counter + gauge + events."""
    from windflow_tpu.control import Drain

    def build(out, control=None):
        pipe = MultiPipe("drn", capacity=4,
                         metrics=True if control else None,
                         control=control)
        pipe.add_source(Source(
            batches=lambda i: keyed_batches(n_batches=40), name="src"))

        def sink(r):
            if r is not None:
                time.sleep(0.0005)
                out.append((int(r["key"]), int(r["id"]),
                            int(r["value"])))

        pipe.add_sink(Sink(sink, name="sink"))
        return pipe

    oracle = []
    build(oracle).run_and_wait_end(timeout=120)
    got = []
    pipe = build(got, ControlPolicy(
        [Drain(deadline=30.0, poll=0.01)], period=0.05))
    _run_quiet(pipe.run)
    time.sleep(0.05)                    # let some rows flow
    assert pipe.request_drain() is True
    assert pipe.controller.draining
    # inboxes are empty; the batch the sink had already popped may
    # still be mid-iteration — let it finish, then nothing moves
    time.sleep(0.3)
    n_at_drain = len(got)
    time.sleep(0.3)
    assert len(got) == n_at_drain
    # idempotent while draining
    assert pipe.request_drain(timeout=5.0) is True
    pipe.release_drain()
    assert not pipe.controller.draining
    pipe.wait(timeout=120)
    assert per_key(got) == per_key(oracle)
    snap = pipe.metrics.snapshot()
    assert snap["counters"].get("ctl_drains", 0) == 1
    assert snap["gauges"]["ctl_draining"] == 0
    phases = [e.get("phase") for e in pipe.events.recent
              if e["event"] == "drain"]
    assert phases[:2] == ["requested", "quiesced"]
    assert "released" in phases


def test_drain_without_rule_or_run_refused():
    from windflow_tpu.control import Drain
    with pytest.raises(ValueError, match="one Drain"):
        ControlPolicy([Drain(), Drain()])
    pipe = MultiPipe("drn2", capacity=4, metrics=True,
                     control=ControlPolicy([AdaptiveShed(3, 0)],
                                           period=0.05))
    with pytest.raises(RuntimeError, match="running"):
        pipe.request_drain()
    pipe2 = MultiPipe(
        "drn3", capacity=4, metrics=True,
        overload=OverloadPolicy(shed="shed_oldest"),
        control=ControlPolicy([AdaptiveShed(3, 0)], period=0.05))
    pipe2.add_source(Source(batches=lambda i: keyed_batches(n_batches=2),
                            name="src"))
    pipe2.add_sink(Sink(lambda r: None, name="sink"))
    _run_quiet(pipe2.run)
    try:
        with pytest.raises(RuntimeError, match="Drain"):
            pipe2.request_drain()
    finally:
        pipe2.wait(timeout=60)


# ------------------------------------------------------ sampler/obs/ui


def test_sampler_subscribe_receives_snapshots_and_survives_errors():
    from windflow_tpu.obs.sampler import Sampler
    from windflow_tpu.runtime.farm import build_pipeline
    got, bad = [], []
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        df2 = Dataflow("sub", capacity=8, metrics=True,
                       sample_period=0.02)
    build_pipeline(df2, [
        Source(batches=lambda i: keyed_batches(n_batches=20),
               name="src"),
        Sink(lambda r: time.sleep(0.005) if r is not None else None,
             vectorized=True, name="sink"),
    ])

    def boom(rec):
        bad.append(rec)
        raise RuntimeError("bad subscriber")

    df2.run()
    sampler = df2._sampler
    assert isinstance(sampler, Sampler)
    sampler.subscribe(boom)
    sampler.subscribe(got.append)
    df2.wait(timeout=60)
    assert got and bad, "subscribers never called"
    assert isinstance(sampler.sub_error, RuntimeError)
    # the good subscriber kept receiving after the bad one raised
    assert {r["dataflow"] for r in got} == {"sub"}
    assert all("nodes" in r for r in got)


def test_control_events_schema_and_files(tmp_path):
    """control/rescale events pass the documented schema end-to-end
    (obs_schema) and land in events.jsonl."""
    from obs_schema import validate_event, validate_file
    got = []
    pipe = _build_pipe(
        got, KeyFarm(Reducer("sum", "value"), 4, 4, pardegree=2,
                     name="kf"),
        control=ControlPolicy([_manual_rule()], period=0.02),
        recovery=RecoveryPolicy(epoch_batches=4, restart_backoff=0.01),
        metrics=True)
    pipe.trace_dir = str(tmp_path)
    pipe.run()
    ctl = pipe.controller
    assert ctl.request_rescale("kf", 3)
    pipe.wait(timeout=120)
    kinds = {e["event"] for e in pipe.events.recent}
    assert {"control", "rescale"} <= kinds, kinds
    for e in pipe.events.recent:
        validate_event(e)
    n = validate_file(os.path.join(str(tmp_path), "events.jsonl"),
                      validate_event)
    assert n > 0


def test_wf_top_renders_control_line():
    spec = importlib.util.spec_from_file_location(
        "wf_top", os.path.join(REPO, "scripts", "wf_top.py"))
    wf_top = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(wf_top)
    sample = {
        "t": time.time(), "seq": 3, "dataflow": "job", "nodes": [],
        "dead_letters": 0,
        "counters": {"ctl_rescale_up": 2, "ctl_decisions": 5,
                     "other": 1},
        "gauges": {"ctl_width_kf": 4.0, "ctl_admission_rate": 50000.0,
                   "ctl_soft_limit": 12.0},
        "histograms": {},
    }
    frame = wf_top.render(sample, None)
    assert "control:" in frame
    assert "width[kf]=4" in frame
    assert "admit[*]=50000/s" in frame
    assert "soft_limit=12" in frame
    assert "rescale_up=2" in frame
    # ctl counters live on the control line, not the counters line
    assert "counters: other=1" in frame


# ------------------------------------------------------- knob contract


def test_control_unset_never_imports_package():
    """Seed contract: control= unset => windflow_tpu.control is never
    imported (subprocess keeps sys.modules clean)."""
    code = (
        "import sys\n"
        "import numpy as np\n"
        "from windflow_tpu.api import MultiPipe\n"
        "from windflow_tpu.core.tuples import Schema\n"
        "from windflow_tpu.patterns.basic import Sink, Source\n"
        "S = Schema(value=np.int64)\n"
        "def gen(sh):\n"
        "    sh.push(key=0, id=0, ts=0, value=1)\n"
        "got = []\n"
        "p = (MultiPipe('seed')\n"
        "     .add_source(Source(gen, S))\n"
        "     .chain_sink(Sink(lambda b: got.append(b),"
        " vectorized=True)))\n"
        "p.run_and_wait_end()\n"
        "assert any(b is not None and len(b) for b in got)\n"
        "bad = [m for m in sys.modules"
        " if m.startswith('windflow_tpu.control')]\n"
        "assert not bad, f'control package imported on seed path: {bad}'\n"
    )
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    r = subprocess.run([sys.executable, "-c", code], cwd=REPO, env=env,
                       capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, r.stdout + r.stderr


def test_preview_build_keeps_initial_width():
    """getNumThreads() before run() must not promote the pre-provisioned
    ceiling into the initial active width (the preview build provisions
    the same pattern object)."""
    oracle = []
    _build_pipe(oracle, KeyFarm(Reducer("sum", "value"), 4, 4,
                                pardegree=2, name="kf")
                ).run_and_wait_end(timeout=120)
    got = []
    pipe = _build_pipe(
        got, KeyFarm(Reducer("sum", "value"), 4, 4, pardegree=2,
                     name="kf"),
        control=ControlPolicy([_manual_rule()], period=0.05),
        recovery=RecoveryPolicy(epoch_batches=5, restart_backoff=0.01),
        metrics=True)
    n = pipe.getNumThreads()
    _run_quiet(lambda: pipe.run_and_wait_end(timeout=120))
    assert pipe.getNumThreads() == n      # preview == materialised
    assert pipe.controller.width_of("kf") == 2
    assert per_key(got) == per_key(oracle)


def test_union_control_policies_must_agree():
    from windflow_tpu import union_multipipes

    def gen(sh):
        sh.push(key=0, id=0, ts=0, value=1)

    def mk(name, pol):
        p = MultiPipe(name, metrics=True, control=pol)
        p.add_source(Source(gen, SCHEMA))
        return p

    adm = [Admission(max_rate=10, min_rate=1, high_depth=8, low_depth=2)]
    u = union_multipipes(mk("a", ControlPolicy(adm)), mk("b", None))
    assert u.control is not None
    with pytest.raises(ValueError, match="conflicting control"):
        union_multipipes(
            mk("c", ControlPolicy(adm)),
            mk("d", ControlPolicy(adm, period=9.0)))


def test_blind_control_runs_without_controller():
    """control= without metrics/sample_period: warned (WF209) and
    inert, but the graph still runs to completion."""
    got = []
    pipe = _build_pipe(
        got, KeyFarm(Reducer("sum", "value"), 4, 4, pardegree=2,
                     name="kf"),
        control=ControlPolicy([_manual_rule()], period=0.05),
        recovery=RecoveryPolicy(epoch_batches=10))
    with pytest.warns(UserWarning, match="WF209"):
        pipe.run_and_wait_end(timeout=120)
    assert pipe.controller is None
    assert got


# ------------------------------------------------------------- soak slice


@pytest.mark.slow
def test_soak_rescale_slice():
    """Small in-suite slice of scripts/soak_rescale.py (the full soak is
    a standalone seeded harness, docs/CONTROL.md)."""
    spec = importlib.util.spec_from_file_location(
        "soak_rescale", os.path.join(REPO, "scripts", "soak_rescale.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    total = 0
    for case in range(6):
        total += mod.run_case(seed=23, case=case)["rescales"]
    assert total > 0, "no rescale completed across the slice"
