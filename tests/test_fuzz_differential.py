"""Randomized cross-core differential fuzz: deterministic random window
configs (shape, type, role, cardinality, disorder, markers) run through
every eligible core implementation — the auto-selected host core, the
pure-Python resident device core, and the native C++ core — and each must
be row-identical per key to the reference ``WinSeqCore`` NIC oracle.

This widens the hand-picked differential matrices the same way the
reference's randomized-parallelism pipe tests widen its fixed suites
(test_pipe_wf_cb.cpp:233-264's re-drawn mt19937 degrees)."""

import warnings

import numpy as np
import pytest

from windflow_tpu.core.windows import PatternConfig, Role, WindowSpec, WinType
from windflow_tpu.core.winseq import WinSeqCore
from windflow_tpu.ops.functions import Reducer

from test_vecinc import assert_equivalent, make_stream, run_core

OPS = ["sum", "min", "max", "count"]
ROLES = [(Role.SEQ, None, (0, 1)),
         (Role.PLQ, PatternConfig(0, 1, 6, 1, 2, 6), (0, 1)),
         (Role.MAP, PatternConfig(0, 1, 6, 0, 1, 6), (1, 3))]


def draw_config(seed):
    rng = np.random.default_rng(1000 + seed)
    win = int(rng.integers(1, 20))
    slide = int(rng.integers(1, 20))
    wt = WinType.CB if rng.random() < 0.6 else WinType.TB
    n_keys = int(rng.choice([3, 17, 120]))
    op = OPS[seed % len(OPS)]
    role, cfg, mi = ROLES[seed % len(ROLES)] if wt is WinType.CB \
        else ROLES[0]
    stream_kw = dict(ooo_frac=float(rng.choice([0.0, 0.15])),
                     gaps=bool(rng.random() < 0.5),
                     markers_at_end=bool(rng.random() < 0.7))
    return win, slide, wt, n_keys, op, role, cfg, mi, stream_kw


@pytest.mark.parametrize("seed", range(16))
def test_fuzz_host_core_selection(seed):
    """Whatever core WinSeq.make_core selects for the drawn config must
    match the reference WinSeqCore oracle row-for-row."""
    from windflow_tpu.patterns.win_seq import WinSeq
    win, slide, wt, n_keys, op, role, cfg, mi, skw = draw_config(seed)
    rng = np.random.default_rng(2000 + seed)
    chunks = make_stream(rng, n_keys, 5, 160, **skw)
    spec = WindowSpec(win, slide, wt)
    red = Reducer(op, out_field="r")
    oracle = run_core(WinSeqCore(spec, red, config=cfg, role=role,
                                 map_indexes=mi), chunks)
    got = run_core(
        WinSeq(Reducer(op, out_field="r"), win, slide, wt, config=cfg,
               role=role, map_indexes=mi).make_core(), chunks)
    assert_equivalent(got, oracle)
    if slide < win:
        # the drawn cardinalities sit below the lazy selector's default
        # threshold, so force BOTH sliding-core regimes through the same
        # config: the lane core directly, and the selector driven through
        # a REAL mid-stream escalation (a single-key prefix chunk keeps
        # the first pick on the per-key core; the following chunks cross
        # the tiny threshold and migrate)
        from windflow_tpu.core.vecinc import (LazySlidingCore,
                                              VecIncSlidingCore,
                                              vec_core_supported)
        assert vec_core_supported(spec, red)   # drawn ranges: W <= 19
        direct = run_core(
            VecIncSlidingCore(spec, Reducer(op, out_field="r"),
                              config=cfg, role=role, map_indexes=mi),
            chunks)
        assert_equivalent(direct, oracle)
        from windflow_tpu.core.tuples import batch_from_columns
        from test_vecinc import SCHEMA
        pre = batch_from_columns(SCHEMA, key=np.zeros(6),
                                 id=np.arange(6), ts=np.arange(6) * 3,
                                 value=np.arange(6))
        esc_chunks = [pre] + chunks
        esc_oracle = run_core(WinSeqCore(spec, Reducer(op, out_field="r"),
                                         config=cfg, role=role,
                                         map_indexes=mi), esc_chunks)
        lazy = LazySlidingCore(spec, Reducer(op, out_field="r"),
                               threshold=2, config=cfg, role=role,
                               map_indexes=mi)
        assert_equivalent(run_core(lazy, esc_chunks), esc_oracle)
        assert isinstance(lazy._core, VecIncSlidingCore), \
            "escalation never happened: the branch is vacuous"


@pytest.mark.parametrize("seed", range(0, 16, 3))
def test_fuzz_device_cores(seed):
    """The resident device cores (Python and native C++) on the same
    drawn configs — device dispatch, coalescing, and EOS padding under
    random shapes must stay oracle-identical."""
    from windflow_tpu.patterns.win_seq_tpu import make_core_for
    win, slide, wt, n_keys, op, role, cfg, mi, skw = draw_config(seed)
    if op == "count":
        op = "sum"   # count is host-free: the device path routes it away
    rng = np.random.default_rng(2000 + seed)
    chunks = make_stream(rng, n_keys, 5, 160, **skw)
    spec = WindowSpec(win, slide, wt)
    oracle = run_core(WinSeqCore(spec, Reducer(op, out_field="value"),
                                 config=cfg, role=role, map_indexes=mi),
                      chunks)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        got = run_core(
            make_core_for(spec, Reducer(op, out_field="value"),
                          config=cfg, role=role, map_indexes=mi,
                          batch_len=32, flush_rows=96, use_resident=True),
            chunks)
    assert_equivalent(got, oracle)


@pytest.mark.parametrize("seed", range(8))
def test_fuzz_multireducer(seed):
    """Multi-stat aggregates under random shapes: count + max + min + sum
    must match the oracle through whatever core the selection picks —
    incl. the pos-extrema split paths when an extremum targets the
    position field (host-free both ways since r5) and the native
    multi-field staging when the stats span several device columns."""
    from windflow_tpu.ops.functions import MultiReducer
    from windflow_tpu.patterns.win_seq import WinSeq
    win, slide, wt, n_keys, _op, role, cfg, mi, skw = draw_config(seed)
    rng = np.random.default_rng(3000 + seed)
    chunks = make_stream(rng, n_keys, 4, 140, **skw)
    spec = WindowSpec(win, slide, wt)
    # alternate each extremum's target between the position field (ts
    # for TB, id for CB — host-free) and the value column (device-worthy)
    pos_field = "ts" if wt is WinType.TB else "id"
    max_field = pos_field if seed % 2 else "value"
    min_field = pos_field if (seed // 2) % 2 else "value"

    def agg():
        return MultiReducer(("count", None, "n"), ("max", max_field, "mx"),
                            ("min", min_field, "mn"),
                            ("sum", "value", "sm"))

    oracle = run_core(WinSeqCore(spec, agg(), config=cfg, role=role,
                                 map_indexes=mi), chunks)
    got = run_core(WinSeq(agg(), win, slide, wt, config=cfg, role=role,
                          map_indexes=mi).make_core(), chunks)
    assert_equivalent(got, oracle)
    # the DEVICE selection is where the pos-extrema split and the native
    # multi-field staging actually live (make_core_for, not
    # WinSeq.make_core — which only picks host cores); run it against
    # the same oracle so those paths are genuinely fuzz-covered
    from windflow_tpu.patterns.win_seq_tpu import make_core_for
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        dev_core = make_core_for(spec, agg(), config=cfg, role=role,
                                 map_indexes=mi, batch_len=64,
                                 flush_rows=200)
    assert_equivalent(run_core(dev_core, chunks), oracle)


@pytest.mark.parametrize("seed", range(6))
def test_fuzz_nested_farm_distribution(seed):
    """Random farm distribution math: a WinFarm worker's private slide +
    PatternConfig (the reference's modular gwid/initial_id arithmetic,
    win_seq.hpp:307-314) against the plain sequential oracle via total
    equality over every emitted window."""
    from windflow_tpu.patterns.basic import Sink, Source
    from windflow_tpu.patterns.win_farm import WinFarm
    from windflow_tpu.patterns.win_seq import WinSeq
    from windflow_tpu.runtime.engine import Dataflow
    from windflow_tpu.runtime.farm import build_pipeline
    from test_vecinc import SCHEMA
    rng = np.random.default_rng(4000 + seed)
    win = int(rng.integers(2, 16))
    slide = int(rng.integers(1, win + 1))
    wt = WinType.CB if seed % 2 else WinType.TB
    deg = int(rng.integers(2, 5))
    chunks = make_stream(rng, 9, 4, 150, gaps=bool(seed % 3 == 0))

    def total(pattern):
        acc = [0]

        def snk(rows):
            if rows is not None and len(rows):
                acc[0] += int(rows["value"].sum())

        df = Dataflow()
        build_pipeline(df, [Source(batches=iter(chunks), schema=SCHEMA),
                            pattern, Sink(snk, vectorized=True)])
        df.run_and_wait_end()
        return acc[0]

    want = total(WinSeq(Reducer("sum"), win, slide, wt))
    got = total(WinFarm(Reducer("sum"), win, slide, wt, pardegree=deg))
    assert got == want, (win, slide, wt, deg)


@pytest.mark.parametrize("seed", range(8))
def test_fuzz_pipe_random_degrees(seed):
    """Full-pipeline fuzz with re-drawn parallelism degrees — the
    reference's randomized pipe_test idiom (test_pipe_wf_cb.cpp:233-264
    re-draws mt19937 degrees per -r run): Source -> chain(Filter) ->
    chain(Map) -> {WinFarm | KeyFarm | PaneFarm | WinMapReduce} -> Sink,
    totals against the sequential oracle on the same stream."""
    from windflow_tpu.api import MultiPipe
    from windflow_tpu.patterns.basic import Filter, Map, Sink, Source
    from windflow_tpu.patterns.key_farm import KeyFarm
    from windflow_tpu.patterns.pane_farm import PaneFarm
    from windflow_tpu.patterns.win_farm import WinFarm
    from windflow_tpu.patterns.win_mapreduce import WinMapReduce
    from windflow_tpu.patterns.win_seq import WinSeq
    from test_vecinc import SCHEMA

    rng = np.random.default_rng(5000 + seed)
    win = int(rng.integers(2, 14))
    slide = int(rng.integers(1, win + 1))
    # deterministic full stage-by-wintype matrix: seeds 0-3 run the four
    # stage kinds under CB (incl. the KeyFarm raw-id oracle branch the
    # MultiPipe mode-table docstring cites), seeds 4-7 under TB — a
    # random or parity-coupled draw left half the matrix unreachable
    wt = WinType.CB if seed < 4 else WinType.TB
    deg = int(rng.integers(2, 5))
    deg2 = int(rng.integers(1, 4))
    stage_deg = int(rng.integers(1, 4))
    chunks = make_stream(rng, 11, 4, 170, markers_at_end=False)

    kind = seed % 4

    def window_stage():
        if kind == 0:
            return WinFarm(Reducer("sum"), win, slide, wt, pardegree=deg)
        if kind == 1:
            return KeyFarm(Reducer("sum"), win, slide, wt, pardegree=deg)
        if kind == 2 and slide < win:
            return PaneFarm(Reducer("sum"), Reducer("sum"), win, slide, wt,
                            plq_degree=deg, wlq_degree=deg2)
        return WinMapReduce(Reducer("sum"), Reducer("sum"), win, slide, wt,
                            map_degree=max(deg, 2), reduce_degree=deg2)

    def run_pipe(stage):
        acc = [0]

        def snk(rows):
            if rows is not None and len(rows):
                acc[0] += int(rows["value"].sum())

        (MultiPipe(f"fuzz{seed}")
         .add_source(Source(batches=iter(chunks), schema=SCHEMA))
         .chain(Filter(lambda b: b["value"] % 7 != 0, vectorized=True,
                       parallelism=stage_deg))
         .chain(Map(lambda b: b.__setitem__("value", b["value"] * 2),
                    vectorized=True, parallelism=stage_deg))
         .add(stage)
         .add_sink(Sink(snk, vectorized=True)))\
            .run_and_wait_end()
        return acc[0]

    got = run_pipe(window_stage())
    if kind == 1 and wt is WinType.CB:
        # reference-faithful asymmetry (multipipe.hpp mode table): a
        # Key_Farm is added with a plain KF_Emitter — its CB windows
        # count RAW tuple ids, gaps and all (:547-589) — while window
        # patterns exposing a spec get the broadcast/TS_RENUMBERING CB
        # treatment (:494-537).  Oracle: the filtered/mapped stream fed
        # straight to the sequential core, raw ids preserved.
        core = WinSeqCore(WindowSpec(win, slide, wt), Reducer("sum"))
        want = 0
        for b in chunks:
            keep = b["value"] % 7 != 0
            fb = b[keep].copy()
            fb["value"] = fb["value"] * 2
            want += int(core.process(fb)["value"].sum())
        want += int(core.flush()["value"].sum())
    else:
        want = run_pipe(WinSeq(Reducer("sum"), win, slide, wt))
    assert got == want, (win, slide, wt, deg, deg2, stage_deg)
