"""A REAL 2-process multi-host run (VERDICT r2 item 7): two Python
processes bootstrap ``jax.distributed.initialize`` over localhost on the
CPU backend, build the SAME global (kf, wf, sp) mesh from the real
process topology (no injected process_of), split the key space with
``local_kf_groups`` / ``process_for_keys``, run one kf-split windowed
pipeline per process over its own keys, and the parent asserts the two
processes' results are disjoint and their union equals the single-process
oracle — the deployment model of parallel/multihost.py exercised as a
runtime capability, not a recipe."""

import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

_WORKER = r"""
import json, sys
import numpy as np
import jax

# re-point jax at a 4-device virtual CPU backend (in-process config, not
# env: a sitecustomize pre-import latches the axon platform otherwise)
try:
    from jax.extend import backend as _jb
    _jb.clear_backends()
except Exception:
    pass
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 4)

port, pid, out_path = sys.argv[1], int(sys.argv[2]), sys.argv[3]
jax.distributed.initialize(coordinator_address=f"127.0.0.1:{port}",
                           num_processes=2, process_id=pid)
assert jax.process_index() == pid
assert len(jax.devices()) == 8, jax.devices()
assert len(jax.local_devices()) == 4

from windflow_tpu.core.tuples import Schema, batch_from_columns
from windflow_tpu.core.windows import WinType
from windflow_tpu.ops.functions import Reducer
from windflow_tpu.parallel.mesh import KF_AXIS
from windflow_tpu.parallel.multihost import (local_kf_groups,
                                             make_multihost_mesh,
                                             process_for_keys)
from windflow_tpu.patterns.basic import Sink, Source
from windflow_tpu.patterns.key_farm import KeyFarm
from windflow_tpu.runtime.engine import Dataflow
from windflow_tpu.runtime.farm import build_pipeline

mesh = make_multihost_mesh(n_sp=2, n_wf=1)       # real process topology
n_kf = int(mesh.shape[KF_AXIS])
mine = set(int(g) for g in local_kf_groups(mesh))

# the shared deterministic stream (both processes derive it identically);
# each process KEEPS ONLY the keys whose kf group it owns — the multihost
# source contract (no row ever crosses the DCN boundary)
schema = Schema(value=np.int64)
keys_all, n = 12, 96
batches = []
for lo in range(0, n, 24):
    m = min(24, n - lo)
    ids = np.repeat(np.arange(lo, lo + m), keys_all)
    ks = np.tile(np.arange(keys_all), m)
    vals = ids * 3 + ks
    b = batch_from_columns(schema, key=ks, id=ids, ts=ids, value=vals)
    owner = process_for_keys(b["key"], mesh)
    batches.append(b[owner == pid])

per_key = {}

def snk(rows):
    if rows is not None:
        for r in rows:
            per_key.setdefault(int(r["key"]), []).append(
                [int(r["id"]), int(r["value"])])

df = Dataflow()
build_pipeline(df, [Source(batches=iter(batches), schema=schema),
                    KeyFarm(Reducer("sum"), 16, 4, WinType.CB,
                            pardegree=2),
                    Sink(snk, vectorized=True)])
df.run_and_wait_end()

# every key this process produced must belong to a kf group it owns
from windflow_tpu.runtime.emitters import default_routing
for k in per_key:
    assert int(default_routing(np.asarray([k]), n_kf)[0]) in mine, k

with open(out_path, "w") as f:
    json.dump({"pid": pid, "n_kf": n_kf, "mine": sorted(mine),
               "per_key": {str(k): v for k, v in per_key.items()}}, f)
"""


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_two_process_kf_split_totals(tmp_path):
    port = _free_port()
    worker = tmp_path / "worker.py"
    worker.write_text(_WORKER)
    env = dict(os.environ)
    env["PYTHONPATH"] = (os.path.dirname(os.path.dirname(__file__))
                         + os.pathsep + env.get("PYTHONPATH", ""))
    procs, outs = [], []
    for pid in range(2):
        out = tmp_path / f"out{pid}.json"
        outs.append(out)
        procs.append(subprocess.Popen(
            [sys.executable, str(worker), str(port), str(pid), str(out)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE))
    results = []
    for p in procs:
        try:
            stdout, stderr = p.communicate(timeout=240)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        assert p.returncode == 0, stderr.decode()[-4000:]
    for out in outs:
        results.append(json.loads(out.read_text()))

    # the two processes partition the kf groups
    assert set(results[0]["mine"]).isdisjoint(results[1]["mine"])
    assert (sorted(results[0]["mine"] + results[1]["mine"])
            == list(range(results[0]["n_kf"])))
    merged = {}
    for r in results:
        for k, rows in r["per_key"].items():
            assert k not in merged, f"key {k} produced by both processes"
            merged[int(k)] = rows

    # single-process oracle over the full stream
    from windflow_tpu.core.tuples import Schema, batch_from_columns
    from windflow_tpu.core.windows import WindowSpec, WinType
    from windflow_tpu.core.winseq import WinSeqCore
    from windflow_tpu.ops.functions import Reducer
    keys_all, n = 12, 96
    want = {}
    core = WinSeqCore(WindowSpec(16, 4, WinType.CB), Reducer("sum"))
    schema = Schema(value=np.int64)
    for lo in range(0, n, 24):
        m = min(24, n - lo)
        ids = np.repeat(np.arange(lo, lo + m), keys_all)
        ks = np.tile(np.arange(keys_all), m)
        res = core.process(batch_from_columns(
            schema, key=ks, id=ids, ts=ids, value=ids * 3 + ks))
        for r in res:
            want.setdefault(int(r["key"]), []).append(
                [int(r["id"]), int(r["value"])])
    for r in core.flush():
        want.setdefault(int(r["key"]), []).append(
            [int(r["id"]), int(r["value"])])
    assert merged == want


_WORKER_DATAPLANE = r"""
import json, sys, time
import numpy as np
import jax

try:
    from jax.extend import backend as _jb
    _jb.clear_backends()
except Exception:
    pass
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 4)

coord_port, pid, my_port, peer_port, out_path = (
    sys.argv[1], int(sys.argv[2]), int(sys.argv[3]), int(sys.argv[4]),
    sys.argv[5])
jax.distributed.initialize(coordinator_address=f"127.0.0.1:{coord_port}",
                           num_processes=2, process_id=pid)

from windflow_tpu.core.tuples import Schema, batch_from_columns
from windflow_tpu.core.windows import WinType
from windflow_tpu.ops.functions import Reducer
from windflow_tpu.parallel.channel import (RowReceiver, RowSender,
                                           partition_and_ship)
from windflow_tpu.parallel.multihost import (make_multihost_mesh,
                                             process_for_keys)
from windflow_tpu.patterns.basic import Sink, Source
from windflow_tpu.patterns.key_farm import KeyFarm
from windflow_tpu.runtime.engine import Dataflow
from windflow_tpu.runtime.farm import build_pipeline

mesh = make_multihost_mesh(n_sp=2, n_wf=1)

# the NON-key-partitioned input: process p generates id range
# [p*n/2, (p+1)*n/2) for EVERY key and ships non-owned rows to the peer
# over the row channel (parallel/channel.py) — the data plane the
# key-local deployment model does not need, exercised for real
schema = Schema(value=np.int64)
keys_all, n = 12, 96
half = n // 2

recv = RowReceiver(n_senders=1, port=my_port)
snd = None
for _ in range(100):
    try:
        snd = RowSender("127.0.0.1", peer_port)
        break
    except OSError:
        time.sleep(0.1)
assert snd is not None, "peer receiver never came up"

def my_chunks():
    lo0 = pid * half
    for lo in range(lo0, lo0 + half, 24):
        m = min(24, lo0 + half - lo)
        ids = np.repeat(np.arange(lo, lo + m), keys_all)
        ks = np.tile(np.arange(keys_all), m)
        yield batch_from_columns(schema, key=ks, id=ids, ts=ids,
                                 value=ids * 3 + ks)

def feed():
    # origin order (p0's ids < p1's): keeps per-key arrival in id order
    def local_phase():
        for b in my_chunks():
            owners = process_for_keys(b["key"], mesh)
            yield partition_and_ship(b, owners, pid, {1 - pid: snd})
        snd.close()
    if pid == 0:
        yield from local_phase()
        yield from recv.batches()
    else:
        yield from recv.batches()
        yield from local_phase()

per_key = {}

def snk(rows):
    if rows is not None:
        for r in rows:
            per_key.setdefault(int(r["key"]), []).append(
                [int(r["id"]), int(r["value"])])

df = Dataflow()
build_pipeline(df, [Source(batches=feed(), schema=schema),
                    KeyFarm(Reducer("sum"), 16, 4, WinType.CB,
                            pardegree=2),
                    Sink(snk, vectorized=True)])
df.run_and_wait_end()

with open(out_path, "w") as f:
    json.dump({"pid": pid,
               "per_key": {str(k): v for k, v in per_key.items()}}, f)
"""


def test_two_process_row_channel_data_plane(tmp_path):
    """The cross-process row channel (parallel/channel.py): each process
    generates HALF the stream for every key and ships non-owned rows to
    the owner over TCP; the merged per-key results must equal the
    single-process oracle over the full stream — the multi-host data
    plane as a runtime capability (r2 VERDICT missing #4)."""
    coord = _free_port()
    ports = [_free_port(), _free_port()]
    worker = tmp_path / "worker_dp.py"
    worker.write_text(_WORKER_DATAPLANE)
    env = dict(os.environ)
    env["PYTHONPATH"] = (os.path.dirname(os.path.dirname(__file__))
                         + os.pathsep + env.get("PYTHONPATH", ""))
    procs, outs = [], []
    for pid in range(2):
        out = tmp_path / f"dp{pid}.json"
        outs.append(out)
        procs.append(subprocess.Popen(
            [sys.executable, str(worker), str(coord), str(pid),
             str(ports[pid]), str(ports[1 - pid]), str(out)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE))
    for p in procs:
        try:
            _stdout, stderr = p.communicate(timeout=240)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        assert p.returncode == 0, stderr.decode()[-4000:]
    merged = {}
    for out in outs:
        r = json.loads(out.read_text())
        for k, rows in r["per_key"].items():
            assert k not in merged, f"key {k} produced by both processes"
            merged[int(k)] = rows

    # single-process oracle over the FULL stream
    from windflow_tpu.core.tuples import Schema, batch_from_columns
    from windflow_tpu.core.windows import WindowSpec, WinType
    from windflow_tpu.core.winseq import WinSeqCore
    from windflow_tpu.ops.functions import Reducer
    keys_all, n = 12, 96
    want = {}
    core = WinSeqCore(WindowSpec(16, 4, WinType.CB), Reducer("sum"))
    schema = Schema(value=np.int64)
    for lo in range(0, n, 24):
        m = min(24, n - lo)
        ids = np.repeat(np.arange(lo, lo + m), keys_all)
        ks = np.tile(np.arange(keys_all), m)
        res = core.process(batch_from_columns(
            schema, key=ks, id=ids, ts=ids, value=ids * 3 + ks))
        for r in res:
            want.setdefault(int(r["key"]), []).append(
                [int(r["id"]), int(r["value"])])
    for r in core.flush():
        want.setdefault(int(r["key"]), []).append(
            [int(r["id"]), int(r["value"])])
    assert merged == want


def test_row_channel_fails_fast_on_dead_peer():
    """A connection dying mid-stream must surface as an error from
    batches(), never as a silently truncated stream (wrong totals)."""
    import socket
    import threading
    import numpy as np
    from windflow_tpu.core.tuples import Schema, batch_from_columns
    from windflow_tpu.parallel.channel import RowReceiver, RowSender

    schema = Schema(value=np.int64)
    recv = RowReceiver(n_senders=1)

    def half_send():
        s = RowSender("127.0.0.1", recv.port)
        ids = np.arange(4)
        s.send(batch_from_columns(schema, key=np.zeros(4), id=ids, ts=ids,
                                  value=ids))
        # die without EOS: hard close mid-protocol
        s._sock.shutdown(socket.SHUT_RDWR)
        s._sock.close()

    t = threading.Thread(target=half_send)
    t.start()
    got, err = [], None
    try:
        for b in recv.batches():
            got.append(b)
    except (ConnectionError, OSError) as e:
        err = e
    t.join()
    assert err is not None, "dead peer was swallowed as EOS"


_RESUME_SENDER = r"""
import os, sys, time
import numpy as np
from windflow_tpu.core.tuples import Schema, batch_from_columns
from windflow_tpu.parallel.channel import RowSender, WireResume

port, flag_path = int(sys.argv[1]), sys.argv[2]
schema = Schema(value=np.int64)
snd = RowSender("127.0.0.1", port, resume=WireResume(deadline=30.0),
                connect_deadline=30.0)

def ship(lo, hi):
    for i in range(lo, hi):
        snd.send(batch_from_columns(schema, key=[0], id=[i], ts=[i],
                                    value=[i]))

ship(0, 8)
snd.send_epoch(1)
ship(8, 16)
snd.send_epoch(2)
# hold the last epoch until the parent signals the restarted receiver is
# up — keeps the sender alive across the peer's death
deadline = time.time() + 60
while not os.path.exists(flag_path):
    assert time.time() < deadline, "restart flag never appeared"
    time.sleep(0.05)
ship(16, 24)
snd.send_epoch(3)
snd.close()
print("SENDER_OK")
"""

_RESUME_RECV_A = r"""
import json, os, sys
import numpy as np
from windflow_tpu.parallel.channel import RowReceiver, WireResume
from windflow_tpu.recovery.epoch import EpochMarker

port, out_path = int(sys.argv[1]), sys.argv[2]
r = RowReceiver(1, port=port, resume=WireResume(deadline=30.0),
                ack_epochs=True, accept_timeout=60.0)
it = r.batches(epoch_markers=True)
sealed = []
for item in it:
    if isinstance(item, EpochMarker):
        break                      # epoch-1 barrier (auto-acked)
    sealed.append(int(item["value"][0]))
with open(out_path, "w") as f:
    json.dump({"sealed": sealed}, f)
    f.flush()
    os.fsync(f.fileno())
taken = 0
for item in it:                    # wander into epoch 2, then die hard
    if not isinstance(item, EpochMarker):
        taken += 1
        if taken >= 3:
            break
os._exit(1)
"""

_RESUME_RECV_B = r"""
import json, sys
import numpy as np
from windflow_tpu.parallel.channel import RowReceiver, WireResume
from windflow_tpu.recovery.epoch import EpochMarker

port, out_path = int(sys.argv[1]), sys.argv[2]
r = RowReceiver(1, port=port, resume=WireResume(deadline=30.0),
                resume_epoch=1, ack_epochs=True, accept_timeout=60.0)
got = []
for item in r.batches(epoch_markers=True):
    if not isinstance(item, EpochMarker):
        got.append(int(item["value"][0]))
r.close()
with open(out_path, "w") as f:
    json.dump({"got": got}, f)
print("RECV_B_OK")
"""


def test_receiver_process_restart_resumes_wire(tmp_path):
    """The resume handshake across REAL process boundaries (the
    in-process twins live in tests/test_channel_faults.py): receiver A
    acks the epoch-1 barrier and hard-exits mid-epoch-2; a fresh process
    B re-binds the same port with resume_epoch=1; the journaling sender
    replays epoch 2 from its journal and finishes — A saw exactly epoch
    1, B sees exactly epochs 2..3, no gaps and no duplicates."""
    port = _free_port()
    env = dict(os.environ)
    env["PYTHONPATH"] = (os.path.dirname(os.path.dirname(__file__))
                         + os.pathsep + env.get("PYTHONPATH", ""))
    env.setdefault("JAX_PLATFORMS", "cpu")
    scripts = {}
    for name, src in (("sender", _RESUME_SENDER), ("recv_a", _RESUME_RECV_A),
                      ("recv_b", _RESUME_RECV_B)):
        p = tmp_path / f"{name}.py"
        p.write_text(src)
        scripts[name] = p
    out_a, out_b = tmp_path / "out_a.json", tmp_path / "out_b.json"
    flag = tmp_path / "restart.flag"

    procs = []
    try:
        recv_a = subprocess.Popen(
            [sys.executable, str(scripts["recv_a"]), str(port), str(out_a)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE)
        procs.append(recv_a)
        sender = subprocess.Popen(
            [sys.executable, str(scripts["sender"]), str(port), str(flag)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE)
        procs.append(sender)
        _out, err_a = recv_a.communicate(timeout=120)
        assert recv_a.returncode == 1, (recv_a.returncode,
                                        err_a.decode()[-4000:])
        recv_b = subprocess.Popen(
            [sys.executable, str(scripts["recv_b"]), str(port), str(out_b)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE)
        procs.append(recv_b)
        flag.touch()
        _out, err_s = sender.communicate(timeout=120)
        assert sender.returncode == 0, err_s.decode()[-4000:]
        _out, err_b = recv_b.communicate(timeout=120)
        assert recv_b.returncode == 0, err_b.decode()[-4000:]
    except subprocess.TimeoutExpired:
        for q in procs:
            q.kill()
        raise

    assert json.loads(out_a.read_text())["sealed"] == list(range(8))
    assert json.loads(out_b.read_text())["got"] == list(range(8, 24))


def test_partition_and_ship_rejects_uncovered_owner():
    import numpy as np
    import pytest as _pytest
    from windflow_tpu.core.tuples import Schema, batch_from_columns
    from windflow_tpu.parallel.channel import partition_and_ship
    schema = Schema(value=np.int64)
    b = batch_from_columns(schema, key=np.arange(6), id=np.arange(6),
                           ts=np.arange(6), value=np.arange(6))
    owners = np.array([0, 1, 2, 0, 1, 2])
    with _pytest.raises(KeyError, match="no\\s+RowSender"):
        partition_and_ship(b, owners, 0, {1: object()})


# ---------------------------------------------------------------------------
# cross-host recovery (docs/ROBUSTNESS.md "Cross-host recovery"): a feeder
# (pid 0) journals a keyed stream to two stateful workers over the row
# plane; each worker seals per-epoch state into a CheckpointStore,
# replicates it to its peer as a portable checkpoint, and acks the sealed
# epoch so the feeder's journal trims.  The kill test hard-kills one worker
# and asserts the survivor's PlaneSupervisor adopts it (restore at the last
# sealed epoch + takeover receiver replaying the journal tail); the roll
# test restarts BOTH workers mid-stream while the feeder keeps emitting.
# In both, the merged outputs must be byte-identical to the uncrashed
# single-process oracle — no gaps, no duplicates.

_PLANE_FEEDER = r"""
import json, sys, time
import numpy as np
from windflow_tpu.core.tuples import Schema, batch_from_columns
from windflow_tpu.parallel.channel import RowSender, WireResume

d1, d2, n_epochs = int(sys.argv[1]), int(sys.argv[2]), int(sys.argv[3])
out_path = sys.argv[4]
schema = Schema(value=np.int64)
senders = {w: RowSender("127.0.0.1", p, resume=WireResume(deadline=120.0),
                        connect_deadline=60.0)
           for w, p in ((1, d1), (2, d2))}
bi = 0
for epoch in range(1, n_epochs + 1):
    for _ in range(2):
        keys = np.arange(8, dtype=np.int64)
        ids = np.full(8, bi, dtype=np.int64)
        vals = 7 * ids + keys + 1
        for w in (1, 2):
            m = (1 + keys % 2) == w
            senders[w].send(batch_from_columns(
                schema, key=keys[m], id=ids[m], ts=ids[m], value=vals[m]))
        bi += 1
    for w in (1, 2):
        senders[w].send_epoch(epoch)
    time.sleep(0.1)   # keep emitting WHILE kills/rolls happen downstream
for w in (1, 2):
    senders[w].close()
with open(out_path, "w") as f:
    json.dump({"batches": bi}, f)
"""

_PLANE_WORKER = r"""
import json, os, sys, threading, time
from windflow_tpu.parallel.channel import (RowReceiver, RowSender,
                                           WireConfig, WireResume)
from windflow_tpu.parallel.plane import PlanePolicy, PlaneSupervisor
from windflow_tpu.recovery.epoch import EpochMarker
from windflow_tpu.recovery.portable import PortableSpool
from windflow_tpu.recovery.store import CheckpointStore

w = int(sys.argv[1])
d1, d2, m1, m2 = (int(a) for a in sys.argv[2:6])
root, die_after, summary_path = sys.argv[6], int(sys.argv[7]), sys.argv[8]
peer = 3 - w
my_data, my_mon = (d1, m1) if w == 1 else (d2, m2)
peer_mon = m2 if w == 1 else m1

store = CheckpointStore(os.path.join(root, f"store{w}"), retain=8)
spool = PortableSpool(os.path.join(root, f"spool{w}"))

# data plane: the feeder's journaling sender; acks are manual, at SEAL
recv = RowReceiver(1, port=my_data, resume=WireResume(deadline=120.0),
                   ack_epochs=False, accept_timeout=60.0)
# monitor plane: peer liveness (its death = our link EOF) + the landing
# zone for the peer's replicated portable checkpoints
mon_recv = RowReceiver(1, port=my_mon, resume=WireResume(deadline=240.0),
                       accept_timeout=60.0, ckpt_sink=spool)
mon_snd = RowSender("127.0.0.1", peer_mon, resume=WireResume(deadline=240.0),
                    connect_deadline=60.0)

adopted_rows, alock = [], threading.Lock()
ctx = {}
adopt_started, adopt_done = threading.Event(), threading.Event()


def apply(rows, sums, sink):
    for r in rows:
        k, v = int(r["key"]), int(r["value"])
        sums[k] = sums.get(k, 0) + v
        sink.append([k, int(r["id"]), sums[k]])


def on_adopt(dead, epoch, st):
    ctx["adopted_from"] = [int(dead), int(epoch)]

    def run():
        try:
            sums2 = st.load(int(epoch), "sums")
            tr = ctx["sup"].takeover_receiver(dead, epoch, n_senders=1)
            pend = []
            for item in tr.batches(epoch_markers=True):
                if isinstance(item, EpochMarker):
                    with alock:
                        adopted_rows.extend(pend)
                    pend = []
                    tr.ack_epoch(int(item.epoch))
                    continue
                apply(item, sums2, pend)
            tr.close()
        except Exception as e:                      # noqa: BLE001
            ctx["adopt_error"] = repr(e)
        finally:
            adopt_done.set()

    threading.Thread(target=run, daemon=True).start()
    adopt_started.set()


policy = PlanePolicy(
    down_deadline=2.0, period=0.1, candidates={1, 2},
    wire=WireConfig(connect_deadline=60.0, heartbeat=2.0,
                    stall_timeout=30.0, resume=True, recovery=False))
sup = PlaneSupervisor(w, {1: ("127.0.0.1", d1), 2: ("127.0.0.1", d2)},
                      {peer: mon_snd}, policy=policy, store=store,
                      spool=spool, on_adopt=on_adopt)
ctx["sup"] = sup
sup.start()

sums, pending = {}, []
out_f = open(os.path.join(root, f"out{w}.jsonl"), "a")
for item in recv.batches(epoch_markers=True):
    if isinstance(item, EpochMarker):
        e = int(item.epoch)
        n = store.save_blob(e, "sums", dict(sums))
        store.commit(e, {"sums": {"bytes": n}})
        for row in pending:
            out_f.write(json.dumps(row) + "\n")
        out_f.flush()
        os.fsync(out_f.fileno())
        pending = []
        sup.replicate(e)
        recv.ack_epoch(e)
        if die_after and e >= die_after:
            os._exit(1)   # kill -9: no EOS, no teardown, nothing flushed
        continue
    apply(item, sums, pending)

if adopt_started.wait(0.5):
    assert adopt_done.wait(120.0), "adopted tail never finished"
    assert "adopt_error" not in ctx, ctx["adopt_error"]

recv.close()
sup.close()
mon_snd.abort()
mon_recv.close()
with alock:
    rows = list(adopted_rows)
with open(summary_path, "w") as f:
    json.dump({"pid": w, "adopted_from": ctx.get("adopted_from"),
               "adopted_rows": rows}, f)
"""

_ROLL_WORKER = r"""
import json, os, sys
from windflow_tpu.parallel.channel import RowReceiver, WireResume
from windflow_tpu.recovery.epoch import EpochMarker
from windflow_tpu.recovery.store import CheckpointStore

w = int(sys.argv[1])
port, root = int(sys.argv[2]), sys.argv[3]
stop_after, resume_from = int(sys.argv[4]), int(sys.argv[5])

store = CheckpointStore(os.path.join(root, f"store{w}"), retain=8)
sums = {}
if resume_from:
    latest = store.latest_complete()
    assert latest is not None and latest[0] == resume_from, latest
    sums = store.load(resume_from, "sums")

recv = RowReceiver(1, port=port, resume=WireResume(deadline=120.0),
                   resume_epoch=(resume_from or None), ack_epochs=False,
                   accept_timeout=60.0)
pending = []
out_f = open(os.path.join(root, f"out{w}.jsonl"), "a")
for item in recv.batches(epoch_markers=True):
    if isinstance(item, EpochMarker):
        e = int(item.epoch)
        n = store.save_blob(e, "sums", dict(sums))
        store.commit(e, {"sums": {"bytes": n}})
        for row in pending:
            out_f.write(json.dumps(row) + "\n")
        out_f.flush()
        os.fsync(out_f.fileno())
        pending = []
        recv.ack_epoch(e)
        if stop_after and e >= stop_after:
            os._exit(0)   # rolling restart: exit at the seal, no EOS —
            #               the feeder's journal bridges the gap
        continue
    for r in item:
        k, v = int(r["key"]), int(r["value"])
        sums[k] = sums.get(k, 0) + v
        pending.append([k, int(r["id"]), sums[k]])
recv.close()
"""


def _plane_oracle(n_epochs):
    """Uncrashed single-process oracle: per-key running sums over the
    deterministic feeder stream, as {key: [[id, cum], ...]}."""
    want, sums = {}, {}
    for bi in range(2 * n_epochs):
        for k in range(8):
            v = 7 * bi + k + 1
            sums[k] = sums.get(k, 0) + v
            want.setdefault(k, []).append([bi, sums[k]])
    return want


def _plane_rows(*paths):
    """Merge [key, id, cum] row files/lists into {key: rows-by-id}."""
    per_key = {}
    for rows in paths:
        for k, rid, cum in rows:
            per_key.setdefault(int(k), []).append([int(rid), int(cum)])
    for rows in per_key.values():
        rows.sort()
    return per_key


def _jsonl(path):
    if not os.path.exists(path):
        return []
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


def test_three_process_kill_and_adopt(tmp_path):
    """ISSUE 18 acceptance: kill -9 one worker of a 3-process plane
    (feeder + 2 stateful workers).  The survivor's PlaneSupervisor must
    detect the death past the down-deadline, elect itself, restore the
    dead peer's state from its replicated portable checkpoint at the
    last SEALED epoch, and rebind the dead peer's address as a resume
    receiver — the feeder's journal replays exactly the unsealed tail.
    Merged outputs (survivor + dead worker's sealed prefix + adopted
    tail) must equal the uncrashed oracle: no gaps, no duplicates."""
    d1, d2, m1, m2 = (_free_port() for _ in range(4))
    env = dict(os.environ)
    env["PYTHONPATH"] = (os.path.dirname(os.path.dirname(__file__))
                         + os.pathsep + env.get("PYTHONPATH", ""))
    env.setdefault("JAX_PLATFORMS", "cpu")
    feeder_py = tmp_path / "feeder.py"
    feeder_py.write_text(_PLANE_FEEDER)
    worker_py = tmp_path / "worker.py"
    worker_py.write_text(_PLANE_WORKER)
    root = str(tmp_path)
    n_epochs = 6

    procs = []
    try:
        workers = {}
        for w, die_after in ((1, 0), (2, 2)):   # worker 2 dies at epoch 2
            workers[w] = subprocess.Popen(
                [sys.executable, str(worker_py), str(w), str(d1), str(d2),
                 str(m1), str(m2), root, str(die_after),
                 str(tmp_path / f"summary{w}.json")],
                env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE)
            procs.append(workers[w])
        feeder = subprocess.Popen(
            [sys.executable, str(feeder_py), str(d1), str(d2),
             str(n_epochs), str(tmp_path / "feeder.json")],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE)
        procs.append(feeder)
        _out, err2 = workers[2].communicate(timeout=240)
        assert workers[2].returncode == 1, (workers[2].returncode,
                                            err2.decode()[-4000:])
        _out, err_f = feeder.communicate(timeout=240)
        assert feeder.returncode == 0, err_f.decode()[-4000:]
        _out, err1 = workers[1].communicate(timeout=240)
        assert workers[1].returncode == 0, err1.decode()[-4000:]
    except subprocess.TimeoutExpired:
        for q in procs:
            q.kill()
        raise

    summary = json.loads((tmp_path / "summary1.json").read_text())
    assert summary["adopted_from"] == [2, 2], summary["adopted_from"]
    merged = _plane_rows(_jsonl(os.path.join(root, "out1.jsonl")),
                         _jsonl(os.path.join(root, "out2.jsonl")),
                         summary["adopted_rows"])
    assert merged == _plane_oracle(n_epochs)


def test_rolling_restart_zero_loss(tmp_path):
    """ISSUE 18 acceptance: roll every stateful worker of the plane —
    each seals an epoch, exits without EOS, and restarts with
    ``resume_epoch=`` at its own sealed checkpoint — while the feeder
    keeps emitting the whole time (its journaling senders bridge each
    restart gap and replay the unsealed tail to the rebooted process).
    Merged outputs must equal the uncrashed oracle: zero record loss,
    zero duplication."""
    d1, d2 = _free_port(), _free_port()
    env = dict(os.environ)
    env["PYTHONPATH"] = (os.path.dirname(os.path.dirname(__file__))
                         + os.pathsep + env.get("PYTHONPATH", ""))
    env.setdefault("JAX_PLATFORMS", "cpu")
    feeder_py = tmp_path / "feeder.py"
    feeder_py.write_text(_PLANE_FEEDER)
    worker_py = tmp_path / "roll_worker.py"
    worker_py.write_text(_ROLL_WORKER)
    root = str(tmp_path)
    n_epochs = 8
    rolls = {1: 2, 2: 5}   # worker -> epoch it restarts at

    procs = []

    def spawn_worker(w, port, stop_after, resume_from):
        p = subprocess.Popen(
            [sys.executable, str(worker_py), str(w), str(port), root,
             str(stop_after), str(resume_from)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE)
        procs.append(p)
        return p

    try:
        phase_a = {w: spawn_worker(w, p, rolls[w], 0)
                   for w, p in ((1, d1), (2, d2))}
        feeder = subprocess.Popen(
            [sys.executable, str(feeder_py), str(d1), str(d2),
             str(n_epochs), str(tmp_path / "feeder.json")],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE)
        procs.append(feeder)
        phase_b = {}
        for w, port in ((1, d1), (2, d2)):      # roll in plane order
            _out, err = phase_a[w].communicate(timeout=240)
            assert phase_a[w].returncode == 0, err.decode()[-4000:]
            phase_b[w] = spawn_worker(w, port, 0, rolls[w])
        _out, err_f = feeder.communicate(timeout=240)
        assert feeder.returncode == 0, err_f.decode()[-4000:]
        for w in (1, 2):
            _out, err = phase_b[w].communicate(timeout=240)
            assert phase_b[w].returncode == 0, err.decode()[-4000:]
    except subprocess.TimeoutExpired:
        for q in procs:
            q.kill()
        raise

    merged = _plane_rows(_jsonl(os.path.join(root, "out1.jsonl")),
                         _jsonl(os.path.join(root, "out2.jsonl")))
    assert merged == _plane_oracle(n_epochs)
