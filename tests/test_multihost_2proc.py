"""A REAL 2-process multi-host run (VERDICT r2 item 7): two Python
processes bootstrap ``jax.distributed.initialize`` over localhost on the
CPU backend, build the SAME global (kf, wf, sp) mesh from the real
process topology (no injected process_of), split the key space with
``local_kf_groups`` / ``process_for_keys``, run one kf-split windowed
pipeline per process over its own keys, and the parent asserts the two
processes' results are disjoint and their union equals the single-process
oracle — the deployment model of parallel/multihost.py exercised as a
runtime capability, not a recipe."""

import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

_WORKER = r"""
import json, sys
import numpy as np
import jax

# re-point jax at a 4-device virtual CPU backend (in-process config, not
# env: a sitecustomize pre-import latches the axon platform otherwise)
try:
    from jax.extend import backend as _jb
    _jb.clear_backends()
except Exception:
    pass
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 4)

port, pid, out_path = sys.argv[1], int(sys.argv[2]), sys.argv[3]
jax.distributed.initialize(coordinator_address=f"127.0.0.1:{port}",
                           num_processes=2, process_id=pid)
assert jax.process_index() == pid
assert len(jax.devices()) == 8, jax.devices()
assert len(jax.local_devices()) == 4

from windflow_tpu.core.tuples import Schema, batch_from_columns
from windflow_tpu.core.windows import WinType
from windflow_tpu.ops.functions import Reducer
from windflow_tpu.parallel.mesh import KF_AXIS
from windflow_tpu.parallel.multihost import (local_kf_groups,
                                             make_multihost_mesh,
                                             process_for_keys)
from windflow_tpu.patterns.basic import Sink, Source
from windflow_tpu.patterns.key_farm import KeyFarm
from windflow_tpu.runtime.engine import Dataflow
from windflow_tpu.runtime.farm import build_pipeline

mesh = make_multihost_mesh(n_sp=2, n_wf=1)       # real process topology
n_kf = int(mesh.shape[KF_AXIS])
mine = set(int(g) for g in local_kf_groups(mesh))

# the shared deterministic stream (both processes derive it identically);
# each process KEEPS ONLY the keys whose kf group it owns — the multihost
# source contract (no row ever crosses the DCN boundary)
schema = Schema(value=np.int64)
keys_all, n = 12, 96
batches = []
for lo in range(0, n, 24):
    m = min(24, n - lo)
    ids = np.repeat(np.arange(lo, lo + m), keys_all)
    ks = np.tile(np.arange(keys_all), m)
    vals = ids * 3 + ks
    b = batch_from_columns(schema, key=ks, id=ids, ts=ids, value=vals)
    owner = process_for_keys(b["key"], mesh)
    batches.append(b[owner == pid])

per_key = {}

def snk(rows):
    if rows is not None:
        for r in rows:
            per_key.setdefault(int(r["key"]), []).append(
                [int(r["id"]), int(r["value"])])

df = Dataflow()
build_pipeline(df, [Source(batches=iter(batches), schema=schema),
                    KeyFarm(Reducer("sum"), 16, 4, WinType.CB,
                            pardegree=2),
                    Sink(snk, vectorized=True)])
df.run_and_wait_end()

# every key this process produced must belong to a kf group it owns
from windflow_tpu.runtime.emitters import default_routing
for k in per_key:
    assert int(default_routing(np.asarray([k]), n_kf)[0]) in mine, k

with open(out_path, "w") as f:
    json.dump({"pid": pid, "n_kf": n_kf, "mine": sorted(mine),
               "per_key": {str(k): v for k, v in per_key.items()}}, f)
"""


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_two_process_kf_split_totals(tmp_path):
    port = _free_port()
    worker = tmp_path / "worker.py"
    worker.write_text(_WORKER)
    env = dict(os.environ)
    env["PYTHONPATH"] = (os.path.dirname(os.path.dirname(__file__))
                         + os.pathsep + env.get("PYTHONPATH", ""))
    procs, outs = [], []
    for pid in range(2):
        out = tmp_path / f"out{pid}.json"
        outs.append(out)
        procs.append(subprocess.Popen(
            [sys.executable, str(worker), str(port), str(pid), str(out)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE))
    results = []
    for p in procs:
        try:
            stdout, stderr = p.communicate(timeout=240)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        assert p.returncode == 0, stderr.decode()[-4000:]
    for out in outs:
        results.append(json.loads(out.read_text()))

    # the two processes partition the kf groups
    assert set(results[0]["mine"]).isdisjoint(results[1]["mine"])
    assert (sorted(results[0]["mine"] + results[1]["mine"])
            == list(range(results[0]["n_kf"])))
    merged = {}
    for r in results:
        for k, rows in r["per_key"].items():
            assert k not in merged, f"key {k} produced by both processes"
            merged[int(k)] = rows

    # single-process oracle over the full stream
    from windflow_tpu.core.tuples import Schema, batch_from_columns
    from windflow_tpu.core.windows import WindowSpec, WinType
    from windflow_tpu.core.winseq import WinSeqCore
    from windflow_tpu.ops.functions import Reducer
    keys_all, n = 12, 96
    want = {}
    core = WinSeqCore(WindowSpec(16, 4, WinType.CB), Reducer("sum"))
    schema = Schema(value=np.int64)
    for lo in range(0, n, 24):
        m = min(24, n - lo)
        ids = np.repeat(np.arange(lo, lo + m), keys_all)
        ks = np.tile(np.arange(keys_all), m)
        res = core.process(batch_from_columns(
            schema, key=ks, id=ids, ts=ids, value=ids * 3 + ks))
        for r in res:
            want.setdefault(int(r["key"]), []).append(
                [int(r["id"]), int(r["value"])])
    for r in core.flush():
        want.setdefault(int(r["key"]), []).append(
            [int(r["id"]), int(r["value"])])
    assert merged == want


_WORKER_DATAPLANE = r"""
import json, sys, time
import numpy as np
import jax

try:
    from jax.extend import backend as _jb
    _jb.clear_backends()
except Exception:
    pass
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 4)

coord_port, pid, my_port, peer_port, out_path = (
    sys.argv[1], int(sys.argv[2]), int(sys.argv[3]), int(sys.argv[4]),
    sys.argv[5])
jax.distributed.initialize(coordinator_address=f"127.0.0.1:{coord_port}",
                           num_processes=2, process_id=pid)

from windflow_tpu.core.tuples import Schema, batch_from_columns
from windflow_tpu.core.windows import WinType
from windflow_tpu.ops.functions import Reducer
from windflow_tpu.parallel.channel import (RowReceiver, RowSender,
                                           partition_and_ship)
from windflow_tpu.parallel.multihost import (make_multihost_mesh,
                                             process_for_keys)
from windflow_tpu.patterns.basic import Sink, Source
from windflow_tpu.patterns.key_farm import KeyFarm
from windflow_tpu.runtime.engine import Dataflow
from windflow_tpu.runtime.farm import build_pipeline

mesh = make_multihost_mesh(n_sp=2, n_wf=1)

# the NON-key-partitioned input: process p generates id range
# [p*n/2, (p+1)*n/2) for EVERY key and ships non-owned rows to the peer
# over the row channel (parallel/channel.py) — the data plane the
# key-local deployment model does not need, exercised for real
schema = Schema(value=np.int64)
keys_all, n = 12, 96
half = n // 2

recv = RowReceiver(n_senders=1, port=my_port)
snd = None
for _ in range(100):
    try:
        snd = RowSender("127.0.0.1", peer_port)
        break
    except OSError:
        time.sleep(0.1)
assert snd is not None, "peer receiver never came up"

def my_chunks():
    lo0 = pid * half
    for lo in range(lo0, lo0 + half, 24):
        m = min(24, lo0 + half - lo)
        ids = np.repeat(np.arange(lo, lo + m), keys_all)
        ks = np.tile(np.arange(keys_all), m)
        yield batch_from_columns(schema, key=ks, id=ids, ts=ids,
                                 value=ids * 3 + ks)

def feed():
    # origin order (p0's ids < p1's): keeps per-key arrival in id order
    def local_phase():
        for b in my_chunks():
            owners = process_for_keys(b["key"], mesh)
            yield partition_and_ship(b, owners, pid, {1 - pid: snd})
        snd.close()
    if pid == 0:
        yield from local_phase()
        yield from recv.batches()
    else:
        yield from recv.batches()
        yield from local_phase()

per_key = {}

def snk(rows):
    if rows is not None:
        for r in rows:
            per_key.setdefault(int(r["key"]), []).append(
                [int(r["id"]), int(r["value"])])

df = Dataflow()
build_pipeline(df, [Source(batches=feed(), schema=schema),
                    KeyFarm(Reducer("sum"), 16, 4, WinType.CB,
                            pardegree=2),
                    Sink(snk, vectorized=True)])
df.run_and_wait_end()

with open(out_path, "w") as f:
    json.dump({"pid": pid,
               "per_key": {str(k): v for k, v in per_key.items()}}, f)
"""


def test_two_process_row_channel_data_plane(tmp_path):
    """The cross-process row channel (parallel/channel.py): each process
    generates HALF the stream for every key and ships non-owned rows to
    the owner over TCP; the merged per-key results must equal the
    single-process oracle over the full stream — the multi-host data
    plane as a runtime capability (r2 VERDICT missing #4)."""
    coord = _free_port()
    ports = [_free_port(), _free_port()]
    worker = tmp_path / "worker_dp.py"
    worker.write_text(_WORKER_DATAPLANE)
    env = dict(os.environ)
    env["PYTHONPATH"] = (os.path.dirname(os.path.dirname(__file__))
                         + os.pathsep + env.get("PYTHONPATH", ""))
    procs, outs = [], []
    for pid in range(2):
        out = tmp_path / f"dp{pid}.json"
        outs.append(out)
        procs.append(subprocess.Popen(
            [sys.executable, str(worker), str(coord), str(pid),
             str(ports[pid]), str(ports[1 - pid]), str(out)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE))
    for p in procs:
        try:
            _stdout, stderr = p.communicate(timeout=240)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        assert p.returncode == 0, stderr.decode()[-4000:]
    merged = {}
    for out in outs:
        r = json.loads(out.read_text())
        for k, rows in r["per_key"].items():
            assert k not in merged, f"key {k} produced by both processes"
            merged[int(k)] = rows

    # single-process oracle over the FULL stream
    from windflow_tpu.core.tuples import Schema, batch_from_columns
    from windflow_tpu.core.windows import WindowSpec, WinType
    from windflow_tpu.core.winseq import WinSeqCore
    from windflow_tpu.ops.functions import Reducer
    keys_all, n = 12, 96
    want = {}
    core = WinSeqCore(WindowSpec(16, 4, WinType.CB), Reducer("sum"))
    schema = Schema(value=np.int64)
    for lo in range(0, n, 24):
        m = min(24, n - lo)
        ids = np.repeat(np.arange(lo, lo + m), keys_all)
        ks = np.tile(np.arange(keys_all), m)
        res = core.process(batch_from_columns(
            schema, key=ks, id=ids, ts=ids, value=ids * 3 + ks))
        for r in res:
            want.setdefault(int(r["key"]), []).append(
                [int(r["id"]), int(r["value"])])
    for r in core.flush():
        want.setdefault(int(r["key"]), []).append(
            [int(r["id"]), int(r["value"])])
    assert merged == want


def test_row_channel_fails_fast_on_dead_peer():
    """A connection dying mid-stream must surface as an error from
    batches(), never as a silently truncated stream (wrong totals)."""
    import socket
    import threading
    import numpy as np
    from windflow_tpu.core.tuples import Schema, batch_from_columns
    from windflow_tpu.parallel.channel import RowReceiver, RowSender

    schema = Schema(value=np.int64)
    recv = RowReceiver(n_senders=1)

    def half_send():
        s = RowSender("127.0.0.1", recv.port)
        ids = np.arange(4)
        s.send(batch_from_columns(schema, key=np.zeros(4), id=ids, ts=ids,
                                  value=ids))
        # die without EOS: hard close mid-protocol
        s._sock.shutdown(socket.SHUT_RDWR)
        s._sock.close()

    t = threading.Thread(target=half_send)
    t.start()
    got, err = [], None
    try:
        for b in recv.batches():
            got.append(b)
    except (ConnectionError, OSError) as e:
        err = e
    t.join()
    assert err is not None, "dead peer was swallowed as EOS"


_RESUME_SENDER = r"""
import os, sys, time
import numpy as np
from windflow_tpu.core.tuples import Schema, batch_from_columns
from windflow_tpu.parallel.channel import RowSender, WireResume

port, flag_path = int(sys.argv[1]), sys.argv[2]
schema = Schema(value=np.int64)
snd = RowSender("127.0.0.1", port, resume=WireResume(deadline=30.0),
                connect_deadline=30.0)

def ship(lo, hi):
    for i in range(lo, hi):
        snd.send(batch_from_columns(schema, key=[0], id=[i], ts=[i],
                                    value=[i]))

ship(0, 8)
snd.send_epoch(1)
ship(8, 16)
snd.send_epoch(2)
# hold the last epoch until the parent signals the restarted receiver is
# up — keeps the sender alive across the peer's death
deadline = time.time() + 60
while not os.path.exists(flag_path):
    assert time.time() < deadline, "restart flag never appeared"
    time.sleep(0.05)
ship(16, 24)
snd.send_epoch(3)
snd.close()
print("SENDER_OK")
"""

_RESUME_RECV_A = r"""
import json, os, sys
import numpy as np
from windflow_tpu.parallel.channel import RowReceiver, WireResume
from windflow_tpu.recovery.epoch import EpochMarker

port, out_path = int(sys.argv[1]), sys.argv[2]
r = RowReceiver(1, port=port, resume=WireResume(deadline=30.0),
                ack_epochs=True, accept_timeout=60.0)
it = r.batches(epoch_markers=True)
sealed = []
for item in it:
    if isinstance(item, EpochMarker):
        break                      # epoch-1 barrier (auto-acked)
    sealed.append(int(item["value"][0]))
with open(out_path, "w") as f:
    json.dump({"sealed": sealed}, f)
    f.flush()
    os.fsync(f.fileno())
taken = 0
for item in it:                    # wander into epoch 2, then die hard
    if not isinstance(item, EpochMarker):
        taken += 1
        if taken >= 3:
            break
os._exit(1)
"""

_RESUME_RECV_B = r"""
import json, sys
import numpy as np
from windflow_tpu.parallel.channel import RowReceiver, WireResume
from windflow_tpu.recovery.epoch import EpochMarker

port, out_path = int(sys.argv[1]), sys.argv[2]
r = RowReceiver(1, port=port, resume=WireResume(deadline=30.0),
                resume_epoch=1, ack_epochs=True, accept_timeout=60.0)
got = []
for item in r.batches(epoch_markers=True):
    if not isinstance(item, EpochMarker):
        got.append(int(item["value"][0]))
r.close()
with open(out_path, "w") as f:
    json.dump({"got": got}, f)
print("RECV_B_OK")
"""


def test_receiver_process_restart_resumes_wire(tmp_path):
    """The resume handshake across REAL process boundaries (the
    in-process twins live in tests/test_channel_faults.py): receiver A
    acks the epoch-1 barrier and hard-exits mid-epoch-2; a fresh process
    B re-binds the same port with resume_epoch=1; the journaling sender
    replays epoch 2 from its journal and finishes — A saw exactly epoch
    1, B sees exactly epochs 2..3, no gaps and no duplicates."""
    port = _free_port()
    env = dict(os.environ)
    env["PYTHONPATH"] = (os.path.dirname(os.path.dirname(__file__))
                         + os.pathsep + env.get("PYTHONPATH", ""))
    env.setdefault("JAX_PLATFORMS", "cpu")
    scripts = {}
    for name, src in (("sender", _RESUME_SENDER), ("recv_a", _RESUME_RECV_A),
                      ("recv_b", _RESUME_RECV_B)):
        p = tmp_path / f"{name}.py"
        p.write_text(src)
        scripts[name] = p
    out_a, out_b = tmp_path / "out_a.json", tmp_path / "out_b.json"
    flag = tmp_path / "restart.flag"

    procs = []
    try:
        recv_a = subprocess.Popen(
            [sys.executable, str(scripts["recv_a"]), str(port), str(out_a)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE)
        procs.append(recv_a)
        sender = subprocess.Popen(
            [sys.executable, str(scripts["sender"]), str(port), str(flag)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE)
        procs.append(sender)
        _out, err_a = recv_a.communicate(timeout=120)
        assert recv_a.returncode == 1, (recv_a.returncode,
                                        err_a.decode()[-4000:])
        recv_b = subprocess.Popen(
            [sys.executable, str(scripts["recv_b"]), str(port), str(out_b)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE)
        procs.append(recv_b)
        flag.touch()
        _out, err_s = sender.communicate(timeout=120)
        assert sender.returncode == 0, err_s.decode()[-4000:]
        _out, err_b = recv_b.communicate(timeout=120)
        assert recv_b.returncode == 0, err_b.decode()[-4000:]
    except subprocess.TimeoutExpired:
        for q in procs:
            q.kill()
        raise

    assert json.loads(out_a.read_text())["sealed"] == list(range(8))
    assert json.loads(out_b.read_text())["got"] == list(range(8, 24))


def test_partition_and_ship_rejects_uncovered_owner():
    import numpy as np
    import pytest as _pytest
    from windflow_tpu.core.tuples import Schema, batch_from_columns
    from windflow_tpu.parallel.channel import partition_and_ship
    schema = Schema(value=np.int64)
    b = batch_from_columns(schema, key=np.arange(6), id=np.arange(6),
                           ts=np.arange(6), value=np.arange(6))
    owners = np.array([0, 1, 2, 0, 1, 2])
    with _pytest.raises(KeyError, match="no\\s+RowSender"):
        partition_and_ship(b, owners, 0, {1: object()})
