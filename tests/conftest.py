"""Test configuration: force JAX onto a virtual 8-device CPU mesh so every
sharding/collective path is exercised without TPU hardware (the driver
separately dry-runs the multi-chip path; bench.py runs on the real chip).
Must run before jax is imported anywhere."""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("WINDFLOW_TPU_HOST_ONLY", "0")
