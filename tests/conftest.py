"""Test configuration: force JAX onto a virtual 8-device CPU mesh so every
sharding/collective path is exercised without TPU hardware (the driver
separately dry-runs the multi-chip path; bench.py runs on the real chip).
Must run before jax is imported anywhere."""

import os
import sys

# force, not setdefault: the outer environment may pin JAX_PLATFORMS to the
# TPU plugin, and tests must run on the virtual CPU mesh
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

# a TPU host's sitecustomize may pre-import jax before this conftest runs,
# latching the platform choice — override through the config API as well
if "jax" in sys.modules:
    import jax
    jax.config.update("jax_platforms", "cpu")


def pytest_configure(config):
    # tier-1 runs `-m 'not slow'` (ROADMAP.md); register the marker so
    # soak tests deselect cleanly instead of warning about an unknown mark
    config.addinivalue_line(
        "markers",
        "slow: long-running soak/stress tests, excluded from the tier-1 "
        "suite (-m 'not slow')")
