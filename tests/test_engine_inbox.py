"""Engine channel tests run against BOTH inbox implementations: the native
C++ blocking ring (default when the toolchain is present) and the Python
queue fallback — failure propagation, backpressure, and EOS draining must be
identical."""

import numpy as np
import pytest

from windflow_tpu.core.tuples import Schema, batch_from_columns
from windflow_tpu.patterns.basic import Map, Sink, Source
from windflow_tpu.runtime.engine import Dataflow
from windflow_tpu.runtime.farm import build_pipeline

SCHEMA = Schema(value=np.int64)


@pytest.fixture(params=["native", "python"])
def inbox_kind(request, monkeypatch):
    if request.param == "python":
        monkeypatch.setenv("WF_NO_NATIVE", "1")
    else:
        from windflow_tpu import native
        if not native.available():
            pytest.skip("native library unavailable")
        monkeypatch.delenv("WF_NO_NATIVE", raising=False)
    return request.param


def make_batches(n=1000, chunk=100):
    return [batch_from_columns(
        SCHEMA, key=np.zeros(chunk), id=np.arange(lo, lo + chunk),
        ts=np.arange(lo, lo + chunk),
        value=np.arange(lo, lo + chunk)) for lo in range(0, n, chunk)]


def test_pipeline_runs_and_sums(inbox_kind):
    got = [0]

    def consume(rows):
        if rows is not None and len(rows):
            got[0] += int(rows["value"].sum())

    df = Dataflow(capacity=4)
    build_pipeline(df, [
        Source(batches=make_batches(), schema=SCHEMA),
        Map(lambda b: b, name="identity"),
        Sink(consume, vectorized=True)])
    df.run_and_wait_end()
    assert got[0] == sum(range(1000))


def test_failing_sink_does_not_deadlock(inbox_kind):
    def consume(rows):
        raise RuntimeError("sink boom")

    df = Dataflow(capacity=2)  # tight: producers must be unblocked
    build_pipeline(df, [
        Source(batches=make_batches(4000, 50), schema=SCHEMA),
        Sink(consume, vectorized=True)])
    with pytest.raises(RuntimeError, match="sink boom"):
        df.run_and_wait_end()


def test_failing_middle_stage_unblocks_producer(inbox_kind):
    calls = [0]

    def boom(b):
        calls[0] += 1
        if calls[0] >= 3:
            raise ValueError("map boom")
        return b

    df = Dataflow(capacity=2)
    build_pipeline(df, [
        Source(batches=make_batches(8000, 50), schema=SCHEMA),
        Map(boom, name="boom", vectorized=True),
        Sink(lambda rows: None, vectorized=True)])
    with pytest.raises(ValueError, match="map boom"):
        df.run_and_wait_end()
