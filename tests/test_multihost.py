"""Multi-host mesh layout (parallel/multihost.py): kf splits along the
process (DCN) boundary so key groups never span hosts and sp/wf
neighbours share a host's ICI.  Multi-host topology is simulated on the
virtual 8-device CPU mesh by injecting a process_of mapping (4 devices
per fake host)."""

import numpy as np
import pytest

import jax

from windflow_tpu.parallel.mesh import KF_AXIS, SP_AXIS, WF_AXIS
from windflow_tpu.parallel.multihost import (initialize, local_kf_groups,
                                             make_multihost_mesh,
                                             process_for_keys)

DEVS = jax.devices()
if len(DEVS) < 8:
    pytest.skip("needs the 8-device virtual CPU mesh (conftest)",
                allow_module_level=True)

#: simulate 2 hosts x 4 chips on the virtual devices
FAKE_PID = {id(d): i // 4 for i, d in enumerate(DEVS[:8])}


def pid_of(d):
    return FAKE_PID[id(d)]


def test_kf_splits_along_host_boundary():
    mesh = make_multihost_mesh(n_sp=2, n_wf=1, devices=DEVS[:8],
                               process_of=pid_of)
    assert dict(mesh.shape) == {KF_AXIS: 4, WF_AXIS: 1, SP_AXIS: 2}
    # every kf row's devices live on ONE host; kf rows are host-major
    row_pids = [{pid_of(d) for d in mesh.devices[g].flat}
                for g in range(4)]
    assert row_pids == [{0}, {0}, {1}, {1}]
    # every sp pair is intra-host (collectives ride ICI, not DCN)
    for g in range(4):
        for w in range(1):
            pids = {pid_of(mesh.devices[g, w, s]) for s in range(2)}
            assert len(pids) == 1


def test_sp_cannot_span_hosts():
    with pytest.raises(ValueError, match="ICI"):
        make_multihost_mesh(n_sp=8, devices=DEVS[:8], process_of=pid_of)


def test_uneven_hosts_rejected():
    uneven = {id(d): (0 if i < 3 else 1) for i, d in enumerate(DEVS[:8])}
    with pytest.raises(ValueError, match="disagree"):
        make_multihost_mesh(n_sp=1, devices=DEVS[:8],
                            process_of=lambda d: uneven[id(d)])


def test_process_for_keys_matches_kf_rows():
    mesh = make_multihost_mesh(n_sp=2, devices=DEVS[:8], process_of=pid_of)
    keys = np.arange(40)
    owner = process_for_keys(keys, mesh, process_of=pid_of)
    # key -> kf group is key % 4; groups 0,1 on host 0, groups 2,3 on 1
    np.testing.assert_array_equal(owner, np.where(keys % 4 < 2, 0, 1))
    np.testing.assert_array_equal(
        local_kf_groups(mesh, process_index=1, process_of=pid_of), [2, 3])


def test_single_process_degenerates_to_plain_mesh():
    mesh = make_multihost_mesh(n_sp=2, n_wf=2, devices=DEVS[:8],
                               process_of=lambda d: 0)
    assert dict(mesh.shape) == {KF_AXIS: 2, WF_AXIS: 2, SP_AXIS: 2}
    # and the sharded streaming step runs on it end-to-end
    from windflow_tpu.parallel.mesh import MeshStreamStep
    rng = np.random.default_rng(0)
    N, B, L = 32, 4, 8
    flat = rng.integers(-9, 9, size=(2, N)).astype(np.int32)
    lens = rng.integers(1, L + 1, size=(2, B)).astype(np.int32)
    starts = rng.integers(0, N - L, size=(2, B)).astype(np.int32)
    step = MeshStreamStep(mesh, op="sum")
    got = np.asarray(step(flat, starts, lens))
    want = np.stack([[flat[k, s:s + l].sum() for s, l in zip(starts[k],
                                                             lens[k])]
                     for k in range(2)])
    np.testing.assert_array_equal(got, want)


def test_initialize_noop_only_for_explicit_single_process(monkeypatch):
    calls = []
    monkeypatch.setattr(jax.distributed, "initialize",
                        lambda **kw: calls.append(kw))
    # the explicit single-process job has nothing to coordinate
    initialize(num_processes=1)
    assert calls == []
    # a zero-arg call must DELEGATE to jax's cluster auto-detection
    # (the canonical spelling on a real pod), never be swallowed
    initialize()
    assert len(calls) == 1
    initialize(coordinator_address="host:1234", num_processes=2,
               process_id=1)
    assert calls[-1]["num_processes"] == 2


def test_custom_routing_changes_key_owners():
    mesh = make_multihost_mesh(n_sp=2, devices=DEVS[:8], process_of=pid_of)
    keys = np.arange(8)
    flipped = process_for_keys(keys, mesh, process_of=pid_of,
                               routing=lambda k, n: (k + 2) % n)
    np.testing.assert_array_equal(
        flipped, np.where((keys + 2) % 4 < 2, 0, 1))
