"""Yahoo Streaming Benchmark correctness tests (yahoo_test_cpu analog):
deterministic event batches through the full YSB pipeline, per-window
per-campaign counts checked against a numpy oracle, kf vs wmr differential."""

import threading

import numpy as np
import pytest

from windflow_tpu.apps.ysb import (ADS_PER_CAMPAIGN, EVENT_SCHEMA,
                                   N_CAMPAIGNS, CampaignGenerator,
                                   YSBAggregate, build_pipeline)
from windflow_tpu.core.tuples import batch_from_columns

WIN_SEC = 0.01          # 10 ms tumbling windows (10s scaled down)
WIN_US = int(WIN_SEC * 1e6)


def fixed_batches(n_events, chunk=1000, ts_step_us=50):
    """Deterministic event stream: the reference's ad/event recurrences with
    a linear timestamp ramp (ts_step_us per event)."""
    campaigns = CampaignGenerator()
    out = []
    for lo in range(0, n_events, chunk):
        v = np.arange(lo, min(lo + chunk, n_events), dtype=np.int64)
        vm = v % 100000
        out.append(batch_from_columns(
            EVENT_SCHEMA, key=np.zeros(len(v), dtype=np.int64), id=v,
            ts=v * ts_step_us, ad_id=vm % campaigns.n_ads,
            event_type=(vm % 3).astype(np.int8),
            revenue=(vm % 97) + 1))
    return out


def oracle_counts(n_events, ts_step_us=50, win_us=WIN_US):
    """Expected {(cmp_id, window_index): count} over filtered events."""
    campaigns = CampaignGenerator()
    v = np.arange(n_events, dtype=np.int64)
    vm = v % 100000
    keep = vm % 3 == 0
    cmp_ids = campaigns.ad_to_cmp[(vm % campaigns.n_ads)[keep]]
    wins = (v[keep] * ts_step_us) // win_us
    out = {}
    for c, w in zip(cmp_ids, wins):
        out[(int(c), int(w))] = out.get((int(c), int(w)), 0) + 1
    return out


class Collect:
    def __init__(self):
        self.rows = []
        self._lock = threading.Lock()

    def __call__(self, live):
        with self._lock:
            self.rows.extend(
                (int(r["key"]), int(r["count"]), int(r["lastUpdate"]),
                 int(r["revenue"]))
                for r in live)


def run_variant(variant, n_events=30000, pardegree2=4):
    got = Collect()
    pipe, sink, sent = build_pipeline(
        variant, duration_sec=0, pardegree1=1, pardegree2=pardegree2,
        win_sec=WIN_SEC, batches=fixed_batches(n_events), on_result=got)
    pipe.run_and_wait_end()
    return got, sink, sent


@pytest.mark.parametrize("variant", ["kf", "kf-tpu", "wmr", "wmr-tpu"])
def test_ysb_counts_match_oracle(variant):
    n = 30000
    got, sink, sent = run_variant(variant)
    assert sent[0] == n
    want = oracle_counts(n)
    # sum of per-window counts == number of filtered+joined events
    assert sum(c for _, c, *_ in got.rows) == sum(want.values())
    # per-campaign totals match
    per_cmp = {}
    for k, c, *_ in got.rows:
        per_cmp[k] = per_cmp.get(k, 0) + c
    want_cmp = {}
    for (c, _), n_ in want.items():
        want_cmp[c] = want_cmp.get(c, 0) + n_
    assert per_cmp == want_cmp
    assert sink.received == len(got.rows)


def test_ysb_kf_tpu_differential():
    """The device-path variant must produce the same windows as the host
    KF variant (count and lastUpdate)."""
    a, _, _ = run_variant("kf")
    b, _, _ = run_variant("kf-tpu")
    assert sorted(a.rows) == sorted(b.rows)


def test_ysb_kf_wmr_differential():
    """Both parallel decompositions produce identical (campaign, count)
    multisets — the test_all differential idea applied to YSB."""
    a, _, _ = run_variant("kf")
    b, _, _ = run_variant("wmr")
    assert sorted((k, c) for k, c, *_ in a.rows) == \
        sorted((k, c) for k, c, *_ in b.rows)


def test_ysb_last_update_is_window_max_ts():
    got, _, _ = run_variant("kf", n_events=5000)
    # for a linear ts ramp, each window's lastUpdate is the max filtered
    # event ts that fell into it; check against the oracle recomputation
    campaigns = CampaignGenerator()
    v = np.arange(5000, dtype=np.int64)
    vm = v % 100000
    keep = vm % 3 == 0
    cmp_ids = campaigns.ad_to_cmp[(vm % campaigns.n_ads)[keep]]
    ts = v[keep] * 50
    wins = ts // WIN_US
    want_max = {}
    for c, w, t in zip(cmp_ids, wins, ts):
        want_max[(int(c), int(w))] = max(want_max.get((int(c), int(w)), 0),
                                         int(t))
    # per-campaign multisets must pair up, not just the global multiset
    want_by_key = {}
    for (c, _), t in want_max.items():
        want_by_key.setdefault(c, []).append(t)
    got_by_key = {}
    for k, _, lu, _r in got.rows:
        got_by_key.setdefault(k, []).append(lu)
    assert {k: sorted(v) for k, v in got_by_key.items()} == \
        {k: sorted(v) for k, v in want_by_key.items()}


def test_ysb_aggregate_batch_matches_scalar():
    agg = YSBAggregate()
    rng = np.random.default_rng(0)
    rows = np.zeros(17, dtype=[("ts", np.int64), ("revenue", np.int64)])
    rows["ts"] = rng.integers(0, 1000, 17)
    rows["revenue"] = rng.integers(1, 98, 17)
    want = agg.apply(0, 0, rows)
    pad = 32
    ts_col = np.zeros((1, pad), dtype=np.int64)
    ts_col[0, :17] = rows["ts"]
    rev_col = np.zeros((1, pad), dtype=np.int64)
    rev_col[0, :17] = rows["revenue"]
    got = agg.apply_batch(np.zeros(1), np.zeros(1),
                          {"ts": ts_col, "revenue": rev_col},
                          np.array([17]))
    assert (int(got["count"][0]), int(got["lastUpdate"][0]),
            int(got["revenue"][0])) == want


def test_ysb_revenue_matches_oracle():
    """r3: the device-worthy SUM(revenue) must equal the per-campaign
    oracle on both the host and the device variants."""
    campaigns = CampaignGenerator()
    n = 30000
    v = np.arange(n, dtype=np.int64)
    vm = v % 100000
    keep = vm % 3 == 0
    cmp_ids = campaigns.ad_to_cmp[(vm % campaigns.n_ads)[keep]]
    rev = ((vm % 97) + 1)[keep]
    want_cmp = {}
    for c, r in zip(cmp_ids, rev):
        want_cmp[int(c)] = want_cmp.get(int(c), 0) + int(r)
    for variant in ("kf", "kf-tpu"):
        got, _, _ = run_variant(variant, n_events=n)
        per_cmp = {}
        for k, _c, _lu, r in got.rows:
            per_cmp[k] = per_cmp.get(k, 0) + r
        assert per_cmp == want_cmp, variant


def test_ysb_wmr_tpu_differential():
    """The device-MAP Win_MapReduce variant must produce the same windows
    (count, lastUpdate, revenue) as the host kf variant."""
    a, _, _ = run_variant("kf")
    b, _, _ = run_variant("wmr-tpu")
    assert sorted(a.rows) == sorted(b.rows)


def test_rich_stats_min_ts_is_host_free():
    """r5 (second half): MIN over the position field is as free as MAX —
    the position-ordered archive's first window row holds it — so
    device_aggregate(rich=True)'s firstUpdate no longer ships the ts
    column: the device half collapses back to the single revenue ring
    and BOTH extremes ride the pos-extrema split.  (The multi-field
    device path stays exercised by tests/test_native.py's multifield
    suite and the recorded on-chip A/B, BASELINE.md round 5.)"""
    import warnings

    from windflow_tpu.apps.ysb import device_aggregate
    from windflow_tpu.core.windows import WindowSpec, WinType
    from windflow_tpu.patterns.win_seq_tpu import make_core_for, \
        split_pos_max

    spec = WindowSpec(10_000_000, 10_000_000, WinType.TB)
    agg = device_aggregate(rich=True)
    dev, pos = split_pos_max(spec, agg)
    assert [p.field for p in dev] == ["revenue"]
    assert sorted((p.op, p.out_field) for p in pos) == [
        ("max", "lastUpdate"), ("min", "firstUpdate")]
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        core = make_core_for(spec, agg, batch_len=256)
    assert getattr(core, "_ship_fields", None) == ("revenue",)
