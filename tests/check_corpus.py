"""Seeded misconfiguration corpus for ``scripts/wf_lint.py`` (ISSUE 11
acceptance): every graph/config here plants at least one specific WF###
diagnostic, and ``tests/test_check.py::test_wf_lint_cli_corpus`` asserts
the CLI reports each of ``PLANTED``.

Not a test module itself — imported by wf_lint via the
``wf_check_pipelines()`` convention (and as a module-level ``WireConfig``
scan target).
"""

import numpy as np

from windflow_tpu.api import MultiPipe
from windflow_tpu.core.tuples import Schema
from windflow_tpu.core.windows import WinType
from windflow_tpu.parallel.channel import WireConfig
from windflow_tpu.parallel.plane import PlanePolicy
from windflow_tpu.patterns.basic import Sink, Source, Map
from windflow_tpu.patterns.pane_farm import PaneFarm
from windflow_tpu.patterns.win_seq import WinSeq
from windflow_tpu.runtime.overload import OverloadPolicy

SCHEMA = Schema(value=np.int64)

#: WF### ids the CLI run over this module must report
PLANTED = ("WF102", "WF103", "WF204", "WF205", "WF207", "WF208",
           "WF213", "WF214", "WF216", "WF217", "WF301")

#: module-level scan target: heartbeat at/above the stall timeout
BAD_WIRE = WireConfig(heartbeat=5.0, stall_timeout=2.0)   # -> WF205

#: module-level scan target: journal that can never trim (no acks)
BAD_RESUME_WIRE = WireConfig(resume=True)                 # -> WF214

#: module-level scan target: supervised plane whose handoff promise
#: has no journals to replay from
BAD_PLANE = PlanePolicy(wire=WireConfig.hardened())       # -> WF216


def _red(key, gwid, rows):
    return {"value": rows["value"].sum()}


def _src(shipper):
    return None


def _window_pipe() -> MultiPipe:
    """WF102 (hopping gap) + WF103 (non-divisible pane factor) +
    WF207 (metrics with no trace_dir)."""
    return (MultiPipe("corpus_windows", metrics=True)
            .add_source(Source(_src, SCHEMA))
            .add(WinSeq(_red, 4, 8, WinType.CB,
                        result_fields={"value": np.int64}))
            .add(PaneFarm(_red, _red, 10, 3, WinType.CB,
                          plq_result_fields={"value": np.int64},
                          wlq_result_fields={"value": np.int64}))
            .chain_sink(Sink(lambda b: None, vectorized=True)))


def _overload_pipe() -> MultiPipe:
    """WF208: shedding policy on unbounded inboxes (never builds)."""
    return (MultiPipe("corpus_overload", capacity=0,
                      overload=OverloadPolicy(shed="shed_newest"))
            .add_source(Source(_src, SCHEMA))
            .chain_sink(Sink(lambda b: None, vectorized=True)))


def _recovery_pipe() -> MultiPipe:
    """WF204: recovery over a sink that never opted into restart."""
    from windflow_tpu.recovery.policy import RecoveryPolicy
    return (MultiPipe("corpus_recovery", recovery=RecoveryPolicy())
            .add_source(Source(_src, SCHEMA))
            .chain_sink(Sink(lambda b: None, vectorized=True)))


def _trace_pipe() -> MultiPipe:
    """WF213: span tracing with no trace_dir (spans stay ring-only)."""
    from windflow_tpu.obs.trace import TracePolicy
    return (MultiPipe("corpus_trace", trace=TracePolicy(sample_rate=0.5))
            .add_source(Source(_src, SCHEMA))
            .chain_sink(Sink(lambda b: None, vectorized=True)))


def _federate_pipe() -> MultiPipe:
    """WF217: federation with no sampler to feed the shipper."""
    from windflow_tpu.obs.federation import FederationPolicy
    return (MultiPipe("corpus_federate",
                      federate=FederationPolicy(host="corpus"))
            .add_source(Source(_src, SCHEMA))
            .chain_sink(Sink(lambda b: None, vectorized=True)))


def _race_pipe() -> MultiPipe:
    """WF301: parallel replicas mutating closed-over shared state."""
    counts = [0]

    def bump(batch):
        counts[0] += len(batch)

    return (MultiPipe("corpus_race")
            .add_source(Source(_src, SCHEMA))
            .add(Map(bump, parallelism=2, vectorized=True))
            .chain_sink(Sink(lambda b: None, vectorized=True)))


def wf_check_pipelines():
    return [_window_pipe(), _overload_pipe(), _recovery_pipe(),
            _trace_pipe(), _federate_pipe(), _race_pipe(), BAD_WIRE,
            BAD_RESUME_WIRE, BAD_PLANE]
