"""Nesting differential tests: WF(PF), WF(WMR), KF(PF), KF(WMR) must match
Win_Seq on the same stream — the compositions exercised by the reference's
test_{wf,kf}+{pf,wm}_* programs and test_all harness."""

import pytest

from windflow_tpu.core.windows import WinType
from windflow_tpu.ops.functions import Reducer
from windflow_tpu.patterns.nesting import KeyFarmOf, WinFarmOf
from windflow_tpu.patterns.pane_farm import PaneFarm
from windflow_tpu.patterns.win_mapreduce import WinMapReduce
from windflow_tpu.patterns.win_seq import WinSeq

from test_farms import cb_stream_batches, tb_stream_batches, run_windowed
from test_pane_wmr import iv


def ref_results(win, slide, wt, batches):
    return iv(run_windowed(WinSeq(Reducer("sum"), win, slide, wt), batches))


@pytest.mark.parametrize("outer", [2, 3])
@pytest.mark.parametrize("plq,wlq", [(1, 1), (2, 2)])
def test_wf_of_pf_cb(outer, plq, wlq):
    # private slide = slide*outer must stay < win (pane_farm sliding check)
    win, slide, keys, n = 16, 4, 3, 140
    inner = PaneFarm(Reducer("sum"), Reducer("sum"), win, slide, WinType.CB,
                     plq_degree=plq, wlq_degree=wlq)
    got = iv(run_windowed(WinFarmOf(inner, pardegree=outer),
                          cb_stream_batches(keys, n)))
    assert got == ref_results(win, slide, WinType.CB, cb_stream_batches(keys, n))


def test_wf_of_pf_tb():
    win, slide, keys, n = 60, 12, 2, 150
    inner = PaneFarm(Reducer("sum"), Reducer("sum"), win, slide, WinType.TB)
    got = iv(run_windowed(WinFarmOf(inner, pardegree=3),
                          tb_stream_batches(keys, n)))
    assert got == ref_results(win, slide, WinType.TB, tb_stream_batches(keys, n))


@pytest.mark.parametrize("outer", [2, 3])
@pytest.mark.parametrize("map_d,red_d", [(2, 1), (3, 2)])
def test_wf_of_wmr_cb(outer, map_d, red_d):
    win, slide, keys, n = 12, 3, 3, 130
    inner = WinMapReduce(Reducer("sum"), Reducer("sum"), win, slide,
                         WinType.CB, map_degree=map_d, reduce_degree=red_d)
    got = iv(run_windowed(WinFarmOf(inner, pardegree=outer),
                          cb_stream_batches(keys, n)))
    assert got == ref_results(win, slide, WinType.CB, cb_stream_batches(keys, n))


@pytest.mark.parametrize("outer", [2, 4])
@pytest.mark.parametrize("plq,wlq", [(1, 1), (2, 1)])
def test_kf_of_pf_cb(outer, plq, wlq):
    win, slide, keys, n = 12, 4, 5, 120
    inner = PaneFarm(Reducer("sum"), Reducer("sum"), win, slide, WinType.CB,
                     plq_degree=plq, wlq_degree=wlq)
    got = iv(run_windowed(KeyFarmOf(inner, pardegree=outer),
                          cb_stream_batches(keys, n)))
    assert got == ref_results(win, slide, WinType.CB, cb_stream_batches(keys, n))


@pytest.mark.parametrize("outer", [2, 3])
@pytest.mark.parametrize("map_d", [2, 3])
def test_kf_of_wmr_cb(outer, map_d):
    win, slide, keys, n = 10, 5, 4, 120
    inner = WinMapReduce(Reducer("sum"), Reducer("sum"), win, slide,
                         WinType.CB, map_degree=map_d)
    got = iv(run_windowed(KeyFarmOf(inner, pardegree=outer),
                          cb_stream_batches(keys, n)))
    assert got == ref_results(win, slide, WinType.CB, cb_stream_batches(keys, n))


def test_kf_of_wmr_tb():
    win, slide, keys, n = 45, 15, 3, 140
    inner = WinMapReduce(Reducer("sum"), Reducer("sum"), win, slide,
                         WinType.TB, map_degree=2, reduce_degree=2)
    got = iv(run_windowed(KeyFarmOf(inner, pardegree=2),
                          tb_stream_batches(keys, n)))
    assert got == ref_results(win, slide, WinType.TB, tb_stream_batches(keys, n))


def test_nested_incremental_stages():
    win, slide, keys, n = 16, 4, 3, 120
    inner = PaneFarm(Reducer("sum"), Reducer("sum"), win, slide, WinType.CB,
                     plq_degree=2, wlq_degree=1, plq_incremental=True,
                     wlq_incremental=True)
    got = iv(run_windowed(WinFarmOf(inner, pardegree=2),
                          cb_stream_batches(keys, n)))
    assert got == ref_results(win, slide, WinType.CB, cb_stream_batches(keys, n))
