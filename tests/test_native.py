"""Differential tests for the C++ native window core (native/wf_native.cpp
via NativeResidentCore): byte-identical results to the pure-Python host core
on the same streams — the native twin of test_resident.py.  Skipped when the
native toolchain is unavailable."""

import warnings

import numpy as np
import pytest

from windflow_tpu.core.tuples import Schema, batch_from_columns
from windflow_tpu.core.windows import PatternConfig, Role, WindowSpec, WinType
from windflow_tpu.core.winseq import WinSeqCore
from windflow_tpu.ops.functions import Reducer

native = pytest.importorskip("windflow_tpu.native")
if not native.available():
    pytest.skip("native library unavailable", allow_module_level=True)

from windflow_tpu.patterns.native_core import NativeResidentCore  # noqa: E402
from windflow_tpu.patterns.win_seq_tpu import make_core_for  # noqa: E402

SCHEMA = Schema(value=np.int64)


def make_native(spec, reducer, **kw):
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        return NativeResidentCore(spec, reducer, **kw)


def run_core(core, batches):
    outs = []
    for b in batches:
        r = core.process(b)
        if len(r):
            outs.append(r)
    r = core.flush()
    if len(r):
        outs.append(r)
    if not outs:
        return np.zeros(0, dtype=core._result_dtype)
    return np.sort(np.concatenate(outs), order=["key", "id"])


def cb_stream(n_keys, per_key, chunk=37, seed=0, lo_val=-50, hi_val=100):
    rng = np.random.default_rng(seed)
    batches = []
    for lo in range(0, per_key, chunk):
        m = min(chunk, per_key - lo)
        ids = np.repeat(np.arange(lo, lo + m), n_keys)
        keys = np.tile(np.arange(n_keys), m)
        vals = rng.integers(lo_val, hi_val, size=m * n_keys).astype(np.int64)
        batches.append(batch_from_columns(
            SCHEMA, key=keys, id=ids, ts=ids, value=vals))
    return batches


def assert_equal_results(a, b):
    assert len(a) == len(b)
    for f in ("key", "id", "ts", "value"):
        np.testing.assert_array_equal(a[f], b[f])


def test_native_is_default_selection(monkeypatch):
    monkeypatch.delenv("WF_NO_NATIVE", raising=False)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        core = make_core_for(WindowSpec(16, 4, WinType.CB), Reducer("sum"))
    assert isinstance(core, NativeResidentCore)


@pytest.mark.parametrize("op", ["sum", "min", "max", "prod"])
@pytest.mark.parametrize("win,slide", [(16, 4), (8, 8), (4, 12)])
@pytest.mark.parametrize("n_keys", [1, 5])
def test_native_cb_matches_host(op, win, slide, n_keys):
    lo, hi = (1, 3) if op == "prod" else (-50, 100)
    batches = cb_stream(n_keys, 503, seed=win * 31 + slide,
                        lo_val=lo, hi_val=hi)
    spec = WindowSpec(win, slide, WinType.CB)
    host = run_core(WinSeqCore(spec, Reducer(op)), batches)
    nat = make_native(spec, Reducer(op), batch_len=64, flush_rows=200)
    assert_equal_results(host, run_core(nat, batches))


@pytest.mark.parametrize("op", ["sum", "max"])
@pytest.mark.parametrize("win,slide", [(20, 5), (10, 10), (6, 16)])
def test_native_tb_matches_host(op, win, slide):
    rng = np.random.default_rng(win + slide)
    nk, per = 3, 400
    ts_all = np.sort(rng.integers(0, 900, size=per))
    batches = []
    for lo in range(0, per, 53):
        m = min(53, per - lo)
        batches.append(batch_from_columns(
            SCHEMA, key=np.tile(np.arange(nk), m),
            id=np.repeat(np.arange(lo, lo + m), nk),
            ts=np.repeat(ts_all[lo:lo + m], nk),
            value=rng.integers(0, 100, size=m * nk).astype(np.int64)))
    spec = WindowSpec(win, slide, WinType.TB)
    host = run_core(WinSeqCore(spec, Reducer(op)), batches)
    nat = make_native(spec, Reducer(op), batch_len=32, flush_rows=150)
    assert_equal_results(host, run_core(nat, batches))


@pytest.mark.parametrize("role,cfg", [
    (Role.PLQ, PatternConfig(0, 1, 8, 1, 2, 8)),
    (Role.MAP, PatternConfig(0, 1, 8, 0, 1, 8)),
    (Role.WLQ, PatternConfig(1, 2, 8, 0, 1, 8)),
])
def test_native_role_renumbering(role, cfg):
    batches = cb_stream(3, 300, chunk=29, seed=7)
    spec = WindowSpec(8, 8, WinType.CB)
    host = run_core(
        WinSeqCore(spec, Reducer("sum"), config=cfg, role=role,
                   map_indexes=(1, 3)), batches)
    nat = make_native(spec, Reducer("sum"), config=cfg, role=role,
                      map_indexes=(1, 3), batch_len=32, flush_rows=100)
    assert_equal_results(host, run_core(nat, batches))


def test_native_regular_descriptors_engage():
    """Steady-state CB sliding windows must take the compressed
    regular-descriptor launch path (per-key scalars expanded on device),
    and still match the host core."""
    from windflow_tpu.ops.resident import ResidentWindowExecutor
    batches = cb_stream(4, 800, chunk=100, seed=31)
    spec = WindowSpec(16, 4, WinType.CB)
    want = run_core(WinSeqCore(spec, Reducer("sum")), batches)
    # count actual launch_regular dispatches (a cache-key delta is order-
    # dependent: the prewarm ladder in an earlier test may have compiled
    # this shape already)
    calls = []
    orig = ResidentWindowExecutor.launch_regular

    def counting(self, *a, **kw):
        calls.append(1)
        return orig(self, *a, **kw)

    ResidentWindowExecutor.launch_regular = counting
    try:
        core = make_native(spec, Reducer("sum"), batch_len=64,
                           flush_rows=250)
        assert_equal_results(want, run_core(core, batches))
    finally:
        ResidentWindowExecutor.launch_regular = orig
    assert calls, "regular-descriptor path never engaged"


def test_native_out_of_order_drops():
    """Late rows are dropped identically (win_seq.hpp:293-305)."""
    rng = np.random.default_rng(13)
    ids = np.arange(200)
    ids[50] = 10       # a late row mid-stream
    ids[120] = 100
    vals = rng.integers(0, 50, size=200).astype(np.int64)
    b = batch_from_columns(SCHEMA, key=np.zeros(200), id=ids, ts=ids,
                           value=vals)
    spec = WindowSpec(12, 4, WinType.CB)
    host = run_core(WinSeqCore(spec, Reducer("sum")), [b])
    nat = make_native(spec, Reducer("sum"), batch_len=16, flush_rows=64)
    assert_equal_results(host, run_core(nat, [b]))


def test_native_markers_and_empty_flush():
    """EOS markers advance firing without being archived."""
    from windflow_tpu.core.tuples import MARKER_FIELD
    b = batch_from_columns(SCHEMA, key=np.zeros(20), id=np.arange(20),
                           ts=np.arange(20) * 10,
                           value=np.ones(20, dtype=np.int64))
    m = batch_from_columns(SCHEMA, key=np.zeros(1), id=[40], ts=[400],
                           value=[0])
    m[MARKER_FIELD] = True
    spec = WindowSpec(8, 4, WinType.CB)
    host = run_core(WinSeqCore(spec, Reducer("sum")), [b, m])
    nat = make_native(spec, Reducer("sum"), batch_len=8, flush_rows=32)
    assert_equal_results(host, run_core(nat, [b, m]))


def test_native_falls_back_on_float_payload():
    schema = Schema(value=np.float64)
    b = batch_from_columns(schema, key=np.zeros(10), id=np.arange(10),
                           ts=np.arange(10),
                           value=np.arange(10, dtype=np.float64))
    nat = make_native(WindowSpec(4, 2, WinType.CB), Reducer("max"),
                      batch_len=8, flush_rows=32)
    out = np.concatenate([nat.process(b), nat.flush()])
    host_core = WinSeqCore(WindowSpec(4, 2, WinType.CB), Reducer("max"))
    want = np.concatenate([host_core.process(b), host_core.flush()])
    np.testing.assert_array_equal(np.sort(out, order=["key", "id"])["value"],
                                  np.sort(want, order=["key", "id"])["value"])


def test_native_wide_values_use_int32_wire():
    batches = cb_stream(2, 256, seed=5, lo_val=-40000, hi_val=40000)
    spec = WindowSpec(16, 4, WinType.CB)
    host = run_core(WinSeqCore(spec, Reducer("sum")), batches)
    nat = make_native(spec, Reducer("sum"), batch_len=64, flush_rows=300)
    assert_equal_results(host, run_core(nat, batches))


@pytest.mark.parametrize("overlap", [True, False])
@pytest.mark.parametrize("shards", [1, 3])
def test_native_overlap_and_shards_match_host(overlap, shards):
    """The ship-thread overlap mode and the synchronous mode produce
    identical results for any shard count."""
    batches = cb_stream(5, 400, chunk=41, seed=29)
    spec = WindowSpec(12, 4, WinType.CB)
    want = run_core(WinSeqCore(spec, Reducer("sum")), batches)
    core = make_native(spec, Reducer("sum"), batch_len=32, flush_rows=120,
                       shards=shards, overlap=overlap)
    assert_equal_results(want, run_core(core, batches))


def test_native_sharded_cores_concurrent_threads():
    """Two sharded cores driven from two threads concurrently (two windowed
    nodes in one pipeline): the shard pool must not mix their tasks —
    regression for the unserialized ShardPool::run data race."""
    import threading
    batches = cb_stream(6, 600, chunk=50, seed=23)
    spec = WindowSpec(16, 4, WinType.CB)
    want = run_core(WinSeqCore(spec, Reducer("sum")), batches)

    results = [None, None]
    def drive(i):
        core = make_native(spec, Reducer("sum"), batch_len=32,
                           flush_rows=120, shards=2)
        results[i] = run_core(core, batches)

    for _ in range(5):
        ts = [threading.Thread(target=drive, args=(i,)) for i in range(2)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        for r in results:
            assert_equal_results(want, r)


def test_native_hopping_gaps():
    batches = cb_stream(2, 300, chunk=41, seed=21)
    spec = WindowSpec(4, 10, WinType.CB)   # hopping: slide > win
    host = run_core(WinSeqCore(spec, Reducer("sum")), batches)
    nat = make_native(spec, Reducer("sum"), batch_len=16, flush_rows=100)
    assert_equal_results(host, run_core(nat, batches))


def test_native_max_delay_flushes_partial_batches():
    """Native core: max_delay_ms ships pending windows via
    wf_core_force_flush on the next process() after the deadline."""
    import time as _time
    import warnings
    import numpy as np
    from windflow_tpu.core.windows import WindowSpec, WinType
    from windflow_tpu.ops.functions import Reducer
    from windflow_tpu.patterns.native_core import NativeResidentCore
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        core = NativeResidentCore(WindowSpec(4, 4, WinType.CB),
                                  Reducer("sum"), batch_len=1 << 20,
                                  flush_rows=1 << 20, max_delay_ms=1)
    from windflow_tpu.core.tuples import Schema, batch_from_columns
    b = batch_from_columns(Schema(value=np.int64), key=np.zeros(8),
                           id=np.arange(8), ts=np.arange(8),
                           value=np.arange(8))
    got = core.process(b)
    _time.sleep(0.01)
    deadline = _time.monotonic() + 5
    n = len(got)
    while n == 0 and _time.monotonic() < deadline:
        _time.sleep(0.01)
        n += len(core.process(b[:0]))
    assert n > 0, "native max_delay did not ship the pending windows"
    core.flush()


def test_native_launch_coalescing_matches_host():
    """Adaptive launch coalescing (wf_launch_coalesce): many small queued
    launches fuse into fewer dispatches; results stay byte-identical to the
    host core.  Tiny flush_rows + big chunks force multiple launches per
    process() call, so the queue is >1 deep at every ship."""
    batches = cb_stream(5, 2000, chunk=997, seed=9)
    spec = WindowSpec(16, 4, WinType.CB)
    host = run_core(WinSeqCore(spec, Reducer("sum")), batches)
    nat = make_native(spec, Reducer("sum"), batch_len=1 << 20,
                      flush_rows=64, overlap=False)
    # count actual merges through the C ABI (not just queue depth: a
    # regressed try_merge that always refuses would still keep results
    # correct via unmerged dispatches)
    merges = []
    real = nat._lib

    class _Shim:
        def __getattr__(self, name):
            if name != "wf_launch_coalesce":
                return getattr(real, name)

            def counting(h, cells, mx, mult):
                n = real.wf_launch_coalesce(h, cells, mx, mult)
                merges.append(n)
                return n
            return counting

    nat._lib = _Shim()
    got = run_core(nat, batches)
    assert_equal_results(host, got)
    assert sum(merges) > 0, "wf_launch_coalesce never merged a pair"


def test_native_coalesce_across_value_widths():
    """Launches whose wire dtypes differ (int8 vs int16 chunks) widen on
    merge without corrupting values."""
    spec = WindowSpec(8, 8, WinType.CB)
    rng = np.random.default_rng(3)
    batches = []
    for c, (lo, hi) in enumerate([(-5, 5), (-3000, 3000), (-5, 5),
                                  (-30000, 30000)]):
        m = 256
        ids = np.repeat(np.arange(c * m, (c + 1) * m), 3)
        keys = np.tile(np.arange(3), m)
        vals = rng.integers(lo, hi, size=m * 3).astype(np.int64)
        batches.append(batch_from_columns(
            SCHEMA, key=keys, id=ids, ts=ids, value=vals))
    host = run_core(WinSeqCore(spec, Reducer("sum")), batches)
    nat = make_native(spec, Reducer("sum"), batch_len=1 << 20,
                      flush_rows=96, overlap=False)
    assert_equal_results(host, run_core(nat, batches))


def test_native_periodic_fast_path_equals_general():
    """The periodic-chunk bulk path must be row-identical to the general
    loop: the same logical stream arranged periodically (fast path
    engages) and pair-shuffled (detection bails -> general loop) gives
    identical sorted results."""
    spec = WindowSpec(16, 4, WinType.CB)
    n_keys, per_key = 8, 600
    rng = np.random.default_rng(31)
    vals = rng.integers(-50, 100, size=per_key * n_keys).astype(np.int64)

    def stream(shuffled):
        batches = []
        for lo in range(0, per_key, 97):
            m = min(97, per_key - lo)
            keys = np.tile(np.arange(n_keys), m)
            ids = np.repeat(np.arange(lo, lo + m), n_keys)
            v = vals[lo * n_keys:(lo + m) * n_keys]
            if shuffled:
                # swap adjacent different-key rows: periodicity breaks,
                # per-key order survives
                perm = np.arange(m * n_keys)
                even = perm[: (m * n_keys) // 2 * 2]
                perm[: len(even)] = even.reshape(-1, 2)[:, ::-1].ravel()
                keys, ids, v = keys[perm], ids[perm], v[perm]
            batches.append(batch_from_columns(
                SCHEMA, key=keys, id=ids, ts=ids * 7, value=v))
        return batches

    a = run_core(make_native(spec, Reducer("sum"), batch_len=64,
                             flush_rows=500), stream(False))
    b = run_core(make_native(spec, Reducer("sum"), batch_len=64,
                             flush_rows=500), stream(True))
    host = run_core(WinSeqCore(spec, Reducer("sum")), stream(False))
    assert_equal_results(host, a)
    assert_equal_results(host, b)


def test_native_periodic_fast_path_cross_chunk_gap():
    """A periodic chunk whose per-key ids jump past the previous chunk's
    (id gap across chunks) must produce the same empty-window firings as
    the general loop."""
    spec = WindowSpec(8, 8, WinType.CB)
    n_keys = 4

    def chunk(lo, m):
        return batch_from_columns(
            SCHEMA, key=np.tile(np.arange(n_keys), m),
            id=np.repeat(np.arange(lo, lo + m), n_keys),
            ts=np.repeat(np.arange(lo, lo + m), n_keys),
            value=np.arange(m * n_keys, dtype=np.int64))

    batches = [chunk(0, 20), chunk(50, 20), chunk(200, 20)]
    host = run_core(WinSeqCore(spec, Reducer("sum")), batches)
    nat = make_native(spec, Reducer("sum"), batch_len=32, flush_rows=64)
    assert_equal_results(host, run_core(nat, batches))


def test_native_rebase_reships_wide_values_on_wide_wire():
    """A ring rebase re-ships ALL live rows; the wire dtype must cover the
    re-shipped (previously shipped) values, not just the pending ones —
    narrow-wire truncation here silently corrupts aggregates."""
    spec = WindowSpec(16, 4, WinType.CB)
    # key 0 ships 8 rows of 3000 (int16 wire) first ...
    b1 = batch_from_columns(SCHEMA, key=np.zeros(8), id=np.arange(8),
                            ts=np.arange(8),
                            value=np.full(8, 3000, dtype=np.int64))
    # ... then 19 NEW keys with tiny values force KP growth -> rebase;
    # the rebase launch re-ships key 0's live 3000s
    rows = []
    for i in range(8, 20):
        for k in range(20):
            rows.append((k, i))
    keys = np.array([r[0] for r in rows])
    ids = np.array([r[1] for r in rows])
    b2 = batch_from_columns(SCHEMA, key=keys, id=ids, ts=ids,
                            value=np.ones(len(rows), dtype=np.int64))
    host = run_core(WinSeqCore(spec, Reducer("sum")), [b1, b2])
    nat = make_native(spec, Reducer("sum"), batch_len=1 << 20, flush_rows=8)
    assert_equal_results(host, run_core(nat, [b1, b2]))


def test_ship_thread_failure_surfaces_and_salvages():
    """A one-shot executor failure on the ship thread must surface on the
    node thread's next process()/flush(); a caller that catches it and
    keeps streaming gets the already-harvested results back (salvage
    path, native_core.py:_raise_ship_exc) and the stream completes."""
    spec = WindowSpec(8, 4, WinType.CB)
    nat = make_native(spec, Reducer("sum"), batch_len=8, flush_rows=32,
                      overlap=True)
    boom = {"at": 3, "calls": 0}
    ex = nat.executors[0]
    orig_launch = ex.launch
    orig_reg = ex.launch_regular

    def failing(*a, **kw):
        boom["calls"] += 1
        if boom["calls"] == boom["at"]:     # fail exactly once
            raise RuntimeError("injected wire failure")
        # launch() takes 6 positional args, launch_regular 9+
        return (orig_reg if len(a) > 6 else orig_launch)(*a, **kw)

    ex.launch = failing
    ex.launch_regular = failing
    batches = cb_stream(2, 400, chunk=50, seed=21)
    rows_before = rows_after = 0
    raised = False
    for b in batches:
        try:
            n = len(nat.process(b))
        except RuntimeError as e:
            assert "injected" in str(e)
            raised = True
            continue
        if raised:
            rows_after += n
        else:
            rows_before += n
    try:
        rows_after += len(nat.flush())
    except RuntimeError as e:
        # failure surfaced at drain time: it raises exactly once, and the
        # retry returns everything salvaged plus the remaining windows
        assert "injected" in str(e)
        raised = True
        rows_after += len(nat.flush())
    assert raised, "injected failure never surfaced"
    # the stream kept going after the caught failure and produced the
    # remaining windows (incl. any salvaged across the raise); only the
    # single failed launch's windows may be missing
    assert rows_after > 0


def test_ship_thread_failure_cancels_dataflow(monkeypatch):
    """A device failure inside a windowed node must cancel the whole
    graph (no deadlock), like any node exception (runtime/engine.py)."""
    from windflow_tpu.core.tuples import Schema
    from windflow_tpu.ops.resident import ResidentWindowExecutor
    from windflow_tpu.patterns.basic import Sink, Source
    from windflow_tpu.patterns.win_seq_tpu import WinSeqTPU
    from windflow_tpu.runtime.engine import Dataflow
    from windflow_tpu.runtime.farm import build_pipeline

    def boom(self, *a, **kw):
        raise RuntimeError("injected device failure")

    monkeypatch.setattr(ResidentWindowExecutor, "launch", boom)
    monkeypatch.setattr(ResidentWindowExecutor, "launch_regular", boom)
    schema = Schema(value=np.int64)
    df = Dataflow()
    build_pipeline(df, [Source(batches=iter(cb_stream(2, 300, chunk=40)),
                               schema=schema),
                        WinSeqTPU(Reducer("sum"), 8, 4, WinType.CB,
                                  batch_len=8, flush_rows=32),
                        Sink(lambda r: None, vectorized=True)])
    with pytest.raises(RuntimeError, match="injected"):
        df.run_and_wait_end()


def test_native_deep_coalescing_ladder():
    """With the wire reported slow (mean service >= 50 ms), the buddy
    ladder is allowed up to 16x: a stream producing hundreds of regular
    launches must reach dispatch counts well below the 4x cap's floor,
    with results still byte-identical to the host core."""
    spec = WindowSpec(16, 4, WinType.CB)
    batches = cb_stream(4, 20000, chunk=2048, seed=5)
    host = run_core(WinSeqCore(spec, Reducer("sum")), batches)
    nat = make_native(spec, Reducer("sum"), batch_len=1 << 20,
                      flush_rows=256, overlap=False)
    # ~312 natural launches (4*20000/256); pretend the wire is stalled so
    # the adaptive cap opens the full ladder
    for ex in nat.executors:
        ex.mean_service_s = lambda: 1.0
    dispatches = []
    for ex in nat.executors:
        orig_r, orig_i = ex.launch_regular, ex.launch

        def count_r(*a, _f=orig_r, **kw):
            dispatches.append("r")
            return _f(*a, **kw)

        def count_i(*a, _f=orig_i, **kw):
            dispatches.append("i")
            return _f(*a, **kw)
        ex.launch_regular, ex.launch = count_r, count_i
    got = run_core(nat, batches)
    assert_equal_results(host, got)
    n_launch = 4 * 20000 // 256
    # the 4x-capped ladder could at best reach ~n_launch/4 (plus rebases);
    # the 16x ladder must do strictly better than that floor
    assert len(dispatches) < n_launch // 4, (
        f"{len(dispatches)} dispatches for ~{n_launch} launches — deep "
        "coalescing did not engage")


def test_native_rebase_launches_never_merge():
    """ADVICE r2: try_merge must reject a rebase launch in either role (A
    or B) — a rebase is a dispatch barrier.  Queue exactly [rebase,
    regular] and coalesce: nothing may merge."""
    spec = WindowSpec(8, 4, WinType.CB)
    # flush_rows far above the feeds: each force_flush makes exactly one
    # launch, so the queue is exactly [rebase, regular]
    nat = make_native(spec, Reducer("sum"), batch_len=1 << 20,
                      flush_rows=4096, overlap=False)
    lib, h = nat._lib, nat._hs[0]
    b1 = cb_stream(2, 32, chunk=32, seed=1)[0]
    off = nat._field_offsets(b1)
    itemsize, o_key, o_id, o_ts, o_mk, o_val = off

    def feed(b):
        bb = np.ascontiguousarray(b)
        lib.wf_cores_process_mt(nat._harr, 1, bb.ctypes.data, len(bb),
                                itemsize, o_key, o_id, o_ts, o_mk, o_val)

    # first flush = rebase launch; second = regular continuation
    feed(cb_stream(2, 64, chunk=64, seed=1)[0])
    lib.wf_core_force_flush(h)
    feed(batch_from_columns(SCHEMA, key=np.tile(np.arange(2), 64),
                            id=np.repeat(np.arange(64, 128), 2),
                            ts=np.repeat(np.arange(64, 128), 2),
                            value=np.ones(128, dtype=np.int64)))
    lib.wf_core_force_flush(h)
    assert lib.wf_launch_pending(h) == 2
    merged = lib.wf_launch_coalesce(h, 1 << 24, 16, 16)
    assert merged == 0, "a rebase launch was merged"
    assert lib.wf_launch_pending(h) == 2
    # drain normally so results stay correct
    host = run_core(WinSeqCore(spec, Reducer("sum")),
                    [cb_stream(2, 64, chunk=64, seed=1)[0],
                     batch_from_columns(
                         SCHEMA, key=np.tile(np.arange(2), 64),
                         id=np.repeat(np.arange(64, 128), 2),
                         ts=np.repeat(np.arange(64, 128), 2),
                         value=np.ones(128, dtype=np.int64))])
    got = run_core(nat, [])
    assert_equal_results(host, got)


def test_prewarm_regular_ladder_covers_merged_shapes():
    """After a run that compiled base regular buckets, the ladder prewarm
    must add the {2x..16x} siblings the coalescer can produce (ring-
    capped), so a wire-stalled timed run never compiles mid-flight."""
    from windflow_tpu.ops import resident as R
    spec = WindowSpec(16, 4, WinType.CB)
    batches = cb_stream(4, 4000, chunk=2048, seed=11)
    nat = make_native(spec, Reducer("sum"), batch_len=1 << 20,
                      flush_rows=256, overlap=False)
    run_core(nat, batches)
    base = [k for k in R._STEP_CACHE if k[0] == "reg"]
    assert base, "no regular buckets compiled"
    n = R.prewarm_regular_ladder()
    assert n > 0
    for key in base:
        _t, op, cap, Rb, KP, C, blk_dt, acc_dt, slide = key
        for m in (2, 4, 8, 16):
            if Rb * m > cap or (KP // 2 + 1) * Rb * m > (1 << 24):
                continue
            sk = ("reg", op, cap, Rb * m, KP, C * m, blk_dt, acc_dt, slide)
            assert sk in R._STEP_CACHE, f"ladder sibling missing: {sk}"
    # idempotent: a second call has nothing left to do
    assert R.prewarm_regular_ladder() == 0


def test_native_multistat_pos_max_split():
    """r3: a MultiReducer with one device-worthy stat rides the native
    core — counts from window lengths, MAX(position) from the C++
    archive's per-window last row (hpmax), sum shipped — and matches the
    host core field-for-field on both TB and CB windows."""
    from windflow_tpu.ops.functions import MultiReducer

    def agg():
        return MultiReducer(("count", None, "n"), ("max", "ts", "hi"),
                            ("sum", "value", "sm"))

    # TB: position field is ts -> max(ts) is the pos-max part
    spec = WindowSpec(50, 50, WinType.TB)
    rng = np.random.default_rng(17)
    nk, per = 3, 400
    batches = []
    for lo in range(0, per, 61):
        m = min(61, per - lo)
        batches.append(batch_from_columns(
            SCHEMA, key=np.tile(np.arange(nk), m),
            id=np.repeat(np.arange(lo, lo + m), nk),
            ts=np.repeat(np.arange(lo, lo + m) * 7, nk),
            value=rng.integers(-50, 100, size=m * nk).astype(np.int64)))
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        core = make_core_for(spec, agg(), batch_len=32, flush_rows=100)
    assert isinstance(core, NativeResidentCore)
    assert [p.out_field for p in core._pos_max_parts] == ["hi"]
    host = run_core(WinSeqCore(spec, agg()), batches)
    got = run_core(core, batches)
    assert len(host) == len(got)
    for f in ("key", "id", "ts", "n", "hi", "sm"):
        np.testing.assert_array_equal(host[f], got[f], err_msg=f)

    # CB sliding: regular-descriptor launches must carry hpmax too
    spec = WindowSpec(16, 4, WinType.CB)
    cb_agg = MultiReducer(("count", None, "n"), ("max", "id", "hi"),
                          ("sum", "value", "sm"))
    batches = cb_stream(4, 900, chunk=128, seed=23)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        core = make_core_for(spec, cb_agg, batch_len=1 << 20,
                             flush_rows=200)
    assert isinstance(core, NativeResidentCore)
    host = run_core(WinSeqCore(spec, MultiReducer(
        ("count", None, "n"), ("max", "id", "hi"),
        ("sum", "value", "sm"))), batches)
    got = run_core(core, batches)
    assert len(host) == len(got)
    for f in ("key", "id", "ts", "n", "hi", "sm"):
        np.testing.assert_array_equal(host[f], got[f], err_msg=f)


def test_native_irregular_coalescing_tb_matches_host():
    """TB launches carry explicit window descriptors; under a stalled
    wire they must merge on those descriptors (r3: coalescing is no
    longer regular-only) with results identical to the host core."""
    rng = np.random.default_rng(41)
    nk, per = 3, 6000
    ts_all = np.sort(rng.integers(0, 3000, size=per))
    batches = []
    for lo in range(0, per, 512):
        m = min(512, per - lo)
        batches.append(batch_from_columns(
            SCHEMA, key=np.tile(np.arange(nk), m),
            id=np.repeat(np.arange(lo, lo + m), nk),
            ts=np.repeat(ts_all[lo:lo + m], nk),
            value=rng.integers(0, 100, size=m * nk).astype(np.int64)))
    spec = WindowSpec(20, 5, WinType.TB)
    host = run_core(WinSeqCore(spec, Reducer("sum")), batches)
    nat = make_native(spec, Reducer("sum"), batch_len=1 << 20,
                      flush_rows=128, overlap=False)
    for ex in nat.executors:
        ex.mean_service_s = lambda: 1.0   # pretend stall: full ladder
    merges = []
    real = nat._lib

    class _Shim:
        def __getattr__(self, name):
            if name != "wf_launch_coalesce":
                return getattr(real, name)

            def counting(h, cells, mx, mult):
                n = real.wf_launch_coalesce(h, cells, mx, mult)
                merges.append(n)
                return n
            return counting

    nat._lib = _Shim()
    got = run_core(nat, batches)
    assert_equal_results(host, got)
    assert sum(merges) > 0, "TB (irregular) launches never merged"


def test_proactive_flush_sizing_is_opt_in(monkeypatch):
    """r4: proactive flush sizing engages ONLY under WF_PROACTIVE (the
    interleaved A/B measured it losing on the dev tunnel, BASELINE.md),
    seeds its multiple from the process-global weather EMA, and '0'
    means off."""
    from windflow_tpu.ops import resident as res
    from windflow_tpu.patterns.native_core import (NativeResidentCore,
                                                   _pick_flush_mult)

    spec = WindowSpec(16, 4, WinType.CB)
    saved = dict(res._WEATHER)
    try:
        res._WEATHER["ema_ms"] = 500.0          # deep-stall weather
        # rule boundaries
        for ms, want in [(None, 1), (30, 1), (31, 2), (120, 4), (241, 16)]:
            assert _pick_flush_mult(ms) == want, (ms, want)

        def mk():
            return make_native(spec, Reducer("sum"), batch_len=64,
                               flush_rows=256, overlap=False)

        monkeypatch.delenv("WF_PROACTIVE", raising=False)
        assert mk()._flush_mult == 1            # default: off
        monkeypatch.setenv("WF_PROACTIVE", "0")
        assert mk()._flush_mult == 1            # '0' means off
        monkeypatch.setenv("WF_PROACTIVE", "1")
        core = mk()
        assert core._flush_mult == _pick_flush_mult(500.0) == 16
        # the sized core still computes correctly, with the stream long
        # enough (3*2000 rows > 256*16) that at least one SIZED natural
        # flush fires mid-stream rather than everything draining at EOS
        batches = cb_stream(3, 2000, chunk=97, seed=5)
        want = run_core(WinSeqCore(spec, Reducer("sum")), batches)
        assert_equal_results(want, run_core(core, batches))
    finally:
        res._WEATHER.clear()
        res._WEATHER.update(saved)


# ------------------------------------------------- multi-field staging (r5)

MF_SCHEMA = Schema(rev=np.int64, amt=np.int64)


def mf_stream(n_keys, per_key, chunk=61, seed=0, amt_lo=-40000,
              amt_hi=40000):
    """Two int64 payload columns with different value ranges (rev fits
    int8, amt needs int16/int32) so per-field wire narrowing is live."""
    rng = np.random.default_rng(seed)
    batches = []
    for lo in range(0, per_key, chunk):
        m = min(chunk, per_key - lo)
        ids = np.repeat(np.arange(lo, lo + m), n_keys)
        keys = np.tile(np.arange(n_keys), m)
        batches.append(batch_from_columns(
            MF_SCHEMA, key=keys, id=ids, ts=ids,
            rev=rng.integers(0, 50, size=m * n_keys).astype(np.int64),
            amt=rng.integers(amt_lo, amt_hi,
                             size=m * n_keys).astype(np.int64)))
    return batches


def mf_agg():
    from windflow_tpu.ops.functions import MultiReducer
    return MultiReducer(("count", None, "n"), ("max", "id", "hi"),
                        ("sum", "rev", "rsum"), ("min", "amt", "alo"),
                        ("max", "amt", "ahi"))


def assert_mf_equal(host, got, fields=("key", "id", "ts", "n", "hi",
                                       "rsum", "alo", "ahi")):
    assert len(host) == len(got)
    for f in fields:
        np.testing.assert_array_equal(host[f], got[f], err_msg=f)


@pytest.mark.parametrize("win,slide,wt", [
    (16, 4, WinType.CB), (24, 24, WinType.CB), (50, 25, WinType.TB)])
def test_native_multifield_matches_host(win, slide, wt):
    """r5: a MultiReducer with >1 device-worthy stat over 2 fields stages
    per-field columns through the C++ core (wf_core_set_fields /
    wf_cores_process_mt_f) into per-field device rings and matches the
    host core field-for-field — counts and MAX(position) still answered
    host-side, the two payload columns narrowed independently."""
    spec = WindowSpec(win, slide, wt)
    if wt is WinType.TB:
        rng = np.random.default_rng(5)
        nk, per = 3, 420
        batches = []
        for lo in range(0, per, 71):
            m = min(71, per - lo)
            batches.append(batch_from_columns(
                MF_SCHEMA, key=np.tile(np.arange(nk), m),
                id=np.repeat(np.arange(lo, lo + m), nk),
                ts=np.repeat(np.arange(lo, lo + m) * 7, nk),
                rev=rng.integers(0, 50, size=m * nk).astype(np.int64),
                amt=rng.integers(-9000, 9000,
                                 size=m * nk).astype(np.int64)))
    else:
        batches = mf_stream(4, 700, seed=win + slide)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        core = make_core_for(spec, mf_agg(), batch_len=64, flush_rows=150)
    assert isinstance(core, NativeResidentCore)
    # CB: max(id) is the position stat (host-free) -> 2 staged fields;
    # TB: position is ts, so id ships as a THIRD staged field
    want_fields = (("rev", "amt") if wt is WinType.CB
                   else ("id", "rev", "amt"))
    assert core._multi and core._ship_fields == want_fields
    host = run_core(WinSeqCore(spec, mf_agg()), batches)
    assert_mf_equal(host, run_core(core, batches))


def test_native_multifield_single_field_multi_op():
    """Two ops over ONE field also take the native multi path (one ring,
    two stat evaluations per dispatch)."""
    from windflow_tpu.ops.functions import MultiReducer
    agg = MultiReducer(("sum", "value", "sm"), ("max", "value", "mx"))
    spec = WindowSpec(16, 4, WinType.CB)
    batches = cb_stream(5, 600, seed=11)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        core = make_core_for(spec, agg, batch_len=64, flush_rows=150)
    assert isinstance(core, NativeResidentCore)
    assert core._multi and core._ship_fields == ("value",)
    host = run_core(WinSeqCore(spec, MultiReducer(
        ("sum", "value", "sm"), ("max", "value", "mx"))), batches)
    assert len(host) == len(core_out := run_core(core, batches))
    for f in ("key", "id", "ts", "sm", "mx"):
        np.testing.assert_array_equal(host[f], core_out[f], err_msg=f)


def test_native_multifield_per_field_wire_narrowing():
    """The C ABI narrows each staged column independently: rev in [0,50)
    ships int8 while amt spans int16 — asserted straight off
    wf_launch_peek_wires on a hand-driven core."""
    import ctypes

    from windflow_tpu import native as nat
    lib = nat.load()
    if lib is None:
        pytest.skip("native library unavailable")
    h = lib.wf_core_new(8, 8, 0, 0, 0, 1, 8, 0, 1, 8, 0, 1, 8,
                        1 << 20, 64, 2)
    try:
        mw = (ctypes.c_int * 2)(2, 2)
        lib.wf_core_set_fields(h, 2, mw)
        b = batch_from_columns(
            MF_SCHEMA, key=np.zeros(128, dtype=np.int64),
            id=np.arange(128), ts=np.arange(128),
            rev=np.full(128, 7, dtype=np.int64),
            amt=np.full(128, 30000, dtype=np.int64))
        f = b.dtype.fields
        voffs = np.array([f["rev"][1], f["amt"][1]], dtype=np.int64)
        harr = (ctypes.c_void_p * 1)(h)
        lib.wf_cores_process_mt_f(
            harr, 1, b.ctypes.data, len(b), b.dtype.itemsize,
            f["key"][1], f["id"][1], f["ts"][1], f["marker"][1],
            voffs.ctypes.data_as(nat.p_i64))
        assert lib.wf_launch_pending(h) >= 1
        wires = (ctypes.c_int * 2)()
        assert lib.wf_launch_peek_wires(h, wires) == 1
        assert list(wires) == [0, 1], "rev int8 wire, amt int16 wire"
    finally:
        lib.wf_core_free(h)


def test_native_multifield_coalescing_matches_host():
    """Queued multi-field launches merge per field (each at its own
    widened wire dtype) and stay exact: tiny flush_rows force a deep
    queue, chunks alternate narrow/wide amt ranges so the merged columns
    must widen."""
    spec = WindowSpec(16, 4, WinType.CB)
    rng = np.random.default_rng(13)
    batches = []
    for c, (lo, hi) in enumerate([(-5, 5), (-30000, 30000)] * 3):
        m = 300
        ids = np.repeat(np.arange(c * m, (c + 1) * m), 3)
        keys = np.tile(np.arange(3), m)
        batches.append(batch_from_columns(
            MF_SCHEMA, key=keys, id=ids, ts=ids,
            rev=rng.integers(0, 40, size=m * 3).astype(np.int64),
            amt=rng.integers(lo, hi, size=m * 3).astype(np.int64)))
    host = run_core(WinSeqCore(spec, mf_agg()), batches)
    nat = make_native(spec, mf_agg(), batch_len=1 << 20, flush_rows=96,
                      overlap=False)
    assert nat._multi
    merges = []
    real = nat._lib

    class _Shim:
        def __getattr__(self, name):
            if name != "wf_launch_coalesce":
                return getattr(real, name)

            def counting(h, cells, mx, mult):
                n = real.wf_launch_coalesce(h, cells, mx, mult)
                merges.append(n)
                return n
            return counting

    nat._lib = _Shim()
    assert_mf_equal(host, run_core(nat, batches))
    assert sum(merges) > 0, "multi-field launches never merged"


def test_native_multifield_sharded_and_overlap():
    """Key-sharded MT (wf_cores_process_mt_f two-phase pool) + ship
    threads compose with multi-field staging."""
    spec = WindowSpec(12, 3, WinType.CB)
    batches = mf_stream(7, 500, chunk=83, seed=29)
    host = run_core(WinSeqCore(spec, mf_agg()), batches)
    nat = make_native(spec, mf_agg(), batch_len=64, flush_rows=120,
                      shards=2, overlap=True)
    assert nat._multi and nat.shards == 2
    assert_mf_equal(host, run_core(nat, batches))


def test_native_multifield_float_routes_python():
    """Float stats keep the Python resident core (the native ABI ships
    int64 columns); >4 distinct fields too."""
    from windflow_tpu.ops.functions import MultiReducer, Reducer as R
    from windflow_tpu.patterns.win_seq_tpu import ResidentWinSeqCore
    spec = WindowSpec(16, 4, WinType.CB)
    agg = MultiReducer(R("sum", "rev", "rs"),
                       R("min", "amt", "al", dtype=np.float32))
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        core = make_core_for(spec, agg, batch_len=64, flush_rows=150)
    assert isinstance(core, ResidentWinSeqCore)
    agg5 = MultiReducer(*[("max", f"f{i}", f"o{i}") for i in range(5)])
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        core5 = make_core_for(spec, agg5, batch_len=64, flush_rows=150)
    assert isinstance(core5, ResidentWinSeqCore)


def test_native_multifield_falls_back_on_nonint_column():
    """A non-int64 batch column (int32 here) under a staged stat falls
    back to the Python core transparently mid-stream — the native ABI
    ships int64 columns and its schema check is at runtime."""
    schema = Schema(rev=np.int64, amt=np.int32)
    rng = np.random.default_rng(31)
    m, nk = 400, 3
    b = batch_from_columns(
        schema, key=np.tile(np.arange(nk), m),
        id=np.repeat(np.arange(m), nk),
        ts=np.repeat(np.arange(m), nk),
        rev=rng.integers(0, 50, size=m * nk).astype(np.int64),
        amt=rng.integers(-9000, 9000, size=m * nk).astype(np.int32))
    from windflow_tpu.ops.functions import MultiReducer
    agg = MultiReducer(("sum", "rev", "rs"), ("max", "amt", "ah"),
                       dtype=np.int64)
    spec = WindowSpec(16, 4, WinType.CB)
    nat = make_native(spec, agg, batch_len=64, flush_rows=150)
    assert nat._multi
    out = nat.process(b)
    tail = nat.flush()
    assert nat._delegate is not None, "expected fallback to Python core"
    got = np.sort(np.concatenate([o for o in (out, tail) if len(o)]),
                  order=["key", "id"])
    host = run_core(WinSeqCore(spec, MultiReducer(
        ("sum", "rev", "rs"), ("max", "amt", "ah"), dtype=np.int64)), [b])
    assert len(host) == len(got)
    for f in ("key", "id", "rs"):
        np.testing.assert_array_equal(host[f], got[f], err_msg=f)


def test_native_pos_min_split_matches_host():
    """r5: MIN over the position field rides the pos-extrema split — the
    window's FIRST archived row, no column shipped — alongside MAX, on
    both TB (pos=ts) and CB (pos=id) windows, native vs host exact."""
    from windflow_tpu.ops.functions import MultiReducer
    from windflow_tpu.patterns.win_seq_tpu import split_pos_max

    # TB: first/lastUpdate style aggregate; device half = sum(value) only
    spec = WindowSpec(50, 25, WinType.TB)
    agg = MultiReducer(("count", None, "n"), ("min", "ts", "first"),
                       ("max", "ts", "last"), ("sum", "value", "sm"))
    dev, pos = split_pos_max(spec, agg)
    assert [p.field for p in dev] == ["value"]
    assert sorted(p.op for p in pos) == ["max", "min"]
    rng = np.random.default_rng(41)
    nk, per = 3, 400
    batches = []
    for lo in range(0, per, 67):
        m = min(67, per - lo)
        batches.append(batch_from_columns(
            SCHEMA, key=np.tile(np.arange(nk), m),
            id=np.repeat(np.arange(lo, lo + m), nk),
            ts=np.repeat(np.arange(lo, lo + m) * 7 + 3, nk),
            value=rng.integers(-50, 100, size=m * nk).astype(np.int64)))
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        core = make_core_for(spec, agg, batch_len=32, flush_rows=150)
    assert isinstance(core, NativeResidentCore)
    host = run_core(WinSeqCore(spec, agg), batches)
    got = run_core(core, batches)
    assert len(host) == len(got)
    for f in ("key", "id", "ts", "n", "first", "last", "sm"):
        np.testing.assert_array_equal(host[f], got[f], err_msg=f)

    # CB sliding (regular-descriptor launches must carry hpmin too) —
    # and an ENTIRELY host-free aggregate routes to the host core
    from windflow_tpu.core.winseq import WinSeqCore as HostCore
    spec = WindowSpec(16, 4, WinType.CB)
    cb = MultiReducer(("min", "id", "lo"), ("max", "id", "hi"),
                      ("sum", "value", "sm"))
    batches = cb_stream(4, 700, chunk=128, seed=47)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        core = make_core_for(spec, cb, batch_len=1 << 20, flush_rows=200)
    assert isinstance(core, NativeResidentCore)
    host = run_core(HostCore(spec, cb), batches)
    got = run_core(core, batches)
    for f in ("key", "id", "lo", "hi", "sm"):
        np.testing.assert_array_equal(host[f], got[f], err_msg=f)
    free = MultiReducer(("count", None, "n"), ("min", "id", "lo"),
                        ("max", "id", "hi"))
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        hostish = make_core_for(spec, free, batch_len=64)
    assert not isinstance(hostish, NativeResidentCore), \
        "fully pos-free aggregate should route to the host core"


def test_posfree_aggregate_forced_device_routes_python():
    """A fully pos-free MultiReducer FORCED onto the device
    (use_resident=True past the host route) needs the Python core's
    ship-the-position-column fallback — the native gate must not claim
    it (review r5: dev_parts empty slipped the vacuous field-count
    clause and raised in NativeResidentCore.__init__)."""
    from windflow_tpu.ops.functions import MultiReducer
    from windflow_tpu.patterns.win_seq_tpu import ResidentWinSeqCore
    free = MultiReducer(("count", None, "n"), ("min", "id", "lo"),
                        ("max", "id", "hi"))
    spec = WindowSpec(16, 4, WinType.CB)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        core = make_core_for(spec, free, batch_len=64, flush_rows=150,
                             use_resident=True)
    assert isinstance(core, ResidentWinSeqCore)
    batches = cb_stream(3, 300, chunk=71, seed=53)
    host = run_core(WinSeqCore(spec, MultiReducer(
        ("count", None, "n"), ("min", "id", "lo"),
        ("max", "id", "hi"))), batches)
    got = run_core(core, batches)
    assert len(host) == len(got)
    for f in ("key", "id", "n", "lo", "hi"):
        np.testing.assert_array_equal(host[f], got[f], err_msg=f)


def test_native_abi_guards():
    """ABI misuse is a defined error, not UB: the single-field process
    entry on a multi-field core returns -1 (review r5: it previously
    dereferenced the missing offsets), and wf_core_set_fields reports
    the accepted count so callers can refuse a short accept."""
    import ctypes

    from windflow_tpu import native as nat
    lib = nat.load()
    if lib is None:
        pytest.skip("native library unavailable")
    assert int(lib.wf_max_fields()) == 4
    h = lib.wf_core_new(8, 8, 0, 0, 0, 1, 8, 0, 1, 8, 0, 1, 8,
                        1 << 20, 64, 2)
    try:
        mw = (ctypes.c_int * 2)(2, 2)
        assert lib.wf_core_set_fields(h, 2, mw) == 2
        assert lib.wf_core_set_fields(h, 9, None) == 4  # clamped accept
        lib.wf_core_set_fields(h, 2, mw)
        b = batch_from_columns(
            MF_SCHEMA, key=np.zeros(16, dtype=np.int64),
            id=np.arange(16), ts=np.arange(16),
            rev=np.ones(16, dtype=np.int64),
            amt=np.ones(16, dtype=np.int64))
        f = b.dtype.fields
        got = lib.wf_core_process(
            h, b.ctypes.data, len(b), b.dtype.itemsize, f["key"][1],
            f["id"][1], f["ts"][1], f["marker"][1], f["rev"][1])
        assert got == -1, "single-field entry on a 2-field core must refuse"
    finally:
        lib.wf_core_free(h)


# ------------------------------------------------- state ABI (ISSUE 17)

def _abi_source_constant():
    import os
    import re
    src = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "native", "wf_native.cpp")
    with open(src) as f:
        m = re.search(r"kStateAbiVersion\s*=\s*(\d+)", f.read())
    assert m, "kStateAbiVersion constant missing from wf_native.cpp"
    return int(m.group(1))


def test_abi_version_matches_source():
    """The loaded .so's wf_abi_version() equals the kStateAbiVersion
    constant in wf_native.cpp — a forgotten rebuild after an ABI bump
    would silently import incompatible blobs otherwise."""
    lib = native.load()
    assert getattr(lib, "wf_has_state_abi", False), (
        "the built library must export the state ABI")
    assert int(lib.wf_abi_version()) == _abi_source_constant()


def test_bind_tolerates_pre_abi_library(monkeypatch):
    """_bind over a library missing the state symbols (a stale .so from
    before this ABI) must succeed with wf_has_state_abi=False instead of
    raising — default paths keep the old library serviceable."""
    _STATE_SYMS = {
        "wf_abi_version", "wf_core_state_size", "wf_core_state_export",
        "wf_core_state_import", "wf_core_key_count", "wf_core_key_list",
        "wf_core_key_state_size", "wf_core_key_export",
        "wf_core_key_import", "wf_core_key_neutralize"}

    class _Fn:
        restype = None
        argtypes = None

    class _OldLib:
        def __getattr__(self, name):
            if name in _STATE_SYMS:
                raise AttributeError(name)
            fn = _Fn()
            self.__dict__[name] = fn
            return fn

    # _bind assigns the module-global _lib; snapshot + restore it
    monkeypatch.setattr(native, "_lib", native._lib)
    lib = native._bind(_OldLib())
    assert lib.wf_has_state_abi is False
    assert lib.wf_has_overload_queue is True


def _dense_stream(n_batches=12, rows=40, n_keys=5, seed=3):
    """Per-key dense ids / monotone ts (the pristine-source contract)."""
    rng = np.random.default_rng(seed)
    ctr = {}
    out = []
    for _ in range(n_batches):
        b = np.zeros(rows, dtype=SCHEMA.dtype())
        keys = rng.integers(0, n_keys, rows)
        b["key"] = keys
        b["value"] = rng.integers(-50, 100, rows)
        for i, k in enumerate(keys.tolist()):
            b["id"][i] = ctr.get(k, 0)
            ctr[k] = ctr.get(k, 0) + 1
        b["ts"] = b["id"]
        out.append(b)
    return out


@pytest.mark.parametrize("shards", [1, 2, 3])
def test_native_state_roundtrip_byte_identical(shards):
    """Crash differential at the core level: run A drains + snapshots at
    a barrier and continues; run B snapshots the same barrier, then a
    FRESH core restores the blob and replays the tail.  Emission streams
    must be byte-identical, batch boundaries included."""
    spec = WindowSpec(8, 4, WinType.CB)
    batches = _dense_stream()
    cut = 6

    def fresh():
        return make_native(spec, Reducer("sum", "value"), batch_len=32,
                           flush_rows=64, shards=shards,
                           overlap=(shards > 1))

    def run(core, bs):
        out = []
        for b in bs:
            out.extend(core.process_batches(b))
        return out

    a = fresh()
    out_a = run(a, batches[:cut])
    out_a.extend(a.checkpoint_drain_batches())
    a.state_snapshot()
    out_a.extend(run(a, batches[cut:]))
    out_a.extend(a.flush_batches())

    b = fresh()
    out_b = run(b, batches[:cut])
    out_b.extend(b.checkpoint_drain_batches())
    snap = b.state_snapshot()
    r = fresh()                      # the restarted worker
    r.state_restore(snap)
    out_b.extend(run(r, batches[cut:]))
    out_b.extend(r.flush_batches())

    assert [x.tobytes() for x in out_a] == [x.tobytes() for x in out_b]


def test_native_state_export_requires_drain():
    """wf_core_state_export refuses an undrained core: pending rows not
    yet flushed to launches would be silently dropped by the blob."""
    core = make_native(WindowSpec(8, 4, WinType.CB),
                       Reducer("sum", "value"), batch_len=32,
                       flush_rows=1 << 20)
    core.process(_dense_stream(n_batches=1)[0])
    with pytest.raises(RuntimeError, match="not drained"):
        core.state_snapshot()
    core.checkpoint_drain_batches()
    core.state_snapshot()            # drained now: export succeeds


def _per_key(rows):
    d = {}
    for r in rows:
        d.setdefault(int(r["key"]), []).append(
            (int(r["id"]), int(r["value"])))
    return d


def test_native_keyed_migration_per_key_equal():
    """Key_Farm migration at a barrier: export+neutralize moving keys on
    the old owner, import on the new owner, feed the tail to the new
    owner — merged per-key result sequences equal the single-core
    oracle's."""
    spec = WindowSpec(8, 4, WinType.CB)
    batches = _dense_stream(n_keys=4)
    cut = 6
    reducer = Reducer("sum", "value")

    oracle = make_native(spec, reducer, batch_len=32, flush_rows=64)
    want = []
    for b in batches:
        want.extend(oracle.process_batches(b))
    want.extend(oracle.flush_batches())
    want = _per_key(np.concatenate([x for x in want if len(x)]))

    w0 = make_native(spec, reducer, batch_len=32, flush_rows=64)
    w1 = make_native(spec, reducer, batch_len=32, flush_rows=64)
    owner = {0: w0, 1: w0, 2: w1, 3: w1}   # pre-cut routing
    got = []

    def feed(b):
        for w in (w0, w1):
            mask = np.isin(b["key"], [k for k, o in owner.items()
                                      if o is w])
            got.extend(w.process_batches(b[mask]))

    for b in batches[:cut]:
        feed(b)
    # the barrier: both drained, keys 0/1 migrate w0 -> w1
    got.extend(w0.checkpoint_drain_batches())
    got.extend(w1.checkpoint_drain_batches())
    assert sorted(w0.keyed_state_keys()) == [0, 1]
    frag = w0.keyed_state_export([0, 1])
    assert frag["kind"] == "native_keys"
    w1.keyed_state_import(frag)
    assert list(w0.keyed_state_keys()) == []   # neutralized on export
    owner[0] = owner[1] = w1
    for b in batches[cut:]:
        feed(b)
    got.extend(w0.flush_batches())
    got.extend(w1.flush_batches())
    got = _per_key(np.concatenate([x for x in got if len(x)]))
    assert got == want


def test_native_stale_so_core_declines_loudly():
    """A core bound against a pre-ABI library (simulated by the flags
    _bind would have left) declines snapshots and migration with
    SnapshotUnsupported while default execution is unchanged."""
    from windflow_tpu.runtime.node import SnapshotUnsupported
    spec = WindowSpec(8, 4, WinType.CB)
    batches = _dense_stream()
    core = make_native(spec, Reducer("sum", "value"), batch_len=32,
                       flush_rows=64)
    core.has_state_abi = False
    core.keyed_migratable = False
    for what in (core.state_snapshot, core.keyed_state_keys,
                 lambda: core.keyed_state_export([0]),
                 lambda: core.keyed_state_import({"kind": "native_keys"}),
                 lambda: core.state_restore({"kind": "native"})):
        with pytest.raises(SnapshotUnsupported, match="state ABI"):
            what()
    host = run_core(WinSeqCore(spec, Reducer("sum", "value")), batches)
    assert_equal_results(host, run_core(core, batches))
