"""Minimally-fixed twin of ``tests/plane_corpus.py``: the same 2-host
deployment with every planted defect repaired — one shared hardened-
style wire on both ends, resume on both ends, matching row dtypes, a
supervised host with a ckpt_sink replica target, and exactly one
telemetry aggregator.  ``scripts/wf_lint.py --plane`` over this module
must report ZERO diagnostics.
"""

from windflow_tpu.check.plane import HostSpec, PlaneSpec
from windflow_tpu.parallel.channel import WireConfig
from windflow_tpu.parallel.plane import PlanePolicy

#: one wire bundle for the whole plane: heartbeat under the stall
#: timeout, journaling paired with receiver epoch tracking
_WIRE = WireConfig(connect_deadline=30.0, heartbeat=2.0,
                   stall_timeout=10.0, resume=True, recovery=True)

_HOSTS = [
    HostSpec(0, sends="<i8", resume=True, plane=PlanePolicy(wire=_WIRE),
             federate=True),
    HostSpec(1, sends="<i8", resume=True, ckpt_sink=True, federate=True,
             aggregator=True),
]

SPEC = PlaneSpec({0: ("10.0.0.1", 9000), 1: ("10.0.0.2", 9000)},
                 _HOSTS, name="plane_corpus_fixed", wire=_WIRE)


def wf_plane_spec():
    return [SPEC]
