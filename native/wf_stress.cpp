// Seeded sanitizer stress corpus for the native runtime (ISSUE 20).
//
// TSan cannot be dlopen'd into an uninstrumented CPython, so the
// sanitizer lane links wf_native.cpp INTO this standalone driver
// (native/Makefile `tsan` / `asan` targets) instead of loading
// libwfnative.so.  scripts/wf_sanitize.py builds and runs it; any
// sanitizer report or stress assertion fails the lane.
//
// Three phases per seeded case:
//
//   1. queue MPMC   — producers mixing push / try_push / push_timed
//                     against consumers mixing pop / try_pop, closed
//                     mid-stream; conservation of count and payload sum
//                     is asserted after the drain.
//   2. close race   — producers parked on a FULL queue while close()
//                     fires, then wf_queue_free's idle-spin teardown
//                     (the documented destructor race, under TSan).
//   3. state ABI    — per-thread cores exercising the PR 17 surface
//                     (wf_core_state_export/import, per-key export /
//                     import / neutralize, and the refusal codes) while
//                     a background thread hammers an unrelated queue —
//                     any accidental shared global between the
//                     subsystems becomes a TSan report.
//
//   ./wf_stress_tsan --seed 1 --n 4

#include <atomic>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <thread>
#include <vector>

using i64 = int64_t;
using u64 = uint64_t;
using u8 = uint8_t;

extern "C" {
void *wf_queue_new(i64 capacity);
void wf_queue_free(void *h);
int wf_queue_push(void *h, i64 src, i64 slot);
int wf_queue_pop(void *h, i64 *src, i64 *slot);
int wf_queue_try_push(void *h, i64 src, i64 slot);
int wf_queue_push_timed(void *h, i64 src, i64 slot, i64 timeout_ms);
int wf_queue_try_pop(void *h, i64 *src, i64 *slot);
void wf_queue_close(void *h);

void *wf_core_new(i64 win, i64 slide, int win_type, int role,
                  i64 id_outer, i64 n_outer, i64 slide_outer,
                  i64 id_inner, i64 n_inner, i64 slide_inner,
                  i64 map_idx0, i64 map_idx1, i64 result_ts_slide,
                  i64 batch_len, i64 flush_rows, int max_wire);
void wf_core_free(void *h);
i64 wf_core_process(void *h, const void *base, i64 n, i64 itemsize,
                    i64 o_key, i64 o_id, i64 o_ts, i64 o_marker,
                    i64 o_val);
i64 wf_core_force_flush(void *h);
int wf_launch_peek(void *h, i64 *K, i64 *R, i64 *B, int *wire, int *rebase,
                   i64 *KP, i64 *cap);
void wf_launch_take(void *h, void *blk, i64 *offs, int32_t *wrows,
                    int32_t *wstarts, int32_t *wlens, i64 *hkey, i64 *hid,
                    i64 *hts, i64 *hlen);
i64 wf_core_state_size(void *h);
i64 wf_core_state_export(void *h, void *buf, i64 cap);
i64 wf_core_state_import(void *h, const void *buf, i64 nbytes);
i64 wf_core_key_count(void *h);
i64 wf_core_key_list(void *h, i64 *out, i64 cap);
i64 wf_core_key_state_size(void *h, i64 key);
i64 wf_core_key_export(void *h, i64 key, void *buf, i64 cap);
i64 wf_core_key_import(void *h, const void *buf, i64 nbytes);
i64 wf_core_key_neutralize(void *h, i64 key);
}

#if defined(__SANITIZE_THREAD__)
// gcc-10's libstdc++ implements condition_variable::wait_for via
// pthread_cond_clockwait (glibc 2.30+), which this toolchain's libtsan
// predates: the missing interceptor makes TSan blind to the unlock /
// relock inside the wait, producing bogus "double lock" and data-race
// reports on every timed wait (NativeQueue::push_timed).  Routing the
// call through the intercepted pthread_cond_timedwait keeps the lock
// modeling intact; the clock conversion below is racy by a scheduling
// quantum, which only stretches a stress timeout, never correctness.
#include <pthread.h>
#include <time.h>
extern "C" int pthread_cond_clockwait(pthread_cond_t *cond,
                                      pthread_mutex_t *mu,
                                      clockid_t clockid,
                                      const struct timespec *abstime) {
    struct timespec rt = *abstime;
    if (clockid != CLOCK_REALTIME) {
        struct timespec now_c, now_rt;
        clock_gettime(clockid, &now_c);
        clock_gettime(CLOCK_REALTIME, &now_rt);
        long long ns =
            (long long)(abstime->tv_sec - now_c.tv_sec) * 1000000000LL +
            (abstime->tv_nsec - now_c.tv_nsec);
        if (ns < 0) ns = 0;
        long long t =
            (long long)now_rt.tv_sec * 1000000000LL + now_rt.tv_nsec + ns;
        rt.tv_sec = (time_t)(t / 1000000000LL);
        rt.tv_nsec = (long)(t % 1000000000LL);
    }
    return pthread_cond_timedwait(cond, mu, &rt);
}
#endif

#define CHECK(cond, ...)                                                   \
    do {                                                                   \
        if (!(cond)) {                                                     \
            std::fprintf(stderr, "wf_stress FAILED %s:%d: %s — ",          \
                         __FILE__, __LINE__, #cond);                       \
            std::fprintf(stderr, __VA_ARGS__);                             \
            std::fprintf(stderr, "\n");                                    \
            std::exit(1);                                                  \
        }                                                                  \
    } while (0)

// splitmix-style seeded generator: deterministic per (seed, stream)
struct Rng {
    u64 s;
    explicit Rng(u64 seed) : s(seed * 0x9e3779b97f4a7c15ULL + 1) {}
    u64 next() {
        u64 z = (s += 0x9e3779b97f4a7c15ULL);
        z = (z ^ (z >> 30)) * 0xbf58476d1ce4e5b9ULL;
        z = (z ^ (z >> 27)) * 0x94d049bb133111ebULL;
        return z ^ (z >> 31);
    }
    i64 range(i64 lo, i64 hi) {  // [lo, hi)
        return lo + (i64)(next() % (u64)(hi - lo));
    }
};

// ------------------------------------------------------ phase 1: queue

static void queue_mpmc_case(u64 seed, int round) {
    Rng cfg(seed ^ (u64)(round * 1315423911ULL));
    const i64 cap = cfg.range(2, 64);
    const int n_prod = (int)cfg.range(2, 5);
    const int n_cons = (int)cfg.range(2, 5);
    const i64 per_prod = cfg.range(200, 1200);
    void *q = wf_queue_new(cap);

    std::atomic<i64> pushed{0}, push_sum{0};
    std::vector<std::thread> prods, cons;
    for (int p = 0; p < n_prod; ++p) {
        prods.emplace_back([&, p] {
            Rng r(seed + 7919 * (u64)(p + 1));
            for (i64 i = 0; i < per_prod; ++i) {
                const i64 slot = r.range(0, 1 << 20);
                int rc;
                switch (r.range(0, 3)) {
                case 0: rc = wf_queue_push(q, p, slot); break;
                case 1:
                    // spin try_push until accepted (1 = would block)
                    do {
                        rc = wf_queue_try_push(q, p, slot);
                    } while (rc == 1);
                    break;
                default:
                    do {
                        rc = wf_queue_push_timed(q, p, slot, 5);
                    } while (rc == 1);
                }
                CHECK(rc == 0 || rc == -1, "push rc=%d", rc);
                if (rc == -1) return;  // closed under us: stop producing
                pushed.fetch_add(1, std::memory_order_relaxed);
                push_sum.fetch_add(slot, std::memory_order_relaxed);
            }
        });
    }
    std::atomic<i64> popped{0}, pop_sum{0};
    for (int cix = 0; cix < n_cons; ++cix) {
        cons.emplace_back([&, cix] {
            Rng r(seed + 104729 * (u64)(cix + 1));
            i64 src, slot;
            for (;;) {
                int rc;
                if (r.range(0, 2) == 0) {
                    do {
                        rc = wf_queue_try_pop(q, &src, &slot);
                    } while (rc == 1);
                } else {
                    rc = wf_queue_pop(q, &src, &slot);
                }
                if (rc == -1) return;  // closed and drained
                CHECK(rc == 0, "pop rc=%d", rc);
                CHECK(src >= 0 && src < n_prod, "src=%lld",
                      (long long)src);
                popped.fetch_add(1, std::memory_order_relaxed);
                pop_sum.fetch_add(slot, std::memory_order_relaxed);
            }
        });
    }
    for (auto &t : prods) t.join();
    wf_queue_close(q);  // wakes the consumers once the buffer drains
    for (auto &t : cons) t.join();
    CHECK(popped.load() == pushed.load(),
          "conservation: pushed=%lld popped=%lld",
          (long long)pushed.load(), (long long)popped.load());
    CHECK(pop_sum.load() == push_sum.load(),
          "payload sum diverged (dup or corruption)");
    wf_queue_free(q);
}

static void queue_close_race_case(u64 seed) {
    // producers parked on a FULL queue when close() lands: every parked
    // push must return -1 (closed), then the idle-spin free() tears the
    // mutex down only after the last waiter left
    Rng cfg(seed);
    const i64 cap = cfg.range(1, 4);
    void *q = wf_queue_new(cap);
    for (i64 i = 0; i < cap; ++i)
        CHECK(wf_queue_push(q, 0, i) == 0, "prefill");
    std::vector<std::thread> prods;
    std::atomic<int> woken{0};
    for (int p = 0; p < 4; ++p) {
        prods.emplace_back([&, p] {
            int rc = wf_queue_push(q, 1, p);  // parks: queue is full
            CHECK(rc == -1, "parked push survived close, rc=%d", rc);
            woken.fetch_add(1);
        });
    }
    std::this_thread::sleep_for(std::chrono::milliseconds(20));
    wf_queue_close(q);
    for (auto &t : prods) t.join();
    CHECK(woken.load() == 4, "woken=%d", woken.load());
    wf_queue_free(q);
}

// -------------------------------------------------- phase 3: state ABI

#pragma pack(push, 1)
struct Row {
    i64 key, id, ts;
    u8 marker;
    i64 value;
};
#pragma pack(pop)
static_assert(sizeof(Row) == 33, "packed row layout");

static void *new_core() {
    // the hand-driven config the in-suite native tests use: win 8,
    // slide 8, CB, SEQ role, identity distribution, huge batch_len so
    // nothing flushes, flush_rows 64, int16 wire
    return wf_core_new(8, 8, 0, 0, 0, 1, 8, 0, 1, 8, 0, 1, 8,
                       (i64)1 << 20, 64, 2);
}

static void drain_launches(void *h) {
    // consume every queued launch (the ship thread's role): export
    // refuses while c->queue is non-empty
    i64 K, R, B, KP, cap;
    int wire, rebase;
    while (wf_launch_peek(h, &K, &R, &B, &wire, &rebase, &KP, &cap) == 1) {
        const i64 nb = B > 0 ? B : 1;
        std::vector<u8> blk((size_t)(K * R) << wire);
        std::vector<i64> offs((size_t)K);
        std::vector<i64> h8((size_t)(4 * nb));
        std::vector<int32_t> w4((size_t)(3 * nb));
        wf_launch_take(h, blk.data(), offs.data(), w4.data(),
                       w4.data() + nb, w4.data() + 2 * nb, h8.data(),
                       h8.data() + nb, h8.data() + 2 * nb,
                       h8.data() + 3 * nb);
    }
}

static void feed(void *h, i64 n_keys, i64 rows_per_key, i64 id0) {
    // PARTIAL windows only (rows_per_key + id0 < win 8): no window
    // fires, so the per-key archives stay non-empty and exportable;
    // force_flush + drain_launches then settles pend_rows and the
    // launch queue — the two halves of the core_drained export gate
    std::vector<Row> rows;
    for (i64 k = 0; k < n_keys; ++k)
        for (i64 i = 0; i < rows_per_key; ++i)
            rows.push_back(Row{k, id0 + i, id0 + i, 0, 100 * k + i});
    const i64 got = wf_core_process(
        h, rows.data(), (i64)rows.size(), (i64)sizeof(Row),
        offsetof(Row, key), offsetof(Row, id), offsetof(Row, ts),
        offsetof(Row, marker), offsetof(Row, value));
    CHECK(got >= 0, "process refused: %lld", (long long)got);
    wf_core_force_flush(h);
    drain_launches(h);
}

static void state_abi_case(u64 seed, int tid) {
    Rng r(seed + 31337 * (u64)(tid + 1));
    const i64 n_keys = r.range(2, 9);
    void *a = new_core();
    feed(a, n_keys, r.range(3, 6), 0);
    CHECK(wf_core_key_count(a) == n_keys, "key_count");

    // full-state round trip into a fresh twin
    const i64 sz = wf_core_state_size(a);
    CHECK(sz > 0, "state_size=%lld", (long long)sz);
    std::vector<u8> blob((size_t)sz);
    CHECK(wf_core_state_export(a, blob.data(), sz) == sz, "export");
    void *b = new_core();
    CHECK(wf_core_state_import(b, blob.data(), sz) == 0, "import");
    CHECK(wf_core_state_size(b) == sz, "round-trip size");
    CHECK(wf_core_key_count(b) == n_keys, "imported key_count");
    std::vector<i64> ka((size_t)n_keys), kb((size_t)n_keys);
    CHECK(wf_core_key_list(a, ka.data(), n_keys) == n_keys, "key_list a");
    CHECK(wf_core_key_list(b, kb.data(), n_keys) == n_keys, "key_list b");
    CHECK(std::memcmp(ka.data(), kb.data(),
                      (size_t)n_keys * 8) == 0, "key sets differ");

    // refusals: import into a non-fresh core, then a corrupted magic
    CHECK(wf_core_state_import(b, blob.data(), sz) == -2,
          "non-fresh import must refuse -2");
    std::vector<u8> bad(blob);
    bad[0] ^= 0xff;
    void *fresh = new_core();
    CHECK(wf_core_state_import(fresh, bad.data(), sz) == -3,
          "bad magic must refuse -3");

    // per-key migration: export + neutralize on A, import on C
    const i64 mk = ka[(size_t)r.range(0, n_keys)];
    const i64 ksz = wf_core_key_state_size(a, mk);
    CHECK(ksz > 0, "key_state_size=%lld", (long long)ksz);
    std::vector<u8> kblob((size_t)ksz);
    CHECK(wf_core_key_export(a, mk, kblob.data(), ksz) == ksz, "kexport");
    CHECK(wf_core_key_neutralize(a, mk) == 0, "neutralize");
    CHECK(wf_core_key_count(a) == n_keys - 1, "count after neutralize");
    CHECK(wf_core_key_state_size(a, mk) == -2,
          "neutralized key must be gone (-2)");
    void *cc = new_core();
    CHECK(wf_core_key_import(cc, kblob.data(), ksz) == 0, "kimport");
    CHECK(wf_core_key_count(cc) == 1, "migrated key_count");
    CHECK(wf_core_key_state_size(cc, mk) == ksz, "migrated key size");

    // the migrated-away key keeps flowing on the NEW owner: tail rows
    // append cleanly to the imported state
    std::vector<Row> tail{Row{mk, 6, 6, 0, 7}};
    CHECK(wf_core_process(cc, tail.data(), 1, (i64)sizeof(Row),
                          offsetof(Row, key), offsetof(Row, id),
                          offsetof(Row, ts), offsetof(Row, marker),
                          offsetof(Row, value)) >= 0, "tail process");

    wf_core_free(a);
    wf_core_free(b);
    wf_core_free(fresh);
    wf_core_free(cc);
}

static void state_abi_phase(u64 seed) {
    // ABI work on per-thread cores while a background thread hammers an
    // unrelated queue: a TSan report here means the two subsystems
    // share state they must not
    void *q = wf_queue_new(8);
    std::atomic<bool> stop{false};
    std::thread noise([&] {
        i64 src, slot, i = 0;
        while (!stop.load(std::memory_order_relaxed)) {
            if (wf_queue_try_push(q, 0, i++) == 0)
                wf_queue_try_pop(q, &src, &slot);
        }
    });
    std::vector<std::thread> workers;
    for (int t = 0; t < 3; ++t)
        workers.emplace_back([=] { state_abi_case(seed, t); });
    for (auto &t : workers) t.join();
    stop.store(true);
    noise.join();
    wf_queue_close(q);
    wf_queue_free(q);
}

int main(int argc, char **argv) {
    u64 seed = 1;
    int n = 4;
    for (int i = 1; i < argc - 1; ++i) {
        if (!std::strcmp(argv[i], "--seed"))
            seed = (u64)std::strtoull(argv[i + 1], nullptr, 10);
        if (!std::strcmp(argv[i], "--n"))
            n = (int)std::strtol(argv[i + 1], nullptr, 10);
    }
    for (int c = 0; c < n; ++c) {
        const u64 cs = seed + (u64)c * 1000003ULL;
        queue_mpmc_case(cs, c);
        queue_close_race_case(cs);
        state_abi_phase(cs);
        std::printf("wf_stress: case %d/%d ok (seed=%llu)\n", c + 1, n,
                    (unsigned long long)cs);
        std::fflush(stdout);
    }
    std::printf("wf_stress: OK (seed=%llu cases=%d)\n",
                (unsigned long long)seed, n);
    return 0;
}
