// windflow-tpu native host runtime: the window-core hot loop in C++.
//
// The reference library's entire hot path is C++ (win_seq.hpp:268-474 runs
// per tuple on a pinned thread).  This translation unit is its counterpart
// for the TPU framework: the per-row window bookkeeping — out-of-order
// drops, per-key archives, window creation/firing arithmetic, PLQ/MAP
// result renumbering, result-timestamp rules, EOS marker handling
// (win_seq.hpp:268-474, window.hpp:63-87, basic.hpp:136) — plus the
// device-staging assembly of the resident-archive path (ops/resident.py):
// narrow-dtype append rectangles, per-key ring offsets, fired-window
// descriptors in ring coordinates, and ring rebase decisions.
//
// Semantics are kept bit-identical to the Python cores (core/winseq.py,
// patterns/win_seq_tpu.py:ResidentWinSeqCore); tests/test_native.py asserts
// the differential.  Python calls in through a plain C ABI via ctypes, so
// every call releases the GIL — farm workers get true multicore host
// parallelism, like the reference's FastFlow pinned threads.
//
// Build: `make -C native` -> libwfnative.so (loaded by windflow_tpu/native).

#include <algorithm>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <functional>
#include <mutex>
#include <thread>
#include <unordered_map>
#include <vector>

using i64 = long long;
using u8 = unsigned char;

static const i64 NEG_INF = -(1LL << 62);

// deepest buddy-coalescing multiplicity the ring is provisioned for (the
// shape ladder stays {1x, 2x, 4x, 8x, 16x} — powers of two, so merged
// dispatches land on a small, warmup-coverable set of compile buckets)
static const i64 kCoalesceLadderMax = 16;
// absolute ring budget (KP * cap cells): 2^25 int32 cells = 128 MB of
// HBM per core — deep-merge provisioning backs off before exceeding it
static const i64 kMaxRingCells = 1LL << 25;
// multi-field staging bound: a core stages up to this many int64 payload
// columns per row (one device ring per field — ops/resident.py
// MultiFieldResidentExecutor); richer aggregates fall back to the Python
// core.  4 covers every tracked workload (YSB --rich-stats ships 2).
static const int kMaxFields = 4;

static inline i64 bucket(i64 n, i64 lo = 8) {
    i64 b = lo;
    while (b < n) b *= 2;
    return b;
}

static inline i64 pymod(i64 a, i64 m) {  // Python's nonnegative modulo
    i64 r = a % m;
    return r < 0 ? r + m : r;
}

// splitmix64 — the shard hash must not correlate with the farm routing
// modulus (default_routing is key % n_workers, so a keyed-farm worker sees
// only keys congruent mod n; sharding by key % S again would collapse
// every row onto one shard)
static inline unsigned long long mix64(unsigned long long x) {
    x += 0x9e3779b97f4a7c15ULL;
    x = (x ^ (x >> 30)) * 0xbf58476d1ce4e5b9ULL;
    x = (x ^ (x >> 27)) * 0x94d049bb133111ebULL;
    return x ^ (x >> 31);
}

namespace {

enum Role { SEQ = 0, PLQ = 1, WLQ = 2, MAP = 3, REDUCE = 4 };
enum WinKind { CB = 0, TB = 1 };

// int64 column -> wire-dtype rectangle row (the H2D payload narrowing)
static inline void copy_narrow(u8 *dst, const i64 *src, i64 cnt, int wire) {
    if (wire == 0)
        for (i64 c = 0; c < cnt; ++c) ((int8_t *)dst)[c] = (int8_t)src[c];
    else if (wire == 1)
        for (i64 c = 0; c < cnt; ++c) ((int16_t *)dst)[c] = (int16_t)src[c];
    else if (wire == 2)
        for (i64 c = 0; c < cnt; ++c) ((int32_t *)dst)[c] = (int32_t)src[c];
    else
        std::memcpy(dst, src, (size_t)cnt * 8);
}

struct KeyState {
    // live archive: SoA ordered by pos, purge advances `start`
    // (core/archive.py's KeyArchive, reference stream_archive.hpp)
    std::vector<i64> pos, ts, val;
    // extra payload columns (fields 1..F-1 of a multi-field core);
    // empty on the default single-field cores so per-key memory stays flat
    std::vector<std::vector<i64>> xval;
    size_t start = 0;
    i64 appended = 0;      // rows ever archived (absolute row domain)
    i64 launched = 0;      // rows already shipped to the device ring
    i64 ring_base = 0;     // absolute row index of ring column 0
    i64 last_pos = NEG_INF;
    i64 initial_id = 0, first_gwid = 0;
    i64 next_lwid = 0, n_fired = 0, emit_counter = 0;
    i64 marker_pos = NEG_INF, marker_ts = 0;
    i64 purge_pos = NEG_INF;  // purge deferred to flush (rebase invariant)
    // per-field value range of UNSHIPPED rows, tracked at append time so
    // flush()'s wire-dtype choice needs no re-scan of the pending rows
    i64 pend_vmin[kMaxFields] = {0}, pend_vmax[kMaxFields] = {0};
    bool pend_any = false;
    int row = -1;             // dense ring row
    // key migrated away at a rescale barrier (wf_core_key_neutralize):
    // eos() and the state ABI skip it so the old owner never emits its
    // windows again; a late row for the key clears the flag and the key
    // restarts from fresh state (same as first contact on a new owner).
    // The dense row itself stays registered — queued launches and wrow
    // entries index rows by position, so rows are never renumbered.
    bool neutral = false;

    inline void note_vals(int nf, const i64 *vs) {
        if (!pend_any) {
            for (int f = 0; f < nf; ++f) pend_vmin[f] = pend_vmax[f] = vs[f];
            pend_any = true;
            return;
        }
        for (int f = 0; f < nf; ++f) {
            if (vs[f] < pend_vmin[f]) pend_vmin[f] = vs[f];
            if (vs[f] > pend_vmax[f]) pend_vmax[f] = vs[f];
        }
    }
    // block-range over-approximation, field 0 (the single-field bulk path)
    inline void note_range0(i64 lo, i64 hi) {
        if (!pend_any) {
            pend_vmin[0] = lo;
            pend_vmax[0] = hi;
            pend_any = true;
        } else {
            if (lo < pend_vmin[0]) pend_vmin[0] = lo;
            if (hi > pend_vmax[0]) pend_vmax[0] = hi;
        }
    }
    // hot-loop threshold caches (derived from next_lwid / n_fired; kept
    // in sync at the only sites that mutate them in the streaming path)
    i64 next_create = 0;      // initial_id + next_lwid*slide
    i64 fire_pos = 0;         // initial_id + n_fired*slide + win

    size_t live() const { return pos.size() - start; }

    void purge() {
        if (purge_pos <= NEG_INF) return;
        const i64 *p = pos.data() + start;
        size_t cut = std::lower_bound(p, p + live(), purge_pos) - p;
        start += cut;
        purge_pos = NEG_INF;
        // amortised compaction (archive.py:purge_below)
        if (start > 4096 && start > live()) {
            pos.erase(pos.begin(), pos.begin() + start);
            ts.erase(ts.begin(), ts.begin() + start);
            val.erase(val.begin(), val.begin() + start);
            for (auto &xv : xval)
                xv.erase(xv.begin(), xv.begin() + start);
            start = 0;
        }
    }
};

struct Launch {
    i64 K = 0, R = 0, B = 0, KP = 0, cap = 0;
    int wire = 0;   // 0=int8 1=int16 2=int32 3=int64
    int rebase = 0;
    // regular-descriptor compression: when every key's windows form an
    // arithmetic sequence (start0 + i*slide, constant len — the steady
    // state of CB sliding windows), only (count, start0, len) per key
    // cross the wire and the device expands them with an iota; widx maps
    // each pending window to its index within its key (host-side gather)
    int regular = 0;
    i64 cmax = 0;
    int mult = 1;   // coalescing multiplicity (buddy scheme: 1, 2, 4, ...)
    std::vector<int32_t> rcount, rstart0, rlen, widx;   // K, K, K, B
    std::vector<u8> blk;              // K*R in wire dtype (field 0)
    // fields 1..F-1 of a multi-field core: one rectangle + wire dtype
    // each (field 0 stays in blk/wire so the single-field ABI and every
    // existing consumer are untouched)
    std::vector<std::vector<u8>> xblk;
    int xwire[kMaxFields] = {0};
    std::vector<i64> offs;            // K ring write offsets
    std::vector<int32_t> rows;        // K per-key valid row counts in blk
    std::vector<int32_t> wrows, wstarts, wlens;   // B window descriptors
    std::vector<i64> hkey, hid, hts, hlen;        // B result headers
    std::vector<i64> hpmax;   // B per-window max position (free from the
                              // ordered archive: the window's last row) —
                              // host-side MAX(ts)/MAX(id) for multi-stat
                              // aggregates without shipping the column
    std::vector<i64> hpmin;   // B per-window MIN position: the window's
                              // FIRST row, free by the same ordering —
                              // first-update style stats never ship
};

struct Core {
    i64 win, slide;
    int kind, role;
    i64 id_outer, n_outer, slide_outer, id_inner, n_inner, slide_inner;
    i64 map_idx0, map_idx1, result_ts_slide;
    i64 batch_len, flush_rows;
    int max_wire;   // widest wire dtype: 2=int32 (default), 3=int64
    // multi-field staging (wf_core_set_fields): number of payload columns
    // and each field's widest admissible wire dtype (max_wire_f[0] shadows
    // max_wire so the per-field logic has one source of truth)
    int n_fields = 1;
    int max_wire_f[kMaxFields];
    bool hopping;

    std::unordered_map<i64, int> rowmap;
    std::vector<int> direct;          // fast dense map for small keys
    std::vector<KeyState> keys;       // dense by ring row
    std::vector<i64> rowkey;

    // pending fired windows (absolute row coords; ring coords at flush)
    std::vector<int32_t> wrow;
    std::vector<i64> wlo, wlen, hkey, hid, hts, hpm, hpmn;
    i64 pend_rows = 0;

    i64 KP = 0, cap = 0;              // current ring geometry
    i64 room_mult = 2;                // per-key append room, in launch
                                      // widths (grows on ring-full rebase)
    std::deque<Launch> queue;
    std::mutex qmu;  // producer (process/eos on the node thread) vs
                     // consumer (wf_launch_peek/take on a ship thread)
    i64 launches_made = 0;  // produced-launch counter; only the producer
                            // thread reads/writes it (queue.size() is
                            // not safe to read unlocked)

    Core(i64 win_, i64 slide_, int kind_, int role_,
         i64 io, i64 no, i64 so, i64 ii, i64 ni, i64 si,
         i64 m0, i64 m1, i64 rts, i64 bl, i64 fr, int mw)
        : win(win_), slide(slide_), kind(kind_), role(role_),
          id_outer(io), n_outer(no), slide_outer(so),
          id_inner(ii), n_inner(ni), slide_inner(si),
          map_idx0(m0), map_idx1(m1), result_ts_slide(rts),
          batch_len(bl), flush_rows(fr), max_wire(mw),
          hopping(slide_ > win_), direct(4096, -1) {
        for (int f = 0; f < kMaxFields; ++f) max_wire_f[f] = mw;
    }

    KeyState &state(i64 key) {
        int r;
        if (key >= 0 && key < (i64)direct.size()) {
            r = direct[(size_t)key];
            if (r >= 0) return keys[r];
        } else {
            auto it = rowmap.find(key);
            if (it != rowmap.end()) return keys[it->second];
        }
        r = (int)keys.size();
        if (key >= 0 && key < (i64)direct.size()) direct[(size_t)key] = r;
        else rowmap.emplace(key, r);
        rowkey.push_back(key);
        keys.emplace_back();
        KeyState &st = keys.back();
        st.row = r;
        if (n_fields > 1) st.xval.resize((size_t)(n_fields - 1));
        // farm distribution math (windows.py PatternConfig,
        // reference win_seq.hpp:307-314)
        i64 a = pymod(id_inner - pymod(key, n_inner), n_inner);
        i64 b = pymod(id_outer - pymod(key, n_outer), n_outer);
        st.first_gwid = a * n_outer + b;
        i64 init_outer = b * slide_outer, init_inner = a * slide_inner;
        st.initial_id = (role == WLQ || role == REDUCE)
                            ? init_inner : init_outer + init_inner;
        st.emit_counter = (role == MAP) ? map_idx0 : 0;
        st.next_create = st.initial_id;
        st.fire_pos = st.initial_id + win;
        return st;
    }

    void emit_windows(KeyState &st, i64 key, i64 w_from, i64 w_to, bool eos) {
        const i64 stride = n_outer * n_inner;
        const i64 *p = st.pos.data() + st.start;
        const size_t n = st.live();
        for (i64 w = w_from; w < w_to; ++w) {
            i64 gwid = st.first_gwid + w * stride;
            i64 s_abs = w * slide + st.initial_id;
            i64 e_abs = s_abs + win;
            size_t lo = std::lower_bound(p, p + n, s_abs) - p;
            size_t hi = eos ? n : (std::lower_bound(p, p + n, e_abs) - p);
            // result ts (winseq.py:_result_ts; window.hpp:121-124,154)
            i64 out_ts = 0;
            if (kind == TB) {
                out_ts = gwid * result_ts_slide + win - 1;
            } else {
                size_t idx = std::lower_bound(p, p + n, e_abs) - p;
                if (idx > 0 && p[idx - 1] >= s_abs)
                    out_ts = st.ts[st.start + idx - 1];
            }
            // marker rows overwrite the result ts of windows they fall
            // below — CB only: TB keeps the closed form above
            // (winseq.py:_result_ts returns before the marker clause)
            if (kind != TB && st.marker_pos > NEG_INF
                && st.marker_pos < e_abs)
                out_ts = st.marker_ts;
            // result id incl. PLQ/MAP renumbering (win_seq.hpp:396-405)
            i64 rid;
            if (role == MAP) {
                rid = st.emit_counter;
                st.emit_counter += map_idx1;
            } else if (role == PLQ) {
                i64 ioff = pymod(id_inner - pymod(key, n_inner), n_inner);
                rid = ioff + st.emit_counter * n_inner;
                st.emit_counter += 1;
            } else {
                rid = gwid;
            }
            i64 abs_lo = (st.appended - (i64)n) + (i64)lo;
            wrow.push_back(st.row);
            wlo.push_back(abs_lo);
            wlen.push_back((i64)(hi - lo));
            hkey.push_back(key);
            hid.push_back(rid);
            hts.push_back(out_ts);
            hpm.push_back(hi > lo ? p[hi - 1] : 0);
            hpmn.push_back(hi > lo ? p[lo] : 0);
            if (!eos) st.purge_pos = std::max(st.purge_pos, s_abs);
        }
    }

    void flush() {
        if (hkey.empty() && pend_rows == 0) return;
        const i64 K = (i64)keys.size();
        const i64 KPb = bucket(std::max<i64>(K, 1));
        // a row-triggered FIRST flush marks a throughput stream: provision
        // the full coalescing ladder's ring room up front, so the steady
        // state has no room-growth rebases at all (each one is an
        // unmergeable dispatch; r3 measured ~4 of them costing ~5 extra
        // RTTs on the 16M-row bench).  Force/EOS-triggered first flushes
        // (tiny or latency-bound streams) keep the minimal ring.
        if (cap == 0 && pend_rows >= flush_rows)
            room_mult = kCoalesceLadderMax + 2;
        bool rebase = (cap == 0) || (KP < KPb);
        i64 maxpend = 0;
        for (auto &st : keys)
            maxpend = std::max(maxpend, st.appended - st.launched);
        if (!rebase) {
            const i64 Rb = bucket(std::max<i64>(maxpend, 1));
            for (auto &st : keys) {
                if (st.launched - st.ring_base + Rb > cap) {
                    rebase = true;
                    // the stream keeps outrunning the ring: provision more
                    // append room next time, up to the full coalescing
                    // ladder's worth — steady streams converge on a ring
                    // deep merges fit in, one-shot streams never pay for it
                    room_mult = std::min<i64>(room_mult * 2,
                                              kCoalesceLadderMax + 2);
                    break;
                }
            }
        }
        i64 R;
        if (rebase) {
            i64 maxlive = 0;
            for (auto &st : keys)
                maxlive = std::max(maxlive, (i64)st.live());
            i64 slack =
                std::max<i64>(flush_rows / std::max<i64>(K, 1), 64);
            KP = KPb;
            // ring room for room_mult launch widths per key: try_merge's
            // offset guard (maxoff + bucket(newR) <= cap) can only admit
            // merges the ring has room for, so coalescing depth is capped
            // by this provisioning (r2: the fixed 2*slack stopped the
            // ladder at ~2x).  room_mult grows on ring-full rebases above,
            // bounded by the absolute ring budget.
            // the ring budget is per CORE: a multi-field core allocates
            // one (KP, cap) device ring per field, so each field's share
            // of the cell budget shrinks accordingly
            while (room_mult > 2
                   && KPb * bucket(std::max<i64>(
                          2 * maxlive + room_mult * slack, 16))
                          > kMaxRingCells / n_fields)
                room_mult /= 2;
            cap = bucket(std::max<i64>(2 * maxlive + room_mult * slack, 16));
            R = maxlive;
            for (auto &st : keys) {
                st.ring_base = st.appended - (i64)st.live();
                st.launched = st.ring_base;
            }
        } else {
            R = maxpend;
        }
        // narrowest wire dtype PER FIELD over the rows to ship.  Steady
        // state uses the per-key ranges tracked at append time (no
        // re-scan); a REBASE re-ships every live row — including
        // previously shipped ones outside the pending range — so it must
        // scan the actual ship range or wide old values would truncate
        // into a narrow wire
        bool anyv = false;
        i64 vmin[kMaxFields] = {0}, vmax[kMaxFields] = {0};
        if (rebase) {
            for (auto &st : keys) {
                for (size_t j = st.start; j < st.pos.size(); ++j) {
                    if (!anyv) {
                        vmin[0] = vmax[0] = st.val[j];
                        for (int f = 1; f < n_fields; ++f)
                            vmin[f] = vmax[f] = st.xval[(size_t)(f - 1)][j];
                        anyv = true;
                        continue;
                    }
                    for (int f = 0; f < n_fields; ++f) {
                        const i64 v = f == 0 ? st.val[j]
                                             : st.xval[(size_t)(f - 1)][j];
                        if (v < vmin[f]) vmin[f] = v;
                        if (v > vmax[f]) vmax[f] = v;
                    }
                }
            }
        } else {
            for (auto &st : keys) {
                if (!st.pend_any) continue;
                if (!anyv) {
                    for (int f = 0; f < n_fields; ++f) {
                        vmin[f] = st.pend_vmin[f];
                        vmax[f] = st.pend_vmax[f];
                    }
                    anyv = true;
                } else {
                    for (int f = 0; f < n_fields; ++f) {
                        vmin[f] = std::min(vmin[f], st.pend_vmin[f]);
                        vmax[f] = std::max(vmax[f], st.pend_vmax[f]);
                    }
                }
            }
        }
        Launch L;
        for (int f = 0; f < n_fields; ++f) {
            int w;
            if (!anyv || (vmin[f] >= -128 && vmax[f] <= 127)) w = 0;
            else if (vmin[f] >= -32768 && vmax[f] <= 32767) w = 1;
            else if (max_wire_f[f] <= 2
                     || (vmin[f] >= INT32_MIN && vmax[f] <= INT32_MAX))
                w = 2;
            else w = 3;   // int64 wire (64-bit accumulate dtype)
            L.xwire[f] = w;
        }
        L.wire = L.xwire[0];
        const i64 Rr = std::max<i64>(R, 1);
        L.blk.assign((size_t)(K * Rr) << L.wire, 0);
        if (n_fields > 1) {
            L.xblk.resize((size_t)(n_fields - 1));
            for (int f = 1; f < n_fields; ++f)
                L.xblk[(size_t)(f - 1)].assign(
                    (size_t)(K * Rr) << L.xwire[f], 0);
        }
        L.offs.assign((size_t)K, 0);
        L.rows.assign((size_t)K, 0);
        for (auto &st : keys) {
            i64 live_start = st.appended - (i64)st.live();
            size_t j0 = st.start + (size_t)(st.launched - live_start);
            i64 cnt = (i64)(st.pos.size() - j0);
            L.offs[(size_t)st.row] = st.launched - st.ring_base;
            L.rows[(size_t)st.row] = (int32_t)cnt;
            copy_narrow(L.blk.data() + ((size_t)(st.row * Rr) << L.wire),
                        st.val.data() + j0, cnt, L.wire);
            for (int f = 1; f < n_fields; ++f)
                copy_narrow(L.xblk[(size_t)(f - 1)].data()
                                + ((size_t)(st.row * Rr) << L.xwire[f]),
                            st.xval[(size_t)(f - 1)].data() + j0, cnt,
                            L.xwire[f]);
            st.launched = st.appended;
            st.pend_any = false;
        }
        const i64 B = (i64)hkey.size();
        L.wrows.resize((size_t)B);
        L.wstarts.resize((size_t)B);
        L.wlens.resize((size_t)B);
        L.hlen.resize((size_t)B);
        for (i64 i = 0; i < B; ++i) {
            int rr = wrow[(size_t)i];
            L.wrows[(size_t)i] = rr;
            L.wstarts[(size_t)i] =
                (int32_t)(wlo[(size_t)i] - keys[(size_t)rr].ring_base);
            L.wlens[(size_t)i] = (int32_t)wlen[(size_t)i];
            L.hlen[(size_t)i] = wlen[(size_t)i];
        }
        // regularity detection (one pass): per key, windows must advance
        // by `slide` ring positions with one constant length
        if (B > 0 && kind == CB && !hopping) {
            L.rcount.assign((size_t)K, 0);
            L.rstart0.assign((size_t)K, 0);
            L.rlen.assign((size_t)K, 0);
            L.widx.resize((size_t)B);
            std::vector<int32_t> expect((size_t)K, 0);
            bool ok = true;
            for (i64 i = 0; i < B; ++i) {
                const size_t r = (size_t)L.wrows[(size_t)i];
                if (L.rcount[r] == 0) {
                    L.rstart0[r] = L.wstarts[(size_t)i];
                    L.rlen[r] = L.wlens[(size_t)i];
                    expect[r] = L.wstarts[(size_t)i];
                }
                if (L.wstarts[(size_t)i] != expect[r]
                    || L.wlens[(size_t)i] != L.rlen[r]) {
                    ok = false;
                    break;
                }
                L.widx[(size_t)i] = L.rcount[r]++;
                expect[r] += (int32_t)slide;
            }
            if (ok) {
                L.regular = 1;
                for (i64 r = 0; r < K; ++r)
                    L.cmax = std::max<i64>(L.cmax, L.rcount[(size_t)r]);
            }
        }
        L.hkey = std::move(hkey);
        L.hid = std::move(hid);
        L.hts = std::move(hts);
        L.hpmax = std::move(hpm);
        L.hpmin = std::move(hpmn);
        L.K = K; L.R = Rr; L.B = B; L.KP = KP; L.cap = cap;
        L.rebase = rebase ? 1 : 0;
        {
            std::lock_guard<std::mutex> lk(qmu);
            queue.push_back(std::move(L));
        }
        ++launches_made;
        for (auto &st : keys) st.purge();
        pend_rows = 0;
        wrow.clear(); wlo.clear(); wlen.clear();
        hkey = {}; hid = {}; hts = {}; hpm = {}; hpmn = {};
    }

    // Bulk path for key-PERIODIC in-order chunks — the shape every
    // benchmark generator produces (row i carries key i % P with per-key
    // ids advancing by 1: bench.py make_stream, the sum_test fixtures'
    // tile layout, reference sum_cb.hpp:89-117).  ONE fused pass verifies
    // the pattern row-by-row against cached expectations (key_of[idx],
    // nextpos[idx]) while copying — no state lookup, no threshold
    // compares, no marker branch beyond one byte test; window math runs
    // once per key per block.  Any pattern break rolls the current block
    // back and returns the consumed prefix; the general loop finishes the
    // tail.  Returns rows consumed (0 = chunk head not periodic).
    i64 process_fast(const u8 *base, i64 n, i64 itemsize, i64 o_key,
                     i64 o_id, i64 o_ts, i64 o_marker, i64 o_val) {
        // single-field only: the bulk path's fused verify+copy is the
        // bench hot loop and stays specialized; multi-field streams (none
        // of which are key-periodic in the tracked workloads) take the
        // general loop
        if (kind != CB || hopping || n < 2 || n_fields > 1) return 0;
        i64 key0;
        std::memcpy(&key0, base + o_key, 8);
        i64 P = -1;
        const i64 scan = std::min<i64>(n, 4096);
        for (i64 i = 1; i < scan; ++i) {
            i64 k;
            std::memcpy(&k, base + i * itemsize + o_key, 8);
            if (k == key0) { P = i; break; }
        }
        if (P <= 0 || n < 2 * P) return 0;
        // admission over the first period: no markers, distinct keys,
        // in-order continuation at/after the worker's initial position
        std::vector<i64> key_of((size_t)P), nextpos((size_t)P);
        for (i64 k = 0; k < P; ++k) {
            const u8 *rp = base + k * itemsize;
            if (rp[o_marker]) return 0;
            std::memcpy(&key_of[(size_t)k], rp + o_key, 8);
            std::memcpy(&nextpos[(size_t)k], rp + o_id, 8);
        }
        {
            // duplicate keys within one period would alias KeyStates and
            // interleave unsorted positions into one archive: bail out
            std::vector<i64> sorted = key_of;
            std::sort(sorted.begin(), sorted.end());
            if (std::adjacent_find(sorted.begin(), sorted.end())
                != sorted.end())
                return 0;
        }
        // state() first for every key (it may grow `keys`, invalidating
        // pointers), then resolve pointers
        for (i64 k = 0; k < P; ++k)
            state(key_of[(size_t)k]);
        std::vector<KeyState *> sts((size_t)P);
        for (i64 k = 0; k < P; ++k) {
            KeyState &st = state(key_of[(size_t)k]);
            if (st.neutral)   // general loop clears the flag per row
                return 0;
            if (nextpos[(size_t)k] < st.last_pos
                || nextpos[(size_t)k] < st.initial_id)
                return 0;
            sts[(size_t)k] = &st;
        }
        // process in blocks so the flush_rows / batch_len launch
        // granularity matches the general loop's
        i64 block = flush_rows;
        if (batch_len < (i64)1 << 40)
            block = std::min(block, batch_len * slide);
        block = std::max(block, P);
        std::vector<i64 *> pw((size_t)P), tw((size_t)P), vw((size_t)P);
        std::vector<i64> mcnt((size_t)P), save_next((size_t)P);
        std::vector<size_t> save_sz((size_t)P);
        i64 consumed = 0;
        i64 idx0 = 0;   // key index of row `consumed`
        while (consumed < n) {
            const i64 take = std::min(block, n - consumed);
            for (i64 k = 0; k < P; ++k) {
                // rows i in [consumed, consumed+take) with (i - k) % P == 0
                const i64 first = (k - idx0 + P) % P;
                const i64 m = first < take ? (take - 1 - first) / P + 1 : 0;
                mcnt[(size_t)k] = m;
                KeyState &st = *sts[(size_t)k];
                save_sz[(size_t)k] = st.pos.size();
                save_next[(size_t)k] = nextpos[(size_t)k];
                st.pos.resize(st.pos.size() + (size_t)m);
                st.ts.resize(st.ts.size() + (size_t)m);
                st.val.resize(st.val.size() + (size_t)m);
                pw[(size_t)k] = st.pos.data() + save_sz[(size_t)k];
                tw[(size_t)k] = st.ts.data() + save_sz[(size_t)k];
                vw[(size_t)k] = st.val.data() + save_sz[(size_t)k];
            }
            // fused verify + copy: one sequential pass over the block
            const u8 *rp = base + consumed * itemsize;
            i64 idx = idx0;
            i64 bmin = INT64_MAX, bmax = INT64_MIN;
            i64 done = 0;
            for (; done < take; ++done) {
                i64 k, id, t, v;
                std::memcpy(&k, rp + o_key, 8);
                std::memcpy(&id, rp + o_id, 8);
                if (k != key_of[(size_t)idx] || id != nextpos[(size_t)idx]
                    || rp[o_marker])
                    break;
                std::memcpy(&t, rp + o_ts, 8);
                std::memcpy(&v, rp + o_val, 8);
                if (v < bmin) bmin = v;
                if (v > bmax) bmax = v;
                *tw[(size_t)idx]++ = t;
                *vw[(size_t)idx]++ = v;
                *pw[(size_t)idx]++ = nextpos[(size_t)idx]++;
                rp += itemsize;
                if (++idx == P) idx = 0;
            }
            if (done < take) {
                // pattern broke mid-block: roll this block back (committed
                // blocks stand); the general loop takes the tail
                for (i64 k = 0; k < P; ++k) {
                    KeyState &st = *sts[(size_t)k];
                    st.pos.resize(save_sz[(size_t)k]);
                    st.ts.resize(save_sz[(size_t)k]);
                    st.val.resize(save_sz[(size_t)k]);
                    nextpos[(size_t)k] = save_next[(size_t)k];
                }
                return consumed;
            }
            // bookkeeping for all keys first (flush() during the firing
            // loop below purges/compacts archives, so no block pointer is
            // touched past this point), then firing with the thresholds
            // evaluated once per key per block
            for (i64 k = 0; k < P; ++k) {
                const i64 m = mcnt[(size_t)k];
                if (m == 0) continue;
                KeyState &st = *sts[(size_t)k];
                st.appended += m;
                pend_rows += m;
                st.last_pos = nextpos[(size_t)k] - 1;
                // the block-wide value range over-approximates per key —
                // safe for wire-dtype choice (never narrower than exact)
                st.note_range0(bmin, bmax);
            }
            for (i64 k = 0; k < P; ++k) {
                if (mcnt[(size_t)k] == 0) continue;
                KeyState &st = *sts[(size_t)k];
                const i64 endpos = st.last_pos;
                if (endpos >= st.next_create) {
                    st.next_lwid = (endpos - st.initial_id) / slide + 1;
                    st.next_create = st.next_lwid * slide + st.initial_id;
                }
                if (endpos >= st.fire_pos) {
                    i64 to = (endpos - st.initial_id - win) / slide + 1;
                    if (to > st.next_lwid) to = st.next_lwid;
                    const i64 from = st.n_fired;
                    st.n_fired = to;
                    st.fire_pos = to * slide + win + st.initial_id;
                    emit_windows(st, key_of[(size_t)k], from, to, false);
                    if ((i64)hkey.size() >= batch_len) flush();
                }
            }
            consumed += take;
            idx0 = (idx0 + take) % P;
            if (pend_rows >= flush_rows) flush();
        }
        return consumed;
    }

    i64 process(const u8 *base, i64 n, i64 itemsize, i64 o_key, i64 o_id,
                i64 o_ts, i64 o_marker, i64 o_val,
                i64 shard_mod = 1, i64 shard_id = 0,
                const u8 *shard_of = nullptr,
                const i64 *o_xval = nullptr) {
        const i64 q0 = launches_made;
        if (shard_of == nullptr && shard_mod == 1) {
            const i64 fdone = process_fast(base, n, itemsize, o_key, o_id,
                                           o_ts, o_marker, o_val);
            if (fdone >= n) return launches_made - q0;
            base += fdone * itemsize;
            n -= fdone;
        }
        // One sequential pass (reads stay prefetch-friendly even with
        // interleaved keys); the per-row divisions of the closed-form
        // firing arithmetic (core/winseq.py) are replaced by two monotone
        // comparisons against cached create/fire position thresholds —
        // divisions only run on the (rare) create/fire events.  With
        // shard_mod > 1 this core owns only keys with mix64(key) %%
        // shard_mod == shard_id (the multithreaded key-sharded path);
        // `shard_of` is the precomputed per-row shard-id byte array from
        // wf_cores_process_mt — a 1-byte compare per foreign row instead
        // of a hash + division per row per shard.
        // a multi-field core driven through the single-field entry points
        // has no extra offsets: refuse (defined error) instead of
        // dereferencing null per appended row
        if (n_fields > 1 && o_xval == nullptr) return -1;
        const u8 sid = (u8)shard_id;
        for (i64 i = 0; i < n; ++i) {
            const u8 *rp = base + i * itemsize;
            if (shard_of != nullptr && shard_of[i] != sid)
                continue;
            i64 key, id, tsv, val;
            std::memcpy(&key, rp + o_key, 8);
            if (shard_of == nullptr && shard_mod > 1
                && (i64)(mix64((unsigned long long)key)
                         % (unsigned long long)shard_mod) != shard_id)
                continue;
            std::memcpy(&id, rp + o_id, 8);
            std::memcpy(&tsv, rp + o_ts, 8);
            std::memcpy(&val, rp + o_val, 8);
            const bool mk = rp[o_marker] != 0;
            KeyState &st = state(key);
            if (st.neutral) st.neutral = false;
            const i64 pos = (kind == CB) ? id : tsv;
            if (pos < st.last_pos) continue;       // out-of-order drop
            st.last_pos = pos;
            if (pos < st.initial_id) continue;     // before worker's slice
            if (mk) {
                st.marker_pos = pos;
                st.marker_ts = tsv;
            } else {
                if (hopping && ((pos - st.initial_id) % slide) >= win)
                    continue;                      // hopping gap
                st.pos.push_back(pos);
                st.ts.push_back(tsv);
                st.val.push_back(val);
                i64 vrow[kMaxFields];
                vrow[0] = val;
                for (int f = 1; f < n_fields; ++f) {
                    i64 v;
                    std::memcpy(&v, rp + o_xval[f - 1], 8);
                    st.xval[(size_t)(f - 1)].push_back(v);
                    vrow[f] = v;
                }
                st.note_vals(n_fields, vrow);
                st.appended++;
                pend_rows++;
            }
            if (pos >= st.next_create) {           // lazy window creation
                st.next_lwid = (pos - st.initial_id) / slide + 1;
                st.next_create = st.next_lwid * slide + st.initial_id;
            }
            if (pos >= st.fire_pos) {              // triggerer fired
                i64 to = (pos - st.initial_id - win) / slide + 1;
                if (to > st.next_lwid) to = st.next_lwid;
                const i64 from = st.n_fired;
                st.n_fired = to;
                st.fire_pos = to * slide + win + st.initial_id;
                emit_windows(st, key, from, to, false);
                if ((i64)hkey.size() >= batch_len) flush();
            }
            // rows-only flush: giant windows accumulate rows long before
            // any fire event; ship bounded rectangles regardless
            if (pend_rows >= flush_rows) flush();
        }
        return launches_made - q0;
    }

    i64 eos() {
        const i64 q0 = launches_made;
        for (size_t r = 0; r < keys.size(); ++r) {
            KeyState &st = keys[r];
            if (st.neutral) continue;   // key migrated away at a rescale
            if (st.n_fired < st.next_lwid) {
                const i64 from = st.n_fired;
                st.n_fired = st.next_lwid;
                emit_windows(st, rowkey[r], from, st.next_lwid, true);
            }
        }
        flush();
        return launches_made - q0;
    }
};

// ---------------------------------------------------------------------------
// Blocking MPSC channel — the FastFlow-queue analog for the threaded engine
// (runtime/engine.py's Inbox).  Carries (src_slot, payload_slot) int pairs;
// the Python side keeps the actual batch objects in a side table keyed by
// payload_slot, so no Python object crosses the ABI.  Blocking push/pop run
// with the GIL released (ctypes), replacing the 50 ms polling loops of the
// queue.Queue fallback with futex waits.  close() is the failure path: it
// wakes everyone; pushes fail immediately, pops drain what is left first.
// ---------------------------------------------------------------------------

struct NativeQueue {
    std::vector<std::pair<i64, i64>> buf;
    size_t cap, head = 0, count = 0;
    std::mutex mu;
    std::condition_variable cv_space, cv_items;
    bool closed = false;
    int waiters = 0;   // threads inside push/pop; free() spins on 0

    explicit NativeQueue(size_t c) : buf(c), cap(c) {}

    int push(i64 src, i64 slot) {
        std::unique_lock<std::mutex> lk(mu);
        ++waiters;
        cv_space.wait(lk, [&] { return count < cap || closed; });
        --waiters;
        if (closed) return -1;
        buf[(head + count) % cap] = {src, slot};
        ++count;
        cv_items.notify_one();
        return 0;
    }

    int pop(i64 *src, i64 *slot) {
        std::unique_lock<std::mutex> lk(mu);
        ++waiters;
        cv_items.wait(lk, [&] { return count > 0 || closed; });
        --waiters;
        if (count == 0) return -1;  // closed and drained
        auto &e = buf[head];
        *src = e.first;
        *slot = e.second;
        head = (head + 1) % cap;
        --count;
        cv_space.notify_one();
        return 0;
    }

    // Overload-policy entry points (runtime/overload.py): non-blocking and
    // deadline-bounded variants.  Return codes: 0 = done, 1 = would block
    // (full / empty / deadline expired), -1 = closed.

    int try_push(i64 src, i64 slot) {
        std::lock_guard<std::mutex> lk(mu);
        if (closed) return -1;
        if (count >= cap) return 1;
        buf[(head + count) % cap] = {src, slot};
        ++count;
        cv_items.notify_one();
        return 0;
    }

    int push_timed(i64 src, i64 slot, i64 timeout_ms) {
        std::unique_lock<std::mutex> lk(mu);
        ++waiters;
        bool ready = cv_space.wait_for(
            lk, std::chrono::milliseconds(timeout_ms),
            [&] { return count < cap || closed; });
        --waiters;
        if (closed) return -1;
        if (!ready) return 1;
        buf[(head + count) % cap] = {src, slot};
        ++count;
        cv_items.notify_one();
        return 0;
    }

    int try_pop(i64 *src, i64 *slot) {
        std::lock_guard<std::mutex> lk(mu);
        if (count == 0) return closed ? -1 : 1;
        auto &e = buf[head];
        *src = e.first;
        *slot = e.second;
        head = (head + 1) % cap;
        --count;
        cv_space.notify_one();
        return 0;
    }

    void close() {
        std::lock_guard<std::mutex> lk(mu);
        closed = true;
        cv_space.notify_all();
        cv_items.notify_all();
    }

    bool idle() {
        std::lock_guard<std::mutex> lk(mu);
        return waiters == 0;
    }
};

}  // namespace

extern "C" {

void *wf_queue_new(i64 capacity) {
    return new NativeQueue((size_t)(capacity > 0 ? capacity : 1 << 16));
}

void wf_queue_free(void *h) {
    // destroying a mutex/condvar another thread is blocked on is undefined
    // behavior: close() wakes everyone, then spin until the last waiter has
    // left push/pop before deleting
    NativeQueue *q = (NativeQueue *)h;
    q->close();
    while (!q->idle()) std::this_thread::yield();
    delete q;
}

int wf_queue_push(void *h, i64 src, i64 slot) {
    return ((NativeQueue *)h)->push(src, slot);
}

int wf_queue_pop(void *h, i64 *src, i64 *slot) {
    return ((NativeQueue *)h)->pop(src, slot);
}

int wf_queue_try_push(void *h, i64 src, i64 slot) {
    return ((NativeQueue *)h)->try_push(src, slot);
}

int wf_queue_push_timed(void *h, i64 src, i64 slot, i64 timeout_ms) {
    return ((NativeQueue *)h)->push_timed(src, slot, timeout_ms);
}

int wf_queue_try_pop(void *h, i64 *src, i64 *slot) {
    return ((NativeQueue *)h)->try_pop(src, slot);
}

void wf_queue_close(void *h) { ((NativeQueue *)h)->close(); }

void *wf_core_new(i64 win, i64 slide, int win_type, int role,
                  i64 id_outer, i64 n_outer, i64 slide_outer,
                  i64 id_inner, i64 n_inner, i64 slide_inner,
                  i64 map_idx0, i64 map_idx1, i64 result_ts_slide,
                  i64 batch_len, i64 flush_rows, int max_wire) {
    return new Core(win, slide, win_type, role, id_outer, n_outer,
                    slide_outer, id_inner, n_inner, slide_inner, map_idx0,
                    map_idx1, result_ts_slide, batch_len, flush_rows,
                    max_wire);
}

void wf_core_free(void *h) { delete (Core *)h; }

i64 wf_core_process(void *h, const void *base, i64 n, i64 itemsize,
                    i64 o_key, i64 o_id, i64 o_ts, i64 o_marker,
                    i64 o_val) {
    return ((Core *)h)->process((const u8 *)base, n, itemsize, o_key, o_id,
                                o_ts, o_marker, o_val);
}

// single source of truth for the staging bound (Python guards read it)
i64 wf_max_fields(void) { return kMaxFields; }

// Multi-field staging (one device ring per payload column,
// ops/resident.py MultiFieldResidentExecutor): declare the column count
// and each field's widest admissible wire dtype.  Contract: call once,
// right after wf_core_new, before any process call — keys registered
// earlier would lack the extra archive columns.  Returns the accepted
// field count; a caller asking for more than kMaxFields MUST treat the
// short return as a refusal (staging only the prefix would hand the
// device uninitialized rectangles for the missing columns).
i64 wf_core_set_fields(void *h, i64 n_fields, const int *max_wires) {
    Core *c = (Core *)h;
    int nf = (int)(n_fields < 1 ? 1 : n_fields);
    if (nf > kMaxFields) nf = kMaxFields;
    c->n_fields = nf;
    for (int f = 0; f < nf; ++f)
        c->max_wire_f[f] = max_wires ? max_wires[f] : c->max_wire;
    c->max_wire = c->max_wire_f[0];
    return nf;
}

// Persistent shard worker pool: threads park on a condvar between chunks
// instead of being spawned/joined per call (the hot path runs one
// wf_cores_process_mt per engine batch).  Leaked at process exit on
// purpose — destroying parked threads during static teardown is riskier
// than letting process exit reap them.
namespace {

struct ShardPool {
    std::vector<std::thread> threads;
    std::mutex run_mu;  // serializes run() callers: a second engine thread
                        // entering mid-run would overwrite job/counters and
                        // silently drop the first caller's shard tasks
    std::mutex mu;
    std::condition_variable cv_task, cv_done;
    const std::function<void(i64)> *job = nullptr;
    i64 n_tasks = 0, next_task = 0, done = 0;
    unsigned long long gen = 0;

    void ensure(i64 n) {  // call with mu held
        while ((i64)threads.size() < n) {
            threads.emplace_back([this] { worker(); });
        }
    }

    void worker() {
        std::unique_lock<std::mutex> lk(mu);
        unsigned long long seen = 0;
        for (;;) {
            cv_task.wait(lk, [&] { return gen != seen; });
            seen = gen;
            while (next_task < n_tasks) {
                const i64 t = next_task++;
                lk.unlock();
                (*job)(t);
                lk.lock();
                if (++done == n_tasks) cv_done.notify_all();
            }
        }
    }

    void run(i64 n, const std::function<void(i64)> &fn) {
        std::lock_guard<std::mutex> outer(run_mu);
        std::unique_lock<std::mutex> lk(mu);
        ensure(n);
        job = &fn;
        n_tasks = n;
        next_task = 0;
        done = 0;
        ++gen;
        cv_task.notify_all();
        cv_done.wait(lk, [&] { return done == n_tasks; });
        job = nullptr;
    }
};

ShardPool *shard_pool() {
    static ShardPool *p = new ShardPool();  // intentionally never deleted
    return p;
}

}  // namespace

// Key-sharded multithreaded processing: sub-core t consumes keys with
// mix64(key) % n_shards == t.  Two pool phases: (A) striped parallel fill
// of a per-row shard-id byte array (one hash per row TOTAL), then (B)
// every shard processes the chunk, skipping foreign rows with a 1-byte
// compare — instead of each of the S shards paying a hash + integer
// division per row (S*n divisions dominated the r1 profile at 56 ns/row).
// Returns total launches queued.
static i64 cores_process_mt_impl(void **hs, i64 n_shards, const void *base,
                                 i64 n, i64 itemsize, i64 o_key, i64 o_id,
                                 i64 o_ts, i64 o_marker, i64 o_val,
                                 const i64 *o_xval) {
    if (n_shards == 1)
        return ((Core *)hs[0])->process((const u8 *)base, n, itemsize,
                                        o_key, o_id, o_ts, o_marker, o_val,
                                        1, 0, nullptr, o_xval);
    // shared scratch: both phases must run under one lock so a second
    // engine thread cannot overwrite the byte array between them (leaked
    // at exit on purpose, like the pool)
    static std::mutex *mt_mu = new std::mutex();
    static std::vector<u8> *shard_of = new std::vector<u8>();
    std::lock_guard<std::mutex> lk(*mt_mu);
    if ((i64)shard_of->size() < n) shard_of->resize((size_t)n);
    u8 *so = shard_of->data();
    const u8 *b8 = (const u8 *)base;
    const unsigned long long mod = (unsigned long long)n_shards;
    const bool pow2 = (mod & (mod - 1)) == 0;
    const unsigned long long mask = mod - 1;
    const i64 stripes = n_shards;
    std::function<void(i64)> assign = [&](i64 t) {
        const i64 lo = t * n / stripes, hi = (t + 1) * n / stripes;
        for (i64 i = lo; i < hi; ++i) {
            i64 key;
            std::memcpy(&key, b8 + i * itemsize + o_key, 8);
            const unsigned long long h = mix64((unsigned long long)key);
            so[i] = (u8)(pow2 ? (h & mask) : (h % mod));
        }
    };
    shard_pool()->run(stripes, assign);
    std::vector<i64> res((size_t)n_shards, 0);
    std::function<void(i64)> fn = [&](i64 t) {
        res[(size_t)t] = ((Core *)hs[t])->process(
            (const u8 *)base, n, itemsize, o_key, o_id, o_ts, o_marker,
            o_val, n_shards, t, so, o_xval);
    };
    shard_pool()->run(n_shards, fn);
    i64 total = 0;
    for (i64 t = 0; t < n_shards; ++t) total += res[(size_t)t];
    return total;
}

i64 wf_cores_process_mt(void **hs, i64 n_shards, const void *base, i64 n,
                        i64 itemsize, i64 o_key, i64 o_id, i64 o_ts,
                        i64 o_marker, i64 o_val) {
    return cores_process_mt_impl(hs, n_shards, base, n, itemsize, o_key,
                                 o_id, o_ts, o_marker, o_val, nullptr);
}

// multi-field form: o_vals carries n_fields payload-column offsets
i64 wf_cores_process_mt_f(void **hs, i64 n_shards, const void *base, i64 n,
                          i64 itemsize, i64 o_key, i64 o_id, i64 o_ts,
                          i64 o_marker, const i64 *o_vals) {
    return cores_process_mt_impl(hs, n_shards, base, n, itemsize, o_key,
                                 o_id, o_ts, o_marker, o_vals[0],
                                 o_vals + 1);
}

i64 wf_core_eos(void *h) { return ((Core *)h)->eos(); }

// --------------------------------------------------------------- renumber
// Per-key dense id renumbering for the ordering layer's single-channel
// TS_RENUMBERING fast path: out[i] = counter[key[i]]++ in one pass, the
// counter table living in the handle so it persists across batches (the
// Python groupby-cumcount needs a stable argsort per batch — measured
// 2026-07-31 at ~6.5M rows/s against this loop's memory-speed pass).
// Small non-negative keys ride a dense vector; anything else the map.
struct Renumber {
    std::vector<i64> dense;
    std::unordered_map<i64, i64> sparse;
};

void *wf_renum_new() { return new Renumber(); }

void wf_renum_free(void *h) { delete (Renumber *)h; }

void wf_renum_run(void *h, const i64 *keys, i64 n, i64 *out) {
    Renumber *r = (Renumber *)h;
    for (i64 i = 0; i < n; ++i) {
        const i64 k = keys[i];
        if (k >= 0 && k < (1 << 20)) {
            if ((i64)r->dense.size() <= k)
                r->dense.resize((size_t)(k + 1), 0);
            out[i] = r->dense[(size_t)k]++;
        } else {
            out[i] = r->sparse[k]++;
        }
    }
}

// counter lookup + post-increment for one key (marker replay at flush:
// the marker row takes the next id exactly like the general path's
// per-key emit_counter)
i64 wf_renum_next(void *h, i64 key) {
    Renumber *r = (Renumber *)h;
    if (key >= 0 && key < (1 << 20)) {
        if ((i64)r->dense.size() <= key)
            r->dense.resize((size_t)(key + 1), 0);
        return r->dense[(size_t)key]++;
    }
    return r->sparse[key]++;
}

// proactive dispatch sizing: the host adjusts the natural launch size to
// the measured wire service (a power-of-2 multiple of the configured
// flush_rows, so natural shapes stay on the prewarmed bucket ladder) —
// the up-front form of what wf_launch_coalesce does reactively after the
// queue has already deepened.  Caller contract: invoked from the producer
// thread between process() calls (flush_rows is producer-read-only, so no
// lock); takes effect at the next flush; ring re-provisioning happens on
// the next rebase via the ordinary ring-full path.
void wf_core_set_flush_rows(void *h, i64 rows) {
    ((Core *)h)->flush_rows = rows;
}

// latency-bounded flushing: ship whatever windows/rows are pending even
// though neither batch_len nor flush_rows has been reached (the host core
// calls this when its max-delay timer expires; no-op when nothing pends)
i64 wf_core_force_flush(void *h) {
    Core *c = (Core *)h;
    const i64 q0 = c->launches_made;
    c->flush();
    return c->launches_made - q0;
}

i64 wf_launch_pending(void *h) {
    Core *c = (Core *)h;
    std::lock_guard<std::mutex> lk(c->qmu);
    return (i64)c->queue.size();
}

// --------------------------------------------------------------- coalescing
// Merge adjacent queued launches into one bigger dispatch.  Over the
// tunneled device each dispatch pays an amortized RTT regardless of size
// (BASELINE.md wire characterization), so when the wire falls behind and
// launches pile up, fusing them trades per-dispatch latency for fewer
// round trips — the adaptive form of a larger flush_rows.  Regular pairs
// whose window sequences stay arithmetic keep the compressed form; any
// other pair (TB windows, mixed) merges on its explicit descriptors.
// Never across a ring rebase.

static inline i64 rd_elem(const u8 *p, int wire, i64 i) {
    switch (wire) {
        case 0: return ((const int8_t *)p)[i];
        case 1: return ((const int16_t *)p)[i];
        case 2: return ((const int32_t *)p)[i];
        default: return ((const i64 *)p)[i];
    }
}

static inline void wr_elem(u8 *p, int wire, i64 i, i64 v) {
    switch (wire) {
        case 0: ((int8_t *)p)[i] = (int8_t)v; break;
        case 1: ((int16_t *)p)[i] = (int16_t)v; break;
        case 2: ((int32_t *)p)[i] = (int32_t)v; break;
        default: ((i64 *)p)[i] = v; break;
    }
}

// merge B into A (A dispatched first; B's rows append right after A's in
// ring order).  When both launches carry regular descriptors and B's
// window sequences continue A's arithmetic, the merged launch stays
// regular; otherwise it falls back to the explicit per-window descriptors
// both launches always carry (wstarts/wlens are RING coordinates, valid
// verbatim after the merge — so TB and mixed launches coalesce too).
// Returns false — leaving both untouched — when the pair is incompatible.
static bool try_merge(Launch &A, Launch &B, i64 slide, i64 max_cells,
                      i64 max_mult, int n_fields) {
    if (A.xblk.size() != B.xblk.size()) return false;
    // never across a ring rebase, in either role: a rebase launch resets
    // the ring geometry, and the invariant is simplest (and testable) when
    // rebases are dispatch barriers (ADVICE r2: A.rebase was previously
    // admitted as a merge target — sound in the cases exercised, but
    // asymmetric with this documented rule)
    if (A.rebase || B.rebase) return false;
    if (A.KP != B.KP || A.cap != B.cap) return false;
    // buddy rule: only equal-multiplicity launches merge, so merged sizes
    // stay at power-of-2 multiples of flush_rows and the device sees a
    // SMALL, warmup-coverable set of shape buckets (a free-form merge
    // produces odd multiplicities whose first dispatch compiles for ~10s
    // over the tunnel — measured — wrecking the run that hits it).
    // `max_mult` is the caller's adaptive depth cap (wire service time
    // driven, <= kCoalesceLadderMax: the ring is provisioned for that);
    // one dispatch then carries <= max_mult RTTs' worth of work.  (A cell
    // budget relative to flush_rows would silently disable merging
    // whenever the padded K*bucket(R) rectangle dwarfs the row count —
    // many keys, or one hot key — so the area guard below is absolute
    // instead.)
    if (max_mult > kCoalesceLadderMax) max_mult = kCoalesceLadderMax;
    if (A.mult != B.mult || A.mult * 2 > max_mult) return false;
    // only LIKE pairs merge: two regular launches stay compressed, two
    // irregular launches concatenate explicit descriptors (their base
    // plain-step shapes are in the compile cache, so the merged diagonal
    // sibling is prewarmed).  A mixed pair — or a regular pair whose
    // window sequences broke continuity — would have to dispatch an
    // irregular shape that NO prior launch compiled, handing the run the
    // very cold mid-stall compile coalescing exists to avoid: reject.
    if (A.regular != B.regular) return false;
    const bool regular = A.regular != 0;
    const i64 K2 = std::max(A.K, B.K);
    // per-key row continuity (B's rows must land right after A's in the
    // ring for B's descriptors to stay valid — true by construction for
    // adjacent flushes, verified here), regularity continuity, width
    i64 newR = 1, maxoff = 0, cmaxA = 0, cmaxB = 0, cmaxM = 0;
    for (i64 k = 0; k < K2; ++k) {
        const i64 ra = k < A.K ? A.rows[(size_t)k] : 0;
        const i64 rb = k < B.K ? B.rows[(size_t)k] : 0;
        if (k < A.K && k < B.K
            && B.offs[(size_t)k] != A.offs[(size_t)k] + ra)
            return false;
        if (regular) {
            const i64 ca = k < A.K ? A.rcount[(size_t)k] : 0;
            const i64 cb = k < B.K ? B.rcount[(size_t)k] : 0;
            if (ca && cb
                && (B.rlen[(size_t)k] != A.rlen[(size_t)k]
                    || B.rstart0[(size_t)k]
                           != A.rstart0[(size_t)k] + (int32_t)(ca * slide)))
                return false;
            cmaxA = std::max(cmaxA, ca);
            cmaxB = std::max(cmaxB, cb);
            cmaxM = std::max(cmaxM, ca + cb);
        }
        newR = std::max(newR, ra + rb);
        maxoff = std::max(maxoff,
                          k < A.K ? A.offs[(size_t)k] : B.offs[(size_t)k]);
    }
    if (K2 * bucket(newR) > max_cells) return false;
    // the Python-side overflow guard is offs.max() + bucket(R) <= cap;
    // respect the same conservative bound so a merged launch never trips it
    if (maxoff + bucket(newR) > A.cap) return false;
    if (regular) {
        // regular dispatch shapes are keyed on (bucket(R), bucket(cmax)).
        // Small per-key window counts can grow the row bucket while the
        // window-count bucket stays clamped — bucket(ca+cb)==bucket(ca)
        // — so merged shapes live on the LOWER TRIANGLE {(Rb*a, C*b),
        // b <= a} of the pair's base shape, which is exactly the set
        // prewarm_regular_ladder compiles (ADVICE r3: the diagonal alone
        // left (2*Rb, C) cold).  Guard the triangle invariant: equal
        // buckets in (both axes), and the window-count bucket may grow at
        // most as fast as the row bucket — a pair whose C bucket would
        // outgrow its R bucket (possible when one launch packs many more
        // windows per row) dispatches a shape no warmup compiled: reject,
        // the pair simply stays unmerged.
        if (bucket(A.R) != bucket(B.R)
            || bucket(std::max<i64>(cmaxA, 1))
                   != bucket(std::max<i64>(cmaxB, 1)))
            return false;
        const i64 rr = bucket(newR) / bucket(A.R);
        const i64 rc = bucket(std::max<i64>(cmaxM, 1))
                       / bucket(std::max<i64>(cmaxA, 1));
        if (rc > rr) return false;
    } else {
        // irregular dispatch shapes are keyed on (bucket(R), bucket(B)):
        // keep merged shapes on the DIAGONAL ladder of the pair's base
        // shape — equal buckets in, proportional buckets out — so the
        // prewarmed {2x..16x} siblings cover every reachable shape and a
        // merge can never manufacture an off-diagonal bucket that
        // compiles cold mid-stall (the exact failure the prewarm exists
        // to prevent).  Rejected pairs simply stay unmerged.
        if (bucket(A.R) != bucket(B.R)
            || bucket(std::max<i64>(A.B, 1)) != bucket(std::max<i64>(B.B, 1)))
            return false;
        const i64 rr = bucket(newR) / bucket(A.R);
        const i64 rb2 = bucket(std::max<i64>(A.B + B.B, 1))
                        / bucket(std::max<i64>(A.B, 1));
        if (rr != rb2) return false;
    }
    // merge every field's rectangle at that field's widened wire dtype
    // (field 0 in blk/wire, extras in xblk/xwire — same geometry)
    std::vector<std::vector<u8>> nblks((size_t)n_fields);
    int nwires[kMaxFields];
    for (int f = 0; f < n_fields; ++f) {
        const std::vector<u8> &Ab = f == 0 ? A.blk : A.xblk[(size_t)(f - 1)];
        const std::vector<u8> &Bb = f == 0 ? B.blk : B.xblk[(size_t)(f - 1)];
        const int wa = f == 0 ? A.wire : A.xwire[f];
        const int wb = f == 0 ? B.wire : B.xwire[f];
        const int wire2 = std::max(wa, wb);
        const i64 isz2 = 1LL << wire2;
        nwires[f] = wire2;
        std::vector<u8> &nblk = nblks[(size_t)f];
        nblk.assign((size_t)(K2 * newR * isz2), 0);
        for (i64 k = 0; k < K2; ++k) {
            const i64 ra = k < A.K ? A.rows[(size_t)k] : 0;
            const i64 rb = k < B.K ? B.rows[(size_t)k] : 0;
            u8 *dst = nblk.data() + (size_t)(k * newR * isz2);
            if (ra) {
                const u8 *src = Ab.data() + (size_t)(k * A.R << wa);
                if (wa == wire2)
                    std::memcpy(dst, src, (size_t)(ra * isz2));
                else
                    for (i64 i = 0; i < ra; ++i)
                        wr_elem(dst, wire2, i, rd_elem(src, wa, i));
            }
            if (rb) {
                const u8 *src = Bb.data() + (size_t)(k * B.R << wb);
                if (wb == wire2)
                    std::memcpy(dst + (size_t)(ra * isz2), src,
                                (size_t)(rb * isz2));
                else
                    for (i64 i = 0; i < rb; ++i)
                        wr_elem(dst, wire2, ra + i, rd_elem(src, wb, i));
            }
        }
    }
    // merged per-key state: offsets are A's (B's new keys keep B's),
    // counts add, window sequences concatenate
    std::vector<i64> noffs((size_t)K2, 0);
    std::vector<int32_t> nrows((size_t)K2, 0), nrc, nrs0, nrl;
    if (regular) {
        nrc.assign((size_t)K2, 0);
        nrs0.assign((size_t)K2, 0);
        nrl.assign((size_t)K2, 0);
    }
    i64 cmax = 0;
    for (i64 k = 0; k < K2; ++k) {
        const i64 ra = k < A.K ? A.rows[(size_t)k] : 0;
        const i64 rb = k < B.K ? B.rows[(size_t)k] : 0;
        noffs[(size_t)k] = k < A.K ? A.offs[(size_t)k] : B.offs[(size_t)k];
        nrows[(size_t)k] = (int32_t)(ra + rb);
        if (regular) {
            const i64 ca = k < A.K ? A.rcount[(size_t)k] : 0;
            const i64 cb = k < B.K ? B.rcount[(size_t)k] : 0;
            nrc[(size_t)k] = (int32_t)(ca + cb);
            nrs0[(size_t)k] = ca ? A.rstart0[(size_t)k]
                                 : (cb ? B.rstart0[(size_t)k] : 0);
            nrl[(size_t)k] = ca ? A.rlen[(size_t)k]
                                : (cb ? B.rlen[(size_t)k] : 0);
            cmax = std::max<i64>(cmax, ca + cb);
        }
    }
    const i64 B1 = A.B, B2 = B.B;
    if (regular) {
        // B's windows index after A's within each key
        A.widx.resize((size_t)(B1 + B2));
        for (i64 i = 0; i < B2; ++i) {
            const i64 r = B.wrows[(size_t)i];
            const i64 base = r < A.K ? A.rcount[(size_t)r] : 0;
            A.widx[(size_t)(B1 + i)] = B.widx[(size_t)i] + (int32_t)base;
        }
    } else {
        A.widx.clear();
    }
    auto cat32 = [](std::vector<int32_t> &a, const std::vector<int32_t> &b) {
        a.insert(a.end(), b.begin(), b.end());
    };
    auto cat64 = [](std::vector<i64> &a, const std::vector<i64> &b) {
        a.insert(a.end(), b.begin(), b.end());
    };
    cat32(A.wrows, B.wrows);
    cat32(A.wstarts, B.wstarts);
    cat32(A.wlens, B.wlens);
    cat64(A.hkey, B.hkey);
    cat64(A.hid, B.hid);
    cat64(A.hts, B.hts);
    cat64(A.hlen, B.hlen);
    cat64(A.hpmax, B.hpmax);
    cat64(A.hpmin, B.hpmin);
    A.blk = std::move(nblks[0]);
    for (int f = 1; f < n_fields; ++f)
        A.xblk[(size_t)(f - 1)] = std::move(nblks[(size_t)f]);
    for (int f = 0; f < n_fields; ++f) A.xwire[f] = nwires[f];
    A.offs = std::move(noffs);
    A.rows = std::move(nrows);
    A.rcount = std::move(nrc);
    A.rstart0 = std::move(nrs0);
    A.rlen = std::move(nrl);
    A.cmax = cmax;
    A.wire = nwires[0];
    A.K = K2;
    A.R = newR;
    A.B = B1 + B2;
    A.mult *= 2;
    A.regular = regular ? 1 : 0;
    return true;
}

// Fuse adjacent queued launch pairs (buddy scheme) while merged
// rectangles stay under max_cells (K * R cells), up to max_merge merges.
// Consumer-side only (the one ship thread consumes; the producer only
// push_backs), so popping interior pairs is race-free; the heavy merge
// runs outside the queue lock so the producer's flush() never stalls
// behind it.  Returns the number of merges performed.
i64 wf_launch_coalesce(void *h, i64 max_cells, i64 max_merge,
                       i64 max_mult) {
    Core *c = (Core *)h;
    i64 merged = 0;
    size_t i = 0;
    const i64 mcap = std::min<i64>(std::max<i64>(max_mult, 1),
                                   kCoalesceLadderMax);
    while (merged < max_merge) {
        Launch A, B;
        {
            std::lock_guard<std::mutex> lk(c->qmu);
            // find the next adjacent candidate pair at or after i (LIKE
            // pairs only: regular+regular compressed, irregular+irregular
            // on explicit descriptors)
            while (i + 1 < c->queue.size()) {
                Launch &a = c->queue[i], &b = c->queue[i + 1];
                if (!a.rebase && !b.rebase && a.regular == b.regular
                    && a.mult == b.mult && a.mult * 2 <= mcap)
                    break;
                ++i;
            }
            if (i + 1 >= c->queue.size()) break;
            A = std::move(c->queue[i]);
            B = std::move(c->queue[i + 1]);
            c->queue.erase(c->queue.begin() + i, c->queue.begin() + i + 2);
        }
        const bool ok = try_merge(A, B, c->slide, max_cells, mcap,
                                  c->n_fields);
        {
            std::lock_guard<std::mutex> lk(c->qmu);
            if (!ok) {
                c->queue.insert(c->queue.begin() + i, std::move(B));
                c->queue.insert(c->queue.begin() + i, std::move(A));
            } else {
                c->queue.insert(c->queue.begin() + i, std::move(A));
            }
        }
        if (ok) {
            ++merged;
            i = 0;   // the merged launch may now neighbor an equal buddy
        } else {
            ++i;     // this pair can never merge; move on
        }
    }
    return merged;
}

int wf_launch_peek(void *h, i64 *K, i64 *R, i64 *B, int *wire, int *rebase,
                   i64 *KP, i64 *cap) {
    Core *c = (Core *)h;
    std::lock_guard<std::mutex> lk(c->qmu);
    if (c->queue.empty()) return 0;
    Launch &L = c->queue.front();
    *K = L.K; *R = L.R; *B = L.B; *wire = L.wire; *rebase = L.rebase;
    *KP = L.KP; *cap = L.cap;
    return 1;
}

// regular-descriptor metadata of the front launch (call between peek and
// take): returns 0 when the front launch is irregular
int wf_launch_peek_regular(void *h, i64 *cmax) {
    Core *c = (Core *)h;
    std::lock_guard<std::mutex> lk(c->qmu);
    if (c->queue.empty()) return 0;
    Launch &L = c->queue.front();
    if (!L.regular) return 0;
    *cmax = L.cmax;
    return 1;
}

// fills the per-key regular descriptors + per-window index map of the
// front launch (valid only when wf_launch_peek_regular returned 1)
void wf_launch_take_regular(void *h, int32_t *rcount, int32_t *rstart0,
                            int32_t *rlen, int32_t *widx) {
    Core *c = (Core *)h;
    std::lock_guard<std::mutex> lk(c->qmu);
    Launch &L = c->queue.front();
    std::memcpy(rcount, L.rcount.data(), (size_t)L.K * 4);
    std::memcpy(rstart0, L.rstart0.data(), (size_t)L.K * 4);
    std::memcpy(rlen, L.rlen.data(), (size_t)L.K * 4);
    if (L.B)
        std::memcpy(widx, L.widx.data(), (size_t)L.B * 4);
}

// one field's rectangle into the caller's buffer (padded when rows_pad>0)
static void take_block(Launch &L, int f, void *blk, i64 rows_pad,
                       i64 cols_pad) {
    const std::vector<u8> &src_v = f == 0 ? L.blk : L.xblk[(size_t)(f - 1)];
    const int wire = f == 0 ? L.wire : L.xwire[f];
    const i64 isz = 1LL << wire;
    if (rows_pad <= 0) {
        std::memcpy(blk, src_v.data(), (size_t)(L.K * L.R * isz));
        return;
    }
    // write straight into the caller's (rows_pad, cols_pad) rectangle,
    // zeroing the padding — saves the ship thread's _pad2 re-copy
    u8 *dst = (u8 *)blk;
    const u8 *src = src_v.data();
    const i64 rowb = L.R * isz, padb = cols_pad * isz;
    for (i64 r = 0; r < L.K; ++r) {
        std::memcpy(dst + r * padb, src + r * rowb, (size_t)rowb);
        std::memset(dst + r * padb + rowb, 0, (size_t)(padb - rowb));
    }
    std::memset(dst + L.K * padb, 0, (size_t)((rows_pad - L.K) * padb));
}

static void take_common(Launch &L, void *blk, i64 rows_pad,
                        i64 cols_pad, i64 *offs, int32_t *wrows,
                        int32_t *wstarts, int32_t *wlens, i64 *hkey,
                        i64 *hid, i64 *hts, i64 *hlen, i64 *hpmax,
                        i64 *hpmin) {
    take_block(L, 0, blk, rows_pad, cols_pad);
    std::memcpy(offs, L.offs.data(), (size_t)L.K * 8);
    if (L.B) {
        std::memcpy(wrows, L.wrows.data(), (size_t)L.B * 4);
        // callers on the regular path pass null: the per-window start/len
        // arrays are replaced by the compressed per-key descriptors
        if (wstarts) std::memcpy(wstarts, L.wstarts.data(), (size_t)L.B * 4);
        if (wlens) std::memcpy(wlens, L.wlens.data(), (size_t)L.B * 4);
        std::memcpy(hkey, L.hkey.data(), (size_t)L.B * 8);
        std::memcpy(hid, L.hid.data(), (size_t)L.B * 8);
        std::memcpy(hts, L.hts.data(), (size_t)L.B * 8);
        std::memcpy(hlen, L.hlen.data(), (size_t)L.B * 8);
        // callers with no host-side position-extremum stats pass null
        if (hpmax) std::memcpy(hpmax, L.hpmax.data(), (size_t)L.B * 8);
        if (hpmin) std::memcpy(hpmin, L.hpmin.data(), (size_t)L.B * 8);
    }
}

static Launch pop_front(Core *c) {
    // move the launch out under the lock; the (potentially multi-MB)
    // copies afterwards must not stall the producer's flush() push
    std::lock_guard<std::mutex> lk(c->qmu);
    Launch L = std::move(c->queue.front());
    c->queue.pop_front();
    return L;
}

void wf_launch_take(void *h, void *blk, i64 *offs, int32_t *wrows,
                    int32_t *wstarts, int32_t *wlens, i64 *hkey, i64 *hid,
                    i64 *hts, i64 *hlen) {
    Core *c = (Core *)h;
    Launch L = pop_front(c);
    take_common(L, blk, 0, 0, offs, wrows, wstarts, wlens,
                hkey, hid, hts, hlen, nullptr, nullptr);
}

// wf_launch_take writing blk into a zero-padded (rows_pad, cols_pad)
// rectangle ready for the device (the ship thread hands it to device_put
// with no further copy)
void wf_launch_take_padded(void *h, void *blk, i64 rows_pad, i64 cols_pad,
                           i64 *offs, int32_t *wrows, int32_t *wstarts,
                           int32_t *wlens, i64 *hkey, i64 *hid, i64 *hts,
                           i64 *hlen, i64 *hpmax, i64 *hpmin) {
    Core *c = (Core *)h;
    Launch L = pop_front(c);
    take_common(L, blk, rows_pad, cols_pad, offs, wrows, wstarts, wlens,
                hkey, hid, hts, hlen, hpmax, hpmin);
}

// per-field wire dtypes of the front launch (size n_fields; call between
// peek and take — the consumer allocates one rectangle per field)
int wf_launch_peek_wires(void *h, int *wires) {
    Core *c = (Core *)h;
    std::lock_guard<std::mutex> lk(c->qmu);
    if (c->queue.empty()) return 0;
    Launch &L = c->queue.front();
    wires[0] = L.wire;
    for (int f = 1; f < c->n_fields; ++f) wires[f] = L.xwire[f];
    return 1;
}

// multi-field wf_launch_take_padded: blks carries n_fields destination
// rectangles (same (rows_pad, cols_pad) geometry, each field's own wire
// dtype as reported by wf_launch_peek_wires)
void wf_launch_take_padded_f(void *h, void **blks, i64 rows_pad,
                             i64 cols_pad, i64 *offs, int32_t *wrows,
                             int32_t *wstarts, int32_t *wlens, i64 *hkey,
                             i64 *hid, i64 *hts, i64 *hlen, i64 *hpmax,
                             i64 *hpmin) {
    Core *c = (Core *)h;
    const int nf = c->n_fields;
    Launch L = pop_front(c);
    take_common(L, blks[0], rows_pad, cols_pad, offs, wrows, wstarts,
                wlens, hkey, hid, hts, hlen, hpmax, hpmin);
    for (int f = 1; f < nf; ++f)
        take_block(L, f, blks[f], rows_pad, cols_pad);
}

// ---------------------------------------------------------------- keymap
// First-appearance key->slot map + ordered-stream scan for the window
// emitters' per-batch bookkeeping (runtime/emitters.py KeyedStreamState,
// semantics of wf_nodes.hpp:104-121's out-of-order drop): one memory-speed
// pass replaces a binary-search slot lookup + stable argsort + segmented
// running max per batch, which together cost ~150 ms per 1M-row batch of
// pure host time on the pipe benchmark.  Layout mirrors Renumber: dense
// vector for small non-negative keys, hash map for the rest.
struct KeyMap {
    std::vector<i64> dense;  // key -> slot+1 (0 = unseen)
    std::unordered_map<i64, i64> sparse;
    i64 n_slots = 0;
};

void *wf_keymap_new() { return new KeyMap(); }
void wf_keymap_free(void *h) { delete (KeyMap *)h; }

// Map keys -> slots, registering unseen keys in first-appearance order
// (the same slot numbering SlotMap produces); returns the total slot
// count after registration so the caller can grow its slot-indexed
// buffers before the scan.
i64 wf_keymap_lookup(void *h, const i64 *keys, i64 n, i64 *slots) {
    KeyMap *m = (KeyMap *)h;
    for (i64 i = 0; i < n; ++i) {
        const i64 k = keys[i];
        i64 *e;
        if (k >= 0 && k < (1 << 20)) {
            if ((i64)m->dense.size() <= k)
                m->dense.resize((size_t)(k + 1), 0);
            e = &m->dense[(size_t)k];
        } else {
            e = &m->sparse[k];
        }
        if (!*e) *e = ++m->n_slots;
        slots[i] = *e - 1;
    }
    return m->n_slots;
}

// In-order scan over (slots, pos): returns 1 when every row's pos is >=
// its slot's running last position (batch-internal predecessors
// included) — the emitter's in-order fast path.  Fills the per-slot
// last-occurrence index for the last-row capture:
//   touched[0..*n_touched) = slots seen in this batch
//   last_idx[s] = index of slot s's LAST row in this batch
// The caller passes last_idx pre-filled with -1 and must reset the
// touched entries afterwards; last_pos is read-only here (on return 0
// the caller runs the general drop path against unchanged state).
i64 wf_keyscan_ordered(const i64 *slots, const i64 *pos, i64 n,
                       const i64 *last_pos, i64 *last_idx,
                       i64 *touched, i64 *n_touched) {
    i64 ok = 1, nt = 0;
    for (i64 i = 0; i < n; ++i) {
        const i64 s = slots[i];
        const i64 li = last_idx[s];
        if (li < 0) {
            touched[nt++] = s;
            if (pos[i] < last_pos[s]) ok = 0;
        } else if (pos[i] < pos[li]) {
            ok = 0;
        }
        last_idx[s] = i;
    }
    *n_touched = nt;
    return ok;
}

// ---------------------------------------------------------------- state ABI
// Exactly-once checkpoint / keyed-migration support (docs/ROBUSTNESS.md
// "Native state ABI").  Blobs are flat little-endian i64 streams: a tagged
// header (magic, ABI version, config echo) followed by per-key records —
// the archive rows still needed by future windows plus the window/ordering
// counters.  Export REQUIRES a drained core (no pending rows, no pending
// fired windows, empty launch queue): the Python barrier protocol
// force-flushes and drains first, so device ring contents never cross the
// ABI — import zeroes the ring geometry (cap = 0) and the next flush
// rebases, re-shipping every live row from the imported archives exactly
// like the no-ring-snapshot restore path of the Python resident core.
//
// kStateAbiVersion stamps every blob and is exposed via wf_abi_version();
// tests compare it against the source constant to catch a stale .so.

static const i64 kStateAbiVersion = 1;
static const i64 kStateMagicCore = 0x57464E5354415445LL;  // "WFNSTATE"
static const i64 kStateMagicKey = 0x57464E534B455931LL;   // "WFNSKEY1"

i64 wf_abi_version(void) { return kStateAbiVersion; }

namespace {

struct StateWr {
    u8 *p;
    const u8 *end;
    bool ok = true;
    void put(i64 v) {
        if (p + 8 > end) { ok = false; return; }
        std::memcpy(p, &v, 8);
        p += 8;
    }
    void put_arr(const i64 *a, size_t n) {
        if (n == 0) return;
        if (p + 8 * n > end) { ok = false; return; }
        std::memcpy(p, a, n * 8);
        p += n * 8;
    }
};

struct StateRd {
    const u8 *p;
    const u8 *end;
    bool ok = true;
    i64 get() {
        if (p + 8 > end) { ok = false; return 0; }
        i64 v;
        std::memcpy(&v, p, 8);
        p += 8;
        return v;
    }
    bool get_arr(i64 *a, size_t n) {
        if (n == 0) return true;
        if (p + 8 * n > end) { ok = false; return false; }
        std::memcpy(a, p, n * 8);
        p += n * 8;
        return true;
    }
};

// export/import precondition: everything the core buffers between the
// append path and the device has been flushed and shipped.  pend_rows == 0
// also implies launched == appended for every key (each append bumps
// pend_rows; only flush() clears it, setting launched = appended).
inline bool core_drained(Core *c) {
    if (c->pend_rows != 0 || !c->wrow.empty()) return false;
    std::lock_guard<std::mutex> lk(c->qmu);
    return c->queue.empty();
}

inline int find_row(Core *c, i64 key) {
    if (key >= 0 && key < (i64)c->direct.size())
        return c->direct[(size_t)key];
    auto it = c->rowmap.find(key);
    return it == c->rowmap.end() ? -1 : it->second;
}

inline i64 key_rec_i64s(const Core *c, const KeyState &st) {
    return 11 + (i64)st.live() * (2 + c->n_fields);
}

void export_key(const Core *c, const KeyState &st, i64 key, StateWr &w) {
    const i64 L = (i64)st.live();
    w.put(key);
    w.put(st.appended);
    w.put(st.last_pos);
    w.put(st.initial_id);
    w.put(st.first_gwid);
    w.put(st.next_lwid);
    w.put(st.n_fired);
    w.put(st.emit_counter);
    w.put(st.marker_pos);
    w.put(st.marker_ts);
    w.put(L);
    w.put_arr(st.pos.data() + st.start, (size_t)L);
    w.put_arr(st.ts.data() + st.start, (size_t)L);
    w.put_arr(st.val.data() + st.start, (size_t)L);
    for (int f = 1; f < c->n_fields; ++f)
        w.put_arr(st.xval[(size_t)(f - 1)].data() + st.start, (size_t)L);
}

bool import_key(Core *c, StateRd &r) {
    const i64 key = r.get();
    const i64 appended = r.get(), last_pos = r.get();
    const i64 initial_id = r.get(), first_gwid = r.get();
    const i64 next_lwid = r.get(), n_fired = r.get();
    const i64 emit_counter = r.get(), marker_pos = r.get();
    const i64 marker_ts = r.get();
    const i64 L = r.get();
    if (!r.ok || L < 0 || appended < L) return false;
    KeyState &st = c->state(key);
    if (!st.neutral && !(st.appended == 0 && st.n_fired == 0
                         && st.last_pos <= NEG_INF))
        return false;   // live state on the importing side: refuse
    st.pos.assign((size_t)L, 0);
    st.ts.assign((size_t)L, 0);
    st.val.assign((size_t)L, 0);
    if (!r.get_arr(st.pos.data(), (size_t)L)) return false;
    if (!r.get_arr(st.ts.data(), (size_t)L)) return false;
    if (!r.get_arr(st.val.data(), (size_t)L)) return false;
    for (int f = 1; f < c->n_fields; ++f) {
        auto &xv = st.xval[(size_t)(f - 1)];
        xv.assign((size_t)L, 0);
        if (!r.get_arr(xv.data(), (size_t)L)) return false;
    }
    st.start = 0;
    st.appended = appended;
    st.last_pos = last_pos;
    st.initial_id = initial_id;
    st.first_gwid = first_gwid;
    st.next_lwid = next_lwid;
    st.n_fired = n_fired;
    st.emit_counter = emit_counter;
    st.marker_pos = marker_pos;
    st.marker_ts = marker_ts;
    st.purge_pos = NEG_INF;
    st.pend_any = false;
    st.neutral = false;
    // nothing of this key is in any ring (the caller zeroes cap so the
    // next flush rebases and re-ships the live rows)
    st.launched = st.ring_base = appended - L;
    st.next_create = st.initial_id + st.next_lwid * c->slide;
    st.fire_pos = st.initial_id + st.n_fired * c->slide + c->win;
    return true;
}

}  // namespace

// Whole-core blob: header (magic, abi, win, slide, kind, role, n_fields,
// room_mult, launches_made, n_keys) + one record per non-neutral key.
// Size/export return -1 when the core is not drained.
i64 wf_core_state_size(void *h) {
    Core *c = (Core *)h;
    if (!core_drained(c)) return -1;
    i64 n = 10;
    for (auto &st : c->keys)
        if (!st.neutral) n += key_rec_i64s(c, st);
    return n * 8;
}

i64 wf_core_state_export(void *h, void *buf, i64 cap) {
    Core *c = (Core *)h;
    if (!core_drained(c)) return -1;
    StateWr w{(u8 *)buf, (const u8 *)buf + cap};
    w.put(kStateMagicCore);
    w.put(kStateAbiVersion);
    w.put(c->win);
    w.put(c->slide);
    w.put((i64)c->kind);
    w.put((i64)c->role);
    w.put((i64)c->n_fields);
    w.put(c->room_mult);
    w.put(c->launches_made);
    i64 nk = 0;
    for (auto &st : c->keys)
        if (!st.neutral) ++nk;
    w.put(nk);
    for (size_t r = 0; r < c->keys.size(); ++r) {
        if (c->keys[r].neutral) continue;
        export_key(c, c->keys[r], c->rowkey[r], w);
    }
    if (!w.ok) return -1;
    return (i64)(w.p - (u8 *)buf);
}

// Import requires a FRESH core (same wf_core_new config, no keys, empty
// queue) — restore builds new handles rather than scrubbing live ones.
// Returns 0 on success; negative codes name the refusal (-2 not fresh,
// -3 bad magic, -4 ABI version mismatch, -5 config echo mismatch,
// -6 truncated/invalid records).
i64 wf_core_state_import(void *h, const void *buf, i64 nbytes) {
    Core *c = (Core *)h;
    if (!c->keys.empty() || c->pend_rows != 0) return -2;
    {
        std::lock_guard<std::mutex> lk(c->qmu);
        if (!c->queue.empty()) return -2;
    }
    StateRd r{(const u8 *)buf, (const u8 *)buf + nbytes};
    if (r.get() != kStateMagicCore) return -3;
    if (r.get() != kStateAbiVersion) return -4;
    if (r.get() != c->win || r.get() != c->slide
        || r.get() != (i64)c->kind || r.get() != (i64)c->role
        || r.get() != (i64)c->n_fields)
        return -5;
    c->room_mult = r.get();
    c->launches_made = r.get();
    const i64 nk = r.get();
    if (!r.ok || nk < 0) return -6;
    for (i64 i = 0; i < nk; ++i)
        if (!import_key(c, r)) return -6;
    // ring geometry resets: the next flush rebases and re-ships every
    // live row from the imported archives (device state never crosses)
    c->KP = 0;
    c->cap = 0;
    return 0;
}

// -- per-key variants (control-plane keyed migration) -----------------------

i64 wf_core_key_count(void *h) {
    Core *c = (Core *)h;
    i64 n = 0;
    for (auto &st : c->keys)
        if (!st.neutral) ++n;
    return n;
}

i64 wf_core_key_list(void *h, i64 *out, i64 cap) {
    Core *c = (Core *)h;
    i64 n = 0;
    for (size_t r = 0; r < c->keys.size(); ++r) {
        if (c->keys[r].neutral) continue;
        if (n < cap) out[n] = c->rowkey[r];
        ++n;
    }
    return n;
}

i64 wf_core_key_state_size(void *h, i64 key) {
    Core *c = (Core *)h;
    if (!core_drained(c)) return -1;
    const int row = find_row(c, key);
    if (row < 0 || c->keys[(size_t)row].neutral) return -2;
    return (3 + key_rec_i64s(c, c->keys[(size_t)row])) * 8;
}

i64 wf_core_key_export(void *h, i64 key, void *buf, i64 cap) {
    Core *c = (Core *)h;
    if (!core_drained(c)) return -1;
    const int row = find_row(c, key);
    if (row < 0 || c->keys[(size_t)row].neutral) return -2;
    StateWr w{(u8 *)buf, (const u8 *)buf + cap};
    w.put(kStateMagicKey);
    w.put(kStateAbiVersion);
    w.put((i64)c->n_fields);
    export_key(c, c->keys[(size_t)row], key, w);
    if (!w.ok) return -1;
    return (i64)(w.p - (u8 *)buf);
}

// Move semantics for migration: after exporting, the old owner
// neutralizes the key — archives and counters reset to fresh-registration
// values, eos()/export skip it — so the migrated key's windows are never
// emitted twice.  The dense row stays registered (launch descriptors
// index rows by position).
i64 wf_core_key_neutralize(void *h, i64 key) {
    Core *c = (Core *)h;
    if (!core_drained(c)) return -1;
    const int row = find_row(c, key);
    if (row < 0) return -2;
    KeyState &st = c->keys[(size_t)row];
    st.pos.clear();
    st.ts.clear();
    st.val.clear();
    for (auto &xv : st.xval) xv.clear();
    st.start = 0;
    st.appended = st.launched = st.ring_base = 0;
    st.last_pos = NEG_INF;
    st.next_lwid = st.n_fired = 0;
    st.emit_counter = (c->role == MAP) ? c->map_idx0 : 0;
    st.marker_pos = NEG_INF;
    st.marker_ts = 0;
    st.purge_pos = NEG_INF;
    st.pend_any = false;
    st.next_create = st.initial_id;
    st.fire_pos = st.initial_id + c->win;
    st.neutral = true;
    return 0;
}

i64 wf_core_key_import(void *h, const void *buf, i64 nbytes) {
    Core *c = (Core *)h;
    if (!core_drained(c)) return -1;
    StateRd r{(const u8 *)buf, (const u8 *)buf + nbytes};
    if (r.get() != kStateMagicKey) return -3;
    if (r.get() != kStateAbiVersion) return -4;
    if (r.get() != (i64)c->n_fields) return -5;
    if (!r.ok || !import_key(c, r)) return -6;
    // the imported rows are in no ring: force a rebase at the next flush
    c->KP = 0;
    c->cap = 0;
    return 0;
}

}  // extern "C"
