"""Headline benchmark — windowed-sum throughput on the device path.

The TPU equivalent of the reference's ``src/sum_test_gpu`` workload
(win_seq_gpu.hpp:309-530: count-based sliding-window sum, micro-batched onto
the device): a deterministic multi-key integer stream is pushed through
``WinSeqTPU`` (archive staging -> batched XLA window evaluation -> async
launches), and we report end-to-end *input tuples per second* including all
host bookkeeping, exactly the metric the reference's self-timing tests print
(`sum_cb.hpp` totalsum runs / `test_ysb_kf.cpp:113`).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

The reference publishes no numbers (BASELINE.md); ``BASELINE_TUPLES_PER_SEC``
is the V100-class bar from BASELINE.json's north star ("＞=1.5x the repo's
V100 tuples/sec"); vs_baseline >= 1.5 is the target.

Derivation of the 20M proxy (the reference ships no benchmark results, so
this is an engineering estimate, load-bearing only as a fixed yardstick):
the reference's GPU path is *host-throughput-bound*, not kernel-bound —
every tuple is processed one at a time by Win_Seq_GPU::svc on the CPU
(win_seq_gpu.hpp:309-530: per-tuple extract, key map lookup, triggerer
arithmetic), and the CUDA work is a trivial sum kernel behind a per-batch
BLOCKING cudaStreamSynchronize (:481).  A per-tuple C++ hot loop of that
shape sustains tens of ns/tuple on one core (~56 ns/tuple measured for our
own richer C++ loop, BASELINE.md wire-budget note), i.e. ~15-30M tuples/s
per worker; 20M is the midpoint, taken as the single-worker V100-host
figure.  The number's role is a STABLE denominator across rounds, not a
measured V100 datum — absolute vs_baseline should be read with that bar.
"""

import glob
import json
import os
import sys
import time

import numpy as np

BASELINE_TUPLES_PER_SEC = 20e6

# workload shape: CB sliding windows, the sum_test_gpu default regime
N_KEYS = 64
N_TUPLES = 16_000_000         # total stream length across keys
WIN, SLIDE = 256, 64
BATCH_LEN = 1 << 15           # fired-window flush trigger (row trigger first)
FLUSH_ROWS = 1 << 19          # rows per fused device dispatch (finer
                              # granularity pipelines through wire stalls)
CHUNK = 1 << 20               # stream batch (rows per engine message)


def derived_good_launch_ms(default: float = 130.0) -> float:
    """Good-weather band edge from the recorded bench history: the 25th
    percentile of every per-run ``mean_launch_ms`` in the driver's
    BENCH_r0*.json artifacts (the weather the tunnel actually delivers
    at its best), replacing the hard-coded 130 ms constant of one
    session (VERDICT r4 weak #1).  Falls back to the constant when no
    history is on disk (fresh checkout)."""
    vals = []
    for p in sorted(glob.glob(os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "BENCH_r*.json"))):
        try:
            with open(p) as f:
                parsed = json.load(f).get("parsed") or {}
            for r in parsed.get("runs", []):
                v = r.get("mean_launch_ms")
                if v:
                    vals.append(float(v))
        except Exception:
            continue
    if len(vals) < 5:
        return default
    vals.sort()
    return max(vals[len(vals) // 4], 60.0)


def relation_check(runs):
    """Self-normalization against the recorded weather relation
    (scripts/weather_relation.py): fit T(L) = T_host + k*L over the
    on-disk current-stack history, then report what this session's
    measured launch service predicts vs what it scored.  A capture whose
    residual is near zero is explained by its weather; a large positive
    residual would flag a framework regression no single-session score
    can show.  Empty dict when history is too thin for the fit."""
    try:
        sys.path.insert(0, os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "scripts"))
        from weather_relation import load_runs
        hist = load_runs(os.path.dirname(os.path.abspath(__file__)))
        if len(hist) < 8 or not runs:
            return {}
        L = np.array([r["mean_launch_ms"] for r in hist]) / 1e3
        T = N_TUPLES / np.array([r["tps"] for r in hist])
        A = np.stack([np.ones_like(L), L], axis=1)
        (t_host, k), *_ = np.linalg.lstsq(A, T, rcond=None)
        import statistics
        med_l = statistics.median(
            (r.get("mean_launch_ms") or 0.0) for r in runs) / 1e3
        med_t = statistics.median(N_TUPLES / r["tps"] for r in runs)
        pred_t = float(t_host + k * med_l)
        return {
            "relation_predicted_median_tps": round(N_TUPLES / pred_t, 1),
            "relation_residual_s": round(med_t - pred_t, 3),
            "relation_fit": {"t_host_s": round(float(t_host), 3),
                             "k": round(float(k), 2),
                             "n_history_runs": len(hist)},
        }
    except Exception:  # diagnostic only — never cost the capture
        return {}


def probe_pallas():
    """One tiny Pallas windowed-reduce launch on the default device:
    (ok, error).  The kernel is kept behind the XLA-gather fallback
    while the toolchain rejects it (_PALLAS_BROKEN, ops/device.py); this
    probe runs once per bench session so the artifact of record notices
    the day a fixed toolchain lands (VERDICT r4 item 7)."""
    try:
        import jax.numpy as jnp
        from windflow_tpu.ops.pallas_kernels import windowed_reduce_pallas
        flat = jnp.arange(256, dtype=jnp.int32)
        starts = jnp.arange(0, 64, 8, dtype=jnp.int32)
        lens = jnp.full(8, 8, dtype=jnp.int32)
        out = np.asarray(windowed_reduce_pallas(flat, starts, lens,
                                                pad=8, op="sum"))
        want = np.add.reduceat(np.arange(256, dtype=np.int64)[:64],
                               np.arange(0, 64, 8))
        if not np.array_equal(out[:8].astype(np.int64), want):
            return False, f"wrong values: {out[:8].tolist()}"
        return True, None
    except Exception as e:  # noqa: BLE001 — the probe IS the handler
        return False, f"{type(e).__name__}: {e}"


def make_stream(schema):
    """Deterministic per-key-ordered integer stream (sum_cb.hpp:89-117)."""
    from windflow_tpu.core.tuples import batch_from_columns
    per_key = N_TUPLES // N_KEYS
    batches = []
    rng = np.random.default_rng(7)
    for lo in range(0, per_key, CHUNK // N_KEYS):
        m = min(CHUNK // N_KEYS, per_key - lo)
        ids = np.repeat(np.arange(lo, lo + m), N_KEYS)
        keys = np.tile(np.arange(N_KEYS), m)
        vals = rng.integers(0, 100, size=m * N_KEYS).astype(np.int64)
        batches.append(batch_from_columns(
            schema, key=keys, id=ids, ts=ids, value=vals))
    return batches


def run_once(batches, schema, host_core=False):
    from windflow_tpu.core.windows import WinType
    from windflow_tpu.ops import resident
    from windflow_tpu.ops.functions import Reducer
    from windflow_tpu.patterns.basic import Sink, Source
    from windflow_tpu.patterns.win_seq import WinSeq
    from windflow_tpu.patterns.win_seq_tpu import WinSeqTPU
    from windflow_tpu.runtime.engine import Dataflow
    from windflow_tpu.runtime.farm import build_pipeline

    n_out = [0]
    total = [0]

    def consume(rows):
        if rows is not None and len(rows):
            n_out[0] += len(rows)
            total[0] += int(rows["value"].sum())

    if host_core:
        # control: the identical workload on the host window core — the
        # framework's floor with ZERO wire in the path, so a capture
        # whose device number sits under it is provably wire-bound
        stage = WinSeq(Reducer("sum"), WIN, SLIDE, WinType.CB)
    else:
        # shards=1: the bench host exposes ONE cpu core (nproc=1), so the
        # key-sharded MT pool buys no parallelism and each extra shard
        # costs a scan pass + smaller launches (sweep 2026-07-30:
        # 1/2/4 shards -> 20.6/15.0/12.8M best-of tps); multi-core hosts
        # should raise shards to ~cores
        # depth=48 + dispatch window 8 (native_core default): the
        # 2026-07-31 interleaved sweeps (scripts/sweep_window.py) measured
        # median 22.8M vs 20.7M at the r3 depth=24/window=4 in the same
        # weather — deeper in-flight pipelining hides more of the
        # per-dispatch RTT without upsizing any dispatch
        stage = WinSeqTPU(Reducer("sum", value_range=(0, 100)), WIN, SLIDE,
                          WinType.CB, batch_len=BATCH_LEN,
                          flush_rows=FLUSH_ROWS, depth=48, shards=1)
    df = Dataflow()
    build_pipeline(df, [
        Source(batches=batches, schema=schema),
        stage,
        Sink(consume, vectorized=True)])
    resident.stats_snapshot(reset=True)
    t0 = time.perf_counter()
    df.run_and_wait_end()
    dt = time.perf_counter() - t0
    # per-run wire diagnostics: a weather-trashed capture (few huge
    # mean_launch_ms, coalesced dispatches) must be distinguishable from a
    # framework regression in the artifact of record (VERDICT r2)
    diag = resident.stats_snapshot(reset=True)
    return dt, n_out[0], total[0], diag


def expected_total(batches) -> int:
    """Host oracle: sum of all complete-window sums, via per-key cumsum."""
    vals = np.concatenate([b["value"] for b in batches])
    keys = np.concatenate([b["key"] for b in batches])
    total = 0
    for k in range(N_KEYS):
        v = vals[keys == k]
        if not len(v):
            continue
        c = np.concatenate([[0], np.cumsum(v)])
        # every *opened* window fires: complete ones on the fly, partial
        # trailing ones at EOS (win_seq.hpp:433-474 flush semantics)
        n_wins = (len(v) - 1) // SLIDE + 1
        starts = np.arange(n_wins) * SLIDE
        total += int(np.sum(c[np.minimum(starts + WIN, len(v))] - c[starts]))
    return total


def main():
    from windflow_tpu.core.tuples import Schema
    schema = Schema(value=np.int64)
    batches = make_stream(schema)

    # full warmup run: compiles every (pad, N) bucket the timed run will hit
    # (executables are cached process-wide across pattern instances) ...
    run_once(batches, schema)
    # ... then the deep-coalescing shape ladder: merged {2x..16x} dispatch
    # buckets only occur under wire stall, exactly when a cold ~10 s
    # mid-run compile would wreck the run that needs the merge — compile
    # them now, deterministically, whatever the warmup weather was
    from windflow_tpu.ops.resident import prewarm_regular_ladder
    prewarm_regular_ladder()

    pallas_ok, pallas_err = probe_pallas()

    # best-of timed runs: the tunneled devices show large run-to-run
    # variance (BASELINE.md wire characterization: ±2x swings), and peak
    # throughput is the capability being measured.  At least 5 runs;
    # sampling extends — up to 12 runs or a 6-minute wall budget — only
    # on measured WIRE WEATHER (median per-run launch service above 2x
    # the good-weather band), never on the score: extending while
    # best < bar is optional stopping that inflates P(best >= bar) in
    # exactly the marginal sessions (VERDICT r3 weak #1).  The fixed
    # best-of-5 is always reported alongside so rounds stay comparable.
    GOOD_LAUNCH_MS = derived_good_launch_ms()   # 25th pct of recorded
    #                          BENCH_r0*.json history (exogenous to the
    #                          score by construction; 130 ms fallback)
    want = expected_total(batches)
    best_dt, n_windows = None, 0
    runs = []
    import statistics
    t_bench0 = time.perf_counter()
    while True:
        dt, n_windows, total, diag = run_once(batches, schema)
        if total != want:
            print(json.dumps({
                "metric": "sum_test_tpu FAILED correctness check",
                "value": 0, "unit": "tuples/sec", "vs_baseline": 0.0}))
            print(f"windowed-sum total {total} != oracle {want}",
                  file=sys.stderr)
            return 1
        runs.append({"tps": round(N_TUPLES / dt, 1), **diag})
        best_dt = dt if best_dt is None else min(best_dt, dt)
        if len(runs) >= 5:
            stalled = statistics.median(
                r.get("mean_launch_ms") or 0.0 for r in runs
            ) > 2 * GOOD_LAUNCH_MS
            if (not stalled or len(runs) >= 12
                    or time.perf_counter() - t_bench0 > 360):
                break
    tps = N_TUPLES / best_dt
    best5 = max(r["tps"] for r in runs[:5])
    med = round(statistics.median(r["tps"] for r in runs), 1)
    # host-core control (no wire): same stream, same window math on the
    # host core.  When the device number undercuts it, the reader can
    # attribute the gap to the wire service the per-run diagnostics
    # quantify — the framework itself is at least this fast.  The control
    # is a DIAGNOSTIC: it must never destroy the five completed device
    # measurements (crash) nor silently swallow a host-path wrongness —
    # failures are recorded loudly in their own field.
    host_err = None
    host_tps = 0.0
    try:
        hdt, _n, htotal, _d = run_once(batches, schema, host_core=True)
        if htotal == want:
            host_tps = N_TUPLES / hdt
        else:
            host_err = f"host-core total {htotal} != oracle {want}"
    except Exception as e:  # noqa: BLE001 — diagnostic only
        host_err = f"{type(e).__name__}: {e}"
    if host_err:
        print(f"host-core control failed: {host_err}", file=sys.stderr)
    # second control: the C++ bookkeeping + launch staging ALONE (queue
    # never shipped) on the same stream — the device path's HOST-side
    # ceiling on this box.  A capture whose device number approaches this
    # is host-bound, not wire-bound: on the 1-core bench host the ship
    # thread, engine and bookkeeping share one core, so this bound —
    # not the wire — is what caps vs_baseline (measured r4: the 30M
    # north star sits above it; see BASELINE.md round 4)
    host_loop_tps = 0.0
    try:
        from windflow_tpu import native as _nat
        _lib = _nat.load()
        if _lib is not None:
            import ctypes
            b0 = batches[0]
            f = b0.dtype.fields
            offs = (b0.dtype.itemsize, f["key"][1], f["id"][1], f["ts"][1],
                    f["marker"][1], f["value"][1])
            h = _lib.wf_core_new(WIN, SLIDE, 0, 0, 0, 1, SLIDE, 0, 1,
                                 SLIDE, 0, 1, SLIDE, BATCH_LEN, FLUSH_ROWS,
                                 3)
            p32 = ctypes.POINTER(ctypes.c_int32)
            p64 = ctypes.POINTER(ctypes.c_longlong)

            def drain():
                # pop + discard staged launches each chunk: the take/fill
                # cost is part of the device path's host side (so the
                # bound gets MORE representative), and the queue never
                # accumulates the whole stream's staged blocks
                K = ctypes.c_longlong()
                R = ctypes.c_longlong()
                B = ctypes.c_longlong()
                KP = ctypes.c_longlong()
                cap = ctypes.c_longlong()
                wire = ctypes.c_int()
                rebase = ctypes.c_int()
                while _lib.wf_launch_peek(
                        h, ctypes.byref(K), ctypes.byref(R),
                        ctypes.byref(B), ctypes.byref(wire),
                        ctypes.byref(rebase), ctypes.byref(KP),
                        ctypes.byref(cap)):
                    Bn = max(B.value, 1)
                    blk = np.empty(
                        (KP.value, max(R.value, 1)),
                        dtype=(np.int8, np.int16, np.int32,
                               np.int64)[wire.value])
                    o8 = np.empty(K.value, dtype=np.int64)
                    w32 = np.empty(Bn, dtype=np.int32)
                    s32 = np.empty(Bn, dtype=np.int32)
                    l32 = np.empty(Bn, dtype=np.int32)
                    h64 = np.empty(Bn, dtype=np.int64)
                    _lib.wf_launch_take_padded(
                        h, blk.ctypes.data_as(ctypes.c_void_p), KP.value,
                        blk.shape[1], o8.ctypes.data_as(p64),
                        w32.ctypes.data_as(p32), s32.ctypes.data_as(p32),
                        l32.ctypes.data_as(p32), h64.ctypes.data_as(p64),
                        h64.ctypes.data_as(p64), h64.ctypes.data_as(p64),
                        h64.ctypes.data_as(p64), None, None)

            try:
                t0 = time.perf_counter()
                for b in batches:
                    _lib.wf_core_process(h, b.ctypes.data, len(b), *offs)
                    drain()
                host_loop_tps = N_TUPLES / (time.perf_counter() - t0)
            finally:
                _lib.wf_core_free(h)
    except Exception as e:  # noqa: BLE001 — diagnostic only
        print(f"host-loop control failed: {e}", file=sys.stderr)
    print(json.dumps({
        "metric": "sum_test_tpu CB windowed-sum input tuples/sec "
                  f"(win={WIN} slide={SLIDE} keys={N_KEYS} "
                  f"flush_rows={FLUSH_ROWS}, {n_windows} windows)",
        "value": round(tps, 1),
        "unit": "tuples/sec",
        "vs_baseline": round(tps / BASELINE_TUPLES_PER_SEC, 3),
        # wire diagnostics per timed run: dispatches ~= launches - merges;
        # mean_launch_ms is dispatch->result-ready wall time.  A capture
        # with mean_launch_ms >> 20 and dispatches << launches was wire-
        # stalled (tunnel weather), not framework-bound: judge the value
        # against median_tps and the per-run spread
        "median_tps": med,
        # the fixed symmetric draw, reported ALWAYS: best of the first 5
        # runs regardless of any extension, so rounds with and without
        # weather-extended sampling compare like for like
        "best5_tps": round(best5, 1),
        "vs_baseline_best5": round(best5 / BASELINE_TUPLES_PER_SEC, 3),
        "host_core_tps": round(host_tps, 1),
        "host_loop_tps": round(host_loop_tps, 1),
        **({"host_core_error": host_err} if host_err else {}),
        # the sampling rule is part of the artifact: extension triggers on
        # measured wire weather (exogenous), never on the score
        "n_runs": len(runs),
        "good_launch_ms": round(GOOD_LAUNCH_MS, 1),
        "sampling": "best-of: >=5 runs, extends to <=12 (6 min wall) "
                    f"while median mean_launch_ms > {2 * GOOD_LAUNCH_MS:.0f}"
                    " (2x good-weather band, 25th pct of BENCH_r* history);"
                    " best5_tps is the fixed best-of-5",
        "pallas_ok": pallas_ok,
        **({"pallas_error": pallas_err} if pallas_err else {}),
        # the capture judges itself against the recorded weather
        # relation: near-zero residual = score explained by the wire
        **relation_check(runs),
        "runs": runs,
    }))
    return 0


if __name__ == "__main__":
    try:
        sys.exit(main())
    except Exception as e:  # the driver needs a JSON line even on failure
        import traceback
        traceback.print_exc()
        print(json.dumps({
            "metric": f"sum_test_tpu CRASHED: {type(e).__name__}: {e}",
            "value": 0, "unit": "tuples/sec", "vs_baseline": 0.0}))
        sys.exit(1)
