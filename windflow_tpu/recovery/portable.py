"""Portable checkpoints — self-describing export/import of a sealed
epoch (docs/ROBUSTNESS.md "Cross-host recovery").

A :class:`~windflow_tpu.recovery.store.CheckpointStore` epoch is local
by construction: pickle blobs plus a manifest in one process's
``checkpoint_dir``.  This module makes a sealed epoch *portable*:

* :func:`export_header` builds the versioned portable header — the
  store manifest plus ``{"v": PORTABLE_VERSION, "origin": pid}`` and a
  CRC32 per blob (recorded at :meth:`CheckpointStore.save_blob` time,
  or computed here for pre-CRC manifests), so the receiving side can
  verify every byte without unpickling anything;
* :func:`ship_checkpoint` streams header + blobs + commit over a
  :class:`~windflow_tpu.parallel.channel.RowSender` as the ``-7``
  portable-checkpoint wire family (the ``-4``/``-5``/``-6`` control
  idiom), riding the existing row plane — no extra port, no sidecar
  protocol;
* :class:`PortableSpool` is the receiving half (a ``RowReceiver``'s
  ``ckpt_sink=``): it verifies version + CRC per frame and lands each
  peer's epochs under ``<root>/peer_<origin>/epoch_NNNNNN`` in the
  exact CheckpointStore layout — so a successor restores a dead peer's
  nodes with the ordinary ``latest_complete()/load()`` recipe.

Blobs ride through OPAQUE: a pickle of host state and PR 17's flat
native state blobs ship byte-identically — portability is framing +
integrity, never re-encoding.  Version skew is refused at the header
(:class:`PortableSkew`): a spool never guesses at a future layout.
"""

from __future__ import annotations

import json
import os
import re
import time
import zlib

from .store import CheckpointStore, _EPOCH_DIR, _safe_id  # noqa: F401

#: bump when the header/frame layout changes; a spool REFUSES other
#: versions (PortableSkew) instead of mis-parsing them
PORTABLE_VERSION = 1

_PEER_DIR = re.compile(r"^peer_(.+)$")


class PortableSkew(RuntimeError):
    """Portable header from an incompatible layout version — refused
    outright (shipping continues to other, same-version peers)."""


def blob_crc(data: bytes) -> int:
    return zlib.crc32(data) & 0xFFFFFFFF


def export_header(store: CheckpointStore, epoch: int,
                  origin=None) -> dict:
    """The self-describing portable header for one sealed epoch of
    ``store``: the manifest's node map with a guaranteed ``crc`` per
    blob (computed from disk when the manifest predates CRC recording),
    under a version + origin envelope."""
    path = os.path.join(store._epoch_dir(epoch), "MANIFEST.json")
    with open(path) as f:
        manifest = json.load(f)
    nodes = {}
    for safe, meta in manifest.get("nodes", {}).items():
        meta = dict(meta)
        if "bytes" in meta and meta.get("crc") is None:
            with open(os.path.join(store._epoch_dir(epoch),
                                   f"{safe}.ckpt"), "rb") as f:
                meta["crc"] = blob_crc(f.read())
        nodes[safe] = meta
    return {"v": PORTABLE_VERSION, "origin": origin, "epoch": int(epoch),
            "t": manifest.get("t"), "partial": manifest.get("partial",
                                                            False),
            "nodes": nodes}


def iter_blobs(store: CheckpointStore, epoch: int, header: dict):
    """Yield ``(meta, raw)`` per non-skipped node of the header —
    ``meta`` is the blob's wire envelope (origin/epoch/node/bytes/crc),
    ``raw`` the exact on-disk bytes."""
    for safe, m in header["nodes"].items():
        if "bytes" not in m:
            continue
        with open(os.path.join(store._epoch_dir(epoch),
                               f"{safe}.ckpt"), "rb") as f:
            raw = f.read()
        yield ({"origin": header["origin"], "epoch": header["epoch"],
                "node": safe, "bytes": len(raw),
                "crc": blob_crc(raw)}, raw)


def ship_checkpoint(sender, store: CheckpointStore, epoch: int,
                    origin=None) -> int:
    """Stream one sealed epoch to a peer over its row-plane sender
    (``RowSender.send_ckpt``); returns the bytes shipped.  Idempotent
    on the receiving spool (re-ships of a landed epoch overwrite it
    bit-identically), so callers simply retry at the next seal when a
    ship raises mid-way."""
    header = export_header(store, epoch, origin=origin)
    return sender.send_ckpt(header, iter_blobs(store, epoch, header))


class PortableSpool:
    """Receiver-side landing zone for ``-7`` portable-checkpoint frames
    (a ``RowReceiver(ckpt_sink=...)``).

    Layout: ``<root>/peer_<origin>/epoch_NNNNNN/<node>.ckpt`` +
    ``MANIFEST.json`` — the CheckpointStore layout per peer, manifest
    written LAST via tmp + rename, so :meth:`store_for` hands back an
    ordinary (read-only) store and :meth:`latest` is exactly
    ``latest_complete()``.  Every blob frame is CRC-verified before the
    rename; a mismatch raises (the connection's read loop surfaces it
    like any torn frame) and the epoch stays unsealed — torn spools are
    invisible to restore, never half-trusted.

    Frames for one origin arrive serially on that sender's connection
    thread; distinct origins land in distinct directories — no locking
    needed.
    """

    def __init__(self, root: str, retain: int = 2, metrics=None,
                 events=None):
        self.root = root
        self.retain = int(retain)
        self._metrics = metrics
        self._events = events
        #: (origin, epoch) -> pending header, staged at offer() and
        #: consumed at commit()
        self._pending: dict = {}
        os.makedirs(root, exist_ok=True)

    # ------------------------------------------------------------ sink API

    def _peer_dir(self, origin) -> str:
        return os.path.join(self.root, f"peer_{_safe_id(str(origin))}")

    def _epoch_dir(self, origin, epoch: int) -> str:
        return os.path.join(self._peer_dir(origin),
                            f"epoch_{int(epoch):06d}")

    def offer(self, header: dict):
        """OFFER frame: version gate + stage the header."""
        v = header.get("v")
        if v != PORTABLE_VERSION:
            raise PortableSkew(
                f"portable checkpoint header v{v} from peer "
                f"{header.get('origin')!r}, this build speaks "
                f"v{PORTABLE_VERSION} — refusing (upgrade the older "
                f"side; docs/ROBUSTNESS.md \"Cross-host recovery\")")
        key = (str(header.get("origin")), int(header["epoch"]))
        self._pending[key] = header
        os.makedirs(self._epoch_dir(*key), exist_ok=True)

    def blob(self, meta: dict, raw: bytes):
        """BLOB frame: CRC + size gate, then tmp-rename into the staged
        epoch directory."""
        if len(raw) != int(meta["bytes"]):
            raise ValueError(
                f"portable blob {meta.get('node')!r}: {len(raw)} bytes "
                f"framed, envelope says {meta['bytes']}")
        if blob_crc(raw) != int(meta["crc"]):
            raise ValueError(
                f"portable blob {meta.get('node')!r}: CRC32 mismatch "
                f"in transit (refusing to land a corrupt checkpoint)")
        d = self._epoch_dir(meta.get("origin"), meta["epoch"])
        os.makedirs(d, exist_ok=True)
        path = os.path.join(d, f"{_safe_id(str(meta['node']))}.ckpt")
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(raw)
        os.replace(tmp, path)

    def commit(self, meta: dict):
        """COMMIT frame: every offered blob must have landed; write the
        manifest (CheckpointStore format + the portable envelope) LAST,
        then prune the peer's retention window."""
        key = (str(meta.get("origin")), int(meta["epoch"]))
        header = self._pending.pop(key, None)
        if header is None:
            raise ValueError(
                f"portable COMMIT for epoch {key[1]} of peer {key[0]!r} "
                f"without a preceding OFFER")
        d = self._epoch_dir(*key)
        for safe, m in header["nodes"].items():
            if "bytes" in m \
                    and not os.path.exists(os.path.join(d,
                                                        f"{safe}.ckpt")):
                raise ValueError(
                    f"portable COMMIT for epoch {key[1]} of peer "
                    f"{key[0]!r}: blob {safe!r} never arrived")
        manifest = {"epoch": header["epoch"],
                    "t": header.get("t") or time.time(),
                    "partial": header.get("partial", False),
                    "nodes": header["nodes"],
                    "v": header["v"], "origin": header["origin"]}
        tmp = os.path.join(d, "MANIFEST.json.tmp")
        with open(tmp, "w") as f:
            json.dump(manifest, f)
        os.replace(tmp, os.path.join(d, "MANIFEST.json"))
        self.store_for(key[0])._prune()
        if self._metrics is not None:
            self._metrics.counter("ckpt_spooled").inc()

    # ------------------------------------------------------------- reading

    def peers(self) -> list:
        try:
            entries = os.listdir(self.root)
        except OSError:
            return []
        return sorted(m.group(1) for m in map(_PEER_DIR.match, entries)
                      if m)

    def store_for(self, origin) -> CheckpointStore:
        """An ordinary CheckpointStore over one peer's spooled epochs —
        restore with the usual ``latest_complete()/load()`` recipe."""
        return CheckpointStore(self._peer_dir(origin), retain=self.retain,
                               metrics=self._metrics, events=self._events)

    def latest(self, origin):
        """(epoch, manifest) of a peer's newest VERIFIED spooled epoch,
        or None (integrity fallback exactly as the local store)."""
        return self.store_for(origin).latest_complete()
