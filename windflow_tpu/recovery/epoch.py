"""Epoch markers, sequence tagging, and the per-node recovery record.

Wire format of the recovery layer, in-process edition: when a dataflow
runs with ``recovery=``, every batch crossing an engine edge is wrapped in
a :class:`Tagged` envelope carrying a per-edge monotone sequence number,
and sources interleave :class:`EpochMarker` control frames.  The envelope
is what makes restart exactly-once for deterministic operators: a
restarted node replays its input journal, regenerates the *same* output
sequence numbers, and consumers drop everything at or below the last
sequence they saw per input channel.

:class:`NodeRecovery` is the per-node state machine the engine's
supervised receive loop drives (runtime/engine.py ``_run_supervised``):
sequence counters, per-channel epoch levels (Chandy–Lamport alignment
over the FIFO inboxes), the bounded input journal retained until the next
epoch checkpoint, held-back items from channels that are ahead of the
node's epoch, and the committed snapshot restarts restore from.
"""

from __future__ import annotations

import time


class EpochMarker:
    """Epoch barrier control frame: "every row of epochs <= ``epoch`` has
    been emitted on this edge".  Injected by sources (RecoveryPolicy
    triggers, or forwarded from a wire channel's epoch frame) and
    forwarded by each node once all its live input channels align."""

    __slots__ = ("epoch",)

    def __init__(self, epoch: int):
        self.epoch = int(epoch)

    def __repr__(self):
        return f"<EpochMarker {self.epoch}>"


class Tagged:
    """Per-edge envelope: ``seq`` is the producer's monotone sequence
    number on that output channel; ``payload`` is a batch or an
    :class:`EpochMarker`."""

    __slots__ = ("seq", "payload")

    def __init__(self, seq: int, payload):
        self.seq = seq
        self.payload = payload

    def __repr__(self):
        return f"<Tagged #{self.seq} {type(self.payload).__name__}>"


def is_ctrl_payload(item) -> bool:
    """True for envelopes whose payload is an epoch marker — the shedding
    inboxes treat these like EOS (never dropped, re-queued on evict)."""
    return type(item) is Tagged and type(item.payload) is EpochMarker


class NodeRecovery:
    """Per-node recovery record (see module docstring).  Created by the
    :class:`~windflow_tpu.recovery.supervisor.Supervisor` at ``run()`` and
    installed as ``node._recov`` — the single hot-path hook
    (runtime/node.py ``emit``/``emit_to``)."""

    __slots__ = (
        "node_id", "policy", "supervisor", "is_source", "journaling",
        "copy_inputs",
        # producer side
        "out_seq", "batches_out", "src_epoch", "last_mark_t",
        # consumer side
        "last_seen", "chan_epoch", "eos", "live", "budget", "epoch",
        "journal", "held", "overflowed", "quarantined",
        "requarantine_skip",
        # restart bookkeeping
        "snapshot", "restarts_used", "unrecoverable",
    )

    def __init__(self, node_id: str, policy, supervisor, is_source: bool,
                 journaling: bool, copy_inputs: bool = False):
        self.node_id = node_id
        self.policy = policy
        self.supervisor = supervisor
        self.is_source = is_source
        #: False for nodes that cannot snapshot (restart impossible):
        #: skip the journal so unbounded streams don't hoard batches
        self.journaling = journaling
        #: the consumer mutates handed-off input batches in place
        #: (node.py ownership protocol) — journal private copies so
        #: replay sees pristine inputs
        self.copy_inputs = copy_inputs
        self.out_seq: list[int] = []
        self.batches_out = 0
        self.src_epoch = 0
        self.last_mark_t = None
        self.last_seen: dict[int, int] = {}
        self.chan_epoch: dict[int, int] = {}
        self.eos: set[int] = set()
        self.live = 0
        self.budget = 0
        self.epoch = 0
        self.journal: list = []
        self.held: list = []
        self.overflowed = False
        #: poison batches quarantined since the last checkpoint, and how
        #: many re-raises a replay should spend budget on WITHOUT
        #: appending a duplicate dead letter (engine._svc_supervised)
        self.quarantined = 0
        self.requarantine_skip = 0
        self.snapshot = None          # (epoch, node_state, runner_state)
        self.restarts_used = 0
        self.unrecoverable = None     # reason string once set

    # ------------------------------------------------------------- producer

    def emit(self, outputs, batch):
        """Tagged broadcast to every output channel; sources then check
        the epoch triggers (markers ride *behind* the batch that tripped
        them, so an epoch is a closed prefix of the stream)."""
        seq = self.out_seq
        for i, (inbox, src) in enumerate(outputs):
            seq[i] += 1
            if type(batch) is EpochMarker:
                # a source forwarding wire-driven epochs (channel.py
                # epoch frames): policy-exempt like EOS
                inbox.put_ctrl(src, Tagged(seq[i], batch))
            else:
                inbox.put(src, Tagged(seq[i], batch))
        if self.is_source and type(batch) is not EpochMarker:
            self._after_source_emit(outputs)

    def emit_to(self, outputs, out: int, batch):
        inbox, src = outputs[out]
        self.out_seq[out] += 1
        if type(batch) is EpochMarker:
            # same contract as emit(): markers are policy-exempt and
            # never count as source batches (a shed marker would stall
            # downstream alignment; a counted one would self-trigger)
            inbox.put_ctrl(src, Tagged(self.out_seq[out], batch))
            return
        inbox.put(src, Tagged(self.out_seq[out], batch))
        if self.is_source:
            self._after_source_emit(outputs)

    def _after_source_emit(self, outputs):
        pol = self.policy
        self.batches_out += 1
        fire = (pol.epoch_batches is not None
                and self.batches_out % pol.epoch_batches == 0)
        if not fire and pol.epoch_period is not None:
            now = time.monotonic()
            if self.last_mark_t is None:
                self.last_mark_t = now
            elif now - self.last_mark_t >= pol.epoch_period:
                fire = True
        if fire:
            self.src_epoch += 1
            self.forward_marker(outputs, self.src_epoch)
            self.last_mark_t = time.monotonic()

    def forward_marker(self, outputs, epoch: int):
        """Broadcast ``EpochMarker(epoch)`` on every output, sequence
        tagged and policy-exempt (a shed marker would stall downstream
        alignment)."""
        marker = EpochMarker(epoch)
        for i, (inbox, src) in enumerate(outputs):
            self.out_seq[i] += 1
            inbox.put_ctrl(src, Tagged(self.out_seq[i], marker))

    # ------------------------------------------------------------- consumer

    def begin(self, n_outputs: int, live: int, budget: int):
        self.out_seq = [0] * n_outputs
        self.live = live
        self.budget = budget

    def journal_append(self, src: int, item, lvl: int = 0):
        """Record one consumed input.  ``lvl`` pins the channel's epoch
        level AT ARRIVAL: replay must make the same hold-or-process
        decision the original dispatch made, and the restored
        ``chan_epoch`` only knows the (possibly later) commit-time
        level — deciding off that would defer items the original run
        processed immediately, perturbing order-sensitive consumers'
        release batching and breaking replay determinism."""
        if not self.journaling or self.overflowed:
            return
        if len(self.journal) >= self.policy.replay_capacity:
            # past the bound the journal can no longer reproduce the
            # post-snapshot input, so restart is off until the next
            # checkpoint trims it — note it once, loudly
            self.overflowed = True
            self.journal.clear()
            self.supervisor.note_overflow(self)
            return
        self.journal.append((src, self._journal_item(item), lvl))

    def _journal_item(self, item):
        if (self.copy_inputs and type(item) is Tagged
                and type(item.payload) is not EpochMarker
                and hasattr(item.payload, "copy")):
            return Tagged(item.seq, item.payload.copy())
        return item

    def barrier_ready(self):
        """The epoch whose barrier is now complete (min channel level over
        live channels, above the node's current epoch); the string
        ``"eos"`` when every channel reached EOS while items are still
        held back (no further barrier can complete — the engine drains
        them); None otherwise."""
        levels = [e for c, e in self.chan_epoch.items() if c not in self.eos]
        if self.live <= 0 and not levels:
            return "eos" if self.held else None
        if len(levels) < self.live:     # a live channel has no marker yet
            return None
        m = min(levels)
        return m if m > self.epoch else None

    def commit(self, epoch: int, node_state):
        """Record the completed checkpoint: runner state + node state;
        the journal resets to exactly the currently held (consumed but
        not yet processed) items — everything else is in the snapshot."""
        # the snapshot's view of last_seen must treat held items as
        # UNSEEN: they are the journal the restore replays, and replay
        # goes through the duplicate check — snapshotting their seqs
        # would silently drop that whole prefix on restore (held seqs
        # are a contiguous per-edge suffix, so first-held-minus-one is
        # the consistent rollback point).  The LIVE last_seen keeps the
        # full values: a true duplicate from a restarted producer still
        # drops, while the held copy processes from the hold queue.
        last = dict(self.last_seen)
        for src, item, _lvl in self.held:
            if type(item) is Tagged:
                if item.seq - 1 < last.get(src, -1):
                    last[src] = item.seq - 1
        runner_state = {
            "live": self.live,
            "eos": set(self.eos),
            "chan_epoch": dict(self.chan_epoch),
            "last_seen": last,
            "out_seq": list(self.out_seq),
            "budget": self.budget,
            "epoch": epoch,
        }
        self.epoch = epoch
        self.snapshot = (epoch, node_state, runner_state)
        self.quarantined = 0
        # held items are consumed-but-unprocessed: they are the exact
        # post-snapshot input prefix, so the journal resets to them
        # (copied under the same mutating-consumer rule as appends)
        self.journal = ([(s, self._journal_item(i), l)
                         for s, i, l in self.held]
                        if self.journaling else [])
        self.overflowed = False

    def restore(self):
        """Reset runner state to the committed snapshot; returns
        (node_state, journal_to_replay).  The journal is re-built by the
        replay itself (dispatch re-appends), so it is detached here."""
        epoch, node_state, rs = self.snapshot
        self.live = rs["live"]
        self.eos = set(rs["eos"])
        self.chan_epoch = dict(rs["chan_epoch"])
        self.last_seen = dict(rs["last_seen"])
        self.out_seq = list(rs["out_seq"])
        self.budget = rs["budget"]
        self.epoch = rs["epoch"]
        todo, self.journal, self.held = self.journal, [], []
        self.overflowed = False
        # replay will re-raise on batches already quarantined since the
        # snapshot: spend budget again, skip the duplicate dead letters
        self.requarantine_skip = self.quarantined
        self.quarantined = 0
        return node_state, todo

    def mark_unrecoverable(self, reason: str):
        if self.unrecoverable is None:
            self.unrecoverable = reason
            self.journal = []
            self.journaling = False
            self.supervisor.note_unrecoverable(self, reason)
