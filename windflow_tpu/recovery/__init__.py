"""Epoch checkpoints and supervised restart (docs/ROBUSTNESS.md "Recovery").

The reference (WindFlow/FastFlow) is a single-process graph with no fault
tolerance: one node exception cancels every queue and all window state is
lost.  This package adds the opt-in recovery layer on top of the failure
*detectors* (overload error budgets, wire ``PeerStall``/``PeerAbort``) and
*sensors* (obs events/metrics) the robustness and observability layers
already provide:

* **epoch barriers** — sources inject :class:`EpochMarker` control frames
  (count- or time-triggered, ``RecoveryPolicy``); markers flow through
  inboxes and align per consumer (Chandy–Lamport over the engine's FIFO
  channels), so each node's snapshot is a globally consistent cut;
* **asynchronous checkpoints** — on barrier alignment each node snapshots
  via ``Node.state_snapshot()/state_restore()`` (host archives and vecinc
  state by deep copy; device-resident rings as a handle whose device→host
  copy overlaps the next batches' compute) into a :class:`CheckpointStore`
  (per-node blobs + manifest, atomic rename, retain last K);
* **supervised restart** — a failed node thread restores the last
  snapshot, replays its bounded per-edge input journal (retained until
  the next epoch checkpoint), and resumes, under a restart budget with
  exponential backoff; emissions are sequence-tagged per edge so replayed
  duplicates are dropped downstream (exactly-once for deterministic
  operators).  Budget spent ⇒ the graph fails exactly as today.

**The contract (same as OverloadPolicy / the obs layer): ``recovery=``
unset ⇒ seed-identical behavior** — no markers, no journals, no
supervisor thread, and a single dead branch on the emit hot path.
"""

from .epoch import EpochMarker, NodeRecovery, Tagged
from .policy import RecoveryPolicy
from .store import CheckpointStore
from .supervisor import Supervisor

__all__ = [
    "RecoveryPolicy", "CheckpointStore", "Supervisor", "EpochMarker",
    "NodeRecovery", "Tagged",
]
