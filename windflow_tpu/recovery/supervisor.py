"""The Supervisor: restart arbitration + the asynchronous checkpoint
writer.

One Supervisor per recovering Dataflow (created at ``run()``, stopped in
``wait()``).  Two jobs:

* **restart arbitration** — a failed node thread asks
  :meth:`authorize_restart`; the supervisor spends the node's restart
  budget, sleeps the exponential backoff (during which the node's bounded
  inbox backpressures producers — the quiesce), and reports the decision.
  The restart itself runs on the node's own thread
  (runtime/engine.py ``_run_supervised``): restore the last snapshot,
  replay the input journal, resume.  Budget spent ⇒ the failure
  propagates exactly as in the un-supervised engine.
* **asynchronous checkpoint writing** — node threads enqueue snapshot
  states at barrier alignment and move on; the writer thread resolves
  lazy handles (the resident ring's device→host copy — overlapping the
  ring's ongoing compute), pickles blobs into the
  :class:`~windflow_tpu.recovery.store.CheckpointStore`, and seals each
  epoch's manifest once every participating node's blob landed.

Checkpoint/restore/restart surface as obs events (``checkpoint``,
``checkpoint_commit``, ``checkpoint_skip``, ``restore``, ``node_restart``,
``recovery_giveup``, ``epoch``) and byte/duration metrics (``ckpt_*``,
``node_restarts`` counters, ``ckpt_write_s`` histogram) when the dataflow
runs with the observability layer on.
"""

from __future__ import annotations

import queue
import threading
import time
from time import perf_counter as _pc

from .epoch import NodeRecovery
from .policy import RecoveryPolicy


def _mutates_input(node) -> bool:
    """True when the node (or a fused head stage) may mutate handed-off
    input batches in place (node.py ownership protocol) — its journal
    must hold private copies for replay."""
    if getattr(node, "input_fresh", False):
        return True
    core = getattr(node, "core", None)
    if core is not None and getattr(core, "owned_input", False):
        return True
    stages = getattr(node, "stages", None)
    if stages:
        return _mutates_input(stages[0])
    return False


class Supervisor:
    """See module docstring.  Thread-safety: restart arbitration and
    blob enqueueing are called from node threads (locked); the store is
    touched only by the writer thread."""

    def __init__(self, dataflow, policy: RecoveryPolicy):
        self.dataflow = dataflow
        self.policy = policy
        self._mu = threading.Lock()
        self.store = None
        self._writer = None
        self._wq = None
        #: node_ids whose blobs an epoch manifest waits for
        self._expected: set[str] = set()
        self._epoch_blobs: dict[int, dict] = {}
        #: highest epoch each node has blobbed (monotone progress)
        self._node_epoch: dict[str, int] = {}
        if policy.checkpoint_dir:
            from .store import CheckpointStore
            self.store = CheckpointStore(policy.checkpoint_dir,
                                         retain=policy.retain,
                                         metrics=dataflow.metrics,
                                         events=dataflow.events)
            self._wq = queue.Queue()
            self._writer = threading.Thread(
                target=self._writer_loop, daemon=True,
                name=f"{dataflow.name}/ckpt-writer")

    # ------------------------------------------------------------- wiring

    def attach_all(self):
        """Install a NodeRecovery on every node of the graph (and on the
        emitting tail stage of fused Combs, whose emissions bypass the
        Comb object itself)."""
        from ..runtime.node import SourceNode
        from ..utils.tracing import node_stats_name
        df = self.dataflow
        for idx, node in enumerate(df.nodes):
            is_source = isinstance(node, SourceNode)
            journaling = bool(getattr(node, "recoverable", False)
                              and not is_source)
            rec = NodeRecovery(
                node_stats_name(df.name, idx, node.name), self.policy,
                self, is_source=is_source, journaling=journaling,
                copy_inputs=_mutates_input(node))
            node._recov = rec
            stages = getattr(node, "stages", None)
            if stages:
                # the Comb's last stage owns the real output channels
                stages[-1]._recov = rec
            for member in (node, *(stages or ())):
                core = getattr(member, "core", None)
                if core is not None and hasattr(core, "snapshot_rings"):
                    # mirror the ring-snapshot knob onto resident cores
                    core.snapshot_rings = self.policy.snapshot_rings
                if core is not None and hasattr(core, "_obs_metrics"):
                    # hand cores with their own snapshot counters (the
                    # native core's native_state_* series) the dataflow
                    # metrics sink — cores hold no dataflow reference
                    core._obs_metrics = df.metrics
            if journaling:
                self._expected.add(rec.node_id)
        if self._writer is not None:
            self._writer.start()

    # ------------------------------------------------------- restart logic

    def authorize_restart(self, node, rec: NodeRecovery,
                          error: BaseException) -> bool:
        """Decide whether the failed node may restore + replay; sleeps
        the backoff on approval.  Called on the failing node's thread."""
        if rec.snapshot is None:
            self._giveup(node, rec, error, "no snapshot")
            return False
        if rec.unrecoverable is not None:
            self._giveup(node, rec, error, rec.unrecoverable)
            return False
        if rec.overflowed:
            self._giveup(node, rec, error, "journal overflowed")
            return False
        with self._mu:
            if rec.restarts_used >= self.policy.max_restarts:
                spent = True
            else:
                spent = False
                rec.restarts_used += 1
                attempt = rec.restarts_used
        if spent:
            self._giveup(node, rec, error,
                         f"restart budget ({self.policy.max_restarts}) "
                         "spent")
            return False
        self._event("node_restart", node=rec.node_id, attempt=attempt,
                    max_restarts=self.policy.max_restarts,
                    epoch=rec.snapshot[0], error=type(error).__name__,
                    message=str(error))
        self._count("node_restarts")
        backoff = self.policy.restart_backoff * (2 ** (attempt - 1))
        # backoff in small slices so a graph failing ELSEWHERE still
        # cancels this node promptly (its producers are blocked on us)
        t_end = time.monotonic() + backoff
        failed = self.dataflow._failed
        while time.monotonic() < t_end:
            if failed.is_set():
                return False
            time.sleep(min(0.02, max(t_end - time.monotonic(), 0)))
        return True

    def _giveup(self, node, rec, error, reason: str):
        self._event("recovery_giveup", node=rec.node_id, reason=reason,
                    error=type(error).__name__, message=str(error))
        bb = getattr(self.dataflow, "_blackbox", None)
        if bb is not None:
            # budget exhaustion is a flight-recorder trigger
            # (docs/OBSERVABILITY.md "Federation & SLOs"): the rings
            # still hold every restart attempt that led here
            bb.dump("recovery_giveup", failed_node=rec.node_id,
                    reason=reason, error=type(error).__name__)

    def note_restored(self, node, rec: NodeRecovery, replayed: int,
                      duration_s: float):
        self._event("restore", node=rec.node_id, epoch=rec.epoch,
                    replayed=replayed, ms=round(duration_s * 1e3, 3))
        self._count("node_restores")

    def note_overflow(self, rec: NodeRecovery):
        self._event("recovery_giveup", node=rec.node_id,
                    reason=f"replay journal exceeded "
                           f"{self.policy.replay_capacity} items "
                           "(restart disabled until the next checkpoint)")

    def note_unrecoverable(self, rec: NodeRecovery, reason: str):
        with self._mu:
            self._expected.discard(rec.node_id)
        self._event("checkpoint_skip", node=rec.node_id, reason=reason)
        if self._wq is not None:
            # epochs parked waiting only on this node can seal now; the
            # store is writer-thread-only, so route through the queue
            self._wq.put(("seal",))

    # ----------------------------------------------------- checkpoint path

    def note_checkpoint(self, node, rec: NodeRecovery, epoch: int,
                        duration_s: float):
        self._event("checkpoint", node=rec.node_id, epoch=epoch,
                    ms=round(duration_s * 1e3, 3))
        self._count("ckpt_snapshots")

    def enqueue_blob(self, rec: NodeRecovery, epoch: int, state):
        """Hand a snapshot to the writer thread (no-op without a store):
        the node thread returns to stream work immediately; lazy handles
        (device→host ring copies) resolve on the writer."""
        if self._wq is not None:
            self._wq.put(("blob", rec.node_id, epoch, state))

    def _writer_loop(self):
        while True:
            item = self._wq.get()
            if item[0] == "stop":
                return
            if item[0] == "seal":
                self._seal_ready()
                continue
            _kind, node_id, epoch, state = item
            t0 = _pc()
            try:
                n = self.store.save_blob(epoch, node_id, state)
                meta = {"bytes": n}
                self._count("ckpt_blobs")
                self._count("ckpt_bytes", n)
                self._hist("ckpt_write_s", _pc() - t0)
            except Exception as e:  # unpicklable user state, disk error
                meta = {"skipped": f"{type(e).__name__}: {e}"}
                self._count("ckpt_skips")
                self._event("checkpoint_skip", node=node_id, epoch=epoch,
                            reason=f"{type(e).__name__}: {e}")
            self._note_blob(epoch, node_id, meta)

    def _note_blob(self, epoch: int, node_id: str, meta: dict):
        with self._mu:
            self._epoch_blobs.setdefault(epoch, {})[node_id] = meta
            # progress is per-node monotone: a blob for epoch E also
            # vouches for every earlier pending epoch of that node —
            # barrier alignment can legitimately skip epochs (a lagging
            # channel EOSing jumps the min), and a strict exact-epoch
            # wait would strand those manifests forever
            if epoch > self._node_epoch.get(node_id, -1):
                self._node_epoch[node_id] = epoch
        self._seal_ready()

    def _seal_ready(self):
        """Seal (manifest + prune) every pending epoch all expected
        nodes have reached, in ascending order so nothing strands."""
        while True:
            with self._mu:
                ready = sorted(
                    e for e in self._epoch_blobs
                    if all(self._node_epoch.get(n, -1) >= e
                           for n in self._expected))
                if not ready:
                    return
                epoch = ready[0]
                blobs = self._epoch_blobs.pop(epoch)
                skipped = [n for n in self._expected if n not in blobs]
            for n in skipped:
                blobs[n] = {"skipped": "epoch passed without checkpoint"}
            partial = any("skipped" in m for m in blobs.values())
            self.store.commit(epoch, blobs, partial=partial)
            self._event("checkpoint_commit", epoch=epoch,
                        nodes=len(blobs), partial=partial,
                        bytes=sum(m.get("bytes", 0)
                                  for m in blobs.values()))
            # the seal is the durability boundary resumable wire edges
            # ack at (Dataflow.on_epoch_sealed → RowReceiver.ack_epoch
            # → sender journals trim).  Read live so listeners
            # registered after run() still fire; swallow — a telemetry
            # hook must not fail a seal.
            for fn in getattr(self.dataflow, "_seal_listeners", ()):
                try:
                    fn(epoch)
                except Exception:
                    pass

    def stop(self, wait_s: float = 30.0):
        """Flush and stop the writer (called from ``Dataflow.wait``).
        ``wait_s`` bounds the flush — a timed-out wait() passes a small
        grace so pending blob writes cannot blow its promised bound
        (the writer is a daemon; unfinished epochs stay unsealed and
        are pruned as torn checkpoints later)."""
        if self._writer is not None and self._writer.is_alive():
            self._wq.put(("stop",))
            self._writer.join(timeout=wait_s)

    # ------------------------------------------------------------- plumbing

    def _event(self, kind: str, **fields):
        ev = self.dataflow.events
        if ev is not None:
            ev.emit(kind, dataflow=self.dataflow.name, **fields)

    def _count(self, name: str, n: int = 1):
        m = self.dataflow.metrics
        if m is not None:
            m.counter(name).inc(n)

    def _hist(self, name: str, v: float):
        m = self.dataflow.metrics
        if m is not None:
            m.histogram(name).observe(v)
