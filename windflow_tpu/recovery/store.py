"""Durable checkpoint store: per-node pickle blobs + an atomically renamed
manifest per epoch, retained last-K.

Layout (under ``RecoveryPolicy.checkpoint_dir``)::

    epoch_000003/
        <node_id>.ckpt      # pickle of the node's state snapshot
        MANIFEST.json       # written LAST, via tmp + os.replace

An epoch directory without a manifest is an incomplete (in-progress or
crashed) checkpoint and is ignored by :meth:`latest_complete`.  Blobs are
also written tmp-then-rename so a reader never observes a torn file.
Snapshot states may contain lazy handles (e.g. the resident ring's
device→host copy, ops/resident.RingSnapshot): :func:`resolve_state`
materialises them just before pickling, on the supervisor's writer
thread — which is what lets the device transfer overlap ongoing compute.
"""

from __future__ import annotations

import json
import os
import pickle
import re
import shutil
import time

_EPOCH_DIR = re.compile(r"^epoch_(\d{6,})$")


def _safe_id(node_id: str) -> str:
    return re.sub(r"[^A-Za-z0-9_.+-]", "_", node_id)


def resolve_state(state):
    """Materialise lazy snapshot handles (objects exposing ``resolve()``)
    into plain picklable values, recursively through dicts/lists/tuples."""
    if hasattr(state, "resolve"):
        return state.resolve()
    if isinstance(state, dict):
        return {k: resolve_state(v) for k, v in state.items()}
    if isinstance(state, (list, tuple)):
        out = [resolve_state(v) for v in state]
        return out if isinstance(state, list) else tuple(out)
    return state


class CheckpointStore:
    """Filesystem checkpoint store (one instance per Dataflow run, used
    from the supervisor's writer thread only — no internal locking)."""

    def __init__(self, root: str, retain: int = 2):
        self.root = root
        self.retain = int(retain)
        os.makedirs(root, exist_ok=True)

    # ------------------------------------------------------------- writing

    def _epoch_dir(self, epoch: int) -> str:
        return os.path.join(self.root, f"epoch_{epoch:06d}")

    def save_blob(self, epoch: int, node_id: str, state) -> int:
        """Pickle one node's (resolved) state; returns the blob size in
        bytes.  Raises on unpicklable state — callers degrade to
        in-memory-only recovery for that node (checkpoint_skip event)."""
        d = self._epoch_dir(epoch)
        os.makedirs(d, exist_ok=True)
        blob = pickle.dumps(resolve_state(state),
                            protocol=pickle.HIGHEST_PROTOCOL)
        path = os.path.join(d, f"{_safe_id(node_id)}.ckpt")
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(blob)
        os.replace(tmp, path)
        return len(blob)

    def commit(self, epoch: int, nodes: dict, partial: bool = False):
        """Seal the epoch: write the manifest (atomic rename, LAST) and
        prune beyond the retention window.  ``nodes`` maps node_id ->
        {"bytes": n} (or {"skipped": reason})."""
        d = self._epoch_dir(epoch)
        os.makedirs(d, exist_ok=True)
        manifest = {"epoch": epoch, "t": time.time(), "partial": partial,
                    "nodes": {_safe_id(k): v for k, v in nodes.items()}}
        tmp = os.path.join(d, "MANIFEST.json.tmp")
        with open(tmp, "w") as f:
            json.dump(manifest, f)
        os.replace(tmp, os.path.join(d, "MANIFEST.json"))
        self._prune()

    def _prune(self):
        done = self.epochs()
        keep_from = done[-self.retain] if len(done) >= self.retain else \
            (done[0] if done else 0)
        try:
            entries = os.listdir(self.root)
        except OSError:
            return
        for name in entries:
            m = _EPOCH_DIR.match(name)
            # anything older than the retention window goes — including
            # UNSEALED directories (torn checkpoints from a crashed
            # writer), which would otherwise accumulate forever; newer
            # unsealed dirs are in-progress epochs and stay
            if m and int(m.group(1)) < keep_from:
                shutil.rmtree(os.path.join(self.root, name),
                              ignore_errors=True)

    # ------------------------------------------------------------- reading

    def epochs(self) -> list:
        """Manifested (complete) epochs, ascending."""
        out = []
        try:
            entries = os.listdir(self.root)
        except OSError:
            return out
        for name in entries:
            m = _EPOCH_DIR.match(name)
            if m and os.path.exists(os.path.join(self.root, name,
                                                 "MANIFEST.json")):
                out.append(int(m.group(1)))
        return sorted(out)

    def latest_complete(self):
        """(epoch, manifest) of the newest sealed checkpoint, or None."""
        done = self.epochs()
        if not done:
            return None
        epoch = done[-1]
        with open(os.path.join(self._epoch_dir(epoch),
                               "MANIFEST.json")) as f:
            return epoch, json.load(f)

    def load(self, epoch: int, node_id: str):
        """Unpickle one node's blob from a sealed epoch."""
        path = os.path.join(self._epoch_dir(epoch),
                            f"{_safe_id(node_id)}.ckpt")
        with open(path, "rb") as f:
            return pickle.load(f)
