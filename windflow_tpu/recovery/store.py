"""Durable checkpoint store: per-node pickle blobs + an atomically renamed
manifest per epoch, retained last-K.

Layout (under ``RecoveryPolicy.checkpoint_dir``)::

    epoch_000003/
        <node_id>.ckpt      # pickle of the node's state snapshot
        MANIFEST.json       # written LAST, via tmp + os.replace

An epoch directory without a manifest is an incomplete (in-progress or
crashed) checkpoint and is ignored by :meth:`latest_complete`.  Blobs are
also written tmp-then-rename so a reader never observes a torn file.
Each committed blob's manifest entry also records its CRC32
(``{"bytes": n, "crc": c}``), and :meth:`latest_complete` *verifies*
the newest sealed epoch against it — a torn or bit-flipped ``.ckpt``
(filesystem damage after the rename, a partially copied directory)
makes the restore fall back to the previous sealed epoch (counted as
``ckpt_fallbacks`` and evented as ``checkpoint_fallback``) instead of
raising mid-restore.
Snapshot states may contain lazy handles (e.g. the resident ring's
device→host copy, ops/resident.RingSnapshot): :func:`resolve_state`
materialises them just before pickling, on the supervisor's writer
thread — which is what lets the device transfer overlap ongoing compute.
"""

from __future__ import annotations

import json
import os
import pickle
import re
import shutil
import time
import zlib

_EPOCH_DIR = re.compile(r"^epoch_(\d{6,})$")


def _safe_id(node_id: str) -> str:
    return re.sub(r"[^A-Za-z0-9_.+-]", "_", node_id)


def resolve_state(state):
    """Materialise lazy snapshot handles (objects exposing ``resolve()``)
    into plain picklable values, recursively through dicts/lists/tuples."""
    if hasattr(state, "resolve"):
        return state.resolve()
    if isinstance(state, dict):
        return {k: resolve_state(v) for k, v in state.items()}
    if isinstance(state, (list, tuple)):
        out = [resolve_state(v) for v in state]
        return out if isinstance(state, list) else tuple(out)
    return state


class CheckpointStore:
    """Filesystem checkpoint store (one instance per Dataflow run, used
    from the supervisor's writer thread only — no internal locking)."""

    def __init__(self, root: str, retain: int = 2, metrics=None,
                 events=None):
        self.root = root
        self.retain = int(retain)
        #: optional observability hooks (obs.MetricsRegistry / EventLog):
        #: only the integrity-fallback path uses them, so a bare store
        #: stays dependency-free
        self._metrics = metrics
        self._events = events
        #: CRC32 of each blob written this run, keyed (epoch, safe_id);
        #: commit() folds them into the manifest's node meta
        self._crc: dict = {}
        os.makedirs(root, exist_ok=True)

    # ------------------------------------------------------------- writing

    def _epoch_dir(self, epoch: int) -> str:
        return os.path.join(self.root, f"epoch_{epoch:06d}")

    def save_blob(self, epoch: int, node_id: str, state) -> int:
        """Pickle one node's (resolved) state; returns the blob size in
        bytes.  Raises on unpicklable state — callers degrade to
        in-memory-only recovery for that node (checkpoint_skip event)."""
        d = self._epoch_dir(epoch)
        os.makedirs(d, exist_ok=True)
        blob = pickle.dumps(resolve_state(state),
                            protocol=pickle.HIGHEST_PROTOCOL)
        safe = _safe_id(node_id)
        path = os.path.join(d, f"{safe}.ckpt")
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(blob)
        os.replace(tmp, path)
        self._crc[(epoch, safe)] = zlib.crc32(blob) & 0xFFFFFFFF
        return len(blob)

    def commit(self, epoch: int, nodes: dict, partial: bool = False):
        """Seal the epoch: write the manifest (atomic rename, LAST) and
        prune beyond the retention window.  ``nodes`` maps node_id ->
        {"bytes": n} (or {"skipped": reason})."""
        d = self._epoch_dir(epoch)
        os.makedirs(d, exist_ok=True)
        safe_nodes = {}
        for k, v in nodes.items():
            safe = _safe_id(k)
            crc = self._crc.pop((epoch, safe), None)
            if crc is not None and "bytes" in v:
                v = dict(v, crc=crc)
            safe_nodes[safe] = v
        manifest = {"epoch": epoch, "t": time.time(), "partial": partial,
                    "nodes": safe_nodes}
        tmp = os.path.join(d, "MANIFEST.json.tmp")
        with open(tmp, "w") as f:
            json.dump(manifest, f)
        os.replace(tmp, os.path.join(d, "MANIFEST.json"))
        # epochs seal in ascending order: CRCs staged at or below this
        # epoch but not committed belong to skipped blobs — drop them
        self._crc = {k: v for k, v in self._crc.items() if k[0] > epoch}
        self._prune()

    def _prune(self):
        done = self.epochs()
        keep_from = done[-self.retain] if len(done) >= self.retain else \
            (done[0] if done else 0)
        try:
            entries = os.listdir(self.root)
        except OSError:
            return
        for name in entries:
            m = _EPOCH_DIR.match(name)
            # anything older than the retention window goes — including
            # UNSEALED directories (torn checkpoints from a crashed
            # writer), which would otherwise accumulate forever; newer
            # unsealed dirs are in-progress epochs and stay
            if m and int(m.group(1)) < keep_from:
                shutil.rmtree(os.path.join(self.root, name),
                              ignore_errors=True)

    # ------------------------------------------------------------- reading

    def epochs(self) -> list:
        """Manifested (complete) epochs, ascending."""
        out = []
        try:
            entries = os.listdir(self.root)
        except OSError:
            return out
        for name in entries:
            m = _EPOCH_DIR.match(name)
            if m and os.path.exists(os.path.join(self.root, name,
                                                 "MANIFEST.json")):
                out.append(int(m.group(1)))
        return sorted(out)

    def verify_epoch(self, epoch: int, manifest: dict) -> str:
        """Integrity-check a sealed epoch's blobs against the manifest:
        every non-skipped node's ``.ckpt`` must exist, match its
        recorded size, and (when the manifest carries one) match its
        CRC32.  Returns None when clean, else a one-line reason."""
        d = self._epoch_dir(epoch)
        for safe, meta in manifest.get("nodes", {}).items():
            if "bytes" not in meta:
                continue            # skipped node: no blob expected
            path = os.path.join(d, f"{safe}.ckpt")
            try:
                with open(path, "rb") as f:
                    blob = f.read()
            except OSError as e:
                return f"{safe}.ckpt unreadable: {type(e).__name__}: {e}"
            if len(blob) != int(meta["bytes"]):
                return (f"{safe}.ckpt torn: {len(blob)} bytes on disk, "
                        f"manifest says {meta['bytes']}")
            crc = meta.get("crc")
            if crc is not None \
                    and (zlib.crc32(blob) & 0xFFFFFFFF) != int(crc):
                return f"{safe}.ckpt corrupt: CRC32 mismatch"
        return None

    def latest_complete(self):
        """(epoch, manifest) of the newest sealed checkpoint whose blobs
        VERIFY (size + CRC32 against the manifest), or None.  A torn or
        corrupt newest epoch falls back to the previous sealed one —
        counted (``ckpt_fallbacks``) and evented (``checkpoint_fallback``)
        — instead of raising mid-restore."""
        for epoch in reversed(self.epochs()):
            try:
                with open(os.path.join(self._epoch_dir(epoch),
                                       "MANIFEST.json")) as f:
                    manifest = json.load(f)
            except (OSError, ValueError) as e:
                self._note_fallback(epoch, f"MANIFEST.json unreadable: "
                                           f"{type(e).__name__}: {e}")
                continue
            reason = self.verify_epoch(epoch, manifest)
            if reason is None:
                return epoch, manifest
            self._note_fallback(epoch, reason)
        return None

    def _note_fallback(self, epoch: int, reason: str):
        if self._metrics is not None:
            self._metrics.counter("ckpt_fallbacks").inc()
        if self._events is not None:
            self._events.emit("checkpoint_fallback", epoch=epoch,
                              reason=reason)

    def load(self, epoch: int, node_id: str):
        """Unpickle one node's blob from a sealed epoch."""
        path = os.path.join(self._epoch_dir(epoch),
                            f"{_safe_id(node_id)}.ckpt")
        with open(path, "rb") as f:
            return pickle.load(f)
