"""RecoveryPolicy — the knobs of the checkpoint/restart layer.

Passing a policy to ``Dataflow``/``MultiPipe`` (``recovery=``) opts the
graph in; ``None`` (the default everywhere) keeps every code path
seed-identical (docs/ROBUSTNESS.md "Recovery").
"""

from __future__ import annotations


class RecoveryPolicy:
    """Per-dataflow recovery knobs.

    Parameters
    ----------
    epoch_batches:
        Count trigger: every source injects an epoch barrier marker after
        this many emitted batches.  ``None`` (default) = no count trigger.
    epoch_period:
        Time trigger, seconds: a source injects a marker when this much
        time has passed since its last one (checked at emission cadence,
        so a silent source injects nothing).  ``None`` = no time trigger.
        With *neither* trigger set only the initial (epoch-0) snapshot
        exists; restart still works but journals are never trimmed by
        barriers, so long streams will exhaust ``replay_capacity``.
    checkpoint_dir:
        Directory for durable checkpoints (per-node blobs + an atomically
        renamed manifest per epoch, written by the supervisor's writer
        thread).  ``None`` = in-memory snapshots only: supervised restart
        works, nothing touches disk.
    retain:
        Keep the last K manifested epochs on disk; older epoch
        directories are pruned after each commit.
    max_restarts:
        Per-node restart budget.  Once spent, the next failure tears the
        graph down exactly like the un-supervised engine.
    restart_backoff:
        Base backoff in seconds before a restart; restart ``i`` sleeps
        ``restart_backoff * 2**(i-1)``.  While a node backs off, its
        bounded inbox backpressures producers — the quiesce.
    replay_capacity:
        Bound on journaled input items per node (batches consumed since
        the last completed checkpoint).  Overflow makes the node
        non-restartable until its next checkpoint trims the journal; a
        crash in that window fails the graph as today.
    snapshot_rings:
        Include device-resident ring contents in checkpoint state (an
        asynchronous device→host copy that overlaps ongoing compute).
        ``False`` restores rings by rebasing from the host archives
        instead — smaller blobs, slower first post-restore flush.
    """

    __slots__ = ("epoch_batches", "epoch_period", "checkpoint_dir",
                 "retain", "max_restarts", "restart_backoff",
                 "replay_capacity", "snapshot_rings")

    def __init__(self, epoch_batches: int = None, epoch_period: float = None,
                 checkpoint_dir: str = None, retain: int = 2,
                 max_restarts: int = 3, restart_backoff: float = 0.05,
                 replay_capacity: int = 1024, snapshot_rings: bool = True):
        if epoch_batches is not None and int(epoch_batches) <= 0:
            raise ValueError("epoch_batches must be a positive batch count "
                             "(None for no count trigger)")
        if epoch_period is not None and float(epoch_period) <= 0:
            raise ValueError("epoch_period must be positive seconds "
                             "(None for no time trigger)")
        if int(retain) < 1:
            raise ValueError("retain must keep at least 1 epoch")
        if int(max_restarts) < 0:
            raise ValueError("max_restarts must be >= 0")
        if float(restart_backoff) < 0:
            raise ValueError("restart_backoff must be >= 0 seconds")
        if int(replay_capacity) < 1:
            raise ValueError("replay_capacity must be >= 1 journaled item")
        self.epoch_batches = (None if epoch_batches is None
                              else int(epoch_batches))
        self.epoch_period = (None if epoch_period is None
                             else float(epoch_period))
        self.checkpoint_dir = checkpoint_dir
        self.retain = int(retain)
        self.max_restarts = int(max_restarts)
        self.restart_backoff = float(restart_backoff)
        self.replay_capacity = int(replay_capacity)
        self.snapshot_rings = bool(snapshot_rings)

    def agrees_with(self, other: "RecoveryPolicy") -> bool:
        """Field equality — the union-merge conflict rule (one Dataflow
        runs one policy, api/multipipe.py)."""
        return all(getattr(self, a) == getattr(other, a)
                   for a in self.__slots__)

    def __repr__(self):
        # every agrees_with() field, so union-conflict errors show the
        # actual difference
        return ("RecoveryPolicy("
                + ", ".join(f"{a}={getattr(self, a)!r}"
                            for a in self.__slots__) + ")")
