"""Multi-chip window evaluation over a ``jax.sharding.Mesh`` — the scale-out
layer the reference does not have (SURVEY.md §2.8: FastFlow is single-process;
"distributed" there means threads). Here the five streaming parallelism
strategies (SURVEY.md §2.7) become mesh axes:

* ``kf`` axis — **group parallelism**: disjoint key groups (Key_Farm,
  kf_nodes.hpp:38-82) or disjoint window subsets (Win_Farm round-robin,
  wf_nodes.hpp:158-173) land on different devices.  Routing is done host-side
  when batches are staged; on device the groups are embarrassingly parallel —
  no collectives, shardings ride ICI for free.
* ``sp`` axis — **window-partition parallelism** (Win_MapReduce,
  win_mapreduce.hpp:147-183): each window's row range is split across the
  ``sp`` shards; every shard reduces its slice (the MAP stage) and the
  partials merge with an XLA collective over ICI (`psum` / gathered monoid
  reduce — the REDUCE stage).  This is the streaming analog of sequence
  parallelism over one long context.

* ``wf`` axis — **window parallelism** (Win_Farm round-robin window
  assignment, wf_nodes.hpp:158-173): the fired-window descriptors shard
  over ``wf``; every shard evaluates its window subset over the (replicated)
  group rows.  No collectives.

The combination is a 3D mesh: a (kf=2, wf=2, sp=2) mesh runs 2 key groups,
each splitting its windows over 2 chips, each window's rows over 2 chips —
the three SURVEY §2.7 streaming decompositions as one SPMD program, jitted
once per shape bucket (powers of two, like ops/device.py).  The sp merge
runs as one psum or as a ring of ICI ppermute hops (``collective="ring"``).
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.device import _bucket

KF_AXIS = "kf"   # key/group parallelism (no collectives; Key_Farm axis)
WF_AXIS = "wf"   # window parallelism (no collectives; Win_Farm axis)
SP_AXIS = "sp"   # within-window partition parallelism (collectives over ICI)


def make_mesh(n_kf: int = 1, n_sp: int = 1, devices=None,
              n_wf: int = 1) -> Mesh:
    """A 3D (kf, wf, sp) device mesh — the three streaming parallelism
    axes of SURVEY.md §2.7 as mesh dimensions.  ``n_kf * n_wf * n_sp``
    must not exceed the device count; on a v5e-8 use e.g. (4, 1, 2) or
    (2, 2, 2).  On a multi-host topology put ``kf`` outermost: key groups
    exchange nothing, so the inter-host (DCN) hops carry no collective
    traffic — only ``sp``'s psum/ppermute rides the intra-slice ICI."""
    devices = list(devices if devices is not None else jax.devices())
    need = n_kf * n_wf * n_sp
    if need > len(devices):
        raise ValueError(f"mesh ({n_kf}x{n_wf}x{n_sp}) needs {need} "
                         f"devices, have {len(devices)}")
    grid = np.asarray(devices[:need], dtype=object).reshape(
        n_kf, n_wf, n_sp)
    return Mesh(grid, (KF_AXIS, WF_AXIS, SP_AXIS))


from ..ops.monoid import OPS as _OPS
from ..ops.monoid import identity as _identity
from ..ops.monoid import jnp_reducer


class MeshWindowedReduce:
    """Sharded batched window reduction: the multi-chip form of
    ``DeviceWindowExecutor`` for built-in monoid ops.

    Global layout (KF = kf-shards, each owning B windows over N rows):

    * ``flat``  (KF, N) sharded ``P(kf, sp)`` — each sp shard holds a
      contiguous N/sp row slice of each group's archive segment,
      replicated over wf;
    * ``starts``/``lens`` (KF, B) sharded ``P(kf, wf)`` — window
      descriptors split over the window axis (``P(kf, None)`` when
      n_wf == 1), replicated over sp;
    * result (KF, B) sharded ``P(kf, wf)`` — every window's reduction,
      identical on all sp shards after the collective.

    Optional fused elementwise stages ride the same kernel (the device-side
    analog of MultiPipe chaining): ``map_fn(values) -> values`` transforms
    rows before windowing; ``filter_fn(values) -> bool`` *removes* rows from
    the aggregation — dropped rows do not count toward count/mean and do not
    contribute to any reduction, exactly like a chained Filter upstream of
    the window operator.
    """

    def __init__(self, mesh: Mesh, op: str = "sum", dtype=jnp.int32,
                 map_fn=None, filter_fn=None, collective: str = "auto"):
        if op not in _OPS:
            raise ValueError(f"unsupported op {op!r}")
        if collective not in ("auto", "psum", "ring"):
            raise ValueError(f"unknown collective {collective!r}")
        self.mesh = mesh
        self.op = op
        self.dtype = jnp.dtype(dtype)
        self.map_fn = map_fn
        self.filter_fn = filter_fn
        # "ring": accumulate sp partials with n_sp-1 ppermute rotations
        # (each hop only talks to its ICI neighbour — the communication
        # pattern of ring attention / ring all-reduce) instead of one
        # psum.  Same result; lets schedulers overlap hops with compute.
        self.collective = collective
        self.n_kf = mesh.shape[KF_AXIS]
        self.n_wf = mesh.shape.get(WF_AXIS, 1)
        self.n_sp = mesh.shape[SP_AXIS]
        self._jits = {}

    # ------------------------------------------------------------ compilation

    def _build(self, B: int, pad: int, Ns: int):
        """Compile for per-shard shapes: B windows/group, pad = max local
        rows per window, Ns = rows per (kf, sp) shard."""
        key = (B, pad, Ns)
        fn = self._jits.get(key)
        if fn is not None:
            return fn

        op, dtype = self.op, self.dtype
        map_fn, filter_fn = self.map_fn, self.filter_fn
        ident = _identity(op, dtype)
        n_sp = self.n_sp
        ring = self.collective == "ring" and n_sp > 1
        from ..ops.monoid import jnp_ufunc
        ufunc = jnp_ufunc(op)

        def ring_combine(x):
            # accumulate the sp partials with n_sp-1 neighbour rotations
            # (ring all-reduce / ring-attention communication pattern):
            # each hop is one ICI ppermute to the next shard
            perm = [(i, (i + 1) % n_sp) for i in range(n_sp)]
            acc = x
            for _ in range(n_sp - 1):
                x = jax.lax.ppermute(x, SP_AXIS, perm)
                acc = ufunc(acc, x)
            return acc

        def local(flat, starts, lens):
            # flat: (1, Ns) — this sp shard's row slice, replicated over
            # wf; starts/lens: (1, B/n_wf) — this wf shard's windows
            r = jax.lax.axis_index(SP_AXIS).astype(jnp.int32)
            base = r * Ns
            v = flat[0]
            if map_fn is not None:
                v = map_fn(v)
            lo = jnp.clip(starts[0] - base, 0, Ns)
            hi = jnp.clip(starts[0] + lens[0] - base, 0, Ns)
            iota = jnp.arange(pad, dtype=jnp.int32)
            idx = jnp.minimum(lo[:, None] + iota[None, :], Ns - 1)
            mask = iota[None, :] < (hi - lo)[:, None]
            if filter_fn is not None:
                # dropped rows leave the aggregation entirely (count too)
                mask = mask & filter_fn(v)[idx]
            if op == "count":
                part = jnp.sum(mask, axis=1).astype(dtype)
            else:
                vals = jnp.where(mask, v[idx], ident).astype(dtype)
                part = jnp_reducer(op)(vals, axis=1)
            if ring:
                if op == "mean":
                    s = ring_combine(part)
                    c = ring_combine(jnp.sum(mask, axis=1))
                    out = s / jnp.maximum(c, 1).astype(dtype)
                else:
                    out = ring_combine(part)
            elif op in ("sum", "count"):
                out = jax.lax.psum(part, SP_AXIS)
            elif op == "mean":
                s = jax.lax.psum(part, SP_AXIS)
                c = jax.lax.psum(jnp.sum(mask, axis=1), SP_AXIS)
                out = s / jnp.maximum(c, 1).astype(dtype)
            elif op == "min":
                out = jax.lax.pmin(part, SP_AXIS)
            elif op == "max":
                out = jax.lax.pmax(part, SP_AXIS)
            else:
                # prod: gather the n_sp partials and fold locally (ICI
                # all-gather of a (B,) vector — tiny); the static
                # replication check cannot see through the local fold
                allp = jax.lax.all_gather(part, SP_AXIS)  # (n_sp, B)
                out = jnp_reducer(op)(allp, axis=0)
            return out[None, :]

        wf = WF_AXIS if self.n_wf > 1 else None
        mapped = jax.shard_map(
            local, mesh=self.mesh,
            in_specs=(P(KF_AXIS, SP_AXIS), P(KF_AXIS, wf),
                      P(KF_AXIS, wf)),
            out_specs=P(KF_AXIS, wf),
            check_vma=(op != "prod" and not ring))
        fn = jax.jit(mapped)
        self._jits[key] = fn
        return fn

    # -------------------------------------------------------------- execution

    def sharding(self, spec: P) -> NamedSharding:
        return NamedSharding(self.mesh, spec)

    def __call__(self, flat: np.ndarray, starts: np.ndarray,
                 lens: np.ndarray) -> np.ndarray:
        """Evaluate all windows. ``flat`` is (KF, N) group rows; ``starts``
        and ``lens`` are (KF, B) window descriptors (row offsets within the
        group's flat segment). Returns (KF, B) reductions."""
        KF, N = flat.shape
        if KF != self.n_kf:
            raise ValueError(f"flat has {KF} groups, mesh kf={self.n_kf}")
        B = starts.shape[1]
        Bb = _bucket(B)
        if Bb % self.n_wf:  # the window axis shards B over wf
            Bb = ((Bb + self.n_wf - 1) // self.n_wf) * self.n_wf
        # shard size: each sp shard gets Ns rows; pad the row axis so any
        # [start, start+pad) window fits inside one shard's clip range
        maxlen = int(lens.max()) if lens.size else 1
        Ns = _bucket(max((N + self.n_sp - 1) // self.n_sp, 1))
        pad = _bucket(min(max(maxlen, 1), Ns))

        gflat = np.zeros((KF, Ns * self.n_sp), dtype=flat.dtype)
        gflat[:, :N] = flat
        gstarts = np.zeros((KF, Bb), dtype=np.int32)
        gstarts[:, :B] = starts
        glens = np.zeros((KF, Bb), dtype=np.int32)
        glens[:, :B] = lens

        wf = WF_AXIS if self.n_wf > 1 else None
        dflat = jax.device_put(gflat, self.sharding(P(KF_AXIS, SP_AXIS)))
        dstarts = jax.device_put(gstarts, self.sharding(P(KF_AXIS, wf)))
        dlens = jax.device_put(glens, self.sharding(P(KF_AXIS, wf)))
        out = self._build(Bb, pad, Ns)(dflat, dstarts, dlens)
        return np.asarray(out)[:, :B]


#: One full SPMD streaming step — the framework's "training step"
#: equivalent.  MeshWindowedReduce already fuses the elementwise Map and
#: Filter stages into the partitioned windowed reduction; this name marks
#: the whole-step usage.
MeshStreamStep = MeshWindowedReduce


def partition_stream_by_key(batch_keys: np.ndarray, n_groups: int,
                            routing=None) -> np.ndarray:
    """Host-side key→group routing for the kf axis (the mesh form of
    KF_Emitter's ``routing(key, n)``, kf_nodes.hpp:73). Returns the group
    index per row; default is ``key % n`` (builders.hpp:190)."""
    if routing is not None:
        return np.asarray(routing(batch_keys, n_groups))
    return batch_keys % n_groups
