"""Multi-chip / multi-host layer: `jax.sharding` meshes over the
streaming axes (kf x wf x sp), DCN-aware multi-host layout, and the
cross-process row channel — the scale-out surface in one import."""

from .channel import RowReceiver, RowSender, partition_and_ship
from .mesh import (KF_AXIS, SP_AXIS, WF_AXIS, MeshStreamStep,
                   MeshWindowedReduce, make_mesh,
                   partition_stream_by_key)
from .multihost import (initialize, local_kf_groups, make_multihost_mesh,
                        process_for_keys)

__all__ = [
    "KF_AXIS", "WF_AXIS", "SP_AXIS", "make_mesh",
    "MeshStreamStep", "MeshWindowedReduce", "partition_stream_by_key",
    "initialize", "make_multihost_mesh", "process_for_keys",
    "local_kf_groups",
    "RowSender", "RowReceiver", "partition_and_ship",
]
