"""Deterministic wire fault injection — the chaos harness of the
resumable row plane (docs/ROBUSTNESS.md "Wire resume").

A :class:`FaultPlan` is a *schedule*, not a probability: it names the
exact logical records (1-based, counted per :class:`~windflow_tpu.
parallel.channel.RowSender` across original sends AND replays) at which
the sender's transmit path misbehaves.  Threaded in under
``WireConfig(faults=...)``, which is the only coupling — ``channel.py``
never imports this module, so a plan-less wire stays byte-identical to
the seed and this file is never even loaded (the standing knob
contract; verified in tests/test_channel_faults.py).

Four fault kinds, mirroring how TCP edges die in practice:

* ``kill``  — the connection drops between frames (peer crash, RST);
* ``torn``  — the connection drops *mid-frame* (power loss while the
  kernel had half a write buffered): the receiver sees a truncated
  frame, the framing resyncs only on a fresh connection;
* ``dup``   — the record is delivered twice (the replay race every
  at-least-once transport has): the receiver must dedup by seq;
* ``stall`` — the link goes silent for ``stall_for`` seconds and then
  drops: long enough past a receiver ``stall_timeout`` to surface as
  :class:`~windflow_tpu.parallel.channel.PeerStall`.

``FaultPlan.seeded(seed)`` derives a reproducible schedule from one
integer — the soak driver's (scripts/soak_wire.py) repro contract: a
failing seed is the whole bug report.
"""

from __future__ import annotations

import random

KINDS = ("kill", "torn", "dup", "stall")


class FaultPlan:
    """Explicit schedule: each ``*_at`` is an iterable of 1-based record
    counts at which that fault fires (a record is one data batch or one
    epoch frame leaving a RowSender, replays included).  Counts must be
    disjoint across kinds — one record dies at most one way."""

    __slots__ = ("kill_at", "torn_at", "dup_at", "stall_at", "stall_for",
                 "seed")

    def __init__(self, kill_at=(), torn_at=(), dup_at=(), stall_at=(),
                 stall_for: float = 0.5, seed=None):
        self.kill_at = frozenset(int(n) for n in kill_at)
        self.torn_at = frozenset(int(n) for n in torn_at)
        self.dup_at = frozenset(int(n) for n in dup_at)
        self.stall_at = frozenset(int(n) for n in stall_at)
        self.stall_for = float(stall_for)
        self.seed = seed
        sets = (self.kill_at, self.torn_at, self.dup_at, self.stall_at)
        total = self.kill_at | self.torn_at | self.dup_at | self.stall_at
        if len(total) != sum(len(s) for s in sets):
            raise ValueError("FaultPlan schedules overlap: a record can "
                             "suffer at most one fault kind")
        if any(n < 1 for n in total):
            raise ValueError("FaultPlan record counts are 1-based")

    @classmethod
    def seeded(cls, seed: int, horizon: int = 48, n_faults: int = 3,
               kinds=KINDS, stall_for: float = 0.5) -> "FaultPlan":
        """A reproducible plan: ``n_faults`` fault points drawn without
        replacement from records ``[2, horizon]`` (never the first
        record, so every schedule exercises an *established* link), each
        assigned a kind from ``kinds`` — all driven by one stdlib
        ``random.Random(seed)``, so the same seed is the same chaos on
        every host and every rerun."""
        bad = [k for k in kinds if k not in KINDS]
        if bad:
            raise ValueError(f"unknown fault kind(s) {bad}; "
                             f"choose from {KINDS}")
        rng = random.Random(seed)
        lo, hi = 2, max(2, int(horizon))
        points = rng.sample(range(lo, hi + 1),
                            min(int(n_faults), hi - lo + 1))
        sched = {k: [] for k in KINDS}
        for p in sorted(points):
            sched[rng.choice(list(kinds))].append(p)
        return cls(kill_at=sched["kill"], torn_at=sched["torn"],
                   dup_at=sched["dup"], stall_at=sched["stall"],
                   stall_for=stall_for, seed=seed)

    def action_for(self, n: int):
        """The fault to inject at record count ``n`` (or None): the one
        hook the sender's transmit path calls."""
        if n in self.kill_at:
            return "kill"
        if n in self.torn_at:
            return "torn"
        if n in self.dup_at:
            return "dup"
        if n in self.stall_at:
            return "stall"
        return None

    def __repr__(self):
        parts = [f"{k}_at={sorted(getattr(self, k + '_at'))}"
                 for k in KINDS if getattr(self, k + "_at")]
        if self.seed is not None:
            parts.append(f"seed={self.seed}")
        return f"FaultPlan({', '.join(parts)})"

class HandoffChaos:
    """Plane-level chaos schedule — the handoff analogue of
    :class:`FaultPlan` (scripts/soak_handoff.py): at which SEALED epoch
    a plane worker dies (``kill``: its successor adopts via the
    replicated portable checkpoint, docs/ROBUSTNESS.md "Cross-host
    recovery") or rolls (``roll``: the same member restarts against its
    own store with ``resume_epoch=``).  Explicit and seeded like every
    plan here: a failing seed is the whole bug report."""

    __slots__ = ("kill", "roll", "seed")

    def __init__(self, kill=(), roll=(), seed=None):
        #: pid -> sealed epoch at which the event fires
        self.kill = {int(p): int(e) for p, e in kill}
        self.roll = {int(p): int(e) for p, e in roll}
        self.seed = seed
        if set(self.kill) & set(self.roll):
            raise ValueError("HandoffChaos schedules overlap: a worker "
                             "cannot both die and roll")
        if any(e < 1 for e in (*self.kill.values(), *self.roll.values())):
            raise ValueError("HandoffChaos epochs are 1-based")

    @classmethod
    def seeded(cls, seed: int, pids, last_epoch: int,
               kinds=("kill", "roll")) -> "HandoffChaos":
        """One reproducible plane event: a pid from ``pids`` suffers a
        kind from ``kinds`` at a sealed epoch in ``[1, last_epoch - 1]``
        (never the final epoch, so every schedule leaves a tail for the
        successor/restart to consume)."""
        bad = [k for k in kinds if k not in ("kill", "roll")]
        if bad:
            raise ValueError(f"unknown handoff kind(s) {bad}; "
                             f"choose from ('kill', 'roll')")
        rng = random.Random(seed)
        pid = rng.choice(sorted(pids))
        epoch = rng.randint(1, max(1, int(last_epoch) - 1))
        kind = rng.choice(sorted(kinds))
        return cls(seed=seed, **{kind: [(pid, epoch)]})

    def event_at(self, pid: int, epoch: int):
        """``"kill"``/``"roll"``/None for worker ``pid`` at the moment
        epoch ``epoch`` seals — the one hook the soak's worker loop
        calls."""
        if self.kill.get(int(pid)) == int(epoch):
            return "kill"
        if self.roll.get(int(pid)) == int(epoch):
            return "roll"
        return None

    def __repr__(self):
        parts = []
        if self.kill:
            parts.append(f"kill={sorted(self.kill.items())}")
        if self.roll:
            parts.append(f"roll={sorted(self.roll.items())}")
        if self.seed is not None:
            parts.append(f"seed={self.seed}")
        return f"HandoffChaos({', '.join(parts)})"
