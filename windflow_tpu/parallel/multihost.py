"""Multi-host execution: process-spanning meshes with DCN-aware layout.

The reference is strictly single-process — "communication" is FastFlow
shared-memory queues between pinned threads (SURVEY.md §2.8: no NCCL, no
MPI, no sockets).  The TPU-native scale-out story goes further: a
``jax.distributed``-initialised job sees every host's chips as one device
set, and the streaming mesh axes (kf × wf × sp, parallel/mesh.py) extend
across hosts with the axis→network mapping chosen so that

* ``kf`` (key groups — Key_Farm parallelism) is split OVER HOSTS first:
  key groups exchange nothing, so the slow inter-host DCN hops carry no
  collective traffic at all;
* ``sp`` (within-window partition — the psum/ring-ppermute axis) stays
  INSIDE one host's slice, so its collectives ride ICI.

This is the streaming analog of the scaling-book recipe "data-parallel
over DCN, model-parallel over ICI".

Deployment model: one engine process per host.  Host-side dataflow
(sources, emitters, host operators) runs per process over its own keys —
``process_for_keys`` gives the owner of each key, and a source that
generates (or receives) only its own key range needs no cross-host hop at
all, exactly like the reference's per-worker key partitioning
(kf_nodes.hpp routing) lifted one level.  Device-side, the sharded
executors (``MeshResidentExecutor``, ``MeshStreamStep``) run one SPMD
program over the global mesh; XLA inserts the (absent, for kf) DCN
collectives.
"""

from __future__ import annotations

import numpy as np

import jax
from jax.sharding import Mesh

from .mesh import KF_AXIS, SP_AXIS, WF_AXIS


def initialize(coordinator_address=None, num_processes=None,
               process_id=None, **kw):
    """``jax.distributed.initialize`` pass-through.  A zero-arg call
    DELEGATES to jax's cluster auto-detection (the canonical spelling on
    a real multi-host TPU pod — swallowing it here would silently build
    single-host meshes with wrong kf ownership).  The only no-op is the
    EXPLICIT single-process job, ``num_processes=1`` with no coordinator:
    there is nothing to coordinate."""
    if (num_processes == 1 and coordinator_address is None
            and process_id in (None, 0) and not kw):
        return
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes, process_id=process_id, **kw)


def _group_by_process(devices, process_of=None):
    """Devices grouped by owning process, process ids ascending.
    ``process_of`` overrides the grouping (tests simulate multi-host on
    virtual single-process devices by injecting a mapping)."""
    pid = (process_of if process_of is not None
           else (lambda d: d.process_index))
    groups = {}
    for d in devices:
        groups.setdefault(pid(d), []).append(d)
    return [groups[p] for p in sorted(groups)]


def make_multihost_mesh(n_kf=None, n_sp: int = 1, n_wf: int = 1,
                        devices=None, process_of=None) -> Mesh:
    """A (kf, wf, sp) mesh over every process's devices with ``kf``
    outermost ALONG THE PROCESS BOUNDARY: the first ``n_processes``
    divisions of the kf axis are whole hosts, so no kf index spans two
    hosts and every sp/wf neighbour lives on the same host (collectives
    on ICI, nothing on DCN).

    ``n_kf`` defaults to all remaining parallelism
    (n_devices // (n_sp * n_wf)); passing it explicitly is validation
    only — it must equal exactly ``n_hosts * per_host_share`` (this mesh
    always spans every device; carve a subset with ``devices=``).
    Constraint: ``n_sp * n_wf`` must divide each host's device count.
    """
    devices = list(devices if devices is not None else jax.devices())
    per_proc = _group_by_process(devices, process_of)
    n_local = len(per_proc[0])
    if any(len(g) != n_local for g in per_proc):
        raise ValueError(
            f"hosts disagree on device count: {[len(g) for g in per_proc]}")
    inner = n_sp * n_wf
    if n_local % inner:
        raise ValueError(
            f"sp*wf = {inner} must divide the per-host device count "
            f"{n_local} (sp collectives must stay on one host's ICI)")
    kf_per_proc = n_local // inner
    total_kf = kf_per_proc * len(per_proc)
    if n_kf is None:
        n_kf = total_kf
    if n_kf != total_kf:
        raise ValueError(
            f"n_kf={n_kf} but the ({len(per_proc)} hosts x {n_local} "
            f"devices) / (sp*wf={inner}) layout gives kf={total_kf}")
    # grid[kf, wf, sp]: host-major kf, then each host's devices reshaped
    # into its local (kf_per_proc, wf, sp) block
    blocks = [np.asarray(g, dtype=object).reshape(kf_per_proc, n_wf, n_sp)
              for g in per_proc]
    grid = np.concatenate(blocks, axis=0)
    return Mesh(grid, (KF_AXIS, WF_AXIS, SP_AXIS))


def process_for_keys(keys: np.ndarray, mesh: Mesh, process_of=None,
                     routing=None) -> np.ndarray:
    """Owning process id per key: key -> kf group -> the process whose
    devices hold that kf row.  A multi-host source keeps only
    ``process_for_keys(k, mesh) == my_pid`` and never ships rows over
    DCN.  ``routing(keys, n_kf) -> groups`` must be the SAME function the
    deployment's emitters use (default: key % n, default_routing) — a
    mismatch would place rows on hosts that don't own their kf group."""
    n_kf = int(mesh.shape[KF_AXIS])
    if routing is None:
        from ..runtime.emitters import default_routing as routing
    pid = (process_of if process_of is not None
           else (lambda d: d.process_index))
    kf_owner = np.asarray(
        [pid(mesh.devices[g, 0, 0]) for g in range(n_kf)])
    return kf_owner[np.asarray(
        routing(np.asarray(keys, dtype=np.int64), n_kf), dtype=np.int64)]


def open_row_plane(my_pid: int, addresses: dict, capacity: int = 64,
                   wire=None, metrics=None, events=None,
                   decode_trace: bool = False, resume=None,
                   resume_epoch: int = None, ckpt_sink=None,
                   telemetry_sink=None):
    """Build the full cross-host row data plane for a process: one
    :class:`~windflow_tpu.parallel.channel.RowReceiver` listening at
    ``addresses[my_pid]`` and one hardened
    :class:`~windflow_tpu.parallel.channel.RowSender` per remote process,
    returned as ``(receiver, {pid: sender})`` — the handles
    ``partition_and_ship`` wants.

    ``addresses`` maps process id -> ``(host, port)`` for every process
    in the job (the deployment's static wiring, typically derived from
    the coordinator address + a port base).  ``wire`` is a
    :class:`~windflow_tpu.parallel.channel.WireConfig`; the default is
    ``WireConfig.hardened()`` — unlike the raw channel classes (whose
    bare defaults stay seed-identical), a *plane* built through this
    helper gets retries, heartbeats and stall timeouts out of the box,
    because hosts boot in arbitrary order and a production job must
    degrade loudly, not hang, when a peer dies (docs/ROBUSTNESS.md).
    Connect order is safe in any boot order: the receiver is bound
    before any outbound connect, and connects retry with backoff until
    the wire deadline.

    ``metrics`` (an ``obs.MetricsRegistry``) and ``events`` (an
    ``obs.EventLog``) opt the whole plane into wire telemetry: every
    channel of this process shares the one registry, so
    ``wire_bytes_sent`` / ``wire_connect_retries`` / heartbeat counters
    aggregate across peers, and reconnect/stall/abort events carry per
    -peer detail (docs/OBSERVABILITY.md).  Pass the owning Dataflow's
    ``.metrics`` / ``.events`` to fold the wire into its sampler
    output; both None (default) = no telemetry, seed-identical wire.

    ``decode_trace=True`` re-attaches inbound span-trace frames
    (``send(..., trace=obs.trace.export())`` on the peer) to their
    batches as ``TracedRows`` so a traced source on this host adopts
    them and the multihost graph stitches one trace
    (docs/OBSERVABILITY.md §tracing); the default discards them.

    ``resume`` (``True`` or a tuned
    :class:`~windflow_tpu.parallel.channel.WireResume`; default taken
    from ``wire.resume``) makes every edge of this plane *resumable*
    (docs/ROBUSTNESS.md "Wire resume"): senders journal outbound frames
    and replay the unacked tail over a fresh connection when a peer
    restarts, receivers dedup by seq — so peer death inside the resume
    deadline becomes a bounded retry instead of a graph error.  A
    RESTARTED process reopening its half of the plane passes
    ``resume_epoch=K`` (its last sealed checkpoint epoch): its receiver
    then asks each reconnecting sender to replay from the epoch-``K``
    barrier rather than from a seq it no longer remembers, which is
    exactly the wire tail the restored dataflow needs.  Unset (and
    unset on ``wire``) ⇒ the plane behaves byte-identically to before
    (no journal, no handshake).

    ``ckpt_sink`` (typically a ``recovery.portable.PortableSpool``)
    opts this process into RECEIVING peers' portable checkpoints (the
    ``-7`` wire family): each peer's sealed epochs land under the
    spool, which is what a :class:`~windflow_tpu.parallel.plane.
    PlaneSupervisor` successor restores a dead peer from
    (docs/ROBUSTNESS.md "Cross-host recovery").  Unset ⇒ the family is
    refused on arrival and nothing new is imported — the seed
    contract.

    ``telemetry_sink`` (typically an ``obs.federation.
    TelemetryAggregator``) opts this process into RECEIVING peers'
    federated-telemetry snapshots (the ``-8`` wire family,
    docs/OBSERVABILITY.md "Federation & SLOs").  Same contract as
    ``ckpt_sink``: unset ⇒ the family is refused on arrival and nothing
    new is imported."""
    from .channel import RowReceiver, RowSender, WireConfig
    if my_pid not in addresses:
        raise KeyError(f"addresses has no entry for this process "
                       f"(pid {my_pid}): {sorted(addresses)}")
    if wire is None:
        wire = WireConfig.hardened()
    wire.validate()   # reject heartbeat >= stall_timeout (WF205)
    host, port = addresses[my_pid]
    receiver = RowReceiver(n_senders=len(addresses) - 1, host=host,
                           port=port, capacity=capacity,
                           # wire= supplies stall_timeout and the
                           # accept deadline (a peer that dies before
                           # ever connecting must surface within the
                           # boot-order budget, not hang batches())
                           metrics=metrics, events=events,
                           decode_trace=decode_trace,
                           resume=resume, resume_epoch=resume_epoch,
                           ckpt_sink=ckpt_sink,
                           telemetry_sink=telemetry_sink, wire=wire)
    senders = {}
    try:
        for pid in sorted(addresses):
            if pid == my_pid:
                continue
            peer_host, peer_port = addresses[pid]
            senders[pid] = RowSender(
                peer_host, peer_port,
                metrics=metrics, events=events,
                resume=resume, wire=wire)
    except Exception:
        for snd in senders.values():
            snd.abort()
        receiver.close()
        raise
    return receiver, senders


def ship_epoch(senders: dict, epoch: int, my_pid: int = None):
    """Broadcast an epoch barrier frame on every outbound row channel of
    this process's data plane (the multihost half of the recovery
    layer's epoch alignment, docs/ROBUSTNESS.md "Recovery"): a source
    that injects epoch ``e`` locally calls this so remote consumers'
    ``batches(epoch_markers=True)`` aligns on the same boundary.  Call
    it AFTER the epoch's last ``partition_and_ship`` — the frame
    promises every row of epochs <= ``e`` is already on the wire.

    On a resumable plane (``open_row_plane(resume=...)``) the epoch
    frame is also the journal's unit of truncation: once the remote
    receiver acks epoch ``e`` (automatic under ``WireConfig(recovery=
    True)``), every journaled frame up to and including this barrier is
    dropped — so calling ``ship_epoch`` at your checkpoint cadence is
    what keeps sender journals bounded by one epoch's width."""
    for pid, snd in senders.items():
        if my_pid is not None and pid == my_pid:
            continue
        snd.send_epoch(epoch)


def local_kf_groups(mesh: Mesh, process_index=None,
                    process_of=None) -> np.ndarray:
    """The kf-group indices whose device rows live on this process."""
    if process_index is None:
        process_index = jax.process_index()
    n_kf = int(mesh.shape[KF_AXIS])
    pid = (process_of if process_of is not None
           else (lambda d: d.process_index))
    return np.asarray([g for g in range(n_kf)
                       if pid(mesh.devices[g, 0, 0]) == process_index])
