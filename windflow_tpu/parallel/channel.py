"""Cross-process row channels: the multi-host data plane.

The reference's "communication backend" is FastFlow shared-memory queues
between threads of ONE process (SURVEY.md §2.8 — no sockets, no MPI).
The multi-host deployment model (parallel/multihost.py) keeps key groups
process-local so the common case ships nothing — but a source whose input
is NOT naturally key-partitioned (a socket feed, a file) must be able to
forward rows to the process that owns their kf group.  This module is
that hop: a typed, length-framed, batched TCP channel carrying the same
SoA batches the in-process engine queues carry, so a remote stage slots
into a pipeline exactly like a local one.

Design notes (DCN-analog, deliberately boring):

* batches cross as raw structured-array bytes with an 8-byte length
  frame; the dtype travels once per connection as JSON of
  ``np.dtype(...).descr`` — a pure data encoding, so a hostile peer can
  at worst describe a weird dtype, never execute code (the channel
  trusts its cluster for data *integrity*, like NCCL/MPI transports do,
  but the wire format must not turn that trust into code execution);
* one receiver accepts any number of senders; per-connection reader
  threads feed one bounded queue, preserving per-sender batch order
  (cross-sender order is interleaved, as with any multi-producer edge —
  an OrderingNode downstream restores it where required);
* EOS is an empty frame per sender; ``batches()`` ends when every
  registered sender has closed — the FastFlow EOS cascade, one level up;
* hardening (all opt-in; with the knobs unset the bytes on the wire and
  the failure behavior are identical to the original protocol):

  - *connect retry*: ``RowSender(connect_deadline=...)`` retries a
    refused connection with exponential backoff + full jitter until the
    total deadline — peers may boot in any order;
  - *heartbeats*: ``RowSender(heartbeat=...)`` ships an empty control
    frame (length ``-2``) whenever the link has been idle for one
    interval, and passively probes the socket so a dead receiver
    surfaces at the *sender* within ~one interval too;
  - *stall timeout*: ``RowReceiver(stall_timeout=...)`` bounds how long
    ``_read_exact`` may sit on a silent socket — a peer that stalls
    mid-frame (or stops heartbeating) surfaces as :class:`PeerStall`
    instead of hanging the reader forever;
  - *abort vs EOS*: ``RowSender.abort()`` sends frame ``-3`` — the
    receiver raises :class:`PeerAbort` instead of counting a clean EOS,
    so a producer that died mid-stream can never silently truncate the
    stream;
  - *telemetry*: ``metrics=`` (an obs.MetricsRegistry) counts
    bytes/frames sent and received, connect retries and heartbeats
    sent/received/missed; ``events=`` (an obs.EventLog) records
    reconnect attempts, heartbeat misses and peer stalls/aborts
    (docs/OBSERVABILITY.md).  Both off (default) ⇒ the data path pays a
    single predictable branch per frame.
"""

from __future__ import annotations

import errno
import json
import queue
import random
import select
import socket
import struct
import threading
import time
from collections import deque

import numpy as np

_LEN = struct.Struct("<q")

#: control-frame codes carried in the length slot (negative = no
#: payload, except EPOCH — an 8-byte epoch follows — and TRACE — one
#: ordinary length-prefixed JSON payload describing the NEXT data
#: frame's span follows)
_EOS_FRAME = -1        # clean end-of-stream (original protocol)
_HEARTBEAT_FRAME = -2  # liveness beacon; carries no data
_ABORT_FRAME = -3      # sender died mid-stream: NOT a clean EOS
_EPOCH_FRAME = -4      # epoch barrier marker; 8-byte epoch payload follows
_TRACE_FRAME = -5      # span context for the next data frame (opt-in)
_RESUME_FRAME = -6     # resume protocol (docs/ROBUSTNESS.md "Wire
#                        resume"); an 8-byte subtype follows:
_RS_HELLO = 1          # sender->receiver on (re)connect: JSON
#                        {token, lo, hi} — identity + journal seq range
_RS_WELCOME = 2        # receiver->sender reply: JSON {"seq": S} (resume
#                        after the last contiguous seq delivered) or
#                        {"epoch": E} (a restarted receiver resuming from
#                        its last sealed checkpoint)
_RS_SEQ = 3            # sender->receiver: 8-byte seq tagging the NEXT
#                        data/epoch frame (the wire Tagged envelope)
_RS_ACK = 4            # receiver->sender: JSON {"epoch": E} | {"seq": S}
#                        — cumulative sealed ack; the sender trims its
#                        journal through it
_CKPT_FRAME = -7       # portable-checkpoint family (docs/ROBUSTNESS.md
#                        "Cross-host recovery"); an 8-byte subtype
#                        follows:
_CK_OFFER = 1          # sender->receiver: length-prefixed JSON portable
#                        header {v, origin, epoch, partial, nodes}
_CK_BLOB = 2           # sender->receiver: length-prefixed JSON envelope
#                        {origin, epoch, node, bytes, crc}, then the raw
#                        blob of exactly `bytes` bytes
_CK_COMMIT = 3         # sender->receiver: length-prefixed JSON {origin,
#                        epoch} — every blob shipped, the spool may seal
_TELEMETRY_FRAME = -8  # federated-telemetry snapshot
#                        (docs/OBSERVABILITY.md "Federation & SLOs"): one
#                        length-prefixed JSON snapshot follows — periodic,
#                        idempotent, never journaled (the next snapshot
#                        supersedes a lost one)


def _send_resume_frame(sock, sub: int, payload: dict):
    js = json.dumps(payload).encode("utf-8")
    sock.sendall(_LEN.pack(_RESUME_FRAME) + _LEN.pack(sub)
                 + _LEN.pack(len(js)) + js)


def _read_resume_json(sock) -> dict:
    n = _LEN.unpack(_read_exact(sock, _LEN.size))[0]
    if not 0 <= n <= (1 << 20):
        raise ChannelError(f"bad resume-frame payload length {n}")
    return json.loads(_read_exact(sock, n).decode("utf-8"))


class TracedRows(np.ndarray):
    """A received batch carrying its sender's span context: a plain
    ndarray view with one extra attribute, ``wf_trace`` (the dict the
    sender passed to ``send(..., trace=...)``, typically
    ``obs.trace.export()``), so consumers that don't care handle it
    exactly like any other batch — and a traced source node's emit
    *adopts* it automatically (obs/trace.py), stitching the remote trace
    onto the local graph.  Only produced by a
    ``RowReceiver(decode_trace=True)``; a default receiver consumes and
    discards trace frames (the field is *optional* on the wire)."""

    wf_trace = None

    def __array_finalize__(self, obj):
        if obj is not None:
            self.wf_trace = getattr(obj, "wf_trace", None)


class ChannelError(ConnectionError):
    """Protocol-level row-channel failure (bad frame, dead peer)."""


class PeerStall(ChannelError):
    """The peer went silent past the receiver's stall timeout — neither
    data nor heartbeat frames arrived (likely hung or partitioned)."""


class PeerAbort(ChannelError):
    """The peer closed with an ABORT frame: it failed mid-stream, so the
    data received so far is a truncated prefix, not a complete stream."""


class WireResume:
    """Knobs of the wire resume protocol (``WireConfig(resume=...)``,
    docs/ROBUSTNESS.md "Wire resume").  ``deadline`` bounds how long a
    broken edge may spend reconnecting before the failure turns fatal
    (the "bounded retry" promise); ``journal_frames`` caps the sender's
    replay journal — past it the oldest *unacked* record is evicted and
    any resume that would need it fails loudly instead of silently
    truncating the stream."""

    __slots__ = ("deadline", "journal_frames")

    def __init__(self, deadline: float = 30.0, journal_frames: int = 4096):
        self.deadline = float(deadline)
        self.journal_frames = int(journal_frames)

    def validate(self) -> "WireResume":
        if self.deadline <= 0:
            raise ValueError("WireResume: deadline must be positive "
                             "seconds (the bounded-retry window)")
        if self.journal_frames < 1:
            raise ValueError("WireResume: journal_frames must retain at "
                             "least 1 record")
        return self


def _as_resume(value):
    """Normalise the ``resume=`` knob: None/False = off, True = default
    :class:`WireResume`, an instance passes through."""
    if value is None or value is False:
        return None
    if value is True:
        return WireResume()
    if isinstance(value, WireResume):
        return value
    raise TypeError(f"resume= must be True/False/None or a WireResume, "
                    f"got {value!r}")


class WireConfig:
    """Bundle of the wire-hardening knobs, for APIs that build several
    channels at once (``multihost.open_row_plane``).  Defaults match the
    un-hardened seed protocol; ``WireConfig.hardened()`` gives the
    recommended production settings (docs/ROBUSTNESS.md).

    ``resume`` (True or a :class:`WireResume`) opts the edge into the
    resume protocol: the sender journals every record and a broken
    connection becomes a bounded reconnect-handshake-replay cycle
    instead of a fatal error.  ``recovery=True`` declares that the
    deployment acks sealed epochs back to the senders
    (``RowReceiver.ack_epoch`` — wired automatically by
    ``batches(epoch_markers=True)`` barriers when set), which is what
    bounds the journal by epoch width; ``resume`` without it is
    statically flagged as WF214.  ``faults`` (a
    ``parallel.faults.FaultPlan``) injects deterministic wire chaos on
    the senders — a test/soak knob, never imported unless set."""

    __slots__ = ("connect_timeout", "connect_deadline", "heartbeat",
                 "stall_timeout", "resume", "recovery", "faults")

    def __init__(self, connect_timeout: float = 30.0,
                 connect_deadline: float = None, heartbeat: float = None,
                 stall_timeout: float = None, resume=None,
                 recovery: bool = False, faults=None):
        self.connect_timeout = connect_timeout
        self.connect_deadline = connect_deadline
        self.heartbeat = heartbeat
        self.stall_timeout = stall_timeout
        self.resume = resume
        self.recovery = bool(recovery)
        self.faults = faults

    @classmethod
    def hardened(cls) -> "WireConfig":
        """Production defaults: 60 s connect deadline (peers boot in any
        order), 2 s heartbeats, 10 s stall timeout (= 5 missed beats)."""
        return cls(connect_deadline=60.0, heartbeat=2.0, stall_timeout=10.0)

    def validate(self) -> "WireConfig":
        """Reject internally inconsistent knob pairings (docs/CHECKS.md
        WF205): a heartbeat interval at or above the stall timeout makes
        every healthy-but-idle link stall out — the receiver gives up
        before the next beat can arrive.  Size ``stall_timeout`` to
        several heartbeat intervals (``hardened()`` uses 2 s / 10 s).
        Called by ``open_row_plane`` AND by the ``RowSender``/
        ``RowReceiver`` constructors; returns self so it chains."""
        if (self.heartbeat is not None and self.stall_timeout is not None
                and self.heartbeat >= self.stall_timeout):
            raise ValueError(
                f"[WF205] WireConfig: heartbeat ({self.heartbeat}s) must "
                f"be < stall_timeout ({self.stall_timeout}s) — the "
                f"receiver would declare PeerStall before a healthy "
                f"peer's next beat arrives")
        rs = _as_resume(self.resume)
        if rs is not None:
            rs.validate()
        if self.faults is not None and not callable(
                getattr(self.faults, "action_for", None)):
            raise TypeError("WireConfig: faults= must provide "
                            "action_for(n) (parallel.faults.FaultPlan)")
        return self


def _encode_dtype(dtype) -> bytes:
    """JSON-encode a dtype via numpy's ``.npy``-format codec
    (``np.lib.format.dtype_to_descr``) — the one descr form numpy
    guarantees round-trippable, covering nested structs, align padding,
    sub-arrays, and unstructured dtypes (plain format strings).  ``None``
    (the EOS-before-data placeholder) encodes as JSON ``null``."""
    if dtype is None:
        return b"null"
    return json.dumps(np.lib.format.dtype_to_descr(np.dtype(dtype))
                      ).encode("utf-8")


def _tuplify_descr(d):
    """JSON turns descr tuples into lists; ``descr_to_dtype`` wants the
    original shapes back, recursively: a descr is a list of field-entry
    *tuples* (possibly nested as a field's format), while sub-array
    shapes and (title, name) pairs are tuples of scalars."""
    if not isinstance(d, list):
        return d
    if d and all(isinstance(e, list) for e in d):
        # a (possibly nested) struct descr: keep the list, tuplify entries
        return [tuple(_tuplify_descr(x) for x in e) for e in d]
    # a sub-array shape or a (title, name) pair
    return tuple(_tuplify_descr(x) for x in d)


def _decode_dtype(raw: bytes):
    """Inverse of :func:`_encode_dtype`."""
    descr = json.loads(raw.decode("utf-8"))
    if descr is None:
        return None
    return np.lib.format.descr_to_dtype(_tuplify_descr(descr))


def _read_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("row channel peer closed mid-frame")
        buf.extend(chunk)
    return bytes(buf)


#: connect errnos worth retrying: the peer is not up YET (boot order) or
#: the path is transiently unreachable.  Config mistakes — unresolvable
#: hostname (gaierror), permission — fail immediately instead of burning
#: the whole deadline.
_TRANSIENT_CONNECT_ERRNOS = frozenset({
    errno.ECONNREFUSED, errno.ECONNRESET, errno.ECONNABORTED,
    errno.ETIMEDOUT, errno.EHOSTUNREACH, errno.EHOSTDOWN,
    errno.ENETUNREACH, errno.ENETDOWN, errno.EAGAIN, errno.EINTR,
})


class _WireTelemetry:
    """One sender's (or receiver's) view into the observability layer:
    pre-resolved counter handles plus the event log, so the data path
    pays one ``self._tm is not None`` branch when telemetry is off and
    plain counter increments when it is on (docs/OBSERVABILITY.md wire
    counters)."""

    __slots__ = ("events", "bytes_sent", "frames_sent", "bytes_recv",
                 "frames_recv", "connect_retries", "heartbeats_sent",
                 "heartbeats_recv", "heartbeat_misses", "traces_sent",
                 "traces_recv", "resumes", "replayed_frames", "acks_sent",
                 "acks_recv", "journal_depth", "ckpt_shipped_bytes",
                 "ckpt_fetched_bytes", "fed_shipped_bytes",
                 "fed_fetched_bytes")

    def __init__(self, metrics, events=None):
        self.events = events
        c = metrics.counter
        self.bytes_sent = c("wire_bytes_sent")
        self.frames_sent = c("wire_frames_sent")
        self.bytes_recv = c("wire_bytes_recv")
        self.frames_recv = c("wire_frames_recv")
        self.connect_retries = c("wire_connect_retries")
        self.heartbeats_sent = c("wire_heartbeats_sent")
        self.heartbeats_recv = c("wire_heartbeats_recv")
        self.heartbeat_misses = c("wire_heartbeat_misses")
        self.traces_sent = c("wire_traces_sent")
        self.traces_recv = c("wire_traces_recv")
        # resume protocol (docs/ROBUSTNESS.md "Wire resume")
        self.resumes = c("wire_resumes")
        self.replayed_frames = c("wire_replayed_frames")
        self.acks_sent = c("wire_acks_sent")
        self.acks_recv = c("wire_acks_recv")
        self.journal_depth = metrics.gauge("wire_journal_depth")
        # portable checkpoints (docs/ROBUSTNESS.md "Cross-host recovery")
        self.ckpt_shipped_bytes = c("ckpt_shipped_bytes")
        self.ckpt_fetched_bytes = c("ckpt_fetched_bytes")
        # federated telemetry (docs/OBSERVABILITY.md "Federation & SLOs")
        self.fed_shipped_bytes = c("fed_shipped_bytes")
        self.fed_fetched_bytes = c("fed_fetched_bytes")

    def emit(self, event: str, **fields):
        if self.events is not None:
            self.events.emit(event, **fields)


def _telemetry(metrics, events):
    """None when both knobs are off — the single-branch disabled path."""
    if metrics is None and events is None:
        return None
    if metrics is None:
        # events-only caller: counters land in a private throwaway
        # registry so the handles stay non-None (one code path)
        from ..obs.registry import MetricsRegistry
        metrics = MetricsRegistry()
    return _WireTelemetry(metrics, events)


def _connect_with_backoff(host: str, port: int, timeout: float,
                          deadline: float, tm=None) -> socket.socket:
    """Retry a refused/unreachable connect with exponential backoff and
    full jitter until `deadline` seconds have elapsed — the peer's
    receiver may simply not be up yet (hosts boot in any order)."""
    t_end = time.monotonic() + deadline
    attempt = 0
    last_err = None
    while True:
        try:
            # clamp the per-attempt timeout to the remaining deadline so
            # a blackholed host (SYN dropped, no RST) cannot overshoot
            # the promised bound by a whole attempt
            left = max(t_end - time.monotonic(), 0.001)
            return socket.create_connection((host, port),
                                            timeout=min(timeout, left))
        except socket.gaierror:
            raise   # unresolvable name: a config error, not boot order
        except socket.timeout as e:
            last_err = e    # per-attempt timeout: transient by definition
        except OSError as e:
            if e.errno not in _TRANSIENT_CONNECT_ERRNOS:
                raise
            last_err = e
        remaining = t_end - time.monotonic()
        if remaining <= 0:
            raise ConnectionError(
                f"row channel connect to {host}:{port} failed for "
                f"{deadline}s ({attempt + 1} attempts); last error: "
                f"{last_err}") from last_err
        if tm is not None:
            tm.connect_retries.inc()
            tm.emit("reconnect_attempt", host=host, port=port,
                    attempt=attempt + 1,
                    error=type(last_err).__name__)
        # full jitter over an exponentially growing window, capped
        backoff = random.uniform(0, min(2.0, 0.05 * (2 ** attempt)))
        time.sleep(min(backoff, remaining))
        attempt += 1


class RowSender:
    """Client end: ships structured-array batches to a RowReceiver.

    ``connect_deadline`` (seconds) opts into connect retry with backoff;
    ``heartbeat`` (seconds) opts into idle-link liveness frames.  Both
    default to off = the original single-attempt, silent-link protocol.

    ``resume`` (True / :class:`WireResume`) opts into the resume
    protocol (docs/ROBUSTNESS.md "Wire resume"): every data/epoch
    record is journaled with a monotone seq and tagged on the wire; a
    link failure on an established edge becomes a bounded
    reconnect-handshake-replay cycle (reusing the connect backoff
    machinery) instead of a fatal error, and sealed-epoch ACK frames
    from the receiver trim the journal.  ``faults`` (a
    ``parallel.faults.FaultPlan``) injects deterministic chaos into the
    transmit path — a test knob.  ``wire`` (a :class:`WireConfig`)
    supplies any knob not given explicitly and is validated here, so a
    direct-constructed sender can no longer carry an inconsistent
    bundle unchecked.
    """

    def __init__(self, host: str, port: int, timeout: float = 30.0,
                 connect_deadline: float = None, heartbeat: float = None,
                 metrics=None, events=None, resume=None, faults=None,
                 wire: WireConfig = None):
        if wire is not None:
            wire.validate()
            timeout = wire.connect_timeout
            if connect_deadline is None:
                connect_deadline = wire.connect_deadline
            if heartbeat is None:
                heartbeat = wire.heartbeat
            if resume is None:
                resume = wire.resume
            if faults is None:
                faults = wire.faults
        #: wire telemetry (obs registry counters + event log); None —
        #: the default — keeps every data-path hook to a single branch
        self._tm = _telemetry(metrics, events)
        self._host, self._port = host, port
        self._timeout = timeout
        self._resume = _as_resume(resume)
        self._faults = faults
        if connect_deadline is None:
            self._sock = socket.create_connection((host, port),
                                                  timeout=timeout)
        else:
            self._sock = _connect_with_backoff(host, port, timeout,
                                               float(connect_deadline),
                                               tm=self._tm)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._dtype_sent = None
        self._send_lock = threading.Lock()
        self._last_send = time.monotonic()
        #: set to the underlying OSError when close() could not deliver
        #: EOS (peer already dead) — the shutdown was NOT clean
        self.failed = None
        self._hb_error = None
        self._hb_stop = None
        self._hb_thread = None
        self._hb_interval = None if heartbeat is None else float(heartbeat)
        if self._resume is not None:
            #: the resume journal: (seq, kind, a, b) where kind "d" is a
            #: data record (a = trace bytes or None, b = payload bytes)
            #: and kind "e" an epoch record (a = epoch int).  Guarded by
            #: _journal_mu — the ACK reader thread trims concurrently.
            self._journal = deque()
            self._journal_mu = threading.Lock()
            self._next_seq = 1
            #: resume is impossible at or below this seq: records there
            #: were trimmed (acked — the receiver vouches for them) or
            #: evicted (journal_frames cap — loud failure if needed)
            self._floor = 0
            self._trimmed_epoch = None
            self._dtype = None
            self._fault_n = 0
            self._link_down = False
            self._closing = False
            self._token = "%016x" % random.getrandbits(64)
            self._ack_thread = None
            s = self._rs_handshake()
            self._rs_replay(s)      # no-op on a fresh journal
            self._start_ack_thread()
        if self._hb_interval is not None:
            self._start_heartbeat()

    # -- liveness ----------------------------------------------------------

    def _start_heartbeat(self):
        self._hb_stop = threading.Event()
        self._hb_thread = threading.Thread(
            target=self._hb_loop, args=(self._hb_interval,),
            daemon=True, name="wf-rowsend-hb")
        self._hb_thread.start()

    def _hb_loop(self, interval: float):
        while not self._hb_stop.wait(interval):
            try:
                # passive death probe: the receiver never sends data, so
                # any EOF/RST here means the peer is gone — surface it
                # now instead of at the next (possibly far-away) send.
                # (A plain recv would honor the socket timeout and block
                # the beat; select(0) keeps the probe non-blocking.)
                # With resume on, the ACK reader thread owns recv — the
                # probe would swallow ACK bytes, so death is its job.
                if self._resume is None:
                    try:
                        readable, _, _ = select.select([self._sock], [],
                                                       [], 0)
                    except ValueError:
                        # fd beyond select's FD_SETSIZE (huge-process
                        # case): skip the probe, the beat itself must
                        # still go out
                        readable = []
                    if readable and self._sock.recv(4096) == b"":
                        raise ConnectionError(
                            "row channel peer closed the connection")
                with self._send_lock:
                    if time.monotonic() - self._last_send >= interval:
                        self._sock.sendall(_LEN.pack(_HEARTBEAT_FRAME))
                        self._last_send = time.monotonic()
                        if self._tm is not None:
                            self._tm.heartbeats_sent.inc()
            except OSError as e:
                self._hb_error = e
                if self._tm is not None:
                    self._tm.heartbeat_misses.inc()
                    self._tm.emit("heartbeat_miss",
                                  error=type(e).__name__, message=str(e))
                return

    def _check_alive(self):
        if self._hb_error is not None:
            raise ChannelError(
                f"row channel peer dead (heartbeat): {self._hb_error}"
            ) from self._hb_error

    def _stop_heartbeat(self):
        if self._hb_stop is not None:
            self._hb_stop.set()
            self._hb_thread.join(timeout=5.0)

    # -- resume protocol (docs/ROBUSTNESS.md "Wire resume") ----------------

    def _rs_handshake(self) -> int:
        """HELLO/WELCOME on a fresh connection; returns the receiver's
        resume point S (journal records with seq > S get replayed).
        Raises :class:`ChannelError` when the journal can no longer
        cover the requested tail — a resume that would silently
        truncate the stream must fail loudly instead."""
        with self._journal_mu:
            lo = self._journal[0][0] if self._journal else self._next_seq
            hi = self._next_seq - 1
        _send_resume_frame(self._sock, _RS_HELLO,
                           {"token": self._token, "lo": lo, "hi": hi})
        n = _LEN.unpack(_read_exact(self._sock, _LEN.size))[0]
        sub = (_LEN.unpack(_read_exact(self._sock, _LEN.size))[0]
               if n == _RESUME_FRAME else None)
        if sub != _RS_WELCOME:
            raise ChannelError(
                f"resume handshake: expected WELCOME, peer sent frame "
                f"{n}/{sub} (is the receiver's resume= on?)")
        w = _read_resume_json(self._sock)
        with self._journal_mu:
            if "seq" in w:
                s = int(w["seq"])
            else:
                s = self._rs_seq_of_epoch(int(w["epoch"]))
            if s < self._floor:
                raise ChannelError(
                    f"[resume] receiver needs records from seq {s + 1}, "
                    f"but this journal no longer holds anything at or "
                    f"below seq {self._floor} (acked-and-trimmed or "
                    f"evicted past journal_frames="
                    f"{self._resume.journal_frames}) — replay would "
                    f"silently truncate the stream, failing loudly "
                    f"instead")
        return s

    def _rs_seq_of_epoch(self, epoch: int) -> int:
        """Map a WELCOME ``{"epoch": E}`` resume point to a seq: replay
        starts after epoch E's marker record.  Caller holds _journal_mu."""
        if epoch <= 0:
            return 0    # a fresh receiver: everything
        for seq, kind, a, _b in self._journal:
            if kind == "e" and a == epoch:
                return seq
        if epoch == self._trimmed_epoch:
            # trimmed/evicted exactly at this marker: the retained tail
            # is exactly the records after it
            return self._floor
        raise ChannelError(
            f"[resume] receiver resumes from epoch {epoch}, which this "
            f"sender's journal cannot locate (sealed acks ran ahead or "
            f"the epoch was never shipped) — cannot replay")

    def _rs_replay(self, s: int) -> int:
        """Re-transmit every journaled record with seq > ``s`` on the
        (fresh) connection; returns the replay count."""
        n = 0
        with self._journal_mu:
            todo = [rec for rec in self._journal if rec[0] > s]
        for rec in todo:
            self._transmit(rec)
            n += 1
        return n

    def _journal_push(self, rec):
        with self._journal_mu:
            self._journal.append(rec)
            if len(self._journal) > self._resume.journal_frames:
                old = self._journal.popleft()
                self._floor = max(self._floor, old[0])
                if old[1] == "e":
                    # evicting through a marker is equivalent to a trim
                    # at it: the retained tail still resumes that epoch
                    self._trimmed_epoch = old[2]
            if self._tm is not None:
                self._tm.journal_depth.set(len(self._journal))

    def _apply_ack(self, w: dict):
        """Trim the journal through a cumulative ACK (the reader
        thread's half of the seal contract)."""
        with self._journal_mu:
            if "epoch" in w:
                e = int(w["epoch"])
                t = None
                for seq, kind, a, _b in self._journal:
                    if kind == "e" and a == e:
                        t = seq
                        break
                if t is None:
                    return   # already trimmed past it: idempotent no-op
            else:
                t = int(w["seq"])
            while self._journal and self._journal[0][0] <= t:
                old = self._journal.popleft()
                if old[1] == "e":
                    self._trimmed_epoch = old[2]
            self._floor = max(self._floor, t)
            if self._tm is not None:
                self._tm.acks_recv.inc()
                self._tm.journal_depth.set(len(self._journal))

    def _start_ack_thread(self):
        t = threading.Thread(target=self._ack_loop, args=(self._sock,),
                             daemon=True, name="wf-rowsend-ack")
        t.start()
        self._ack_thread = t

    def _ack_loop(self, sock):
        """Owns recv on the resume connection: applies ACK frames and
        marks the link down on EOF/reset so the next send resumes
        proactively.  Exits silently when superseded by a reconnect
        (its socket is no longer ``self._sock``) or on close()."""
        try:
            while not self._closing:
                try:
                    r, _, _ = select.select([sock], [], [], 0.25)
                except (OSError, ValueError):
                    return
                if not r:
                    continue
                n = _LEN.unpack(_read_exact(sock, _LEN.size))[0]
                if n != _RESUME_FRAME:
                    raise ChannelError(
                        f"unexpected frame {n} from receiver on resume "
                        f"channel")
                sub = _LEN.unpack(_read_exact(sock, _LEN.size))[0]
                if sub != _RS_ACK:
                    raise ChannelError(
                        f"unexpected resume subtype {sub} from receiver")
                self._apply_ack(_read_resume_json(sock))
        except (OSError, ValueError):
            if not self._closing and sock is self._sock:
                self._link_down = True

    def _transmit(self, rec):
        """Write one journaled record's frames (SEQ tag + payload) on
        the current connection; the single place the fault plan hooks.
        Caller holds _send_lock."""
        seq, kind, a, b = rec
        act = None
        if self._faults is not None:
            self._fault_n += 1
            act = self._faults.action_for(self._fault_n)
        tm = self._tm
        if kind == "e":
            frame = (_LEN.pack(_RESUME_FRAME) + _LEN.pack(_RS_SEQ)
                     + _LEN.pack(seq)
                     + _LEN.pack(_EPOCH_FRAME) + _LEN.pack(a))
        else:
            if self._dtype_sent is None:
                # dtype travels once per CONNECTION (not per stream):
                # resent untagged after every reconnect
                d = _encode_dtype(self._dtype)
                self._sock.sendall(_LEN.pack(len(d)) + d)
                self._dtype_sent = self._dtype
                if tm is not None:
                    tm.frames_sent.inc()
                    tm.bytes_sent.inc(_LEN.size + len(d))
            frame = (_LEN.pack(_RESUME_FRAME) + _LEN.pack(_RS_SEQ)
                     + _LEN.pack(seq))
            if a is not None:
                frame += (_LEN.pack(_TRACE_FRAME) + _LEN.pack(len(a)) + a)
                if tm is not None:
                    tm.traces_sent.inc()
            frame += _LEN.pack(len(b)) + b
        if act == "kill":
            self._sock.close()
            raise ConnectionResetError(f"[fault] killed before record "
                                       f"{self._fault_n}")
        if act == "torn":
            self._sock.sendall(frame[:max(1, len(frame) // 2)])
            self._sock.close()
            raise ConnectionResetError(f"[fault] torn frame at record "
                                       f"{self._fault_n}")
        if act == "stall":
            stall_for = self._faults.stall_for
            time.sleep(stall_for)
            self._sock.close()
            raise ConnectionResetError(f"[fault] stalled {stall_for}s "
                                       f"then dropped at record "
                                       f"{self._fault_n}")
        self._sock.sendall(frame)
        if act == "dup":
            self._sock.sendall(frame)   # duplicated delivery: the
            #                             receiver must dedup by seq
        self._last_send = time.monotonic()
        if tm is not None:
            tm.frames_sent.inc()
            tm.bytes_sent.inc(len(frame) * (2 if act == "dup" else 1))

    def _deliver(self, rec):
        """Transmit one record, entering the bounded resume cycle on any
        link failure.  Caller holds _send_lock."""
        if not self._link_down and self._hb_error is None:
            try:
                self._transmit(rec)
                return
            except ChannelError:
                raise
            except OSError as e:
                err = e
        else:
            err = self._hb_error or ConnectionError(
                "row channel link marked down by the ack reader")
        self._resume_cycle(err)

    def _resume_cycle(self, err):
        """Reconnect + handshake + replay within the resume deadline
        (the journaled record that just failed replays too).  Caller
        holds _send_lock.  Raises :class:`ChannelError` once the
        deadline is spent — the bounded-retry promise."""
        rs = self._resume
        tm = self._tm
        if tm is not None:
            tm.emit("wire_down", role="sender", host=self._host,
                    port=self._port, error=type(err).__name__,
                    message=str(err))
        t_end = time.monotonic() + rs.deadline
        try:
            self._sock.close()
        except OSError:
            pass
        self._hb_error = None
        # the link IS down until the cycle completes: anything polling
        # the flag concurrently (plane supervisor membership, a
        # replicate() skip) must see the truth mid-cycle, or a peer
        # death is masked for the whole reconnect deadline
        self._link_down = True
        last = err
        while True:
            left = t_end - time.monotonic()
            if left <= 0:
                self._link_down = True
                raise ChannelError(
                    f"[resume] could not re-establish the row channel to "
                    f"{self._host}:{self._port} within {rs.deadline}s; "
                    f"last error: {last}") from last
            try:
                self._sock = _connect_with_backoff(
                    self._host, self._port, self._timeout, left, tm=tm)
                self._sock.setsockopt(socket.IPPROTO_TCP,
                                      socket.TCP_NODELAY, 1)
                self._dtype_sent = None
                s = self._rs_handshake()
                n = self._rs_replay(s)
            except ChannelError:
                raise   # protocol-fatal: journal cannot cover the tail
            except OSError as e:
                last = e
                try:
                    self._sock.close()
                except OSError:
                    pass
                continue
            break
        self._link_down = False
        if tm is not None:
            tm.resumes.inc()
            tm.replayed_frames.inc(n)
            tm.emit("wire_resume", role="sender", replayed=n, from_seq=s,
                    host=self._host, port=self._port)
        self._start_ack_thread()
        if (self._hb_interval is not None and self._hb_stop is not None
                and not self._hb_stop.is_set()
                and not self._hb_thread.is_alive()):
            # the beat thread died with the old link: revive it
            self._start_heartbeat()

    def _transmit_eos(self):
        """EOS on the current connection (resume path); preceded by the
        dtype placeholder when this connection never carried one, so
        the receiver's framing stays uniform.  Caller holds _send_lock."""
        if self._dtype_sent is None:
            d = _encode_dtype(self._dtype)
            self._sock.sendall(_LEN.pack(len(d)) + d)
            self._dtype_sent = self._dtype if self._dtype is not None \
                else True
        self._sock.sendall(_LEN.pack(_EOS_FRAME))

    # -- data path ---------------------------------------------------------

    def send(self, batch: np.ndarray, trace: dict = None):
        """Ship one batch.  ``trace`` (optional, a small JSON-able dict
        — typically ``obs.trace.export()``) rides ahead of the data as a
        TRACE control frame, so a span sampled on this host survives the
        row-plane hop (a ``decode_trace=True`` receiver reattaches it,
        any other receiver discards it).  ``trace=None`` — the default —
        keeps the bytes on the wire identical to the original
        protocol."""
        if len(batch) == 0:
            return
        if self._resume is not None:
            # resume path: journal the record, then deliver (any link
            # failure turns into the bounded reconnect/replay cycle)
            if self._dtype is None:
                self._dtype = batch.dtype
            elif batch.dtype != self._dtype:
                raise TypeError(
                    f"row channel dtype changed mid-stream: "
                    f"{self._dtype} -> {batch.dtype}")
            tp = (json.dumps(trace).encode("utf-8")
                  if trace is not None else None)
            payload = np.ascontiguousarray(batch).tobytes()
            with self._send_lock:
                rec = (self._next_seq, "d", tp, payload)
                self._next_seq += 1
                self._journal_push(rec)
                self._deliver(rec)
            return
        self._check_alive()
        with self._send_lock:
            if self._dtype_sent is None:
                d = _encode_dtype(batch.dtype)
                self._sock.sendall(_LEN.pack(len(d)) + d)
                self._dtype_sent = batch.dtype
                if self._tm is not None:
                    self._tm.frames_sent.inc()
                    self._tm.bytes_sent.inc(_LEN.size + len(d))
            elif batch.dtype != self._dtype_sent:
                raise TypeError(
                    f"row channel dtype changed mid-stream: "
                    f"{self._dtype_sent} -> {batch.dtype}")
            if trace is not None:
                tp = json.dumps(trace).encode("utf-8")
                self._sock.sendall(_LEN.pack(_TRACE_FRAME)
                                   + _LEN.pack(len(tp)) + tp)
                if self._tm is not None:
                    self._tm.traces_sent.inc()
                    self._tm.bytes_sent.inc(2 * _LEN.size + len(tp))
            payload = np.ascontiguousarray(batch).tobytes()
            self._sock.sendall(_LEN.pack(len(payload)) + payload)
            self._last_send = time.monotonic()
            if self._tm is not None:
                self._tm.frames_sent.inc()
                self._tm.bytes_sent.inc(_LEN.size + len(payload))

    def send_epoch(self, epoch: int):
        """Ship an epoch barrier control frame (recovery layer,
        docs/ROBUSTNESS.md "Recovery"): "every row of epochs <=
        ``epoch`` has been sent on this channel".  The receiver aligns
        markers across all its senders (``batches(epoch_markers=True)``)
        so multihost rows align on the same epochs as in-process edges.
        Like every hardening knob: never sent unless the application
        calls it, so the bytes on the wire stay seed-identical
        otherwise."""
        if self._resume is not None:
            with self._send_lock:
                rec = (self._next_seq, "e", int(epoch), None)
                self._next_seq += 1
                self._journal_push(rec)
                self._deliver(rec)
            return
        self._check_alive()
        with self._send_lock:
            self._sock.sendall(_LEN.pack(_EPOCH_FRAME)
                               + _LEN.pack(int(epoch)))
            self._last_send = time.monotonic()
            if self._tm is not None:
                self._tm.frames_sent.inc()
                self._tm.bytes_sent.inc(2 * _LEN.size)

    def send_ckpt(self, header: dict, blobs) -> int:
        """Stream one sealed epoch's portable checkpoint (``-7`` family,
        docs/ROBUSTNESS.md "Cross-host recovery"): OFFER header, one
        BLOB frame per ``(meta, raw)`` of ``blobs``, then COMMIT.  The
        receiving side must run a ``ckpt_sink=`` (typically a
        ``recovery.portable.PortableSpool``).

        Checkpoint frames are NOT journaled — shipping is idempotent
        (the spool seals per (origin, epoch), re-ships overwrite
        bit-identically), so on a resumable link a mid-ship failure
        gets one resume cycle (reconnect + data-journal replay) and a
        clean retransmit from the OFFER; past that — or on a plain
        link — the failure raises and the caller retries at its next
        seal.  Like every hardening knob: never sent unless the
        application calls it, so the bytes on the wire stay
        seed-identical otherwise."""
        blobs = list(blobs)
        with self._send_lock:
            if self._resume is not None:
                if self._link_down or self._hb_error is not None:
                    self._resume_cycle(self._hb_error or ConnectionError(
                        "row channel link marked down by the ack reader"))
                try:
                    return self._transmit_ckpt(header, blobs)
                except OSError as e:
                    self._resume_cycle(e)
                    return self._transmit_ckpt(header, blobs)
            self._check_alive()
            return self._transmit_ckpt(header, blobs)

    def _transmit_ckpt(self, header: dict, blobs) -> int:
        """Write the whole ``-7`` sequence on the current connection.
        Caller holds _send_lock."""
        tm = self._tm
        total = 0

        def _part(sub: int, payload: dict, raw: bytes = b""):
            js = json.dumps(payload).encode("utf-8")
            frame = (_LEN.pack(_CKPT_FRAME) + _LEN.pack(sub)
                     + _LEN.pack(len(js)) + js + raw)
            self._sock.sendall(frame)
            return len(frame)

        total += _part(_CK_OFFER, header)
        for meta, raw in blobs:
            total += _part(_CK_BLOB, meta, raw)
        total += _part(_CK_COMMIT, {"origin": header.get("origin"),
                                    "epoch": header["epoch"]})
        self._last_send = time.monotonic()
        if tm is not None:
            tm.frames_sent.inc(2 + len(blobs))
            tm.bytes_sent.inc(total)
            tm.ckpt_shipped_bytes.inc(total)
        return total

    def send_telemetry(self, snap: dict) -> int:
        """Ship one federated-telemetry snapshot (``-8`` family,
        docs/OBSERVABILITY.md "Federation & SLOs").  The receiving side
        must run a ``telemetry_sink=`` (typically an
        ``obs.federation.TelemetryAggregator``).

        Telemetry frames are NOT journaled — shipping is periodic and
        lossy-tolerant (the next snapshot supersedes a lost one), so on
        a resumable link a mid-ship failure gets one resume cycle and a
        clean retransmit; past that — or on a plain link — the failure
        raises and the shipper simply tries again at its next period.
        Like every hardening knob: never sent unless the application
        calls it, so the bytes on the wire stay seed-identical
        otherwise."""
        js = json.dumps(snap).encode("utf-8")
        with self._send_lock:
            if self._resume is not None:
                if self._link_down or self._hb_error is not None:
                    self._resume_cycle(self._hb_error or ConnectionError(
                        "row channel link marked down by the ack reader"))
                try:
                    return self._transmit_telemetry(js)
                except OSError as e:
                    self._resume_cycle(e)
                    return self._transmit_telemetry(js)
            self._check_alive()
            return self._transmit_telemetry(js)

    def _transmit_telemetry(self, js: bytes) -> int:
        """Write one ``-8`` frame on the current connection.  Caller
        holds _send_lock."""
        frame = _LEN.pack(_TELEMETRY_FRAME) + _LEN.pack(len(js)) + js
        self._sock.sendall(frame)
        self._last_send = time.monotonic()
        if self._tm is not None:
            self._tm.frames_sent.inc()
            self._tm.bytes_sent.inc(len(frame))
            self._tm.fed_shipped_bytes.inc(len(frame))
        return len(frame)

    def close(self):
        """Signal EOS (empty frame) and close the socket.  If the EOS
        frame cannot be delivered (peer already dead) the failure is
        SURFACED — ``self.failed`` is set and :class:`ChannelError`
        raised — never reported as a clean shutdown: the peer may have
        consumed a truncated stream.  With ``resume`` on, a dead link
        gets one full resume cycle (reconnect + replay) before the EOS
        is declared undeliverable."""
        self._stop_heartbeat()
        if self._resume is not None:
            err = None
            try:
                with self._send_lock:
                    try:
                        if self._link_down or self._hb_error is not None:
                            # a half-closed link accepts the EOS write
                            # into the void (peer FIN'd, no RST yet):
                            # resume FIRST, like _deliver, or a
                            # restarted peer never hears from us again
                            self._resume_cycle(
                                self._hb_error or ConnectionError(
                                    "row channel link marked down by "
                                    "the ack reader"))
                        self._transmit_eos()
                    except OSError as e:
                        self._resume_cycle(e)   # ChannelError past the
                        #                         deadline propagates
                        self._transmit_eos()
            except OSError as e:
                err = e
            self._closing = True
            self._sock.close()
            if err is not None:
                self.failed = err
                raise ChannelError(
                    f"RowSender.close: EOS frame not delivered — peer "
                    f"dead past the resume deadline (receiver may see a "
                    f"truncated stream): {err}") from err
            return
        err = self._hb_error
        try:
            if err is None:
                with self._send_lock:
                    if self._dtype_sent is None:
                        # dtype never sent: ship a placeholder so the
                        # receiver's framing stays uniform (empty dtype,
                        # then EOS)
                        d = _encode_dtype(None)
                        self._sock.sendall(_LEN.pack(len(d)) + d)
                    self._sock.sendall(_LEN.pack(_EOS_FRAME))
        except OSError as e:
            err = e
        finally:
            self._sock.close()
        if err is not None:
            self.failed = err
            raise ChannelError(
                f"RowSender.close: EOS frame not delivered — peer dead "
                f"before clean shutdown (receiver may see a truncated "
                f"stream): {err}") from err

    def abort(self):
        """Failure-path close: best-effort ABORT frame (length ``-3``) so
        the receiver fails fast with :class:`PeerAbort` instead of
        hanging or mistaking the death for a clean EOS.  Never raises —
        it is called from error paths that must not mask the original
        failure."""
        self._stop_heartbeat()
        if self._resume is not None:
            self._closing = True   # the ack reader exits silently
        if self._tm is not None:
            self._tm.emit("peer_abort", role="sender")
        try:
            with self._send_lock:
                self._sock.sendall(_LEN.pack(_ABORT_FRAME))
        except OSError:
            pass    # peer already gone: its reader fails on EOF instead
        finally:
            self._sock.close()


class RowReceiver:
    """Server end: accepts ``n_senders`` connections and yields their
    batches until every sender closes.

    ``stall_timeout`` (seconds) bounds how long a reader may wait on a
    silent socket: a peer that stalls mid-frame or stops heartbeating
    surfaces as :class:`PeerStall` from ``batches()`` instead of hanging
    the pipeline forever.  Size it to several sender heartbeat intervals
    (``WireConfig.hardened()`` uses 5x).  Default off = original
    wait-forever behavior."""

    def __init__(self, n_senders: int, host: str = "127.0.0.1",
                 port: int = 0, capacity: int = 64,
                 stall_timeout: float = None, accept_timeout: float = None,
                 metrics=None, events=None, decode_trace: bool = False,
                 resume=None, resume_epoch: int = None, ack_epochs=None,
                 ckpt_sink=None, telemetry_sink=None,
                 wire: WireConfig = None):
        if wire is not None:
            wire.validate()
            if stall_timeout is None:
                stall_timeout = wire.stall_timeout
            if accept_timeout is None:
                accept_timeout = wire.connect_deadline
            if resume is None:
                resume = wire.resume
            if ack_epochs is None:
                # recovery= declares the sealed-ack loop is wired: the
                # completed barriers of batches() ack automatically
                ack_epochs = wire.recovery
        self._tm = _telemetry(metrics, events)  # see RowSender
        #: opt-in span passthrough: True re-attaches sender trace frames
        #: to their batches as :class:`TracedRows` (``batch.wf_trace``);
        #: False (default) consumes and discards them, so a tracing
        #: sender is always safe to point at a non-tracing receiver
        self.decode_trace = bool(decode_trace)
        #: opt-in portable-checkpoint landing zone (``-7`` family): an
        #: object with offer(header)/blob(meta, raw)/commit(meta) —
        #: typically ``recovery.portable.PortableSpool``.  None (the
        #: default) REFUSES the family loudly: a peer shipping
        #: checkpoints at an unconfigured receiver is a deployment
        #: error, not a silent drop.
        self._ckpt_sink = ckpt_sink
        #: opt-in federated-telemetry landing zone (``-8`` family): an
        #: object with accept(snapshot_dict) — typically
        #: ``obs.federation.TelemetryAggregator``.  Same contract as
        #: ``ckpt_sink``: None (the default) REFUSES the family loudly.
        self._telemetry_sink = telemetry_sink
        self.n_senders = int(n_senders)
        self.stall_timeout = stall_timeout
        #: bound on the ACCEPT phase: how long to wait for all senders to
        #: connect at all.  Size it to the deployment's boot-order budget
        #: (the senders' connect_deadline), NOT to stall_timeout — hosts
        #: legitimately boot much slower than a live link may stall.
        self.accept_timeout = accept_timeout
        self._resume = _as_resume(resume)
        # acks only exist on the resume protocol: the flag is inert
        # (and batches() stays seed-identical) without it
        self._auto_ack = bool(ack_epochs) and self._resume is not None
        self._srv = socket.create_server((host, port),
                                         backlog=self.n_senders)
        self.host, self.port = self._srv.getsockname()[:2]
        self._q = queue.Queue(maxsize=capacity)
        self._conns: list[socket.socket] = []
        if self._resume is not None:
            #: restarted-receiver resume point: offered in WELCOME until
            #: the first record lands on a channel, after which the last
            #: contiguous seq takes over
            self._resume_epoch = (None if resume_epoch is None
                                  else int(resume_epoch))
            self._mu = threading.Lock()
            self._ack_mu = threading.Lock()
            self._tokens: dict[str, int] = {}    # sender token -> idx
            self._last_seq: dict[int, int] = {}  # idx -> last seq seen
            self._gen: dict[int, int] = {}       # idx -> connection gen
            self._conn_of: dict[int, socket.socket] = {}
            self._finished: set[int] = set()
            self._down: dict[int, threading.Timer] = {}
            self._closed = False
            target = self._accept_loop_resume
        else:
            target = self._accept_loop
        self._accept_thread = threading.Thread(target=target,
                                               daemon=True,
                                               name="wf-rowrecv-accept")
        self._accept_thread.start()

    def _accept_loop(self):
        readers = []
        accepted = 0
        failure = None
        accept_end = (time.monotonic() + float(self.accept_timeout)
                      if self.accept_timeout is not None else None)
        try:
            for _ in range(self.n_senders):
                if accept_end is not None:
                    # a TOTAL window over all senders: each accept gets
                    # the remaining budget, not a fresh per-peer clock
                    self._srv.settimeout(
                        max(accept_end - time.monotonic(), 0.001))
                conn, _addr = self._srv.accept()
                conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                if self.stall_timeout is not None:
                    conn.settimeout(float(self.stall_timeout))
                self._conns.append(conn)
                t = threading.Thread(target=self._read_loop,
                                     args=(conn, accepted),
                                     daemon=True, name="wf-rowrecv")
                t.start()
                readers.append(t)
                accepted += 1
        except socket.timeout:
            failure = PeerStall(
                f"only {accepted}/{self.n_senders} senders connected "
                f"within the {self.accept_timeout}s accept window")
        except OSError:
            # server closed while accepting (receiver torn down / failure
            # path): the senders that never connected must surface as an
            # error, not leave batches() blocked forever
            failure = ChannelError(
                f"row channel receiver closed with only {accepted}/"
                f"{self.n_senders} senders connected")
        finally:
            self._srv.close()
            if failure is not None:
                # one error + one done-marker per missing sender keeps
                # the batches() accounting exact and wakes it NOW
                for _ in range(self.n_senders - accepted):
                    self._q.put((None, failure))
                    self._q.put((None, None))

    # -- resume protocol (docs/ROBUSTNESS.md "Wire resume") ----------------

    def _accept_loop_resume(self):
        """Resume-mode accept: the server socket stays open for the
        receiver's whole life (reconnecting senders and late boots keep
        arriving); each connection handshakes and reads on its own
        thread.  The boot window (``accept_timeout``) still bounds how
        long the FIRST connection of every sender may take."""
        failure = None
        accept_end = (time.monotonic() + float(self.accept_timeout)
                      if self.accept_timeout is not None else None)
        try:
            while True:
                with self._mu:
                    if (self._closed
                            or len(self._finished) >= self.n_senders):
                        return
                    booting = len(self._tokens) < self.n_senders
                if booting and accept_end is not None:
                    left = accept_end - time.monotonic()
                    if left <= 0:
                        raise socket.timeout()
                    self._srv.settimeout(min(left, 0.5))
                else:
                    self._srv.settimeout(0.5)
                try:
                    conn, _addr = self._srv.accept()
                except socket.timeout:
                    continue
                conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                if self.stall_timeout is not None:
                    conn.settimeout(float(self.stall_timeout))
                self._conns.append(conn)
                threading.Thread(target=self._serve_conn, args=(conn,),
                                 daemon=True, name="wf-rowrecv").start()
        except socket.timeout:
            with self._mu:
                known = len(self._tokens)
            failure = PeerStall(
                f"only {known}/{self.n_senders} senders connected "
                f"within the {self.accept_timeout}s accept window")
        except OSError:
            with self._mu:
                known = len(self._tokens)
            failure = ChannelError(
                f"row channel receiver closed with only {known}/"
                f"{self.n_senders} senders connected")
        finally:
            self._srv.close()
            if failure is not None:
                for _ in range(self.n_senders - known):
                    self._q.put((None, failure))
                    self._q.put((None, None))

    def _serve_conn(self, conn: socket.socket):
        """HELLO -> idx assignment -> WELCOME -> read loop, one thread
        per accepted connection.  A known token re-connecting replaces
        its channel (generation bump) and resumes from the last
        contiguous seq this receiver delivered."""
        tm = self._tm
        try:
            n = _LEN.unpack(_read_exact(conn, _LEN.size))[0]
            sub = (_LEN.unpack(_read_exact(conn, _LEN.size))[0]
                   if n == _RESUME_FRAME else None)
            if sub != _RS_HELLO:
                raise ChannelError(
                    f"resume receiver: expected HELLO, peer sent frame "
                    f"{n}/{sub} (is the sender's resume= on?)")
            token = str(_read_resume_json(conn).get("token"))
            with self._mu:
                if self._closed:
                    conn.close()
                    return
                if token in self._tokens:
                    idx = self._tokens[token]
                    if idx in self._finished:
                        conn.close()   # re-connect after its clean EOS
                        return
                else:
                    if len(self._tokens) >= self.n_senders:
                        conn.close()   # over-subscribed plane
                        return
                    idx = len(self._tokens)
                    self._tokens[token] = idx
                self._gen[idx] = gen = self._gen.get(idx, 0) + 1
                self._conn_of[idx] = conn
                timer = self._down.pop(idx, None)
                last = self._last_seq.get(idx, 0)
            if timer is not None:
                timer.cancel()
            if last == 0 and self._resume_epoch is not None:
                welcome = {"epoch": self._resume_epoch}
            else:
                welcome = {"seq": last}
            _send_resume_frame(conn, _RS_WELCOME, welcome)
        except (OSError, ValueError):
            try:
                conn.close()
            except OSError:
                pass
            return
        if gen > 1 and tm is not None:
            tm.resumes.inc()
            tm.emit("wire_resume", role="receiver", sender=idx,
                    resume_point=welcome)
        self._read_loop_resume(conn, idx, gen)

    def _rs_fresh(self, idx: int, seq) -> bool:
        """Seq dedup, exactly the in-process ``_run_supervised`` rule:
        a record at or below the last seq seen on this channel is a
        replayed duplicate and drops."""
        if seq is None:
            return True   # an untagged peer (no resume): no dedup
        with self._mu:
            if seq <= self._last_seq.get(idx, 0):
                return False
            self._last_seq[idx] = seq
            return True

    def _rs_channel_down(self, idx: int, gen: int, err: Exception):
        """A resumable channel broke: instead of failing batches() now,
        arm the resume deadline — a reconnect cancels it; expiry
        surfaces the original error (the bounded-retry promise)."""
        with self._mu:
            if idx in self._finished or self._gen.get(idx) != gen:
                return   # superseded by a newer connection
            self._conn_of.pop(idx, None)
            if self._closed:
                # receiver torn down: no reconnect is coming — wake a
                # consumer still blocked in batches() with the error
                # now, like the non-resume reader does (puts outside
                # the lock: a full queue must not hold _mu hostage)
                self._finished.add(idx)
                closed = True
            else:
                closed = False
        if closed:
            self._q.put((idx, err))
            self._q.put((idx, None))
            return
        with self._mu:
            if idx in self._finished or self._gen.get(idx) != gen:
                return
            t = threading.Timer(self._resume.deadline, self._rs_expire,
                                args=(idx, gen, err))
            t.daemon = True
            self._down[idx] = t
            t.start()
        if self._tm is not None:
            self._tm.emit("wire_down", role="receiver", sender=idx,
                          error=type(err).__name__, message=str(err))

    def _rs_expire(self, idx: int, gen: int, err: Exception):
        with self._mu:
            if (self._closed or self._down.pop(idx, None) is None
                    or self._gen.get(idx) != gen):
                return
            self._finished.add(idx)
        self._q.put((idx, err))
        self._q.put((idx, None))

    def _next_frame_resume(self, conn: socket.socket, pending):
        """Resume-mode framing: like :meth:`_next_frame` but understands
        the ``-6`` family — returns ``(frame, trace, seq)`` where seq is
        the SEQ tag announced for this record (None for untagged)."""
        tm = self._tm
        trace = None
        while True:
            n = _LEN.unpack(_read_exact(conn, _LEN.size))[0]
            if n >= 0:
                raw = _read_exact(conn, n)
                if tm is not None:
                    tm.frames_recv.inc()
                    tm.bytes_recv.inc(_LEN.size + n)
                return raw, trace, pending
            if n == _EOS_FRAME:
                return None, None, None
            if n == _HEARTBEAT_FRAME:
                if tm is not None:
                    tm.heartbeats_recv.inc()
                continue
            if n == _EPOCH_FRAME:
                epoch = _LEN.unpack(_read_exact(conn, _LEN.size))[0]
                if tm is not None:
                    tm.frames_recv.inc()
                    tm.bytes_recv.inc(2 * _LEN.size)
                from ..recovery.epoch import EpochMarker
                return EpochMarker(epoch), None, pending
            if n == _TRACE_FRAME:
                tn = _LEN.unpack(_read_exact(conn, _LEN.size))[0]
                if not 0 <= tn <= (1 << 20):
                    raise ChannelError(
                        f"bad trace-frame payload length {tn}")
                tp = _read_exact(conn, tn)
                if tm is not None:
                    tm.traces_recv.inc()
                    tm.bytes_recv.inc(2 * _LEN.size + tn)
                if self.decode_trace:
                    trace = json.loads(tp.decode("utf-8"))
                continue
            if n == _RESUME_FRAME:
                sub = _LEN.unpack(_read_exact(conn, _LEN.size))[0]
                if sub != _RS_SEQ:
                    raise ChannelError(
                        f"unexpected resume subtype {sub} mid-stream")
                pending = _LEN.unpack(_read_exact(conn, _LEN.size))[0]
                continue
            if n == _CKPT_FRAME:
                self._ckpt_frame(conn)
                continue
            if n == _TELEMETRY_FRAME:
                self._telemetry_frame(conn)
                continue
            if n == _ABORT_FRAME:
                if tm is not None:
                    tm.emit("peer_abort", role="receiver")
                raise PeerAbort(
                    "row channel sender ABORTED mid-stream (its process "
                    "failed): data received so far is a truncated "
                    "prefix, not a complete stream")
            raise ChannelError(f"bad row-channel frame length {n}")

    def _read_loop_resume(self, conn: socket.socket, idx: int, gen: int):
        from ..recovery.epoch import EpochMarker
        try:
            dtype = None
            got_dtype = False
            pending = None
            while True:
                raw, trace, pending = self._next_frame_resume(conn,
                                                              pending)
                if raw is None:
                    break   # clean EOS
                if type(raw) is EpochMarker:
                    if self._rs_fresh(idx, pending):
                        self._q.put((idx, raw))
                    pending = None
                    continue
                if not got_dtype:
                    # first payload frame of a connection is its dtype
                    # (resent per connection, never SEQ-tagged)
                    dtype = _decode_dtype(raw)
                    got_dtype = True
                    continue
                fresh = self._rs_fresh(idx, pending)
                pending = None
                if not fresh:
                    continue   # duplicate delivery: drop (trace too)
                arr = np.frombuffer(raw, dtype=dtype).copy()
                if trace is not None:
                    arr = arr.view(TracedRows)
                    arr.wf_trace = trace
                self._q.put((idx, arr))
        except PeerAbort as e:
            # a deliberate mid-stream failure is NOT resumable: the
            # sender's process declared itself dead
            conn.close()
            with self._mu:
                self._finished.add(idx)
                self._conn_of.pop(idx, None)
            self._q.put((idx, e))
            self._q.put((idx, None))
            return
        except socket.timeout as e:
            stall = PeerStall(
                f"row channel peer silent for {self.stall_timeout}s "
                f"(no data or heartbeat): stalled mid-stream or "
                f"partitioned")
            stall.__cause__ = e
            if self._tm is not None:
                self._tm.emit("peer_stall",
                              stall_timeout=self.stall_timeout)
            conn.close()
            self._rs_channel_down(idx, gen, stall)
            return
        except Exception as e:  # noqa: BLE001 — any other reader failure
            # (EOF/RST mid-frame, torn frame, undecodable dtype) arms
            # the resume deadline: the sender gets that long to
            # reconnect and replay before the error surfaces
            conn.close()
            self._rs_channel_down(idx, gen, e)
            return
        conn.close()
        with self._mu:
            self._finished.add(idx)
            self._conn_of.pop(idx, None)
        self._q.put((idx, None))   # this sender is done

    def ack_epoch(self, epoch: int):
        """Cumulative sealed-epoch acknowledgement: tell every live
        sender that everything up to and including epoch ``epoch`` is
        durably incorporated on this side, so their journals trim
        through that marker (the journal-bound guarantee).  Call it when
        the epoch is SEALED (checkpoint committed — e.g. from
        ``Dataflow.on_epoch_sealed``); a receiver built with
        ``WireConfig(recovery=True)`` acks automatically as barriers
        complete in :meth:`batches`.  A link that is down simply keeps
        its journal — the next (cumulative) ack trims it."""
        if self._resume is None:
            raise RuntimeError("ack_epoch needs a resume= receiver")
        with self._mu:
            conns = list(self._conn_of.values())
        for conn in conns:
            try:
                with self._ack_mu:
                    _send_resume_frame(conn, _RS_ACK,
                                       {"epoch": int(epoch)})
                if self._tm is not None:
                    self._tm.acks_sent.inc()
            except OSError:
                pass

    def _ckpt_frame(self, conn: socket.socket):
        """Consume one portable-checkpoint frame (``-7`` family,
        docs/ROBUSTNESS.md "Cross-host recovery") and hand it to the
        configured ``ckpt_sink``.  Runs inline on the connection's read
        thread — a sink failure (CRC mismatch, version skew) surfaces
        exactly like a torn frame, through the read loop's error path."""
        sub = _LEN.unpack(_read_exact(conn, _LEN.size))[0]
        if sub not in (_CK_OFFER, _CK_BLOB, _CK_COMMIT):
            raise ChannelError(f"unexpected ckpt subtype {sub}")
        meta = _read_resume_json(conn)
        raw = b""
        if sub == _CK_BLOB:
            nb = int(meta.get("bytes", -1))
            if not 0 <= nb <= (1 << 31):
                raise ChannelError(f"bad ckpt blob length {nb}")
            raw = _read_exact(conn, nb)
        sink = self._ckpt_sink
        if sink is None:
            raise ChannelError(
                "portable-checkpoint frame received but this receiver "
                "has no ckpt_sink= (give it a recovery.portable."
                "PortableSpool, or stop the peer's checkpoint shipping)")
        if self._tm is not None:
            self._tm.frames_recv.inc()
            self._tm.ckpt_fetched_bytes.inc(3 * _LEN.size + len(raw))
        if sub == _CK_OFFER:
            sink.offer(meta)
        elif sub == _CK_BLOB:
            sink.blob(meta, raw)
        else:
            sink.commit(meta)

    def _telemetry_frame(self, conn: socket.socket):
        """Consume one federated-telemetry frame (``-8`` family,
        docs/OBSERVABILITY.md "Federation & SLOs") and hand the decoded
        snapshot to the configured ``telemetry_sink``.  Runs inline on
        the connection's read thread — a sink failure surfaces exactly
        like a torn frame, through the read loop's error path."""
        n = _LEN.unpack(_read_exact(conn, _LEN.size))[0]
        if not 0 <= n <= (1 << 20):
            raise ChannelError(f"bad telemetry-frame payload length {n}")
        raw = _read_exact(conn, n)
        sink = self._telemetry_sink
        if sink is None:
            raise ChannelError(
                "telemetry frame received but this receiver has no "
                "telemetry_sink= (give it an obs.federation."
                "TelemetryAggregator, or stop the peer's federation "
                "shipping)")
        if self._tm is not None:
            self._tm.frames_recv.inc()
            self._tm.fed_fetched_bytes.inc(2 * _LEN.size + len(raw))
        sink.accept(json.loads(raw.decode("utf-8")))

    def _next_frame(self, conn: socket.socket):
        """One payload frame as ``(frame, trace_or_None)`` — ``frame``
        is bytes, an :class:`EpochMarker`, or None on clean EOS.
        Heartbeat frames are consumed silently; a TRACE frame is held
        and attached to the data frame that follows it (or discarded
        when ``decode_trace`` is off); an ABORT frame raises."""
        tm = self._tm
        trace = None
        while True:
            n = _LEN.unpack(_read_exact(conn, _LEN.size))[0]
            if n >= 0:
                raw = _read_exact(conn, n)
                if tm is not None:
                    tm.frames_recv.inc()
                    tm.bytes_recv.inc(_LEN.size + n)
                return raw, trace
            if n == _EOS_FRAME:
                return None, None
            if n == _HEARTBEAT_FRAME:
                if tm is not None:
                    tm.heartbeats_recv.inc()
                continue
            if n == _EPOCH_FRAME:
                epoch = _LEN.unpack(_read_exact(conn, _LEN.size))[0]
                if tm is not None:
                    tm.frames_recv.inc()
                    tm.bytes_recv.inc(2 * _LEN.size)
                from ..recovery.epoch import EpochMarker
                return EpochMarker(epoch), None
            if n == _TRACE_FRAME:
                tn = _LEN.unpack(_read_exact(conn, _LEN.size))[0]
                if not 0 <= tn <= (1 << 20):
                    raise ChannelError(
                        f"bad trace-frame payload length {tn}")
                tp = _read_exact(conn, tn)
                if tm is not None:
                    tm.traces_recv.inc()
                    tm.bytes_recv.inc(2 * _LEN.size + tn)
                if self.decode_trace:
                    # an undecodable trace surfaces like any bad frame
                    # (version-mismatched peer), via _read_loop's
                    # catch-all -> batches() raise
                    trace = json.loads(tp.decode("utf-8"))
                continue
            if n == _CKPT_FRAME:
                self._ckpt_frame(conn)
                continue
            if n == _TELEMETRY_FRAME:
                self._telemetry_frame(conn)
                continue
            if n == _ABORT_FRAME:
                if tm is not None:
                    tm.emit("peer_abort", role="receiver")
                raise PeerAbort(
                    "row channel sender ABORTED mid-stream (its process "
                    "failed): data received so far is a truncated prefix, "
                    "not a complete stream")
            raise ChannelError(f"bad row-channel frame length {n}")

    def _read_loop(self, conn: socket.socket, idx: int):
        from ..recovery.epoch import EpochMarker
        try:
            dtype = None
            got_dtype = False
            while True:
                raw, trace = self._next_frame(conn)
                if raw is None:
                    break
                if type(raw) is EpochMarker:
                    self._q.put((idx, raw))
                    continue
                if not got_dtype:
                    # first payload frame of a connection is its dtype
                    dtype = _decode_dtype(raw)
                    got_dtype = True
                    continue
                arr = np.frombuffer(raw, dtype=dtype).copy()
                if trace is not None:
                    arr = arr.view(TracedRows)
                    arr.wf_trace = trace
                self._q.put((idx, arr))
        except socket.timeout as e:
            stall = PeerStall(
                f"row channel peer silent for {self.stall_timeout}s "
                f"(no data or heartbeat): stalled mid-stream or "
                f"partitioned")
            stall.__cause__ = e
            if self._tm is not None:
                self._tm.emit("peer_stall",
                              stall_timeout=self.stall_timeout)
            self._q.put((idx, stall))
        except Exception as e:  # noqa: BLE001 — ANY reader failure (IO,
            # undecodable dtype from a version-mismatched peer, bad frame)
            # must surface in batches(); the finally's None alone would
            # count this sender as a clean EOS and silently truncate the
            # stream — the exact failure the docstring promises to prevent
            self._q.put((idx, e))
        finally:
            conn.close()
            self._q.put((idx, None))   # this sender is done

    def batches(self, epoch_markers: bool = False):
        """Yield batches until every sender has sent EOS; raises if any
        connection died mid-stream (fail fast — a silently truncated
        stream would produce silently wrong window totals).  When the
        feeding source node of a Dataflow iterates this, a raised peer
        failure lands in ``Dataflow._errors`` and ``wait()`` re-raises
        it — remote death is a graph error, not a hang.

        ``epoch_markers=True`` opts into wire epoch alignment
        (docs/ROBUSTNESS.md "Recovery"): when every still-active sender
        has shipped its epoch-``e`` frame (``RowSender.send_epoch``), one
        :class:`~windflow_tpu.recovery.epoch.EpochMarker` is yielded —
        after every row of epochs <= ``e`` from every sender, and before
        any row of later epochs (rows from senders that run ahead are
        held back until the barrier completes).  A recovery-enabled
        source that re-emits the marker hands the engine an epoch
        boundary consistent across hosts.  Alignment is tracked either
        way; with the default ``False`` the markers are consumed
        silently, preserving the original yield sequence."""
        done_idx: set = set()
        done = 0
        my_epoch = 0
        level: dict = {}   # sender idx -> highest epoch frame seen
        held: dict = {}    # sender idx -> [(level_at_dequeue, batch)]
        from ..recovery.epoch import EpochMarker

        def _min_level():
            lv = [level.get(i, 0) for i in range(self.n_senders)
                  if i not in done_idx]
            return min(lv) if lv else None

        while done < self.n_senders:
            idx, item = self._q.get()
            advanced = False
            if item is None:
                done += 1
                if idx is not None:
                    done_idx.add(idx)
                advanced = True     # a finished sender leaves the min
            elif isinstance(item, Exception):
                raise item
            elif type(item) is EpochMarker:
                if item.epoch > level.get(idx, 0):
                    level[idx] = item.epoch
                    advanced = True
            elif epoch_markers and level.get(idx, 0) > my_epoch:
                # sender is past the open epoch: hold its rows until the
                # stragglers align (content epoch = level + 1 at dequeue).
                # Without the opt-in, frames are consumed silently and
                # rows yield immediately — the original sequence, no
                # unbounded buffering behind a slow straggler.
                held.setdefault(idx, []).append((level[idx], item))
            else:
                yield item
            if not advanced:
                continue
            m = _min_level()
            if m is None or m <= my_epoch:
                continue
            # barrier(s) complete through epoch m.  A row held at level
            # L is content of epoch L+1: when the min jumps several
            # epochs at once (a sender skipping epochs after a coarse
            # restart), rows with L < m are content the marker claims
            # to cover and must precede it; rows at exactly L == m open
            # the next epoch and follow it.
            my_epoch = m
            if self._auto_ack:
                # WireConfig(recovery=True): a completed barrier is this
                # plane's seal point — ack it so sender journals trim
                self.ack_epoch(m)
            for i in sorted(held):
                keep = []
                for lvl, row in held[i]:
                    if lvl < m:
                        yield row
                    else:
                        keep.append((lvl, row))
                held[i] = keep
            if epoch_markers:
                yield EpochMarker(m)
            for i in sorted(held):
                keep = []
                for lvl, row in held[i]:
                    if lvl <= m:
                        yield row
                    else:
                        keep.append((lvl, row))
                held[i] = keep
        # stragglers: every sender closed, release anything still held
        for i in sorted(held):
            for _lvl, row in held[i]:
                yield row

    def close(self):
        """Tear the receiver down (failure path / tests): close the
        listening socket and every accepted connection.  Live senders
        see a reset on their next send, and a consumer blocked in
        batches() during the accept phase is woken with a classified
        error — fail fast, not hang."""
        if self._resume is not None:
            with self._mu:
                self._closed = True
                timers = list(self._down.values())
                self._down.clear()
            for t in timers:
                t.cancel()
        try:
            # closing an fd does NOT wake a thread blocked in accept();
            # shutdown() does (Linux: accept returns EINVAL)
            self._srv.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        self._srv.close()
        for conn in self._conns:
            try:
                # as with accept() above: close() alone neither wakes a
                # reader thread blocked in recv() nor reliably FINs the
                # peer while one is — shutdown() does both, so a
                # resumable sender's ack reader sees the EOF and marks
                # the link down promptly
                conn.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                conn.close()
            except OSError:
                pass


def partition_and_ship(batch: np.ndarray, owners: np.ndarray, my_pid: int,
                       senders: dict, trace: dict = None) -> np.ndarray:
    """Split one batch by owning process (``owners`` from
    ``multihost.process_for_keys``): rows owned here are returned for
    local processing; every other process's rows go out through its
    ``senders[pid]`` RowSender.  The one-call form of the multi-host
    source contract for non-key-partitioned inputs.  ``trace``
    (typically ``obs.trace.export()``) rides with every shipped part so
    a sampled batch's span survives the hop.

    With resumable senders (``open_row_plane(resume=...)``) each
    shipped part is journaled under a seq before it hits the wire, so a
    peer restart mid-call replays the missing parts transparently —
    callers need no try/except around the ship loop; a failure raised
    here means the resume deadline itself was exhausted."""
    mine = batch[owners == my_pid]
    covered = np.isin(owners, [my_pid, *senders])
    if not covered.all():
        # fail fast: a pid with rows but no sender would silently truncate
        # the stream (and so silently corrupt window totals downstream)
        missing = sorted(set(np.asarray(owners)[~covered].tolist()))
        raise KeyError(f"rows owned by process(es) {missing} but no "
                       "RowSender registered for them")
    for pid, snd in senders.items():
        if pid == my_pid:
            continue
        part = batch[owners == pid]
        if len(part):
            snd.send(part, trace=trace)
    return mine
