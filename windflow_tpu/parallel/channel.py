"""Cross-process row channels: the multi-host data plane.

The reference's "communication backend" is FastFlow shared-memory queues
between threads of ONE process (SURVEY.md §2.8 — no sockets, no MPI).
The multi-host deployment model (parallel/multihost.py) keeps key groups
process-local so the common case ships nothing — but a source whose input
is NOT naturally key-partitioned (a socket feed, a file) must be able to
forward rows to the process that owns their kf group.  This module is
that hop: a typed, length-framed, batched TCP channel carrying the same
SoA batches the in-process engine queues carry, so a remote stage slots
into a pipeline exactly like a local one.

Design notes (DCN-analog, deliberately boring):

* batches cross as raw structured-array bytes with an 8-byte length
  frame; the dtype travels once per connection as JSON of
  ``np.dtype(...).descr`` — a pure data encoding, so a hostile peer can
  at worst describe a weird dtype, never execute code (the channel
  trusts its cluster for data *integrity*, like NCCL/MPI transports do,
  but the wire format must not turn that trust into code execution);
* one receiver accepts any number of senders; per-connection reader
  threads feed one bounded queue, preserving per-sender batch order
  (cross-sender order is interleaved, as with any multi-producer edge —
  an OrderingNode downstream restores it where required);
* EOS is an empty frame per sender; ``batches()`` ends when every
  registered sender has closed — the FastFlow EOS cascade, one level up.
"""

from __future__ import annotations

import json
import queue
import socket
import struct
import threading

import numpy as np

_LEN = struct.Struct("<q")


def _encode_dtype(dtype) -> bytes:
    """JSON-encode a dtype via numpy's ``.npy``-format codec
    (``np.lib.format.dtype_to_descr``) — the one descr form numpy
    guarantees round-trippable, covering nested structs, align padding,
    sub-arrays, and unstructured dtypes (plain format strings).  ``None``
    (the EOS-before-data placeholder) encodes as JSON ``null``."""
    if dtype is None:
        return b"null"
    return json.dumps(np.lib.format.dtype_to_descr(np.dtype(dtype))
                      ).encode("utf-8")


def _tuplify_descr(d):
    """JSON turns descr tuples into lists; ``descr_to_dtype`` wants the
    original shapes back, recursively: a descr is a list of field-entry
    *tuples* (possibly nested as a field's format), while sub-array
    shapes and (title, name) pairs are tuples of scalars."""
    if not isinstance(d, list):
        return d
    if d and all(isinstance(e, list) for e in d):
        # a (possibly nested) struct descr: keep the list, tuplify entries
        return [tuple(_tuplify_descr(x) for x in e) for e in d]
    # a sub-array shape or a (title, name) pair
    return tuple(_tuplify_descr(x) for x in d)


def _decode_dtype(raw: bytes):
    """Inverse of :func:`_encode_dtype`."""
    descr = json.loads(raw.decode("utf-8"))
    if descr is None:
        return None
    return np.lib.format.descr_to_dtype(_tuplify_descr(descr))


def _read_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("row channel peer closed mid-frame")
        buf.extend(chunk)
    return bytes(buf)


class RowSender:
    """Client end: ships structured-array batches to a RowReceiver."""

    def __init__(self, host: str, port: int, timeout: float = 30.0):
        self._sock = socket.create_connection((host, port), timeout=timeout)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._dtype_sent = None

    def send(self, batch: np.ndarray):
        if len(batch) == 0:
            return
        if self._dtype_sent is None:
            d = _encode_dtype(batch.dtype)
            self._sock.sendall(_LEN.pack(len(d)) + d)
            self._dtype_sent = batch.dtype
        elif batch.dtype != self._dtype_sent:
            raise TypeError(
                f"row channel dtype changed mid-stream: {self._dtype_sent}"
                f" -> {batch.dtype}")
        payload = np.ascontiguousarray(batch).tobytes()
        self._sock.sendall(_LEN.pack(len(payload)) + payload)

    def close(self):
        """Signal EOS (empty frame) and close the socket."""
        try:
            if self._dtype_sent is None:
                # dtype never sent: ship a placeholder so the receiver's
                # framing stays uniform (empty dtype, then EOS)
                d = _encode_dtype(None)
                self._sock.sendall(_LEN.pack(len(d)) + d)
            self._sock.sendall(_LEN.pack(-1))
        finally:
            self._sock.close()


class RowReceiver:
    """Server end: accepts ``n_senders`` connections and yields their
    batches until every sender closes."""

    def __init__(self, n_senders: int, host: str = "127.0.0.1",
                 port: int = 0, capacity: int = 64):
        self.n_senders = int(n_senders)
        self._srv = socket.create_server((host, port),
                                         backlog=self.n_senders)
        self.host, self.port = self._srv.getsockname()[:2]
        self._q = queue.Queue(maxsize=capacity)
        self._accept_thread = threading.Thread(target=self._accept_loop,
                                               daemon=True,
                                               name="wf-rowrecv-accept")
        self._accept_thread.start()

    def _accept_loop(self):
        readers = []
        try:
            for _ in range(self.n_senders):
                conn, _addr = self._srv.accept()
                conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                t = threading.Thread(target=self._read_loop, args=(conn,),
                                     daemon=True, name="wf-rowrecv")
                t.start()
                readers.append(t)
        except OSError:
            pass  # server closed while accepting: senders never came
        finally:
            self._srv.close()

    def _read_loop(self, conn: socket.socket):
        try:
            n = _LEN.unpack(_read_exact(conn, _LEN.size))[0]
            dtype = _decode_dtype(_read_exact(conn, n))
            while True:
                n = _LEN.unpack(_read_exact(conn, _LEN.size))[0]
                if n < 0:
                    break
                raw = _read_exact(conn, n)
                self._q.put(np.frombuffer(raw, dtype=dtype).copy())
        except Exception as e:  # noqa: BLE001 — ANY reader failure (IO,
            # undecodable dtype from a version-mismatched peer, bad frame)
            # must surface in batches(); the finally's None alone would
            # count this sender as a clean EOS and silently truncate the
            # stream — the exact failure the docstring promises to prevent
            self._q.put(e)
        finally:
            conn.close()
            self._q.put(None)   # this sender is done

    def batches(self):
        """Yield batches until every sender has sent EOS; raises if any
        connection died mid-stream (fail fast — a silently truncated
        stream would produce silently wrong window totals)."""
        done = 0
        while done < self.n_senders:
            item = self._q.get()
            if item is None:
                done += 1
            elif isinstance(item, Exception):
                raise item
            else:
                yield item


def partition_and_ship(batch: np.ndarray, owners: np.ndarray, my_pid: int,
                       senders: dict) -> np.ndarray:
    """Split one batch by owning process (``owners`` from
    ``multihost.process_for_keys``): rows owned here are returned for
    local processing; every other process's rows go out through its
    ``senders[pid]`` RowSender.  The one-call form of the multi-host
    source contract for non-key-partitioned inputs."""
    mine = batch[owners == my_pid]
    covered = np.isin(owners, [my_pid, *senders])
    if not covered.all():
        # fail fast: a pid with rows but no sender would silently truncate
        # the stream (and so silently corrupt window totals downstream)
        missing = sorted(set(np.asarray(owners)[~covered].tolist()))
        raise KeyError(f"rows owned by process(es) {missing} but no "
                       "RowSender registered for them")
    for pid, snd in senders.items():
        if pid == my_pid:
            continue
        part = batch[owners == pid]
        if len(part):
            snd.send(part)
    return mine
