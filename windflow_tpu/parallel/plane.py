"""Plane supervisor — per-process membership, death detection, and
successor handoff for a multihost row plane (docs/ROBUSTNESS.md
"Cross-host recovery").

PR 16 made single *edges* of the row plane resumable (sender journals +
seq dedup) and PR 17 made sealed state portable in principle (flat
native blobs).  This layer closes the remaining gap named by ROADMAP
item 3: ``kill -9`` of a WHOLE process.  Each process runs one
:class:`PlaneSupervisor` over its half of the plane
(:func:`~windflow_tpu.parallel.multihost.open_row_plane` handles):

* **membership** — the supervisor polls the health of every outbound
  sender (the resume ack-reader marks ``_link_down`` on EOF/RST, the
  heartbeat thread records ``_hb_error``), so a dead peer is observed
  passively within a beat interval, no extra probe traffic;
* **death** — a peer continuously down past ``down_deadline`` is
  declared dead (``membership`` event, state ``dead``); every survivor
  computes the same deterministic successor (ring order over the live
  candidate pids), so election needs no coordination round;
* **handoff** — the successor pulls the dead peer's newest *portable
  checkpoint* from its local :class:`~windflow_tpu.recovery.portable.
  PortableSpool` (replicated there at every seal via
  :meth:`replicate`), restores those nodes with the ordinary
  ``latest_complete()/load()`` recipe, and rebinds the dead peer's
  address with :meth:`takeover_receiver` — a resumable receiver opened
  with ``resume_epoch=K``, so every journaling sender that was feeding
  the dead process reconnects, replays its tail since the epoch-``K``
  barrier, and the receivers dedup the replayed prefix.  No gap, no
  duplicate.

The layer is strictly opt-in: constructing no supervisor (and passing
no ``ckpt_sink=``) keeps the plane byte-identical to the seed and this
module un-imported — the same contract every hardening knob holds.
"""

from __future__ import annotations

import threading
import time
from time import monotonic as _monotonic


class PlanePolicy:
    """Static description of a supervised plane — the membership knobs
    plus the :class:`~windflow_tpu.parallel.channel.WireConfig` its
    edges run.  Separated from the live :class:`PlaneSupervisor` so
    pre-flight validation (``check/``, WF216) can judge the pairing
    without opening a socket: a plane that promises handoff over a
    non-resumable wire silently loses every in-flight frame at the
    handoff point.

    ``down_deadline`` (seconds) is how long a peer must stay
    continuously unreachable before it is declared dead — size it ABOVE
    the wire's resume deadline, or a peer that was about to resume gets
    its nodes adopted out from under it (a split brain on the key
    space).  ``period`` is the membership poll cadence; ``candidates``
    optionally restricts which pids may adopt (e.g. exclude a
    feeder-only process that holds no state plane)."""

    __slots__ = ("down_deadline", "period", "candidates", "wire")

    def __init__(self, down_deadline: float = 10.0, period: float = 0.5,
                 candidates=None, wire=None):
        if float(down_deadline) <= 0:
            raise ValueError("down_deadline must be positive seconds")
        if float(period) <= 0:
            raise ValueError("period must be positive seconds")
        self.down_deadline = float(down_deadline)
        self.period = float(period)
        self.candidates = (None if candidates is None
                           else frozenset(int(p) for p in candidates))
        self.wire = wire

    def validate(self):
        """Raise on a statically-refusable pairing (the WF216 conflict
        is a warning in ``check/`` but loud here at runtime wiring)."""
        if self.wire is not None:
            self.wire.validate()
        return self

    def __repr__(self):
        return (f"PlanePolicy(down_deadline={self.down_deadline}, "
                f"period={self.period}, candidates="
                f"{sorted(self.candidates) if self.candidates else None})")


class PlaneSupervisor:
    """See module docstring.  One per process; owns a daemon poll
    thread between :meth:`start` and :meth:`close`.

    ``on_adopt(dead_pid, epoch, store)`` is the application's restore
    hook, called on the supervisor thread when THIS process is elected
    successor: ``epoch``/``store`` point at the dead peer's newest
    verified spooled checkpoint (``None``/``None`` when the peer never
    replicated one — the successor owns the keys but starts them
    fresh).  The hook typically loads the blobs, then calls
    :meth:`takeover_receiver` and consumes the replayed tail."""

    def __init__(self, my_pid: int, addresses: dict, senders: dict,
                 policy: PlanePolicy = None, store=None, spool=None,
                 metrics=None, events=None, on_adopt=None,
                 on_death=None):
        self.policy = (policy or PlanePolicy()).validate()
        self.my_pid = int(my_pid)
        self.addresses = dict(addresses)
        self.senders = senders
        self.store = store
        self.spool = spool
        self.on_adopt = on_adopt
        #: observability hook, called as ``on_death(pid, down_for)`` on
        #: EVERY death declaration (not just when this process adopts) —
        #: the federation layer's black-box trigger
        #: (docs/OBSERVABILITY.md "Federation & SLOs"); failures are
        #: swallowed so a telemetry bug cannot block the handoff path
        self.on_death = on_death
        self._metrics = metrics
        self._events = events
        self._down_since: dict[int, float] = {}
        self._dead: set[int] = set()
        self._adopted: set[int] = set()
        self._stop = threading.Event()
        self._thread = None
        self._mu = threading.Lock()
        wire = self.policy.wire
        if wire is not None and not getattr(wire, "resume", None):
            # the stand-alone runtime twin of the WF216 pre-flight
            # diagnostic (same pattern as the engine's WF207 warning)
            import warnings
            from ..check.diagnostics import CheckWarning
            warnings.warn(
                "[WF216] plane supervisor over a wire without resume=: "
                "at handoff the in-flight frames of the dead process "
                "have no journal to replay from and are silently lost "
                "(set WireConfig(resume=True, recovery=True); "
                "docs/ROBUSTNESS.md \"Cross-host recovery\")",
                CheckWarning, stacklevel=2)
        self._set_gauge("plane_members", len(self.addresses))
        self._set_gauge("plane_down", 0)

    # ------------------------------------------------------------ lifecycle

    def start(self) -> "PlaneSupervisor":
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="wf-plane-supervisor")
        self._thread.start()
        return self

    def close(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)

    # ----------------------------------------------------------- membership

    def _peer_down(self, pid: int) -> bool:
        snd = self.senders.get(pid)
        if snd is None:
            return False
        return (getattr(snd, "_link_down", False)
                or getattr(snd, "_hb_error", None) is not None)

    def live(self) -> list:
        """Pids not declared dead (this process included), ascending."""
        with self._mu:
            return sorted(p for p in self.addresses
                          if p not in self._dead)

    def dead(self) -> list:
        with self._mu:
            return sorted(self._dead)

    def successor_for(self, dead_pid: int) -> int:
        """Deterministic, coordination-free election: the first live
        candidate after ``dead_pid`` in pid ring order.  Every survivor
        evaluates the same function over the same membership view, so
        all agree without a vote; returns None when no candidate
        survives."""
        cand = self.policy.candidates
        with self._mu:
            ring = sorted(p for p in self.addresses
                          if p not in self._dead
                          and (cand is None or p in cand))
        if not ring:
            return None
        for p in ring:
            if p > dead_pid:
                return p
        return ring[0]

    def _loop(self):
        period = self.policy.period
        deadline = self.policy.down_deadline
        while not self._stop.wait(period):
            now = _monotonic()
            for pid in self.addresses:
                if pid == self.my_pid:
                    continue
                with self._mu:
                    is_dead = pid in self._dead
                down = self._peer_down(pid)
                if is_dead:
                    if not down:
                        # a restarted/taken-over peer answered a resumed
                        # send: back in the membership
                        with self._mu:
                            self._dead.discard(pid)
                            self._down_since.pop(pid, None)
                        self._event("membership", peer=pid, state="up",
                                    rejoined=True)
                    continue
                if not down:
                    if self._down_since.pop(pid, None) is not None:
                        self._event("membership", peer=pid, state="up")
                    continue
                t0 = self._down_since.setdefault(pid, now)
                if t0 == now:
                    self._event("membership", peer=pid, state="down",
                                deadline=deadline)
                elif now - t0 >= deadline:
                    self._declare_dead(pid, now - t0)
            with self._mu:
                members = len(self.addresses) - len(self._dead)
                n_down = len(self._down_since)
            self._set_gauge("plane_members", members)
            self._set_gauge("plane_down", n_down)

    def _declare_dead(self, pid: int, down_for: float):
        with self._mu:
            self._dead.add(pid)
            self._down_since.pop(pid, None)
        successor = self.successor_for(pid)
        self._event("membership", peer=pid, state="dead",
                    down_for=round(down_for, 3), successor=successor)
        if self.on_death is not None:
            try:
                self.on_death(pid, down_for)
            except Exception:  # noqa: BLE001 — telemetry must not
                pass           # block the handoff path
        if successor == self.my_pid:
            self._adopt(pid)

    # -------------------------------------------------------------- handoff

    def _adopt(self, dead_pid: int):
        """This process won the election for ``dead_pid``'s nodes: look
        up its newest spooled portable checkpoint and hand both to the
        application's restore hook."""
        with self._mu:
            if dead_pid in self._adopted:
                return
            self._adopted.add(dead_pid)
        self._event("handoff", dead=dead_pid, successor=self.my_pid,
                    phase="elected")
        epoch, store = None, None
        if self.spool is not None:
            found = self.spool.latest(dead_pid)
            if found is not None:
                epoch = found[0]
                store = self.spool.store_for(dead_pid)
        self._count("plane_handoffs")
        try:
            if self.on_adopt is not None:
                self.on_adopt(dead_pid, epoch, store)
        except Exception as e:  # noqa: BLE001 — the hook is user code
            self._event("handoff", dead=dead_pid, successor=self.my_pid,
                        phase="failed", epoch=epoch,
                        error=type(e).__name__, message=str(e))
            raise
        self._event("handoff", dead=dead_pid, successor=self.my_pid,
                    phase="adopted", epoch=epoch)

    def takeover_receiver(self, dead_pid: int, epoch, n_senders: int,
                          capacity: int = 64, ckpt_sink=None):
        """Rebind a dead peer's plane address as a resumable receiver
        resuming from its last sealed epoch: every journaling sender
        that fed the dead process reconnects here (same host:port),
        gets ``WELCOME {"epoch": K}``, and replays its tail since that
        barrier — which is exactly the wire the restored state needs
        next.  The caller consumes it like any plane receiver."""
        from .channel import RowReceiver, WireConfig
        wire = self.policy.wire or WireConfig.hardened()
        host, port = self.addresses[dead_pid]
        return RowReceiver(
            n_senders=n_senders, host=host, port=port, capacity=capacity,
            metrics=self._metrics, events=self._events,
            resume=wire.resume or True,
            resume_epoch=None if epoch is None else int(epoch),
            ckpt_sink=ckpt_sink, wire=wire)

    # ---------------------------------------------------------- replication

    def replicate(self, epoch: int) -> int:
        """Ship this process's sealed epoch to every live peer (the
        portable ``-7`` family) so a successor can restore it after our
        death; returns total bytes shipped.  Per-peer failures are
        swallowed (the peer may itself be mid-restart — the next seal
        re-ships), so the hook is safe on the seal path."""
        if self.store is None:
            raise RuntimeError("replicate() needs a PlaneSupervisor "
                               "built with store= (this process's "
                               "CheckpointStore)")
        from ..recovery.portable import ship_checkpoint
        total = 0
        for pid in self.live():
            snd = self.senders.get(pid)
            if snd is None:
                continue
            if (getattr(snd, "_link_down", False)
                    or getattr(snd, "_hb_error", None) is not None):
                # a down link must not block the seal path for a whole
                # reconnect cycle: skip now, the next seal re-ships
                continue
            try:
                total += ship_checkpoint(snd, self.store, epoch,
                                         origin=self.my_pid)
            except (OSError, ValueError):
                continue
        return total

    def attach(self, dataflow) -> "PlaneSupervisor":
        """Wire :meth:`replicate` onto a recovering Dataflow's seal
        boundary (``Dataflow.on_epoch_sealed``): every sealed epoch is
        replicated to the plane the moment it becomes durable — the
        cadence that keeps a successor at most one epoch behind."""
        dataflow.on_epoch_sealed(self.replicate)
        return self

    # -------------------------------------------------------------- plumbing

    def _event(self, kind: str, **fields):
        if self._events is not None:
            self._events.emit(kind, plane=self.my_pid, **fields)

    def _count(self, name: str, n: int = 1):
        if self._metrics is not None:
            self._metrics.counter(name).inc(n)

    def _set_gauge(self, name: str, v):
        if self._metrics is not None:
            self._metrics.gauge(name).set(v)


def open_supervised_plane(my_pid: int, addresses: dict,
                          policy: PlanePolicy = None, spool_dir=None,
                          store=None, capacity: int = 64, metrics=None,
                          events=None, on_adopt=None,
                          resume_epoch: int = None, telemetry_sink=None,
                          on_death=None):
    """One-call supervised plane: ``open_row_plane`` with a hardened
    RESUMABLE wire (the supervisor's handoff promise needs journals —
    WF216), a :class:`~windflow_tpu.recovery.portable.PortableSpool`
    at ``spool_dir`` as the receiver's ``ckpt_sink``, and a started
    :class:`PlaneSupervisor`.  Returns ``(receiver, senders,
    supervisor)``."""
    from .channel import WireConfig
    from .multihost import open_row_plane
    from ..recovery.portable import PortableSpool
    policy = policy or PlanePolicy()
    if policy.wire is None:
        policy.wire = WireConfig(connect_deadline=60.0, heartbeat=2.0,
                                 stall_timeout=10.0, resume=True,
                                 recovery=True)
    spool = (PortableSpool(spool_dir, metrics=metrics, events=events)
             if spool_dir is not None else None)
    receiver, senders = open_row_plane(
        my_pid, addresses, capacity=capacity, wire=policy.wire,
        metrics=metrics, events=events, resume_epoch=resume_epoch,
        ckpt_sink=spool, telemetry_sink=telemetry_sink)
    sup = PlaneSupervisor(my_pid, addresses, senders, policy=policy,
                          store=store, spool=spool, metrics=metrics,
                          events=events, on_adopt=on_adopt,
                          on_death=on_death).start()
    return receiver, senders, sup
