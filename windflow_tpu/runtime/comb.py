"""Node fusion — the runtime's equivalent of FastFlow's ``ff_comb``
(``ff/combine.hpp``, used by the reference for chaining at
multipipe.hpp:244-271 and for LEVEL1/2 optimisation at pane_farm.hpp:435-464):
several nodes execute in ONE thread, with the upstream node's emissions
delivered synchronously into the downstream node's ``svc`` instead of
through a queue.

Fusion preserves every lifecycle guarantee of the engine contract: inner
``svc_init``/``svc_end`` run in the (single) combined thread, and EOS
flushing cascades stage by stage — stage i's ``eosnotify`` may still emit,
and those emissions are seen by stage i+1 *before* its own ``eosnotify``.
"""

from __future__ import annotations

from .node import Node, SourceNode


class _SyncOut:
    """Output channel that delivers synchronously into the next fused stage
    (replaces the inter-thread Inbox; same ``put`` shape)."""

    __slots__ = ("dst", "channel")

    def __init__(self, dst: Node, channel: int = 0):
        self.dst = dst
        self.channel = channel

    def put(self, src, batch):
        self.dst.svc(batch, self.channel)

    def put_eos(self, src):  # EOS is driven by Comb's lifecycle, not queues
        pass


class Comb(Node):
    """Run `stages` fused in one thread: stage i's emit() calls stage i+1's
    svc() directly; the last stage's emissions leave through the Comb's own
    output channels."""

    def __init__(self, stages: list[Node], name: str = None):
        if not stages:
            raise ValueError("Comb needs at least one stage")
        super().__init__(name or "+".join(s.name for s in stages))
        self.stages = list(stages)
        for a, b in zip(self.stages, self.stages[1:]):
            a._outputs = [(_SyncOut(b), 0)]
            # fused edges are direct handoffs: a stage whose producer
            # yields fresh batches may mutate them in place (node.py
            # ownership protocol) — this is where the per-edge proof
            # happens, since inside a Comb the producer is known
            b.input_fresh = bool(a.yields_fresh)
        #: the Comb hands downstream whatever its last stage emits
        self.yields_fresh = bool(self.stages[-1].yields_fresh)
        #: the Comb's inbox feeds its FIRST stage, so the overload
        #: contract of that stage governs the fused node (shed only if
        #: the head may shed, runtime/overload.py)
        self.shed_safe = bool(getattr(self.stages[0], "shed_safe", False))
        #: if ANY fused stage is a framework shell or stateful window
        #: core, an error mid-chain cannot be attributed to a cleanly
        #: un-processed batch — the fused node inherits fail-fast
        self.quarantine_exempt = any(
            getattr(s, "quarantine_exempt", False) for s in self.stages)
        #: an explicitly configured member budget still governs the chain
        #: (tightest wins; one svc error parks the chain's input batch)
        budgets = [s.error_budget for s in self.stages
                   if getattr(s, "error_budget", None) is not None]
        if budgets:
            self.error_budget = min(budgets)
        #: recovery: the fused node restores stage by stage, so every
        #: member must support snapshots — and no NON-TAIL stage may be
        #: an async device core: its wall-clock poll() harvest cadence
        #: shapes how many emissions leave the tail per input, so replay
        #: could not regenerate the original seq numbering (the
        #: per-launch discipline of _AsyncLaunchRecovery only governs a
        #: stage the engine drives directly).  Instance attr overrides
        #: the class default.
        self.recoverable = (
            all(getattr(s, "recoverable", False) for s in self.stages)
            and not any(
                hasattr(getattr(s, "core", None), "process_batches")
                for s in self.stages[:-1]))

    # -- recovery ----------------------------------------------------------

    def checkpoint_prepare(self):
        """Drain fused device stages in order: a mid-chain stage's
        drained results flow synchronously through the later stages
        (whose own drains then run after absorbing them); the last
        stage's residue is returned for the runner to emit."""
        tail = []
        for i, s in enumerate(self.stages):
            for out in (s.checkpoint_prepare() or ()):
                if out is None or not len(out):
                    continue
                if i + 1 < len(self.stages):
                    self.stages[i + 1].svc(out, 0)
                else:
                    tail.append(out)
        return tail

    def state_snapshot(self):
        return [s.state_snapshot() for s in self.stages]

    def state_restore(self, snap):
        for s, part in zip(self.stages, snap):
            s.state_restore(part)

    # -- lifecycle ---------------------------------------------------------

    def svc_init(self):
        # the engine wired the graph's edges onto the Comb itself; the last
        # stage emits through them
        self.stages[-1]._outputs = self._outputs
        self.stages[0].n_input_channels = self.n_input_channels
        if self._tracer is not None:
            # span sampling survives fusion (obs/trace.py): only the LAST
            # stage crosses a real inbox, so only it wraps traced batches;
            # a fused SOURCE makes its sampling decision at the FIRST
            # stage's emit (the ingest anchor), which flows to the tail
            # through the shared thread-local — inner synchronous edges
            # need no wrapping and the middle stages stay hook-free
            last = self.stages[-1]
            last._tracer = self._tracer
            # inherit the Comb's own wrap flag: a nested Comb that is
            # itself an inner (synchronous-edge) stage must not let its
            # tail wrap either
            last._trace_wrap = self._trace_wrap
            last._hop_id = self._hop_id
            first = self.stages[0]
            if self._trace_origin:
                first._trace_origin = True
                first._hop_id = self._hop_id
                if first is not last:
                    first._tracer = self._tracer
                    first._trace_wrap = False
        for s in self.stages[1:]:
            s.n_input_channels = 1
        for s in self.stages:
            s.stats = self.stats
            # the engine stamps the observability registry on the Comb's
            # context; fused stages keep their own ctx (their replica
            # index differs), so the handle is forwarded explicitly
            s.ctx.metrics = self.ctx.metrics
            s.svc_init()

    def svc(self, batch, channel: int = 0):
        self.stages[0].svc(batch, channel)

    def on_channel_eos(self, channel: int):
        self.stages[0].on_channel_eos(channel)

    def eosnotify(self):
        # cascade: flushing stage i may emit into stage i+1 (synchronously),
        # which then flushes its own state on top
        for i, s in enumerate(self.stages):
            s.eosnotify()
            if i + 1 < len(self.stages):
                self.stages[i + 1].on_channel_eos(0)

    def svc_end(self):
        for s in self.stages:
            s.svc_end()


class SourceComb(Comb, SourceNode):
    """Comb whose first stage is a source: the engine drives ``generate``
    (sources are dispatched by type, engine.py) and the generated batches
    flow synchronously through the fused downstream stages."""

    def generate(self):
        self.stages[0].generate()


def make_comb(stages: list[Node], name: str = None) -> Comb:
    """Fuse `stages` into one schedulable node, source-aware."""
    cls = SourceComb if isinstance(stages[0], SourceNode) else Comb
    return cls(stages, name)
