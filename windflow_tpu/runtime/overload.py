"""Overload & robustness policy — the knobs that make a dataflow *degrade*
instead of dying or hanging when a stage is slow or an item is malformed.

The FastFlow reference gets graceful-under-load behavior from bounded
lock-free queues alone: producers block, end of story.  That is still the
default here (``block``), but a production stream job usually prefers one
of the classic shedding disciplines once a consumer cannot keep up:

* ``shed_oldest`` — drop the item at the head of the full inbox and admit
  the new one (bounded staleness: the consumer always sees the most
  recent data; the standard choice for monitoring/analytics feeds);
* ``shed_newest`` — drop the incoming item (bounded history: what is
  queued wins; the choice when older context must finish first);
* ``put_deadline`` — keep blocking semantics but bound the wait: a ``put``
  that cannot complete within the deadline raises :class:`OverloadError`,
  which tears the graph down with a *clear* error instead of a silent
  stall (fail fast over hang).

EOS frames are exempt from every policy: shedding or timing out an EOS
would corrupt the per-channel EOS counting the engine's termination
protocol relies on.

The same policy object carries the *poison-tuple* budget: when a node's
``svc`` raises and ``error_budget`` allows, the offending batch goes to
the dataflow's dead-letter queue (``Dataflow.dead_letters``, inspectable
after ``wait()``) instead of tearing the graph down; once the budget is
exhausted the next error fails fast exactly like today.

With no policy set (the default everywhere) every code path is identical
to the pre-robustness engine — the "knobs unset => seed-identical
behavior" contract (docs/ROBUSTNESS.md).
"""

from __future__ import annotations

#: valid shedding disciplines for OverloadPolicy.shed
SHED_POLICIES = ("block", "shed_oldest", "shed_newest")


class OverloadError(RuntimeError):
    """A blocking inbox ``put`` exceeded its configured deadline: the
    downstream stage is not keeping up and the policy says fail fast."""


class OverloadPolicy:
    """Per-dataflow robustness knobs (see module docstring).

    Parameters
    ----------
    shed:
        ``"block"`` (default — today's behavior), ``"shed_oldest"`` or
        ``"shed_newest"``.
    put_deadline:
        Seconds a blocking ``put`` may wait before raising
        :class:`OverloadError`.  Only meaningful with ``shed="block"``
        (the shedding policies never block).  ``None`` = wait forever.
    soft_limit:
        Occupancy at which the shedding disciplines start dropping,
        *below* the hard inbox capacity.  ``None`` (default) = shed only
        when full, exactly the pre-control behavior.  Only meaningful
        with a shedding policy; read per ``put``, so the control plane's
        :class:`~windflow_tpu.control.policy.AdaptiveShed` rule moves it
        at runtime (a single attribute store — atomic under the GIL).
    error_budget:
        Default per-node poison-tuple allowance: how many ``svc``
        exceptions a node may quarantine to the dead-letter queue before
        failing fast.  0 (default) = every error fails fast, exactly like
        the seed engine.  A node-level ``error_budget`` (set via
        ``withErrorBudget`` on a builder or directly on a pattern)
        overrides this default.
    """

    __slots__ = ("shed", "put_deadline", "error_budget", "soft_limit")

    def __init__(self, shed: str = "block", put_deadline: float = None,
                 error_budget: int = 0, soft_limit: int = None):
        if shed not in SHED_POLICIES:
            raise ValueError(
                f"shed={shed!r}: must be one of {SHED_POLICIES}")
        if put_deadline is not None:
            put_deadline = float(put_deadline)
            if put_deadline <= 0:
                raise ValueError("put_deadline must be positive (None to "
                                 "wait forever)")
            if shed != "block":
                raise ValueError(
                    f"put_deadline only applies to shed='block' "
                    f"(shed={shed!r} never blocks)")
        if error_budget < 0:
            raise ValueError("error_budget must be >= 0")
        if soft_limit is not None:
            if int(soft_limit) < 1:
                raise ValueError("soft_limit must be >= 1 item (None to "
                                 "shed only when full)")
            if shed == "block":
                raise ValueError(
                    "soft_limit only applies to the shedding policies "
                    "(shed='block' has no drop point to move)")
        self.shed = shed
        self.put_deadline = put_deadline
        self.error_budget = int(error_budget)
        self.soft_limit = None if soft_limit is None else int(soft_limit)

    @property
    def reshapes_put(self) -> bool:
        """True when the inbox ``put`` path differs from the seed engine
        (a shedding discipline or a deadline is active)."""
        return self.shed != "block" or self.put_deadline is not None

    def __repr__(self):
        return (f"OverloadPolicy(shed={self.shed!r}, "
                f"put_deadline={self.put_deadline}, "
                f"error_budget={self.error_budget}, "
                f"soft_limit={self.soft_limit})")


class DeadLetter:
    """One quarantined poison batch: which node choked, on what, and why.
    Collected in ``Dataflow.dead_letters`` (thread-safe append), in
    arrival order, inspectable after ``wait()``."""

    __slots__ = ("node", "batch", "channel", "error")

    def __init__(self, node: str, batch, channel: int,
                 error: BaseException):
        self.node = node
        self.batch = batch
        self.channel = channel
        self.error = error

    def __repr__(self):
        rows = len(self.batch) if hasattr(self.batch, "__len__") else "?"
        return (f"<DeadLetter node={self.node!r} rows={rows} "
                f"error={type(self.error).__name__}: {self.error}>")

    def to_event(self) -> dict:
        """JSON-safe summary for the runtime event log
        (obs/events.py ``quarantine`` events): everything but the batch
        payload itself, which stays only in ``Dataflow.dead_letters``."""
        return {
            "node": self.node,
            "channel": self.channel,
            "rows": (len(self.batch)
                     if hasattr(self.batch, "__len__") else None),
            "error": type(self.error).__name__,
            "message": str(self.error),
        }
