"""Dataflow node contract — the runtime substrate's equivalent of FastFlow's
``ff_node_t`` (``svc_init/svc/svc_end/eosnotify``, see reference usage at
win_seq.hpp:256,268,433,477).

Differences from the reference, by design:

* the unit of exchange is a *batch* (structured numpy array), not a tuple
  pointer — tuple-at-a-time is the degenerate batch of one;
* nodes are wired by an :class:`~windflow_tpu.runtime.engine.Dataflow` graph
  and run by worker threads; emission goes through :meth:`Node.emit` /
  :meth:`Node.emit_to` (the ``ff_send_out`` / ``ff_send_out_to`` analogs,
  standard.hpp:79);
* EOS is per-input-channel, counted by the runner; when every input channel
  has delivered EOS the node gets a final :meth:`eosnotify` to flush state,
  then EOS propagates downstream.
"""

from __future__ import annotations


class SnapshotUnsupported(RuntimeError):
    """This node cannot produce a state snapshot in its current
    configuration (e.g. state held in native-library tables with no
    extraction API) — the recovery layer marks it non-restartable and a
    failure there tears the graph down exactly like the seed engine."""


class RuntimeContext:
    """Execution context handed to "rich" user functions
    (reference context.hpp:45-80): the replica's parallelism degree and
    index within its pattern.

    When the owning dataflow runs with a metrics registry
    (``metrics=`` / ``sample_period=``, docs/OBSERVABILITY.md), the
    engine stamps it on ``ctx.metrics`` before ``svc_init`` so rich
    functions can record custom metrics
    (``ctx.metrics.counter("late_rows").inc(n)``); ``None`` otherwise —
    the no-observability default costs user code one attribute check."""

    __slots__ = ("parallelism", "index", "name", "metrics")

    def __init__(self, parallelism: int = 1, index: int = 0, name: str = ""):
        self.parallelism = parallelism
        self.index = index
        self.name = name
        self.metrics = None

    def getParallelism(self) -> int:
        return self.parallelism

    def getReplicaIndex(self) -> int:
        return self.index


class Node:
    """Base dataflow node. Subclasses override `svc` (and optionally the
    lifecycle hooks). During execution `self._outputs` holds the output
    channels and `self.ctx` the RuntimeContext.

    Batch-ownership protocol (copy elision): batches are logically
    immutable once emitted — the race-safety model — but a node whose
    every emission is a freshly allocated array it never touches again
    declares ``yields_fresh = True``, transferring ownership downstream.
    A consumer whose ``input_fresh`` was set by the wiring layer (Comb
    fusion, or MultiPipe's ordering interposition) may then mutate the
    batch in place instead of taking a private copy — the reference's
    in-place Map flavour (map.hpp:141) generalised to every handed-off
    edge.  Both default to False: unknown producers are shared."""

    #: every batch this node emits is newly allocated and never reused
    yields_fresh = False
    #: the wiring layer proved this node's input batches are handed off
    input_fresh = False
    #: per-node poison-tuple allowance (runtime/overload.py): how many svc
    #: exceptions this node may quarantine to the dataflow's dead-letter
    #: queue before failing fast.  None = defer to the dataflow's
    #: OverloadPolicy.error_budget (itself 0 = fail fast, the default).
    #: Set via builders' withErrorBudget / a pattern's error_budget
    #: (propagated onto replicas by runtime/farm.py).
    error_budget = None
    #: framework shell nodes (emitters, collectors, ordering merges) set
    #: this True: an error there is a framework bug, never a poison
    #: tuple, so the dataflow-wide error_budget default must NOT
    #: quarantine it (an explicit node-level error_budget still wins)
    quarantine_exempt = False
    #: span-tracing hooks (obs/trace.py): the engine stamps ``_tracer``
    #: on every node of a traced dataflow (``trace=``, docs/
    #: OBSERVABILITY.md §tracing); ``_trace_origin`` marks source nodes,
    #: whose emissions make the sampling/wire-adoption decision;
    #: ``_trace_wrap`` is False only on fused inner stages
    #: (runtime/comb.py), whose synchronous edges carry the span via the
    #: thread-local instead of a Stamped wrapper; ``_hop_id`` is the
    #: canonical node id spans are recorded under.  All default to the
    #: disabled state, so an untraced graph pays one dead ``_tracer is
    #: not None`` branch per emitted batch — the standard opt-in
    #: contract.
    _tracer = None
    _trace_origin = False
    _trace_wrap = True
    _hop_id = None
    #: True on nodes whose inbox may LOAD-SHED under a shedding
    #: OverloadPolicy: farm heads (routing emitters — dropping there is
    #: dropping raw stream items, the classic shedding point) and
    #: stateless operator/sink workers.  False (default) on internal
    #: farm edges — a shed copy of a window-range multicast or of a
    #: dense-id result stream would silently corrupt windows, so those
    #: edges keep blocking and the backpressure propagates to the
    #: nearest shed-safe inbox upstream.
    shed_safe = False
    #: recovery layer (docs/ROBUSTNESS.md "Recovery"): True on node
    #: classes whose state the supervised-restart path can snapshot and
    #: restore (stateless operators trivially; window cores via their
    #: core's deep copy / device hooks).  False (default) means a crash
    #: here fails the graph exactly like the seed engine even when
    #: ``recovery=`` is on.
    recoverable = False
    #: instance attributes carrying mutable stream state — the default
    #: ``state_snapshot`` deep-copies exactly these (empty = stateless)
    state_attrs = ()
    #: per-node recovery record (recovery/epoch.NodeRecovery), installed
    #: by the Supervisor when the dataflow opts in; None (the class
    #: default) keeps emit()/emit_to() on the seed path — the single
    #: dead branch the recovery contract allows on the hot path
    _recov = None
    #: control-plane epoch hooks (control/rescale.py), installed by the
    #: Controller when ``control=`` is set.  ``_ctl_seal_hook`` runs
    #: just before a completed barrier's marker forwards (the farm
    #: emitter announces a pending rescale's seal epoch there);
    #: ``_ctl_epoch_hook`` runs after the barrier checkpoint committed —
    #: the point the rescale migration actually seals at.  Both are
    #: checked once per EPOCH (engine ``_checkpoint_node`` /
    #: ``_complete_barriers``), never on the per-item path.
    _ctl_seal_hook = None
    _ctl_epoch_hook = None

    def __init__(self, name: str = None):
        self.name = name or type(self).__name__
        self._outputs = []   # list of (inbox, src_index) set by the graph
        self.n_input_channels = 0  # set by the engine before svc_init
        self.ctx = RuntimeContext()
        # per-node service-time counters (the LOG_DIR equivalent; see
        # utils/tracing.py). Filled by the runner when tracing is enabled.
        self.stats = None

    # -- lifecycle ---------------------------------------------------------
    def svc_init(self):
        """Called once in the node's thread before any input."""

    def svc(self, batch, channel: int = 0):
        """Process one input batch from input `channel`."""
        raise NotImplementedError

    def on_channel_eos(self, channel: int):
        """Called when one input channel reaches EOS (eosnotify(id))."""

    def eosnotify(self):
        """Called once after ALL input channels reached EOS; flush here."""

    def svc_end(self):
        """Called after eosnotify, before the thread exits."""

    # -- recovery hooks ----------------------------------------------------
    def checkpoint_prepare(self):
        """Called at epoch-barrier alignment before ``state_snapshot``:
        drain any in-flight async work whose results are not yet part of
        this node's state (device launch queues) and return the output
        batches to emit — one per launch, in launch order, so replayed
        emission numbering stays deterministic (None/empty: nothing to
        drain)."""
        return None

    def state_snapshot(self):
        """Snapshot this node's mutable state (any deep-copied/immutable
        object; None for stateless).  Raise :class:`SnapshotUnsupported`
        when the current configuration cannot snapshot."""
        if not self.state_attrs:
            return None
        import copy
        return {a: copy.deepcopy(getattr(self, a))
                for a in self.state_attrs}

    def state_restore(self, snap):
        """Reset state to a ``state_snapshot`` value.  The snapshot must
        survive repeated restores, so mutable state is copied back in."""
        if snap:
            import copy
            for a, v in snap.items():
                setattr(self, a, copy.deepcopy(v))

    # -- emission ----------------------------------------------------------
    def emit(self, batch):
        """Send to every output channel (broadcast for 1 output; nodes with
        several outputs that need routing use emit_to)."""
        if batch is None:
            return
        if self.stats is not None:
            self.stats.record_departure()
        tr = self._tracer
        if tr is not None:
            # span tracing (obs/trace.py): sources decide sampling here;
            # traced batches cross inboxes as Stamped wrappers (the
            # recovery envelope, below, wraps OUTSIDE — the journal
            # replays exactly what was emitted)
            batch = tr.outgoing(batch, self)
        if self._recov is not None:
            # recovery layer on: sequence-tag the emission per edge (and
            # let sources trail epoch markers) — recovery/epoch.py
            self._recov.emit(self._outputs, batch)
            return
        for inbox, src in self._outputs:
            inbox.put(src, batch)

    def emit_to(self, out: int, batch):
        """Send to one specific output channel (ff_send_out_to)."""
        if batch is None:
            return
        if self.stats is not None:
            self.stats.record_departure()
        tr = self._tracer
        if tr is not None:
            batch = tr.outgoing(batch, self)
        if self._recov is not None:
            self._recov.emit_to(self._outputs, out, batch)
            return
        inbox, src = self._outputs[out]
        inbox.put(src, batch)

    @property
    def n_outputs(self) -> int:
        return len(self._outputs)

    def __repr__(self):
        return f"<{type(self).__name__} {self.name!r}>"


class SourceNode(Node):
    """A node with no inputs: `generate` drives emission."""

    def generate(self):
        """Produce the stream by calling emit(); return to signal EOS."""
        raise NotImplementedError

    def svc(self, batch, channel=0):  # pragma: no cover
        raise RuntimeError("source nodes receive no input")
