"""Routing nodes: the batch-native versions of the reference's L2 graph
nodes (``standard.hpp``): pass-through / round-robin / keyed emitters and the
trivial merging collector.

Routing a batch means *splitting* it by destination with a vectorised
predicate — the analog of per-tuple ``ff_send_out_to`` (standard.hpp:73-81)
— so routing cost is O(batch), not O(tuple) dispatches.
"""

from __future__ import annotations

import numpy as np

from ..core.tuples import MARKER_FIELD
from .node import Node

_NEG_INF = np.int64(-(2 ** 62))


class KeyedStreamState:
    """Per-key last-tuple bookkeeping shared by window emitters: the
    out-of-order drop and the EOS-marker source (wf_nodes.hpp:60-121,
    wm_nodes.hpp:52-104).  Also absorbs markers arriving from an enclosing
    nesting emitter so this emitter's own markers carry the key's global
    last tuple."""

    __slots__ = ("pos_field", "last")

    def __init__(self, pos_field: str):
        self.pos_field = pos_field
        self.last = {}  # key -> (last_pos, last_row_copy)

    def filter(self, batch: np.ndarray) -> np.ndarray:
        """Absorb marker rows and drop out-of-order rows; returns the
        surviving (real) rows, arrival order preserved."""
        mk = batch[MARKER_FIELD]
        if np.any(mk):
            for row in batch[mk]:
                k = int(row["key"])
                p = int(row[self.pos_field])
                prev = self.last.get(k)
                if prev is None or p >= prev[0]:
                    self.last[k] = (p, row.copy())
            batch = batch[~mk]
        if len(batch) == 0:
            return batch
        keys = batch["key"]
        pos = batch[self.pos_field].astype(np.int64)
        # fast path: per-key nondecreasing (the overwhelmingly common case
        # for in-order streams) — one grouped monotonicity check, no
        # per-key Python loop
        from ..core.tuples import group_by_key
        order, starts, _g_ends = group_by_key(keys)
        ks = keys[order]
        ps = pos[order]
        same_key = np.ones(len(ks), dtype=bool)
        same_key[starts] = False
        in_order = not np.any((np.diff(ps) < 0) & same_key[1:])
        if in_order:
            firsts = ps[starts]
            lasts_idx = _g_ends - 1
            ok_heads = True
            for i, s in enumerate(starts):
                k = int(ks[s])
                prev = self.last.get(k)
                if prev is not None and firsts[i] < prev[0]:
                    ok_heads = False
                    break
            if ok_heads:
                # ONE vectorised take of the last row per key, then O(K)
                # dict stores of views into it (a per-key row.copy() here
                # costs a python-level copy per distinct key per chunk)
                lastrows = batch[order[lasts_idx]]
                for i, li in enumerate(lasts_idx):
                    self.last[int(ks[li])] = (int(ps[li]), lastrows[i])
                return batch
        # slow path: genuine out-of-order rows — per-key running max over
        # contiguous sorted slices (O(n + K), not a mask per key)
        ends = _g_ends
        keep_sorted = np.ones(len(ks), dtype=bool)
        for i in range(len(starts)):
            sl = slice(int(starts[i]), int(ends[i]))
            p = ps[sl]
            k = int(ks[starts[i]])
            prev = self.last.get(k)
            lastpos = prev[0] if prev else _NEG_INF
            runmax = np.maximum.accumulate(np.concatenate(([lastpos], p)))[:-1]
            ok = p >= runmax
            keep_sorted[sl] = ok
            if ok.any():
                li = int(starts[i]) + int(np.flatnonzero(ok)[-1])
                self.last[k] = (int(ps[li]), batch[order[li]].copy())
        keep = np.empty(len(batch), dtype=bool)
        keep[order] = keep_sorted
        return batch if keep.all() else batch[keep]

    def marker_batch(self) -> np.ndarray | None:
        """One marker row per key (its last tuple), for EOS replay."""
        rows = [row for _, row in self.last.values() if row is not None]
        if not rows:
            return None
        markers = np.stack(rows)
        markers[MARKER_FIELD] = True
        return markers


def default_routing(keys: np.ndarray, n: int) -> np.ndarray:
    """key -> destination in [0, n): the reference default is k % n
    (builders.hpp:190)."""
    return keys % n


class StandardEmitter(Node):
    """Pass-through (n=1), block round-robin, or keyed routing emitter
    (standard.hpp:40-88)."""

    def __init__(self, n_dest: int, routing=None, name="emitter"):
        super().__init__(name)
        self.n_dest = n_dest
        self.routing = routing  # vectorised fn(keys, n) -> dest indices
        self._rr = 0

    def svc(self, batch, channel=0):
        if self.n_dest == 1:
            self.emit_to(0, batch)
            return
        if self.routing is None:
            # round-robin whole chunks: preserves per-key order only within a
            # replica, exactly like the reference's per-tuple round-robin
            self.emit_to(self._rr, batch)
            self._rr = (self._rr + 1) % self.n_dest
            return
        dest = np.asarray(self.routing(batch["key"], self.n_dest))
        if len(batch) and (dest[0] == dest[-1]) and not np.any(dest != dest[0]):
            self.emit_to(int(dest[0]), batch)
            return
        for d in range(self.n_dest):
            sub = batch[dest == d]
            if len(sub):
                self.emit_to(d, sub)


class Collector(Node):
    """Trivial multi-in merge (standard.hpp:91-94)."""

    def __init__(self, name="collector"):
        super().__init__(name)

    def svc(self, batch, channel=0):
        self.emit(batch)


# NOTE: the reference's broadcast_node (multipipe.hpp:50-115) has no node
# here on purpose: it exists only to feed CB-window farms the whole stream
# inside MultiPipe, and this framework's MultiPipe covers that case with a
# TS_RENUMBERING ordered merge instead (api/multipipe.py:_maybe_order) —
# a broadcast + per-worker renumber pair never materialises.
