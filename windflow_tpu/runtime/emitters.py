"""Routing nodes: the batch-native versions of the reference's L2 graph
nodes (``standard.hpp``): pass-through / round-robin / keyed emitters and the
trivial merging collector.

Routing a batch means *splitting* it by destination with a vectorised
predicate — the analog of per-tuple ``ff_send_out_to`` (standard.hpp:73-81)
— so routing cost is O(batch), not O(tuple) dispatches.
"""

from __future__ import annotations

import ctypes

import numpy as np

from ..core.tuples import MARKER_FIELD
from .node import Node

_NEG_INF = np.int64(-(2 ** 62))
_P64 = ctypes.POINTER(ctypes.c_longlong)


def _p64(a: np.ndarray):
    return a.ctypes.data_as(_P64)


class KeyedStreamState:
    """Per-key last-tuple bookkeeping shared by window emitters: the
    out-of-order drop and the EOS-marker source (wf_nodes.hpp:60-121,
    wm_nodes.hpp:52-104).  Also absorbs markers arriving from an enclosing
    nesting emitter so this emitter's own markers carry the key's global
    last tuple.

    State is slot-indexed parallel arrays (core/slots.py).  When the
    native library is available, slot lookup and the in-order check ride
    ONE memory-speed C pass per batch (wf_keymap_lookup +
    wf_keyscan_ordered) instead of a binary-search lookup + stable
    argsort + segmented running max — together ~150 ms per 1M-row batch
    of pure host time on the pipe benchmark, the farm emitter's largest
    cost.  The numpy slot path remains both the no-toolchain fallback
    and the out-of-order general path (identical semantics, pinned by
    the emitter differential tests)."""

    __slots__ = ("pos_field", "_slots", "_last_pos", "_rows", "_n", "_cap",
                 "_lib", "_km", "_last_idx", "_touched", "_nt", "pos_cache")

    def __init__(self, pos_field: str):
        from ..native import load
        self.pos_field = pos_field
        self._lib = load()
        self._km = (self._lib.wf_keymap_new()
                    if self._lib is not None else None)
        if self._km is None:
            from ..core.slots import SlotMap
            self._slots = SlotMap(on_register=self._on_register)
        else:
            self._slots = None
        self._last_pos = np.zeros(0, dtype=np.int64)
        self._rows = None     # (cap,) structured array, slot-indexed
        self._n = 0
        self._cap = 0
        self._last_idx = np.empty(0, dtype=np.int64)   # scan scratch
        self._touched = np.empty(0, dtype=np.int64)
        self._nt = ctypes.c_longlong(0)
        #: after filter(): the contiguous int64 pos column of the batch
        #: filter RETURNED, when that batch is the unmodified input (the
        #: in-order fast path) — callers reuse it instead of re-gathering
        #: the strided field; None whenever rows were dropped/changed
        self.pos_cache = None

    def __del__(self):
        km = getattr(self, "_km", None)
        if km is not None:
            self._lib.wf_keymap_free(km)
            self._km = None

    def _on_register(self, new_keys):
        self._grow_count(len(new_keys))

    def _grow_count(self, m):
        if self._n + m > self._cap:
            # amortised doubling: exact-size concatenate per registration
            # is quadratic when keys trickle in across batches
            self._cap = max(self._cap * 2, self._n + m, 1024)
            grown = np.full(self._cap, _NEG_INF, dtype=np.int64)
            grown[:self._n] = self._last_pos[:self._n]
            self._last_pos = grown
            if self._rows is not None:
                gr = np.zeros(self._cap, dtype=self._rows.dtype)
                gr[:self._n] = self._rows[:self._n]
                self._rows = gr
            if self._km is not None:
                li = np.full(self._cap, -1, dtype=np.int64)
                li[:self._n] = self._last_idx[:self._n]
                self._last_idx = li
                self._touched = np.empty(self._cap, dtype=np.int64)
        self._n += m

    def _lookup(self, keys: np.ndarray) -> np.ndarray:
        """Slots for `keys`, registering unseen keys (first-appearance
        order — identical numbering in both implementations)."""
        keys = np.ascontiguousarray(keys, dtype=np.int64)
        if self._km is None:
            return self._slots.lookup(keys)
        slots = np.empty(len(keys), dtype=np.int64)
        ns = self._lib.wf_keymap_lookup(self._km, _p64(keys), len(keys),
                                        _p64(slots))
        if ns > self._n:
            self._grow_count(ns - self._n)
        return slots

    def _rows_buf(self, dtype):
        if self._rows is None:
            self._rows = np.zeros(self._cap, dtype=dtype)
        elif self._rows.dtype != dtype:
            # a mid-stream schema change would silently zero the columns
            # absent from the old dtype in every captured last-row (EOS
            # marker replay) — upstream schemas are fixed at build time, so
            # this is a bug upstream: fail loudly (ADVICE r2)
            raise TypeError(
                f"batch dtype changed mid-stream: {self._rows.dtype} -> "
                f"{dtype} (operator schemas are fixed at graph build)")
        return self._rows

    def _store_last(self, slots_of_rows, rows, sorted_order=None):
        """Per-slot last-row capture: rows are in priority order (arrival,
        or pos for markers), so the LAST occurrence per slot wins.
        ``sorted_order`` passes a precomputed stable slot sort to avoid
        re-sorting on the hot path."""
        buf = self._rows_buf(rows.dtype)
        order = (np.argsort(slots_of_rows, kind="stable")
                 if sorted_order is None else sorted_order)
        s = slots_of_rows[order]
        last = np.ones(len(s), dtype=bool)
        last[:-1] = s[1:] != s[:-1]
        buf[s[last]] = rows[order[last]]

    def filter(self, batch: np.ndarray) -> np.ndarray:
        """Absorb marker rows and drop out-of-order rows; returns the
        surviving (real) rows, arrival order preserved."""
        self.pos_cache = None
        mk = batch[MARKER_FIELD]
        if np.any(mk):
            mrows = batch[mk]
            mpos = mrows[self.pos_field].astype(np.int64)
            mslots = self._lookup(mrows["key"])
            ok = mpos >= self._last_pos[mslots]
            if not ok.all():
                mrows, mpos, mslots = mrows[ok], mpos[ok], mslots[ok]
            if len(mrows):
                # order by pos so the stored last row is the max-pos
                # marker (ties: later arrival wins, like the dict form)
                mo = np.argsort(mpos, kind="stable")
                self._store_last(mslots[mo], mrows[mo])
                np.maximum.at(self._last_pos, mslots, mpos)
            batch = batch[~mk]
        if len(batch) == 0:
            return batch
        slots = self._lookup(batch["key"])
        pos = np.ascontiguousarray(batch[self.pos_field], dtype=np.int64)
        if self._km is not None:
            ok = self._lib.wf_keyscan_ordered(
                _p64(slots), _p64(pos), len(batch), _p64(self._last_pos),
                _p64(self._last_idx), _p64(self._touched),
                ctypes.byref(self._nt))
            t = self._touched[:self._nt.value]
            li = self._last_idx[t]
            self._last_idx[t] = -1        # scratch hygiene for next batch
            if ok:
                # in-order: capture each touched slot's last row + pos
                # (tiny gathers — one row per distinct key)
                buf = self._rows_buf(batch.dtype)
                buf[t] = batch[li]
                self._last_pos[t] = pos[li]
                self.pos_cache = pos
                return batch
        return self._filter_general(batch, slots, pos)

    def _filter_general(self, batch, slots, pos):
        """The numpy path: in-order store, or the out-of-order drop via
        the segmented exclusive running max."""
        from ..core.slots import segmented_excl_running_max, segments
        order = np.argsort(slots, kind="stable")
        s = slots[order]
        ps = pos[order]
        starts, ends = segments(s)
        seg_first = np.zeros(len(s), dtype=bool)
        seg_first[starts] = True
        within_bad = np.zeros(len(s), dtype=bool)
        within_bad[1:] = (np.diff(ps) < 0) & ~seg_first[1:]
        head_bad = ps[starts] < self._last_pos[s[starts]]
        if not within_bad.any() and not head_bad.any():
            # in-order fast path: store each key's last row, done
            lasts = ends - 1
            self._last_pos[s[lasts]] = ps[lasts]
            self._store_last(slots, batch, sorted_order=order)
            self.pos_cache = pos
            return batch
        # out-of-order: the shared segmented exclusive running max
        # (core/slots.py; also the vecinc drop pass)
        excl = segmented_excl_running_max(s, ps, starts,
                                          self._last_pos[s[starts]])
        keep_sorted = ps >= excl
        liv = np.flatnonzero(keep_sorted)
        if len(liv):
            ls, le = segments(s[liv])
            self._last_pos[s[liv[ls]]] = ps[liv[le - 1]]
            self._store_last(slots[order[liv]], batch[order[liv]],
                             sorted_order=np.arange(len(liv)))
        keep = np.empty(len(batch), dtype=bool)
        keep[order] = keep_sorted
        return batch if keep.all() else batch[keep]

    def state_snapshot(self):
        """Recovery snapshot of the per-key bookkeeping, numpy path only
        — the native keymap keeps key->slot in a C table with no
        extraction API, so the native path returns None (the owning
        emitter then raises SnapshotUnsupported and a crash there fails
        the graph exactly like the seed engine)."""
        if self._km is not None:
            return None
        return {
            "slots": self._slots.state_snapshot(),
            "last_pos": self._last_pos.copy(),
            "rows": None if self._rows is None else self._rows.copy(),
            "n": self._n, "cap": self._cap,
        }

    def state_restore(self, snap):
        self._slots.state_restore(snap["slots"])
        self._last_pos = snap["last_pos"].copy()
        self._rows = None if snap["rows"] is None else snap["rows"].copy()
        self._n = snap["n"]
        self._cap = snap["cap"]
        self.pos_cache = None

    def marker_batch(self) -> np.ndarray | None:
        """One marker row per key (its last tuple), for EOS replay."""
        if self._rows is None or self._n == 0:
            return None
        seen = self._last_pos[:self._n] > _NEG_INF
        if not seen.any():
            return None
        markers = self._rows[:self._n][seen].copy()
        markers[MARKER_FIELD] = True
        return markers


def default_routing(keys: np.ndarray, n: int) -> np.ndarray:
    """key -> destination in [0, n): the reference default is k % n
    (builders.hpp:190)."""
    return keys % n


class StandardEmitter(Node):
    """Pass-through (n=1), block round-robin, or keyed routing emitter
    (standard.hpp:40-88).

    ``n_active`` <= ``n_dest`` is the width actually routed over: equal
    by default (seed behavior), narrower when the control plane
    pre-provisioned the farm to a ``Rescale`` rule's ``max_workers``
    (docs/CONTROL.md) — the controller then moves ``n_active`` at epoch
    barriers, and a crash-restore replays routing decisions at the width
    the snapshot pinned (``state_attrs``)."""

    quarantine_exempt = True    # framework shell: errors here fail fast
    shed_safe = True            # farm head: shedding drops raw stream rows
    recoverable = True          # round-robin cursor + active width
    state_attrs = ("_rr", "n_active")

    def __init__(self, n_dest: int, routing=None, name="emitter"):
        super().__init__(name)
        self.n_dest = n_dest
        self.n_active = n_dest
        self.routing = routing  # vectorised fn(keys, n) -> dest indices
        self._rr = 0

    def svc(self, batch, channel=0):
        n = self.n_active
        if n == 1:
            self.emit_to(0, batch)
            return
        if self.routing is None:
            # round-robin whole chunks: preserves per-key order only within a
            # replica, exactly like the reference's per-tuple round-robin
            self.emit_to(self._rr, batch)
            self._rr = (self._rr + 1) % n
            return
        dest = np.asarray(self.routing(batch["key"], n))
        if len(batch) and (dest[0] == dest[-1]) and not np.any(dest != dest[0]):
            self.emit_to(int(dest[0]), batch)
            return
        for d in range(n):
            sub = batch[dest == d]
            if len(sub):
                self.emit_to(d, sub)


class Collector(Node):
    """Trivial multi-in merge (standard.hpp:91-94)."""

    quarantine_exempt = True    # framework shell: errors here fail fast
    recoverable = True          # stateless pass-through merge

    def __init__(self, name="collector"):
        super().__init__(name)

    def svc(self, batch, channel=0):
        self.emit(batch)


# NOTE: the reference's broadcast_node (multipipe.hpp:50-115) has no node
# here on purpose: it exists only to feed CB-window farms the whole stream
# inside MultiPipe, and this framework's MultiPipe covers that case with a
# TS_RENUMBERING ordered merge instead (api/multipipe.py:_maybe_order) —
# a broadcast + per-worker renumber pair never materialises.
