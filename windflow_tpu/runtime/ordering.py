"""Ordering nodes: k-way merge of per-channel ordered streams with per-key
watermarks — the reference ``OrderingNode`` (orderingNode.hpp:49-225).

Semantics reproduced exactly:

* per key, ``maxs[c]`` tracks the greatest position seen from channel ``c``
  for THAT key (Key_Descriptor::maxs, orderingNode.hpp:72); buffered rows
  are released once their position is <= min(maxs)
  (orderingNode.hpp:151-162);
* EOS *markers* are set aside (keeping the max-position one per key) and
  re-emitted last at EOS, after the residual buffer flush
  (orderingNode.hpp:134-147, 188-220);
* ``TS_RENUMBERING`` rewrites ids with a dense per-key counter after the
  time-ordered merge (orderingNode.hpp:167-172) — this is what lets
  count-windows sit behind a broadcast in MultiPipe.

Batch-native: rows are buffered per (key, channel) as column chunks and the
releasable prefix is computed with numpy merges, so cost is O(rows log k)
with tiny constants rather than a per-tuple priority queue.
"""

from __future__ import annotations

import enum

import numpy as np

from ..core.tuples import MARKER_FIELD
from .node import Node

_NEG_INF = -(2 ** 62)


class OrderingMode(enum.Enum):
    ID = "id"                      # merge by tuple id
    TS = "ts"                      # merge by timestamp
    TS_RENUMBERING = "ts_renum"    # merge by ts, then renumber ids densely


class _KeyBuf:
    __slots__ = ("chans", "marker_row", "marker_pos", "emit_counter",
                 "maxs")

    def __init__(self, n_channels, per_key):
        self.chans = [[] for _ in range(n_channels)]  # lists of row chunks
        self.marker_row = None
        self.marker_pos = _NEG_INF
        self.emit_counter = 0
        # per-channel greatest position seen FOR THIS KEY — the reference's
        # Key_Descriptor::maxs (orderingNode.hpp:72, per key, not global:
        # producers like PLQ/MAP workers emit per-key-monotone ids that are
        # NOT globally monotone across keys, so a global watermark would
        # release rows early and downstream cores would drop their
        # out-of-order siblings).  Allocated only in per-key mode; the
        # default global-watermark mode never reads it.
        self.maxs = (np.full(n_channels, _NEG_INF, dtype=np.int64)
                     if per_key else None)

    def has_rows(self):
        return any(self.chans)


class OrderingCore:
    """Reusable merge engine (also fused in front of farm workers, the
    ff_comb(OrderingNode, worker) analog, win_farm.hpp:157-162).

    Two watermark granularities, for two classes of producer:

    * ``per_key_watermarks=True`` — the reference's semantics
      (Key_Descriptor::maxs, orderingNode.hpp:72,151-162): per key,
      ``maxs[c]`` tracks the greatest position channel ``c`` delivered for
      THAT key.  Required when channels are only per-key monotone — e.g.
      PLQ/MAP workers emitting per-key-renumbered ids (the LEVEL2 fused
      merge), where a global watermark would release rows early and the
      downstream core would drop their out-of-order siblings.
    * ``per_key_watermarks=False`` (default) — one watermark per channel,
      global across keys.  Valid only when each channel's stream is
      GLOBALLY nondecreasing in position (sources are monotone; union
      branches, multi-emitter splits of a monotone stream), and required
      there for liveness: a key flowing on only one channel still advances
      instead of buffering until EOS.

    A channel that reaches EOS is excluded from the min (its watermark
    jumps to +inf, orderingNode.hpp:182-221) so the merge never stalls on
    finished producers."""

    def __init__(self, n_channels: int, mode: OrderingMode,
                 per_key_watermarks: bool = False,
                 ordered_input: bool = False,
                 owned_input: bool = False):
        self.n_channels = n_channels
        self.mode = mode
        self.per_key = per_key_watermarks
        #: the wiring layer proved every pushed batch is handed off
        #: (producer yields_fresh — node.py ownership protocol): the
        #: renumbering fast path may write ids into the batch in place
        #: instead of taking a private copy (0.2-0.3 s of the 8M-row
        #: pipe run)
        self.owned_input = bool(owned_input)
        #: the caller vouches the (single) channel is ts-ordered per key
        #: WITHIN each batch — the precondition for the renumbering fast
        #: path.  A disordered single tail (TS_RENUMBERING chosen via
        #: `not ordered`) must take the general path, whose per-release
        #: stable ts-sort fixes intra-batch inversions before ids are
        #: assigned.
        self.ordered_input = bool(ordered_input)
        self.pos_field = "id" if mode is OrderingMode.ID else "ts"
        self._keys: dict[int, _KeyBuf] = {}
        #: channels that reached EOS (excluded from every key's min)
        self._eos = np.zeros(n_channels, dtype=bool)
        self.watermark = np.full(n_channels, _NEG_INF, dtype=np.int64)
        self._released_upto = _NEG_INF
        #: native per-key counter table for the single-channel fast path
        #: (lazy; None = numpy fallback with per-key emit_counters)
        self._renum = None
        self._renum_lib = None

    def __del__(self):
        if getattr(self, "_renum", None) is not None:
            self._renum_lib.wf_renum_free(self._renum)
            self._renum = None

    def state_snapshot(self):
        """Recovery snapshot of the merge state (buffered chunks,
        watermarks, renumbering counters).  Returns None when the native
        per-key renumbering table is active — its counters live in a C
        table with no extraction API, so the owning node reports
        SnapshotUnsupported and a crash there fails as in the seed
        engine (snapshots taken *before* the table's lazy creation are
        fine: a fresh table equals the all-zero counter state)."""
        if self._renum is not None:
            return None
        import copy
        return {
            "keys": copy.deepcopy(self._keys),
            "eos": self._eos.copy(),
            "watermark": self.watermark.copy(),
            "released_upto": self._released_upto,
        }

    def state_restore(self, snap):
        import copy
        self._keys = copy.deepcopy(snap["keys"])
        self._eos = snap["eos"].copy()
        self.watermark = snap["watermark"].copy()
        self._released_upto = snap["released_upto"]
        if self._renum is not None:
            # table created after the snapshot was taken — the snapshot
            # predates every fast-path push, so all counters were zero:
            # a fresh table (lazily recreated on the next push) matches
            self._renum_lib.wf_renum_free(self._renum)
            self._renum = None

    def _buf(self, key):
        b = self._keys.get(key)
        if b is None:
            b = _KeyBuf(self.n_channels, self.per_key)
            self._keys[key] = b
        return b

    def _upto(self, kb: _KeyBuf) -> int:
        live = kb.maxs[~self._eos]
        return int(live.min()) if len(live) else 2 ** 62

    def _release(self, kb: _KeyBuf, key: int, upto: int) -> np.ndarray | None:
        """Pop every buffered row with pos <= upto, merged in pos order."""
        take = []
        for c, chunks in enumerate(kb.chans):
            if not chunks:
                continue
            rows = chunks[0] if len(chunks) == 1 else np.concatenate(chunks)
            pos = rows[self.pos_field]
            cut = int(np.searchsorted(pos, upto, side="right"))
            if cut:
                take.append(rows[:cut])
                kb.chans[c] = [rows[cut:]] if cut < len(rows) else []
            else:
                kb.chans[c] = [rows]
        if not take:
            return None
        merged = take[0] if len(take) == 1 else np.concatenate(take)
        order = np.argsort(merged[self.pos_field], kind="stable")
        merged = merged[order]     # advanced indexing: always a fresh array
        if self.mode is OrderingMode.TS_RENUMBERING:
            merged["id"] = kb.emit_counter + np.arange(len(merged))
            kb.emit_counter += len(merged)
        return merged

    def _push_single_channel(self, batch: np.ndarray):
        """SINGLE-upstream TS_RENUMBERING fast path: with one channel
        there is nothing to merge — every row is releasable the moment it
        arrives, already in per-key order (the per-channel contract), so
        the whole push reduces to a vectorised per-key cumcount over the
        batch IN ARRIVAL ORDER: no pos argsort, no per-key buffer
        fragmentation, one output batch instead of one array per key.
        Measured 2026-07-31: the general path ran this exact case at
        5.3 M rows/s and was the pipe benchmark's single largest host
        cost (1.2 s of a 2.9 s run).  The renumbering itself rides the
        native per-key counter loop when available (wf_renum_run, one
        GIL-released memory-speed pass — the numpy groupby-cumcount
        needs a stable argsort per batch, ~6.5 M rows/s); per-key
        emit_counters are the fallback."""
        out = batch if self.owned_input else batch.copy()
        if self._renum is None and self._renum_lib is None:
            from ..native import load
            lib = load()
            # False = tried-and-unavailable sentinel: never re-attempt
            # the load on this hot path
            self._renum_lib = lib if lib is not None else False
            if lib is not None:
                self._renum = lib.wf_renum_new()
        if self._renum is not None:
            import ctypes
            p64 = ctypes.POINTER(ctypes.c_longlong)
            keys_c = np.ascontiguousarray(batch["key"])
            ids = np.empty(len(batch), dtype=np.int64)
            self._renum_lib.wf_renum_run(
                self._renum, keys_c.ctypes.data_as(p64), len(batch),
                ids.ctypes.data_as(p64))
            out["id"] = ids
        else:
            keys = batch["key"]
            order = np.argsort(keys, kind="stable")
            sk = keys[order]
            bounds = np.flatnonzero(np.diff(sk)) + 1
            starts = np.concatenate(([0], bounds))
            # position of each (key-sorted) row within its key group
            grp = np.zeros(len(sk), dtype=np.int64)
            grp[bounds] = 1
            np.cumsum(grp, out=grp)
            within = np.arange(len(sk), dtype=np.int64) - starts[grp]
            base = np.empty(len(starts), dtype=np.int64)
            for g, s in enumerate(starts):
                kb = self._buf(int(sk[s]))
                n_g = (bounds[g] if g < len(bounds) else len(sk)) - s
                base[g] = kb.emit_counter
                kb.emit_counter += int(n_g)
            ids_sorted = base[grp] + within
            new_ids = np.empty(len(batch), dtype=np.int64)
            new_ids[order] = ids_sorted
            out["id"] = new_ids
        # keep the watermark honest for flush()/diagnostics
        self.watermark[0] = max(int(self.watermark[0]),
                                int(batch[self.pos_field].max()))
        return [out]

    def push(self, batch: np.ndarray, channel: int):
        """Buffer one per-key-ordered batch from `channel`; yield releasable
        merged chunks."""
        out = []
        marker = batch[MARKER_FIELD]
        if np.any(marker):
            for row in batch[marker]:
                kb = self._buf(int(row["key"]))
                p = int(row[self.pos_field])
                if p > kb.marker_pos or kb.marker_row is None:
                    kb.marker_pos = p
                    kb.marker_row = row.copy()
            batch = batch[~marker]
        if len(batch) == 0:
            return out
        if (self.n_channels == 1 and not self.per_key
                and self.ordered_input
                and self.mode is OrderingMode.TS_RENUMBERING):
            out.extend(self._push_single_channel(batch))
            return out
        keys = batch["key"]
        order = np.argsort(keys, kind="stable")
        sk = keys[order]
        bounds = np.flatnonzero(np.diff(sk)) + 1
        touched = []
        for grp in np.split(order, bounds):
            key = int(keys[grp[0]])
            kb = self._buf(key)
            rows = batch[grp]
            kb.chans[channel].append(rows)
            if self.per_key:
                # per-key watermark advance (orderingNode.hpp:151-152);
                # only this key's buffered rows can become releasable
                kb.maxs[channel] = max(int(kb.maxs[channel]),
                                       int(rows[self.pos_field][-1]))
                rel = self._release(kb, key, self._upto(kb))
                if rel is not None:
                    out.append(rel)
            else:
                touched.append((key, kb))
        if self.per_key:
            return out
        wm = self.watermark
        wm[channel] = max(int(wm[channel]),
                          int(batch[self.pos_field].max()))
        upto = int(wm[~self._eos].min()) if not self._eos.all() else 2 ** 62
        if upto > self._released_upto:
            # watermark advanced: rows of ANY key may become releasable
            self._released_upto = upto
            out.extend(self._release_all(upto))
        else:
            # no advance: only this batch's keys can have new releasable
            # rows (those below the standing watermark) — skip the
            # every-key scan on the merge hot path
            for key, kb in touched:
                rel = self._release(kb, key, upto)
                if rel is not None:
                    out.append(rel)
        return out

    def _release_all(self, upto: int):
        """A watermark advance can release buffered rows of ANY key."""
        out = []
        for key, kb in self._keys.items():
            if not kb.has_rows():
                continue
            rel = self._release(kb, key, upto)
            if rel is not None:
                out.append(rel)
        return out

    def channel_eos(self, channel: int):
        """Exclude a finished channel from the watermark min and release
        what that unblocks (orderingNode.hpp:182-221)."""
        self._eos[channel] = True
        if not self.per_key:
            self.watermark[channel] = 2 ** 62
            upto = (int(self.watermark[~self._eos].min())
                    if not self._eos.all() else 2 ** 62)
            self._released_upto = max(self._released_upto, upto)
            return self._release_all(upto)
        out = []
        for key, kb in self._keys.items():
            if not kb.has_rows():
                continue
            rel = self._release(kb, key, self._upto(kb))
            if rel is not None:
                out.append(rel)
        return out

    def flush(self):
        """EOS: release everything, then the per-key marker (renumbered too,
        orderingNode.hpp:197-219)."""
        out = []
        for key, kb in self._keys.items():
            rel = self._release(kb, key, 2 ** 62)
            if rel is not None:
                out.append(rel)
            if kb.marker_row is not None:
                m = kb.marker_row.copy().reshape(1)
                if self.mode is OrderingMode.TS_RENUMBERING:
                    if self._renum is not None:
                        # the native counter table owns this key's ids
                        m["id"] = self._renum_lib.wf_renum_next(
                            self._renum, int(key))
                    else:
                        m["id"] = kb.emit_counter
                        kb.emit_counter += 1
                out.append(m)
                kb.marker_row = None
        return out


class OrderingNode(Node):
    """Standalone ordering node (multi-in)."""

    #: outputs are merge gathers, renumbered copies, or (owned elision)
    #: batches that were themselves handed off — fresh either way
    yields_fresh = True
    #: framework merge, not user code: a dropped batch here would
    #: silently corrupt the ordered stream — always fail fast
    quarantine_exempt = True
    #: recovery: merge buffers + watermarks snapshot as plain data (the
    #: native renumbering table is the one dynamic exception, see
    #: OrderingCore.state_snapshot)
    recoverable = True

    def __init__(self, n_channels: int, mode: OrderingMode, name="ordering",
                 ordered_input: bool = False, owned_input: bool = False):
        super().__init__(name)
        self.core = OrderingCore(n_channels, mode,
                                 ordered_input=ordered_input,
                                 owned_input=owned_input)

    def state_snapshot(self):
        snap = self.core.state_snapshot()
        if snap is None:
            from .node import SnapshotUnsupported
            raise SnapshotUnsupported(
                f"{self.name}: native renumbering counters are not "
                "snapshotable")
        return snap

    def state_restore(self, snap):
        self.core.state_restore(snap)

    def svc(self, batch, channel=0):
        for out in self.core.push(batch, channel):
            self.emit(out)

    def on_channel_eos(self, channel: int):
        for out in self.core.channel_eos(channel):
            self.emit(out)

    def eosnotify(self):
        for out in self.core.flush():
            self.emit(out)
