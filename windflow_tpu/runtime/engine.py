"""Threaded dataflow engine — the runtime replacing FastFlow's pipeline of
pinned threads + lock-free SPSC queues (SURVEY.md §2.8).

Host-side dataflow stays on CPU threads exactly like the reference; the
difference is that channel payloads are whole batches, so queue traffic is
O(stream/chunk) instead of O(stream), and the Python GIL is released inside
the numpy/XLA kernels doing the real work.  When the native C++ substrate is
built (native/), Inbox transparently switches to the native blocking MPSC
ring (mutex + condvar — the win over queue.Queue is GIL-released futex
waits instead of 50 ms polling, not lock-freedom).

Topology model: a directed graph of Nodes. Each node owns one Inbox; an edge
(a -> b) reserves a source-slot in b's inbox so b can count per-channel EOS
(the FastFlow multi-in protocol) and ordering nodes can tell channels apart.
"""

from __future__ import annotations

import queue
import threading
from time import perf_counter_ns as _pc_ns

from .node import Node, RuntimeContext, SourceNode

_EOS = object()


class _Cancelled(BaseException):
    """Raised inside a node thread when the dataflow failed elsewhere —
    unblocks producers stuck on a dead consumer's bounded queue."""


class Inbox:
    """MPSC channel carrying (src_slot, batch) pairs.  Blocking operations
    poll the dataflow's failure flag so a raised node cannot deadlock the
    graph (a full queue whose consumer died would block producers
    forever)."""

    def __init__(self, capacity: int = 0, failed: threading.Event = None):
        self._q = queue.Queue(maxsize=capacity)
        self.n_sources = 0
        self._failed = failed

    def register_source(self) -> int:
        slot = self.n_sources
        self.n_sources += 1
        return slot

    def _blocking(self, op):
        while True:
            try:
                return op()
            except (queue.Full, queue.Empty):
                if self._failed is not None and self._failed.is_set():
                    raise _Cancelled() from None

    def put(self, src: int, item):
        self._blocking(lambda: self._q.put((src, item), timeout=0.05))

    def put_eos(self, src: int):
        self._blocking(lambda: self._q.put((src, _EOS), timeout=0.05))

    def get(self):
        return self._blocking(lambda: self._q.get(timeout=0.05))

    def cancel(self):
        """Failure path: wake any blocked producer/consumer (the Python
        queue relies on the 50 ms poll; the native ring wakes instantly)."""


class NativeInbox:
    """Inbox over the C++ blocking ring (native/wf_native.cpp NativeQueue):
    blocking push/pop wait on a futex with the GIL released instead of the
    Python queue's 50 ms timeout polling.  Batch objects never cross the
    ABI — they sit in a side table keyed by the slot id the ring carries
    (the payload-pointer discipline of FastFlow's SPSC queues)."""

    def __init__(self, capacity: int, failed: threading.Event = None,
                 lib=None):
        self._lib = lib
        self._h = lib.wf_queue_new(capacity)
        self._items = {}
        self._seq = 0
        self._seq_lock = threading.Lock()
        self.n_sources = 0

    def __del__(self):
        h = getattr(self, "_h", None)
        if h:
            # wf_queue_free closes first and spins until the last blocked
            # thread has left push/pop before destroying the mutex
            self._lib.wf_queue_free(h)
            self._h = None

    def register_source(self) -> int:
        slot = self.n_sources
        self.n_sources += 1
        return slot

    def _push(self, src: int, item):
        with self._seq_lock:
            self._seq += 1
            slot = self._seq
        self._items[slot] = item
        if self._lib.wf_queue_push(self._h, src, slot) != 0:
            self._items.pop(slot, None)
            raise _Cancelled()

    def put(self, src: int, item):
        self._push(src, item)

    def put_eos(self, src: int):
        self._push(src, _EOS)

    def get(self):
        import ctypes
        src = ctypes.c_longlong()
        slot = ctypes.c_longlong()
        if self._lib.wf_queue_pop(self._h, ctypes.byref(src),
                                  ctypes.byref(slot)) != 0:
            raise _Cancelled()
        return src.value, self._items.pop(slot.value)

    def cancel(self):
        self._lib.wf_queue_close(self._h)


def _make_inbox(capacity: int, failed: threading.Event):
    if capacity > 0:  # capacity 0 = unbounded, which only the Python
        from ..native import enabled  # queue implements
        lib = enabled()
        if lib is not None:
            return NativeInbox(capacity, failed, lib=lib)
    return Inbox(capacity, failed)


class Dataflow:
    """A graph of nodes executed by one thread per node
    (MultiPipe::run_and_wait_end spawns cardinality()-1 threads,
    multipipe.hpp:1010; same model here)."""

    def __init__(self, name: str = "dataflow", capacity: int = 16,
                 trace_dir: str = None):
        # bounded inboxes give natural backpressure (FastFlow's
        # FF_BOUNDED_BUFFER, the yahoo Makefile default): a source cannot
        # run unboundedly ahead of a slow consumer, keeping queue latency
        # proportional to capacity x batch size.  0 = unbounded.
        from ..utils.tracing import default_trace_dir
        self.name = name
        self.capacity = capacity
        self.trace_dir = trace_dir or default_trace_dir()
        self.nodes: list[Node] = []
        self._inboxes: dict[int, Inbox] = {}
        self._edges: list[tuple[Node, Node]] = []
        self._threads: list[threading.Thread] = []
        self._errors: list[BaseException] = []
        self._failed = threading.Event()

    def add(self, node: Node, ctx: RuntimeContext = None) -> Node:
        if ctx is not None:
            node.ctx = ctx
        self.nodes.append(node)
        self._inboxes[id(node)] = _make_inbox(self.capacity, self._failed)
        return node

    def connect(self, src: Node, dst: Node):
        """Add an edge; the order of connect() calls from one src defines its
        output-channel indexing (emit_to)."""
        inbox = self._inboxes[id(dst)]
        slot = inbox.register_source()
        src._outputs.append((inbox, slot))
        self._edges.append((src, dst))

    # ------------------------------------------------------------------ run

    def _run_node(self, node: Node):
        try:
            node.n_input_channels = self._inboxes[id(node)].n_sources
            if self.trace_dir:
                from ..utils.tracing import NodeStats
                # index disambiguates same-named nodes (two 'map.0' stages)
                idx = self.nodes.index(node)
                node.stats = NodeStats(f"{self.name}_{idx:02d}_{node.name}")
            node.svc_init()
            if isinstance(node, SourceNode):
                node.generate()
            else:
                inbox = self._inboxes[id(node)]
                live = inbox.n_sources
                stats = node.stats
                while live > 0:
                    src, item = inbox.get()
                    if item is _EOS:
                        live -= 1
                        node.on_channel_eos(src)
                    elif stats is None:
                        node.svc(item, src)
                    else:
                        t0 = _pc_ns()
                        node.svc(item, src)
                        stats.record_svc(len(item), _pc_ns() - t0)
            node.eosnotify()
            node.svc_end()
            if node.stats is not None:
                node.stats.write(self.trace_dir)
        except _Cancelled:
            pass  # the graph failed elsewhere; exit quietly
        except BaseException as e:  # propagate to run_and_wait_end
            self._errors.append(e)
            self._failed.set()  # unblock producers stuck on our inbox
            for inbox in self._inboxes.values():
                inbox.cancel()  # native rings wake instantly
        finally:
            try:
                for inbox, src in node._outputs:
                    inbox.put_eos(src)
            except _Cancelled:
                pass

    def run(self):
        if self._threads:
            raise RuntimeError(
                f"Dataflow {self.name!r} already started; a graph runs once")
        for node in self.nodes:
            t = threading.Thread(target=self._run_node, args=(node,),
                                 name=f"{self.name}/{node.name}", daemon=True)
            self._threads.append(t)
            t.start()

    def wait(self):
        for t in self._threads:
            t.join()
        if self._errors:
            raise self._errors[0]

    def run_and_wait_end(self):
        self.run()
        self.wait()

    def cardinality(self) -> int:
        """Number of execution threads (multipipe.hpp:973)."""
        return len(self.nodes)
